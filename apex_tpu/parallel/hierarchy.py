"""Topology-aware hierarchical compressed gradient sync (collectives v2).

PR 3's compressed collectives are *flat*: one psum topology, one wire
dtype for the whole sync. On a multi-slice pod that shape is exactly
what apexlint APX203 flags — a reduction whose replica groups cross the
DCN boundary while every slice still holds its full membership, so the
slow hop carries the *whole* gradient. The fix the papers converge on
(EQuARX's block-scaled quantized all-reduce, arXiv 2506.17615; DynamiQ's
per-hop compression-aware routing, arXiv 2602.08923; the hierarchical
intra/inter groups the reference hand-builds in
`apex/contrib/optimizers/distributed_fused_adam.py:250-290`) is a
**hierarchical schedule on the factored mesh**:

1. **reduce-scatter within each slice over ICI** — after this hop every
   chip owns ``1/intra`` of the bucket and the slice sum is done on the
   fast links;
2. **reduce across slices over DCN** on the owned shard only — the DCN
   groups hold exactly **one member per slice** (the shape APX203
   recognizes as hierarchical) and carry ``1/intra`` of the bytes;
3. **all-gather back over ICI** to restore the full synced gradient.

Each hop picks its own wire dtype (``None``/fp32, ``"bf16"``, ``"int8"``
blockwise-scaled with error feedback). The choice is made by
:func:`plan_comm` — not folklore: it minimizes the predicted
``MeshModel.hop_seconds`` using the model's per-link ``link_bytes_per_s``
and the **measured** α from linkbench calibration when present
(``MeshModel.calibration`` — ``scripts/link_probe.py`` provenance),
falling back to the defaults table when uncalibrated. int8's two-phase
decomposition pays more per-collective latencies (α) than a single bf16
psum, so a latency-dominated measured link legitimately flips the
planner's answer — the plan records which world it was planned for.

Error-feedback semantics across hops (EF-SGD/1-bit-Adam argument,
applied per hop): every compression error is re-injected into the NEXT
step's local gradient by exactly one device —

- the within-slice quantization error is device-local (each chip's own
  cast/quantize error on its own gradient);
- the DCN hop's phase-2 requantization error belongs to the shard's
  owner inside its DCN group;
- the gather hop compresses a value already replicated across slices,
  so only the ``data_inter`` rank-0 copy re-injects it (anything else
  would count it ``inter``-times).

The residual therefore stays a per-device pytree exactly like the flat
path's (:func:`apex_tpu.parallel.comm.init_residual`).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel import comm as _comm

__all__ = ["Hop", "CommPlan", "plan_comm", "hierarchical_sync",
           "hierarchical_pmean", "DTYPE_CHOICES"]

#: wire-dtype candidates, highest precision first — the planner walks
#: DOWN this ladder and drops precision only while each step buys at
#: least ``min_gain`` of predicted hop time
DTYPE_CHOICES = (None, "bf16", "int8")


@dataclasses.dataclass(frozen=True)
class Hop:
    """One collective hop of the schedule, with its planning inputs
    (α/β recorded so wire accounting and predictions are reproducible
    from the plan alone)."""

    op: str                  # "reduce_scatter" | "all_reduce" | "all_gather"
    axis: str                # program/mesh axis name the hop runs over
    size: int                # that axis's size
    link: str                # "ici" | "dcn"
    dtype: Optional[str]     # None | "bf16" | "int8"
    alpha_us: float          # per-collective latency used for planning
    bytes_per_s: float       # link bandwidth used for planning
    calibrated: bool         # True when α/β came from linkbench

    def n_collectives(self) -> int:
        """Collective instructions the hop issues (each pays α): int8
        moves payload + scales (×2), and the two-phase int8 all-reduce
        is an all-to-all + all-gather of both (×4)."""
        if self.dtype != "int8":
            return 1
        return 4 if self.op == "all_reduce" else 2

    def wire_bytes(self, elems: int,
                   compress_block: int = _comm.DEFAULT_COMPRESS_BLOCK
                   ) -> int:
        """Per-chip ring-factored wire bytes for a bucket of ``elems``
        fp32-logical elements entering the sync. ``elems`` is the FULL
        bucket; reduce/gather hops on the owned shard see ``1/size`` of
        it from the scatter hop upstream."""
        k = self.size
        payload = _comm.dtype_wire_bytes(elems, self.dtype,
                                         compress_block)
        # ring factors: an all-reduce moves 2(k-1)/k of its buffer per
        # chip, a reduce-scatter / all-gather (k-1)/k each
        factor = 2 * (k - 1) / k if self.op == "all_reduce" \
            else (k - 1) / k
        return int(factor * payload)

    def seconds(self, elems: int,
                compress_block: int = _comm.DEFAULT_COMPRESS_BLOCK
                ) -> float:
        return (self.n_collectives() * self.alpha_us * 1e-6
                + self.wire_bytes(elems, compress_block)
                / self.bytes_per_s)


@dataclasses.dataclass(frozen=True)
class CommPlan:
    """The per-hop schedule + the provenance it was derived from.

    Reproducible by construction: two calls to :func:`plan_comm` with
    the same :class:`~apex_tpu.lint.mesh_model.MeshModel` produce the
    same plan, and the recorded α/β per hop state whether the numbers
    were linkbench-measured or the defaults table."""

    hops: Tuple[Hop, ...]
    compress_block: int
    source: str              # "measured" | "defaults"
    mesh_name: Optional[str]
    grad_bytes: Optional[int]  # payload the plan was optimized for

    # -- geometry -------------------------------------------------------------

    @property
    def is_hierarchical(self) -> bool:
        return len(self.hops) > 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        seen: List[str] = []
        for h in self.hops:
            if h.axis not in seen:
                seen.append(h.axis)
        return tuple(seen)

    @property
    def world(self) -> int:
        n, seen = 1, set()
        for h in self.hops:
            if h.axis not in seen:
                seen.add(h.axis)
                n *= h.size
        return n

    @property
    def intra(self) -> Hop:
        """The within-slice scatter hop (hierarchical plans only)."""
        return self.hops[0]

    @property
    def inter(self) -> Hop:
        """The cross-slice reduce hop (hierarchical plans only)."""
        return self.hops[1]

    def dtype_by_link(self) -> Dict[str, Optional[str]]:
        """``{link: dtype}`` — the per-hop dtype split headline (the
        reduce hops; the gather rides ici with its own dtype)."""
        out: Dict[str, Optional[str]] = {}
        for h in self.hops:
            out.setdefault(h.link, h.dtype)
        return out

    # -- accounting -----------------------------------------------------------

    def flat_ring_factor(self) -> float:
        """The per-chip ring factor of the flat all-reduce this plan
        replaces — the normalizer that keeps
        :func:`apex_tpu.parallel.comm.wire_bytes` in all-reduce-
        equivalent units across flat and hierarchical schedules."""
        n = self.world
        return 2 * (n - 1) / n

    def _hop_elems(self, elems: int) -> List[int]:
        """Bucket elements each hop actually moves: reduce/gather on
        the shard after a scatter, full size elsewhere."""
        out = []
        for h in self.hops:
            if h.op == "all_reduce" and len(self.hops) > 1:
                out.append(-(-elems // self.hops[0].size))
            else:
                out.append(elems)
        return out

    def bucket_wire_bytes(self, elems: int) -> int:
        """Per-chip ring-factored wire bytes of one bucket through the
        whole schedule."""
        return sum(h.wire_bytes(e, self.compress_block)
                   for h, e in zip(self.hops, self._hop_elems(elems)))

    def hop_seconds(self, grad_bytes: Optional[int] = None
                    ) -> List[float]:
        """α–β-predicted seconds per hop, plan order, for one full
        sync of ``grad_bytes`` (defaults to the planned payload) —
        the join key :mod:`apex_tpu.monitor.comm_drift` compares
        measured wire times against."""
        nbytes = grad_bytes if grad_bytes is not None else \
            (self.grad_bytes or 0)
        elems = nbytes // 4
        return [h.seconds(e, self.compress_block)
                for h, e in zip(self.hops, self._hop_elems(elems))]

    def predicted_seconds(self, grad_bytes: Optional[int] = None
                          ) -> Dict[str, float]:
        """Predicted seconds per link class for one full sync of
        ``grad_bytes`` (defaults to the planned payload)."""
        out: Dict[str, float] = {}
        for h, s in zip(self.hops, self.hop_seconds(grad_bytes)):
            out[h.link] = out.get(h.link, 0.0) + s
        return out

    def describe(self) -> str:
        hops = " -> ".join(
            f"{h.op}[{h.axis}={h.size}/{h.link}:"
            f"{h.dtype or 'fp32'}]" for h in self.hops)
        return f"CommPlan({hops}, {self.source})"

    def to_json(self) -> Dict:
        return {
            "version": 1, "source": self.source,
            "mesh": self.mesh_name, "grad_bytes": self.grad_bytes,
            "compress_block": self.compress_block,
            "hops": [dataclasses.asdict(h) for h in self.hops],
        }


def _choose_dtype(mk_hop, elems: int, compress_block: int,
                  min_gain: float, dtypes=DTYPE_CHOICES) -> Hop:
    """Walk the precision ladder: accept a lower-precision wire dtype
    only while it beats the current pick's predicted time by at least
    ``min_gain`` — a latency-bound hop (measured α dominating) keeps
    precision, a bandwidth-bound one compresses."""
    best = mk_hop(dtypes[0])
    for dt in dtypes[1:]:
        cand = mk_hop(dt)
        if cand.seconds(elems, compress_block) < \
                best.seconds(elems, compress_block) * (1 - min_gain):
            best = cand
    return best


def plan_comm(mesh_model, grad_bytes: int, *,
              compress_block: int = _comm.DEFAULT_COMPRESS_BLOCK,
              min_gain: float = 0.05,
              dtypes=DTYPE_CHOICES) -> CommPlan:
    """Derive the gradient-sync :class:`CommPlan` from a
    :class:`~apex_tpu.lint.mesh_model.MeshModel`.

    A model with a DCN axis yields the 3-hop hierarchical schedule
    (scatter over ICI, reduce over DCN, gather over ICI); a single-slice
    model yields a flat 1-hop plan whose dtype is still planner-chosen.
    Per-hop dtype minimizes ``α·n_collectives + wire/β`` with the
    model's measured calibration when present (``source="measured"``)
    or the defaults table (``source="defaults"``) — the provenance is
    recorded in the plan.
    """
    ici = [a for a in mesh_model.axes if a.link == "ici"]
    dcn = [a for a in mesh_model.axes if a.link == "dcn"]
    if len(ici) != 1 or len(dcn) > 1:
        raise NotImplementedError(
            f"plan_comm wants one ici axis and at most one dcn axis, "
            f"got {mesh_model!r} (nD hierarchies are ROADMAP item 1)")

    def link_params(link: str):
        cal = mesh_model.calibration.get(link) or {}
        return (float(cal.get("alpha_us", 0.0)),
                float(mesh_model.link_bytes_per_s[link]),
                bool(cal))

    elems = int(grad_bytes) // 4

    def mk(op, axis, size, link, dt):
        alpha, bps, cal = link_params(link)
        return Hop(op=op, axis=axis.name, size=size, link=link,
                   dtype=dt, alpha_us=alpha, bytes_per_s=bps,
                   calibrated=cal)

    if not dcn:
        hop = _choose_dtype(
            lambda dt: mk("all_reduce", ici[0], ici[0].size, "ici", dt),
            elems, compress_block, min_gain, dtypes)
        return CommPlan(hops=(hop,), compress_block=compress_block,
                        source=("measured" if mesh_model.measured
                                else "defaults"),
                        mesh_name=mesh_model.name,
                        grad_bytes=int(grad_bytes))

    intra, inter = ici[0], dcn[0]
    shard_elems = -(-elems // intra.size)
    rs = _choose_dtype(
        lambda dt: mk("reduce_scatter", intra, intra.size, "ici", dt),
        elems, compress_block, min_gain, dtypes)
    ar = _choose_dtype(
        lambda dt: mk("all_reduce", inter, inter.size, "dcn", dt),
        shard_elems, compress_block, min_gain, dtypes)
    ag = _choose_dtype(
        lambda dt: mk("all_gather", intra, intra.size, "ici", dt),
        elems, compress_block, min_gain, dtypes)
    return CommPlan(hops=(rs, ar, ag), compress_block=compress_block,
                    source=("measured" if mesh_model.measured
                            else "defaults"),
                    mesh_name=mesh_model.name,
                    grad_bytes=int(grad_bytes))


# --- execution ----------------------------------------------------------------

def _int8_reduce_scatter(buf: jax.Array, axis_name: str, block: int):
    """Quantize + all_to_all + exact fp32 shard sum: the within-slice
    scatter hop at ~¼ wire bytes. ``buf`` length must be a multiple of
    ``world * block``. Returns ``(shard_sum, err_local)`` — the local
    quantization error over the whole buffer, for error feedback."""
    world = jax.lax.axis_size(axis_name)
    per = buf.shape[0] // world
    q, s = _comm._quantize_int8(buf, block)
    err = buf - _comm._dequantize_int8(q, s, block)
    qt = jax.lax.all_to_all(q.reshape(world, per), axis_name,
                            split_axis=0, concat_axis=0, tiled=True)
    st = jax.lax.all_to_all(s.reshape(world, per // block), axis_name,
                            split_axis=0, concat_axis=0, tiled=True)
    deq = (qt.astype(jnp.float32).reshape(world, per // block, block)
           * st[:, :, None])
    return jnp.sum(deq, axis=0).reshape(per), err


def _reduce_scatter_hop(flat, hop: Hop, block: int, want_err: bool):
    if hop.dtype == "int8":
        shard, err = _int8_reduce_scatter(flat, hop.axis, block)
        return shard, (err if want_err else None)
    if hop.dtype == "bf16":
        wire = flat.astype(jnp.bfloat16)
        err = (flat - wire.astype(jnp.float32)) if want_err else None
        shard = jax.lax.psum_scatter(
            wire, hop.axis, scatter_dimension=0,
            tiled=True).astype(jnp.float32)
        return shard, err
    shard = jax.lax.psum_scatter(flat, hop.axis, scatter_dimension=0,
                                 tiled=True)
    return shard, None


def _all_reduce_hop(shard, hop: Hop, block: int, want_err: bool):
    """Cross-slice reduce of the owned shard. The returned error is
    already owner-resolved (each position's error re-injected exactly
    once across the DCN group)."""
    if hop.dtype == "int8":
        red, err_local, err_shard = _comm._int8_all_reduce(
            shard, hop.axis, block)
        if not want_err:
            return red, None
        rank = jax.lax.axis_index(hop.axis)
        per = shard.shape[0] // hop.size
        mine = jax.lax.dynamic_slice(err_local, (rank * per,), (per,))
        err = jax.lax.dynamic_update_slice(
            err_local, mine + err_shard, (rank * per,))
        return red, err
    if hop.dtype == "bf16":
        wire = shard.astype(jnp.bfloat16)
        err = (shard - wire.astype(jnp.float32)) if want_err else None
        return jax.lax.psum(wire, hop.axis).astype(jnp.float32), err
    return jax.lax.psum(shard, hop.axis), None


def _all_gather_hop(shard, hop: Hop, block: int, want_err: bool,
                    inter_axis: Optional[str]):
    """Gather the reduced shards back over ICI. The compression error
    is on a value replicated across slices, so only the ``inter``
    rank-0 copy feeds it back (see module docstring)."""
    def owner_mask(err):
        if err is None or inter_axis is None:
            return err
        r = jax.lax.axis_index(inter_axis)
        return jnp.where(r == 0, err, jnp.zeros_like(err))

    if hop.dtype == "int8":
        q, s = _comm._quantize_int8(shard, block)
        err = (shard - _comm._dequantize_int8(q, s, block)) \
            if want_err else None
        full_q = jax.lax.all_gather(q, hop.axis, axis=0, tiled=True)
        full_s = jax.lax.all_gather(s, hop.axis, axis=0, tiled=True)
        return (_comm._dequantize_int8(full_q, full_s, block),
                owner_mask(err))
    if hop.dtype == "bf16":
        wire = shard.astype(jnp.bfloat16)
        err = (shard - wire.astype(jnp.float32)) if want_err else None
        full = jax.lax.all_gather(wire, hop.axis, axis=0,
                                  tiled=True).astype(jnp.float32)
        return full, owner_mask(err)
    return (jax.lax.all_gather(shard, hop.axis, axis=0, tiled=True),
            None)


def hierarchical_sync(grads, plan: CommPlan, *,
                      message_size: Optional[int] = None,
                      gradient_average: bool = True,
                      gradient_predivide_factor: float = 1.0,
                      residual=None, chain: bool = True):
    """Bucketed hierarchical compressed all-reduce of a gradient
    pytree, per ``plan``. Call inside ``shard_map`` over the plan's
    axes (build the mesh with
    :func:`apex_tpu.parallel.mesh.hierarchical_data_mesh` or match the
    mesh-model axis names).

    Arithmetic knobs match :func:`apex_tpu.parallel.comm
    .bucketed_all_reduce`; with ``residual`` the return value is
    ``(synced, new_residual)`` and every hop's compression error is
    error-fed into the next step (module docstring). Per-bucket trace
    sub-spans ``bucketNN/ici`` and ``bucketNN/dcn`` scope each hop's
    collectives for the registry, apexlint and ``wire_report``'s
    per-hop split.
    """
    from apex_tpu.trace.spans import span as _span

    if not plan.is_hierarchical:
        raise ValueError("flat CommPlan — use bucketed_all_reduce with "
                         f"compress={plan.hops[0].dtype!r} (DDP routes "
                         "this automatically)")
    rs_hop, ar_hop, ag_hop = plan.hops
    block = plan.compress_block
    world_i = jax.lax.axis_size(rs_hop.axis)
    world_x = jax.lax.axis_size(ar_hop.axis)
    if world_i != rs_hop.size or world_x != ar_hop.size:
        raise ValueError(
            f"plan sizes ({rs_hop.axis}={rs_hop.size}, "
            f"{ar_hop.axis}={ar_hop.size}) do not match the mesh "
            f"({rs_hop.axis}={world_i}, {ar_hop.axis}={world_x})")
    world = world_i * world_x
    pre = gradient_predivide_factor

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    r_leaves = None
    if residual is not None:
        r_leaves = list(jax.tree_util.tree_leaves(residual))
        if len(r_leaves) != len(leaves):
            raise ValueError(
                f"residual has {len(r_leaves)} leaves, grads have "
                f"{len(leaves)} — build it with init_residual(grads)")
    want_err = r_leaves is not None

    out = list(leaves)
    token = None
    for bi, bkt in enumerate(_comm.bucket_plan(leaves, message_size)):
        with _span(f"bucket{bi:02d}", kind="collective"):
            flat = jnp.concatenate(
                [jnp.ravel(jnp.asarray(leaves[i]))
                 for i in bkt.leaf_idx]).astype(jnp.float32)
            n0 = flat.shape[0]
            if pre != 1.0:
                flat = flat / pre
            if want_err:
                flat = flat + jnp.concatenate(
                    [jnp.ravel(r_leaves[i]) for i in bkt.leaf_idx])
            # pad so every hop tiles exactly: the intra scatter needs
            # world_i | n, the DCN int8 two-phase needs
            # (world_x * block) | shard — one lcm-ish multiple covers
            # both (zeros quantize exactly; see _quantize_int8)
            mult = world_i * world_x * block
            npad = -(-n0 // mult) * mult - n0
            fpad = jnp.pad(flat, (0, npad)) if npad else flat
            if chain and token is not None:
                fpad, _ = jax.lax.optimization_barrier((fpad, token))

            per = fpad.shape[0] // world_i
            with _span("ici", kind="collective"):
                shard, err_a = _reduce_scatter_hop(fpad, rs_hop, block,
                                                   want_err)
            with _span("dcn", kind="collective"):
                shard, err_b = _all_reduce_hop(shard, ar_hop, block,
                                               want_err)
            with _span("ici", kind="collective"):
                full, err_c = _all_gather_hop(shard, ag_hop, block,
                                              want_err, ar_hop.axis)

            if gradient_average:
                post = world / pre
                if post != 1.0:
                    full = full / post
            token = full

            err = None
            if want_err:
                err = err_a if err_a is not None else \
                    jnp.zeros_like(fpad)
                shard_err = None
                for e in (err_b, err_c):
                    if e is not None:
                        shard_err = e if shard_err is None \
                            else shard_err + e
                if shard_err is not None:
                    rank_i = jax.lax.axis_index(rs_hop.axis)
                    off = rank_i * per
                    mine = jax.lax.dynamic_slice(err, (off,), (per,))
                    err = jax.lax.dynamic_update_slice(
                        err, mine + shard_err, (off,))
                err = err[:n0]

            red = full[:n0]
            off = 0
            for i in bkt.leaf_idx:
                n = _comm._leaf_size(leaves[i])
                shape = jnp.asarray(leaves[i]).shape
                out[i] = red[off:off + n].reshape(shape).astype(
                    _comm._leaf_dtype(leaves[i]))
                if err is not None:
                    r_leaves[i] = err[off:off + n].reshape(shape)
                off += n

    synced = jax.tree_util.tree_unflatten(treedef, out)
    if residual is None:
        return synced
    r_def = jax.tree_util.tree_structure(residual)
    return synced, jax.tree_util.tree_unflatten(r_def, r_leaves)


def hierarchical_pmean(x, plan: CommPlan):
    """Cross-replica mean matching the plan's topology: one psum per
    mesh axis (within-slice groups over ICI, one-member-per-slice
    groups over DCN) instead of the flat whole-mesh all-reduce a
    ``pmean`` over the axis tuple lowers to — the scalar twin of the
    hierarchical grad sync, so APX203 stays absent on the loss mean
    too."""
    for axis in plan.axis_names:
        x = jax.lax.psum(x, axis)
    return x / plan.world
