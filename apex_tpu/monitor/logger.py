"""The host half of the telemetry subsystem: buffered fetch + sinks.

``MetricsLogger`` receives the on-device :class:`~apex_tpu.monitor.Metrics`
snapshot returned by each step and *buffers the device arrays* — nothing
is fetched until ``flush()`` (every ``flush_every`` records, or at
``close()``), so the device→host transfer amortizes over N steps and the
steady-state step loop never blocks on telemetry. With jax's async
dispatch the ``record()`` call itself costs a list append and a clock
read.

On top of the in-graph counters the logger derives host-side health:

- rolling **step time** (wall clock between ``record()`` calls) and
  **throughput** over a sliding window;
- **MFU**, when the per-step model FLOPs are known — call ``attach()``
  with the jitted step and example args and they are taken from XLA's
  cost analysis (reusing :mod:`apex_tpu.prof.hlo`), the peak from
  :func:`apex_tpu.prof.device_peak_flops` (unknown chips report
  ``mfu=None``, never a misleading 0 — same contract as
  ``StepReport.table``);
- **collective bytes per step** from the compiled HLO (see
  :mod:`apex_tpu.monitor.collectives`).

Typical wiring::

    logger = monitor.MetricsLogger(
        sinks=[monitor.StdoutSink(), monitor.JSONLSink("metrics.jsonl")],
        flush_every=10)
    logger.attach(train_step, state, batch)     # statics: flops, coll bytes
    for batch in data:
        state, loss = train_step(state, batch)  # state carries .metrics
        logger.record(state.metrics)
    logger.close()
"""

from __future__ import annotations

import atexit
import collections
import math
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax

from apex_tpu.monitor.metrics import Metrics, metrics_to_dict
from apex_tpu.monitor.sinks import Sink, StdoutSink

__all__ = ["MetricsLogger", "ChannelSpec", "CHANNELS"]


class ChannelSpec(NamedTuple):
    """One declarative row of the event-channel registry: adding a
    channel is adding a row here (ctor kwarg ``{name}_sink=``, the
    ``record_*`` method, close handling and non-finite nulling all
    derive from it) — not another 30-line clone of the previous
    channel's plumbing."""

    name: str                 #: channel name; ctor kwarg = f"{name}_sink"
    kinds: Tuple[str, ...]    #: event kinds on this channel (the
                              #: ``check_metrics_schema.py --kind`` enum)
    method: str               #: the logger's record-method name
    null_nonfinite: bool      #: null Infinity/NaN before emit (the
                              #: strict-JSON contract); channels whose
                              #: emitters never produce non-finite
                              #: numbers skip the walk
    nested_null: bool = False  #: also null one level of nested dicts
                               #: (goodput's buckets_ms)
    why_unbuffered: str = ""  #: one line: why this channel must never
                              #: buffer (every record_* channel is
                              #: unbuffered; the buffered path is the
                              #: Metrics pytree via record()/flush())


#: the event-channel registry. Every channel is UNBUFFERED (events are
#: rare and forensic — a record that only landed at flush time could be
#: lost to the very crash/escalation it documents); the per-channel
#: ``why_unbuffered`` line carries the channel-specific version of that
#: argument. Validate a channel's stream with
#: ``check_metrics_schema.py --kind <name>`` (``trace`` events use
#: ``--kind trace``; the registry rows and the validator's tables are
#: kept in lockstep — scripts/check_metrics_schema.py names each
#: emitter module).
CHANNELS: Tuple[ChannelSpec, ...] = (
    ChannelSpec("trace", ("span", "step", "crash", "watchdog"),
                "record_event", False,
                why_unbuffered="host-side span/step/crash events from "
                "apex_tpu.trace; losing them to a crash would defeat "
                "the point"),
    ChannelSpec("memory", ("memory", "memory_report", "retrace",
                           "compile"), "record_memory", True,
                why_unbuffered="retrace warnings and allocator samples "
                "are rare; an OOM dump must not wait on a flush"),
    ChannelSpec("lint", ("lint_report", "lint_finding"),
                "record_lint", False,
                why_unbuffered="lint runs are rare AOT audits"),
    ChannelSpec("ckpt", ("ckpt_save", "ckpt_restore",
                         "ckpt_escalation"), "record_ckpt", True,
                why_unbuffered="an escalation record buffered to flush "
                "time would be lost to the very crash it documents"),
    ChannelSpec("guard", ("guard_anomaly", "guard_action",
                          "guard_rewind"), "record_guard", True,
                why_unbuffered="a rewind record could be lost to the "
                "escalation it precedes; a NaN-loss anomaly's z is "
                "non-finite by construction"),
    ChannelSpec("goodput", ("goodput", "straggler", "linkfit"),
                "record_goodput", True, nested_null=True,
                why_unbuffered="per-step attribution and straggler "
                "warnings are forensic; a zero-wall warmup step has "
                "no finite goodput fraction (nested buckets nulled)"),
    ChannelSpec("roofline", ("roofline", "regress", "tune"),
                "record_roofline", True,
                why_unbuffered="roofline joins, sentinel verdicts and "
                "autotune sweep/consult records are rare AOT/offline "
                "audits"),
    ChannelSpec("cluster", ("cluster_lease", "cluster_generation",
                            "cluster_fence", "cluster_coord"),
                "record_cluster", True,
                why_unbuffered="a fence refusal usually precedes the "
                "zombie exit it documents"),
    ChannelSpec("integrity", ("integrity_check", "integrity_vote",
                              "integrity_repair"), "record_integrity",
                True,
                why_unbuffered="a divergence vote could be lost to "
                "the rewind/escalation it precedes"),
    ChannelSpec("numerics", ("numerics_check", "scale_update",
                             "precision_verdict"), "record_numerics",
                True,
                why_unbuffered="scale backoffs and precision verdicts "
                "are rare and may immediately precede the overflow "
                "skip they explain"),
    ChannelSpec("podview", ("pod_align", "pod_skew", "pod_drift"),
                "record_podview", True,
                why_unbuffered="pod merges and drift reports are rare "
                "offline/audit joins, and a skew-blame record may "
                "immediately precede the straggler escalation it "
                "explains (an unaligned rank's residual is null)"),
    ChannelSpec("sharding", ("sharding_mesh", "sharding"),
                "record_sharding", True,
                why_unbuffered="per-axis attribution rows are rare AOT "
                "audits (shard_report / mesh_explain pre-flights), and "
                "an unmeasured link's predicted_s is null by contract"),
    ChannelSpec("dynamics", ("dynamics_check", "gns",
                             "convergence_verdict"), "record_dynamics",
                True,
                why_unbuffered="dynamics checks ride the amortized "
                "host-poll cadence already, a convergence flag may "
                "immediately precede the abort it argues for, and an "
                "undefined GNS estimate is null by contract"),
)

def _null_nonfinite(rec: Dict, nested: bool) -> None:
    """Null non-finite numbers in place (Infinity/NaN are not valid
    strict JSON; the schema contract is finite-or-null — the *event*
    behind a non-finite gauge is already counted elsewhere)."""
    for k, v in rec.items():
        if isinstance(v, float) and not math.isfinite(v):
            rec[k] = None
        elif nested and isinstance(v, dict):
            rec[k] = {kk: (None if isinstance(vv, float)
                           and not math.isfinite(vv) else vv)
                      for kk, vv in v.items()}


def _channel_method(spec: ChannelSpec):
    def _record(self, event: Dict) -> None:
        sink = getattr(self, f"{spec.name}_sink")
        if sink is None or self._closed:
            return
        rec = dict(event)
        if spec.null_nonfinite:
            _null_nonfinite(rec, spec.nested_null)
        sink.emit(rec)

    _record.__name__ = spec.method
    _record.__doc__ = (
        f"Emit one {spec.name}-channel event (``kind`` in "
        f"{spec.kinds}) — a plain-dict pass-through, no device "
        f"access, NOTHING buffered: {spec.why_unbuffered}. "
        + ("Non-finite numbers are nulled to keep the strict-JSON "
           "contract. " if spec.null_nonfinite else "")
        + f"Validate the stream with ``check_metrics_schema.py "
        f"--kind {spec.name}``.")
    return _record


class MetricsLogger:
    """See the module docstring. The logger is a context manager and
    registers itself with ``atexit``, so a crashed run never loses its
    buffered tail: ``__exit__`` flushes on exceptions too, and an
    un-``close()``d logger (hard ``sys.exit``, unhandled error above the
    ``with``) is flushed at interpreter exit.

    Beyond the buffered metrics stream, the logger carries one
    **unbuffered event channel per** :data:`CHANNELS` **row** — pass
    ``{name}_sink=`` (``trace_sink=``, ``guard_sink=``, …,
    ``podview_sink=``) and feed events through the matching
    ``record_*`` method; each channel's stream validates under
    ``check_metrics_schema.py --kind {name}``. Events never mix with
    the metrics wire format. Adding a channel is one registry row, not
    another clone of this plumbing.
    """

    def __init__(self, sinks: Optional[Sequence[Sink]] = None, *,
                 flush_every: int = 10, window: int = 50,
                 peak_flops: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 collective_bytes_per_step: Optional[int] = None,
                 logical_collective_bytes: Optional[int] = None,
                 donation_safe: bool = False,
                 **channel_sinks: Optional[Sink]):
        self.sinks: List[Sink] = (list(sinks) if sinks is not None
                                  else [StdoutSink()])
        self.flush_every = max(int(flush_every), 1)
        self.flops_per_step = flops_per_step
        self.collective_bytes_per_step = collective_bytes_per_step
        # the event channels: one ``{name}_sink`` attribute + one
        # ``record_*`` method per CHANNELS row (the registry is the
        # single source of truth — docstrings, nulling policy and
        # close() all derive from it)
        valid = {f"{c.name}_sink" for c in CHANNELS}
        unknown = set(channel_sinks) - valid
        if unknown:
            raise TypeError(
                f"MetricsLogger got unknown channel sink(s) "
                f"{sorted(unknown)}; known channels: {sorted(valid)}")
        for spec in CHANNELS:
            setattr(self, f"{spec.name}_sink",
                    channel_sinks.get(f"{spec.name}_sink"))
        self.memory_report = None      # last attached prof.MemoryReport
        self.lint_report = None        # last attached lint.Report
        self.roofline_report = None    # last attached RooflineReport
        self.shard_report = None       # last attached prof.ShardReport
        #: the uncompressed payload one step SEMANTICALLY moves (e.g.
        #: ``4 * n_params`` for an fp32 grad sync) — enables the
        #: per-record ``wire_to_logical`` ratio, same contract as
        #: :func:`apex_tpu.monitor.wire_report`
        self.logical_collective_bytes = logical_collective_bytes
        #: per-dtype wire breakdown from the compiled step (set by
        #: :meth:`attach`): ``{dtype: bytes}`` — the stdout table's
        #: logical-vs-wire columns read it
        self.collective_bytes_by_dtype: Optional[Dict[str, int]] = None
        #: snapshot each recorded metrics pytree into fresh device
        #: buffers (async scalar copies). REQUIRED when the step is
        #: jitted with donate_argnums over the state carrying the
        #: metrics: donation invalidates the input buffers on the next
        #: dispatch, and an un-snapshotted buffered record would be
        #: "Array has been deleted" by flush time.
        self.donation_safe = donation_safe
        if peak_flops is None:
            from apex_tpu.prof.report import device_peak_flops
            peak_flops = device_peak_flops() or None
        self.peak_flops = peak_flops
        # buffered device snapshots + their host receipt times
        self._buf: List[Metrics] = []
        self._times: List[float] = []
        self._last_time: Optional[float] = None
        # sliding (time) window for throughput; bounded deque
        self._window = collections.deque(maxlen=max(int(window), 2))
        self._closed = False
        # crash-safe tail: flush whatever is buffered at interpreter
        # exit if the run never reached close()
        atexit.register(self._atexit_close)

    # -- compile-time statics ------------------------------------------------

    def attach(self, step_fn, *args, **kwargs) -> "MetricsLogger":
        """Derive per-step statics from the compiled step: model FLOPs
        (XLA cost analysis) and collective traffic (optimized HLO), from
        ONE AOT compile of ``step_fn`` — an upfront cost paid once at
        setup, never per step. Statics the caller already set explicitly
        (constructor kwargs) are kept, and nothing compiles when both
        are preset."""
        from apex_tpu.monitor.collectives import (
            collective_bytes_by_dtype, collective_bytes_from_text)
        from apex_tpu.prof import hlo as _hlo
        if (self.flops_per_step is not None
                and self.collective_bytes_per_step is not None):
            # the preset path stays compile-free (its whole point); the
            # per-dtype wire split then simply stays unset (the stdout
            # table shows n/a) unless the caller sets
            # collective_bytes_by_dtype directly
            return self
        compiled = _hlo._compile(step_fn, *args, **kwargs)
        hlo_text = compiled.as_text()
        if self.flops_per_step is None:
            flops = float(_hlo.cost_analysis_of(compiled).get("flops", 0.0))
            self.flops_per_step = flops if flops > 0 else None
        if self.collective_bytes_by_dtype is None:
            # one {dtype: bytes} rollup over the opcodes — the
            # wire_report breakdown that makes compressed sync auditable
            # from the live table (a bf16 DDP step shows bf16 wire
            # bytes at half its fp32 logical payload)
            per: Dict[str, int] = {}
            for per_op in collective_bytes_by_dtype(hlo_text).values():
                for dt, nbytes in per_op.items():
                    per[dt] = per.get(dt, 0) + nbytes
            self.collective_bytes_by_dtype = per
        if self.collective_bytes_per_step is None:
            self.collective_bytes_per_step = collective_bytes_from_text(
                hlo_text).get("total", 0)
        return self

    # -- per-step path (cheap, never syncs) ----------------------------------

    def record(self, metrics: Metrics, **extra) -> None:
        """Buffer one device snapshot. ``extra`` keys (host scalars only)
        are merged into the emitted record at flush."""
        if self.donation_safe:
            from apex_tpu.monitor.metrics import metrics_snapshot
            metrics = metrics_snapshot(metrics)
        now = time.perf_counter()
        self._buf.append((metrics, dict(extra)) if extra else (metrics, None))
        self._times.append(now)
        self._window.append(now)
        if len(self._buf) >= self.flush_every:
            self.flush()

    # -- amortized fetch + emit ----------------------------------------------

    def _throughput(self) -> Optional[float]:
        if len(self._window) < 2:
            return None
        dt = self._window[-1] - self._window[0]
        if dt <= 0:
            return None
        return (len(self._window) - 1) / dt

    def flush(self) -> None:
        """One device→host fetch for every buffered snapshot, then emit."""
        if not self._buf:
            return
        buf, times = self._buf, self._times
        self._buf, self._times = [], []
        try:
            host = jax.device_get([m for m, _ in buf])
        except RuntimeError:
            # a donated step invalidated buffered snapshots (the caller
            # should pass donation_safe=True) — salvage what survives
            # record-by-record instead of losing the whole window
            host = []
            for m, _ in buf:
                try:
                    host.append(jax.device_get(m))
                except RuntimeError:
                    host.append(None)
            buf = [b for b, h in zip(buf, host) if h is not None]
            times = [t for t, h in zip(times, host) if h is not None]
            host = [h for h in host if h is not None]
        thru = self._throughput()
        for (_, extra), m, t in zip(buf, host, times):
            rec: Dict = metrics_to_dict(m)
            if self._last_time is None:
                rec["step_time_ms"] = None
            else:
                rec["step_time_ms"] = (t - self._last_time) * 1e3
            self._last_time = t
            rec["throughput_steps_per_s"] = thru
            if thru and self.flops_per_step and self.peak_flops:
                rec["mfu"] = self.flops_per_step * thru / self.peak_flops
            else:
                rec["mfu"] = None
            rec["collective_bytes"] = self.collective_bytes_per_step
            # the per-dtype logical-vs-wire split (wire_report's
            # accounting, attached per record so compressed-sync runs
            # show their ratio without a separate script)
            rec["wire_by_dtype"] = self.collective_bytes_by_dtype
            if (self.logical_collective_bytes
                    and self.collective_bytes_per_step is not None):
                rec["logical_bytes"] = self.logical_collective_bytes
                rec["wire_to_logical"] = (self.collective_bytes_per_step
                                          / self.logical_collective_bytes)
            else:
                rec["logical_bytes"] = self.logical_collective_bytes
                rec["wire_to_logical"] = None
            rec["wall_time"] = time.time()
            if extra:
                rec.update(extra)
            # non-finite gauges (diverged loss, ...) become null on the
            # wire: Infinity/NaN are not valid strict JSON, and the
            # schema contract is finite-or-null (the *event* is already
            # counted in overflow_count)
            for k, v in rec.items():
                if isinstance(v, float) and not math.isfinite(v):
                    rec[k] = None
            for sink in self.sinks:
                sink.emit(rec)

    # -- event channels ------------------------------------------------------
    # record_event / record_memory / record_lint / record_ckpt /
    # record_guard / record_goodput / record_roofline / record_cluster /
    # record_integrity / record_numerics / record_podview /
    # record_sharding are generated
    # from the CHANNELS
    # registry after the class body — one declarative row per channel,
    # not one 30-line clone. Typical wirings (see each subsystem's
    # docs): ``tracer.subscribe(lambda st: logger.record_event(
    # st.to_event(rank)))``, ``CompileWatcher.subscribe(
    # logger.record_memory)``, ``CheckpointManager(event_sink=
    # logger.record_ckpt)``, ``GuardPolicy(event_sink=
    # logger.record_guard, integrity_sink=logger.record_integrity)``,
    # ``GoodputLedger.subscribe(logger.record_goodput)``,
    # ``ClusterMembership(event_sink=logger.record_cluster)``, and the
    # numerics observatory's host poll feeding ``record_numerics``.

    def sample_memory(self, step: Optional[int] = None, *,
                      device=None, **extra) -> Optional[Dict]:
        """Sample the device allocator (``device.memory_stats()`` — a
        host-side runtime call, zero device dispatches) and emit one
        ``kind="memory"`` event. Off-TPU backends report no stats; the
        event still lands (values null) so the stream shape is uniform.
        Returns the emitted record (or None when there is no sink)."""
        from apex_tpu.prof.memory import device_memory_sample
        if self.memory_sink is None or self._closed:
            return None
        rec: Dict = {"kind": "memory", "step": step, "rank": 0,
                     "wall_time": time.time()}
        try:
            import jax as _jax
            rec["rank"] = _jax.process_index()
        except Exception:
            pass
        rec.update(device_memory_sample(device))
        if extra:
            rec.update(extra)
        self.record_memory(rec)
        return rec

    def attach_memory_report(self, report) -> "MetricsLogger":
        """Attach a :class:`apex_tpu.prof.MemoryReport` (the compiled
        step's footprint): emits one ``kind="memory_report"`` event and
        keeps the report for consumers (``bench.py`` reads
        ``peak_live_bytes``; hand it to
        ``FlightRecorder.attach_memory_report`` too so crash dumps name
        the biggest buffers)."""
        self.memory_report = report
        if report is not None:
            try:
                rank = jax.process_index()
            except Exception:
                rank = 0
            self.record_memory(report.to_event(rank=rank))
        return self

    def attach_shard_report(self, report,
                            step: Optional[int] = None,
                            **to_events_kwargs) -> "MetricsLogger":
        """Attach an :class:`apex_tpu.prof.ShardReport` (the compiled
        step's per-axis HBM disposition): emits its ``sharding_mesh``
        header + one ``kind="sharding"`` row per axis on the sharding
        channel and keeps the report for consumers (``bench.py`` reads
        the per-axis bytes into its ``axis_hbm`` column). Extra kwargs
        (``wire_by_axis=``, ``predicted_s=``, ``candidate=``) pass
        through to :meth:`~apex_tpu.prof.ShardReport.to_events`."""
        self.shard_report = report
        if report is not None:
            try:
                rank = jax.process_index()
            except Exception:
                rank = 0
            for ev in report.to_events(rank=rank, step=step,
                                       **to_events_kwargs):
                self.record_sharding(ev)
        return self

    def attach_lint_report(self, report,
                           step: Optional[int] = None) -> "MetricsLogger":
        """Attach an :class:`apex_tpu.lint.Report`: emits its
        ``lint_report`` header + one ``lint_finding`` event per finding
        and keeps the report for consumers (``bench.py`` reads the
        finding count into its default JSON)."""
        self.lint_report = report
        if report is not None:
            for ev in report.to_events(step=step):
                self.record_lint(ev)
        return self

    def attach_roofline_report(self, report,
                               step: Optional[int] = None,
                               top: Optional[int] = None
                               ) -> "MetricsLogger":
        """Attach an :class:`apex_tpu.prof.RooflineReport`: emits one
        ``kind="roofline"`` event per row (``top`` bounds it) and keeps
        the report for consumers (``bench.py`` reads ``worst_gaps``
        into its default JSON)."""
        self.roofline_report = report
        if report is not None:
            try:
                rank = jax.process_index()
            except Exception:
                rank = 0
            for ev in report.to_events(rank=rank, step=step, top=top):
                self.record_roofline(ev)
        return self

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        for sink in self.sinks:
            sink.close()
        for spec in CHANNELS:
            sink = getattr(self, f"{spec.name}_sink")
            if sink is not None:
                sink.close()
        self._closed = True
        atexit.unregister(self._atexit_close)

    def _atexit_close(self) -> None:
        try:
            self.close()
        except Exception:
            pass          # a dead backend at exit must not mask the exit

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        # flushes buffered rows on the exception path too — the tail of
        # a crashed run's metrics reaches the sinks before unwind
        self.close()


# materialize one record method per registry row (record_event,
# record_memory, ..., record_numerics) — the registry is the single
# source of truth for channel names, nulling policy and docstrings
for _spec in CHANNELS:
    setattr(MetricsLogger, _spec.method, _channel_method(_spec))
del _spec
