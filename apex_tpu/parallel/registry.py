"""Declarative registry of every collective scope this stack emits.

The reference builds its process sub-groups imperatively and scatters
the knowledge of "who communicates over what" across modules
(`apex/parallel/__init__.py:21-95` SyncBN groups,
`apex/parallel/distributed.py:604-624` allreduce groups,
`apex/contrib/optimizers/distributed_fused_adam.py:250-290`
hierarchical groups). Here the same knowledge is ONE table: each
:class:`CollectiveScope` entry names the named-scope pattern a planned
collective runs under, the canonical mesh axis it communicates over,
and the subsystem that owns it.

Two consumers read the table (keep them in lockstep by construction —
they import this module, nothing is duplicated):

- **apexlint APX102/APX202** (:mod:`apex_tpu.lint.hlo_pass`,
  :mod:`apex_tpu.lint.spmd_pass`): a compiled collective whose stripped
  scope matches no entry is a reshard nobody planned;
- **the mesh model** (:mod:`apex_tpu.lint.mesh_model`): a matched scope
  resolves to its mesh axis, so topology rules (APX203) can say *which*
  axis a DCN-crossing collective was reduced over and which link class
  its bytes ride.

This table is the seed of ROADMAP item 1's ``MeshPlan``: when the
(dp, tp, pp, sp, zero) axes land, each new subsystem registers its
collective scopes here — one row per planned collective family, next
to nothing else.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence, Tuple

from apex_tpu.parallel.mesh import (DATA_AXIS, DATA_INTER_AXIS,
                                    DATA_INTRA_AXIS, SEQ_AXIS)

__all__ = ["CollectiveScope", "COLLECTIVE_SCOPES", "known_patterns",
           "scope_axis", "scope_entry"]


@dataclasses.dataclass(frozen=True)
class CollectiveScope:
    """One planned collective family: where it runs, over which axis."""

    pattern: str      # regex fragment matched against the STRIPPED
                      # scope path (prof.xplane.strip_scope output)
    axis: str         # canonical mesh-axis name it communicates over
    subsystem: str    # owning subsystem (ddp, zero, sync_batchnorm, ...)
    description: str  # one-line: what the collective does


#: the one canonical allowlist — every collective this package
#: deliberately emits runs under a named scope matching exactly one row.
#: A compiled collective matching none of them is a reshard nobody
#: asked for (apexlint APX102/APX202).
COLLECTIVE_SCOPES: Tuple[CollectiveScope, ...] = (
    # hop sub-spans of the hierarchical schedule FIRST: scope_entry
    # returns the first match, and the hierarchical hops nest under
    # ddp/sync_gradients (``ddp/sync_gradients/bucketNN/ici``) — the
    # parent row would otherwise swallow the factored-axis attribution
    # (canonical names — a deployment using its mesh model's own axis
    # names still matches the pattern)
    CollectiveScope(r"(^|/)bucket\d+/ici", DATA_INTRA_AXIS, "ddp",
                    "hierarchical sync within-slice hop (reduce-"
                    "scatter / all-gather over ICI)"),
    CollectiveScope(r"(^|/)bucket\d+/dcn", DATA_INTER_AXIS, "ddp",
                    "hierarchical sync cross-slice hop (one-member-"
                    "per-slice reduce over DCN)"),
    # dynamics sub-spans BEFORE the parent sync row for the same
    # first-match reason: a probe called inside the sync scope nests as
    # ``ddp/sync_gradients/…/dynamics_gns`` and the parent pattern
    # would swallow it
    CollectiveScope(r"ddp/dynamics_gns", DATA_AXIS, "ddp",
                    "gradient-noise-scale probe: one scalar psum of "
                    "the per-replica squared grad norm "
                    "(apex_tpu.monitor.dynamics)"),
    CollectiveScope(r"ddp/dynamics_geom", DATA_AXIS, "ddp",
                    "replica-gradient geometry probe: all-gather of "
                    "the per-replica [|g_i|^2, g_i.gbar] scalar pair "
                    "(cosine spectrum + Adasum projection "
                    "coefficients)"),
    CollectiveScope(r"ddp/sync_gradients", DATA_AXIS, "ddp",
                    "gradient all-reduce across the data axis"),
    CollectiveScope(r"(^|/)bucket\d+", DATA_AXIS, "ddp",
                    "per-bucket overlapped all-reduce sub-spans"),
    CollectiveScope(r"ddp/loss_pmean", DATA_AXIS, "ddp",
                    "cross-replica loss averaging for the logged "
                    "metric"),
    CollectiveScope(r"(?i)sync_?batch_?norm", DATA_AXIS,
                    "sync_batchnorm",
                    "cross-replica batch-norm statistics psums"),
    CollectiveScope(r"zero/(grad_scatter|param_gather)", DATA_AXIS,
                    "zero",
                    "ZeRO gradient reduce-scatter / parameter "
                    "all-gather"),
    CollectiveScope(r"guard/integrity_(check|repair)", DATA_AXIS,
                    "guard",
                    "cross-replica integrity fingerprint compare "
                    "(pmin/pmax/all-gather of one uint32 scalar) and "
                    "the in-place repair bit-pattern broadcast"),
    CollectiveScope(r"(^|/)ring_", SEQ_AXIS, "ring_attention",
                    "ring/Ulysses sequence-parallel attention "
                    "permutes and all-to-alls"),
)


def known_patterns() -> Tuple[str, ...]:
    """The regex fragments, in registry order — the APX102 allowlist
    (re-exported as ``parallel.distributed.KNOWN_COLLECTIVE_SCOPES``
    for backward compatibility)."""
    return tuple(s.pattern for s in COLLECTIVE_SCOPES)


def scope_entry(scope: str,
                extra: Sequence[str] = ()) -> Optional[CollectiveScope]:
    """The registry row a stripped scope path matches, or None.

    ``extra`` patterns (per-call allowlist extensions, the
    ``known_scopes=`` lint argument) match as anonymous rows with no
    axis attribution."""
    for entry in COLLECTIVE_SCOPES:
        if re.search(entry.pattern, scope):
            return entry
    for pat in extra:
        if re.search(pat, scope):
            return CollectiveScope(pat, "", "user", "caller-supplied "
                                   "known_scopes= pattern")
    return None


def scope_axis(scope: str) -> Optional[str]:
    """Canonical mesh axis a planned collective scope communicates
    over, or None for an unknown scope."""
    entry = scope_entry(scope)
    return entry.axis if entry is not None else None
