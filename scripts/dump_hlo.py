"""Dump the optimized HLO of the ResNet-50 bench step to a file.

Usage: python scripts/dump_hlo.py OUT.txt [--unfused] [--batch N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    out_path = sys.argv[1]
    fused = "--unfused" not in sys.argv
    batch = 256
    if "--batch" in sys.argv:
        batch = int(sys.argv[sys.argv.index("--batch") + 1])

    from apex_tpu import amp, models, ops
    from apex_tpu.optim import FusedSGD
    from apex_tpu.prof import hlo as _hlo

    policy = amp.Policy.from_opt_level("O2")
    model = models.ResNet50(num_classes=1000, dtype=policy.compute_dtype,
                            fused_bn=fused)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 224, 224, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    amp_opt = amp.Amp(policy, FusedSGD(lr=0.1, momentum=0.9))
    state = amp_opt.init(params)

    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            return loss, mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    text = _hlo.compiled_hlo(jstep, state, batch_stats, x, y)
    with open(out_path, "w") as f:
        f.write(text)
    print(f"wrote {len(text)} chars to {out_path}")


if __name__ == "__main__":
    main()
