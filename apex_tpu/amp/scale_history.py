"""Per-tensor delayed scaling: the loss scaler generalized per site.

ROADMAP item 5 names this verbatim: "delayed/dynamic scaling as an
AmpState extension (the loss-scale machinery generalizes to per-tensor
scale histories)". The dynamic loss scaler
(:mod:`apex_tpu.amp.scaler`) keeps ONE scalar for the whole backward;
fp8 needs one scale per cast site, derived from that site's *measured*
amax history rather than from overflow trial-and-error — the standard
delayed-scaling recipe. This module lands the state machine as pure
state machinery (no fp8 kernels yet — those are item 5's second half):

- :class:`ScaleHistoryState` carries, per site, a rolling **amax
  window** (``f32[S, window]``), the current scale, a growth tracker
  and a cumulative overflow counter — a pytree next to
  ``LossScaleState`` in the train state: checkpointable, donate-able,
  ``lax.scan``-carryable;
- :func:`scale_history_update` folds one step's per-site amax (the
  :class:`~apex_tpu.monitor.numerics.NumericsState` ``amax`` row, or a
  directly-computed ``jnp.max(jnp.abs(x))``) and derives the
  **next-step scale**:

  ``scale = 2 ** floor(log2(fmt.max_finite / (margin · max(window))))``

  clamped to ``[min_scale, max_scale]`` — always a power of two, so
  scaling is exact (exponent shift, zero rounding);
- the **growth/backoff semantics are the loss scaler's**
  (`scaler.py` parity): a nonfinite amax this step = an overflow event
  — ``scale *= backoff_factor`` immediately, tracker reset, the window
  slot records the previous window max (a poisoned measurement must
  not enter the history); upward moves are rate-limited to
  ``growth_factor`` per ``growth_interval`` consecutive clean steps,
  so a transiently small window cannot leap the scale and saturate on
  the next real activation.

The update is deterministic arithmetic: a synthetic amax ramp tracks a
pure-numpy oracle **exactly** (``scripts/numerics_audit.py --cpu8``
claim (c) asserts it; tests/test_numerics.py carries the unit twin).
Scale *changes* are reported as ``kind="scale_update"`` events
(:func:`scale_update_events`) on the numerics channel.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.monitor.numerics import FORMAT_TABLE

__all__ = ["ScaleHistoryConfig", "ScaleHistoryState",
           "scale_history_init", "scale_history_update",
           "scale_update_events"]


class ScaleHistoryConfig(NamedTuple):
    """Static per-tensor delayed-scaling configuration (hashable; safe
    to close over in jit). Defaults mirror
    :class:`~apex_tpu.amp.scaler.LossScaleConfig` where the semantics
    are shared (growth ×2, backoff ×0.5)."""

    fmt: str = "fp8_e4m3"          #: target format (FORMAT_TABLE key)
    window: int = 16               #: amax history length in updates
    margin: float = 2.0            #: headroom divisor under max_finite
    growth_factor: float = 2.0     #: max upward scale move per interval
    backoff_factor: float = 0.5    #: overflow response (shared w/ loss
                                   #: scaler)
    growth_interval: int = 1       #: clean updates per upward move
    min_scale: float = 2.0 ** -64
    max_scale: float = 2.0 ** 64


class ScaleHistoryState(NamedTuple):
    """Per-site delayed-scaling state — ``[S]``-row device arrays, one
    row per site in the companion :func:`site_names
    <apex_tpu.monitor.numerics.site_names>` tuple's order."""

    amax_history: jax.Array    # f32[S, window] rolling amax window
    cursor: jax.Array          # i32 next window slot (shared; updates
                               #   are lockstep across sites)
    scale: jax.Array           # f32[S] the NEXT step's scale
    growth_tracker: jax.Array  # i32[S] consecutive clean updates
    overflow_count: jax.Array  # i32[S] cumulative nonfinite-amax events
    step: jax.Array            # i32 updates folded


def scale_history_init(cfg: ScaleHistoryConfig = ScaleHistoryConfig(),
                       *, n_sites: int) -> ScaleHistoryState:
    """Fresh per-site scale state — thread through the step like
    ``LossScaleState``. Scales start at 1.0 and converge to the
    window-derived value within ``window`` updates (the delayed-
    scaling warmup; docs/numerics.md#delayed-scaling)."""
    if cfg.fmt not in FORMAT_TABLE:
        raise ValueError(f"ScaleHistoryConfig.fmt must be one of "
                         f"{tuple(FORMAT_TABLE)}, got {cfg.fmt!r}")
    if int(cfg.window) < 1:
        raise ValueError(f"window must be >= 1, got {cfg.window}")
    if int(n_sites) < 1:
        raise ValueError(f"n_sites must be >= 1, got {n_sites}")
    if not 0.0 < float(cfg.backoff_factor) < 1.0:
        raise ValueError("backoff_factor must be in (0, 1)")
    if float(cfg.growth_factor) < 1.0:
        raise ValueError("growth_factor must be >= 1")
    import math as _math
    for name in ("growth_factor", "backoff_factor", "min_scale",
                 "max_scale"):
        v = float(getattr(cfg, name))
        if not (v > 0 and _math.frexp(v)[0] == 0.5):
            # every factor the scale is ever multiplied or clipped by
            # must itself be a power of two, or the "scaling is an
            # exact exponent shift" invariant (and the schema's
            # power-of-two-gauge claim) silently breaks on the first
            # backoff
            raise ValueError(f"ScaleHistoryConfig.{name} must be a "
                             f"power of two — scales stay exact "
                             f"exponent shifts — got {v}")
    s = int(n_sites)
    return ScaleHistoryState(
        amax_history=jnp.zeros((s, int(cfg.window)), jnp.float32),
        cursor=jnp.int32(0),
        scale=jnp.ones((s,), jnp.float32),
        growth_tracker=jnp.zeros((s,), jnp.int32),
        overflow_count=jnp.zeros((s,), jnp.int32),
        step=jnp.int32(0))


def _pow2_floor(x: jax.Array) -> jax.Array:
    """2**floor(log2(x)) elementwise for positive finite x — exact
    power-of-two quantization of the derived scale. Derived from the
    float's own exponent field (``frexp``: x = m·2^e, m ∈ [0.5, 1), so
    floor(log2 x) = e−1) and rebuilt with ``ldexp`` — bit-exact, where
    an ``exp2(floor(log2 x))`` chain rounds through the transcendental
    lowering (observed: 131072.06 on the CPU backend)."""
    _m, e = jnp.frexp(x)
    return jnp.ldexp(jnp.ones_like(x), e - 1)


def scale_history_update(sh: ScaleHistoryState,
                         cfg: ScaleHistoryConfig,
                         amax: jax.Array) -> ScaleHistoryState:
    """Fold one step's per-site amax (``f32[S]``, the measured
    ``max|x|`` of each site's tensor at its CURRENT precision — from
    the numerics observatory use
    :func:`apex_tpu.monitor.numerics.scale_amax`, NOT ``ns.amax``:
    the state's amax is the finite max by design and alone never
    carries the nonfinite overflow signal the backoff keys on) and
    derive the next-step scales. Pure ``jnp`` — rides the existing
    dispatch (the ``numerics/no-extra-dispatch`` compile-check case
    drives it inside the instrumented step).

    Semantics per site, in loss-scaler terms (`scaler.py` parity):

    - **overflow** (amax nonfinite): ``scale *= backoff_factor``
      (clamped at ``min_scale``), tracker reset, the window records
      the previous window max instead of the poisoned measurement;
    - **clean**: the window records amax; the window-derived target
      ``2**floor(log2(max_finite / (margin · window_max)))`` applies
      immediately when it moves the scale DOWN (saturation danger is
      never rate-limited), and upward only after ``growth_interval``
      consecutive clean updates and by at most ``growth_factor`` per
      update (then the tracker resets) — growth interval 1 with a
      large factor reproduces plain delayed scaling.
    """
    amax = jnp.asarray(amax, jnp.float32)
    if amax.shape != sh.scale.shape:
        raise ValueError(f"amax shape {amax.shape} != n_sites "
                         f"{sh.scale.shape}")
    fmt = FORMAT_TABLE[cfg.fmt]
    finite = jnp.isfinite(amax)
    prev_max = jnp.max(sh.amax_history, axis=1)
    recorded = jnp.where(finite, amax, prev_max)
    hist = sh.amax_history.at[:, sh.cursor % cfg.window].set(recorded)
    window_max = jnp.max(hist, axis=1)

    # the delayed-scaling target from the measured window
    target = jnp.where(
        window_max > 0,
        _pow2_floor(fmt.max_finite / (cfg.margin * window_max)),
        sh.scale)
    target = jnp.clip(target, cfg.min_scale, cfg.max_scale)

    tracker = jnp.where(finite, sh.growth_tracker + 1,
                        jnp.int32(0))
    may_grow = tracker >= cfg.growth_interval
    grown = jnp.minimum(target,
                        jnp.minimum(sh.scale * cfg.growth_factor,
                                    cfg.max_scale))
    clean_scale = jnp.where(target < sh.scale, target,
                            jnp.where(may_grow, grown, sh.scale))
    backed_off = jnp.maximum(sh.scale * cfg.backoff_factor,
                             cfg.min_scale)
    new_scale = jnp.where(finite, clean_scale,
                          backed_off).astype(jnp.float32)
    new_tracker = jnp.where(
        finite,
        jnp.where(jnp.logical_and(may_grow, grown > sh.scale),
                  jnp.int32(0), tracker),
        jnp.int32(0)).astype(jnp.int32)
    return ScaleHistoryState(
        amax_history=hist,
        cursor=(sh.cursor + 1) % jnp.int32(cfg.window),
        scale=new_scale,
        growth_tracker=new_tracker,
        overflow_count=(sh.overflow_count
                        + jnp.where(finite, 0, 1).astype(jnp.int32)),
        step=sh.step + 1)


def scale_update_events(prev: ScaleHistoryState,
                        new: ScaleHistoryState,
                        sites: Sequence[str], *,
                        rank: int = 0,
                        include_holds: bool = False) -> List[Dict]:
    """Host-side diff of two consecutive states into
    ``kind="scale_update"`` events — one per site whose scale MOVED
    (action ``grow``/``backoff``; ``include_holds`` adds ``hold`` rows
    for the rest). Fetches both states once; wire through
    ``MetricsLogger(numerics_sink=…)``. Under a donating step, fetch
    ``prev`` (``jax.device_get``) BEFORE the next dispatch — donation
    invalidates its buffers, the same hazard
    ``MetricsLogger(donation_safe=)`` covers for metrics."""
    import numpy as np
    p, n = jax.device_get((prev, new))
    ps, nsc = np.asarray(p.scale), np.asarray(n.scale)
    over = np.asarray(n.overflow_count) - np.asarray(p.overflow_count)
    amax = np.asarray(
        n.amax_history[:, int(np.asarray(p.cursor)) % p.amax_history.shape[1]])
    step = int(np.asarray(n.step))
    events: List[Dict] = []
    for i, site in enumerate(sites):
        if nsc[i] > ps[i]:
            action = "grow"
        elif nsc[i] < ps[i]:
            action = "backoff" if over[i] > 0 else "shrink"
        else:
            if not include_holds:
                continue
            action = "hold"
        a: Optional[float] = float(amax[i])
        events.append({"kind": "scale_update", "rank": rank,
                       "step": step, "site": site, "action": action,
                       "scale": float(nsc[i]),
                       "prev_scale": float(ps[i]),
                       "amax": a if np.isfinite(a) else None})
    return events
