"""Training-dynamics observatory tests (docs/dynamics.md).

Covers: the in-graph fold (cadence, EMA seeding, sentinel semantics,
companion mechanics, shape validation), the GNS estimator algebra on a
hand-computed case, the replica-geometry join, the host report
(nulls-by-contract, med/MAD effective-LR outliers, fixture
round-trip), the convergence band comparator, the 13th metrics
channel + schema negative twins, the `Amp.step(dynamics=)` hook with
the O0-O3 bitwise observation-parity sweep, the `ddp/dynamics_*`
registry pins, and the sentinel's new direction-aware columns."""

import io
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, monitor, parallel
from apex_tpu.monitor import convergence as cv
from apex_tpu.monitor import dynamics as dx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _observe_once(trees, grads=None, weights=None, cfg=None, world=1):
    cfg = cfg or dx.DynamicsConfig()
    sites = dx.site_names(trees)
    ds = dx.dynamics_init(cfg, sites=sites, world=world)
    ds = dx.dynamics_observe(ds, cfg, trees, grads=grads,
                             weights=weights)
    return ds, sites


def _probe(local_sq_mean, pooled_sq, local_sqs, dots):
    arr = jnp.asarray(local_sqs, jnp.float32)
    return dx.DynamicsProbe(
        local_sq_mean=jnp.float32(local_sq_mean),
        pooled_sq=jnp.float32(pooled_sq),
        local_sqs=arr,
        dots=jnp.asarray(dots, jnp.float32),
        world=jnp.float32(arr.shape[0]))


# --- the fold -----------------------------------------------------------------

class TestDynamicsFold:
    def test_cadence(self):
        cfg = dx.DynamicsConfig(check_every=2)
        trees = {"t": jnp.ones((4,), jnp.float32)}
        ds = dx.dynamics_init(cfg, sites=dx.site_names(trees))
        for _ in range(4):
            ds = dx.dynamics_observe(ds, cfg, trees)
        assert int(ds.step) == 4
        assert int(ds.check_count) == 2          # steps 0 and 2
        assert int(ds.last_check_step) == 2

    def test_eff_lr_ema_seeded_by_first_check(self):
        cfg = dx.DynamicsConfig(ema=0.5)
        g = {"t": jnp.full((4,), 1.0, jnp.float32)}
        ds, _ = _observe_once({"t": jnp.full((4,), 8.0, jnp.float32)},
                              grads=g, cfg=cfg)
        assert float(ds.eff_lr_ema[0]) == pytest.approx(8.0)
        ds = dx.dynamics_observe(
            ds, cfg, {"t": jnp.full((4,), 4.0, jnp.float32)}, grads=g)
        assert float(ds.eff_lr_ema[0]) == pytest.approx(6.0)

    def test_no_companion_sentinels(self):
        ds, _ = _observe_once({"t": jnp.ones((4,), jnp.float32)})
        assert float(ds.eff_lr[0]) == -1.0
        assert float(ds.eff_lr_ema[0]) == -1.0
        assert float(ds.uw_ratio[0]) == -1.0
        assert float(ds.world) == -1.0           # no probe folded
        assert float(ds.cos_min_ema) == -2.0

    def test_companion_ratios(self):
        upd = {"t": jnp.full((4,), 0.01, jnp.float32)}
        ds, _ = _observe_once(
            upd, grads={"t": jnp.full((4,), 1.0, jnp.float32)},
            weights={"t": jnp.full((4,), 2.0, jnp.float32)})
        assert float(ds.eff_lr[0]) == pytest.approx(0.01)
        assert float(ds.uw_ratio[0]) == pytest.approx(0.005)

    def test_mismatched_trees_refused(self):
        cfg = dx.DynamicsConfig()
        ds = dx.dynamics_init(cfg, sites=("a", "b"))
        with pytest.raises(ValueError):
            dx.dynamics_observe(ds, cfg, {"a": jnp.zeros(2)})
        with pytest.raises(ValueError):
            dx.dynamics_observe(
                ds, cfg, {"a": jnp.zeros(2)},
                grads={"nope": jnp.zeros(2)})

    def test_config_validation(self):
        with pytest.raises(ValueError):
            dx.dynamics_init(dx.DynamicsConfig(check_every=0),
                             sites=("t",))
        with pytest.raises(ValueError):
            dx.dynamics_init(dx.DynamicsConfig(ema=1.0), sites=("t",))
        with pytest.raises(ValueError):
            dx.dynamics_init(dx.DynamicsConfig(local_batch=0),
                             sites=("t",))
        with pytest.raises(ValueError):
            dx.dynamics_init(sites=())
        with pytest.raises(ValueError):
            dx.dynamics_init(sites=("t",), world=0)

    def test_probe_world_mismatch_refused(self):
        cfg = dx.DynamicsConfig()
        ds = dx.dynamics_init(cfg, sites=("t",), world=2)
        with pytest.raises(ValueError):
            dx.dynamics_observe(
                ds, cfg, {"t": jnp.zeros(2)},
                probe=_probe(1.0, 1.0, [1.0] * 4, [1.0] * 4))

    def test_probe_fold_geometry(self):
        cfg = dx.DynamicsConfig()
        ds = dx.dynamics_init(cfg, sites=("t",), world=4)
        ds = dx.dynamics_observe(
            ds, cfg, {"t": jnp.zeros(2)},
            probe=_probe(1.0, 1.0, [1.0, 1.0, 4.0, 1.0],
                         [1.0, 0.5, 1.0, 1.0]))
        assert float(ds.world) == 4.0
        cos = np.asarray(ds.cos)
        # cos_i = dot_i / sqrt(|g_i|^2 * |g_bar|^2)
        assert cos[0] == pytest.approx(1.0)
        assert cos[1] == pytest.approx(0.5)
        assert cos[2] == pytest.approx(0.5)      # 1.0 / sqrt(4)
        proj = np.asarray(ds.proj)               # dot_i / |g_i|^2
        assert proj[2] == pytest.approx(0.25)
        assert float(ds.cos_min_ema) == pytest.approx(0.5)  # seeded

    def test_probe_thunk_and_trees_thunk(self):
        cfg = dx.DynamicsConfig()
        ds = dx.dynamics_init(cfg, sites=("t",), world=2)
        ds = dx.dynamics_observe(
            ds, cfg, lambda: {"t": jnp.ones(2)},
            probe=lambda: _probe(2.0, 1.0, [2.0, 2.0], [1.0, 1.0]))
        assert int(ds.check_count) == 1
        assert float(ds.local_sq) == pytest.approx(2.0)

    def test_scan_carryable(self):
        cfg = dx.DynamicsConfig(check_every=2)
        trees = {"t": jnp.ones((4,), jnp.float32)}
        ds0 = dx.dynamics_init(cfg, sites=dx.site_names(trees))

        def body(ds, _):
            return dx.dynamics_observe(ds, cfg, trees), ()

        ds, _ = jax.lax.scan(body, ds0, None, length=6)
        assert int(ds.check_count) == 3


# --- the GNS estimator --------------------------------------------------------

class TestGnsEstimator:
    def test_hand_computed_case(self):
        # b=4, world=4 -> B=16; |G_b|^2=2, |G_B|^2=1:
        #   g2 = (16*1 - 4*2)/12 = 2/3;  s = (2-1)/(1/4-1/16) = 16/3
        #   gns = s/g2 = 8
        est = dx._gns_estimate(2.0, 1.0, 4.0, 4)
        assert est["g2_est"] == pytest.approx(2.0 / 3.0)
        assert est["s_est"] == pytest.approx(16.0 / 3.0)
        assert est["gns"] == pytest.approx(8.0)
        assert est["b_crit"] == pytest.approx(8.0)

    def test_undefined_without_world(self):
        assert dx._gns_estimate(2.0, 1.0, None, 4)["gns"] is None
        assert dx._gns_estimate(2.0, 1.0, 1.0, 4)["gns"] is None

    def test_noise_free_is_null_not_fake(self):
        # replicated gradients: local == pooled -> S estimate 0
        est = dx._gns_estimate(1.0, 1.0, 4.0, 4)
        assert est["gns"] is None and est["b_crit"] is None


# --- the host report ----------------------------------------------------------

class TestDynamicsReport:
    def test_nulls_before_any_probe(self):
        ds, sites = _observe_once({"t": jnp.ones((4,), jnp.float32)})
        rep = dx.dynamics_report(ds, sites)
        assert rep.world is None and rep.gns is None
        assert rep.cos_spectrum == [] and rep.cos_min is None
        assert rep.eff_lr == [None]
        assert rep.fingerprint == "dynamics|gns|global"

    def test_gns_through_state(self):
        cfg = dx.DynamicsConfig(local_batch=4)
        ds = dx.dynamics_init(cfg, sites=("t",), world=4)
        ds = dx.dynamics_observe(
            ds, cfg, {"t": jnp.zeros(2)},
            probe=_probe(2.0, 1.0, [2.0] * 4, [1.0] * 4))
        rep = dx.dynamics_report(ds, ("t",), local_batch=4)
        assert rep.gns == pytest.approx(8.0)
        assert rep.world == 4.0

    def test_eff_lr_outliers_med_mad(self):
        trees = {"p": {k: jnp.ones((2,), jnp.float32)
                       for k in "abcde"}}
        sites = dx.site_names(trees)
        stats = {
            "sites": sites, "step": 10, "check_count": 5,
            "last_check_step": 8, "world": -1.0,
            "local_sq": 0.0, "local_sq_ema": 0.0,
            "pooled_sq": 0.0, "pooled_sq_ema": 0.0,
            "cos": np.full(1, -2.0), "proj": np.zeros(1),
            "cos_min_ema": -2.0, "cos_mean_ema": -2.0,
            "eff_lr": np.zeros(5),
            "eff_lr_ema": np.array([0.1, 0.11, 0.09, 0.1, 100.0]),
            "uw_ratio": np.full(5, -1.0)}
        rep = dx.dynamics_report(stats)
        assert len(rep.eff_lr_outliers) == 1
        out = rep.eff_lr_outliers[0]
        assert out["eff_lr"] == pytest.approx(100.0)
        assert out["fingerprint"].startswith("dynamics|eff_lr|p/")
        assert "OUTLIER" in rep.table()

    def test_fixture_round_trip(self):
        cfg = dx.DynamicsConfig(local_batch=2)
        ds = dx.dynamics_init(cfg, sites=("t",), world=2)
        ds = dx.dynamics_observe(
            ds, cfg, {"t": jnp.full((4,), 0.5, jnp.float32)},
            grads={"t": jnp.ones((4,), jnp.float32)},
            probe=_probe(2.0, 1.5, [2.0, 2.0], [1.7, 1.7]))
        text = dx.stats_to_json(ds, ("t",), local_batch=2)
        rep_a = dx.dynamics_report(ds, ("t",), local_batch=2)
        rep_b = dx.dynamics_report(dx.stats_from_json(text))
        assert rep_b.local_batch == 2       # recorded in the fixture
        assert rep_b.gns == pytest.approx(rep_a.gns)
        assert rep_b.cos_spectrum == pytest.approx(rep_a.cos_spectrum)
        assert rep_b.eff_lr[0] == pytest.approx(rep_a.eff_lr[0])


# --- the convergence comparator -----------------------------------------------

class TestConvergence:
    def test_calibration_validation(self):
        with pytest.raises(ValueError):
            cv.calibrate_band([[1.0, 2.0]])              # < 2 runs
        with pytest.raises(ValueError):
            cv.calibrate_band([[1.0], [1.0]])            # < 2 steps
        with pytest.raises(ValueError):
            cv.calibrate_band([[1.0, float("nan")],
                               [1.0, 2.0]])              # nonfinite

    def test_identical_runs_floor_band_pass(self):
        run = [1.0, 0.5, 0.25, 0.125]
        band = cv.calibrate_band([run, list(run)], floor=1e-9)
        assert band.threshold == pytest.approx(1e-9)
        v = cv.convergence_report(run, list(run), band=band)
        assert v.ok and v.first_flag_step is None
        assert v.n_flagged == 0

    def test_flags_at_the_right_step(self):
        a = [1.0, 0.9, 0.8, 0.7, 0.6]
        b = [1.0, 0.9, 0.8, 5.0, 9.0]
        band = cv.Band(threshold=0.5, median_gap=0.0, mad_gap=0.0,
                       z=6.0, n_pairs=1, n_steps=5, floor=0.5)
        v = cv.convergence_report(a, b, band=band)
        assert not v.ok
        assert v.first_flag_step == 3 and v.n_flagged == 2
        assert v.max_gap == pytest.approx(8.4)
        assert v.max_gap_step == 4

    def test_grace_exempts_warmup(self):
        a = [9.0, 1.0, 1.0]
        b = [1.0, 1.0, 1.0]
        band = cv.Band(threshold=0.5, median_gap=0.0, mad_gap=0.0,
                       z=6.0, n_pairs=1, n_steps=3, floor=0.5)
        assert not cv.convergence_report(a, b, band=band).ok
        assert cv.convergence_report(a, b, band=band, grace=1).ok

    def test_nonfinite_compared_loss_always_flags(self):
        a = [1.0, 1.0]
        b = [1.0, float("inf")]
        band = cv.Band(threshold=1e9, median_gap=0.0, mad_gap=0.0,
                       z=6.0, n_pairs=1, n_steps=2, floor=1e9)
        v = cv.convergence_report(a, b, band=band)
        assert not v.ok and v.first_flag_step == 1
        assert v.to_event()["max_gap"] is None   # inf nulled on wire

    def test_inline_calibration_path(self):
        a = [1.0, 0.5, 0.25]
        v = cv.convergence_report(a, list(a),
                                  calibration=[a, [1.01, 0.52, 0.26]])
        assert v.ok
        assert v.band.n_pairs == 1

    def test_event_shape(self):
        a = [1.0, 0.5]
        v = cv.convergence_report(a, list(a), calibration=[a, a])
        ev = v.to_event()
        assert ev["kind"] == "convergence_verdict"
        assert ev["verdict"] == "pass"
        assert ev["fingerprint"] == "dynamics|convergence|loss"
        assert v.fingerprint == "dynamics|convergence|loss"


# --- the dynamics channel + schema --------------------------------------------

def _lines(events):
    return [json.dumps(e) for e in events]


_DC_AGG = {"kind": "dynamics_check", "rank": 0, "step": 4,
           "check_count": 2, "site": None, "n_sites": 2,
           "eff_lr": 0.01, "uw_ratio": 0.001, "cos_min": 0.98,
           "cos_mean": 0.99, "world": 8.0}
_DC_SITE = {"kind": "dynamics_check", "rank": 0, "step": 4,
            "check_count": 2, "site": "dynamics/update/['w']",
            "n_sites": 2, "eff_lr": 0.01, "uw_ratio": None,
            "cos_min": None, "cos_mean": None, "world": None}
_GNS = {"kind": "gns", "rank": 0, "step": 4, "check_count": 2,
        "gns": 35.4, "b_crit": 35.4, "local_sq": 102.4,
        "pooled_sq": 21.8, "world": 8.0, "local_batch": 4,
        "cos_min": 0.98, "cos_mean": 0.99,
        "fingerprint": "dynamics|gns|global"}
_CV = {"kind": "convergence_verdict", "rank": 0, "step": 20,
       "verdict": "flag", "first_flag_step": 20, "n_flagged": 12,
       "n_steps": 60, "max_gap": 0.4, "band_threshold": 0.005,
       "band_z": 8.0, "fingerprint": "dynamics|convergence|loss"}


class TestDynamicsSchema:
    def _check(self, lines):
        from scripts.check_metrics_schema import check_dynamics_lines
        return check_dynamics_lines(lines)

    def test_valid_stream(self):
        assert self._check(_lines([_DC_AGG, _DC_SITE, _GNS,
                                   _CV])) == []

    def test_null_gns_by_contract(self):
        ev = dict(_GNS, gns=None, b_crit=None, world=None,
                  cos_min=None, cos_mean=None)
        assert self._check(_lines([ev])) == []

    def test_pass_verdict_null_flag_step(self):
        ev = dict(_CV, verdict="pass", step=None,
                  first_flag_step=None, n_flagged=0)
        assert self._check(_lines([ev])) == []

    # negative twins ----------------------------------------------------------

    def test_unknown_kind_rejected(self):
        errs = self._check(_lines([dict(_DC_AGG,
                                        kind="dynamics_meow")]))
        assert errs and "kind" in errs[0]

    def test_cosine_out_of_range_rejected(self):
        assert self._check(_lines([dict(_DC_AGG, cos_min=1.5)]))
        assert self._check(_lines([dict(_GNS, cos_mean=-1.5)]))

    def test_nonpositive_gns_rejected(self):
        assert self._check(_lines([dict(_GNS, gns=-1.0)]))
        assert self._check(_lines([dict(_GNS, b_crit=0.0)]))

    def test_verdict_enum_rejected(self):
        assert self._check(_lines([dict(_CV, verdict="maybe")]))

    def test_pass_with_flag_step_rejected(self):
        ev = dict(_CV, verdict="pass", n_flagged=0)
        assert self._check(_lines([ev]))         # first_flag_step set

    def test_flag_without_flag_step_rejected(self):
        assert self._check(_lines([dict(_CV, first_flag_step=None)]))

    def test_missing_fingerprint_rejected(self):
        ev = dict(_GNS)
        del ev["fingerprint"]
        assert any("fingerprint" in e
                   for e in self._check(_lines([ev])))

    def test_overflagged_rejected(self):
        assert self._check(_lines([dict(_CV, n_flagged=61)]))

    # the wired channel -------------------------------------------------------

    def test_channel_emission_validates(self):
        buf = io.StringIO()
        logger = monitor.MetricsLogger(
            sinks=[], dynamics_sink=monitor.JSONLSink(buf))
        cfg = dx.DynamicsConfig(local_batch=4)
        ds = dx.dynamics_init(cfg, sites=("t",), world=4)
        ds = dx.dynamics_observe(
            ds, cfg, {"t": jnp.full((4,), 0.5, jnp.float32)},
            grads={"t": jnp.ones((4,), jnp.float32)},
            probe=_probe(2.0, 1.0, [2.0] * 4, [1.4] * 4))
        for ev in dx.check_events(ds, ("t",), local_batch=4):
            logger.record_dynamics(ev)
        v = cv.convergence_report([1.0, 0.5], [1.0, 0.5],
                                  calibration=[[1.0, 0.5], [1.0, 0.5]])
        logger.record_dynamics(v.to_event())
        logger.close()
        lines = [l for l in buf.getvalue().splitlines() if l.strip()]
        assert self._check(lines) == []
        kinds = {json.loads(l)["kind"] for l in lines}
        assert kinds == {"dynamics_check", "gns",
                         "convergence_verdict"}


# --- the amp hook + opt-level parity sweep ------------------------------------

class TestAmpDynamicsHook:
    def _run(self, opt_level, observe, steps=6):
        import optax
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(16, 4).astype("float32")
                                   * 0.1),
                  "b": jnp.zeros((4,), jnp.float32)}
        x = jnp.asarray(rng.randn(8, 16).astype("float32"))
        y = jnp.asarray(rng.randn(8, 4).astype("float32"))
        amp_opt, state = amp.initialize(params, optax.sgd(0.05),
                                        opt_level, verbosity=0)

        def loss_fn(mp, x, y):
            return jnp.mean(jnp.square(x @ mp["w"] + mp["b"] - y))

        dcfg = dx.DynamicsConfig(check_every=2)
        ds = dx.dynamics_init(
            dcfg, sites=amp_opt.dynamics_sites(state.params))

        if observe:
            @jax.jit
            def step(state, ds, x, y):
                state, loss, fin, ds = amp_opt.step(
                    state, loss_fn, x, y, dynamics=(ds, dcfg))
                return state, ds, loss
        else:
            @jax.jit
            def step(state, ds, x, y):
                state, loss, fin = amp_opt.step(state, loss_fn, x, y)
                return state, ds, loss

        losses = []
        for _ in range(steps):
            state, ds, loss = step(state, ds, x, y)
            losses.append(np.asarray(loss).tobytes())
        return losses, jax.device_get(state.params), ds

    @pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
    def test_trajectory_bit_identical_observed_vs_not(self, opt_level):
        l_obs, p_obs, ds = self._run(opt_level, observe=True)
        l_ref, p_ref, _ = self._run(opt_level, observe=False)
        assert l_obs == l_ref
        for k in p_ref:
            assert np.array_equal(np.asarray(p_obs[k]),
                                  np.asarray(p_ref[k]))
        assert int(ds.check_count) == 3          # steps 0, 2, 4

    def test_observed_state_folds_companions(self):
        _, _, ds = self._run("O2", observe=True)
        rep = dx.dynamics_report(
            ds, ("dynamics/update/['b']", "dynamics/update/['w']"))
        assert all(v is not None and v > 0 for v in rep.eff_lr)
        assert all(v is not None and v > 0 for v in rep.uw_ratio)

    def test_dynamics_sites_naming(self):
        import optax
        params = {"w": jnp.ones((4, 2), jnp.float32),
                  "b": jnp.zeros((2,), jnp.float32)}
        amp_opt, _ = amp.initialize(params, optax.sgd(0.1), "O1",
                                    verbosity=0)
        assert amp_opt.dynamics_sites(params) == (
            "dynamics/update/['b']", "dynamics/update/['w']")

    def test_step_returns_grow_with_hooks(self):
        import optax
        from apex_tpu.monitor import numerics as nx
        params = {"w": jnp.ones((4, 2), jnp.float32)}
        amp_opt, state = amp.initialize(params, optax.sgd(0.1), "O2",
                                        verbosity=0)
        dcfg = dx.DynamicsConfig()
        ds = dx.dynamics_init(dcfg,
                              sites=amp_opt.dynamics_sites(params))

        def lf(mp):
            return jnp.mean(jnp.square(mp["w"]))

        ret = amp_opt.step(state, lf, dynamics=(ds, dcfg))
        assert len(ret) == 4 and isinstance(ret[3], dx.DynamicsState)
        ncfg = nx.NumericsConfig()
        ns = nx.numerics_init(ncfg,
                              sites=amp_opt.numerics_sites(params))
        ret = amp_opt.step(state, lf, numerics=(ns, ncfg),
                           dynamics=(ds, dcfg))
        # growth order: ... numerics, then dynamics LAST
        assert len(ret) == 5
        assert isinstance(ret[3], nx.NumericsState)
        assert isinstance(ret[4], dx.DynamicsState)


# --- the registry rows --------------------------------------------------------

class TestRegistryPins:
    def test_axis_attribution(self):
        assert parallel.scope_axis("ddp/dynamics_gns") == \
            parallel.DATA_AXIS
        assert parallel.scope_axis("ddp/dynamics_geom") == \
            parallel.DATA_AXIS

    def test_subsystem_and_flat_patterns(self):
        from apex_tpu.parallel.distributed import \
            KNOWN_COLLECTIVE_SCOPES
        for scope in ("ddp/dynamics_gns", "ddp/dynamics_geom"):
            entry = parallel.scope_entry(scope)
            assert entry is not None and entry.subsystem == "ddp"
            assert any(__import__("re").search(p, scope)
                       for p in KNOWN_COLLECTIVE_SCOPES)

    def test_probe_emits_registered_scopes(self):
        # the probe's spans carry exactly the registered names — a
        # rename on either side would orphan the axis attribution
        import inspect
        from apex_tpu.parallel import distributed as dist
        src = inspect.getsource(dist.dynamics_probe)
        assert '"ddp/dynamics_gns"' in src
        assert '"ddp/dynamics_geom"' in src


# --- compile-check + sentinel columns -----------------------------------------

class TestCompileCheck:
    def test_dynamics_case_runs_green(self):
        from apex_tpu.ops import compile_check as cc
        assert cc.run(pattern="dynamics/no-extra-dispatch")


class TestSentinelColumns:
    def _baseline(self):
        with open(os.path.join(REPO, "scripts",
                               "perf_baseline.json")) as f:
            return json.load(f)

    def test_direction_aware_rows_declared(self):
        rows = {m["name"]: m for m in self._baseline()["metrics"]}
        assert rows["gns"]["direction"] == "lower"
        assert rows["gns"]["path"] == ["extra", "gns"]
        # a cosine DROP is the regression, so 'higher' is better
        assert rows["grad_cosine_min"]["direction"] == "higher"
        assert rows["grad_cosine_min"]["path"] == \
            ["extra", "grad_cosine_min"]

    def test_old_rounds_skip_with_note_not_join_failure(self, tmp_path):
        from apex_tpu.prof import sentinel as sn
        specs = sn.metric_specs_from_baseline(self._baseline())
        names = {s.name for s in specs}
        assert {"gns", "grad_cosine_min"} <= names
        # an old committed round predating the columns: extraction
        # simply omits them — no error, no fake zero
        old = {"metric": "resnet", "value": 100.0,
               "extra": {"batch": 32, "mfu": 0.3}}
        assert "gns" not in sn.extract_metrics(old, specs)
        p = tmp_path / "BENCH_r01.json"
        p.write_text(json.dumps(old))
        rows = sn.load_rows([str(p)], specs)
        assert rows[0]["row"] is not None        # joined, not failed
        assert "gns" not in rows[0]["metrics"]
        # a new row judged against a column-less history: the verdict
        # is an insufficient-history note, never a flag
        spec = next(s for s in specs if s.name == "gns")
        v = sn.check_row([], 40.0, spec)
        assert not v.regressed and "insufficient history" in v.note