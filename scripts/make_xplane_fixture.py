#!/usr/bin/env python
"""Regenerate tests/fixtures/resnet_step.xplane.pb (+ the BERT-layer
fixture bert_layer.xplane.pb).

Miniature XSpace traces shaped exactly like on-chip
``jax.profiler.trace`` captures (device plane "/device:TPU:0" with
"XLA Modules" + "XLA Ops" lines, per-op HLO metadata carrying fusion
kinds and named-scope paths, plus a host plane the parser must skip).
Written with a pure-stdlib protobuf encoder — regenerating the fixtures
needs no tensorflow, and ``tests/test_prof.py::TestXplaneFixture`` /
``tests/test_roofline.py`` pin the decoded tables against the values
below, so a parser or roofline-join regression surfaces in CI instead
of only on-chip.

The ResNet op set is a faithful miniature of a real v5e capture's shape
(mega-fusions dominating, one conv, one all-reduce, a copy) with
hand-chosen durations — small enough to commit, rich enough to exercise
opcode extraction, fusion-kind categories, collective classification,
scope attribution, and occurrence aggregation.

The BERT op set is one BERT-Large layer's fwd+bwd hot ops at the bench
geometry (b=16 s=512 h=16 d=64, hidden 1024/4096), with durations taken
from the PERF.md round-5 ledger — notably the fused backward attention
kernel at 549 us against its ~436 us d=64 MXU floor, the one >10% gap
ROADMAP item 4 is chasing — and op durations summing to within 5% of
the module time, so ``apex_tpu.prof.roofline``'s attribution-closure
and worst-gap assertions (``scripts/roofline_audit.py --cpu8``) are
regression-tested tf-free.

Usage: python scripts/make_xplane_fixture.py            # both fixtures
       python scripts/make_xplane_fixture.py OUT.pb     # resnet only
       python scripts/make_xplane_fixture.py --bert OUT.pb
"""

import os
import sys


# --- minimal protobuf encoder (wire format) ----------------------------------

def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(fno: int, v: int) -> bytes:
    return _uvarint(fno << 3 | 0) + _uvarint(v)


def field_bytes(fno: int, v: bytes) -> bytes:
    return _uvarint(fno << 3 | 2) + _uvarint(len(v)) + v


def field_str(fno: int, s: str) -> bytes:
    return field_bytes(fno, s.encode())


# --- XSpace schema subset (field numbers per the tsl xplane proto) -----------

def event(metadata_id: int, duration_ps: int, offset_ps: int = 0) -> bytes:
    return (field_varint(1, metadata_id) + field_varint(2, offset_ps)
            + field_varint(3, duration_ps))


def line(name: str, events) -> bytes:
    body = field_str(2, name)
    for ev in events:
        body += field_bytes(4, ev)
    return body


def event_metadata(mid: int, name: str) -> bytes:
    return field_varint(1, mid) + field_str(2, name)


def plane(name: str, lines=(), metadata=()) -> bytes:
    body = field_str(2, name)
    for l in lines:
        body += field_bytes(3, l)
    for mid, md in metadata:
        body += field_bytes(4, field_varint(1, mid) + field_bytes(2, md))
    return body


def xspace(planes) -> bytes:
    return b"".join(field_bytes(1, p) for p in planes)


# --- the fixture content -----------------------------------------------------

#: (metadata_id, HLO text, [duration_us per occurrence]) — the pinned
#: per-op table lives in tests/test_prof.py; keep the two in lockstep.
OPS = [
    (10, '%fusion.31 = bf16[64,14,14,256]{3,2,1,0:T(8,128)(2,1)} '
         'fusion(bf16[64,14,14,256]{3,2,1,0} %p0, bf16[256]{0} %p1), '
         'kind=kOutput, calls=%fused_computation.31, '
         'metadata={op_name="jit(step)/jvp(amp/fwd)/stage3/bn_relu"}',
     [93.0, 91.5]),
    (11, '%convolution.7 = bf16[64,14,14,256]{3,2,1,0:T(8,128)(2,1)} '
         'convolution(bf16[64,14,14,256]{3,2,1,0} %x, '
         'bf16[3,3,256,256]{3,2,1,0} %w), window={size=3x3 pad=1_1x1_1}, '
         'dim_labels=b01f_01io->b01f, '
         'metadata={op_name="jit(step)/jvp(amp/fwd)/stage3/conv"}',
     [74.2, 73.8]),
    (12, '%all-reduce.3 = f32[524288]{0:T(1024)} all-reduce('
         'f32[524288]{0} %grads), replica_groups={{0,1,2,3,4,5,6,7}}, '
         'to_apply=%sum, metadata={op_name='
         '"jit(step)/ddp/sync_gradients/bucket00/psum"}',
     [41.0]),
    (13, '%fusion.88 = (f32[1024]{0}, f32[1024]{0}) fusion('
         'bf16[64,14,14,1024]{3,2,1,0} %dz), kind=kInput, '
         'calls=%fused_computation.88, metadata={op_name='
         '"jit(step)/transpose(jvp(amp/fwd))/stage3/bn_bwd_sums"}',
     [49.7, 50.3]),
    (14, '%copy.5 = bf16[64,56,56,64]{3,2,1,0:T(8,128)(2,1)} '
         'copy(bf16[64,56,56,64]{1,3,2,0} %p4)',
     [12.5]),
    (15, '%custom-call.9 = bf16[64,512,8,64]{3,2,1,0} custom-call('
         'bf16[64,512,8,64]{3,2,1,0} %q), custom_call_target='
         '"tpu_custom_call", metadata={op_name='
         '"jit(step)/jvp(amp/fwd)/attn/flash_attention"}',
     [31.0]),
]

MODULE_RUNS = [990.0, 1010.0]     # us — two steps captured


# --- the BERT-layer fixture (roofline regression target) ---------------------
#
# One BERT-Large encoder layer's fwd+bwd hot ops at the bench geometry
# (b=16, s=512, h=16, d=64 -> 8192 tokens, hidden 1024, ffn 4096), ONE
# captured step, durations from the PERF.md round-5 per-component
# ledger. The roofline math this pins (v5e: 197 TFLOP/s, 819 GB/s,
# d=64 -> 0.5 MXU cap):
#   attn fwd  354.0 us vs 4*B*H*S^2*D / 98.5e12 = 174.4 us  (eff 0.49)
#   attn bwd  549.0 us vs 10*B*H*S^2*D / 98.5e12 = 436.1 us (eff 0.79)
#     ^ THE known fused-backward gap (PERF round-5: "~550 vs ~440")
#   LN fwd     55.0 us vs 33.6 MB / 819 GB/s = 41.0 us      (memory)
#   LN bwd     71.0 us vs 50.4 MB / 819 GB/s = 61.5 us      (memory)
#   MLP fc1   370.0 us vs 2*8192*4096*1024 / 197e12 = 348.8 (eff 0.94)
#   MLP fc2   365.0 us vs same                              (eff 0.96)
#   bias grad  90.0 us vs 67.1 MB / 819 GB/s = 82.0 us      (memory)
# Op sum 1854.0 us vs the 1900.0 us module run = 2.4% closure error,
# inside roofline_audit's 5% gate.
BERT_OPS = [
    (20, '%custom-call.201 = bf16[16,512,16,64]{3,2,1,0} custom-call('
         'bf16[16,512,16,64]{3,2,1,0} %q, bf16[16,512,16,64]{3,2,1,0} '
         '%k, bf16[16,512,16,64]{3,2,1,0} %v), custom_call_target='
         '"tpu_custom_call", metadata={op_name='
         '"jit(step)/jvp(bert/encoder_5/attn)/flash_attention_fwd"}',
     [354.0]),
    (21, '%custom-call.202 = (bf16[16,512,16,64]{3,2,1,0}, '
         'bf16[16,512,16,64]{3,2,1,0}, bf16[16,512,16,64]{3,2,1,0}) '
         'custom-call(bf16[16,512,16,64]{3,2,1,0} %q, '
         'bf16[16,512,16,64]{3,2,1,0} %k, bf16[16,512,16,64]{3,2,1,0} '
         '%v, bf16[16,512,16,64]{3,2,1,0} %do), custom_call_target='
         '"tpu_custom_call", metadata={op_name="jit(step)/transpose('
         'jvp(bert/encoder_5/attn))/flash_attention_bwd"}',
     [549.0]),
    (22, '%fusion.210 = bf16[8192,1024]{1,0} fusion('
         'bf16[8192,1024]{1,0} %x, f32[1024]{0} %gamma, '
         'f32[1024]{0} %beta), kind=kOutput, calls=%fused_ln_fwd, '
         'metadata={op_name='
         '"jit(step)/jvp(bert/encoder_5/layer_norm)/ln_fwd"}',
     [55.0]),
    (23, '%fusion.211 = (bf16[8192,1024]{1,0}, f32[1024]{0}, '
         'f32[1024]{0}) fusion(bf16[8192,1024]{1,0} %dz, '
         'bf16[8192,1024]{1,0} %x, f32[1024]{0} %gamma), kind=kInput, '
         'calls=%fused_ln_bwd, metadata={op_name="jit(step)/transpose('
         'jvp(bert/encoder_5/layer_norm))/ln_bwd"}',
     [71.0]),
    (24, '%dot.220 = bf16[8192,4096]{1,0} dot(bf16[8192,1024]{1,0} %h, '
         'bf16[1024,4096]{1,0} %w1), lhs_contracting_dims={1}, '
         'rhs_contracting_dims={0}, metadata={op_name='
         '"jit(step)/jvp(bert/encoder_5/mlp)/fc1"}',
     [370.0]),
    (25, '%dot.221 = bf16[8192,1024]{1,0} dot(bf16[8192,4096]{1,0} '
         '%act, bf16[4096,1024]{1,0} %w2), lhs_contracting_dims={1}, '
         'rhs_contracting_dims={0}, metadata={op_name='
         '"jit(step)/jvp(bert/encoder_5/mlp)/fc2"}',
     [365.0]),
    (26, '%fusion.230 = f32[4096]{0} fusion(bf16[8192,4096]{1,0} '
         '%dact), kind=kInput, calls=%fused_bias_grad, '
         'metadata={op_name="jit(step)/transpose('
         'jvp(bert/encoder_5/mlp))/bias_grad"}',
     [90.0]),
]

BERT_MODULE_RUNS = [1900.0]       # us — one step captured


def build(ops=OPS, module_runs=MODULE_RUNS) -> bytes:
    md = [(1, event_metadata(1, "jit_step(1234)"))]
    op_events = []
    t = 0
    for mid, hlo, durs in ops:
        md.append((mid, event_metadata(mid, hlo)))
        for d in durs:
            op_events.append(event(mid, int(d * 1e6), offset_ps=t))
            t += int(d * 1e6)
    mod_events = [event(1, int(d * 1e6), offset_ps=i * 10 ** 9)
                  for i, d in enumerate(module_runs)]
    device = plane("/device:TPU:0",
                   lines=[line("XLA Modules", mod_events),
                          line("XLA Ops", op_events)],
                   metadata=md)
    host = plane("/host:CPU",
                 lines=[line("python", [event(1, 5_000_000)])],
                 metadata=[(1, event_metadata(1, "hostloop"))])
    return xspace([host, device])


_FIXTURES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures")


def _write(out: str, ops, module_runs) -> None:
    data = build(ops, module_runs)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {out} ({len(data)} bytes, {len(ops)} ops, "
          f"{len(module_runs)} module runs)")


def main() -> int:
    args = sys.argv[1:]
    if args and args[0] == "--bert":
        out = args[1] if len(args) > 1 else os.path.join(
            _FIXTURES, "bert_layer.xplane.pb")
        _write(out, BERT_OPS, BERT_MODULE_RUNS)
        return 0
    if args:                           # explicit path: resnet only
        _write(args[0], OPS, MODULE_RUNS)
        return 0
    _write(os.path.join(_FIXTURES, "resnet_step.xplane.pb"),
           OPS, MODULE_RUNS)
    _write(os.path.join(_FIXTURES, "bert_layer.xplane.pb"),
           BERT_OPS, BERT_MODULE_RUNS)
    return 0


if __name__ == "__main__":
    sys.exit(main())
