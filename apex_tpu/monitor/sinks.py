"""Pluggable host-side metric sinks: stdout table / JSONL / CSV.

A sink consumes fully materialized host records (plain dicts of python
numbers, already fetched from device by the logger's flush) — sinks never
touch jax arrays, so adding one can never add a device sync.

The JSONL wire format is the contract validated by
``scripts/check_metrics_schema.py``; keep the two in lockstep.
"""

from __future__ import annotations

import csv
import io
import json
import os
import sys
from typing import Dict, List, Optional, TextIO

__all__ = ["Sink", "StdoutSink", "JSONLSink", "CSVSink"]


class Sink:
    """Interface: ``emit`` one record dict per step, ``close`` at teardown."""

    def emit(self, record: Dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _fmt(v, width=9):
    if v is None:
        return "n/a".rjust(width)
    if isinstance(v, float):
        if v == 0 or 1e-3 <= abs(v) < 1e5:
            return f"{v:.4g}".rjust(width)
        return f"{v:.2e}".rjust(width)
    return str(v).rjust(width)


class StdoutSink(Sink):
    """Aligned table line per step, header re-printed every
    ``header_every``.

    The ``wire`` column is the per-dtype collective wire breakdown the
    logger's :meth:`~apex_tpu.monitor.MetricsLogger.attach` reads off
    the compiled HLO (``wire_report``'s accounting), and ``w/l`` the
    wire-to-logical ratio — a ``compress="bf16"`` DDP run shows
    ``bf16:47.7M`` at ``w/l 0.50`` live, without a separate audit
    script. Both print ``n/a`` until the statics are attached."""

    _COLS = ("step", "loss", "loss_scale", "grad_norm", "skip_count",
             "step_time_ms", "throughput_steps_per_s", "mfu",
             "wire_by_dtype", "wire_to_logical")
    _HEADS = ("step", "loss", "scale", "gnorm", "skip", "ms/step",
              "steps/s", "mfu", "wire", "w/l")
    _WIDTHS = {"wire_by_dtype": 22}

    def __init__(self, stream: Optional[TextIO] = None,
                 header_every: int = 20):
        self.stream = stream or sys.stdout
        self.header_every = header_every
        self._n = 0

    def emit(self, record: Dict) -> None:
        if self._n % self.header_every == 0:
            self.stream.write(" ".join(
                h.rjust(self._WIDTHS.get(c, 9))
                for c, h in zip(self._COLS, self._HEADS)) + "\n")
        vals = []
        for c in self._COLS:
            v = record.get(c)
            width = self._WIDTHS.get(c, 9)
            if c == "mfu" and isinstance(v, float):
                vals.append(f"{v:.1%}".rjust(width))
                continue
            if c == "wire_by_dtype":
                if isinstance(v, dict) and v:
                    from apex_tpu.utils.format import fmt_bytes
                    txt = "+".join(
                        f"{dt}:{fmt_bytes(nb, compact=True)}"
                        for dt, nb in sorted(v.items(),
                                             key=lambda kv: -kv[1]))
                elif isinstance(v, dict):
                    txt = "0"
                else:
                    txt = "n/a"
                if len(txt) > width:      # keep the dominant dtype
                    txt = txt[:width - 1] + "~"
                vals.append(txt.rjust(width))
                continue
            if c == "wire_to_logical" and isinstance(v, float):
                vals.append(f"{v:.2f}".rjust(width))
                continue
            vals.append(_fmt(v, width))
        self.stream.write(" ".join(vals) + "\n")
        self.stream.flush()
        self._n += 1


class JSONLSink(Sink):
    """One JSON object per line — the machine-readable stream
    (``scripts/check_metrics_schema.py`` validates it).

    Doubles as the **trace-event channel** sink: pass one as
    ``MetricsLogger(trace_sink=...)`` and span/step timeline events from
    :mod:`apex_tpu.trace` stream to it (validate with
    ``check_metrics_schema.py --kind trace``).
    """

    def __init__(self, path_or_stream):
        if isinstance(path_or_stream, (str, os.PathLike)):
            self.stream: TextIO = open(path_or_stream, "w")
            self._owns = True
        else:
            self.stream = path_or_stream
            self._owns = False

    def emit(self, record: Dict) -> None:
        self.stream.write(json.dumps(record) + "\n")
        self.stream.flush()

    def close(self) -> None:
        if self._owns:
            self.stream.close()


class CSVSink(Sink):
    """CSV with a header derived from the first record's keys; later
    records are projected onto those columns (missing → empty)."""

    def __init__(self, path_or_stream):
        if isinstance(path_or_stream, (str, os.PathLike)):
            self.stream: TextIO = open(path_or_stream, "w", newline="")
            self._owns = True
        else:
            self.stream = path_or_stream
            self._owns = False
        self._writer: Optional[csv.DictWriter] = None
        self._fields: List[str] = []

    def emit(self, record: Dict) -> None:
        if self._writer is None:
            self._fields = list(record.keys())
            self._writer = csv.DictWriter(self.stream, self._fields,
                                          extrasaction="ignore")
            self._writer.writeheader()
        self._writer.writerow({k: record.get(k, "") for k in self._fields})
        self.stream.flush()

    def close(self) -> None:
        if self._owns:
            self.stream.close()
