"""apex_tpu.models — the model families the reference's examples/configs
exercise (BASELINE.json): ResNet (imagenet example), DCGAN (multi-loss amp
example), BERT-style transformer (FusedLAMB config), RNN stacks
(`apex.RNN`).
"""

from apex_tpu.models.resnet import (
    ResNet, ResNet18, ResNet50, ResNet101,
    BasicBlock, BottleneckBlock, RESNET50_FLOPS_PER_IMAGE,
)
from apex_tpu.models.transformer import (
    BertEncoder, BertLarge, TransformerLayer, MultiheadAttention,
    FusedLayerNormModule, mlm_loss,
)
from apex_tpu.models.dcgan import Generator, Discriminator

__all__ = [
    "ResNet", "ResNet18", "ResNet50", "ResNet101",
    "BasicBlock", "BottleneckBlock", "RESNET50_FLOPS_PER_IMAGE",
    "BertEncoder", "BertLarge", "TransformerLayer", "MultiheadAttention",
    "FusedLayerNormModule", "mlm_loss",
    "Generator", "Discriminator",
]
