"""Per-axis HBM attribution from the compiled module's shardings.

:mod:`apex_tpu.prof.memory` answers "which *class* owns each HBM byte"
(params / optimizer_state / activations / ...); this module answers the
question ROADMAP item 1's nD-parallelism arc needs next: **per mesh
axis**, is each buffer *sharded by* that axis (every coordinate holds a
distinct tile — HBM shrinks with the axis) or *replicated over* it
(every coordinate holds the same bytes — HBM does not shrink)? The
compiled program is again the ground truth: the optimized module's
entry parameters carry their ``sharding={...}`` HloSharding annotation
(``{replicated}``, iota tile assignments ``{devices=[8,1]<=[8]}``,
explicit device lists, ``last_tile_dim_replicate``), and the
:class:`~apex_tpu.lint.mesh_model.MeshModel` supplies the device→axis
coordinate arithmetic, so "sharded by which axis" is a pure join — no
device ever dispatches.

One deliberate escape hatch: **manual sharding is annotation-invisible**.
A ``shard_map`` program that carves its own shards (the ZeRO optimizer
state: ``in_specs=P()`` while each rank holds a distinct
``dynamic_slice`` of the full state) compiles to parameters annotated
``{replicated}`` even though no byte is actually replicated. The
``overrides=`` mapping (arg-path regex → axis names) lets the caller
*declare* that layout; such rows report ``source="declared"`` so the
table never passes a declaration off as a measurement.

The per-axis HBM table closes over :func:`memory_report`'s class totals
by construction (:meth:`ShardReport.closure` asserts it within 1%, the
memory_budget pattern), and :meth:`ShardReport.forecast_axes` prices a
hypothetical further sharding (``{"tp": 2, "pp": 2}``) per class: only
the portion replicated over *every* current axis can shrink.

Consumers: ``scripts/mesh_explain.py`` (the AOT MeshPlan pre-flight),
``MetricsLogger.attach_shard_report`` (the ``sharding`` event channel,
``check_metrics_schema.py --kind sharding``), and ``bench.py``'s
``axis_hbm`` column. See docs/memory.md and docs/parallel.md.
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.prof.memory import (BUFFER_CLASSES, MemoryReport,
                                  memory_report, parse_entry, shape_bytes)

__all__ = ["ShardRecord", "ShardReport", "shard_report",
           "parse_hlo_sharding", "parameter_shardings"]


# --- HloSharding text parsing ------------------------------------------------

#: ``sharding={...}`` on an entry parameter line. Tuple shardings
#: (nested braces) don't occur on flat jax entry parameters; a body we
#: cannot parse degrades to ``form="unparsed"`` (treated replicated —
#: the conservative direction: never claim HBM shrink without evidence).
_SHARDING_RE = re.compile(r"sharding=\{(?P<body>[^{}]*)\}")

_DEVICES_RE = re.compile(
    r"devices=\[(?P<dims>[\d,]+)\]"
    r"(?:<=\[(?P<iota>[\d,]+)\](?:T\((?P<perm>[\d,]+)\))?"
    r"|(?P<list>[\d,]+))")


def parse_hlo_sharding(body: str, n_devices: int
                       ) -> Tuple[Optional[List[int]], str]:
    """Parse one HloSharding body into ``(tiles, form)``.

    ``tiles[device_id]`` is the data-tile index the device holds —
    devices mapping to the same tile hold identical bytes. Forms:
    ``"replicated"`` / ``"maximal"`` (all devices one tile), ``"tiled"``
    (iota or explicit device list, ``last_tile_dim_replicate`` folds
    the trailing replication dim), ``"unparsed"`` (``tiles=None``).
    """
    b = body.strip()
    if b == "replicated":
        return [0] * n_devices, "replicated"
    if b.startswith("maximal"):
        # the whole tensor on one device; nothing is axis-sharded
        return [0] * n_devices, "maximal"
    m = _DEVICES_RE.search(b)
    if not m:
        return None, "unparsed"
    dims = [int(x) for x in m.group("dims").split(",") if x]
    total = 1
    for d in dims:
        total *= d
    if m.group("iota") is not None:
        rdims = [int(x) for x in m.group("iota").split(",") if x]
        arr = np.arange(int(np.prod(rdims))).reshape(rdims)
        if m.group("perm"):
            arr = arr.transpose([int(x) for x in
                                 m.group("perm").split(",") if x])
        order = arr.reshape(-1).tolist()
    else:
        order = [int(x) for x in m.group("list").split(",") if x]
    if len(order) != total or total != n_devices:
        return None, "unparsed"     # sub-group sharding: out of scope
    rep = dims[-1] if "last_tile_dim_replicate" in b else 1
    rep = max(rep, 1)
    tiles = [0] * n_devices
    for i, dev in enumerate(order):
        if not 0 <= dev < n_devices:
            return None, "unparsed"
        tiles[dev] = i // rep
    return tiles, "tiled"


def parameter_shardings(hlo_text: str) -> Dict[str, str]:
    """``{parameter_name: sharding body}`` for every annotated entry
    parameter of an optimized module (a separate scan — ``parse_entry``
    keeps its record shape; both read the same lines)."""
    out: Dict[str, str] = {}
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if " parameter(" not in line:
            continue
        m = _SHARDING_RE.search(line)
        if not m:
            continue
        name = line.split(" = ", 1)[0].strip().lstrip("%")
        out[name] = m.group("body")
    return out


# --- per-axis disposition ----------------------------------------------------

def _axis_disposition(tiles: Sequence[int], mesh_model) -> Dict[str, str]:
    """Per mesh axis: ``"sharded"`` when some pair of devices differing
    only along that axis holds different tiles, else ``"replicated"``."""
    out: Dict[str, str] = {}
    names = mesh_model.axis_names
    coords = [mesh_model.coords(d) for d in range(mesh_model.n_devices)]
    for ax in names:
        groups: Dict[Tuple[int, ...], set] = {}
        for d, t in enumerate(tiles):
            key = tuple(coords[d][n] for n in names if n != ax)
            groups.setdefault(key, set()).add(t)
        out[ax] = ("sharded" if any(len(s) > 1 for s in groups.values())
                   else "replicated")
    return out


@dataclasses.dataclass
class ShardRecord:
    """One entry argument's per-axis disposition."""

    name: str                 # HLO parameter name
    path: str                 # jax argument path (metadata op_name)
    cls: str                  # one of BUFFER_CLASSES
    bytes: int                # LOCAL per-device bytes (parsed shape)
    axes: Dict[str, str]      # {axis: "sharded" | "replicated"}
    shard_factor: int         # distinct tiles — global = local * factor
    source: str               # "annotation" | "declared" | "none"
    sharding: str             # raw annotation body ("" when absent)

    @property
    def global_bytes(self) -> int:
        """Pod-wide bytes this argument semantically holds once."""
        return self.bytes * self.shard_factor

    def sharded_by(self, axis: str) -> bool:
        return self.axes.get(axis) == "sharded"


# --- the report --------------------------------------------------------------

def _fmt_bytes(n) -> str:
    from apex_tpu.utils.format import fmt_bytes
    return fmt_bytes(n)


@dataclasses.dataclass
class ShardReport:
    """Per-axis HBM disposition of one compiled step.

    ``axis_table[axis]`` holds ``{"sharded": {cls: bytes}, "replicated":
    {cls: bytes}}`` — for every axis the two sides sum to the memory
    report's attributed class totals, so the table *closes* the same
    way memory_budget's class sum does (:meth:`closure`). Classes with
    no entry-argument backing (activations, the temp share of comm,
    outputs) are per-device working sets with no statically visible
    cross-device redundancy: they are attributed sharded-by every axis
    with ``source="local"`` — a stated convention, not a measurement.
    """

    mesh_name: str
    axis_names: Tuple[str, ...]
    axis_sizes: Dict[str, int]
    records: List[ShardRecord]
    axis_table: Dict[str, Dict[str, Dict[str, int]]]
    class_totals: Dict[str, int]          # memory report's classes
    memory: Optional[MemoryReport] = None

    # -- per-axis rollups ----------------------------------------------------

    def axis_bytes(self, axis: str) -> Dict[str, int]:
        """``{"sharded_bytes", "replicated_bytes"}`` summed over
        classes for one axis (``KeyError`` on an unknown axis)."""
        t = self.axis_table[axis]
        return {"sharded_bytes": sum(t["sharded"].values()),
                "replicated_bytes": sum(t["replicated"].values())}

    def attributed_total(self) -> int:
        return sum(self.class_totals.values())

    def closure(self) -> Tuple[bool, float]:
        """(ok, worst relative error): every axis's sharded+replicated
        sum must close over the memory report's attributed total —
        within 1%, the memory_budget pattern."""
        total = self.attributed_total()
        worst = 0.0
        for ax in self.axis_names:
            b = self.axis_bytes(ax)
            s = b["sharded_bytes"] + b["replicated_bytes"]
            if total:
                worst = max(worst, abs(s - total) / total)
            elif s:
                worst = 1.0
        return worst <= 0.01, worst

    def class_shard_ratio(self, cls: str) -> Optional[float]:
        """local/global byte ratio of one *argument-backed* class —
        the ZeRO audit number (~1/world for fully sharded opt state).
        None when the class has no argument records (local temps have
        no statically known global footprint)."""
        recs = [r for r in self.records if r.cls == cls]
        if not recs:
            return None
        local = sum(r.bytes for r in recs)
        glob = sum(r.global_bytes for r in recs)
        return (local / glob) if glob else None

    # -- what-if axis forecaster ---------------------------------------------

    def forecast_axes(self, factors: Mapping[str, int]) -> Dict[str, Any]:
        """Analytic shrink forecast for a hypothetical further sharding
        (``{"tp": 2, "pp": 2}``): per class, only the portion currently
        replicated over EVERY mesh axis can shrink — it divides by the
        product of the factors; already-sharded and local portions are
        carried unchanged. Returns per-class now/eligible/forecast
        bytes plus totals."""
        prod = 1
        for name, f in factors.items():
            f = int(f)
            if f < 1:
                raise ValueError(f"axis {name!r}: factor must be >= 1")
            prod *= f
        per_class: Dict[str, Dict[str, int]] = {}
        for cls in BUFFER_CLASSES:
            total = self.class_totals.get(cls, 0)
            recs = [r for r in self.records if r.cls == cls]
            arg_local = sum(r.bytes for r in recs)
            fully_rep = sum(
                r.bytes for r in recs
                if all(not r.sharded_by(ax) for ax in self.axis_names))
            # eligible = the fully-replicated byte fraction of the
            # class's argument-backed share; the temp remainder
            # (total - args) is local working set, never eligible
            eligible = 0
            if total and arg_local:
                arg_share = min(arg_local, total)
                eligible = int(round(arg_share * fully_rep / arg_local))
            forecast = total - eligible + (eligible + prod - 1) // prod
            per_class[cls] = {"now": total, "eligible": eligible,
                              "forecast": forecast}
        return {"factors": dict(factors),
                "per_class": per_class,
                "total_now": sum(v["now"] for v in per_class.values()),
                "total_forecast": sum(v["forecast"]
                                      for v in per_class.values())}

    # -- renderings ----------------------------------------------------------

    def table(self) -> str:
        lines = [f"shard report — mesh={self.mesh_name} "
                 + " x ".join(f"{a}={self.axis_sizes[a]}"
                              for a in self.axis_names)]
        head = f"{'axis':<12} {'sharded':>12} {'replicated':>12}  per-class sharded"
        lines.append(head)
        for ax in self.axis_names:
            b = self.axis_bytes(ax)
            per = " ".join(
                f"{cls}={_fmt_bytes(v)}"
                for cls, v in self.axis_table[ax]["sharded"].items()
                if v)
            lines.append(f"{ax:<12} {_fmt_bytes(b['sharded_bytes']):>12} "
                         f"{_fmt_bytes(b['replicated_bytes']):>12}  {per}")
        lines.append("arguments:")
        for r in sorted(self.records, key=lambda r: -r.bytes)[:12]:
            axes = ",".join(a for a in self.axis_names
                            if r.sharded_by(a)) or "-"
            lines.append(
                f"  {_fmt_bytes(r.bytes):>12} {r.cls:<16} "
                f"sharded_by={axes:<24} x{r.shard_factor} "
                f"[{r.source}] {(r.path or r.name)[:48]}")
        return "\n".join(lines)

    def to_events(self, rank: int = 0, step: Optional[int] = None,
                  candidate: Optional[str] = None,
                  wire_by_axis: Optional[Mapping[str, int]] = None,
                  predicted_s: Optional[Mapping[str, float]] = None
                  ) -> List[Dict]:
        """``kind="sharding_mesh"`` header + one ``kind="sharding"``
        row per axis (plus an ``axis="unknown"`` row when the caller's
        ``wire_by_axis`` carries unattributed traffic — never silently
        dropped). Wire rows on a *composite* axis the mesh factors
        (the registry's flat ``data`` over data_inter x data_intra)
        are declared in the header's ``extra_axes`` so the per-stream
        axis enum stays strict. ``check_metrics_schema.py --kind
        sharding`` validates the stream."""
        now = time.time()
        wire = dict(wire_by_axis or {})
        pred = dict(predicted_s or {})
        rows = list(self.axis_names)
        rows += [a for a in wire if a not in rows]
        extra = [a for a in rows
                 if a not in self.axis_names and a != "unknown"]
        evs: List[Dict] = [{
            "kind": "sharding_mesh", "rank": rank, "step": step,
            "mesh": self.mesh_name, "axes": list(self.axis_names),
            "axis_sizes": dict(self.axis_sizes),
            "extra_axes": extra or None,
            "candidate": candidate, "wall_time": now}]
        for ax in rows:
            if ax in self.axis_table:
                b = self.axis_bytes(ax)
            else:                       # e.g. "unknown": wire-only row
                b = {"sharded_bytes": 0, "replicated_bytes": 0}
            evs.append({
                "kind": "sharding", "rank": rank, "step": step,
                "axis": ax, "candidate": candidate,
                "hbm_sharded_bytes": b["sharded_bytes"],
                "hbm_replicated_bytes": b["replicated_bytes"],
                "wire_bytes": wire.get(ax),
                "predicted_s": pred.get(ax),
                "wall_time": now})
        return evs


# --- the builder -------------------------------------------------------------

def shard_report(compiled, mesh_model, *,
                 report: Optional[MemoryReport] = None,
                 batch_size: Optional[int] = None,
                 overrides: Optional[Mapping[str, Sequence[str]]] = None
                 ) -> ShardReport:
    """Build a :class:`ShardReport` from a compiled executable (or an
    optimized-HLO text) and a mesh model. AOT-only: no dispatch.

    ``overrides`` maps arg-path regexes to the axis names the buffer is
    *actually* sharded by despite its annotation — the manual-sharding
    escape hatch (ZeRO's ``in_specs=P()`` opt state). ``report=`` skips
    rebuilding the memory report when the caller already has one for
    the same executable.
    """
    if isinstance(compiled, str):
        hlo_text = compiled
        if report is None:
            raise ValueError("pass report= when giving hlo_text "
                             "(class totals come from memory_report)")
    else:
        hlo_text = compiled.as_text()
        if report is None:
            report = memory_report(compiled, batch_size=batch_size)

    n = mesh_model.n_devices
    names = mesh_model.axis_names
    sizes = {a.name: a.size for a in mesh_model.axes}
    ann = parameter_shardings(hlo_text)
    ovr = [(re.compile(p), tuple(axes))
           for p, axes in (overrides or {}).items()]

    args_meta, _instrs, _root = parse_entry(hlo_text)
    from apex_tpu.prof.memory import classify_arg_path
    records: List[ShardRecord] = []
    for name, shape, path, _pnum in args_meta:
        nbytes = shape_bytes(shape)
        cls = classify_arg_path(path or name)
        body = ann.get(name, "")
        declared = next((axes for rx, axes in ovr
                         if rx.search(path or name)), None)
        if declared is not None:
            axes = {ax: ("sharded" if ax in declared else "replicated")
                    for ax in names}
            factor = 1
            for ax in declared:
                factor *= sizes.get(ax, 1)
            src = "declared"
        elif body:
            tiles, form = parse_hlo_sharding(body, n)
            if tiles is None:
                axes = {ax: "replicated" for ax in names}
                factor = 1
            else:
                axes = _axis_disposition(tiles, mesh_model)
                factor = len(set(tiles))
            src = "annotation"
        else:
            axes = {ax: "replicated" for ax in names}
            factor = 1
            src = "none"
        records.append(ShardRecord(
            name=name, path=path, cls=cls, bytes=nbytes, axes=axes,
            shard_factor=max(factor, 1), source=src, sharding=body))

    # distribute the memory report's class totals per axis: argument-
    # backed classes split by the parsed byte fractions (XLA padding
    # cancels in the ratio); the temp remainder of each class is a
    # per-device working set -> sharded by every axis ("local")
    class_totals = dict(report.classes)
    axis_table: Dict[str, Dict[str, Dict[str, int]]] = {
        ax: {"sharded": {}, "replicated": {}} for ax in names}
    for cls in BUFFER_CLASSES:
        total = class_totals.get(cls, 0)
        recs = [r for r in records if r.cls == cls]
        arg_local = sum(r.bytes for r in recs)
        arg_share = min(arg_local, total) if arg_local else 0
        temp_share = max(total - arg_share, 0)
        for ax in names:
            if arg_local:
                sh = sum(r.bytes for r in recs if r.sharded_by(ax))
                sharded = int(round(arg_share * sh / arg_local))
            else:
                sharded = 0
            sharded += temp_share          # local temps: sharded-by all
            axis_table[ax]["sharded"][cls] = sharded
            axis_table[ax]["replicated"][cls] = total - sharded

    return ShardReport(
        mesh_name=mesh_model.name or "mesh",
        axis_names=tuple(names), axis_sizes=sizes,
        records=records, axis_table=axis_table,
        class_totals=class_totals, memory=report)
