"""High-level amp API: the TPU-native ``amp.initialize`` equivalent.

The reference wires model + optimizer + scaler together imperatively
(`apex/amp/frontend.py:195-358`, `apex/amp/_initialize.py:145-263`,
`apex/amp/_process_optimizer.py:321-489`). Functionally, the same bundle is a
value: :class:`AmpState` holds fp32 master params, optimizer state and one
loss-scaler state per loss; :class:`Amp` builds and advances it inside your
jitted train step.

Typical single-loss use::

    policy = amp.Policy.from_opt_level("O2")
    amp_opt = amp.Amp(policy, optimizer)           # optimizer: optax-style tx
    state = amp_opt.init(params)

    @jax.jit
    def train_step(state, batch):
        def loss_fn(model_params):
            logits = model.apply(model_params, batch["x"])
            return cross_entropy(logits, batch["y"])
        state, loss, finite = amp_opt.step(state, loss_fn)
        return state, loss

    # amp_opt.step handles: cast masters -> model dtype, scale loss, grad,
    # unscale fp32, finite check, scaler schedule, skip-on-overflow commit.

Multi-loss (DCGAN pattern — ``num_losses``/``loss_id``,
`examples/dcgan/main_amp.py:215-253`)::

    amp_opt = amp.Amp(policy, tx, num_losses=3)
    grads, state, finite = amp_opt.backward(state, loss_fn, loss_id=1)
    state = amp_opt.apply_gradients(state, grads, finite)
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import Policy, policy_scope, _promote
from apex_tpu.amp import scaler as _scaler
from apex_tpu.amp.scaler import (
    LossScaleConfig, LossScaleState, loss_scale_init, loss_scale_update,
    scale_loss, unscale_grads,
)
from apex_tpu.monitor.metrics import Metrics, metrics_init
from apex_tpu.trace.debug_nans import nan_probe
from apex_tpu.trace.spans import span as trace_span
from apex_tpu.utils import global_norm, tree_all_finite, tree_cast, \
    tree_select


class AmpState(NamedTuple):
    """The complete mixed-precision training state (a pytree).

    ``params`` are the optimizer-facing params: fp32 masters when the policy
    uses master weights (O1/O2), model-dtype otherwise (O3). Checkpointing
    this tuple round-trips everything the reference saves across
    ``amp.state_dict`` + optimizer/model state dicts — and because masters
    are fp32, checkpoints are fp32 exactly like the O2 state-dict hook
    guarantees (`apex/amp/_initialize.py:133-142`). Hand the whole tuple to
    :class:`apex_tpu.ckpt.CheckpointManager` (``mgr.save(step, state,
    params=params0)``): every field — including a ZeRO
    ``ShardedOptState`` in ``opt_state`` — saves where it lives and
    restores onto a *different* mesh shape (docs/checkpointing.md);
    donation-safe, so a step jitted with ``donate_argnums`` over this
    state needs no special handling.

    ``metrics`` is the opt-in telemetry pytree (``Amp(..., monitor=True)``,
    see apex_tpu.monitor): ``None`` — a leafless pytree node — when
    monitoring is off, so existing states/checkpoints keep their exact
    leaf structure.
    """
    step: jax.Array
    params: Any
    opt_state: Any
    scalers: Tuple[Optional[LossScaleState], ...]
    metrics: Optional[Metrics] = None


class Amp:
    """Bundles a precision policy, an optimizer, and loss scaling.

    ``monitor=True`` threads an :class:`apex_tpu.monitor.Metrics` pytree
    through the state: backward records loss + scaler events,
    ``apply_gradients`` records grad/param norms and step/skip counts —
    all as pure in-graph arithmetic (no extra dispatches, no host syncs;
    hand ``state.metrics`` to a :class:`apex_tpu.monitor.MetricsLogger`
    to ship them off-device on an amortized cadence).
    """

    def __init__(self, policy: Policy, tx, *, num_losses: int = 1,
                 monitor: bool = False):
        self.policy = policy
        self.tx = tx
        self.num_losses = num_losses
        self.monitor = monitor
        self.scale_cfg = LossScaleConfig.from_policy_field(policy.loss_scale)

    # -- state construction --------------------------------------------------

    def init(self, params) -> AmpState:
        """Build AmpState from fp32 params.

        Master-weights policies keep params fp32 (the masters); pure-half
        policies (O3) store them in the model dtype. Mirrors
        ``lazy_init_with_master_weights`` (`_process_optimizer.py:28-90`)
        minus the laziness — state is explicit from step zero.
        """
        if self.policy.master_weights or self.policy.cast_model_type is None:
            master = tree_cast(params, jnp.float32)
        else:
            master = self.policy.cast_params(params)
        return AmpState(
            step=jnp.int32(0),
            params=master,
            opt_state=self.tx.init(master),
            scalers=tuple(loss_scale_init(self.scale_cfg)
                          for _ in range(self.num_losses)),
            metrics=metrics_init() if self.monitor else None,
        )

    def model_params(self, state: AmpState):
        """Model-dtype view of the params for the forward pass
        (master→model cast; `_process_optimizer.py:93-139` in reverse)."""
        return self.policy.cast_params(state.params)

    # -- gradient production -------------------------------------------------

    def backward(self, state: AmpState, loss_fn: Callable, *args,
                 loss_id: int = 0, has_aux: bool = False, **kwargs):
        """Scaled backward for one loss: returns (out, grads_fp32, state', finite).

        ``loss_fn(model_params, *args, **kwargs)`` is differentiated at the
        *master* params with the model-dtype cast inside the graph, so grads
        come back w.r.t. masters in fp32 — the grad-copy elision of
        ``_prepare/_post_amp_backward`` (`_process_optimizer.py:142-202`)
        falls out of autodiff for free.
        """
        return self.backward_accumulate(
            state, loss_fn, *args, stashed=None, finite=True,
            loss_id=loss_id, has_aux=has_aux, **kwargs)

    def backward_accumulate(self, state: AmpState, loss_fn: Callable,
                            *args, stashed=None, finite=True,
                            loss_id: int = 0, has_aux: bool = False,
                            **kwargs):
        """Scaled backward that ADDS onto fp32 grads stashed from previous
        microbatches — the accumulate-across-backwards semantics of
        ``unscale_with_stashed`` / ``multi_tensor_axpby``
        (`apex/amp/scaler.py:152-190`, `_process_optimizer.py:142-158`).

        Each microbatch may unscale at a *different* dynamic scale (the
        schedule advances between backwards, exactly like the reference);
        the stash is always fp32 and already unscaled, so the combine is
        ``stash + grads/scale`` in one pass. ``finite`` accumulates with
        logical-and so a single overflowing microbatch skips the whole
        accumulated step.

        Returns ``(out, acc_grads, state', finite')``. Typical use::

            acc, fin = None, True
            for mb in microbatches:          # or lax.scan
                out, acc, state, fin = amp_opt.backward_accumulate(
                    state, loss_fn, mb, stashed=acc, finite=fin)
            state = amp_opt.apply_gradients(state, acc, fin)

        Gradients accumulate as a SUM; divide each microbatch loss by the
        number of microbatches for a mean (the reference convention).
        """
        sstate = state.scalers[loss_id]

        # built-in forensic spans: "amp/fwd" scopes the forward ops in
        # xplane traces and anchors the NaN-provenance probes; the
        # probes are identity unless trace.debug_nans is on (the
        # trace/no-extra-dispatch contract)
        def scaled(p):
            mp = self.policy.cast_params(p)
            with policy_scope(self.policy):
                with trace_span("amp/fwd"):
                    out = loss_fn(mp, *args, **kwargs)
            loss = nan_probe("amp/fwd", out[0] if has_aux else out)
            return scale_loss(loss, sstate), out

        grads, out = jax.grad(scaled, has_aux=True)(state.params)
        grads = nan_probe("amp/bwd", grads)
        loss_val = out[0] if has_aux else out
        if self.scale_cfg is None:
            grads = tree_cast(grads, jnp.float32)
            if stashed is not None:
                grads = jax.tree_util.tree_map(
                    lambda s, g: s + g if jnp.issubdtype(
                        jnp.asarray(g).dtype, jnp.floating) else g,
                    stashed, grads)
            if state.metrics is not None:
                state = state._replace(
                    metrics=state.metrics.record_loss(loss_val)._replace(
                        loss_scale=jnp.float32(1.0)))
            return out, grads, state, finite
        with trace_span("amp/unscale"):
            if stashed is None:
                acc, this_finite = unscale_grads(grads, sstate)
            else:
                acc, this_finite = _scaler.unscale_grads_with_stashed(
                    grads, stashed, sstate)
        acc = nan_probe("amp/unscale", acc)
        if state.metrics is not None:
            new_sstate, metrics = loss_scale_update(
                sstate, this_finite, self.scale_cfg, metrics=state.metrics)
            metrics = metrics.record_loss(loss_val)
        else:
            new_sstate = loss_scale_update(sstate, this_finite,
                                           self.scale_cfg)
            metrics = None
        scalers = tuple(new_sstate if i == loss_id else s
                        for i, s in enumerate(state.scalers))
        if isinstance(finite, bool):
            new_finite = this_finite if finite else jnp.bool_(False)
        else:
            new_finite = jnp.logical_and(finite, this_finite)
        return out, acc, state._replace(scalers=scalers, metrics=metrics), \
            new_finite

    # -- update --------------------------------------------------------------

    def apply_gradients(self, state: AmpState, grads, grads_finite, *,
                        metrics_grad_norm=None) -> AmpState:
        """Optimizer update committed only where grads were finite.

        The skipped step neither moves params nor advances optimizer
        state/step count — the bitwise property the reference tests demand
        (`tests/L0/run_amp/test_fused_sgd.py`).

        Fused apex_tpu optimizers expose ``step`` (new params directly, one
        arena kernel); optax transforms go through ``update`` + tree add.
        """
        with trace_span("amp/update"):
            if hasattr(self.tx, "step") and callable(
                    getattr(self.tx, "step")):
                new_params, new_opt_state = self.tx.step(
                    grads, state.opt_state, state.params)
            else:
                updates, new_opt_state = self.tx.update(
                    grads, state.opt_state, state.params)
                new_params = jax.tree_util.tree_map(
                    lambda p, u: (p + u.astype(p.dtype)), state.params,
                    updates)
        committed_params = nan_probe("amp/update", tree_select(
            grads_finite, new_params, state.params))
        committed_opt = tree_select(grads_finite, new_opt_state,
                                    state.opt_state)
        if isinstance(grads_finite, bool):
            new_step = state.step + (1 if grads_finite else 0)
        else:
            new_step = state.step + jnp.where(grads_finite, 1, 0).astype(
                jnp.int32)
        metrics = state.metrics
        if metrics is not None:
            # counters advance on the SKIPPED branch too (they are
            # telemetry, not training state) — so they sit outside the
            # tree_select above. The grad-norm gauge holds its last
            # finite value across overflow steps (the event itself is in
            # overflow/skip counts); garbage-grad norms would poison the
            # logged stream with inf.
            fin = jnp.asarray(grads_finite, jnp.bool_)
            # metrics_grad_norm: the TRUE gradient norm when the caller
            # pre-scaled `grads` (the guard's LR backoff) — the gauge
            # must report gradient health, not the damped update
            gnorm = (metrics_grad_norm if metrics_grad_norm is not None
                     else global_norm(grads))
            metrics = metrics.count_step(grads_finite).record_norms(
                grad_norm=jnp.where(fin, gnorm, metrics.grad_norm),
                param_norm=global_norm(committed_params))
        return state._replace(step=new_step, params=committed_params,
                              opt_state=committed_opt, metrics=metrics)

    def numerics_sites(self, params) -> tuple:
        """The stable site tuple :meth:`step`'s ``numerics=`` hook
        observes for ``params``-shaped state: one site per leaf for
        each of the three amp observation points — ``amp/cast`` (the
        model-dtype forward copy, the tensors an fp8 rollout would
        narrow first), ``amp/grads`` (the unscaled fp32 grads) and
        ``amp/update`` (the committed optimizer delta, with the weight
        itself as the update-to-weight companion). Feed it to
        :func:`apex_tpu.monitor.numerics.numerics_init`."""
        from apex_tpu.monitor.numerics import site_names
        return site_names({"amp/cast": params, "amp/grads": params,
                           "amp/update": params})

    def dynamics_sites(self, params) -> tuple:
        """The stable site tuple :meth:`step`'s ``dynamics=`` hook
        observes for ``params``-shaped state: one ``dynamics/update``
        site per leaf — the committed optimizer delta, with the
        unscaled fp32 grad as the effective-LR companion and the
        weight itself as the update-to-weight companion. Feed it to
        :func:`apex_tpu.monitor.dynamics.dynamics_init`."""
        from apex_tpu.monitor.dynamics import site_names
        return site_names({"dynamics/update": params})

    def step(self, state: AmpState, loss_fn: Callable, *args,
             loss_id: int = 0, has_aux: bool = False, guard=None,
             numerics=None, dynamics=None, **kwargs):
        """backward + apply in one call. Returns (state', out, finite).

        ``guard=(guard_state, guard_config)`` threads an
        :class:`apex_tpu.guard.GuardState` through the step: the
        anomaly detectors observe the unscaled loss, the fp32 grads and
        the committed params between backward and apply, and the commit
        predicate becomes ``finite AND no skip-class anomaly`` — the
        loss scaler's overflow skip generalized to loss spikes, grad
        explosions and nonfinite state (docs/resilience.md). An
        optional third element ``guard=(gs, gcfg, replica_ok)`` feeds
        the cross-replica integrity verdict
        (:func:`apex_tpu.guard.integrity_ok` of this step's fingerprint
        check, docs/resilience.md#integrity) into the same observe +
        commit path, so a silently diverged replica's polluted update
        is vetoed by the unified select and counted in the
        ``replica_divergence`` class. The
        guard's LR-backoff rung applies as **gradient scaling**: grads
        are multiplied by ``gs.lr_scale`` before the optimizer (exact
        LR-equivalence for the SGD family; adaptive optimizers like
        Adam normalize much of a pure scale away — own the LR directly
        in your schedule when you need a stronger brake there). The
        return grows a fourth element:
        ``(state', out, committed, guard_state')``. All of it is
        in-graph arithmetic riding the existing dispatch (the
        ``guard/no-extra-dispatch`` compile-check case).

        ``numerics=(numerics_state, numerics_config)`` additionally
        folds the numerics observatory
        (:func:`apex_tpu.monitor.numerics.numerics_observe`) over the
        three amp observation points — the model-dtype cast copy
        (``amp/cast``), the unscaled fp32 grads (``amp/grads``) and
        the committed update delta with its update-to-weight ratio
        (``amp/update``) — under the sites
        :meth:`numerics_sites` names. Observation is read-only: the
        trajectory is bit-identical with it on or off at every opt
        level (the parity sweep in tests/test_numerics.py), and the
        return grows an element ``numerics_state'`` (after the
        guard state, when both are threaded).

        ``dynamics=(dynamics_state, dynamics_config)`` folds the
        training-dynamics observatory
        (:func:`apex_tpu.monitor.dynamics.dynamics_observe`) over the
        committed update delta (``dynamics/update`` sites —
        :meth:`dynamics_sites`), with the unscaled fp32 grads as the
        effective-LR companion and the pre-step params as the
        update-to-weight companion. An optional third element
        ``dynamics=(ds, dcfg, probe)`` threads a
        :class:`~apex_tpu.monitor.dynamics.DynamicsProbe` (or a
        zero-arg thunk returning one) from
        :func:`apex_tpu.parallel.distributed.dynamics_probe` — the
        GNS + replica-geometry collectives, for steps running under a
        dp axis. Same read-only contract (the O0–O3 parity sweep in
        tests/test_dynamics.py); the return grows a FINAL element
        ``dynamics_state'`` (after guard and numerics states, when
        threaded)."""
        out, grads, state, finite = self.backward(
            state, loss_fn, *args, loss_id=loss_id, has_aux=has_aux, **kwargs)
        old_params = state.params
        # the numerics fold observes the UNSCALED fp32 grads — the
        # guard's lr_scale damping below is a response, not a property
        # of the gradients, and telemetry must read the same with or
        # without a guard threaded
        obs_grads = grads
        if guard is None:
            new_state = self.apply_gradients(state, grads, finite)
            ret = (new_state, out, finite)
        else:
            from apex_tpu.guard import guard_observe, guard_ok
            if len(guard) == 3:
                gs, gcfg, replica_ok = guard
            else:
                gs, gcfg = guard
                replica_ok = None
            loss_val = out[0] if has_aux else out
            true_norm = global_norm(grads)
            gs = guard_observe(gs, gcfg, loss=loss_val,
                               grad_norm=true_norm,
                               params=state.params, grads_finite=finite,
                               replica_ok=replica_ok)
            grads = jax.tree_util.tree_map(
                lambda g: g * gs.lr_scale.astype(g.dtype)
                if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)
                else g, grads)
            committed = jnp.logical_and(jnp.asarray(finite, jnp.bool_),
                                        guard_ok(gs, gcfg))
            new_state = self.apply_gradients(state, grads, committed,
                                             metrics_grad_norm=true_norm)
            ret = (new_state, out, committed, gs)
        def _update_delta():
            return jax.tree_util.tree_map(
                lambda n, o: (n.astype(jnp.float32)
                              - o.astype(jnp.float32))
                if jnp.issubdtype(jnp.asarray(n).dtype, jnp.floating)
                else n, new_state.params, old_params)

        if numerics is not None:
            from apex_tpu.monitor.numerics import numerics_observe
            ns, ncfg = numerics

            def _trees():
                # built INSIDE the fold's lax.cond branch
                # (numerics_observe calls the thunk there), so the cast
                # copy and the fp32 update delta cost nothing on
                # off-steps — the off-step no-fold contract covers the
                # observation inputs too
                return {"amp/cast": self.policy.cast_params(old_params),
                        "amp/grads": obs_grads,
                        "amp/update": _update_delta()}

            ns = numerics_observe(ns, ncfg, _trees,
                                  weights={"amp/update": old_params})
            ret = ret + (ns,)
        if dynamics is None:
            return ret
        from apex_tpu.monitor.dynamics import dynamics_observe
        if len(dynamics) == 3:
            ds, dcfg, probe = dynamics
        else:
            ds, dcfg = dynamics
            probe = None

        def _dyn_trees():
            # same thunk discipline: the update delta (and a thunked
            # probe's collectives) trace inside the fold's cond branch
            return {"dynamics/update": _update_delta()}

        ds = dynamics_observe(ds, dcfg, _dyn_trees, probe=probe,
                              grads={"dynamics/update": obs_grads},
                              weights={"dynamics/update": old_params})
        return ret + (ds,)

    # -- memory accounting ---------------------------------------------------

    def memory_footprint(self, params) -> dict:
        """Analytic HBM bytes of the mixed-precision state for ``params``
        — the master-weight accounting the
        :class:`apex_tpu.prof.MemoryReport` class table is cross-checked
        against (docs/memory.md). Host-side shape arithmetic only.

        Under a master-weights policy (O1/O2) every parameter is held
        TWICE: the fp32 master (``state.params`` — classified
        ``params`` in the report, since it is the checkpointed weight)
        plus the model-dtype forward copy materialized per step (an
        ``activations``-class temp under ``amp/fwd``). O3 keeps one
        model-dtype copy. Returns ``{"n_params", "master_bytes",
        "model_copy_bytes", "scaler_bytes", "metrics_bytes",
        "total_bytes", "master_dtype", "model_dtype"}``.
        """
        import numpy as np
        leaves = jax.tree_util.tree_leaves(params)
        n = sum(int(np.prod(np.shape(l))) for l in leaves)
        if self.policy.master_weights or self.policy.cast_model_type is None:
            master_dt = jnp.dtype(jnp.float32)
        else:
            master_dt = jnp.dtype(self.policy.compute_dtype)
        model_dt = jnp.dtype(self.policy.compute_dtype)
        master_bytes = n * master_dt.itemsize
        # the per-step forward copy exists only when the stored params
        # and the compute dtype differ (O1/O2 masters): under O3 the
        # stored params ARE model-dtype and the cast is an elided no-op
        model_copy = (n * model_dt.itemsize
                      if (self.policy.cast_model_type is not None
                          and master_dt != model_dt) else 0)
        scaler_bytes = (8 * self.num_losses
                        if self.scale_cfg is not None else 0)
        metrics_bytes = 9 * 4 if self.monitor else 0
        return {
            "n_params": n,
            "master_bytes": master_bytes,
            "model_copy_bytes": model_copy,
            "scaler_bytes": scaler_bytes,
            "metrics_bytes": metrics_bytes,
            "total_bytes": (master_bytes + model_copy + scaler_bytes
                            + metrics_bytes),
            "master_dtype": str(master_dt),
            "model_dtype": str(model_dt),
        }

    # -- checkpoint parity ---------------------------------------------------

    def state_dict(self, state: AmpState):
        """Scaler state as a plain dict (``amp.state_dict``,
        `apex/amp/frontend.py:361-370`)."""
        return {
            f"loss_scaler{i}": None if s is None else
            {"loss_scale": s.loss_scale, "unskipped": s.growth_tracker}
            for i, s in enumerate(state.scalers)
        }

    def load_state_dict(self, state: AmpState, sd) -> AmpState:
        """Restore scaler state (`apex/amp/frontend.py:373-400`)."""
        scalers = []
        for i, s in enumerate(state.scalers):
            entry = sd.get(f"loss_scaler{i}")
            if s is None or entry is None:
                scalers.append(s)
            else:
                scalers.append(LossScaleState(
                    loss_scale=jnp.float32(entry["loss_scale"]),
                    growth_tracker=jnp.int32(entry["unskipped"])))
        return state._replace(scalers=tuple(scalers))


def initialize(params, tx, opt_level: str = "O1", *,
               half_dtype=jnp.bfloat16, num_losses: int = 1,
               verbosity: int = 1, monitor: bool = False,
               **policy_overrides) -> Tuple[Amp, AmpState]:
    """One-call setup: ``amp_opt, state = amp.initialize(params, tx, "O2")``.

    The ergonomic mirror of ``amp.initialize(model, optimizer, opt_level)``
    (`apex/amp/frontend.py:195-358`) for the functional world: builds the
    policy preset (kwarg overrides win), the Amp bundle, and the initial
    state in one step. ``verbosity=1`` prints the selected-properties
    banner on process 0 (`frontend.py:328-356`); 0 is silent.
    ``monitor=True`` threads the apex_tpu.monitor metrics pytree through
    the state (see docs/monitoring.md).
    """
    policy = Policy.from_opt_level(opt_level, half_dtype=half_dtype,
                                   **policy_overrides)
    if verbosity > 0:
        from apex_tpu.parallel.launch import maybe_print
        maybe_print(f"apex_tpu.amp: selected optimization level {opt_level}",
                    rank0=True)
        maybe_print("Settings for this optimization level "
                    "(overrides applied):", rank0=True)
        for field in ("enabled", "half_dtype", "cast_model_type",
                      "patch_ops", "keep_batchnorm_fp32",
                      "master_weights", "loss_scale"):
            maybe_print(f"{field:<24}: {getattr(policy, field)}",
                        rank0=True)
    amp_opt = Amp(policy, tx, num_losses=num_losses, monitor=monitor)
    return amp_opt, amp_opt.init(params)


# -- Decorator parity (`apex/amp/amp.py:30-64`) ------------------------------

def half_function(fn):
    """Run ``fn`` with floating args cast to the ambient policy's half dtype."""
    from apex_tpu.amp.policy import current_policy

    def wrapped(*args, **kwargs):
        p = current_policy()
        if p.enabled and (p.patch_ops or p.cast_model_type is not None):
            args = tree_cast(args, jnp.dtype(p.half_dtype))
            kwargs = tree_cast(kwargs, jnp.dtype(p.half_dtype))
        return fn(*args, **kwargs)
    return wrapped


def float_function(fn):
    """Run ``fn`` with floating args cast to fp32."""
    from apex_tpu.amp.policy import current_policy

    def wrapped(*args, **kwargs):
        p = current_policy()
        if p.enabled:
            args = tree_cast(args, jnp.float32)
            kwargs = tree_cast(kwargs, jnp.float32)
        return fn(*args, **kwargs)
    return wrapped


def promote_function(fn):
    """Run ``fn`` with floating args promoted to their widest dtype."""
    def wrapped(*args, **kwargs):
        dts = [jnp.asarray(x).dtype
               for x in jax.tree_util.tree_leaves((args, kwargs))
               if hasattr(x, "dtype") or isinstance(x, (int, float))]
        target = _promote(dts)
        if dts:
            args = tree_cast(args, target)
            kwargs = tree_cast(kwargs, target)
        return fn(*args, **kwargs)
    return wrapped
