"""apex_tpu.prof tests — annotate / xplane parse / HLO cost analysis.

Mirrors the reference's pyprof tests (`tests/L0/run_pyprof_nvtx`,
`run_pyprof_data`): the nvtx tier asserts every wrapped call still
computes correctly and markers are emitted; the data tier feeds
hand-built kernel records through the analyzers. Here: named scopes must
appear in lowered HLO, the module interceptor must record call shapes,
the xplane parser is fed a hand-built XSpace proto, and cost analysis
must report real FLOPs for a matmul.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import prof


def test_scope_names_appear_in_hlo():
    def f(x):
        with prof.scope("my_marker_scope"):
            y = x @ x
        return jnp.tanh(y).sum()

    text = jax.jit(f).lower(jnp.ones((64, 64))).as_text(debug_info=True)
    assert "my_marker_scope" in text


def test_annotate_decorator_preserves_semantics():
    @prof.annotate("step")
    def f(x):
        return 2.0 * x

    np.testing.assert_allclose(f(jnp.arange(4.0)), [0, 2, 4, 6])


def test_annotate_modules_records_calls():
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(8)(x)
            return nn.Dense(4)(x)

    net = Net()
    x = jnp.ones((2, 16))
    params = net.init(jax.random.PRNGKey(0), x)
    with prof.annotate_modules() as records:
        out = net.apply(params, x)
    assert out.shape == (2, 4)
    paths = [r.path for r in records]
    assert any("Dense_0" in p for p in paths)
    assert any("Dense_1" in p for p in paths)
    dense0 = next(r for r in records if "Dense_0" in r.path)
    assert dense0.method == "__call__"
    assert ((2, 16), "float32") in jax.tree_util.tree_leaves(
        [dense0.args]) or str(dense0.args).count("16")


def test_cost_analysis_matmul_flops():
    def f(a, b):
        return a @ b

    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    cost = prof.cost_analysis(f, a, b)
    # 2*M*N*K = 2*128*64*256 = 4.19e6; XLA may count slightly differently
    assert cost["flops"] >= 2 * 128 * 64 * 256 * 0.9
    assert cost["bytes_accessed"] > 0


def test_op_estimates_finds_dot():
    def f(a, b):
        return jnp.tanh(a @ b)

    a = jnp.ones((32, 64), jnp.float32)
    b = jnp.ones((64, 16), jnp.float32)
    ests = prof.op_estimates(f, a, b)
    assert ests, "no instructions parsed from optimized HLO"
    dots = [e for e in ests if e.opcode == "dot"]
    fusion_flops = sum(e.flops for e in ests)
    # the dot may stay top-level or be fused; either way some op should
    # carry the matmul flops when a top-level dot exists
    if dots:
        assert dots[0].flops == pytest.approx(2 * 32 * 16 * 64)
    assert all(e.bytes >= 0 for e in ests)
    assert fusion_flops >= 0


def _build_xspace(tmp_path):
    """Hand-build an XSpace proto shaped like a real TPU trace."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"

    md_mod = plane.event_metadata[1]
    md_mod.id = 1
    md_mod.name = "jit_step(123)"
    md_fus = plane.event_metadata[2]
    md_fus.id = 2
    md_fus.name = ("%fusion.3 = f32[128,128]{1,0:T(8,128)} "
                   "fusion(f32[128,128]{1,0} %p0), kind=kLoop, "
                   "calls=%fused_computation")
    md_conv = plane.event_metadata[3]
    md_conv.id = 3
    md_conv.name = ("%convolution.7 = f32[8,16,16,64]{3,2,1,0} "
                    "convolution(f32[8,16,16,32]{3,2,1,0} %x, "
                    "f32[3,3,32,64]{3,2,1,0} %w), dim_labels=b01f_01io->b01f")

    mods = plane.lines.add()
    mods.name = "XLA Modules"
    for i in range(2):
        ev = mods.events.add()
        ev.metadata_id = 1
        ev.offset_ps = i * 10**9
        ev.duration_ps = 500_000_000  # 500 us

    ops = plane.lines.add()
    ops.name = "XLA Ops"
    for i in range(2):
        ev = ops.events.add()
        ev.metadata_id = 2
        ev.duration_ps = 100_000_000  # 100 us
        ev = ops.events.add()
        ev.metadata_id = 3
        ev.duration_ps = 300_000_000  # 300 us

    p = tmp_path / "host.xplane.pb"
    p.write_bytes(xs.SerializeToString())
    return str(p)


def test_xplane_parser_synthetic(tmp_path):
    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    path = _build_xspace(tmp_path)
    tp = prof.parse_trace(path)
    assert tp.device == "/device:TPU:0"
    assert tp.module_runs == 2
    assert tp.module_total_us == pytest.approx(1000.0)
    assert len(tp.ops) == 2
    conv = tp.ops[0]  # sorted by total time desc: conv 600us > fusion 200us
    assert conv.opcode == "convolution"
    assert conv.category == "conv"
    assert conv.occurrences == 2
    assert conv.total_us == pytest.approx(600.0)
    fus = tp.ops[1]
    assert fus.category == "fusion.loop"
    assert fus.avg_us == pytest.approx(100.0)
    cats = tp.by_category()
    assert cats["conv"] == pytest.approx(600.0)
    assert "conv" in tp.table()


def test_xplane_parse_without_tensorflow(tmp_path, monkeypatch):
    """With the tf proto import blocked, parse raises an actionable error
    naming the HLO-estimates fallback (the reference degrades its scaler
    import the same way, apex/amp/scaler.py:39-52)."""
    import builtins
    path = tmp_path / "host.xplane.pb"
    path.write_bytes(b"")
    real_import = builtins.__import__

    def block_tf(name, *args, **kwargs):
        if name.startswith("tensorflow"):
            raise ModuleNotFoundError("No module named 'tensorflow'")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", block_tf)
    with pytest.raises(ImportError, match="op_estimates"):
        prof.parse_trace(str(path))


def test_trace_capture_roundtrip(tmp_path):
    """End-to-end: capture a real trace, parse it without raising."""
    logdir = str(tmp_path / "trace")

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x).sum()

    x = jnp.ones((64, 64))
    f(x).block_until_ready()
    with prof.trace(logdir):
        np.asarray(f(x))
    found = prof.parse_trace.__globals__["latest_xplane"](logdir)
    assert found is not None, "trace produced no xplane.pb"
    tp = prof.parse_trace(logdir)
    # CPU backend has no device plane; parser must degrade, not raise
    assert isinstance(tp.ops, list)


def test_profile_step_cpu():
    def f(x):
        return (x @ x).sum()

    rep = prof.profile_step(f, jnp.ones((64, 64)), iters=2, warmup=1)
    assert rep.cost["flops"] > 0
    assert rep.wall_us > 0
    assert isinstance(rep.table(), str)
    # CPU: no device plane → mfu computes to 0 (peak unknown)
    assert rep.mfu() == 0.0


_REPO_ROOT = str(__import__("pathlib").Path(__file__).resolve().parents[1])


def test_cli_on_synthetic_trace(tmp_path):
    """`python -m apex_tpu.prof <logdir>` — the pyprof.parse/prof CLI
    equivalent — renders the op table from a trace dir."""
    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")
    import subprocess, sys
    path = _build_xspace(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof", str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r.returncode == 0, r.stderr
    assert "convolution" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof", str(tmp_path), "--csv"],
        capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r2.returncode == 0
    assert r2.stdout.startswith("name,category,occurrences,total_us")


def test_cli_empty_dir(tmp_path):
    import subprocess, sys
    r = subprocess.run(
        [sys.executable, "-m", "apex_tpu.prof", str(tmp_path)],
        capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r.returncode == 1
