"""SelfMultiheadAttn / EncdecMultiheadAttn modules.

Module mirrors of `apex.contrib.multihead_attn`
(`self_multihead_attn.py:27-200`, `encdec_multihead_attn.py`): packed
QKV/KV projections, ``impl='fast'`` (fused blockwise kernel,
apex_tpu.ops.attention) vs ``impl='default'`` (pure-jnp reference path),
optional pre-LayerNorm + residual add (``include_norm_add``, the
``*_norm_add`` CUDA variants), additive masks, and softmax/output dropout.

Softmax dropout on the fast path runs *inside* the fused kernel (the
reference fuses Philox dropout into its softmax kernel, `dropout.h`);
the per-step seed is drawn from the module's ``'dropout'`` rng stream.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from apex_tpu.ops import attention as A
from apex_tpu.ops.layer_norm import fused_layer_norm_affine


def _softmax_dropout(mod, rate, deterministic):
    """(rate, seed) for the fused kernel: 0-rate when not training, else
    a fresh int32 seed folded out of the module's 'dropout' rng stream."""
    if rate <= 0 or deterministic:
        return 0.0, None
    rng = mod.make_rng("dropout")
    seed = jax.lax.bitcast_convert_type(
        jax.random.bits(rng, dtype=jnp.uint32), jnp.int32)
    return rate, seed


def _dropout_attention(mod, q, k, v, bias, causal, rate, deterministic):
    """Default-impl attention with *softmax-probability* dropout — the
    single dropout the reference applies (`softmax.h` dropout fused into
    the probability matrix; `self_multihead_attn_func.py:120-140`)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(d)
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2:]
        cmask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(cmask, s, A.NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if rate > 0 and not deterministic:
        rng = mod.make_rng("dropout")
        keep = jax.random.bernoulli(rng, 1.0 - rate, p.shape)
        p = jnp.where(keep, p / (1.0 - rate), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


class SelfMultiheadAttn(nn.Module):
    """Packed-QKV self attention (`self_multihead_attn.py:27-90`).

    ``separate_qkv_params`` mirrors the reference flag of the same name.
    Inputs/outputs are (B, S, H) batch-first.
    """
    hidden: int
    heads: int
    dropout: float = 0.0
    bias: bool = True
    include_norm_add: bool = False
    separate_qkv_params: bool = False
    impl: str = "fast"

    @nn.compact
    def __call__(self, x, attn_bias=None, causal: bool = False,
                 deterministic: bool = True):
        h, nh = self.hidden, self.heads
        d = h // nh
        B, S = x.shape[0], x.shape[1]

        residual = x
        if self.include_norm_add:
            w = self.param("ln_scale", nn.initializers.ones, (h,),
                           jnp.float32)
            b = self.param("ln_bias", nn.initializers.zeros, (h,),
                           jnp.float32)
            x = fused_layer_norm_affine(x, w, b, 1e-5)

        if self.separate_qkv_params:
            q = nn.Dense(h, use_bias=self.bias, name="q_proj")(x)
            k = nn.Dense(h, use_bias=self.bias, name="k_proj")(x)
            v = nn.Dense(h, use_bias=self.bias, name="v_proj")(x)
        else:
            qkv = nn.Dense(3 * h, use_bias=self.bias, name="qkv_proj")(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)

        shape4 = lambda t: t.reshape(B, S, nh, d)
        q, k, v = map(shape4, (q, k, v))

        if self.impl == "fast":
            rate, seed = _softmax_dropout(self, self.dropout,
                                          deterministic)
            ctx = A.flash_attention(q, k, v, bias=attn_bias, causal=causal,
                                    dropout_rate=rate, dropout_seed=seed)
        else:
            ctx = _dropout_attention(
                self, q, k, v, attn_bias, causal, self.dropout,
                deterministic)
        ctx = ctx.reshape(B, S, h)
        out = nn.Dense(h, use_bias=self.bias, name="out_proj")(ctx)
        if self.include_norm_add:
            # reference applies output dropout before the residual add in
            # the norm-add variant (`self_multihead_attn.py:165`,
            # jit_dropout_add)
            if self.dropout > 0 and not deterministic:
                out = nn.Dropout(self.dropout, deterministic=False)(out)
            out = out + residual
        return out


class EncdecMultiheadAttn(nn.Module):
    """Encoder-decoder attention with packed KV
    (`encdec_multihead_attn.py`): q from the decoder stream, k/v projected
    together from the encoder memory."""
    hidden: int
    heads: int
    dropout: float = 0.0
    bias: bool = True
    include_norm_add: bool = False
    impl: str = "fast"

    @nn.compact
    def __call__(self, query, key, attn_bias=None,
                 deterministic: bool = True):
        h, nh = self.hidden, self.heads
        d = h // nh
        B, Sq = query.shape[0], query.shape[1]
        Sk = key.shape[1]

        residual = query
        if self.include_norm_add:
            w = self.param("ln_scale", nn.initializers.ones, (h,),
                           jnp.float32)
            b = self.param("ln_bias", nn.initializers.zeros, (h,),
                           jnp.float32)
            query = fused_layer_norm_affine(query, w, b, 1e-5)

        q = nn.Dense(h, use_bias=self.bias, name="q_proj")(query)
        kv = nn.Dense(2 * h, use_bias=self.bias, name="kv_proj")(key)
        k, v = jnp.split(kv, 2, axis=-1)

        q = q.reshape(B, Sq, nh, d)
        k = k.reshape(B, Sk, nh, d)
        v = v.reshape(B, Sk, nh, d)

        if self.impl == "fast":
            rate, seed = _softmax_dropout(self, self.dropout,
                                          deterministic)
            ctx = A.flash_attention(q, k, v, bias=attn_bias,
                                    dropout_rate=rate, dropout_seed=seed)
        else:
            ctx = _dropout_attention(self, q, k, v, attn_bias, False,
                                     self.dropout, deterministic)
        ctx = ctx.reshape(B, Sq, h)
        out = nn.Dense(h, use_bias=self.bias, name="out_proj")(ctx)
        if self.include_norm_add:
            # output dropout before residual, `encdec_multihead_attn.py`
            # norm-add path
            if self.dropout > 0 and not deterministic:
                out = nn.Dropout(self.dropout, deterministic=False)(out)
            out = out + residual
        return out
