from apex_tpu.utils.backoff import backoff_sleep
from apex_tpu.utils.bits import uint_view_dtype
from apex_tpu.utils.fsio import fsync_dir, write_atomic
from apex_tpu.utils.tree import (
    tree_cast,
    tree_all_finite,
    tree_select,
    tree_zeros_like,
    tree_size,
    global_norm,
)

__all__ = [
    "tree_cast",
    "tree_all_finite",
    "tree_select",
    "tree_zeros_like",
    "tree_size",
    "global_norm",
    "backoff_sleep",
    "uint_view_dtype",
    "write_atomic",
    "fsync_dir",
]
