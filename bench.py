"""Headline bench: ResNet-50 mixed-precision training throughput.

The BASELINE.json metric — images/sec/chip + MFU on ResNet-50, amp O2
(bf16 compute, fp32 masters) + fused SGD — measured on whatever single
accelerator is present. Prints ONE JSON line.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# per-chip peak bf16 FLOP/s by device kind (public spec sheets)
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in _PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 0.0  # unknown/CPU: MFU reported as 0


def main():
    from apex_tpu import amp, models, ops
    from apex_tpu.optim import FusedSGD

    on_tpu = jax.default_backend() == "tpu"
    batch = 128 if on_tpu else 8
    size = 224 if on_tpu else 64

    model = models.ResNet50(num_classes=1000)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, size, size, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)

    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    amp_opt = amp.Amp(amp.Policy.from_opt_level("O2"),  # bf16 compute
                      FusedSGD(lr=0.1, momentum=0.9))
    state = amp_opt.init(params)

    @jax.jit
    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            return loss, mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    # warmup / compile. NOTE: sync via host fetch of the loss —
    # block_until_ready does not actually block on the experimental axon
    # TPU platform, producing fantasy timings.
    for _ in range(3):
        state, batch_stats, loss = step(state, batch_stats, x, y)
    float(loss)

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        state, batch_stats, loss = step(state, batch_stats, x, y)
    float(loss)
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    # fwd+bwd ≈ 3x fwd FLOPs, scaled to the bench image size
    flops_img = models.RESNET50_FLOPS_PER_IMAGE * 3 * (size / 224) ** 2
    peak = peak_flops(jax.devices()[0])
    mfu = (img_s * flops_img / peak) if peak else 0.0

    print(json.dumps({
        "metric": "resnet50_amp_o2_images_per_sec",
        "value": round(img_s, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.60, 4),  # north star: 60% MFU
        "extra": {"mfu": round(mfu, 4), "batch": batch, "size": size,
                  "device": getattr(jax.devices()[0], "device_kind", "?"),
                  "loss": float(loss)},
    }))


if __name__ == "__main__":
    main()
