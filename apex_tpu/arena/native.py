"""ctypes bridge to the native layout planner (apex_tpu/csrc).

Loads ``apex_tpu/_native/libapex_tpu.so`` if present (built by
``make -C apex_tpu/csrc``), attempts an on-demand build once if not, and
falls back to pure-Python implementations with identical semantics — the
same graceful degradation the reference uses for its extensions
(`apex/parallel/__init__.py:14-19`, `apex/amp/scaler.py:39-52`).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "..", "_native", "libapex_tpu.so")
_CSRC = os.path.join(_HERE, "..", "csrc")

_lib = None
_load_failed = False
_tried_build = False


def _load():
    """Load (building once if needed) the native planner; None on any
    failure — a stale or mis-built .so (missing symbol, ABI mismatch) must
    degrade to the Python fallback, never crash."""
    global _lib, _load_failed, _tried_build
    if _lib is not None or _load_failed:
        return _lib
    if not os.path.exists(_SO_PATH) and not _tried_build:
        _tried_build = True
        try:
            subprocess.run(["make", "-C", _CSRC], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            pass
    if os.path.exists(_SO_PATH):
        try:
            lib = ctypes.CDLL(_SO_PATH)
            lib.apex_plan_layout.restype = ctypes.c_int64
            lib.apex_plan_buckets.restype = ctypes.c_int64
            lib.apex_plan_shards.restype = ctypes.c_int64
            lib.apex_native_abi_version.restype = ctypes.c_int64
            if lib.apex_native_abi_version() == 1:
                _lib = lib
        except (OSError, AttributeError):
            pass
    if _lib is None:
        _load_failed = True  # don't retry CDLL on every planner call
    return _lib


def native_available() -> bool:
    return _load() is not None


def _as_i64(arr):
    return np.ascontiguousarray(arr, dtype=np.int64)


def plan_layout(sizes, alignment):
    """(offsets, padded, total) for aligned slot layout."""
    lib = _load()
    sizes = _as_i64(sizes)
    n = len(sizes)
    if lib is not None and n:
        offsets = np.empty(n, np.int64)
        padded = np.empty(n, np.int64)
        total = lib.apex_plan_layout(
            ctypes.c_int64(n),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(alignment),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            padded.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return offsets, padded, int(total)
    # Python fallback — identical semantics
    alignment = max(int(alignment), 1)
    padded = (sizes + alignment - 1) // alignment * alignment
    offsets = np.concatenate([[0], np.cumsum(padded)[:-1]]).astype(np.int64) \
        if n else np.zeros(0, np.int64)
    return offsets, padded, int(padded.sum())


def plan_buckets(padded, bucket_elems):
    """(bucket_ids, num_buckets) — greedy size-capped bucketing."""
    lib = _load()
    padded = _as_i64(padded)
    n = len(padded)
    if lib is not None and n:
        ids = np.empty(n, np.int64)
        nb = lib.apex_plan_buckets(
            ctypes.c_int64(n),
            padded.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(bucket_elems),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return ids, int(nb)
    bucket_elems = max(int(bucket_elems), 1)
    ids = np.zeros(n, np.int64)
    bucket = fill = 0
    for i in range(n):
        if fill > 0 and fill + padded[i] > bucket_elems:
            bucket += 1
            fill = 0
        ids[i] = bucket
        fill += int(padded[i])
    return ids, (bucket + 1 if n else 0)


def plan_shards(total_elems, world_size, alignment):
    """(shard_starts, shard_size) — equal aligned ZeRO shards."""
    lib = _load()
    if lib is not None and world_size > 0:
        starts = np.empty(world_size, np.int64)
        per = lib.apex_plan_shards(
            ctypes.c_int64(total_elems), ctypes.c_int64(world_size),
            ctypes.c_int64(alignment),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return starts, int(per)
    alignment = max(int(alignment), 1)
    per = -(-total_elems // world_size)
    per = -(-per // alignment) * alignment
    return np.arange(world_size, dtype=np.int64) * per, int(per)
