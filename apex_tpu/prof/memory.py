"""HBM footprint reports + buffer attribution + OOM forensics.

The reference's pyprof pipeline stops at time/FLOPs (`apex/pyprof/prof/`);
on TPU the other failure axis is HBM, and today an OOM is diagnosed by
bisecting batch sizes. The compiler already knows everything needed: XLA's
buffer assignment totals are exposed as ``Compiled.memory_analysis()``
and the optimized (scheduled) HLO text carries every buffer with its
shape, layout, and the named-scope path it was traced under. This module
turns both into a :class:`MemoryReport`:

- **totals** — argument / output / temp / generated-code bytes from
  ``memory_analysis()`` (normalized across jax versions);
- **per-buffer attribution** — every entry argument is attributed by its
  *argument path* (jax records ``state.opt_state.slots['m']['float32']``
  as parameter metadata) and every temp by the *named scope* of its
  defining instruction, then bucketed into classes: **params**,
  **optimizer_state**, **activations**, **comm** (``ddp/sync_gradients``
  buckets, collective buffers), **inputs**, **outputs** — so ZeRO shard
  savings and ``bucket_plan`` buffer overhead become a printed
  ``report.table()``, not folklore;
- **peak-live estimate** — the optimized module is scheduled
  (``is_scheduled=true``), so a liveness walk over the instruction order
  (buffers live from definition to last use, arguments for the whole
  program) yields a peak-live-bytes estimate and the class mix at the
  peak;
- **what-if batch scaler** — buffers whose leading dimension is the
  (per-device) batch are scaled linearly to forecast the peak at other
  batch sizes against the device's HBM capacity
  (``device.memory_stats()``), answering "what batch OOMs?" before the
  chip does.

Wiring: :meth:`apex_tpu.monitor.MetricsLogger.sample_memory` streams
runtime ``memory_stats()`` samples into the ``memory`` event channel, and
:meth:`apex_tpu.trace.FlightRecorder.attach_memory_report` embeds the
last report in crash dumps so an OOM dump names the biggest buffers
instead of just dying. See docs/memory.md.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from apex_tpu.prof.hlo import _DTYPE_BYTES, _compile, cost_analysis_of
# ONE scope-stripping rule for device-time attribution (xplane) and
# byte attribution (here)
from apex_tpu.prof.xplane import strip_scope as _strip_scope

__all__ = [
    "MemoryReport", "BufferRecord", "memory_report", "memory_stats_of",
    "hbm_capacity", "device_memory_sample", "BUFFER_CLASSES",
    "parse_entry",
]

#: attribution classes, in table order. The first four are the
#: training-semantics split the ZeRO/Megatron accounting discipline
#: names; inputs/outputs make the attribution total (arguments + outputs
#: + temps + generated code) closed — scripts/memory_budget.py asserts
#: the class sum matches ``memory_analysis()`` within 1%.
BUFFER_CLASSES = ("params", "optimizer_state", "activations", "comm",
                  "inputs", "outputs")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

# entry-computation instruction: "%name = SHAPE opcode(args...)", where
# SHAPE may be a tuple whose layout annotations contain parens
_INSTR_RE = re.compile(
    r"^(?P<root>ROOT )?%?(?P<n>[^ ]+) = "
    r"(?P<shape>\((?:[^()]|\([^()]*\))*\)|[^ ]+) "
    r"(?P<op>[\w-]+)\(")

_OP_NAME_RE = re.compile(r'op_name="((?:[^"\\]|\\.)*)"')

# opcodes whose "result" is a view / control artifact, not a fresh
# HBM allocation — excluded from the liveness walk
_NO_ALLOC_OPS = ("parameter", "get-tuple-element", "bitcast", "tuple",
                 "after-all", "partition-id", "replica-id",
                 "opt-barrier")

_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "all-to-all", "collective-permute",
                   "collective-broadcast", "ragged-all-to-all")


def shape_bytes(shape_text: str) -> int:
    """Total bytes of every typed shape in an HLO type string (tuples
    sum their elements; layout annotations are ignored — estimates are
    unpadded logical bytes)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(shape_text):
        if dt not in _DTYPE_BYTES:
            continue
        elems = int(np.prod([int(d) for d in dims.split(",") if d] or [1]))
        total += elems * _DTYPE_BYTES[dt]
    return total


def _leading_dim(shape_text: str) -> Optional[int]:
    m = _SHAPE_RE.search(shape_text)
    if not m:
        return None
    dims = [int(d) for d in m.group(2).split(",") if d]
    return dims[0] if dims else None




def classify_arg_path(path: str) -> str:
    """Attribution class of an entry argument from its argument path
    (jax records the path — e.g. ``state.opt_state.slots['m']['f32']``
    — as the parameter's metadata op_name)."""
    p = path.lower()
    if "opt_state" in p or "optimizer" in p:
        return "optimizer_state"
    if "residual" in p:                    # error-feedback comm residuals
        return "comm"
    if "scaler" in p or "metrics" in p:
        return "optimizer_state"           # training-state bookkeeping
    if "params" in p or "master" in p or "batch_stats" in p:
        return "params"
    return "inputs"


def classify_scope(scope: str, opcode: str) -> str:
    """Attribution class of a temp buffer from its defining
    instruction's named scope + opcode."""
    if opcode.startswith(_COLLECTIVE_OPS):
        return "comm"
    if "ddp/sync_gradients" in scope or re.search(r"(^|/)bucket\d", scope):
        return "comm"
    return "activations"


@dataclasses.dataclass
class BufferRecord:
    """One attributed buffer of the compiled module."""

    name: str          # instruction / parameter name
    kind: str          # "argument" | "temp" | "output"
    bytes: int         # logical (unpadded) bytes of the result shape
    shape: str         # HLO type string
    cls: str           # one of BUFFER_CLASSES
    scope: str         # arg path (arguments) or named-scope path (temps)
    batch_scaled: bool = False   # leading dim == the given batch size


def memory_stats_of(compiled) -> Dict[str, int]:
    """Normalized ``memory_analysis()`` totals of a compiled executable:
    {"argument", "output", "temp", "alias", "generated_code", "total"}
    bytes (zeros when the backend reports nothing). ``total`` counts
    each byte once: arguments + outputs + temps + generated code."""
    out = {"argument": 0, "output": 0, "temp": 0, "alias": 0,
           "generated_code": 0}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        if isinstance(ma, (list, tuple)):          # older per-device lists
            ma = ma[0] if ma else None
    if ma is not None:
        out["argument"] = int(getattr(ma, "argument_size_in_bytes", 0))
        out["output"] = int(getattr(ma, "output_size_in_bytes", 0))
        out["temp"] = int(getattr(ma, "temp_size_in_bytes", 0))
        out["alias"] = int(getattr(ma, "alias_size_in_bytes", 0))
        out["generated_code"] = int(
            getattr(ma, "generated_code_size_in_bytes", 0))
    out["total"] = (out["argument"] + out["output"] + out["temp"]
                    + out["generated_code"])
    return out


def hbm_capacity(device=None) -> Optional[int]:
    """Device memory capacity in bytes from ``memory_stats()`` —
    None when the backend doesn't report (CPU)."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return None
    v = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    return int(v) if v else None


def device_memory_sample(device=None) -> Dict[str, Optional[int]]:
    """One runtime HBM sample (host-side call, no device dispatch):
    {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"} — values None
    when the backend doesn't report them (CPU). Feed to
    ``MetricsLogger.sample_memory`` for the ``memory`` event channel."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:
        stats = {}
    pick = lambda k: int(stats[k]) if k in stats else None
    return {"bytes_in_use": pick("bytes_in_use"),
            "peak_bytes_in_use": pick("peak_bytes_in_use"),
            "bytes_limit": pick("bytes_limit")}


# --- entry-computation parse -------------------------------------------------

def _entry_lines(hlo_text: str) -> List[str]:
    lines = hlo_text.splitlines()
    for i, line in enumerate(lines):
        if line.startswith("ENTRY"):
            body = []
            for l in lines[i + 1:]:
                if l.startswith("}"):
                    break
                body.append(l.strip())
            return body
    return []


_PARAM_NUM_RE = re.compile(r"parameter\((\d+)\)")


def parse_entry(hlo_text: str):
    """(args, instrs, root_operands) of the entry computation.

    args: [(name, shape, arg_path, param_number)];
    instrs: [(idx, name, shape, opcode, operands, scope, is_root)].

    Shared scheduled-HLO parser: buffer attribution here and the
    apexlint HLO pass (``apex_tpu.lint``) read the same records.
    """
    args, instrs = [], []
    root_ops: List[str] = []
    for idx, line in enumerate(_entry_lines(hlo_text)):
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name = m.group("n").lstrip("%")
        shape, op = m.group("shape"), m.group("op")
        sm = _OP_NAME_RE.search(line)
        op_name = sm.group(1) if sm else ""
        if op == "parameter":
            pm = _PARAM_NUM_RE.search(line)
            pnum = int(pm.group(1)) if pm else len(args)
            # the arg-path metadata has escaped quotes: state.params[\'w\']
            args.append((name, shape, op_name.replace("\\'", "'"), pnum))
        # operand names: %-prefixed tokens inside the call parens
        tail = line.split(f" {op}(", 1)[-1]
        operands = re.findall(r"%([\w.\-]+)", tail)
        is_root = bool(m.group("root"))
        if is_root:
            root_ops = operands
        instrs.append((idx, name, shape, op, operands,
                       _strip_scope(op_name), is_root))
    return args, instrs, root_ops


def _liveness(instrs, batch_size: Optional[int]):
    """Scheduled liveness walk over the entry computation.

    Returns (peak_temp_bytes, peak_idx, live_at_peak records,
    batch_scaled_peak_bytes, comm_peak_bytes). Buffers are live from
    their defining instruction to their last top-level use; arguments
    are excluded here (they are live program-wide and counted from
    ``memory_analysis`` argument bytes instead)."""
    defs: Dict[str, Tuple[int, int, str, str, str]] = {}
    last_use: Dict[str, int] = {}
    for idx, name, shape, op, operands, scope, _root in instrs:
        for o in operands:
            if o in defs:
                last_use[o] = idx
        if op in _NO_ALLOC_OPS:
            continue
        nbytes = shape_bytes(shape)
        if nbytes <= 0:
            continue
        defs[name] = (idx, nbytes, shape, op, scope)

    events: Dict[int, int] = {}
    for name, (didx, nbytes, _s, _o, _sc) in defs.items():
        events[didx] = events.get(didx, 0) + nbytes
        end = last_use.get(name, didx)
        events[end + 1] = events.get(end + 1, 0) - nbytes
    live, peak, peak_idx = 0, 0, 0
    for idx in sorted(events):
        live += events[idx]
        if live > peak:
            peak, peak_idx = live, idx

    at_peak: List[BufferRecord] = []
    batch_peak = comm_peak = 0
    for name, (didx, nbytes, shape, op, scope) in defs.items():
        if didx <= peak_idx <= last_use.get(name, didx):
            cls = classify_scope(scope, op)
            scaled = bool(batch_size and batch_size > 1
                          and _leading_dim(shape) == batch_size)
            at_peak.append(BufferRecord(
                name=name, kind="temp", bytes=nbytes, shape=shape,
                cls=cls, scope=scope, batch_scaled=scaled))
            if scaled:
                batch_peak += nbytes
            if cls == "comm":
                comm_peak += nbytes
    at_peak.sort(key=lambda r: -r.bytes)
    return peak, peak_idx, at_peak, batch_peak, comm_peak


# --- the report --------------------------------------------------------------

def _fmt_bytes(n: Optional[float]) -> str:
    from apex_tpu.utils.format import fmt_bytes
    return fmt_bytes(n)


@dataclasses.dataclass
class MemoryReport:
    """Per-class, per-buffer HBM footprint of one compiled step."""

    stats: Dict[str, int]             # memory_analysis totals
    classes: Dict[str, int]           # BUFFER_CLASSES -> bytes
    buffers: List[BufferRecord]       # arguments + temps live at peak
    peak_live_bytes: int              # args + peak live temps (estimate)
    batch_size: Optional[int]         # per-device batch the step compiled at
    batch_bytes: int                  # peak bytes scaling with that batch
    hbm_limit: Optional[int]          # device capacity, None off-TPU
    device_kind: str
    flops: float = 0.0                # XLA cost analysis, for context

    @property
    def total_bytes(self) -> int:
        return self.stats["total"]

    def attributed_total(self) -> int:
        """Sum over classes — scripts/memory_budget.py asserts this
        matches ``memory_analysis()`` within 1%."""
        return sum(self.classes.values())

    # -- what-if batch scaler ------------------------------------------------

    def forecast(self, batch: int) -> Dict[str, Any]:
        """Forecast peak-live bytes at another (per-device) batch size:
        batch-scaled buffers grow linearly, the rest is fixed. ``fits``
        is None when the device doesn't report HBM capacity."""
        if not self.batch_size or self.batch_size < 1:
            raise ValueError("report was built without batch_size=")
        scale = batch / self.batch_size
        peak = int(self.peak_live_bytes - self.batch_bytes
                   + self.batch_bytes * scale)
        fits = None if self.hbm_limit is None else peak <= self.hbm_limit
        return {"batch": batch, "peak_bytes": peak, "fits": fits,
                "headroom_bytes": (None if self.hbm_limit is None
                                   else self.hbm_limit - peak)}

    def max_batch(self) -> Optional[int]:
        """Largest forecast (per-device) batch fitting HBM capacity —
        None when capacity or batch scaling is unknown."""
        if (self.hbm_limit is None or not self.batch_size
                or self.batch_bytes <= 0):
            return None
        fixed = self.peak_live_bytes - self.batch_bytes
        if fixed >= self.hbm_limit:
            return 0
        per_item = self.batch_bytes / self.batch_size
        return int((self.hbm_limit - fixed) // per_item)

    # -- renderings ----------------------------------------------------------

    def top_buffers(self, n: int = 8) -> List[BufferRecord]:
        return sorted(self.buffers, key=lambda r: -r.bytes)[:n]

    def table(self, top: int = 10) -> str:
        s = self.stats
        lines = [
            f"memory report — device={self.device_kind} "
            f"total={_fmt_bytes(s['total'])} "
            f"(args {_fmt_bytes(s['argument'])} + out "
            f"{_fmt_bytes(s['output'])} + temp {_fmt_bytes(s['temp'])} + "
            f"code {_fmt_bytes(s['generated_code'])}; aliased "
            f"{_fmt_bytes(s['alias'])})",
            f"peak live estimate: {_fmt_bytes(self.peak_live_bytes)}"
            + (f" of {_fmt_bytes(self.hbm_limit)} HBM"
               if self.hbm_limit else "")
            + (f"; batch-scaled {_fmt_bytes(self.batch_bytes)} "
               f"@ b={self.batch_size}" if self.batch_size else ""),
            f"{'class':<16} {'bytes':>12} {'share':>7}",
        ]
        total = max(self.attributed_total(), 1)
        for cls in BUFFER_CLASSES:
            b = self.classes.get(cls, 0)
            lines.append(f"{cls:<16} {_fmt_bytes(b):>12} "
                         f"{100.0 * b / total:>6.1f}%")
        lines.append(f"top buffers ({min(top, len(self.buffers))} of "
                     f"{len(self.buffers)}):")
        for r in self.top_buffers(top):
            where = r.scope or r.name
            lines.append(f"  {_fmt_bytes(r.bytes):>12} {r.cls:<16} "
                         f"{r.kind:<8} {where[:60]}")
        mb = self.max_batch()
        if mb is not None:
            lines.append(f"forecast: max per-device batch ~{mb} before "
                         f"HBM capacity")
        return "\n".join(lines)

    def summary(self, top: int = 5) -> Dict[str, Any]:
        """JSON-able digest for crash dumps / the memory event channel
        — the piece :class:`apex_tpu.trace.FlightRecorder` embeds so an
        OOM dump names the biggest buffers."""
        return {
            "total_bytes": self.total_bytes,
            "attributed_bytes": self.attributed_total(),
            "peak_live_bytes": self.peak_live_bytes,
            "hbm_limit": self.hbm_limit,
            "batch_size": self.batch_size,
            "batch_bytes": self.batch_bytes,
            "classes": dict(self.classes),
            "top_buffers": [
                {"name": r.name, "bytes": r.bytes, "class": r.cls,
                 "scope": r.scope[:120]} for r in self.top_buffers(top)],
        }

    def to_event(self, rank: int = 0, step: Optional[int] = None) -> Dict:
        """``kind="memory_report"`` event for the memory channel
        (``check_metrics_schema.py --kind memory`` validates)."""
        ev: Dict[str, Any] = {"kind": "memory_report", "rank": rank,
                              "step": step}
        ev.update(self.summary())
        return ev


def memory_report(fn, *args, batch_size: Optional[int] = None,
                  **kwargs) -> MemoryReport:
    """Build a :class:`MemoryReport` for a step function.

    ``fn`` may be a plain callable (jitted here), a jitted function, or
    an **already-compiled** executable (``.lower(...).compile()`` —
    then pass no args and nothing compiles here). ``batch_size`` is the
    per-device batch dimension of the compiled step; buffers whose
    leading dim equals it are marked batch-scaled and drive
    :meth:`MemoryReport.forecast`. AOT-only: no dispatch ever runs.
    """
    if hasattr(fn, "memory_analysis") and hasattr(fn, "as_text"):
        compiled = fn
    else:
        compiled = _compile(fn, *args, **kwargs)
    stats = memory_stats_of(compiled)
    text = compiled.as_text()
    try:
        flops = float(cost_analysis_of(compiled).get("flops", 0.0))
    except Exception:
        flops = 0.0

    arg_records: List[BufferRecord] = []
    classes = {cls: 0 for cls in BUFFER_CLASSES}
    args_meta, instrs, _root = parse_entry(text)
    parsed_arg_bytes = 0
    for name, shape, path, _pnum in args_meta:
        nbytes = shape_bytes(shape)
        parsed_arg_bytes += nbytes
        cls = classify_arg_path(path or name)
        scaled = bool(batch_size and batch_size > 1
                      and _leading_dim(shape) == batch_size)
        arg_records.append(BufferRecord(
            name=name, kind="argument", bytes=nbytes, shape=shape,
            cls=cls, scope=path, batch_scaled=scaled))
        classes[cls] += nbytes
    # XLA may pad/align argument allocations: scale the parsed per-path
    # attribution to the memory_analysis argument total so the class sum
    # stays closed over the real allocation
    if parsed_arg_bytes and stats["argument"]:
        ratio = stats["argument"] / parsed_arg_bytes
        if abs(ratio - 1.0) > 1e-6:
            for r in arg_records:
                r.bytes = int(r.bytes * ratio)
            for cls in ("params", "optimizer_state", "inputs", "comm"):
                classes[cls] = int(classes[cls] * ratio)

    peak_temp, _peak_idx, at_peak, batch_peak, comm_peak = _liveness(
        instrs, batch_size)
    # temps: the memory_analysis temp+code total is authoritative; the
    # comm share is carved out from the scope-attributed liveness peak
    temp_total = stats["temp"] + stats["generated_code"]
    comm_bytes = min(comm_peak, temp_total)
    classes["comm"] += comm_bytes
    classes["activations"] += temp_total - comm_bytes
    classes["outputs"] += stats["output"]

    arg_bytes_scaled = sum(r.bytes for r in arg_records
                           if r.batch_scaled)
    peak_live = stats["argument"] + min(peak_temp, temp_total or peak_temp)

    dev = jax.devices()[0]
    return MemoryReport(
        stats=stats, classes=classes,
        buffers=arg_records + at_peak,
        peak_live_bytes=peak_live,
        batch_size=batch_size,
        batch_bytes=batch_peak + arg_bytes_scaled,
        hbm_limit=hbm_capacity(dev),
        device_kind=getattr(dev, "device_kind", "?"),
        flops=flops)
