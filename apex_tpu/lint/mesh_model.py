"""Declarative mesh/topology model for pod-scale static analysis.

A TPU pod is not a flat set of devices: within a slice, chips talk over
ICI (hundreds of GB/s per chip); across slices — and across pods — the
hop is DCN, more than an order of magnitude slower. The cross-rank lint
rules (:mod:`apex_tpu.lint.spmd_pass`) need exactly one fact per
collective to judge it: *which link class do these replica groups
span?* This module is that fact, stated declaratively:

- a :class:`MeshAxis` per mesh dimension, major-to-minor (the same
  row-major device layout ``jax.sharding.Mesh`` uses), each tagged with
  the link class collectives over it ride (``"ici"`` or ``"dcn"``);
- per-link byte budgets (bytes/s, and optionally bytes/step) so a
  finding can carry a time estimate next to its wire bytes;
- device-id → axis-coordinate arithmetic, slice identity (the
  coordinate tuple over the DCN axes), and replica-group hop
  classification.

This is the ``MeshPlan``-shaped table ROADMAP item 1 will consume: the
(dp, tp, pp, sp, zero) axes each become one :class:`MeshAxis` row, the
per-axis collective-scope registry
(:mod:`apex_tpu.parallel.registry`) names which subsystem communicates
over which row, and the topology rules stay unchanged.

Specs (the ``scripts/apexlint.py --mesh`` grammar):

- ``dp2x4`` — data parallelism factored (2 slices over DCN) x (4 chips
  over ICI): axes ``[("data_inter", 2, dcn), ("data_intra", 4, ici)]``.
  Generally ``dpAxB``.
- ``2slice`` — N slices over DCN, the local axis absorbing the
  remaining devices (size resolved against ``n_devices``). Generally
  ``Nslice``.
- ``ici8`` / ``iciN`` — one flat ICI axis (single-slice pod view).
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["MeshAxis", "MeshModel", "parse_mesh_spec", "LINK_CLASSES",
           "DEFAULT_LINK_BYTES_PER_S"]

#: link classes, fastest first; a group's hop class is the SLOWEST
#: link it spans
LINK_CLASSES = ("ici", "dcn")

#: default per-link bandwidth budgets (bytes/s): ICI is the v5e
#: per-chip class pod_comm_budget pins (~450 GB/s); DCN the
#: per-host-NIC class (~25 GB/s) — override per deployment.
DEFAULT_LINK_BYTES_PER_S = {"ici": 4.5e11, "dcn": 2.5e10}


@dataclasses.dataclass(frozen=True)
class MeshAxis:
    """One mesh dimension: name, size, and the link its hops ride."""

    name: str
    size: int
    link: str = "ici"

    def __post_init__(self):
        if self.link not in LINK_CLASSES:
            raise ValueError(f"axis {self.name!r}: link must be one of "
                             f"{LINK_CLASSES}, got {self.link!r}")
        if self.size < 1:
            raise ValueError(f"axis {self.name!r}: size must be >= 1, "
                             f"got {self.size}")


class MeshModel:
    """Axes + link budgets + the coordinate arithmetic the rules use.

    Device ids are laid out row-major over the axes, major-to-minor —
    identical to ``jax.sharding.Mesh(np.arange(n).reshape(sizes),
    names)``, so the flattened ids in compiled ``replica_groups=``
    (with ``use_global_device_ids=true``) index straight into this
    model.
    """

    def __init__(self, axes: Sequence[MeshAxis],
                 link_bytes_per_s: Optional[Dict[str, float]] = None,
                 budget_bytes_per_step: Optional[Dict[str, int]] = None,
                 name: Optional[str] = None,
                 calibration: Optional[Dict[str, Dict]] = None):
        axes = tuple(axes)
        if not axes:
            raise ValueError("a mesh model needs at least one axis")
        if len({a.name for a in axes}) != len(axes):
            raise ValueError("duplicate axis names")
        self.axes = axes
        self.link_bytes_per_s = dict(DEFAULT_LINK_BYTES_PER_S)
        self.link_bytes_per_s.update(link_bytes_per_s or {})
        #: optional per-link wire budget one step may spend (a lint
        #: consumer can gate on it; None = unbudgeted)
        self.budget_bytes_per_step = dict(budget_bytes_per_step or {})
        self.name = name
        #: measurement provenance when the byte budgets came from
        #: :mod:`apex_tpu.monitor.linkbench` rather than the defaults:
        #: ``{link: {"alpha_us", "bytes_per_s", "residual",
        #: "n_samples", "axis"}}`` — round-trips through JSON so a
        #: committed model states where its numbers came from
        self.calibration = dict(calibration or {})

    @property
    def measured(self) -> bool:
        """True when the link budgets carry calibration provenance."""
        return bool(self.calibration)

    # -- geometry -------------------------------------------------------------

    @property
    def n_devices(self) -> int:
        n = 1
        for a in self.axes:
            n *= a.size
        return n

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(a.name for a in self.axes)

    def axis(self, name: str) -> MeshAxis:
        for a in self.axes:
            if a.name == name:
                return a
        raise KeyError(name)

    def coords(self, device_id: int) -> Dict[str, int]:
        """Axis coordinates of a flattened device id (row-major,
        major-to-minor)."""
        if not 0 <= device_id < self.n_devices:
            raise ValueError(f"device id {device_id} outside mesh of "
                             f"{self.n_devices}")
        out: Dict[str, int] = {}
        rem = device_id
        for a in reversed(self.axes):
            out[a.name] = rem % a.size
            rem //= a.size
        return out

    def slice_id(self, device_id: int) -> Tuple[int, ...]:
        """Coordinate tuple over the DCN axes — devices sharing it live
        in the same slice (all-ICI reachable)."""
        c = self.coords(device_id)
        return tuple(c[a.name] for a in self.axes if a.link == "dcn")

    # -- replica-group classification -----------------------------------------

    def group_axes(self, group: Iterable[int]) -> List[str]:
        """Axis names along which a replica group's members vary."""
        members = list(group)
        if len(members) < 2:
            return []
        coords = [self.coords(m) for m in members]
        return [a.name for a in self.axes
                if len({c[a.name] for c in coords}) > 1]

    def group_hop(self, group: Iterable[int]) -> str:
        """The slowest link class a replica group spans: ``"dcn"`` when
        its members live in more than one slice, else ``"ici"``."""
        slices = {self.slice_id(m) for m in group}
        return "dcn" if len(slices) > 1 else "ici"

    def is_flat_dcn_group(self, group: Iterable[int]) -> bool:
        """True for a DCN-crossing group that ALSO has >1 member inside
        some slice — the flat one-hop shape. A hierarchical schedule
        reduces within-slice first, so its DCN-crossing group holds
        exactly one member per slice."""
        members = list(group)
        per_slice: Dict[Tuple[int, ...], int] = {}
        for m in members:
            s = self.slice_id(m)
            per_slice[s] = per_slice.get(s, 0) + 1
        return len(per_slice) > 1 and max(per_slice.values()) > 1

    def hop_seconds(self, nbytes: int, hop: str) -> float:
        """Wire time estimate for ``nbytes`` over a link class."""
        return nbytes / self.link_bytes_per_s[hop]

    # -- (de)serialization ----------------------------------------------------

    def to_json(self) -> Dict:
        """The declarative table: JSON round-trips so a deployment can
        commit its topology next to its bench baselines."""
        out = {
            "version": 1,
            "name": self.name,
            "axes": [dataclasses.asdict(a) for a in self.axes],
            "link_bytes_per_s": self.link_bytes_per_s,
            "budget_bytes_per_step": self.budget_bytes_per_step,
        }
        if self.calibration:
            out["calibration"] = self.calibration
        return out

    @classmethod
    def from_json(cls, data) -> "MeshModel":
        if isinstance(data, str):
            data = json.loads(data)
        if not isinstance(data, dict) or "axes" not in data:
            raise ValueError("not a mesh model "
                             '(expected {"version": 1, "axes": [...]})')
        axes = [MeshAxis(**a) for a in data["axes"]]
        return cls(axes,
                   link_bytes_per_s=data.get("link_bytes_per_s"),
                   budget_bytes_per_step=data.get(
                       "budget_bytes_per_step"),
                   name=data.get("name"),
                   calibration=data.get("calibration"))

    def __repr__(self) -> str:
        axes = " x ".join(f"{a.name}={a.size}({a.link})"
                          for a in self.axes)
        return f"MeshModel({axes})"


_DP_RE = re.compile(r"^dp(\d+)x(\d+)$")
_SLICE_RE = re.compile(r"^(\d+)slice$")
_ICI_RE = re.compile(r"^ici(\d+)$")


def parse_mesh_spec(spec: str,
                    n_devices: Optional[int] = None) -> MeshModel:
    """Build a :class:`MeshModel` from a compact spec string.

    ``dpAxB``: A slices over DCN x B chips over ICI (A*B devices) —
    ``dp2x4`` is the 8-device two-slice CPU-mesh audit topology.
    ``Nslice``: N slices over DCN, local ICI size =
    ``n_devices / N`` (requires ``n_devices``).
    ``iciN``: one flat N-chip ICI axis (single slice).
    A path to a ``.json`` file (or a raw JSON object string) loads the
    declarative table instead.
    """
    spec = spec.strip()
    if spec.startswith("{") or spec.endswith(".json"):
        if spec.endswith(".json"):
            with open(spec) as f:
                return MeshModel.from_json(json.load(f))
        return MeshModel.from_json(spec)
    m = _DP_RE.match(spec)
    if m:
        inter, intra = int(m.group(1)), int(m.group(2))
        if n_devices is not None and inter * intra != n_devices:
            raise ValueError(f"spec {spec!r} wants {inter * intra} "
                             f"devices, have {n_devices}")
        return MeshModel(
            (MeshAxis("data_inter", inter, "dcn"),
             MeshAxis("data_intra", intra, "ici")), name=spec)
    m = _SLICE_RE.match(spec)
    if m:
        n_slices = int(m.group(1))
        if n_devices is None:
            raise ValueError(f"spec {spec!r} needs n_devices to size "
                             "the local axis")
        if n_devices % n_slices:
            raise ValueError(f"{n_devices} devices not divisible into "
                             f"{n_slices} slices")
        return MeshModel(
            (MeshAxis("slice", n_slices, "dcn"),
             MeshAxis("data", n_devices // n_slices, "ici")),
            name=spec)
    m = _ICI_RE.match(spec)
    if m:
        n = int(m.group(1))
        if n_devices is not None and n != n_devices:
            raise ValueError(f"spec {spec!r} wants {n} devices, have "
                             f"{n_devices}")
        return MeshModel((MeshAxis("data", n, "ici"),), name=spec)
    raise ValueError(
        f"unknown mesh spec {spec!r} (want dpAxB | Nslice | iciN | "
        "a mesh-model .json)")
