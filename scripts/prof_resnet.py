"""Per-op profile of the ResNet-50 bench step (PERF.md methodology).

Usage: python scripts/prof_resnet.py [--unfused] [--batch N] [--top N]
Prints device time, bytes accessed, MFU, and the top fusions.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    fused = "--unfused" not in sys.argv
    batch = 256
    top = 25
    if "--batch" in sys.argv:
        batch = int(sys.argv[sys.argv.index("--batch") + 1])
    if "--top" in sys.argv:
        top = int(sys.argv[sys.argv.index("--top") + 1])

    from apex_tpu import amp, models, ops, prof
    from apex_tpu.optim import FusedSGD

    policy = amp.Policy.from_opt_level("O2")
    dx_dist = os.environ.get("APEX_TPU_DX_DISTRIBUTE") or None
    model = models.ResNet50(num_classes=1000, dtype=policy.compute_dtype,
                            fused_bn=fused, dx_distribute=dx_dist)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 224, 224, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    amp_opt = amp.Amp(policy, FusedSGD(lr=0.1, momentum=0.9))
    state = amp_opt.init(params)

    # APEX_TPU_REMAT: checkpoint policy over the whole forward — the
    # round-5 bytes-vs-FLOPs experiment (PERF.md round-5 ResNet section)
    remat = os.environ.get("APEX_TPU_REMAT")  # "nothing" | "dots"

    def apply_fn(variables, xb, **kw):
        if not remat:
            return model.apply(variables, xb, **kw)
        pol = {"nothing": jax.checkpoint_policies.nothing_saveable,
               "dots": jax.checkpoint_policies.checkpoint_dots}[remat]

        def inner(mp, bs, xb):
            return model.apply({"params": mp, "batch_stats": bs}, xb,
                               train=True, mutable=["batch_stats"])

        return jax.checkpoint(inner, policy=pol)(
            variables["params"], variables["batch_stats"], xb)

    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = apply_fn(
                {"params": mp, "batch_stats": batch_stats}, xb,
                train=True, mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            return loss, mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    import tempfile
    import time

    jstep = jax.jit(step, donate_argnums=(0, 1))
    from apex_tpu.prof import hlo as _hlo
    cost = _hlo.cost_analysis(jstep, state, batch_stats, x, y)
    for _ in range(3):
        state, batch_stats, loss = jstep(state, batch_stats, x, y)
    float(loss)

    iters = 5
    logdir = tempfile.mkdtemp(prefix="apex_tpu_prof_")
    t0 = time.perf_counter()
    with prof.trace(logdir):
        for _ in range(iters):
            state, batch_stats, loss = jstep(state, batch_stats, x, y)
        float(loss)
    wall = (time.perf_counter() - t0) / iters

    from apex_tpu.prof import xplane as _xplane
    profile = _xplane.parse_trace(logdir)
    dev_us = (profile.module_total_us / profile.module_runs
              if profile.module_runs else wall * 1e6)
    print(f"fused_bn={fused} batch={batch}")
    print(f"wall/iter={wall*1e6:.0f}us device/iter={dev_us:.0f}us "
          f"flops={cost['flops']:.3g} bytes={cost['bytes_accessed']:.3g}")
    cats = "  ".join(f"{k}={v:.0f}us"
                     for k, v in list(profile.by_category().items())[:8])
    print(cats)
    print(profile.table(top=top))
    peak = prof.device_peak_flops() or float("inf")
    print("MFU:", cost["flops"] / (dev_us * 1e-6) / peak)
    print("img/s:", batch / (dev_us * 1e-6))


if __name__ == "__main__":
    main()
