"""Bucketed & compressed gradient-sync numerics on the 8-device CPU mesh.

The contract tree:

- exact bucketed == plain per-tensor psum, bitwise (same additions in
  the same order — the reference's ``allreduce_bucket`` is arithmetic-
  transparent, `apex/parallel/distributed.py:425-475`);
- ``compress="bf16"`` matches the fp32 mean within masters tolerance,
  and its error-feedback residual is exactly the local cast error;
- ``compress="int8"`` with error feedback converges a short training
  trajectory to the exact-arithmetic optimum (the EF-SGD/1-bit-Adam
  argument: quantization error is re-injected, so it cannot accumulate
  as trajectory bias);
- the ZeRO ``grad_scatter_dtype`` wire compression stays within bf16
  tolerance of the fp32 scatter.

HLO-structure assertions (per-bucket all-reduces, wire bytes) live in
tests/test_pod_hlo.py; this file owns the values.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu import parallel
from apex_tpu.parallel import comm


def _shard_eval(mesh, fn, *args, in_specs=P("data"), out_specs=P()):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*args)


def _grad_tree(scale=1.0):
    rng = np.random.RandomState(0)
    return {"a": jnp.asarray(rng.randn(300, 7) * scale, jnp.float32),
            "b": jnp.asarray(rng.randn(513) * scale, jnp.float32),
            "c": jnp.asarray(rng.randn(40, 5) * scale, jnp.bfloat16),
            "n": jnp.arange(3)}


class TestBucketPlan:
    def test_reverse_parameter_order(self):
        leaves = [jax.ShapeDtypeStruct((100,), jnp.float32),
                  jax.ShapeDtypeStruct((100,), jnp.float32),
                  jax.ShapeDtypeStruct((100,), jnp.float32)]
        plan = comm.bucket_plan(leaves, 150)
        # bucket 0 must hold the LAST leaf (backward produces it first)
        assert plan[0].leaf_idx == (2,)
        assert plan[1].leaf_idx == (1,)
        assert plan[2].leaf_idx == (0,)

    def test_dtype_groups_and_caps(self):
        leaves = jax.tree_util.tree_leaves(_grad_tree())
        plan = comm.bucket_plan(leaves, 600)
        dts = {b.dtype for b in plan}
        assert dts == {"float32", "bfloat16"}
        # int leaf never lands in a bucket
        covered = [i for b in plan for i in b.leaf_idx]
        assert sorted(covered) == [0, 1, 2]     # a, b, c of the 4 leaves
        # multi-tensor buckets respect the cap at tensor granularity
        for b in plan:
            if len(b.leaf_idx) > 1:
                assert b.elems <= 600

    def test_single_bucket_when_uncapped(self):
        leaves = jax.tree_util.tree_leaves(_grad_tree())
        plan = comm.bucket_plan(leaves, None)
        per_dtype = {}
        for b in plan:
            per_dtype[b.dtype] = per_dtype.get(b.dtype, 0) + 1
        assert all(v == 1 for v in per_dtype.values())

    def test_wire_bytes_modes(self):
        leaves = [jax.ShapeDtypeStruct((1024,), jnp.float32)]
        plan = comm.bucket_plan(leaves, None)
        assert comm.wire_bytes(plan) == 4096
        assert comm.wire_bytes(plan, "bf16") == 2048
        # int8: payload + one f32 scale per 256-block
        assert comm.wire_bytes(plan, "int8") == 1024 + 4 * 4


class TestBucketedExact:
    def test_matches_plain_sync_bitwise(self, mesh8):
        tree = _grad_tree()

        def mk(bucketed):
            def step(x):
                shard = jax.lax.axis_index("data").astype(jnp.float32)
                g = {"a": tree["a"] * (shard + 1),
                     "b": tree["b"] * (shard + 1),
                     "c": tree["c"] * (shard + 1).astype(jnp.bfloat16),
                     "n": tree["n"]}
                if bucketed:
                    return comm.bucketed_all_reduce(g, "data",
                                                    message_size=600)
                return parallel.sync_gradients(g, "data")
            return step

        out_b = _shard_eval(mesh8, mk(True), jnp.zeros(8))
        out_p = _shard_eval(mesh8, mk(False), jnp.zeros(8))
        for k in ("a", "b", "c"):
            np.testing.assert_array_equal(
                np.asarray(out_b[k], np.float32),
                np.asarray(out_p[k], np.float32), err_msg=k)
        np.testing.assert_array_equal(out_b["n"], tree["n"])

    def test_arithmetic_knobs(self, mesh8):
        def step(x):
            g = {"w": x * jnp.ones((64,))}
            return comm.bucketed_all_reduce(
                g, "data", message_size=32,
                gradient_predivide_factor=8.0)["w"]

        out = _shard_eval(mesh8, step, jnp.arange(1.0, 9.0))
        np.testing.assert_allclose(np.asarray(out), 4.5, rtol=1e-6)

        def step_nosum(x):
            g = {"w": x * jnp.ones((64,))}
            return comm.bucketed_all_reduce(
                g, "data", gradient_average=False)["w"]

        out = _shard_eval(mesh8, step_nosum, jnp.ones(8))
        np.testing.assert_allclose(np.asarray(out), 8.0)

    def test_residual_passthrough_exact_mode(self, mesh8):
        def step(x):
            g = {"w": x * jnp.ones((64,))}
            r = comm.init_residual(g)
            out, r2 = comm.bucketed_all_reduce(g, "data", residual=r)
            return out["w"], r2["w"]

        out, r2 = _shard_eval(mesh8, step, jnp.arange(1.0, 9.0),
                              out_specs=(P(), P()))
        np.testing.assert_allclose(np.asarray(out), 4.5, rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(r2), 0.0)


class TestBf16Compress:
    def test_close_to_exact_mean(self, mesh8):
        tree = _grad_tree()

        def step(x):
            shard = jax.lax.axis_index("data").astype(jnp.float32)
            g = {"a": tree["a"] * (shard + 1), "b": tree["b"]}
            return comm.bucketed_all_reduce(g, "data", message_size=600,
                                            compress="bf16")

        out = _shard_eval(mesh8, step, jnp.zeros(8))
        np.testing.assert_allclose(np.asarray(out["a"]),
                                   np.asarray(tree["a"]) * 4.5,
                                   rtol=3e-2, atol=3e-2)

    def test_residual_is_local_cast_error(self, mesh8):
        tree = {"a": _grad_tree()["a"]}

        def step(x):
            r = comm.init_residual(tree)
            out, r2 = comm.bucketed_all_reduce(tree, "data",
                                               compress="bf16",
                                               residual=r)
            return out["a"], r2["a"]

        _, r2 = _shard_eval(mesh8, step, jnp.zeros(8),
                            out_specs=(P(), P()))
        a = np.asarray(tree["a"], np.float32)
        exp = a - np.asarray(np.asarray(tree["a"]).astype(jnp.bfloat16),
                             np.float32)
        np.testing.assert_allclose(np.asarray(r2), exp, atol=1e-7)

    def test_error_feedback_kills_rounding_bias(self, mesh8):
        """A constant gradient that bf16 consistently rounds away: the
        stateless compressed mean is biased every step, while error
        feedback re-injects the residual so the *time-averaged* synced
        gradient converges to the true value."""
        g_val = 1.0 + 1.0 / 512.0       # not representable in bf16

        def step(r):
            g = {"w": jnp.full((256,), g_val, jnp.float32)}
            out, r2 = comm.bucketed_all_reduce(
                g, "data", compress="bf16", residual={"w": r[0]})
            return out["w"], r2["w"][None]

        mapped = jax.jit(jax.shard_map(
            step, mesh=mesh8, in_specs=(P("data"),),
            out_specs=(P(), P("data")), check_vma=False))

        r = jnp.zeros((8, 256), jnp.float32)
        total_ef = np.zeros(256, np.float64)
        steps = 8
        for _ in range(steps):
            out, r = mapped(r)
            total_ef += np.asarray(out, np.float64)
        err_ef = abs(float(total_ef[0]) / steps - g_val)

        # stateless twin: bias = the cast error, every step
        def step_plain(x):
            g = {"w": jnp.full((256,), g_val, jnp.float32)}
            return comm.bucketed_all_reduce(g, "data",
                                            compress="bf16")["w"]
        out_p = _shard_eval(mesh8, step_plain, jnp.zeros(8))
        err_plain = abs(float(np.asarray(out_p)[0]) - g_val)

        assert err_plain > 1e-3, "test value was bf16-representable"
        assert err_ef < err_plain / 4, (err_ef, err_plain)


class TestInt8Compress:
    def test_quantizer_roundtrip_bound(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(4096) * 5.0, jnp.float32)
        q, s = comm._quantize_int8(x, 256)
        back = comm._dequantize_int8(q, s, 256)
        err = np.abs(np.asarray(back) - np.asarray(x))
        bound = np.repeat(np.asarray(s), 256) / 2 + 1e-7
        assert np.all(err <= bound)

    def test_allreduce_close_to_exact(self, mesh8):
        tree = {"a": _grad_tree()["a"]}

        def step(x):
            shard = jax.lax.axis_index("data").astype(jnp.float32)
            g = {"a": tree["a"] * (shard + 1)}
            return comm.bucketed_all_reduce(g, "data", compress="int8")

        out = _shard_eval(mesh8, step, jnp.zeros(8))
        ref = np.asarray(tree["a"]) * 4.5
        np.testing.assert_allclose(np.asarray(out["a"]), ref,
                                   rtol=5e-2, atol=5e-2)

    def test_error_feedback_trajectory_converges(self, mesh8):
        """Short data-parallel GD on a quadratic: per-device loss
        0.5‖w − t_d‖², exact mean gradient drives w → mean(t). The
        int8+EF trajectory must land where the exact one does, and
        strictly closer than the stateless-int8 trajectory."""
        dim, lr, steps = 512, 0.4, 30
        rng = np.random.RandomState(7)
        targets = jnp.asarray(rng.randn(8, dim) * 3.0, jnp.float32)
        t_mean = np.mean(np.asarray(targets), axis=0)

        def mk(compress, ef):
            def step(w, r, t):
                g = {"w": w - t[0]}
                if ef:
                    out, r2 = comm.bucketed_all_reduce(
                        g, "data", compress=compress,
                        residual={"w": r[0]})
                    return w - lr * out["w"], r2["w"][None]
                out = comm.bucketed_all_reduce(g, "data",
                                               compress=compress)
                return w - lr * out["w"], r
            return jax.jit(jax.shard_map(
                step, mesh=mesh8,
                in_specs=(P(), P("data"), P("data")),
                out_specs=(P(), P("data")), check_vma=False))

        def run(compress, ef):
            w = jnp.zeros((dim,), jnp.float32)
            r = jnp.zeros((8, dim), jnp.float32)
            f = mk(compress, ef)
            for _ in range(steps):
                w, r = f(w, r, targets)
            return np.asarray(w)

        w_exact = run(None, False)
        w_ef = run("int8", True)

        d_exact = float(np.linalg.norm(w_exact - t_mean))
        d_ef = float(np.linalg.norm(w_ef - t_mean))
        scale = float(np.linalg.norm(t_mean))
        assert d_exact < 1e-3 * scale
        # the EF trajectory lands at the exact optimum despite every
        # gradient having crossed the wire as int8
        assert d_ef < 0.02 * scale, (d_ef, scale)

    def test_rejects_axis_tuple(self):
        with pytest.raises(NotImplementedError):
            comm.bucketed_all_reduce({"w": jnp.ones(4)},
                                     ("a", "b"), compress="int8")


class TestQuantizerBlocks:
    def test_pad_and_mask_non_divisible_length(self):
        """Lengths not divisible by compress_block: the quantizer pads
        with zeros to the next block boundary and the pad is EXACTLY
        invisible — same payload as quantizing the manually padded
        buffer, zero error on the pad, and dequantize(n=) masks it."""
        rng = np.random.RandomState(11)
        n, block = 1000, 256
        x = jnp.asarray(rng.randn(n) * 3.0, jnp.float32)
        q, s = comm._quantize_int8(x, block)
        assert q.shape[0] == 1024 and s.shape[0] == 4
        xp = jnp.pad(x, (0, 1024 - n))
        q_ref, s_ref = comm._quantize_int8(xp, block)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(q_ref))
        np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))
        back = comm._dequantize_int8(q, s, block, n=n)
        assert back.shape[0] == n
        # pad region dequantizes to exact zeros (never perturbs scales)
        full = comm._dequantize_int8(q, s, block)
        np.testing.assert_array_equal(np.asarray(full[n:]), 0.0)
        err = np.abs(np.asarray(back) - np.asarray(x))
        bound = np.repeat(np.asarray(s), block)[:n] / 2 + 1e-7
        assert np.all(err <= bound)

    def test_divisible_length_unchanged(self):
        x = jnp.asarray(np.random.RandomState(3).randn(512), jnp.float32)
        q, s = comm._quantize_int8(x, 256)
        assert q.shape[0] == 512 and s.shape[0] == 2
        assert comm._dequantize_int8(q, s, 256).shape[0] == 512


def _dp2x4(calibration=None):
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    mm = parse_mesh_spec("dp2x4")
    if calibration:
        mm.calibration.update(calibration)
    return mm


class TestCommPlan:
    def test_defaults_plan_is_int8_hierarchical(self):
        from apex_tpu.parallel import hierarchy
        plan = hierarchy.plan_comm(_dp2x4(), grad_bytes=100 * 2 ** 20)
        assert plan.is_hierarchical and plan.source == "defaults"
        ops = [(h.op, h.link, h.dtype) for h in plan.hops]
        assert ops == [("reduce_scatter", "ici", "int8"),
                       ("all_reduce", "dcn", "int8"),
                       ("all_gather", "ici", "int8")]
        assert plan.dtype_by_link() == {"ici": "int8", "dcn": "int8"}
        assert plan.world == 8
        assert plan.axis_names == ("data_intra", "data_inter")

    def test_measured_model_changes_the_plan(self):
        """The acceptance-criteria unit: a measured (calibrated) model
        derives a DIFFERENT plan than the defaults. int8's two-phase
        DCN decomposition pays 4 per-collective latencies vs bf16's
        one, so a latency-dominated measured DCN link (large α) keeps
        the DCN hop at bf16 — and the provenance is recorded."""
        from apex_tpu.parallel import hierarchy
        nbytes = 100 * 2 ** 20
        cal = {"dcn": {"alpha_us": 2000.0, "bytes_per_s": 2.5e10,
                       "residual": 0.0, "n_samples": 8,
                       "axis": "data_inter"}}
        measured = hierarchy.plan_comm(_dp2x4(cal), grad_bytes=nbytes)
        default = hierarchy.plan_comm(_dp2x4(), grad_bytes=nbytes)
        assert measured.source == "measured"
        assert default.source == "defaults"
        assert measured.inter.dtype == "bf16"
        assert default.inter.dtype == "int8"
        assert measured.inter.calibrated
        assert not default.inter.calibrated
        assert measured.inter.alpha_us == 2000.0

    def test_plan_reproducible(self):
        from apex_tpu.parallel import hierarchy
        a = hierarchy.plan_comm(_dp2x4(), grad_bytes=1 << 20)
        b = hierarchy.plan_comm(_dp2x4(), grad_bytes=1 << 20)
        assert a == b

    def test_flat_plan_for_single_slice_model(self):
        from apex_tpu.lint.mesh_model import parse_mesh_spec
        from apex_tpu.parallel import hierarchy
        plan = hierarchy.plan_comm(parse_mesh_spec("ici8"),
                                   grad_bytes=1 << 20)
        assert not plan.is_hierarchical
        assert plan.hops[0].op == "all_reduce"
        assert plan.world == 8

    def test_wire_bytes_mixed_hops(self):
        """comm.wire_bytes with a CommPlan accounts the per-hop dtype
        mix — the all-reduce-equivalent ratio sits near the int8
        hierarchical prediction, NOT the single-mode int8 figure."""
        from apex_tpu.parallel import hierarchy
        plan = hierarchy.plan_comm(_dp2x4(), grad_bytes=1 << 22)
        leaves = [jax.ShapeDtypeStruct((1 << 20,), jnp.float32)]
        bplan = comm.bucket_plan(leaves, None)
        flat = comm.wire_bytes(bplan, None)
        hier = comm.wire_bytes(bplan, plan)
        assert 0.15 < hier / flat < 0.35, hier / flat
        # bucket_table grows the wire column without error
        table = comm.bucket_table(bplan, plan)
        assert "wire MiB" in table

    def test_predicted_seconds_cover_both_links(self):
        from apex_tpu.parallel import hierarchy
        plan = hierarchy.plan_comm(_dp2x4(), grad_bytes=100 * 2 ** 20)
        pred = plan.predicted_seconds()
        assert set(pred) == {"ici", "dcn"} and all(
            v > 0 for v in pred.values())
        js = plan.to_json()
        assert js["source"] == "defaults" and len(js["hops"]) == 3


AX2 = ("data_inter", "data_intra")


class TestHierarchicalSync:
    def _plan(self, **kw):
        from apex_tpu.parallel import hierarchy
        return hierarchy.plan_comm(_dp2x4(), grad_bytes=1 << 20, **kw)

    def test_close_to_exact_mean(self, mesh2x4):
        from apex_tpu.parallel import hierarchy
        tree = _grad_tree()
        plan = self._plan()

        def step(x):
            s = (jax.lax.axis_index("data_inter") * 4
                 + jax.lax.axis_index("data_intra")).astype(jnp.float32)
            g = {"a": tree["a"] * (s + 1), "b": tree["b"],
                 "n": tree["n"]}
            return hierarchy.hierarchical_sync(g, plan,
                                               message_size=600)

        out = jax.shard_map(step, mesh=mesh2x4, in_specs=(P(AX2),),
                            out_specs=P(), check_vma=False)(
            jnp.zeros(8))
        ref = np.asarray(tree["a"]) * 4.5
        np.testing.assert_allclose(np.asarray(out["a"]), ref,
                                   rtol=5e-2, atol=5e-2)
        np.testing.assert_array_equal(out["n"], tree["n"])

    def test_ef_trajectory_converges_to_fp32(self, mesh2x4):
        """The acceptance trajectory: data-parallel GD on a quadratic
        over the 2-slice x 4-chip mesh, every gradient crossing both
        hops as int8 with error feedback, lands within tolerance of
        the fp32 (exact sync) optimum."""
        from apex_tpu.parallel import hierarchy
        dim, lr, steps = 512, 0.4, 30
        rng = np.random.RandomState(7)
        targets = jnp.asarray(rng.randn(8, dim) * 3.0, jnp.float32)
        t_mean = np.mean(np.asarray(targets), axis=0)
        plan = hierarchy.plan_comm(_dp2x4(), grad_bytes=dim * 4,
                                   compress_block=64)

        def mk(hier):
            def step(w, r, t):
                g = {"w": w - t[0]}
                if hier:
                    out, r2 = hierarchy.hierarchical_sync(
                        g, plan, residual={"w": r[0]})
                    return w - lr * out["w"], r2["w"][None]
                out = parallel.sync_gradients(g, AX2)
                return w - lr * out["w"], r
            return jax.jit(jax.shard_map(
                step, mesh=mesh2x4,
                in_specs=(P(), P(AX2), P(AX2)),
                out_specs=(P(), P(AX2)), check_vma=False))

        def run(hier):
            w = jnp.zeros((dim,), jnp.float32)
            r = jnp.zeros((8, dim), jnp.float32)
            f = mk(hier)
            for _ in range(steps):
                w, r = f(w, r, targets)
            return np.asarray(w)

        w_exact = run(False)
        w_ef = run(True)
        scale = float(np.linalg.norm(t_mean))
        assert np.linalg.norm(w_exact - t_mean) < 1e-3 * scale
        assert np.linalg.norm(w_ef - t_mean) < 0.02 * scale

    def test_bf16_dcn_hop_variant(self, mesh2x4):
        """The measured-model plan shape (int8 ICI / bf16 DCN) also
        sums correctly."""
        from apex_tpu.parallel import hierarchy
        cal = {"dcn": {"alpha_us": 2000.0, "bytes_per_s": 2.5e10,
                       "residual": 0.0, "n_samples": 8,
                       "axis": "data_inter"}}
        # 100 MiB payload: the wire term is big enough that bf16's
        # halving beats α, but int8's 4-collective α cost is not
        plan = hierarchy.plan_comm(_dp2x4(cal), grad_bytes=100 * 2 ** 20)
        assert plan.inter.dtype == "bf16"
        tree = {"a": _grad_tree()["a"]}

        def step(x):
            s = (jax.lax.axis_index("data_inter") * 4
                 + jax.lax.axis_index("data_intra")).astype(jnp.float32)
            g = {"a": tree["a"] * (s + 1)}
            return hierarchy.hierarchical_sync(g, plan)

        out = jax.shard_map(step, mesh=mesh2x4, in_specs=(P(AX2),),
                            out_specs=P(), check_vma=False)(
            jnp.zeros(8))
        ref = np.asarray(tree["a"]) * 4.5
        np.testing.assert_allclose(np.asarray(out["a"]), ref,
                                   rtol=5e-2, atol=5e-2)

    def test_ddp_comm_plan_wiring(self, mesh2x4):
        ddp = parallel.DistributedDataParallel(
            mesh2x4, comm_plan=self._plan())
        assert ddp.world_size == 8
        assert set(ddp.axis_name) == {"data_inter", "data_intra"}
        vals = jnp.linspace(0.1, 1.7, 640, dtype=jnp.float32)

        def step(x):
            g = {"w": vals}              # identical on every device
            r = ddp.init_residual(g)
            out, r2 = ddp.sync(g, residual=r)
            return out["w"], r2["w"]

        out, r2 = jax.shard_map(
            step, mesh=mesh2x4, in_specs=(P(AX2),),
            out_specs=(P(), P()), check_vma=False)(jnp.zeros(8))
        np.testing.assert_allclose(np.asarray(out), np.asarray(vals),
                                   rtol=2e-2, atol=2e-2)
        assert r2.shape == (640,)

    def test_ddp_comm_plan_validation(self, mesh8, mesh2x4):
        plan = self._plan()
        with pytest.raises(ValueError):       # axes not in the mesh
            parallel.DistributedDataParallel(mesh8, comm_plan=plan)
        with pytest.raises(ValueError):       # does not compose
            parallel.DistributedDataParallel(
                mesh2x4, comm_plan=plan, compress="bf16")
        with pytest.raises(ValueError):
            parallel.DistributedDataParallel(
                mesh2x4, comm_plan=plan, delay_allreduce=True)

    def test_hierarchical_pmean_matches_flat(self, mesh2x4):
        from apex_tpu.parallel import hierarchy
        plan = self._plan()

        def step(x):
            return (hierarchy.hierarchical_pmean(x[0], plan),
                    jax.lax.pmean(x[0], AX2))

        h, f = jax.shard_map(step, mesh=mesh2x4, in_specs=(P(AX2),),
                             out_specs=(P(), P()), check_vma=False)(
            jnp.arange(1.0, 9.0))
        np.testing.assert_allclose(float(h), float(f), rtol=1e-6)


class TestDDPWiring:
    def test_sync_bucketed_matches_default(self, mesh8):
        ddp_b = parallel.DistributedDataParallel(
            mesh8, bucket_allreduce=True, message_size=600)
        ddp_d = parallel.DistributedDataParallel(mesh8)
        tree = _grad_tree()

        def mk(ddp):
            def step(x):
                shard = jax.lax.axis_index("data").astype(jnp.float32)
                g = {"a": tree["a"] * (shard + 1), "b": tree["b"],
                     "n": tree["n"]}
                return ddp.sync(g)
            return step

        out_b = _shard_eval(mesh8, mk(ddp_b), jnp.zeros(8))
        out_d = _shard_eval(mesh8, mk(ddp_d), jnp.zeros(8))
        # 1-ulp slack: the default path's combined variadic all-reduce
        # may pick a different reduction schedule than per-bucket psums
        for k in ("a", "b"):
            np.testing.assert_allclose(np.asarray(out_b[k]),
                                       np.asarray(out_d[k]),
                                       rtol=1e-6, err_msg=k)
        np.testing.assert_array_equal(np.asarray(out_b["n"]),
                                      np.asarray(out_d["n"]))

    def test_sync_residual_roundtrip(self, mesh8):
        ddp = parallel.DistributedDataParallel(mesh8, compress="bf16")
        vals = jnp.linspace(0.1, 1.7, 128, dtype=jnp.float32)

        def step(x):
            g = {"w": vals}              # identical on every device
            r = ddp.init_residual(g)
            out, r2 = ddp.sync(g, residual=r)
            return out["w"], r2["w"]

        out, r2 = _shard_eval(mesh8, step, jnp.arange(1.0, 9.0),
                              out_specs=(P(), P()))
        np.testing.assert_allclose(np.asarray(out), np.asarray(vals),
                                   rtol=1e-2, atol=1e-2)
        assert r2.shape == (128,)

    def test_no_sync_passes_residual_through(self, mesh8):
        ddp = parallel.DistributedDataParallel(mesh8, compress="bf16")

        def step(x):
            g = {"w": x * jnp.ones((16,))}
            r = ddp.init_residual(g)
            out, r2 = ddp.sync(g, residual=r)
            return out["w"], r2["w"]

        with ddp.no_sync():
            out, r2 = _shard_eval(mesh8, step, jnp.arange(8.0),
                                  out_specs=(P("data"), P("data")))
        # untouched grads, untouched residual — per-device rows concat
        np.testing.assert_allclose(
            np.asarray(out).reshape(8, 16)[:, 0], np.arange(8.0))
        np.testing.assert_array_equal(np.asarray(r2), 0.0)

    def test_mode_validation(self, mesh8):
        with pytest.raises(ValueError):
            parallel.DistributedDataParallel(mesh8, compress="fp4")
        with pytest.raises(ValueError):
            parallel.DistributedDataParallel(
                mesh8, compress="bf16", allreduce_always_fp32=True)
        with pytest.raises(ValueError):
            parallel.DistributedDataParallel(
                mesh8, bucket_allreduce=True, delay_allreduce=True)


class TestZeROScatterDtype:
    def test_bf16_scatter_close_to_fp32(self, mesh8):
        from apex_tpu.optim import DistributedFusedAdam

        rng = np.random.RandomState(5)
        params = {"w": jnp.asarray(rng.randn(4096) / 10, jnp.float32)}

        def mk(wire):
            opt = DistributedFusedAdam(lr=1e-2, axis_name="data",
                                       grad_scatter_dtype=wire)

            def prog(params, xb):
                opt_state = opt.init(params)

                def loss_fn(p):
                    return jnp.mean(jnp.square(p["w"])) * jnp.mean(xb)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                new_p, _ = opt.step(grads, opt_state, params)
                return new_p["w"], jax.lax.pmean(loss, "data")
            return prog

        x = jnp.ones((8, 4))
        w32, _ = _shard_eval(mesh8, mk(None), params, x,
                             in_specs=(P(), P("data")),
                             out_specs=(P(), P()))
        wbf, _ = _shard_eval(mesh8, mk(jnp.bfloat16), params, x,
                             in_specs=(P(), P("data")),
                             out_specs=(P(), P()))
        assert wbf.dtype == jnp.float32     # masters stay fp32
        np.testing.assert_allclose(np.asarray(wbf), np.asarray(w32),
                                   rtol=1e-2, atol=1e-4)
        assert float(np.max(np.abs(np.asarray(wbf)
                                   - np.asarray(params["w"])))) > 0
