"""``python -m apex_tpu.ops`` — on-device kernel compile validation.

Compiles and runs every Pallas kernel family on the attached accelerator
and checks outputs against oracles. See ops/compile_check.py.
"""

import sys

from apex_tpu.ops.compile_check import main

sys.exit(main())
