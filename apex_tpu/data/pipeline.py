"""Threaded ImageFolder input pipeline + device prefetch.

Rebuild of the reference's input machinery for `examples/imagenet`:
torch DataLoader / DALI decode+augment (`main_amp.py:28-57`) feeding the
CUDA-stream `data_prefetcher` (`main_amp.py:264-317`). The TPU design:

- **Decode/augment workers**: a thread pool decodes JPEGs with PIL
  (libjpeg releases the GIL inside the C decoder, so threads scale to
  the host's cores without torch's worker *processes*) and applies the
  standard train transform — RandomResizedCrop(scale 0.08-1.0, ratio
  3/4-4/3) + horizontal flip — in numpy.
- **Batch assembly** into one contiguous NHWC float32 (or pre-cast
  half) array per batch: a single host buffer per transfer, the
  "pinned staging buffer" role.
- **Device prefetch**: :class:`DevicePrefetcher` keeps ``depth``
  batches device_put ahead of the training loop; with JAX's async
  dispatch this is the whole stream-overlap machinery.

No tf.data/grain in the image; PIL is the decode engine (the same
libjpeg-turbo DALI wraps). Measured honestly: `measure_source` reports
loader-only throughput so input-bound configs are visible
(BENCH_TABLE.md notes) instead of silently capping training numbers.
"""

from __future__ import annotations

import concurrent.futures
import os
import queue
import threading
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")

#: bounded retries for transient decode-path IO (shared-fs blips,
#: networked storage hiccups) — override with APEX_TPU_DATA_IO_RETRIES
_IO_ATTEMPTS = max(int(os.environ.get("APEX_TPU_DATA_IO_RETRIES", "3")), 1)


def _read_rgb_with_retry(path: str, attempts: int = None):
    """Open + fully decode one image, retrying transient ``OSError``s
    with jittered backoff. A loader thread that dies on one NFS blip
    kills the whole batch future; a file that stays unreadable after
    ``attempts`` tries raises with the path and attempt count named —
    the poisoned-input case is then the guard's problem, not a hang."""
    from PIL import Image

    attempts = _IO_ATTEMPTS if attempts is None else max(int(attempts), 1)
    last = None
    for k in range(attempts):
        try:
            with Image.open(path) as img:
                return img.convert("RGB")   # convert forces the decode
        except OSError as e:
            last = e
            if k + 1 < attempts:
                from apex_tpu.utils.backoff import backoff_sleep
                backoff_sleep(k, base_s=0.05, cap_s=0.5)
    raise OSError(f"failed to read image {path!r} after {attempts} "
                  f"attempts: {last}") from last


def _list_imagefolder(root: str):
    """(paths, labels, class_names) for a torchvision-ImageFolder-style
    tree: root/<class>/<image>."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    paths, labels = [], []
    for i, c in enumerate(classes):
        cdir = os.path.join(root, c)
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith(IMG_EXTS):
                paths.append(os.path.join(cdir, f))
                labels.append(i)
    if not paths:
        raise FileNotFoundError(f"no images under {root!r}")
    return paths, np.asarray(labels, np.int32), classes


def _random_resized_crop(img, size: int, rng: np.random.RandomState,
                         scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """torchvision RandomResizedCrop semantics (the reference's train
    transform, `main_amp.py:230-236`), on a PIL image."""
    from PIL import Image

    w, h = img.size
    area = w * h
    for _ in range(10):
        target = area * rng.uniform(*scale)
        log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
        ar = np.exp(log_r)
        cw = int(round(np.sqrt(target * ar)))
        ch = int(round(np.sqrt(target / ar)))
        if 0 < cw <= w and 0 < ch <= h:
            x0 = rng.randint(0, w - cw + 1)
            y0 = rng.randint(0, h - ch + 1)
            box = (x0, y0, x0 + cw, y0 + ch)
            break
    else:  # fallback: center crop of the short side
        s = min(w, h)
        x0, y0 = (w - s) // 2, (h - s) // 2
        box = (x0, y0, x0 + s, y0 + s)
    return img.resize((size, size), Image.BILINEAR, box=box)


def _stable_seed(seed: int, epoch: int, pos: int) -> int:
    """Per-image augmentation seed as a pure function of
    ``(source seed, epoch, position in the epoch's order)`` — a
    splitmix-style avalanche instead of a sequentially-consumed
    RandomState, so resuming an epoch at batch k reproduces the exact
    augmentation stream without replaying the first k batches (the
    checkpoint cursor contract, docs/checkpointing.md)."""
    x = (seed * 0x9E3779B9 + epoch * 0x85EBCA6B + pos * 0xC2B2AE35
         + 0x27D4EB2F) & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    x ^= x >> 16
    return x & 0x7FFFFFFF


def _decode_one(path: str, size: int, seed: int, train: bool):
    from PIL import Image

    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    img = _read_rgb_with_retry(path)
    if train:
        img = _random_resized_crop(img, size, rng)
    else:
        s = min(img.size)
        w, h = img.size
        img = img.resize((size, size), Image.BILINEAR,
                         box=((w - s) // 2, (h - s) // 2,
                              (w + s) // 2, (h + s) // 2))
    arr = np.asarray(img, np.uint8)
    if train and rng.rand() < 0.5:
        arr = arr[:, ::-1]
    return arr


class ImageFolderSource:
    """Batched (x, y) iterator over an ImageFolder tree.

    A thread pool decodes/augments ``workers`` images concurrently (PIL
    drops the GIL in libjpeg); batches come out as one contiguous NHWC
    array scaled to [0, 1) in ``dtype``. Iteration order reshuffles per
    epoch like the reference's ``shuffle=True`` loader.

    **Multi-host**: pass ``process_index``/``process_count`` (defaults:
    the JAX process topology) and each rank reads a *disjoint* strided
    slice of the sorted file list — ranks never open overlapping files,
    so N hosts divide the decode work instead of duplicating it
    (ROADMAP item 5b).

    **Resumable**: :meth:`state` returns the ``(epoch, shard, batch)``
    cursor — capture it in the checkpoint tuple
    (``CheckpointManager.save(..., extra={"cursor": src.state()})``)
    and :meth:`load_state` resumes the stream at exactly the next
    batch: the epoch order is a pure function of ``seed + epoch`` and
    per-image augmentation seeds are position-derived
    (:func:`_stable_seed`), so nothing depends on consumed RNG state.
    """

    def __init__(self, root: str, batch: int, size: int = 224, *,
                 workers: Optional[int] = None, train: bool = True,
                 seed: int = 0, dtype=np.float32,
                 drop_last: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        self.paths, self.labels, self.classes = _list_imagefolder(root)
        # each field independently falls back to the JAX topology: a
        # caller passing only process_count must still land on ITS OWN
        # rank's shard, not silently on shard 0 everywhere
        if process_count is None:
            try:
                import jax
                process_count = jax.process_count()
            except Exception:
                process_count = 1
        if process_index is None:
            try:
                import jax
                process_index = jax.process_index()
            except Exception:
                process_index = 0
        self.process_count = max(int(process_count), 1)
        self.process_index = int(process_index)
        if not (0 <= self.process_index < self.process_count):
            raise ValueError(f"process_index {self.process_index} out of "
                             f"range for process_count "
                             f"{self.process_count}")
        if self.process_count > 1:
            # strided file-shard assignment over the (sorted) global
            # list, EQUALIZED to exactly floor(N/world) files per rank:
            # disjoint by construction, and every rank yields the same
            # number of batches per epoch — ranks driving one lockstep
            # collective step per batch stay synchronized at the epoch
            # tail (the ≤world-1 remainder files are dropped, the
            # drop_last convention applied across ranks)
            per = len(self.paths) // self.process_count
            if per == 0:
                raise ValueError(
                    f"rank {self.process_index}/{self.process_count} "
                    f"got an empty file shard — fewer files than ranks")
            sl = slice(self.process_index, per * self.process_count,
                       self.process_count)
            self.paths = self.paths[sl]
            self.labels = self.labels[sl]
        self.batch = batch
        self.size = size
        self.train = train
        self.seed = seed
        self.dtype = dtype
        self.drop_last = drop_last
        self.workers = workers or min(16, (os.cpu_count() or 1))
        self._pool = concurrent.futures.ThreadPoolExecutor(self.workers)
        self._epoch = 0
        self._batch = 0            # next batch index within the epoch

    def __len__(self):
        n = len(self.paths) // self.batch
        if not self.drop_last and len(self.paths) % self.batch:
            n += 1
        return n

    def close(self) -> None:
        """Shut the decode pool down (idempotent). Sources used for a
        one-off probe should be closed so their worker threads don't
        outlive the measurement."""
        self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- the resumable cursor ------------------------------------------------

    def state(self) -> dict:
        """The ``(epoch, batch)`` cursor of the NEXT batch this source
        will yield, plus the shard identity — everything a checkpoint
        needs to resume the stream exactly (host ints only; JSON-safe).
        """
        return {"epoch": int(self._epoch), "batch": int(self._batch),
                "shard": int(self.process_index),
                "n_shards": int(self.process_count),
                "seed": int(self.seed), "n_files": len(self.paths),
                "batch_size": int(self.batch),
                "drop_last": bool(self.drop_last)}

    def load_state(self, cursor: dict) -> "ImageFolderSource":
        """Resume from a :meth:`state` cursor. Refuses a cursor from a
        different file shard, a changed file set, or a different batch
        geometry (batch size / drop_last shift where batch index k
        starts) — silently resuming a mismatched stream would double-
        or skip-read data."""
        for key, have in (("shard", self.process_index),
                          ("n_shards", self.process_count),
                          ("seed", self.seed),
                          ("n_files", len(self.paths)),
                          ("batch_size", self.batch),
                          ("drop_last", self.drop_last)):
            want = cursor.get(key, have)
            if int(want) != int(have):
                raise ValueError(
                    f"data cursor mismatch: checkpoint has {key}="
                    f"{want}, this source has {have} — rebuild the "
                    f"source with the same seed and shard assignment "
                    f"(or the dataset changed under the checkpoint)")
        self._epoch = int(cursor["epoch"])
        self._batch = int(cursor["batch"])
        return self

    def cursor_index(self) -> int:
        """Linear batch index of the cursor: ``epoch · batches_per_epoch
        + batch`` — the total batches this source has yielded (or
        skipped) since construction. The coordinate
        :meth:`apex_tpu.guard.GuardPolicy.rewind` differences to size
        the offending window. A cursor captured right after an epoch's
        last batch (the transient ``batch == batches_per_epoch`` state,
        before the generator's epilogue wraps it) maps to the same
        index as the next epoch's batch 0 — the two states are the same
        stream position."""
        return self._epoch * len(self) + self._batch

    def skip_batches(self, n: int) -> "ImageFolderSource":
        """Advance the cursor ``n`` batches WITHOUT decoding anything —
        the guard's poison-batch fast-forward: after a rewind restores
        the checkpoint cursor, skipping the offending window costs zero
        image reads, and the stream continues exactly where a run that
        never saw those batches would be (epoch order and per-image
        augmentation seeds are pure functions of the cursor, so the
        downstream stream is bitwise-identical). Crosses epoch
        boundaries. Call between batches — a live :meth:`epoch`
        generator does not see cursor mutations; rebuild iteration
        after calling this (as after :meth:`load_state`)."""
        per = len(self)
        if per == 0:
            raise ValueError("cannot skip batches on a source that "
                             "yields none (fewer files than batch size)")
        n = int(n)
        if n < 0:
            raise ValueError(f"skip_batches needs n >= 0, got {n}")
        # linear-index arithmetic, NOT increment-then-wrap: a cursor
        # loaded from the post-epoch transient (batch == per, captured
        # right after an epoch's last yielded batch) aliases the next
        # epoch's batch 0, and incrementing it before wrapping would
        # swallow one skip — landing a guard rewind one batch short of
        # the offending window's end
        idx = self._epoch * per + self._batch + n
        self._epoch, self._batch = divmod(idx, per)
        return self

    def epoch(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Iterate the current epoch from the cursor position (batch 0
        on a fresh source; mid-epoch after :meth:`load_state`). The
        order is ``RandomState(seed + epoch)``'s permutation and each
        image's augmentation seed derives from its position — both pure
        functions of the cursor, never of consumed RNG state."""
        e = self._epoch
        order = np.random.RandomState(self.seed + e).permutation(
            len(self.paths))
        b = self.batch
        starts = range(0, len(order) - (b - 1 if self.drop_last
                                        else 0), b)
        for bi, start in enumerate(starts):
            if bi < self._batch:
                continue                 # cursor skip: nothing decoded
            idx = order[start:start + b]
            futs = [self._pool.submit(
                _decode_one, self.paths[i], self.size,
                _stable_seed(self.seed, e, start + j), self.train)
                    for j, i in enumerate(idx)]
            x = np.empty((len(idx), self.size, self.size, 3), self.dtype)
            for j, f in enumerate(futs):
                x[j] = f.result().astype(self.dtype)
            x *= np.asarray(1.0 / 255.0, self.dtype)
            self._batch = bi + 1
            yield x, self.labels[idx]
        self._epoch += 1
        self._batch = 0

    def batches(self, steps: int) -> Iterator[Tuple[np.ndarray,
                                                    np.ndarray]]:
        """Exactly ``steps`` batches, re-entering epochs as needed."""
        if len(self) == 0:
            raise ValueError(
                f"dataset has {len(self.paths)} images < batch size "
                f"{self.batch} with drop_last — no batch can be formed")
        done = 0
        while done < steps:
            for xb, yb in self.epoch():
                yield xb, yb
                done += 1
                if done >= steps:
                    return


def synthetic_source(batch, size, steps, seed=0, num_classes=1000):
    """Host-synthetic batches (the no-dataset default)."""
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        x = rng.rand(batch, size, size, 3).astype(np.float32)
        y = rng.randint(0, num_classes, batch).astype(np.int32)
        yield x, y


class DevicePrefetcher:
    """Host→device prefetch: the `data_prefetcher` role
    (`examples/imagenet/main_amp.py:264-317`).

    A background thread device_puts upcoming batches (with the fp16/bf16
    input cast the reference does on its side stream) into a bounded
    queue while the device trains on the current one. JAX's async
    dispatch provides the "stream overlap".
    """

    def __init__(self, it, sharding=None, cast_dtype=None, depth: int = 2):
        import jax

        self.q = queue.Queue(maxsize=depth)
        self._sentinel = object()
        self._error = None

        def work():
            try:
                for batch in it:
                    if cast_dtype is not None:
                        batch = (batch[0].astype(cast_dtype),) + batch[1:]
                    self.q.put(jax.device_put(batch, sharding))
            except BaseException as e:          # surface in the consumer
                self._error = e
            finally:
                self.q.put(self._sentinel)

        self.t = threading.Thread(target=work, daemon=True)
        self.t.start()

    def __iter__(self):
        while True:
            item = self.q.get()
            if item is self._sentinel:
                if self._error is not None:
                    raise self._error
                return
            yield item


def measure_source(src, steps: int = 20) -> float:
    """Loader-only images/sec — the input-bound-vs-compute-bound probe.

    Compare against the model's synthetic-data img/s: if this number is
    lower, the config is input-bound and training throughput will cap
    here no matter the chip.
    """
    import time

    it = iter(src)
    x, _ = next(it)       # warm the pool
    n = 0
    t0 = time.perf_counter()
    for i, (x, _) in enumerate(it):
        n += x.shape[0]
        if i + 1 >= steps:
            break
    dt = time.perf_counter() - t0
    return n / dt if dt > 0 else float("inf")


def make_fake_imagefolder(root: str, n_classes: int = 4,
                          per_class: int = 8, size: int = 256,
                          seed: int = 0) -> str:
    """Write a small synthetic ImageFolder tree of JPEGs (for tests and
    loader benchmarks in images-free environments)."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    for c in range(n_classes):
        d = os.path.join(root, f"class_{c:03d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 256, (size, size, 3), np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img_{i:04d}.jpg"),
                                      quality=85)
    return root
