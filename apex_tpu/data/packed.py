"""Packed pre-decoded dataset cache — the DALI-accelerated-input role.

The reference offers DALI as a first-class decode path because JPEG
decode on host CPUs cannot feed a fast chip
(`examples/imagenet/main_amp.py:28-57`); on this host the threaded-PIL
loader measures ~5× below compute at 224 px (ROUND3_NOTES item 5). The
TPU-side answer is a one-time decode into packed uint8-NHWC shards:

- **build**: decode every image once (resize short side to
  ``store_size``, center crop) with the thread pool, write
  ``shard_*.npy`` files of (N, S, S, 3) uint8 plus ``labels.npy`` and
  ``meta.json``.
- **read**: ``PackedSource`` memory-maps the shards and assembles
  batches by global index — per-epoch shuffle like the live loader,
  random-crop + horizontal-flip augmentation in pure numpy slicing
  (no decode, no resize on the hot path), float scale into one
  contiguous output buffer.

The augmentation trade is the standard fast-pipeline one (DALI's fused
decode+crop): random ``size``-crop from the ``store_size`` cache plus
flip, instead of the full RandomResizedCrop scale range; pass
``rrc=True`` to do true RandomResizedCrop on the cached pixels (PIL
resize from array — still ~an order of magnitude cheaper than JPEG
decode).

Measured with ``python -m apex_tpu.data --bench DIR --cache CACHE`` —
the loader-vs-compute criterion (≥ synthetic-data img/s at 224 px) is
checked in BENCH_TABLE.md.
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import json
import os
from typing import Iterator, Optional, Tuple

import numpy as np

META = "meta.json"


def _v3_view_on_strided() -> bool:
    """numpy>=1.23 allows an itemsize-changing view on arrays that are
    contiguous only in the last axis — probed once, not per image."""
    try:
        np.zeros((4, 4, 3), np.uint8)[1:3, 1:3].view("V3")
        return True
    except ValueError:
        return False


_V3_STRIDED_OK = _v3_view_on_strided()


def _fingerprint(root: str, paths, labels) -> str:
    """Content identity of the source listing: relative paths, labels,
    and each file's (size, mtime_ns). Guards cache reuse against a
    same-count dataset whose files, labels, or in-place contents
    changed (ADVICE r4: count+size alone served stale pixels).

    Detecting in-place edits costs one metadata sweep even on the
    cache-HIT path; it is batched as one scandir per class directory
    (readdir-plus filesystems serve size/mtime from the directory
    pass), which bounds the warm-start cost at directory enumeration —
    the same order as the listing build_cache already does."""
    stats = {}
    for d in sorted({os.path.dirname(p) for p in paths}):
        with os.scandir(d) as it:
            for e in it:
                stats[e.path] = e.stat()
    h = hashlib.sha256()
    for p, y in zip(paths, labels):
        st = stats.get(p)
        if st is None:
            # deleted/renamed between the listing and this sweep, or a
            # path-normalization mismatch with the scandir key: fall
            # back to a direct stat; a file that is truly gone
            # fingerprints as absent (so the cache rebuilds) instead of
            # raising KeyError on the warm-check path (ADVICE r5)
            try:
                st = os.stat(p)
            except OSError:
                h.update(os.path.relpath(p, root).encode())
                h.update(b"\0%d\0missing\n" % int(y))
                continue
        h.update(os.path.relpath(p, root).encode())
        h.update(b"\0%d\0%d\0%d\n" % (int(y), st.st_size,
                                      st.st_mtime_ns))
    return h.hexdigest()


def _decode_store(path: str, store_size: int) -> np.ndarray:
    """Resize short side to store_size, center crop — the one-time
    decode transform (deterministic; augmentation happens at read)."""
    from PIL import Image

    with Image.open(path) as img:
        img = img.convert("RGB")
        w, h = img.size
        s = min(w, h)
        box = ((w - s) // 2, (h - s) // 2, (w + s) // 2, (h + s) // 2)
        img = img.resize((store_size, store_size), Image.BILINEAR,
                         box=box)
        return np.asarray(img, np.uint8)


def build_cache(root: str, cache_dir: str, *, store_size: int = 256,
                shard_images: int = 4096,
                workers: Optional[int] = None) -> str:
    """One-time decode of an ImageFolder tree into packed shards.
    Idempotent: an existing complete cache (matching meta) is reused."""
    from apex_tpu.data.pipeline import _list_imagefolder

    paths, labels, classes = _list_imagefolder(root)
    os.makedirs(cache_dir, exist_ok=True)
    meta_path = os.path.join(cache_dir, META)
    fp = _fingerprint(root, paths, labels)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        if (meta.get("n") == len(paths)
                and meta.get("store_size") == store_size
                and meta.get("fingerprint") == fp):
            return cache_dir

    workers = workers or min(16, (os.cpu_count() or 1))
    pool = concurrent.futures.ThreadPoolExecutor(workers)
    try:
        shards = []
        for s0 in range(0, len(paths), shard_images):
            chunk = paths[s0:s0 + shard_images]
            buf = np.empty((len(chunk), store_size, store_size, 3),
                           np.uint8)
            for i, arr in enumerate(pool.map(
                    lambda p: _decode_store(p, store_size), chunk)):
                buf[i] = arr
            name = f"shard_{len(shards):05d}.npy"
            np.save(os.path.join(cache_dir, name), buf)
            shards.append({"file": name, "n": len(chunk)})
    finally:
        pool.shutdown(wait=False)
    np.save(os.path.join(cache_dir, "labels.npy"), labels)
    meta = {"n": len(paths), "store_size": store_size,
            "shards": shards, "classes": classes, "fingerprint": fp}
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    return cache_dir


class PackedSource:
    """Batched (x, y) iterator over a packed cache — drop-in for
    :class:`ImageFolderSource` (same epoch/shuffle/batches surface, so
    the prefetcher and `measure_source` compose unchanged)."""

    def __init__(self, cache_dir: str, batch: int, size: int = 224, *,
                 train: bool = True, seed: int = 0, dtype=np.float32,
                 drop_last: bool = True, rrc: bool = False,
                 workers: Optional[int] = None):
        with open(os.path.join(cache_dir, META)) as f:
            self.meta = json.load(f)
        self.store = self.meta["store_size"]
        if size > self.store:
            raise ValueError(f"crop size {size} > cached store size "
                             f"{self.store}")
        self.batch = batch
        self.size = size
        self.train = train
        self.seed = seed
        self.dtype = dtype
        self.drop_last = drop_last
        self.rrc = rrc
        self.classes = self.meta["classes"]
        self.labels = np.load(os.path.join(cache_dir, "labels.npy"))
        # memory-mapped shards + global-index offsets
        self._maps = [np.load(os.path.join(cache_dir, s["file"]),
                              mmap_mode="r")
                      for s in self.meta["shards"]]
        self._starts = np.cumsum(
            [0] + [s["n"] for s in self.meta["shards"]])
        self.workers = workers or min(8, (os.cpu_count() or 1))
        self._pool = concurrent.futures.ThreadPoolExecutor(self.workers)
        self._epoch = 0

    def close(self) -> None:
        self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __len__(self):
        n = self.meta["n"] // self.batch
        if not self.drop_last and self.meta["n"] % self.batch:
            n += 1
        return n

    def _fill_slab(self, idx, shard_ids, y0s, x0s, flips, rrc_seeds,
                   u8, j0, j1):
        """Crop/flip cached images idx[j0:j1] into u8[j0:j1] — one
        contiguous slab per worker (per-image task dispatch costs more
        than the crop itself on small copies)."""
        c = self.size
        u8v = u8.view("V3")   # 3-byte items: reversed-width copies run
        # ~1.8x faster than numpy's per-channel negative-stride loop
        for j in range(j0, j1):
            img = self._maps[shard_ids[j]][idx[j]
                                           - self._starts[shard_ids[j]]]
            if rrc_seeds is not None:
                from PIL import Image
                from apex_tpu.data.pipeline import _random_resized_crop
                rng = np.random.RandomState(int(rrc_seeds[j]))
                pil = _random_resized_crop(
                    Image.fromarray(np.asarray(img)), c, rng)
                crop = np.asarray(pil, np.uint8)
            else:
                crop = img[y0s[j]:y0s[j] + c, x0s[j]:x0s[j] + c]
            if flips is not None and flips[j]:
                if _V3_STRIDED_OK:
                    u8v[j] = crop.view("V3")[:, ::-1]
                else:
                    u8[j] = crop[:, ::-1]
            else:
                u8[j] = crop

    def epoch(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        rng = np.random.RandomState(self.seed + self._epoch)
        order = rng.permutation(self.meta["n"])
        self._epoch += 1
        b = self.batch
        c, s = self.size, self.store
        stop = len(order) - (b - 1 if self.drop_last else 0)
        out_u8 = self.dtype == np.uint8 or self.dtype is np.uint8
        for start in range(0, stop, b):
            idx = order[start:start + b]
            n = len(idx)
            shard_ids = np.searchsorted(self._starts, idx, "right") - 1
            # augment decisions drawn vectorized, once per batch
            if self.train:
                y0s = rng.randint(0, s - c + 1, n)
                x0s = rng.randint(0, s - c + 1, n)
                flips = rng.rand(n) < 0.5
                rrc_seeds = (rng.randint(1 << 31, size=n)
                             if self.rrc else None)
            else:
                y0s = x0s = np.full(n, (s - c) // 2)
                flips = rrc_seeds = None
            u8 = np.empty((n, c, c, 3), np.uint8)
            if self.workers <= 1 or n < 2 * self.workers:
                self._fill_slab(idx, shard_ids, y0s, x0s, flips,
                                rrc_seeds, u8, 0, n)
            else:
                w = self.workers
                bounds = [(n * i // w, n * (i + 1) // w)
                          for i in range(w)]
                list(self._pool.map(
                    lambda se: self._fill_slab(idx, shard_ids, y0s,
                                               x0s, flips, rrc_seeds,
                                               u8, *se), bounds))
            if out_u8:
                # raw mode: normalization happens on-device (the DALI
                # GPU-normalize role) — quarter the host-side bytes
                yield u8, self.labels[idx]
            else:
                # one-pass convert+scale ufunc (no separate astype)
                x = np.multiply(u8, np.float32(1.0 / 255.0),
                                dtype=np.float32)
                if self.dtype != np.float32:
                    x = x.astype(self.dtype)
                yield x, self.labels[idx]

    def batches(self, steps: int) -> Iterator[Tuple[np.ndarray,
                                                    np.ndarray]]:
        if len(self) == 0:
            raise ValueError("cache smaller than one batch")
        done = 0
        while done < steps:
            for xb, yb in self.epoch():
                yield xb, yb
                done += 1
                if done >= steps:
                    return
