"""L1-grade trajectory cross-product harness.

Mirrors the reference's strongest correctness statement
(`tests/L1/common/run_test.sh:1-120` + `compare.py:34-46`): the SAME
multi-step training run executed on two independent implementations must
produce the same loss trajectory across the full option grid
{O0,O1,O2,O3} x {loss_scale: dynamic, static-128, none} x
{keep_batchnorm_fp32: on, off}, including runs with mid-trajectory
overflow injections.

The reference compares {CUDA-extension, python-only} builds bitwise. The
analogue here is {fused path: Pallas kernels + FusedSGD arena kernel}
vs {oracle path: pure-jnp reference ops + a jnp SGD replica}. Floating
trajectories compare at dtype-appropriate tolerance (reduction orders
legitimately differ between a padded Pallas block and a plain jnp
reduction); everything *decision-shaped* — step counts, skip decisions,
loss-scale schedule values — must agree BITWISE. Determinism and
checkpoint/resume of a single path are asserted bitwise.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.linen as nn

from apex_tpu import amp, ops
from apex_tpu.optim import FusedSGD
from apex_tpu.ops.layer_norm import layer_norm_reference
from apex_tpu.ops.xentropy import softmax_cross_entropy_reference

BATCH, HW, CH, HIDDEN, CLASSES = 8, 8, 8, 32, 10
STEPS = 6
LR, MOMENTUM = 0.05, 0.9


# --- the two implementations -------------------------------------------------

class Net(nn.Module):
    """Conv + BN + Dense + LayerNorm + Dense — every knob in the grid has
    something to act on (BN for keep_batchnorm_fp32, LayerNorm + CE for
    the fused-op surface)."""
    fused: bool
    dtype: object = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(CH, (3, 3), dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="bn")(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(HIDDEN, dtype=self.dtype, name="fc1")(x)
        w = self.param("ln_scale", nn.initializers.ones, (HIDDEN,),
                       jnp.float32)
        b = self.param("ln_bias", nn.initializers.zeros, (HIDDEN,),
                       jnp.float32)
        if self.fused:
            x = ops.fused_layer_norm_affine(x, w, b, 1e-5)
        else:
            x = layer_norm_reference(x, w, b, 1e-5)
        x = nn.Dense(CLASSES, dtype=self.dtype, name="fc2")(x)
        return x


class RefSGD:
    """Plain-jnp replica of the FusedSGD math (momentum buffer
    initialized to the raw first gradient, optional wd placement —
    `multi_tensor_sgd_kernel.cu:30-180` semantics) with the fused
    optimizer's (init/step) protocol."""

    def __init__(self, lr, momentum):
        self.lr, self.momentum = lr, momentum

    def init(self, params):
        return {"count": jnp.int32(0),
                "m": jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)}

    def step(self, grads, state, params):
        count = state["count"] + 1
        first = count == 1

        def upd(p, g, m):
            g32 = g.astype(jnp.float32)
            m2 = jnp.where(first, g32,
                           self.momentum * m + g32)
            p2 = p.astype(jnp.float32) - self.lr * m2
            return p2.astype(p.dtype), m2

        flat = jax.tree_util.tree_map(upd, params, grads, state["m"])
        new_p = jax.tree_util.tree_map(lambda t: t[0], flat,
                                       is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree_util.tree_map(lambda t: t[1], flat,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return new_p, {"count": count, "m": new_m}


def _data(poison_steps=()):
    rng = np.random.RandomState(0)
    xs = rng.randn(STEPS, BATCH, HW, HW, 3).astype(np.float32)
    for s in poison_steps:
        xs[s, 0, 0, 0, 0] = np.inf
    ys = rng.randint(0, CLASSES, (STEPS, BATCH))
    return jnp.asarray(xs), jnp.asarray(ys, jnp.int32)


def _run(policy, fused: bool, poison_steps=()):
    """Train STEPS steps; return (losses, final params, scaler history,
    step count)."""
    model = Net(fused=fused, dtype=policy.compute_dtype)
    xs, ys = _data(poison_steps)
    variables = model.init(jax.random.PRNGKey(0), xs[0], train=True)
    params, batch_stats = variables["params"], variables.get(
        "batch_stats", {})

    tx = FusedSGD(lr=LR, momentum=MOMENTUM) if fused else \
        RefSGD(LR, MOMENTUM)
    amp_opt = amp.Amp(policy, tx)
    state = amp_opt.init(params)

    ce = (ops.softmax_cross_entropy_loss if fused
          else softmax_cross_entropy_reference)

    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb,
                train=True, mutable=["batch_stats"])
            return jnp.mean(ce(logits, yb)), mut["batch_stats"]

        (loss, new_bs), grads, state2, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        state2 = amp_opt.apply_gradients(state2, grads, finite)
        return state2, new_bs, loss, finite

    jstep = jax.jit(step)
    losses, scales, finites = [], [], []
    for i in range(STEPS):
        state, batch_stats, loss, finite = jstep(
            state, batch_stats, xs[i], ys[i])
        losses.append(float(loss))
        finites.append(bool(finite) if isinstance(finite, bool)
                       else bool(np.asarray(finite)))
        s = state.scalers[0]
        scales.append(None if s is None else float(s.loss_scale))
    return losses, state, scales, finites


# --- the grid ----------------------------------------------------------------

OPT_LEVELS = ["O0", "O1", "O2", "O3"]
LOSS_SCALES = [("dynamic", "dynamic"), ("static128", 128.0), ("none", None)]
KEEP_BN = [True, False]


def _make_policy(opt_level, loss_scale, keep_bn):
    try:
        return amp.Policy.from_opt_level(
            opt_level, loss_scale=loss_scale,
            keep_batchnorm_fp32=keep_bn)
    except ValueError:
        return None   # combination rejected by validation (like the
                      # reference skipping inapplicable combos)


GRID = [(ol, sn, sv, kb)
        for (ol, (sn, sv), kb) in itertools.product(
            OPT_LEVELS, LOSS_SCALES, KEEP_BN)]


class TestCrossProduct:
    @pytest.mark.parametrize(
        "opt_level,scale_name,scale_val,keep_bn", GRID,
        ids=[f"{ol}-{sn}-bn{int(kb)}" for ol, sn, sv, kb in GRID])
    def test_fused_matches_oracle_trajectory(self, opt_level, scale_name,
                                             scale_val, keep_bn):
        policy = _make_policy(opt_level, scale_val, keep_bn)
        if policy is None:
            pytest.skip("combination rejected by Policy validation")

        l_fused, st_fused, sc_fused, f_fused = _run(policy, fused=True)
        l_ref, st_ref, sc_ref, f_ref = _run(policy, fused=False)

        # decision-shaped state: BITWISE
        assert f_fused == f_ref, "skip decisions diverged"
        assert sc_fused == sc_ref, "loss-scale schedule diverged"
        assert int(st_fused.step) == int(st_ref.step)

        # float trajectories: dtype-appropriate tolerance
        tol = 1e-5 if policy.compute_dtype is None else 2e-2
        np.testing.assert_allclose(l_fused, l_ref, rtol=tol, atol=tol,
                                   err_msg="loss trajectories diverged")
        fa = jax.tree_util.tree_leaves_with_path(st_fused.params)
        fb = {jax.tree_util.keystr(p): l for p, l in
              jax.tree_util.tree_leaves_with_path(st_ref.params)}
        for path, a in fa:
            key = jax.tree_util.keystr(path)
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(fb[key], np.float32),
                rtol=tol, atol=tol, err_msg=f"params diverged at {key}")

    def test_deterministic_rerun_bitwise(self):
        """The same path run twice is bitwise identical — the property
        that makes the reference's build-to-build compare meaningful."""
        policy = amp.Policy.from_opt_level("O2")
        l1, st1, _, _ = _run(policy, fused=True)
        l2, st2, _, _ = _run(policy, fused=True)
        assert l1 == l2
        for a, b in zip(jax.tree_util.tree_leaves(st1.params),
                        jax.tree_util.tree_leaves(st2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestOverflowInjection:
    """`tests/L0/run_amp/test_fused_sgd.py` overflow pattern: poison
    chosen iterations, assert skip semantics and post-recovery agreement."""

    def test_fp16_dynamic_overflow_skip_both_paths(self):
        policy = amp.Policy.from_opt_level("O2", half_dtype=jnp.float16,
                                           loss_scale="dynamic")
        poison = (2, 4)
        l_f, st_f, sc_f, fin_f = _run(policy, fused=True,
                                      poison_steps=poison)
        l_r, st_r, sc_r, fin_r = _run(policy, fused=False,
                                      poison_steps=poison)

        # both paths must skip exactly the poisoned steps
        assert fin_f == fin_r
        assert [i for i, f in enumerate(fin_f) if not f] == list(poison)
        # step counter advanced only on clean steps — bitwise
        assert int(st_f.step) == int(st_r.step) == STEPS - len(poison)
        # scale halved at each overflow, schedule identical — bitwise
        assert sc_f == sc_r
        assert sc_f[2] == sc_f[1] / 2 and sc_f[4] == sc_f[3] / 2
        # params agree after recovery
        for a, b in zip(jax.tree_util.tree_leaves(st_f.params),
                        jax.tree_util.tree_leaves(st_r.params)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=2e-2, atol=2e-2)

    def test_checkpoint_resume_bitwise(self):
        """Mid-trajectory save/restore continues bitwise identically to
        the uninterrupted run (README 'Checkpointing' contract)."""
        policy = amp.Policy.from_opt_level("O2", half_dtype=jnp.float16)
        model = Net(fused=True, dtype=policy.compute_dtype)
        xs, ys = _data()
        variables = model.init(jax.random.PRNGKey(0), xs[0], train=True)
        amp_opt = amp.Amp(policy, FusedSGD(lr=LR, momentum=MOMENTUM))
        state = amp_opt.init(variables["params"])
        bs = variables.get("batch_stats", {})

        def step(state, bs, xb, yb):
            def loss_fn(mp):
                logits, mut = model.apply(
                    {"params": mp, "batch_stats": bs}, xb, train=True,
                    mutable=["batch_stats"])
                return jnp.mean(ops.softmax_cross_entropy_loss(
                    logits, yb)), mut["batch_stats"]
            (loss, bs2), grads, st, fin = amp_opt.backward(
                state, loss_fn, has_aux=True)
            return amp_opt.apply_gradients(st, grads, fin), bs2

        jstep = jax.jit(step)
        for i in range(3):
            state, bs = jstep(state, bs, xs[i], ys[i])
        # round-trip through host numpy (what a checkpointer does)
        saved = jax.tree_util.tree_map(
            lambda x: None if x is None else jnp.asarray(np.asarray(x)),
            (state, bs), is_leaf=lambda x: x is None)
        restored_state, restored_bs = saved
        for i in range(3, STEPS):
            state, bs = jstep(state, bs, xs[i], ys[i])
            restored_state, restored_bs = jstep(
                restored_state, restored_bs, xs[i], ys[i])
        for a, b in zip(jax.tree_util.tree_leaves(state.params),
                        jax.tree_util.tree_leaves(restored_state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
