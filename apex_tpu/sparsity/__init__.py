"""apex_tpu.sparsity — 2:4 structured sparsity (ASP, SURVEY.md §2.8)."""

from apex_tpu.sparsity.masklib import (
    create_mask, m4n2_1d, m4n2_2d_greedy, m4n2_2d_best, density,
)
from apex_tpu.sparsity.asp import (
    ASP, ASPState, compute_sparse_masks, prune, default_whitelist,
)

__all__ = [
    "create_mask", "m4n2_1d", "m4n2_2d_greedy", "m4n2_2d_best", "density",
    "ASP", "ASPState", "compute_sparse_masks", "prune",
    "default_whitelist",
]
