"""2:4 structured sparsity mask computation.

Rebuild of `apex/contrib/sparsity/sparse_masklib.py:25-160`: for every
contiguous group of 4 elements along the last (reduction) dimension keep
the 2 with the pattern maximizing preserved magnitude. ``m4n2_1d`` is the
exhaustive 6-pattern search (`create_mask`'s "1d best"). The 2d variants
operate per 4x4 block so the mask is 2:4 along BOTH rows and columns
(the transposed weight used by dgrad is then also structured-sparse,
`sparse_masklib.py:54-66`): ``m4n2_2d_greedy`` is the reference's greedy
descending-magnitude fill with row/column counters (`mn_2d_greedy`,
`sparse_masklib.py:69-97`) vectorized over all blocks at once;
``m4n2_2d_best`` is the exhaustive search over the 90 doubly-2:4 4x4
patterns (`mn_2d_best`, `sparse_masklib.py:123-139`).

Everything is pure tensor math (the reference computes masks in torch on
device for 1d/2d-best but drops to a per-block numpy loop for greedy,
`sparse_masklib.py:71-97`) — here all patterns are jit/vmap friendly with
no host loops over elements.
"""

from __future__ import annotations

import functools
import itertools

import jax
import jax.numpy as jnp
import numpy as np

# all C(4,2)=6 binary patterns with exactly 2 of 4 kept
_PATTERNS_4C2 = np.array(
    [p for p in itertools.product((0, 1), repeat=4) if sum(p) == 2],
    np.float32)                                    # (6, 4)


@functools.lru_cache(maxsize=None)
def _patterns_4x4_2d() -> np.ndarray:
    """The 90 4x4 binary patterns whose every row AND column keeps
    exactly 2 of 4 (`compute_valid_2d_patterns`,
    `sparse_masklib.py:102-120`), flattened to (90, 16)."""
    rows = _PATTERNS_4C2                           # (6, 4)
    combos = np.stack(np.meshgrid(*([np.arange(6)] * 4),
                                  indexing="ij"), -1).reshape(-1, 4)
    pats = rows[combos]                            # (1296, 4, 4)
    valid = (pats.sum(axis=1) == 2).all(axis=1)    # column sums == 2
    return pats[valid].reshape(-1, 16).astype(np.float32)


def m4n2_1d(w) -> jax.Array:
    """Boolean mask, groups of 4 along the last dim, keep best 2.

    Tail elements (last dim % 4) are always kept — same behavior as the
    reference's padding treatment.
    """
    shape = w.shape
    n = shape[-1]
    ngroups = n // 4
    body_len = ngroups * 4
    body = jnp.abs(w[..., :body_len].astype(jnp.float32))
    body = body.reshape(*shape[:-1], ngroups, 4)
    patterns = jnp.asarray(_PATTERNS_4C2)          # (6, 4)
    scores = jnp.einsum("...gi,pi->...gp", body, patterns)
    best = jnp.argmax(scores, axis=-1)             # (..., g)
    mask_body = patterns[best]                     # (..., g, 4)
    mask_body = mask_body.reshape(*shape[:-1], body_len) > 0.5
    if body_len < n:
        tail = jnp.ones((*shape[:-1], n - body_len), bool)
        return jnp.concatenate([mask_body, tail], axis=-1)
    return mask_body


def _to_blocks(w):
    """(..., R, C) -> (N, 16) of 4x4 blocks covering the divisible body,
    plus the bookkeeping to undo it. Tail rows/cols (R%4, C%4) stay dense
    (`mn_2d_greedy` only iterates rowCount/colCount multiples of m,
    `sparse_masklib.py:74-76`)."""
    *lead, r, c = w.shape
    rb, cb = (r // 4) * 4, (c // 4) * 4
    body = w[..., :rb, :cb].astype(jnp.float32)
    nlead = int(np.prod(lead)) if lead else 1
    blocks = body.reshape(nlead, rb // 4, 4, cb // 4, 4)
    blocks = jnp.swapaxes(blocks, 2, 3).reshape(-1, 16)
    return blocks, (lead, r, c, rb, cb, nlead)


def _from_blocks(mask_flat, meta, w_shape):
    lead, r, c, rb, cb, nlead = meta
    m = mask_flat.reshape(nlead, rb // 4, cb // 4, 4, 4)
    m = jnp.swapaxes(m, 2, 3).reshape(*lead, rb, cb)
    if rb < r:
        m = jnp.concatenate(
            [m, jnp.ones((*lead, r - rb, cb), m.dtype)], axis=-2)
    if cb < c:
        m = jnp.concatenate(
            [m, jnp.ones((*lead, r, c - cb), m.dtype)], axis=-1)
    return m


def m4n2_2d_greedy(w) -> jax.Array:
    """Per-4x4-block greedy doubly-2:4 mask — the algorithm of
    ``mn_2d_greedy`` (`sparse_masklib.py:69-97`): visit block entries in
    descending |magnitude|, keep an entry unless its row or column
    already holds 2 kept entries. The reference runs this as a numpy
    loop per block; here all blocks step together through the 16
    magnitude ranks (vectorized one-hot scatters), so it jits and runs
    on device."""
    if w.ndim < 2:
        return m4n2_1d(w)
    blocks, meta = _to_blocks(w)
    n = blocks.shape[0]
    order = jnp.argsort(-jnp.abs(blocks), axis=-1)   # (N, 16) descending
    rowcnt = jnp.zeros((n, 4), jnp.int32)
    colcnt = jnp.zeros((n, 4), jnp.int32)
    mask = jnp.zeros((n, 16), bool)
    for t in range(16):
        idx = order[:, t]                            # (N,)
        rr, cc = idx // 4, idx % 4
        r1 = jax.nn.one_hot(rr, 4, dtype=jnp.int32)  # (N, 4)
        c1 = jax.nn.one_hot(cc, 4, dtype=jnp.int32)
        can = ((jnp.sum(rowcnt * r1, axis=1) < 2)
               & (jnp.sum(colcnt * c1, axis=1) < 2))  # (N,)
        take = can[:, None]
        rowcnt = rowcnt + r1 * take
        colcnt = colcnt + c1 * take
        mask = mask | (jax.nn.one_hot(idx, 16, dtype=jnp.int32)
                       * take).astype(bool)
    return _from_blocks(mask, meta, w.shape)


def m4n2_2d_best(w) -> jax.Array:
    """Exhaustive per-4x4-block doubly-2:4 mask (``mn_2d_best``,
    `sparse_masklib.py:123-139`): argmax of preserved |magnitude| over
    the 90 valid patterns — one batched matmul over all blocks."""
    if w.ndim < 2:
        return m4n2_1d(w)
    blocks, meta = _to_blocks(w)
    pats = jnp.asarray(_patterns_4x4_2d())           # (90, 16)
    scores = jnp.abs(blocks) @ pats.T                # (N, 90)
    mask = pats[jnp.argmax(scores, axis=-1)] > 0.5   # (N, 16)
    return _from_blocks(mask, meta, w.shape)


_PATTERNS = {
    "m4n2_1d": m4n2_1d,
    "m4n2_2d_greedy": m4n2_2d_greedy,
    "m4n2_2d_best": m4n2_2d_best,
}


def create_mask(w, pattern: str = "m4n2_1d") -> jax.Array:
    """Mask for one tensor (`sparse_masklib.py:145-160`). Tensors with
    fewer than 4 elements in the last dim are left dense."""
    if pattern not in _PATTERNS:
        raise ValueError(f"unknown pattern {pattern!r}; "
                         f"have {sorted(_PATTERNS)}")
    if w.shape[-1] < 4:
        return jnp.ones(w.shape, bool)
    return _PATTERNS[pattern](w)


def density(mask) -> float:
    return float(jnp.mean(mask.astype(jnp.float32)))
