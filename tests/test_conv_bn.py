"""Distributed-dgrad conv+BN unit (ops/conv_bn.py) vs the autodiff oracle.

The unit's backward distributes the conv transposes over the three
linear terms of BN's dx (weight-folded scales, batch-independent
constant term); these tests pin it bitwise-close to plain autodiff of
``relu?(bn(conv(a, w)) [+ r])`` in fp32, across kernel sizes, strides,
residual joins, and the zero-init-γ corner (the ResNet recipe).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.conv_bn import (
    ConvBNAct, conv_bn_act_train, conv_bn_add_act_train, make_conv_cfg,
)


def _oracle(a, w, scale, bias, r, *, strides, relu, eps=1e-5):
    x = jax.lax.conv_general_dilated(
        a, w, window_strides=strides, padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    mean = jnp.mean(x, axis=(0, 1, 2))
    var = jnp.mean(jnp.square(x - mean), axis=(0, 1, 2))
    y = (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias
    if r is not None:
        y = y + r
    if relu:
        y = jnp.maximum(y, 0.0)
    return y


def _rand(rng, shape):
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


@pytest.mark.parametrize("ksize,strides", [
    ((1, 1), (1, 1)), ((3, 3), (1, 1)), ((3, 3), (2, 2)),
    ((1, 1), (2, 2)),
])
@pytest.mark.parametrize("relu", [True, False])
def test_conv_bn_act_grads_match_autodiff(ksize, strides, relu):
    rng = np.random.default_rng(0)
    a = _rand(rng, (4, 8, 8, 6))
    w = _rand(rng, ksize + (6, 5)) * 0.3
    scale = _rand(rng, (5,)) * 0.5 + 1.0
    bias = _rand(rng, (5,)) * 0.2
    cfg = make_conv_cfg(strides=strides, relu=relu)
    t_shape = _oracle(a, w, scale, bias, None, strides=strides,
                      relu=relu).shape
    t = _rand(rng, t_shape)

    def loss_unit(a, w, scale, bias):
        z, *_ = conv_bn_act_train(a, w, scale, bias, cfg)
        return jnp.sum(z * t)

    def loss_ref(a, w, scale, bias):
        return jnp.sum(_oracle(a, w, scale, bias, None, strides=strides,
                               relu=relu) * t)

    got = jax.grad(loss_unit, argnums=(0, 1, 2, 3))(a, w, scale, bias)
    want = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(a, w, scale, bias)
    for g, wnt, name in zip(got, want, ["da", "dw", "dscale", "dbias"]):
        np.testing.assert_allclose(g, wnt, rtol=2e-4, atol=2e-4,
                                   err_msg=name)


@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("zero_gamma", [False, True])
def test_conv_bn_add_act_grads_match_autodiff(relu, zero_gamma):
    rng = np.random.default_rng(1)
    a = _rand(rng, (4, 8, 8, 4))
    w = _rand(rng, (1, 1, 4, 8)) * 0.3
    scale = (jnp.zeros((8,)) if zero_gamma
             else _rand(rng, (8,)) * 0.5 + 1.0)
    bias = _rand(rng, (8,)) * 0.2
    r = _rand(rng, (4, 8, 8, 8))
    cfg = make_conv_cfg(strides=(1, 1), relu=relu)
    t = _rand(rng, (4, 8, 8, 8))

    def loss_unit(a, w, r, scale, bias):
        z, *_ = conv_bn_add_act_train(a, w, r, scale, bias, cfg)
        return jnp.sum(z * t)

    def loss_ref(a, w, r, scale, bias):
        return jnp.sum(_oracle(a, w, scale, bias, r, strides=(1, 1),
                               relu=relu) * t)

    got = jax.grad(loss_unit, argnums=(0, 1, 2, 3, 4))(a, w, r, scale,
                                                       bias)
    want = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(a, w, r, scale,
                                                       bias)
    for g, wnt, name in zip(got, want,
                            ["da", "dw", "dr", "dscale", "dbias"]):
        np.testing.assert_allclose(g, wnt, rtol=2e-4, atol=2e-4,
                                   err_msg=name)


def test_conv_bn_forward_stats():
    rng = np.random.default_rng(2)
    a = _rand(rng, (2, 6, 6, 3))
    w = _rand(rng, (3, 3, 3, 4)) * 0.3
    scale = jnp.ones((4,))
    bias = jnp.zeros((4,))
    cfg = make_conv_cfg(strides=(1, 1), relu=True)
    z, mean, var, count = conv_bn_act_train(a, w, scale, bias, cfg)
    x = jax.lax.conv_general_dilated(
        a, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(mean, jnp.mean(x, (0, 1, 2)), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(
        var, jnp.mean(jnp.square(x - jnp.mean(x, (0, 1, 2))), (0, 1, 2)),
        rtol=1e-4, atol=1e-5)
    assert count == float(x.size // x.shape[-1])
    assert z.shape == x.shape


# baseline (separate Conv_i + _BN_i) → dist-"all" (ConvBNAct_i) block
# leaf mapping. Creation order: Conv_2 = final 1x1 (→ join unit CBA_3),
# Conv_3 = projection (→ CBA_2) — the checkpoint-layout ordering.
_BLOCK_MAP = {
    ("Conv_0", "kernel"): ("ConvBNAct_0", "kernel"),
    ("Conv_1", "kernel"): ("ConvBNAct_1", "kernel"),
    ("Conv_3", "kernel"): ("ConvBNAct_2", "kernel"),
    ("Conv_2", "kernel"): ("ConvBNAct_3", "kernel"),
    ("_BN_0",): ("ConvBNAct_0",),
    ("_BN_1",): ("ConvBNAct_1",),
    ("_BN_2",): ("ConvBNAct_2",),
    ("_BN_3",): ("ConvBNAct_3",),
}


def _map_block_params(bp, *, params):
    """Re-key one baseline BottleneckBlock subtree into the dist-'all'
    ConvBNAct layout (params=True) or batch_stats (params=False)."""
    out = {}
    for (src, *rest), (dst, *_) in _BLOCK_MAP.items():
        if src.startswith("Conv"):
            if params:
                out.setdefault(dst, {})["kernel"] = bp[src]["kernel"]
        else:
            leaf = bp[src]["FusedBNAct_0"]
            for k, v in leaf.items():
                out.setdefault(_BLOCK_MAP[(src,)][0], {})[k] = v
    return out


def test_resnet_dx_distribute_matches_baseline_grads():
    """Full-model integration: with parameters copied leaf-for-leaf into
    the fused tree, dist-'all' must reproduce the baseline's loss AND
    every parameter gradient (mapped back) to fp32 tolerance — this
    exercises the cfg wiring, residual paths and stat plumbing of the
    ConvBNAct units inside the real BottleneckBlock."""
    from apex_tpu.models import ResNet
    from apex_tpu.models.resnet import BottleneckBlock
    from apex_tpu.ops import softmax_cross_entropy_loss

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 7, 4), jnp.int32)

    def build(mode):
        return ResNet(stage_sizes=[1], block=BottleneckBlock,
                      num_classes=7, width=8, dx_distribute=mode)

    base, dist = build(None), build("all")
    vb = base.init(jax.random.PRNGKey(0), x, train=True)
    nb = sum(t.size for t in jax.tree_util.tree_leaves(vb["params"]))
    vd_shape = dist.init(jax.random.PRNGKey(0), x, train=True)
    nd = sum(t.size for t in
             jax.tree_util.tree_leaves(vd_shape["params"]))
    assert nb == nd, "fused units must not change the parameter count"

    # copy baseline params/stats into the dist tree
    def remap(tree, params):
        out = {}
        for k, v in tree.items():
            if k.startswith("BottleneckBlock"):
                out[k] = _map_block_params(v, params=params)
            else:
                out[k] = v
        return out

    pd = remap(vb["params"], params=True)
    sd = remap(vb["batch_stats"], params=False)
    chex_leaves_b = jax.tree_util.tree_structure(vd_shape["params"])
    assert jax.tree_util.tree_structure(pd) == chex_leaves_b

    def loss_fn(model, params, stats):
        logits, mut = model.apply(
            {"params": params, "batch_stats": stats}, x, train=True,
            mutable=["batch_stats"])
        return jnp.mean(softmax_cross_entropy_loss(logits, y)), \
            mut["batch_stats"]

    (lb, bsb), gb = jax.value_and_grad(
        lambda p: loss_fn(base, p, vb["batch_stats"]),
        has_aux=True)(vb["params"])
    (ld, bsd), gd = jax.value_and_grad(
        lambda p: loss_fn(dist, p, sd), has_aux=True)(pd)

    np.testing.assert_allclose(float(lb), float(ld), rtol=1e-5)
    # gradients: map the dist grads back and compare every leaf
    for k, v in gb.items():
        vd_g = gd[k]
        if k.startswith("BottleneckBlock"):
            vd_g_mapped = _map_block_params(v, params=True)  # structure
            for (src, *rest), (dst, *_) in _BLOCK_MAP.items():
                if src.startswith("Conv"):
                    np.testing.assert_allclose(
                        v[src]["kernel"], gd[k][dst]["kernel"],
                        rtol=5e-4, atol=5e-5, err_msg=f"{k}/{src}")
                else:
                    for leaf in ("scale", "bias"):
                        np.testing.assert_allclose(
                            v[src]["FusedBNAct_0"][leaf],
                            gd[k][dst][leaf], rtol=5e-4, atol=5e-5,
                            err_msg=f"{k}/{src}/{leaf}")
        else:
            for (pa, ga), (pb_, gb_) in zip(
                    jax.tree_util.tree_leaves_with_path(v),
                    jax.tree_util.tree_leaves_with_path(vd_g)):
                np.testing.assert_allclose(ga, gb_, rtol=5e-4,
                                           atol=5e-5, err_msg=str(k))
    # updated running stats must agree too (stat plumbing)
    for (pa, a), (pb_, b) in zip(
            jax.tree_util.tree_leaves_with_path(remap(bsb, params=False)),
            jax.tree_util.tree_leaves_with_path(bsd)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5,
                                   err_msg=jax.tree_util.keystr(pa))


def _map_block_params_join(bp, *, params):
    """Baseline BottleneckBlock subtree → dx_distribute='join' layout:
    only the final 1x1+BN+add+relu becomes a ConvBNAct unit, so Conv_0/1
    and _BN_0/1 keep their names, the projection shifts Conv_3→Conv_2,
    and baseline Conv_2 (final 1x1) + _BN_3 fold into ConvBNAct_0."""
    out = {}
    if params:
        out["Conv_0"] = bp["Conv_0"]
        out["Conv_1"] = bp["Conv_1"]
        out["Conv_2"] = bp["Conv_3"]          # projection
        out["ConvBNAct_0"] = {"kernel": bp["Conv_2"]["kernel"]}
    for name in ("_BN_0", "_BN_1"):
        out[name] = bp[name]
    out["_BN_2"] = bp["_BN_2"]            # projection BN
    for k, v in bp["_BN_3"]["FusedBNAct_0"].items():
        out.setdefault("ConvBNAct_0", {})[k] = v
    return out


def test_block_dx_distribute_join_matches_baseline():
    """ADVICE r4: 'join' mode had no coverage. Block-level oracle — the
    join-only BottleneckBlock (skipped final conv, ConvBNAct join unit,
    shifted flax auto-names) must reproduce the baseline block's output,
    every gradient, and the updated running stats."""
    from apex_tpu.models.resnet import BottleneckBlock

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((4, 16, 16, 8)), jnp.float32)

    def build(mode):
        return BottleneckBlock(features=4, strides=(2, 2),
                               dx_distribute=mode)

    base, dist = build(None), build("join")
    vb = base.init(jax.random.PRNGKey(0), x, train=True)
    pj = _map_block_params_join(vb["params"], params=True)
    sj = _map_block_params_join(vb["batch_stats"], params=False)
    vj_shape = dist.init(jax.random.PRNGKey(0), x, train=True)
    assert (jax.tree_util.tree_structure(pj)
            == jax.tree_util.tree_structure(vj_shape["params"]))

    t = jnp.asarray(rng.standard_normal((4, 8, 8, 16)), jnp.float32)

    def loss_fn(model, params, stats):
        z, mut = model.apply(
            {"params": params, "batch_stats": stats}, x, train=True,
            mutable=["batch_stats"])
        return jnp.sum(z * t), mut["batch_stats"]

    (lb, bsb), gb = jax.value_and_grad(
        lambda p: loss_fn(base, p, vb["batch_stats"]),
        has_aux=True)(vb["params"])
    (lj, bsj), gj = jax.value_and_grad(
        lambda p: loss_fn(dist, p, sj), has_aux=True)(pj)

    np.testing.assert_allclose(float(lb), float(lj), rtol=1e-5)
    gb_mapped = _map_block_params_join(gb, params=True)
    for (pa, a), (pb_, b) in zip(
            jax.tree_util.tree_leaves_with_path(gb_mapped),
            jax.tree_util.tree_leaves_with_path(gj)):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-5,
                                   err_msg=jax.tree_util.keystr(pa))
    bsb_mapped = _map_block_params_join(bsb, params=False)
    for (pa, a), (pb_, b) in zip(
            jax.tree_util.tree_leaves_with_path(bsb_mapped),
            jax.tree_util.tree_leaves_with_path(bsj)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5,
                                   err_msg=jax.tree_util.keystr(pa))
