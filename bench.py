"""Headline bench: ResNet-50 mixed-precision training throughput.

The BASELINE.json metric — images/sec/chip + MFU on ResNet-50, amp O2
(bf16 compute, fp32 masters) + fused SGD — measured on whatever single
accelerator is present. Prints ONE JSON line.

See PERF.md for the profiling breakdown behind the current number
(captured with apex_tpu.prof).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _measure(batch: int, size: int, iters: int):
    from apex_tpu import amp, models, ops
    from apex_tpu.optim import FusedSGD

    policy = amp.Policy.from_opt_level("O2")  # bf16 compute, fp32 masters
    model = models.ResNet50(num_classes=1000, dtype=policy.compute_dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, size, size, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)

    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    amp_opt = amp.Amp(policy, FusedSGD(lr=0.1, momentum=0.9))
    state = amp_opt.init(params)

    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            return loss, mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    # donate train state so XLA updates buffers in place (no state copies)
    jstep = jax.jit(step, donate_argnums=(0, 1))

    # warmup / compile. NOTE: sync via host fetch of the loss —
    # block_until_ready does not actually block on the experimental axon
    # TPU platform, producing fantasy timings.
    for _ in range(3):
        state, batch_stats, loss = jstep(state, batch_stats, x, y)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, batch_stats, loss = jstep(state, batch_stats, x, y)
    loss_val = float(loss)
    dt = time.perf_counter() - t0
    return batch * iters / dt, loss_val


def main():
    from apex_tpu import models, prof

    on_tpu = jax.default_backend() == "tpu"
    size = 224 if on_tpu else 64
    iters = 20 if on_tpu else 3
    # batch sweep: 256 is the sweet spot measured on v5e (see PERF.md).
    # Each candidate runs full warmup+iters (compiles dominate anyway);
    # an OOM on the bigger batch falls back to the next instead of
    # killing the bench.
    batches = (256, 128) if on_tpu else (8,)
    best, best_loss, best_batch = 0.0, float("nan"), batches[0]
    for b in batches:
        try:
            img_s, loss_val = _measure(b, size, iters)
        except Exception as e:  # RESOURCE_EXHAUSTED on small-HBM chips
            if "RESOURCE_EXHAUSTED" not in str(e) and "memory" not in \
                    str(e).lower():
                raise
            continue
        if img_s > best:
            best, best_loss, best_batch = img_s, loss_val, b

    # fwd+bwd ≈ 3x fwd FLOPs, scaled to the bench image size
    flops_img = models.RESNET50_FLOPS_PER_IMAGE * 3 * (size / 224) ** 2
    peak = prof.device_peak_flops()
    mfu = (best * flops_img / peak) if peak else 0.0

    print(json.dumps({
        "metric": "resnet50_amp_o2_images_per_sec",
        "value": round(best, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.60, 4),  # north star: 60% MFU
        "extra": {"mfu": round(mfu, 4), "batch": best_batch, "size": size,
                  "device": getattr(jax.devices()[0], "device_kind", "?"),
                  "loss": best_loss},
    }))


if __name__ == "__main__":
    main()
