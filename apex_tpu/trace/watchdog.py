"""Hang watchdog: dump forensics when no step completes in time.

The failure mode crash handlers can't see: a rank wedged inside a
collective (a peer died, a deadlock, a stuck DMA) never raises — the
process sits in a device wait forever and the job burns chips silently.
The watchdog is a daemon thread armed by step-completion heartbeats
(``notify_step``, or automatically via a :class:`Tracer` subscription);
when ``deadline_s`` passes without one it:

- dumps every Python thread's stack (``sys._current_frames``) — the
  wedged frame names the blocking call;
- dumps the flight recorder (last N steps, in-flight span/collective);
- tags which ranks went silent: each rank of a multi-host
  ``parallel.launch`` run watches itself, so the rank field of the dump
  that fired IS the silent rank — collect the per-rank files
  (:func:`apex_tpu.trace.rank_path`) and the ranks that wrote a
  ``kind="watchdog"`` line are the wedged ones, the ones that kept
  heartbeating are innocent.

The dump is JSONL in the same trace schema
(``scripts/check_metrics_schema.py --kind trace``).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from apex_tpu.trace.recorder import FlightRecorder, _rank, _process_count, \
    rank_path
from apex_tpu.trace.spans import Tracer

__all__ = ["HangWatchdog"]


def _thread_stacks() -> Dict[str, List[str]]:
    """Formatted stack per live thread, keyed "name (tid)"."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for tid, frame in sys._current_frames().items():
        key = f"{names.get(tid, '?')} ({tid})"
        out[key] = [l.rstrip() for l in traceback.format_stack(frame)]
    return out


class HangWatchdog:
    """Fire when no step completes within ``deadline_s``.

    ::

        wd = trace.HangWatchdog(deadline_s=300, recorder=recorder,
                                path="dumps/hang.jsonl")
        wd.start()
        for i, batch in enumerate(data):
            state, loss = train_step(state, batch)
            wd.notify_step(i)            # or tracer-driven via on_step
        wd.stop()

    Fires at most once per stall (re-arms when heartbeats resume);
    ``on_fire`` (called with the dump dict) hooks alerting. The thread is
    a daemon — it never blocks interpreter exit.

    ``on_stall`` is the *escalation* hook — typically an
    :class:`apex_tpu.ckpt.EscalationPolicy` — invoked AFTER the hang
    dump is written: it may save the last host checkpoint snapshot and
    hard-exit the process (``os._exit``), turning a silent wedged rank
    into a restartable failure instead of an indefinite hang
    (docs/checkpointing.md §escalation). Unlike ``on_fire`` (alerting;
    exceptions swallowed), an ``on_stall`` that exits is the intended
    behavior.
    """

    def __init__(self, deadline_s: float = 300.0, *,
                 recorder: Optional[FlightRecorder] = None,
                 tracer: Optional[Tracer] = None,
                 path: Optional[str] = None,
                 on_fire: Optional[Callable[[Dict], None]] = None,
                 on_stall: Optional[Callable[[Dict], None]] = None,
                 poll_s: Optional[float] = None):
        self.deadline_s = float(deadline_s)
        self.recorder = recorder
        self.tracer = tracer
        if tracer is not None:
            tracer.subscribe(lambda st: self.notify_step(st.step))
        if path is None and recorder is not None:
            # recorder.path is already per-rank; suffixing it again
            # would double the rank tag
            root, ext = os.path.splitext(recorder.path)
            self.path = f"{root}.hang{ext or '.jsonl'}"
        else:
            self.path = rank_path(path) if path else None
        self.on_fire = on_fire
        self.on_stall = on_stall
        self.poll_s = poll_s if poll_s is not None else \
            max(self.deadline_s / 10.0, 0.05)
        self._beat = time.monotonic()
        self._last_step: Optional[int] = None
        self._fired_for_beat: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fire_count = 0
        #: early-warning tier (straggler detection): the last warning
        #: event received and how many arrived — see :meth:`early_warning`
        self.last_warning: Optional[Dict] = None
        self.warning_count = 0

    # -- heartbeat -----------------------------------------------------------

    def notify_step(self, step: Optional[int] = None) -> None:
        """Mark a completed step — re-arms the deadline (thread-safe)."""
        self._last_step = step
        self._beat = time.monotonic()

    def early_warning(self, event: Dict) -> None:
        """The tier BELOW the hard deadline: a peer subsystem (the
        straggler detector, :class:`apex_tpu.trace.StragglerWatch`)
        reports degraded-but-alive progress. Records the event and
        invokes the ``on_fire`` alerting hook (tagged
        ``reason="early-warning"``) — never ``on_stall``: steps are
        still landing, so escalation (checkpoint + exit) would turn a
        slow run into a dead one. Thread-safe, never raises."""
        self.last_warning = dict(event)
        self.warning_count += 1
        if self.on_fire is not None:
            try:
                self.on_fire(dict(event, reason="early-warning"))
            except Exception:
                pass

    def lease_expired(self, event: Dict) -> None:
        """Peer-death tier (docs/resilience.md#straggler): a PEER
        rank's cluster lease expired — this rank is healthy but a
        member it collectives with is dead (or paused long enough to
        be fenced as dead). Alerting-only, like :meth:`early_warning`
        (tagged ``tier="lease-expiry"``): the coordinated response —
        shrink + generation bump — belongs to the recovery layer
        (:class:`apex_tpu.cluster.RecoveryCoordinator` /
        ``elastic_run``'s relaunch hygiene), not to a per-rank
        watchdog; escalating every survivor here would turn one dead
        rank into a pod-wide exit storm before the coordinator could
        agree on a checkpoint. Wire it as the ``ClusterMembership``
        caller's hook on :meth:`~apex_tpu.cluster.ClusterMembership.
        expired_ranks` observations. The wedged-collective case (this
        rank BLOCKED on the dead peer) is the hard deadline's job —
        :class:`apex_tpu.cluster.CollectiveDeadline` names the
        collective and does escalate."""
        self.early_warning(dict(event, tier="lease-expiry"))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HangWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, name="apex_tpu.trace.watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(self.poll_s * 2, 1.0))
        self._thread = None

    def __enter__(self) -> "HangWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the watchdog loop ---------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            beat = self._beat
            idle = time.monotonic() - beat
            if idle < self.deadline_s:
                continue
            if self._fired_for_beat == beat:
                continue                    # already reported this stall
            self._fired_for_beat = beat
            try:
                self.fire(idle_s=idle)
            except Exception:
                pass          # a broken dump must not kill the daemon

    def fire(self, idle_s: Optional[float] = None) -> Dict:
        """Collect + write the hang dump (also callable manually)."""
        self.fire_count += 1
        event: Dict = {
            "kind": "watchdog", "reason": "hang",
            "rank": _rank(), "process_count": _process_count(),
            "silent_ranks": [_rank()],    # self-watch: the firing rank
            "pid": os.getpid(), "wall_time": time.time(),
            "deadline_s": self.deadline_s,
            "seconds_since_last_step": (
                idle_s if idle_s is not None
                else time.monotonic() - self._beat),
            "last_step": self._last_step,
            "last_completed_span": (
                self.recorder.last_completed_span if self.recorder
                else (self.tracer.last_completed_span
                      if self.tracer else None)),
            "in_flight_spans": (self.tracer.open_spans
                                if self.tracer is not None else []),
            "in_flight_collective": (self.tracer.in_flight_collective
                                     if self.tracer is not None else None),
            "stacks": _thread_stacks(),
        }
        if self.path:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "w") as f:
                f.write(json.dumps(event) + "\n")
            if self.recorder is not None:
                # append the flight record to the same file for one-stop
                # forensics (ring steps after the watchdog header).
                # fetch_metrics=False: the runtime is by definition
                # wedged when a hang fires, and a device_get against a
                # hung runtime blocks forever — host-side span timings
                # still land; the metric values are the one casualty
                with open(self.path, "a") as f:
                    self.recorder.dump_records(f, event["rank"],
                                               fetch_metrics=False)
        if self.on_fire is not None:
            try:
                self.on_fire(event)
            except Exception:
                pass
        if self.on_stall is not None:
            # escalation LAST, after the hang dump is safely on disk:
            # an exit-mode policy never returns (checkpoint-save →
            # crash-dump → os._exit — the designed shrink-and-continue
            # trigger), and a raise-mode policy invoked on this daemon
            # thread completes the save/dump and records its `tripped`
            # flag instead of raising (a raise here could not unwind
            # the wedged main thread; _loop's guard would swallow it).
            self.on_stall(event)
        return event
