"""CheckpointManager: the save/restore orchestrator.

Glues the three layers together — donation-safe async capture
(:mod:`.snapshot`), the crash-safe one-file-per-process format
(:mod:`.format`), and elastic ZeRO re-partitioning (:mod:`.elastic`) —
and emits the ``ckpt`` JSONL event channel
(``check_metrics_schema.py --kind ckpt``).

::

    mgr = ckpt.CheckpointManager("ckpts", keep=2,
                                 event_sink=logger.record_ckpt)
    for i, batch in enumerate(source.batches(...)):
        state = train_step(state, batch)            # donated
        if i % save_every == 0:
            mgr.save(i, state, params=params0,
                     extra={"cursor": source.state()})
    mgr.wait()

    # elastic resume — on any mesh shape:
    like = build_state_on_new_mesh()
    state, manifest = mgr.restore(like)
    cursor = manifest["extra"]["cursor"]

``save`` costs the step path only the device-copy dispatch (the
``ckpt_save_stall_ms`` bench column); the host fetch, serialization,
hashing and the temp-then-rename commit all happen on the snapshot
worker thread. ``save_last_snapshot`` is the escalation entry point: it
durably writes the newest already-fetched host snapshot without ever
touching the (possibly wedged) device — see
:class:`apex_tpu.ckpt.EscalationPolicy`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from apex_tpu.ckpt import elastic as _elastic
from apex_tpu.ckpt import format as _format
from apex_tpu.ckpt.format import CheckpointError
from apex_tpu.ckpt.snapshot import (HostSnapshot, Snapshotter,
                                    device_snapshot, is_prng_key,
                                    tree_paths)

__all__ = ["CheckpointManager"]


def _rank() -> int:
    import jax
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", "0"))


def _process_count() -> int:
    import jax
    try:
        return jax.process_count()
    except Exception:
        return int(os.environ.get("WORLD_SIZE", "1"))


class CheckpointManager:
    """See the module docstring.

    ``event_sink`` is any callable taking one JSON-able dict (wire
    ``MetricsLogger(ckpt_sink=...)`` via ``logger.record_ckpt``);
    ``keep`` bounds retention (rank 0 deletes older committed
    checkpoints after each commit); ``meta`` statics land in every
    manifest (mesh shape, run tags, ...).

    ``fence`` (an :class:`apex_tpu.cluster.ClusterMembership`, or any
    object with ``generation`` + ``check(what, *, path, step)``)
    generation-fences every mutation: data-file writes, the manifest
    commit, and retention deletes all validate the fence token against
    the cluster's committed generation first, so a zombie of a
    previous incarnation is refused (``StaleGenerationError``, after a
    ``cluster_fence`` event) instead of corrupting the successor run's
    checkpoints. The manifest records the committing generation, and
    every ``ckpt_save``/``ckpt_restore``/``ckpt_escalation`` event
    carries it as the ``generation`` field. ``rank``/``process_count``
    override the jax-derived defaults — a per-rank local checkpoint
    tree (each rank its own root, its own single-writer commit) passes
    ``rank=0, process_count=1`` regardless of the pod shape.
    """

    def __init__(self, root: str, *, keep: int = 2,
                 event_sink: Optional[Callable[[Dict], None]] = None,
                 meta: Optional[Dict] = None,
                 barrier_timeout_s: float = 120.0,
                 fence=None,
                 rank: Optional[int] = None,
                 process_count: Optional[int] = None):
        self.root = root
        self.keep = int(keep)
        self.event_sink = event_sink
        self.meta = dict(meta or {})
        self.barrier_timeout_s = float(barrier_timeout_s)
        self.fence = fence
        self.rank = _rank() if rank is None else int(rank)
        self.process_count = (_process_count() if process_count is None
                              else int(process_count))
        self._snap = Snapshotter(on_ready=self._write_snapshot)
        self._pending_zero: Dict[str, int] = {}
        self._last_committed: Optional[str] = None
        self.error: Optional[BaseException] = None
        # serializes _write between the snapshot worker and an
        # escalation thread — two writers racing on the same step dir
        # would interleave bytes under one manifest hash
        self._write_lock = threading.Lock()
        # zero_layout is static for a fixed (state structure, params):
        # cache it so the per-step snapshot() cadence never re-walks
        # the tree or re-plans the arena on the hot path
        self._zero_cache: Optional[Tuple[Any, Any, Dict[str, int]]] = None

    # -- events ----------------------------------------------------------------

    def _emit(self, event: Dict) -> None:
        if self.event_sink is None:
            return
        try:
            ev = _format.tag_generation(
                dict(event, rank=self.rank, wall_time=time.time()),
                self.fence)
            self.event_sink(ev)
        except Exception:
            pass                  # telemetry must never break a save

    # -- save ------------------------------------------------------------------

    def save(self, step: int, tree, *, params: Any = None,
             zero: Optional[Dict[str, int]] = None,
             extra: Optional[Dict] = None,
             block: bool = False) -> float:
        """Snapshot + asynchronously persist the training tuple.

        ``params`` (the tree the sharded optimizer was initialized
        from) lets the manager record each ZeRO slot buffer's logical
        length for elastic restore; pass ``zero=`` directly to override.
        Returns the step-path stall in ms (full duration when
        ``block=True``). Raises any error a previous async write hit.
        """
        self.raise_pending()
        self._pending_zero = self._layout_for(tree, params, zero)
        return self._snap.capture(step, tree, extra=extra, block=block)

    def snapshot(self, step: int, tree, *, params: Any = None,
                 zero: Optional[Dict[str, int]] = None,
                 extra: Optional[Dict] = None) -> float:
        """Capture WITHOUT committing: refresh the host-side snapshot
        (what an escalation persists) at step cadence while actual disk
        commits run at a coarser ``save`` cadence — the cheap half of
        the snapshot-every-step / commit-every-N pattern
        (docs/checkpointing.md §escalation). Returns the stall in ms.
        """
        self.raise_pending()
        self._pending_zero = self._layout_for(tree, params, zero)
        return self._snap.capture(step, tree, extra=extra,
                                  persist=False)

    def _layout_for(self, tree, params, zero) -> Dict[str, int]:
        """The manifest's ZeRO layout map, cached per (state structure,
        params object) — static across steps, so the per-step
        ``snapshot`` cadence never re-walks the tree or re-plans the
        arena on the step path."""
        import jax
        if zero is not None:
            return dict(zero)
        td = jax.tree_util.tree_structure(tree)
        if (self._zero_cache is not None
                and self._zero_cache[0] == td
                and self._zero_cache[1] is params):
            return self._zero_cache[2]
        layout = _elastic.zero_layout(tree, params=params)
        self._zero_cache = (td, params, layout)
        return layout

    def _write_snapshot(self, snap: HostSnapshot) -> None:
        if not snap.persist:
            return                 # capture-only (snapshot() cadence)
        try:
            self._write(snap, wait_for_ranks=True)
        except BaseException as e:     # surfaced on the next save/wait
            self.error = e

    def _write(self, snap: HostSnapshot, *, wait_for_ranks: bool,
               reason: str = "periodic",
               lock_timeout_s: Optional[float] = None) -> Optional[str]:
        t0 = time.perf_counter()
        # serialize writers: the snapshot worker and an escalation
        # thread persisting the SAME HostSnapshot would otherwise race
        # on one step dir (interleaved tmp bytes under one manifest
        # hash). The escalation path bounds its wait — if the worker is
        # wedged on the multi-rank commit barrier (dead peers), waiting
        # longer is futile and the previous cooperative checkpoint is
        # the restore point.
        acquired = self._write_lock.acquire(
            timeout=lock_timeout_s if lock_timeout_s is not None
            else -1)
        if not acquired:
            return None
        try:
            d = _format.step_dir(self.root, snap.step)
            if os.path.exists(os.path.join(d, _format.MANIFEST)):
                return d           # this step already committed
            leaves = tree_paths(snap.tree)
            rec = _format.write_process_file(d, self.rank, leaves,
                                             fence=self.fence)
            if self.rank == 0:
                _format.commit_manifest(
                    d, step=snap.step,
                    process_count=self.process_count,
                    meta=dict(self.meta, reason=reason),
                    zero=self._pending_zero, extra=snap.extra,
                    prng_impls=snap.prng_impls,
                    wait_for_ranks=wait_for_ranks,
                    barrier_timeout_s=self.barrier_timeout_s,
                    fence=self.fence)
                self._last_committed = d
                # retention runs only after COOPERATIVE commits: a
                # lone-rank escalation manifest may cover only this
                # rank's leaves, and letting it gc the last
                # fully-committed checkpoint would destroy the very
                # fallback its own restore error points at
                if self.keep > 0 and wait_for_ranks:
                    _format.gc_checkpoints(self.root, self.keep,
                                           fence=self.fence)
        finally:
            self._write_lock.release()
        self._emit({
            "kind": "ckpt_save", "step": snap.step, "path": d,
            "reason": reason, "bytes": int(rec.get("bytes", 0)),
            "n_arrays": len(rec.get("arrays", [])),
            "stall_ms": round(snap.stall_ms, 3),
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
        })
        if (self.rank != 0 and not wait_for_ranks
                and not os.path.exists(os.path.join(d,
                                                    _format.MANIFEST))):
            # a lone-rank escalation on a non-zero rank wrote its data
            # file but nothing will ever commit the manifest (rank 0 is
            # the dead/preempted one) — don't report a checkpoint path
            # that latest_checkpoint()/restore() can never find
            return None
        return d

    def save_last_snapshot(self, reason: str = "escalation"
                           ) -> Optional[str]:
        """Durably persist the newest fetched host snapshot — the
        escalation path. Never touches the device (a wedged runtime
        cannot block it) and never waits for peer ranks (they may be
        dead); the manifest commits with whatever files exist, and
        restore's coverage check decides usability. Returns the
        checkpoint dir, or None when no snapshot ever finished."""
        snap = self._snap.last
        if snap is None:
            return None
        try:
            return self._write(snap, wait_for_ranks=False,
                               reason=reason, lock_timeout_s=15.0)
        except BaseException:
            return None

    def wait(self) -> None:
        """Drain the in-flight snapshot + write; raise its error."""
        self._snap.wait()
        self.raise_pending()

    def raise_pending(self) -> None:
        if self.error is not None:
            err, self.error = self.error, None
            raise err

    @property
    def last_host_snapshot(self) -> Optional[HostSnapshot]:
        return self._snap.last

    # -- discovery -------------------------------------------------------------

    def latest(self) -> Optional[str]:
        return _format.latest_checkpoint(self.root)

    def all_steps(self):
        return _format.committed_steps(self.root)

    # -- restore ---------------------------------------------------------------

    def restore(self, like, *, ckpt_dir: Optional[str] = None,
                verify: bool = True,
                io_deadline_s: float = 30.0) -> Tuple[Any, Dict]:
        """Load the newest committed checkpoint into the structure (and
        onto the mesh) of ``like``.

        ``like`` is a freshly-initialized state tree on the TARGET mesh
        — its shapes and shardings define where every leaf lands:
        replicated leaves must match shape exactly; ZeRO slot buffers
        (named in the manifest's ``zero`` map) are gathered, truncated
        to their logical length, re-padded to the like leaf's length and
        re-scattered with its sharding — the elastic 8→4 (or 4→8) path.
        Returns ``(tree, manifest)``; the data-pipeline cursor and any
        other save-time ``extra`` ride in ``manifest["extra"]``.
        ``io_deadline_s`` bounds each data-file read of the gather
        (jittered retries inside it) — an elastic relaunch must refuse
        with the file named rather than hang on one stuck shared-fs
        read.
        """
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        d = ckpt_dir or self.latest()
        if d is None:
            raise CheckpointError(
                f"no committed checkpoint under {self.root!r} — nothing "
                f"to restore (a crash before the first commit leaves "
                f"only partial step_* dirs, which are not checkpoints)")
        # pin the directory for the whole read — manifest included: a
        # concurrent gc_checkpoints(keep=N) on another rank must not
        # delete it mid-read (the marker is advisory, TTL'd, and
        # refreshed while held; see format.checkpoint_in_use)
        with _format.checkpoint_in_use(d, self.rank):
            manifest = _format.read_manifest(d)
            flat = jax.tree_util.tree_flatten_with_path(like)
            want = [jax.tree_util.keystr(p) for p, _ in flat[0]]
            loaded = _format.assemble_arrays(d, manifest, paths=want,
                                             verify=verify,
                                             io_deadline_s=io_deadline_s)
        zero = manifest.get("zero", {})
        impls = manifest.get("prng_impls", {})
        resharded = 0
        out_leaves = []
        for (path, leaf), pstr in zip(flat[0], want):
            val = loaded[pstr]
            if is_prng_key(leaf):
                # compare/restore via the raw key_data view — the typed
                # key's own shape hides the trailing impl lanes
                kd_shape = tuple(jax.random.key_data(leaf).shape)
                if tuple(val.shape) != kd_shape:
                    raise CheckpointError(
                        f"PRNG key data shape mismatch for {pstr}: "
                        f"checkpoint has {tuple(val.shape)}, target key "
                        f"expects {kd_shape}")
                val = jax.random.wrap_key_data(
                    jnp.asarray(val),
                    impl=impls.get(pstr) or "threefry2x32")
                if hasattr(leaf, "sharding"):
                    val = jax.device_put(val, leaf.sharding)
                out_leaves.append(val)
                continue
            if pstr in zero:
                tgt_len = (int(np.prod(np.shape(leaf)))
                           if np.ndim(leaf) == 1 else -1)
                if np.ndim(leaf) != 1:
                    raise CheckpointError(
                        f"{pstr} is recorded as a ZeRO slot buffer but "
                        f"the like leaf is not 1-D ({np.shape(leaf)})")
                if tuple(val.shape) != (tgt_len,):
                    resharded += 1
                val = _elastic.repartition_flat(val, int(zero[pstr]),
                                                tgt_len)
            elif tuple(val.shape) != tuple(np.shape(leaf)):
                raise CheckpointError(
                    f"shape mismatch for {pstr}: checkpoint has "
                    f"{tuple(val.shape)}, target expects "
                    f"{tuple(np.shape(leaf))} — only ZeRO slot buffers "
                    f"reshape across meshes; did the model change?")
            if isinstance(leaf, jax.Array):
                want_dt = np.dtype(leaf.dtype)
                if np.dtype(val.dtype) != want_dt:
                    raise CheckpointError(
                        f"dtype mismatch for {pstr}: checkpoint "
                        f"{val.dtype}, target {want_dt}")
                val = jax.device_put(val, leaf.sharding)
            out_leaves.append(val)
        tree = jax.tree_util.tree_unflatten(flat[1], out_leaves)
        self._emit({
            "kind": "ckpt_restore", "step": int(manifest["step"]),
            "path": d, "n_arrays": len(out_leaves),
            "resharded": resharded,
            "from_processes": int(manifest.get("process_count", 1)),
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
        })
        return tree, manifest
