"""Per-op profile of the BERT-Large LAMB bench step (VERDICT r2 item 3).

Usage: python scripts/prof_bert.py [--batch N] [--seq N] [--top N]
           [--lint]

``--lint`` runs apexlint over the exact jitted step being profiled and
fails (exit 1) on any error-severity finding — the donation audit that
keeps this driver honest: the step carries the whole AmpState (fp32
params + LAMB m/v slots) in argnum 0, and donating it is what keeps
opt state from being re-allocated every step (apexlint APX101 flags
the miss, and quantifies the wasted HBM, if the donation ever drops).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np


def main():
    batch = 16
    seq = 512
    top = 30
    argv = sys.argv
    if "--batch" in argv:
        batch = int(argv[argv.index("--batch") + 1])
    if "--seq" in argv:
        seq = int(argv[argv.index("--seq") + 1])
    if "--top" in argv:
        top = int(argv[argv.index("--top") + 1])

    from apex_tpu import prof
    import bench

    # the ONE construction of this step (bench row + apexlint flagship
    # share it — see bench._bert_step_builder)
    step, state, (toks, labels), policy, enc, variables = \
        bench._bert_step_builder(batch, seq)

    import tempfile
    import time

    # donate the FULL carried state (argnum 0 = AmpState: fp32 params,
    # LAMB m/v arena slots, scalers) — apexlint's donation rule audits
    # this aliasing from the compiled HLO (--lint below / docs/linting.md)
    jstep = jax.jit(step, donate_argnums=(0,))
    from apex_tpu.prof import hlo as _hlo
    # ONE AOT compile feeds the cost analysis AND (under --lint) the
    # lint HLO pass — BERT-Large compiles are minutes-class, never twice
    compiled = jstep.lower(state, toks, labels).compile()
    ca = _hlo.cost_analysis_of(compiled)
    cost = {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}

    if "--lint" in argv:
        from apex_tpu import lint
        rep = lint.lint_step(jstep, state, toks, labels, policy=policy,
                             compiled=compiled, fn_name="prof_bert_step")
        print(rep.table())
        if rep.errors:
            sys.exit(1)
    for _ in range(3):
        state, loss = jstep(state, toks, labels)
    float(loss)

    iters = 5
    logdir = tempfile.mkdtemp(prefix="apex_tpu_prof_bert_")
    t0 = time.perf_counter()
    with prof.trace(logdir):
        for _ in range(iters):
            state, loss = jstep(state, toks, labels)
        float(loss)
    wall = (time.perf_counter() - t0) / iters

    from apex_tpu.prof import xplane as _xplane
    profile = _xplane.parse_trace(logdir)
    dev_us = (profile.module_total_us / profile.module_runs
              if profile.module_runs else wall * 1e6)
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(variables["params"]))
    model_flops = 6.0 * n_params * batch * seq
    print(f"batch={batch} seq={seq} params={n_params/1e6:.1f}M")
    print(f"wall/iter={wall*1e6:.0f}us device/iter={dev_us:.0f}us "
          f"xla_flops={cost['flops']:.3g} "
          f"model_flops={model_flops:.3g} "
          f"bytes={cost['bytes_accessed']:.3g}")
    cats = "  ".join(f"{k}={v:.0f}us"
                     for k, v in list(profile.by_category().items())[:8])
    print(cats)
    print(profile.table(top=top))
    peak = prof.device_peak_flops() or float("inf")
    print("model-flops MFU:", model_flops / (dev_us * 1e-6) / peak)
    print("seq/s:", batch / (dev_us * 1e-6))


if __name__ == "__main__":
    main()
