"""NaN provenance: name the first span whose outputs went non-finite.

``jax.config.jax_debug_nans`` localizes a NaN to an *HLO op*, at the
cost of disabling async dispatch and rerunning un-jitted. This mode is
coarser and cheaper: opt-in per-span finiteness probes
(``jax.debug.callback``) that ride the compiled step and report, on the
host, the first *span* ("amp/bwd", "fp16/unscale", your own
``@trace.span`` functions) whose outputs contained a NaN/Inf — enough to
know which phase of the step to bisect, from a live or crashed run.

Contract (the ``trace/no-extra-dispatch`` compile-check case): with the
mode OFF, :func:`nan_probe` returns its input untouched — the compiled
program is bit-identical to an unprobed one, zero extra dispatches or
host traffic. The flag is read at *trace* time, so enable it BEFORE the
step first compiles (or use ``jax.clear_caches()`` / a fresh jit) — the
same build-per-flag caveat as ``DistributedDataParallel.no_sync``.

Usage::

    with trace.debug_nans():
        jstep = jax.jit(step)              # compiled WITH probes
        for batch in data:
            state, loss = jstep(state, batch)
            hit = trace.first_nan()
            if hit is not None:
                raise FloatingPointError(f"non-finite in {hit['span']}")
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["debug_nans", "debug_nans_enabled", "nan_probe", "first_nan",
           "reset_nan_state"]

_enabled = False
_lock = threading.Lock()
#: first non-finite report since the last reset: {"span": name, "count": n}
_first: Optional[Dict[str, Any]] = None
_probe_serial = 0


def debug_nans_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def debug_nans(enable: bool = True):
    """Enable per-span finiteness probes for steps traced inside."""
    global _enabled
    prev = _enabled
    _enabled = bool(enable)
    try:
        yield
    finally:
        _enabled = prev


def reset_nan_state() -> None:
    """Forget any recorded non-finite hit (e.g. at each step boundary)."""
    global _first
    with _lock:
        _first = None


def first_nan() -> Optional[Dict[str, Any]]:
    """The first recorded non-finite span since the last reset, or None.

    ``{"span": name, "order": probe-serial}`` — "first" means the
    earliest probe (in program order) that observed a non-finite value;
    with jax's async dispatch the callback may land after the step call
    returns, so fetch a step output (or ``jax.block_until_ready``)
    before trusting a ``None``.
    """
    with _lock:
        return dict(_first) if _first is not None else None


def _report(name: str, order: int, ok) -> None:
    global _first
    if bool(ok):
        return
    with _lock:
        # program order, not callback arrival order, decides "first"
        if _first is None or order < _first["order"]:
            _first = {"span": name, "order": order}


def _make_cb(name: str, order: int):
    # name/order are trace-time statics — closed over, since
    # jax.debug.callback only ships array arguments to the host
    def cb(ok):
        _report(name, order, ok)
    return cb


def nan_probe(name: str, tree: Any) -> Any:
    """Probe a pytree for finiteness under the debug_nans mode.

    Mode off (the default): returns ``tree`` unchanged — adds nothing to
    the program. Mode on: reduces every inexact leaf to one ``all
    finite`` scalar and attaches a ``jax.debug.callback`` that records
    this span's name on the host when the check fails. The value itself
    passes through either way, so probes drop into any expression:
    ``grads = nan_probe("amp/bwd", grads)``.
    """
    if not _enabled:
        return tree
    global _probe_serial
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype")
              and jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    if not leaves:
        return tree
    ok = jnp.bool_(True)
    for l in leaves:
        # isfinite in the leaf's own dtype: a float32 downcast would
        # overflow finite float64 values (x64 mode) into false positives
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(l)))
    _probe_serial += 1
    jax.debug.callback(_make_cb(name, _probe_serial), ok)
    return tree
