"""apex_tpu.guard — self-healing training.

Three layers close the detect→recover→prove loop (docs/resilience.md):

- **in-graph detection** (:mod:`~apex_tpu.guard.detect`): a
  :class:`GuardState` pytree carried through the jitted step — rolling
  robust-z loss-spike detection, grad-norm explosion flags, nonfinite
  grad/loss/param probes, and an amp-style LR-backoff schedule — all
  pure ``jnp`` with zero extra dispatches (the
  ``guard/no-extra-dispatch`` compile-check case); skip-class anomalies
  never commit (:func:`guard_commit`, amp's overflow skip generalized).
- **silent-divergence defense** (:mod:`~apex_tpu.guard.integrity`):
  cross-replica integrity fingerprints — each replica folds its
  committed params into one order-independent uint32 scalar, compared
  across the dp axis with pmin/pmax inside the jitted step — catch the
  fault class every loud probe misses (a finite flipped bit, a buggy
  compressed collective); a quorum vote names the minority and the
  policy repairs it IN PLACE with a bit-exact broadcast from the
  majority (``scripts/integrity_audit.py --cpu8`` is the asserted
  proof; docs/resilience.md#integrity).
- **the policy ladder** (:mod:`~apex_tpu.guard.policy`):
  :class:`GuardPolicy` escalates per anomaly class with hysteresis and
  budgets — in-graph skip/backoff → in-place **repair** of a
  quorum-named diverged replica → **rewind** to the last good
  :mod:`apex_tpu.ckpt` snapshot with the :mod:`apex_tpu.data` cursor
  fast-forwarded past the offending window (bitwise-equal to a run that
  never saw those batches) → hand-off to
  :class:`apex_tpu.ckpt.EscalationPolicy` (checkpoint + dump + exit 75).
- **deterministic chaos** (:mod:`~apex_tpu.guard.chaos`): a seeded,
  replayable :class:`FaultPlan` keyed by (step, rank, site) injecting
  NaN/Inf grads, poisoned batches, param bit-flips, overflow storms,
  stalled collectives, SIGKILL and truncated checkpoints — consumed by
  ``tests/test_guard.py`` and the asserted
  ``scripts/chaos_audit.py --cpu8`` soak.
"""

from apex_tpu.guard import chaos, integrity
from apex_tpu.guard.chaos import (ChaosHarness, Fault, FaultPlan,
                                  inject_activation, inject_grads)
from apex_tpu.guard.detect import (A_GRAD_EXPLOSION, A_LOSS_SPIKE,
                                   A_NONFINITE_GRAD, A_NONFINITE_LOSS,
                                   A_NONFINITE_PARAM,
                                   A_REPLICA_DIVERGENCE,
                                   ANOMALY_CLASSES,
                                   LR_BACKOFF_MASK, REWIND_MASK,
                                   SKIP_MASK, GuardConfig, GuardState,
                                   anomaly_classes, guard_commit,
                                   guard_init, guard_observe, guard_ok)
from apex_tpu.guard.integrity import (IntegrityConfig, IntegrityState,
                                      IntegrityVote, absorb_verify,
                                      fingerprint_tree,
                                      integrity_check, integrity_commit,
                                      integrity_init, integrity_ok,
                                      integrity_resize,
                                      make_repair_fn, make_verify_fn,
                                      vote)
from apex_tpu.guard.policy import (GuardAction, GuardEscalation,
                                   GuardPolicy)

__all__ = [
    "GuardConfig", "GuardState", "guard_init", "guard_observe",
    "guard_ok", "guard_commit", "anomaly_classes", "ANOMALY_CLASSES",
    "A_LOSS_SPIKE", "A_GRAD_EXPLOSION", "A_NONFINITE_GRAD",
    "A_NONFINITE_LOSS", "A_NONFINITE_PARAM", "A_REPLICA_DIVERGENCE",
    "SKIP_MASK", "REWIND_MASK", "LR_BACKOFF_MASK",
    "GuardPolicy", "GuardAction", "GuardEscalation",
    "IntegrityConfig", "IntegrityState", "IntegrityVote",
    "integrity_init", "integrity_check", "integrity_ok",
    "integrity_commit", "integrity_resize", "fingerprint_tree",
    "vote", "absorb_verify",
    "make_repair_fn", "make_verify_fn", "integrity",
    "FaultPlan", "Fault", "ChaosHarness", "chaos",
    "inject_grads", "inject_activation",
]
