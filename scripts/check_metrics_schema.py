#!/usr/bin/env python
"""Validate a metrics JSONL stream emitted by apex_tpu.monitor.JSONLSink.

The wire-format contract (keep in lockstep with
``apex_tpu/monitor/sinks.py`` / ``logger.py``):

- every line is a standalone JSON object;
- the REQUIRED keys are present on every line;
- ``step`` is a strictly increasing integer (the in-graph counter
  counts *attempted* steps, so the stream is monotonic even across
  overflow-skipped updates);
- counters are non-negative integers;
- every numeric value is finite — Infinity/NaN never reach the wire
  (the logger nulls non-finite gauges); ``null`` is allowed only for
  the NULLABLE gauges (first-record step time, unknown-chip MFU, ...).

Pure stdlib on purpose: CI and log-shipping hosts can run it without
jax. Exit status 0 = valid, 1 = violations (printed one per line),
2 = usage/IO error.

Usage: python scripts/check_metrics_schema.py METRICS.jsonl
"""

from __future__ import annotations

import json
import math
import sys
from typing import List

REQUIRED = (
    "step", "loss", "loss_scale", "grad_norm", "param_norm",
    "overflow_count", "skip_count", "growth_count", "backoff_count",
    "step_time_ms", "throughput_steps_per_s", "mfu",
)
COUNTERS = ("step", "overflow_count", "skip_count", "growth_count",
            "backoff_count")
NULLABLE = ("step_time_ms", "throughput_steps_per_s", "mfu",
            "collective_bytes", "loss", "grad_norm", "param_norm")


def check_lines(lines) -> List[str]:
    """All schema violations in an iterable of JSONL lines (empty = ok)."""
    errors: List[str] = []
    prev_step = None
    n_records = 0
    for i, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            errors.append(f"line {i}: not valid JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"line {i}: not a JSON object")
            continue
        n_records += 1
        for key in REQUIRED:
            if key not in rec:
                errors.append(f"line {i}: missing required key {key!r}")
        for key, v in rec.items():
            if v is None:
                if key not in NULLABLE:
                    errors.append(f"line {i}: {key!r} is null "
                                  f"(only {NULLABLE} may be)")
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            if not math.isfinite(v):
                errors.append(f"line {i}: {key!r} is non-finite ({v!r})")
        for key in COUNTERS:
            v = rec.get(key)
            if v is None or key not in rec:
                continue
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"line {i}: counter {key!r} must be a "
                              f"non-negative int, got {v!r}")
        step = rec.get("step")
        if isinstance(step, int) and not isinstance(step, bool):
            if prev_step is not None and step <= prev_step:
                errors.append(f"line {i}: step {step} not greater than "
                              f"previous step {prev_step}")
            prev_step = step
    if n_records == 0:
        errors.append("no records found")
    return errors


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 2
    try:
        with open(argv[0]) as f:
            errors = check_lines(f)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"{argv[0]}: INVALID ({len(errors)} violations)",
              file=sys.stderr)
        return 1
    print(f"{argv[0]}: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
