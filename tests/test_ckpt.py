"""Elastic checkpoint/restore + fault escalation (apex_tpu.ckpt).

The ISSUE-6 acceptance suite: donation-safe async snapshots, the
manifest-last crash-safe commit (SIGKILL mid-save at every instrumented
crash point), elastic ZeRO resume on a smaller mesh (bitwise vs an
uninterrupted run), watchdog/SIGTERM escalation into
checkpoint-save → crash-dump → nonzero exit, and the kill-a-rank
2-process run that relaunches on half the devices.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import arena, ckpt, monitor, optim, parallel, trace

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --- snapshots ----------------------------------------------------------------

class TestSnapshot:
    def test_survives_donation(self):
        """The core donation-safety contract: a snapshot taken before a
        donating dispatch materializes the pre-dispatch values after
        the original buffers are invalidated."""
        w = jnp.arange(8.0, dtype=jnp.float32)

        @jax.jit
        def bump(w):
            return w + 1.0

        donating = jax.jit(lambda w: w * 2.0, donate_argnums=(0,))
        snap = ckpt.Snapshotter()
        snap.capture(0, {"w": w})
        _ = donating(w)                    # invalidates w's buffer
        snap.wait()
        assert snap.last is not None
        np.testing.assert_array_equal(snap.last.tree["w"],
                                      np.arange(8.0, dtype=np.float32))
        del bump

    def test_prng_key_roundtrip(self, tmp_path):
        key = jax.random.key(7)
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, {"rng": key, "x": jnp.float32(3.0)}, block=True)
        like = {"rng": jax.random.key(0), "x": jnp.float32(0.0)}
        restored, manifest = mgr.restore(like)
        assert jax.dtypes.issubdtype(restored["rng"].dtype,
                                     jax.dtypes.prng_key)
        np.testing.assert_array_equal(
            jax.random.key_data(restored["rng"]),
            jax.random.key_data(key))
        # the restored key DRAWS identically
        np.testing.assert_array_equal(
            np.asarray(jax.random.normal(restored["rng"], (4,))),
            np.asarray(jax.random.normal(key, (4,))))
        assert manifest["prng_impls"]

    def test_capture_only_snapshot_writes_nothing(self, tmp_path):
        root = str(tmp_path / "ck")
        mgr = ckpt.CheckpointManager(root)
        mgr.snapshot(5, {"w": jnp.ones(4)})
        mgr.wait()
        assert mgr.latest() is None
        assert mgr.last_host_snapshot.step == 5
        # ...but an escalation can persist it on demand
        path = mgr.save_last_snapshot("stall")
        assert path and mgr.latest() == path
        assert ckpt.read_manifest(path)["step"] == 5
        assert ckpt.read_manifest(path)["meta"]["reason"] == "stall"


# --- format: durable commit ---------------------------------------------------

class TestFormat:
    def test_roundtrip_mixed_dtypes(self, tmp_path):
        tree = {
            "f32": jnp.asarray(np.random.RandomState(0).randn(33, 7),
                               jnp.float32),
            "bf16": jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16),
            "i32": jnp.int32(42),
            "host": np.arange(5, dtype=np.int64),
        }
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(2, tree, extra={"cursor": {"epoch": 1, "batch": 7}},
                 block=True)
        like = jax.tree_util.tree_map(
            lambda v: (np.zeros_like(v) if isinstance(v, np.ndarray)
                       else jnp.zeros_like(v)), tree)
        restored, manifest = mgr.restore(like)
        for k in ("f32", "bf16", "i32"):
            got, want = np.asarray(restored[k]), np.asarray(tree[k])
            assert got.dtype == want.dtype, k
            np.testing.assert_array_equal(got, want, err_msg=k)
        np.testing.assert_array_equal(np.asarray(restored["host"]),
                                      tree["host"])
        assert manifest["extra"]["cursor"] == {"epoch": 1, "batch": 7}
        assert manifest["step"] == 2

    def test_latest_ignores_uncommitted_and_gc_keeps(self, tmp_path):
        root = str(tmp_path / "ck")
        mgr = ckpt.CheckpointManager(root, keep=2)
        for step in (1, 2, 3):
            mgr.save(step, {"w": jnp.float32(step)}, block=True)
        # keep=2: step 1 collected, 2+3 committed
        assert ckpt.committed_steps(root) == [2, 3]
        # a partial dir (no manifest) is invisible to latest()
        os.makedirs(os.path.join(root, "step_00000009"))
        assert ckpt.latest_checkpoint(root).endswith("step_00000003")

    def test_restore_mismatches_are_actionable(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, {"w": jnp.ones((4, 4))}, block=True)
        with pytest.raises(ckpt.CheckpointError, match="shape mismatch"):
            mgr.restore({"w": jnp.ones((2, 2))})
        with pytest.raises(ckpt.CheckpointError, match="missing required"):
            mgr.restore({"w": jnp.ones((4, 4)), "extra": jnp.ones(3)})
        with pytest.raises(ckpt.CheckpointError, match="nothing "
                           "to restore"):
            ckpt.CheckpointManager(str(tmp_path / "empty")).restore(
                {"w": jnp.ones(1)})

    def test_hash_verification_catches_corruption(self, tmp_path):
        root = str(tmp_path / "ck")
        mgr = ckpt.CheckpointManager(root)
        mgr.save(1, {"w": jnp.arange(64.0)}, block=True)
        d = mgr.latest()
        fpath = os.path.join(d, "proc00000.npz")
        data = bytearray(open(fpath, "rb").read())
        data[-20] ^= 0xFF
        open(fpath, "wb").write(bytes(data))
        with pytest.raises(ckpt.CheckpointError, match="hash mismatch"):
            mgr.restore({"w": jnp.zeros(64)})


# --- crash consistency: SIGKILL mid-save --------------------------------------

_KILL_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, sys.argv[2])
    import numpy as np
    from apex_tpu import ckpt
    mgr = ckpt.CheckpointManager(sys.argv[1])
    mgr.save(9, {"w": np.arange(4096, dtype=np.float32)}, block=True)
    sys.exit(3)   # unreachable: the crash env SIGKILLs us mid-save
""")


class TestCrashConsistency:
    @pytest.mark.parametrize("point", ["before_data_rename",
                                       "before_manifest"])
    def test_mid_save_kill_keeps_previous_loadable(self, tmp_path,
                                                   point):
        root = str(tmp_path / "ck")
        mgr = ckpt.CheckpointManager(root)
        tree = {"w": jnp.arange(4096, dtype=jnp.float32) * 2.0}
        mgr.save(1, tree, block=True)
        before = mgr.latest()

        env = dict(os.environ, APEX_TPU_CKPT_TEST_CRASH=point,
                   JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-c", _KILL_CHILD, root, _REPO_ROOT],
            env=env, capture_output=True, text=True, timeout=240)
        assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)

        assert mgr.latest() == before, \
            f"kill at {point} changed the committed checkpoint"
        restored, manifest = mgr.restore({"w": jnp.zeros(4096)},
                                         verify=True)
        assert manifest["step"] == 1
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))


# --- elastic: resume on a smaller mesh ----------------------------------------

def _opt():
    return optim.DistributedFusedAdam(lr=1e-2, weight_decay=0.01)


def _state_specs(opt):
    from apex_tpu.optim.distributed import ShardedOptState
    return ShardedOptState(
        count=P(), slots={n: {"float32": P("data")}
                          for n in opt.slot_names})


def _zero_train(mesh, params, gstack, steps, state=None):
    """``steps`` ZeRO-Adam steps on ``mesh`` from per-device dyadic
    grads (8 global slices combined into world local means — exact in
    fp32, so mesh size never changes the arithmetic)."""
    opt = _opt()
    world = mesh.shape["data"]
    per = 8 // world
    glocal = jax.tree_util.tree_map(
        lambda g: g.reshape(world, per, *g.shape[1:]).mean(axis=1),
        gstack)
    sspec = _state_specs(opt)
    if state is None:
        def body(p, g):
            g0 = jax.tree_util.tree_map(lambda x: x[0], g)
            s = opt.init(p)
            for _ in range(steps):
                p, s = opt.step(g0, s, p)
            return p, s
        f = jax.jit(jax.shard_map(body, mesh=mesh,
                                  in_specs=(P(), P("data")),
                                  out_specs=(P(), sspec),
                                  check_vma=False))
        return f(params, glocal)

    def body(p, g, s):
        g0 = jax.tree_util.tree_map(lambda x: x[0], g)
        for _ in range(steps):
            p, s = opt.step(g0, s, p)
        return p, s
    f = jax.jit(jax.shard_map(body, mesh=mesh,
                              in_specs=(P(), P("data"), sspec),
                              out_specs=(P(), sspec), check_vma=False))
    return f(params, glocal, state)


class TestElastic:
    def test_repartition_flat_units(self):
        buf = np.arange(12.0, dtype=np.float32)
        out = ckpt.repartition_flat(buf, 10, 20)
        assert out.shape == (20,)
        np.testing.assert_array_equal(out[:10], buf[:10])
        assert (out[10:] == 0).all()
        np.testing.assert_array_equal(ckpt.repartition_flat(buf, 10, 10),
                                      buf[:10])
        with pytest.raises(ValueError, match="cannot hold"):
            ckpt.repartition_flat(buf, 10, 8)
        with pytest.raises(ValueError, match="shorter than"):
            ckpt.repartition_flat(buf, 99, 128)

    def test_zero_layout_names_match_tree_paths(self, mesh8):
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(600, 1200), jnp.float32)}
        _, state = _zero_train(
            mesh8, params,
            {"w": jnp.zeros((8,) + params["w"].shape, jnp.float32)},
            steps=1)
        tree = {"opt": state}
        layout = ckpt.zero_layout(tree, params=params)
        names = {p for p, _ in
                 __import__("apex_tpu.ckpt.snapshot",
                            fromlist=["tree_paths"]).tree_paths(tree)}
        assert layout, "no ZeRO leaves found"
        assert set(layout) <= names, (set(layout) - names)
        spec = arena.plan(params)
        assert all(v == spec.partition("float32").buffer_len
                   for v in layout.values())
        # the optimizer's own layout helper agrees leaf-for-leaf
        assert _opt().checkpoint_layout(params) == {
            "float32": spec.partition("float32").buffer_len}

    def test_zero_state_requires_params_for_layout(self, mesh8, tmp_path):
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(600, 1200), jnp.float32)}
        _, state = _zero_train(
            mesh8, params,
            {"w": jnp.zeros((8,) + params["w"].shape, jnp.float32)},
            steps=1)
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        with pytest.raises(ValueError, match="pass\\s+params"):
            mgr.save(1, {"opt": state})

    def test_zero_resume_on_smaller_mesh_bitwise(self, mesh8, devices,
                                                 tmp_path):
        """The elasticity acceptance: train ZeRO on 8 devices, save,
        resume on 4 — bitwise-equal (params, master, m, v) to the
        uninterrupted 4-device run at the same program granularity,
        with dyadic grads making every collective sum exact."""
        mesh4 = Mesh(np.array(devices[:4]), ("data",))
        rng = np.random.RandomState(0)
        params = {"w1": jnp.asarray(rng.randn(600, 1200), jnp.float32),
                  "w2": jnp.asarray(rng.randn(257), jnp.float32)}
        gstack = {k: jnp.asarray(
            rng.randint(-64, 64, (8,) + v.shape).astype(np.float32)
            / 64.0) for k, v in params.items()}

        p8, s8 = _zero_train(mesh8, params, gstack, steps=2)
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        mgr.save(2, {"params": p8, "opt": s8}, params=params,
                 block=True)

        like_s4 = _zero_train(mesh4, params, gstack, steps=0)[1]
        like = {"params": jax.device_put(p8, NamedSharding(mesh4, P())),
                "opt": like_s4}
        restored, manifest = mgr.restore(like)
        assert manifest["step"] == 2

        # sanity: the restored buffers really are the smaller layout
        got = restored["opt"].slots["master"]["float32"]
        assert got.shape == like_s4.slots["master"]["float32"].shape
        assert got.shape[0] < s8.slots["master"]["float32"].shape[0]

        p4, s4 = _zero_train(mesh4, params, gstack, steps=2)
        p_el, s_el = _zero_train(mesh4, restored["params"], gstack,
                                 steps=1, state=restored["opt"])
        p_un, s_un = _zero_train(mesh4, p4, gstack, steps=1, state=s4)
        L = arena.plan(params).partition("float32").buffer_len
        for k in params:
            np.testing.assert_array_equal(
                np.asarray(p_el[k]), np.asarray(p_un[k]), err_msg=k)
        for slot in ("master", "m", "v"):
            np.testing.assert_array_equal(
                np.asarray(s_el.slots[slot]["float32"])[:L],
                np.asarray(s_un.slots[slot]["float32"])[:L],
                err_msg=slot)
        assert int(s_el.count) == int(s_un.count) == 3


# --- escalation ---------------------------------------------------------------

class TestEscalation:
    def test_watchdog_on_stall_saves_and_trips(self, tmp_path):
        root = str(tmp_path / "ck")
        mgr = ckpt.CheckpointManager(root)
        mgr.snapshot(4, {"w": jnp.arange(16.0)})
        mgr.wait()
        events = []
        policy = ckpt.EscalationPolicy(mgr, mode="raise",
                                       event_sink=events.append)
        wd = trace.HangWatchdog(deadline_s=0.2, poll_s=0.05,
                                path=str(tmp_path / "hang.jsonl"),
                                on_stall=policy)
        wd.start()
        try:
            deadline = time.monotonic() + 10.0
            while policy.tripped is None and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            wd.stop()
        assert policy.tripped == "stall"
        latest = ckpt.latest_checkpoint(root)
        assert latest is not None
        assert ckpt.read_manifest(latest)["step"] == 4
        assert os.path.exists(str(tmp_path / "hang.jsonl"))
        assert [e["kind"] for e in events] == ["ckpt_escalation"]
        assert events[0]["reason"] == "stall"

    def test_escalation_without_snapshot_still_exits_cleanly(self,
                                                             tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path / "ck"))
        policy = ckpt.EscalationPolicy(mgr, mode="raise")
        with pytest.raises(ckpt.PreemptionError):
            policy.on_stall({})
        assert mgr.latest() is None      # nothing to save, no wreckage

    def test_elastic_run_shrinks_then_succeeds(self):
        calls = []

        def train_fn(world, attempt):
            calls.append(world)
            if len(calls) == 1:
                raise ckpt.PreemptionError("stall")
            if len(calls) == 2:
                raise SystemExit(ckpt.ESCALATION_EXIT_CODE)
            return f"done@{world}"

        out = parallel.elastic_run(
            train_fn, world_sizes=parallel.shrink_schedule(8,
                                                           min_world=2))
        assert out == "done@2"
        assert calls == [8, 4, 2]
        # non-escalation exits propagate — escalation never masks bugs
        with pytest.raises(SystemExit):
            parallel.elastic_run(lambda w, a: (_ for _ in ()).throw(
                SystemExit(1)), world_sizes=[8, 4])

    def test_event_stream_validates_and_rejects_garbage(self, tmp_path):
        from scripts.check_metrics_schema import check_ckpt_lines
        root = str(tmp_path / "ck")
        path = tmp_path / "events.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], ckpt_sink=monitor.JSONLSink(str(path)))
        mgr = ckpt.CheckpointManager(root,
                                     event_sink=logger.record_ckpt)
        mgr.save(1, {"w": jnp.ones(8)}, block=True)
        mgr.restore({"w": jnp.zeros(8)})
        policy = ckpt.EscalationPolicy(mgr, mode="raise")
        with pytest.raises(ckpt.PreemptionError):
            policy.on_stall()
        logger.close()
        lines = path.read_text().splitlines()
        assert not check_ckpt_lines(lines)
        kinds = [json.loads(l)["kind"] for l in lines]
        assert kinds == ["ckpt_save", "ckpt_restore", "ckpt_escalation"]
        # negative twin: a malformed action must be rejected
        bad = json.dumps({"kind": "ckpt_escalation", "reason": "stall",
                          "action": "shrug"})
        assert check_ckpt_lines([bad])
        assert check_ckpt_lines(['{"kind": "ckpt_save", "step": 1}'])


# --- kill-a-rank: the end-to-end acceptance -----------------------------------
#
# Two launch processes × 4 virtual CPU devices = the 8-device mesh.
# This CPU backend forms the cluster but cannot run cross-process
# programs (the same limitation tests/test_trace.py works around), so
# the cross-rank sync point — the thing a dead host wedges forever on a
# real pod — is an explicit file barrier standing in for the collective;
# the watchdog/escalation machinery under test is exercised for real:
# rank 1 SIGKILLs itself mid-run, rank 0 wedges on the barrier, the
# HangWatchdog fires, the EscalationPolicy commits the last host
# snapshot (never touching the runtime), dumps, and exits 75 — and the
# job relaunches on a 4-device mesh from that checkpoint.

_RANK_CHILD = textwrap.dedent("""
    import os, signal, sys, time
    import jax
    from apex_tpu import _compat
    jax.config.update("jax_platforms", "cpu")
    _compat.request_cpu_devices(4)

    root, barrier_dir = sys.argv[1], sys.argv[2]
    from apex_tpu.parallel.launch import distributed_init
    distributed_init()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu import ckpt, parallel, trace

    # per-process 4-device data mesh over the ADDRESSABLE devices (this
    # backend cannot run cross-process programs; the cross-rank sync is
    # the file barrier below)
    mesh = Mesh(np.array(jax.local_devices()), ("data",))

    def beat(r, i):
        open(os.path.join(barrier_dir, f"beat_{r}_{i}"), "w").close()

    def wait_peer(r, i):
        p = os.path.join(barrier_dir, f"beat_{r}_{i}")
        while not os.path.exists(p):     # the "collective": blocks
            time.sleep(0.02)             # forever when the peer dies

    np_rng = np.random.RandomState(0)
    w = jnp.asarray(np_rng.randn(16, 1), jnp.float32)
    xg = np_rng.randn(32, 16).astype("float32")
    yg = np_rng.randn(32, 1).astype("float32")

    def step(w, x, y):
        g = jax.grad(lambda w: jnp.mean((x @ w - y) ** 2))(w)
        g = jax.lax.pmean(g, "data")
        return w - 0.1 * g, jnp.mean((x @ w - y) ** 2)

    spmd = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))

    mgr = ckpt.CheckpointManager(root, barrier_timeout_s=60)
    policy = ckpt.EscalationPolicy(mgr)          # mode="exit", code 75
    rec = trace.FlightRecorder(
        os.path.join(barrier_dir, "crash.jsonl"),
        escalation=policy).install()
    policy.recorder = rec
    wd = trace.HangWatchdog(deadline_s=4.0, poll_s=0.2,
                            recorder=rec, on_stall=policy).start()

    for i in range(1, 10):
        w, loss = spmd(w, xg, yg)
        float(np.asarray(loss))
        beat(rank, i)
        if rank == 1 and i == 3:
            print("RANK1 DYING", flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        wait_peer(1 - rank, i)     # the wedge point when the peer dies
        # snapshot only a GLOBALLY completed step — mirrors a real DDP
        # loop, where the wedge is inside the step's collective and the
        # last usable snapshot is the last step every rank finished
        mgr.snapshot(i, {"w": w, "i": jnp.int32(i)})
        if i == 1:
            mgr.save(1, {"w": w, "i": jnp.int32(i)}, block=True)
        wd.notify_step(i)
        print(f"STEP {i} rank {rank}", flush=True)
    print("FINISHED WITHOUT ESCALATION", flush=True)
""")

_RESUME_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    from apex_tpu import _compat
    jax.config.update("jax_platforms", "cpu")
    _compat.request_cpu_devices(4)
    jax.config.update("jax_default_matmul_precision", "highest")

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from apex_tpu import ckpt

    root = sys.argv[1]
    mesh = Mesh(np.array(jax.devices()), ("data",))
    rep = NamedSharding(mesh, P())

    np_rng = np.random.RandomState(0)
    xg = np_rng.randn(32, 16).astype("float32")
    yg = np_rng.randn(32, 1).astype("float32")

    def step(w, x, y):
        g = jax.grad(lambda w: jnp.mean((x @ w - y) ** 2))(w)
        g = jax.lax.pmean(g, "data")
        return w - 0.1 * g, jnp.mean((x @ w - y) ** 2)

    spmd = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))

    mgr = ckpt.CheckpointManager(root)
    like = {"w": jax.device_put(jnp.zeros((16, 1), jnp.float32), rep),
            "i": jax.device_put(jnp.int32(0), rep)}
    restored, manifest = mgr.restore(like)
    print("RESTORED_STEP", manifest["step"], int(restored["i"]),
          flush=True)
    w = restored["w"]
    for i in range(3):
        w, loss = spmd(w, xg, yg)
        print("LOSS", float(np.asarray(loss)).hex(), flush=True)
""")


def _env_2proc(port):
    return {
        **os.environ,
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
        "WORLD_SIZE": "2",
        "JAX_PLATFORMS": "cpu",
        "TF_CPP_MIN_LOG_LEVEL": "2",
    }


class TestKillARank:
    def test_kill_one_rank_escalates_and_resumes_on_smaller_mesh(
            self, tmp_path):
        """SIGKILL one rank of the 8-device (2-proc × 4) run: the
        survivor's watchdog escalates to checkpoint-save → crash-dump →
        exit 75; relaunching on a 4-device mesh restores that
        checkpoint and continues with losses bitwise-equal to an
        uninterrupted 4-device run from the same checkpoint — all
        within the subprocess timeouts (bounded wall clock)."""
        root = str(tmp_path / "ckpts")
        barrier = str(tmp_path / "barrier")
        os.makedirs(barrier)
        env_base = _env_2proc(_free_port())
        procs = []
        for rank in range(2):
            env = {**env_base, "RANK": str(rank)}
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _RANK_CHILD, root, barrier],
                env=env, cwd=_REPO_ROOT, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("kill-a-rank run timed out (escalation never "
                        "fired):\n" + "\n---\n".join(o or ""
                                                     for o in outs))
        joined = "\n---rank-output---\n".join(outs)
        if "STEP 1" not in outs[0]:
            if any(s in joined for s in ("UNAVAILABLE",
                                         "DEADLINE_EXCEEDED",
                                         "Permission denied",
                                         "unreachable")):
                pytest.skip(f"cluster bring-up unsupported:\n{joined}")
            pytest.fail(f"rank 0 never completed a step:\n{joined}")

        # rank 1 died by SIGKILL; rank 0 escalated with the policy code
        assert procs[1].returncode == -signal.SIGKILL, joined
        assert procs[0].returncode == ckpt.ESCALATION_EXIT_CODE, joined
        assert "FINISHED WITHOUT ESCALATION" not in joined

        # the escalation committed the survivor's last snapshot
        # (step 3: both ranks completed it before the kill)
        latest = ckpt.latest_checkpoint(root)
        assert latest is not None, "escalation committed no checkpoint"
        manifest = ckpt.read_manifest(latest)
        assert manifest["step"] == 3, manifest["step"]
        assert manifest["meta"]["reason"] == "stall"
        # the cooperative step-1 checkpoint has both ranks' files
        first = ckpt.read_manifest(ckpt.step_dir(root, 1))
        assert first["n_files"] == 2

        # the survivor's hang dump names the wedge
        crash = os.path.join(barrier, "crash.rank0.jsonl")
        assert os.path.exists(crash), os.listdir(barrier)
        hdr = json.loads(open(crash).readline())
        assert hdr["kind"] == "crash"
        assert hdr["reason"] == "escalation:stall"

        # relaunch on the 4-device mesh, twice: restore + 3 steps must
        # agree bitwise (the second run is the "uninterrupted run from
        # the same checkpoint" oracle)
        results = []
        for _ in range(2):
            r = subprocess.run(
                [sys.executable, "-c", _RESUME_CHILD, root],
                env=dict(os.environ, JAX_PLATFORMS="cpu",
                         TF_CPP_MIN_LOG_LEVEL="2"),
                cwd=_REPO_ROOT, capture_output=True, text=True,
                timeout=240)
            assert r.returncode == 0, r.stdout + r.stderr
            results.append(r.stdout.splitlines())
        for a, b in zip(*results):
            assert a == b, (results, "relaunch runs diverged")
        assert results[0][0].startswith("RESTORED_STEP 3 3")
        losses = [l for l in results[0] if l.startswith("LOSS")]
        assert len(losses) == 3


_SIGTERM_CHILD = textwrap.dedent("""
    import os, signal, sys
    sys.path.insert(0, sys.argv[3])
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from apex_tpu import ckpt, trace

    mgr = ckpt.CheckpointManager(sys.argv[1])
    policy = ckpt.EscalationPolicy(mgr)
    rec = trace.FlightRecorder(sys.argv[2], escalation=policy).install()
    rec.record(step=3)
    mgr.snapshot(3, {"w": jnp.arange(32.0)})
    mgr.wait()
    print("READY", flush=True)
    os.kill(os.getpid(), signal.SIGTERM)   # the preemption signal
    print("UNREACHABLE", flush=True)
""")


class TestPreemption:
    def test_sigterm_saves_checkpoint_before_dump(self, tmp_path):
        root = str(tmp_path / "ck")
        dump = str(tmp_path / "crash.jsonl")
        r = subprocess.run(
            [sys.executable, "-c", _SIGTERM_CHILD, root, dump,
             _REPO_ROOT],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=240)
        assert "READY" in r.stdout and "UNREACHABLE" not in r.stdout, \
            r.stdout + r.stderr
        assert r.returncode != 0           # SIGTERM terminates
        latest = ckpt.latest_checkpoint(root)
        assert latest is not None, "preemption did not commit"
        assert ckpt.read_manifest(latest)["step"] == 3
        assert ckpt.read_manifest(latest)["meta"]["reason"] == "preempt"
        lines = [json.loads(l) for l in
                 open(dump).read().splitlines()]
        assert lines[0]["kind"] == "crash"
        assert lines[0]["reason"] == "signal:SIGTERM"
