"""Span API + per-step host timeline — the forensic half of annotation.

:mod:`apex_tpu.prof.annotate` puts names *into the compiled program*
(``jax.named_scope``) so xplane traces attribute device time per scope.
This module adds the host half the flight recorder and hang watchdog
need: ``span("fwd")`` is a context manager / decorator that layers the
same in-graph scope + ``jax.profiler.TraceAnnotation`` AND records a
wall-clock (begin, duration) event into the active :class:`Tracer`'s
per-step timeline. The timeline is emitted two ways:

- :meth:`Tracer.chrome_trace` / :meth:`Tracer.write_chrome_trace` —
  Chrome-trace-format JSON (``{"traceEvents": [...]}``) that loads in
  Perfetto / ``chrome://tracing``;
- :meth:`Tracer.timeline` — a :class:`StepTimeline` table (one row per
  step, one column per span) plus ``kind="span"``/``kind="step"`` JSONL
  events for the monitor trace-event channel
  (``scripts/check_metrics_schema.py --kind trace`` validates them).

Passive by default: with no Tracer entered, ``span`` costs one global
read plus the named-scope enter (no ops added to the compiled program —
asserted by the ``trace/no-extra-dispatch`` compile check). Spans inside
a jitted function execute at *trace time* only; their host durations
attribute compile/trace cost (useful on step 0), while their named
scopes attribute device time on every step via xplane. Host-side spans
around the dispatch measure wall clock per step — remember jax dispatch
is async, so wrap the sync point (e.g. the host fetch) in its own span.
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax

__all__ = ["span", "step", "Tracer", "SpanEvent", "StepTrace",
           "StepTimeline", "current_tracer"]

# active Tracer stack (innermost last). Thread-local so a watchdog /
# helper thread entering its own tracer never corrupts the train loop's.
_tls = threading.local()


def _stack() -> List["Tracer"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_tracer() -> Optional["Tracer"]:
    """The innermost active Tracer on this thread, or None (passive)."""
    st = _stack()
    return st[-1] if st else None


class SpanEvent:
    """One span occurrence: name, begin time, duration.

    ``aborted`` marks a span unwound by an exception — it was in flight,
    not completed, when the step died (the duration then measures begin
    → unwind)."""

    __slots__ = ("name", "kind", "t_start", "dur_ms", "depth", "aborted")

    def __init__(self, name: str, kind: str, t_start: float,
                 dur_ms: float, depth: int, aborted: bool = False):
        self.name = name
        self.kind = kind          # "span" | "collective"
        self.t_start = t_start    # perf_counter seconds (trace-relative)
        self.dur_ms = dur_ms
        self.depth = depth        # nesting depth inside the step
        self.aborted = aborted

    def to_event(self, step: Optional[int], rank: int) -> Dict:
        ev = {"kind": "span", "name": self.name, "span_kind": self.kind,
              "step": step, "rank": rank, "t_ms": self.t_start * 1e3,
              "dur_ms": self.dur_ms, "depth": self.depth}
        if self.aborted:
            ev["aborted"] = True
        return ev


class StepTrace:
    """The span timeline of one step (plus whatever rides along)."""

    def __init__(self, step: Optional[int], t_start: float):
        self.step = step
        self.t_start = t_start
        self.dur_ms: Optional[float] = None
        self.spans: List[SpanEvent] = []
        self.aborted = False

    def span_ms(self) -> Dict[str, float]:
        """Total duration per span name (summed over occurrences)."""
        out: Dict[str, float] = {}
        for s in self.spans:
            out[s.name] = out.get(s.name, 0.0) + s.dur_ms
        return out

    def to_event(self, rank: int) -> Dict:
        ev = {"kind": "step", "step": self.step, "rank": rank,
              "t_ms": self.t_start * 1e3, "dur_ms": self.dur_ms,
              "spans": [{"name": s.name, "dur_ms": round(s.dur_ms, 4)}
                        for s in self.spans]}
        if self.aborted:
            ev["aborted"] = True
        return ev


class StepTimeline:
    """Tabular view of a list of StepTraces: steps x span columns."""

    def __init__(self, steps: List[StepTrace]):
        self.steps = steps

    def columns(self) -> List[str]:
        cols: List[str] = []
        for st in self.steps:
            for s in st.spans:
                if s.name not in cols:
                    cols.append(s.name)
        return cols

    def table(self, width: int = 12) -> str:
        cols = self.columns()
        heads = ["step", "total_ms"] + cols
        lines = [" ".join(h[-width:].rjust(width) for h in heads)]
        for st in self.steps:
            per = st.span_ms()
            row = [str(st.step if st.step is not None else "-"),
                   f"{st.dur_ms:.2f}" if st.dur_ms is not None else "n/a"]
            row += [f"{per[c]:.2f}" if c in per else "-" for c in cols]
            lines.append(" ".join(v.rjust(width) for v in row))
        return "\n".join(lines)


def _rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return 0


class Tracer:
    """Collects StepTraces from ``span``/``step`` used under it.

    ::

        tracer = trace.Tracer()
        with tracer:
            for batch in data:
                with trace.step():
                    with trace.span("dispatch"):
                        state, loss = train_step(state, batch)
                    with trace.span("fetch"):
                        logger.record(state.metrics)
        tracer.write_chrome_trace("timeline.json")
        print(tracer.timeline().table())

    ``on_step`` (a callable taking the finished StepTrace) is the fan-out
    hook: the flight recorder and the hang watchdog both subscribe
    through it, as can :meth:`apex_tpu.monitor.MetricsLogger.record_event`
    via :meth:`step_event`. ``max_steps`` bounds the retained timeline
    (older steps drop off; forensic retention belongs to the
    FlightRecorder's ring buffer).
    """

    def __init__(self, *, max_steps: int = 1024,
                 on_step: Optional[Callable[[StepTrace], None]] = None):
        self.max_steps = max(int(max_steps), 1)
        self._on_step: List[Callable[[StepTrace], None]] = (
            [on_step] if on_step else [])
        self.steps: List[StepTrace] = []
        self._t0 = time.perf_counter()
        self._step_count = 0
        self._current: Optional[StepTrace] = None
        self._open: List[Any] = []     # (name, kind, t_begin) stack
        self.last_completed_span: Optional[str] = None
        # spans unwound by an exception since the last step began: they
        # were IN FLIGHT when the step died (the unwind closes the
        # context managers, so open_spans alone would read empty by the
        # time a crash handler looks) — innermost first, (name, kind)
        self.aborted_spans: List[Any] = []
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self) -> "Tracer":
        _stack().append(self)
        return self

    def __exit__(self, *exc) -> None:
        st = _stack()
        if self in st:
            st.remove(self)

    def subscribe(self, fn: Callable[[StepTrace], None]) -> None:
        self._on_step.append(fn)

    # -- step boundaries -----------------------------------------------------

    def begin_step(self, step: Optional[int] = None) -> StepTrace:
        if step is None:
            step = self._step_count
        self._step_count = step + 1
        self.aborted_spans = []
        self._current = StepTrace(step, time.perf_counter() - self._t0)
        return self._current

    def end_step(self, aborted: bool = False) -> Optional[StepTrace]:
        st = self._current
        if st is None:
            return None
        st.dur_ms = (time.perf_counter() - self._t0 - st.t_start) * 1e3
        st.aborted = aborted
        self._current = None
        if not aborted:
            # the step completed: any span unwound by a caught-and-
            # recovered exception inside it is no longer in flight
            self.aborted_spans = []
        with self._lock:
            self.steps.append(st)
            if len(self.steps) > self.max_steps:
                del self.steps[:len(self.steps) - self.max_steps]
        for fn in list(self._on_step):
            try:
                fn(st)
            except Exception:
                pass          # observers never break the train loop
        return st

    # -- span recording (called by the span context manager) -----------------

    def _span_begin(self, name: str, kind: str) -> None:
        self._open.append((name, kind, time.perf_counter() - self._t0))

    def _span_end(self, aborted: bool = False) -> None:
        if not self._open:
            return
        name, kind, t0 = self._open.pop()
        now = time.perf_counter() - self._t0
        ev = SpanEvent(name, kind, t0, (now - t0) * 1e3,
                       depth=len(self._open), aborted=aborted)
        if aborted:
            # an exception unwound this span — it was in flight, not
            # completed; keep it visible to crash handlers
            self.aborted_spans.append((name, kind))
        else:
            self.last_completed_span = name
        target = self._current
        if target is not None:
            target.spans.append(ev)

    def add_span_event(self, name: str, kind: str, dur_ms: float,
                       t_end: Optional[float] = None) -> None:
        """Record a span *retroactively* — an event whose duration was
        only known after the fact (e.g. a compile detected by
        :mod:`apex_tpu.prof.compile_watch` once the dispatch returned).
        The event is back-dated so the timeline shows it where it
        actually ran; it lands in the current step (or the latest
        retained one, so post-step compiles are not lost)."""
        now = (time.perf_counter() if t_end is None else t_end) - self._t0
        ev = SpanEvent(name, kind, now - dur_ms * 1e-3, dur_ms,
                       depth=len(self._open))
        target = self._current
        if target is not None:
            target.spans.append(ev)
            return
        with self._lock:
            if self.steps:
                self.steps[-1].spans.append(ev)
            else:
                st = StepTrace(None, ev.t_start)
                st.dur_ms = dur_ms
                st.spans.append(ev)
                self.steps.append(st)

    @property
    def open_spans(self) -> List[str]:
        """Names of in-flight spans, outermost first: still-open ones
        plus any already unwound by the in-progress exception."""
        return ([name for name, _, _ in self._open]
                + [name for name, _ in reversed(self.aborted_spans)])

    @property
    def in_flight_collective(self) -> Optional[str]:
        """Deepest in-flight span tagged ``kind="collective"``, if any
        (exception-unwound collectives included)."""
        for name, kind in self.aborted_spans:
            if kind == "collective":
                return name
        for name, kind, _ in reversed(self._open):
            if kind == "collective":
                return name
        return None

    def in_flight_collective_age(self) -> Optional[Any]:
        """``(name, age_seconds, start)`` of the deepest OPEN
        ``kind="collective"`` span, or None. The age is host wall time
        since the span opened — what
        :class:`apex_tpu.cluster.CollectiveDeadline` polls to tell a
        *hung* collective (one span instance open past the deadline)
        from a *slow* one (which closes and reopens, resetting the
        age); ``start`` is the span's fixed open timestamp on the
        tracer clock — the stable instance identity its fire-once
        logic keys on. Exception-unwound collectives are excluded:
        they already belong to the crash handlers, not a liveness
        poll."""
        now = time.perf_counter() - self._t0
        for name, kind, t0 in reversed(self._open):
            if kind == "collective":
                return name, max(now - t0, 0.0), t0
        return None

    # -- exports -------------------------------------------------------------

    def timeline(self) -> StepTimeline:
        with self._lock:
            return StepTimeline(list(self.steps))

    def step_events(self, rank: Optional[int] = None) -> List[Dict]:
        """``kind="step"`` JSONL events for every retained step."""
        r = _rank() if rank is None else rank
        with self._lock:
            return [st.to_event(r) for st in self.steps]

    def span_events(self, rank: Optional[int] = None) -> List[Dict]:
        """Flat ``kind="span"`` JSONL events for every retained span."""
        r = _rank() if rank is None else rank
        out: List[Dict] = []
        with self._lock:
            for st in self.steps:
                out.extend(s.to_event(st.step, r) for s in st.spans)
        return out

    def chrome_trace(self, rank: Optional[int] = None) -> Dict:
        """Chrome-trace-format dict (loads in Perfetto/chrome://tracing).

        One complete-duration ("ph": "X") event per span plus one per
        step; pid is the process rank so multi-host dumps merge into one
        per-rank-track view, and ``process_name``/``thread_name``
        metadata events ("ph": "M") label each rank's track ("rank N")
        — without them a multi-rank Perfetto merge shows N anonymous
        pid tracks whose spans visually collide.
        """
        r = _rank() if rank is None else rank
        events: List[Dict] = [
            {"name": "process_name", "ph": "M", "pid": r, "tid": 0,
             "args": {"name": f"rank {r}"}},
            {"name": "process_sort_index", "ph": "M", "pid": r, "tid": 0,
             "args": {"sort_index": r}},
            {"name": "thread_name", "ph": "M", "pid": r, "tid": 0,
             "args": {"name": f"rank {r} steps"}},
        ]
        with self._lock:
            for st in self.steps:
                if st.dur_ms is not None:
                    events.append({
                        "name": f"step {st.step}", "ph": "X", "cat": "step",
                        "ts": st.t_start * 1e6, "dur": st.dur_ms * 1e3,
                        "pid": r, "tid": 0,
                        "args": {"step": st.step}})
                for s in st.spans:
                    events.append({
                        "name": s.name, "ph": "X", "cat": s.kind,
                        "ts": s.t_start * 1e6, "dur": s.dur_ms * 1e3,
                        "pid": r, "tid": 1 + s.depth,
                        "args": {"step": st.step}})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "metadata": {"producer": "apex_tpu.trace", "rank": r}}

    def write_chrome_trace(self, path: str,
                           rank: Optional[int] = None) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(rank), f)
        return path


class span:
    """``with trace.span("fwd"): ...`` / ``@trace.span("fwd")``.

    Layers, innermost to outermost:

    - ``jax.named_scope(name)`` — names the HLO ops traced inside, so the
      span shows up in xplane device traces and HLO dumps;
    - ``jax.profiler.TraceAnnotation(name)`` — a host-timeline range for
      the profiler;
    - a wall-clock event in the active :class:`Tracer` (if any).

    ``kind="collective"`` tags the span for the flight recorder's
    in-flight-collective forensics (see
    ``DistributedDataParallel.sync``). As a decorator, when
    :func:`apex_tpu.trace.debug_nans` mode is on, the wrapped function's
    outputs are additionally probed for finiteness and this span's name
    is reported as NaN provenance (see :mod:`apex_tpu.trace.debug_nans`).
    """

    def __init__(self, name: str, *, kind: str = "span"):
        self.name = name
        self.kind = kind
        self._scope = None
        self._annot = None
        self._tracer: Optional[Tracer] = None

    def __enter__(self) -> "span":
        self._tracer = current_tracer()
        if self._tracer is not None:
            self._tracer._span_begin(self.name, self.kind)
        self._annot = jax.profiler.TraceAnnotation(self.name)
        self._annot.__enter__()
        self._scope = jax.named_scope(self.name)
        self._scope.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        if self._scope is not None:
            self._scope.__exit__(*exc)
            self._scope = None
        if self._annot is not None:
            self._annot.__exit__(*exc)
            self._annot = None
        if self._tracer is not None:
            self._tracer._span_end(aborted=bool(exc and exc[0]))
            self._tracer = None

    def __call__(self, fn: Callable) -> Callable:
        from apex_tpu.trace.debug_nans import nan_probe

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with span(self.name, kind=self.kind):
                out = fn(*args, **kwargs)
            return nan_probe(self.name, out)

        return wrapped


class step:
    """``with trace.step(): ...`` — delimits one train step's timeline.

    Nested ``span``s land in this step's StepTrace; on exit the finished
    StepTrace fans out to the tracer's subscribers (flight recorder,
    watchdog heartbeat, metric-logger trace channel). A no-op when no
    Tracer is active.
    """

    def __init__(self, step: Optional[int] = None):
        self._step = step
        self._tracer: Optional[Tracer] = None

    def __enter__(self) -> "step":
        self._tracer = current_tracer()
        if self._tracer is not None:
            self._tracer.begin_step(self._step)
        return self

    def __exit__(self, *exc) -> None:
        if self._tracer is not None:
            self._tracer.end_step(aborted=bool(exc and exc[0]))
            self._tracer = None
