"""Fused single-process optimizers over the flat arena.

TPU-native rebuild of ``apex.optimizers`` (SURVEY.md §2.4): the reference
partitions params by dtype into tensor lists and makes one
``multi_tensor_applier`` launch per group per step
(`apex/optimizers/fused_adam.py:119-199`). Here params/grads/state live in
per-dtype arena buffers and one Pallas kernel per partition updates the
whole model (apex_tpu.ops.optim_kernels). Python-side per-param list
building — a hot loop the reference pays every step — does not exist:
flatten/unflatten trace once under jit and fuse into the step.

Two protocols in one object:

- fused:  ``new_params, new_state = opt.step(grads, state, params)``
          (the fast path; apex's ``optimizer.step()``)
- optax:  ``updates, new_state = opt.update(grads, state, params)``
          (GradientTransformation-compatible, costs one extra subtract)

``apex_tpu.amp.Amp`` auto-detects the fused protocol.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import arena
from apex_tpu.ops import optim_kernels as K
from apex_tpu.ops import multi_tensor as MT

Scalar = Union[float, jax.Array, Callable[[jax.Array], jax.Array]]


class _LeafOut:
    """Per-leaf multi-output bundle for the tree strategy — deliberately
    NOT a pytree container (a plain tuple would collide with tuple nodes
    in user param trees)."""
    __slots__ = ("vals",)

    def __init__(self, *vals):
        self.vals = vals


def _bias_corrections(count, beta1, beta2, enabled, sqrt2=False):
    if not enabled:
        return jnp.float32(1.0), jnp.float32(1.0)
    step = jnp.asarray(count, jnp.float32)
    bc1 = 1.0 - jnp.power(jnp.float32(beta1), step)
    bc2 = 1.0 - jnp.power(jnp.float32(beta2), step)
    return bc1, (jnp.sqrt(bc2) if sqrt2 else bc2)


class FusedOptState(NamedTuple):
    """Optimizer state: step count + named flat slot buffers per partition.

    ``slots["m"]["float32"]`` is the momentum buffer covering every fp32
    parameter. All slots are fp32 regardless of param dtype.
    """
    count: jax.Array
    slots: Dict[str, Dict[str, jax.Array]]


class FusedOptimizer:
    """Base: arena planning, flatten/unflatten, dual protocol.

    ``strategy`` selects how the fused update is laid out:

    - ``"arena"``: flatten params/grads into per-dtype flat buffers and
      run one Pallas kernel per partition — the direct
      `multi_tensor_apply` rebuild.
    - ``"tree"``: per-tensor jnp updates (identical f32 math) that XLA
      fuses into per-tensor roofline passes. On TPU there is no kernel
      -launch overhead to amortize, and the arena's flatten/unflatten
      is a genuine relayout of every byte (measured ~28 ms/step on
      BERT-Large 334M: the flat T(1024) buffer vs the params' T(8,128)
      tiling), so for large models the tree strategy is strictly
      faster; PERF.md round 2 measured the two tying already at
      ResNet-50 scale.
    - ``"auto"`` (default): tree for models over ~8M params, arena
      below (where the arena's single-kernel dispatch is measured
      equivalent and the L1 bitwise harness pins its layout).
    """

    #: names of fp32 state buffers allocated per partition
    slot_names = ()

    #: "auto" switches to the tree strategy at this many parameters
    TREE_THRESHOLD = 8_000_000

    def __init__(self, lr: Scalar, strategy: str = "auto"):
        if strategy not in ("auto", "tree", "arena"):
            raise ValueError(f"unknown strategy {strategy!r}")
        self.lr = lr
        self.strategy = strategy

    def _use_tree(self, params) -> bool:
        if self.strategy != "auto":
            return self.strategy == "tree"
        n = sum(int(np.prod(l.shape)) if l.shape else 1
                for l in jax.tree_util.tree_leaves(params))
        return n >= self.TREE_THRESHOLD

    @staticmethod
    def _split(out_tree, n):
        """tree of per-leaf ``_LeafOut`` bundles -> n trees.

        The bundle is an unregistered class (NOT a tuple): structural
        tuples inside a user's params pytree would be indistinguishable
        from per-leaf outputs and silently corrupt the split."""
        is_o = lambda x: isinstance(x, _LeafOut)
        return tuple(
            jax.tree_util.tree_map(lambda o, i=i: o.vals[i], out_tree,
                                   is_leaf=is_o)
            for i in range(n))

    # -- protocol ------------------------------------------------------------

    def init(self, params) -> FusedOptState:
        if self._use_tree(params):
            zeros = lambda t: jax.tree_util.tree_map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), t)
            return FusedOptState(
                count=jnp.int32(0),
                slots={name: zeros(params) for name in self.slot_names})
        spec = arena.plan(params)
        return FusedOptState(
            count=jnp.int32(0),
            slots={name: arena.zeros(spec, dtype=jnp.float32)
                   for name in self.slot_names})

    def step(self, grads, state: FusedOptState, params):
        """Fused update: returns (new_params, new_state)."""
        if self._use_tree(params):
            return self._tree_step(grads, state, params)
        spec = arena.plan(params)
        p_bufs = arena.flatten(params, spec)
        g_bufs = arena.flatten(grads, spec, cast=jnp.float32)
        count = state.count + 1
        lr = self.lr(count) if callable(self.lr) else self.lr

        ctx = self._step_context(spec, g_bufs)
        new_p, new_slots = {}, {name: {} for name in self.slot_names}
        for part in spec.partitions:
            dt = part.dtype
            slots = {name: state.slots[name][dt] for name in self.slot_names}
            p_out, s_out = self._partition_step(
                spec, dt, p_bufs[dt], g_bufs[dt], slots, count, lr, ctx=ctx)
            new_p[dt] = p_out
            for name in self.slot_names:
                new_slots[name][dt] = s_out[name]
        return (arena.unflatten(new_p, spec),
                FusedOptState(count=count, slots=new_slots))

    def update(self, grads, state: FusedOptState, params):
        """optax GradientTransformation protocol (updates = new - old)."""
        new_params, new_state = self.step(grads, state, params)
        updates = jax.tree_util.tree_map(
            lambda n, o: (n.astype(jnp.float32)
                          - o.astype(jnp.float32)).astype(o.dtype),
            new_params, params)
        return updates, new_state

    # -- subclass hooks ------------------------------------------------------

    def _step_context(self, spec, g_bufs):
        """Once-per-step work over all partitions (e.g. global grad norms)."""
        return None

    def _partition_step(self, spec, dt, p, g, slots, count, lr, ctx):
        raise NotImplementedError

    def _tree_step(self, grads, state, params):
        raise NotImplementedError(
            f"{type(self).__name__} has no tree strategy; construct with "
            f"strategy='arena'")

    def _resolve_lr(self, count):
        return self.lr(count) if callable(self.lr) else self.lr


class FusedAdam(FusedOptimizer):
    """Adam/AdamW over the arena (`apex/optimizers/fused_adam.py:34-202`).

    ``adam_w_mode=True`` decouples weight decay (AdamW), matching the
    reference default.
    """

    slot_names = ("m", "v")

    def __init__(self, lr: Scalar = 1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                 strategy: str = "auto"):
        super().__init__(lr, strategy)
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction

    def _partition_step(self, spec, dt, p, g, slots, count, lr, ctx):
        p2, m2, v2 = K.adam_update(
            p, g, slots["m"], slots["v"], lr=lr, beta1=self.beta1,
            beta2=self.beta2, eps=self.eps, weight_decay=self.weight_decay,
            step=count, adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction)
        return p2, {"m": m2, "v": v2}

    def _tree_step(self, grads, state, params):
        count = state.count + 1
        lr = self._resolve_lr(count)
        bc1, bc2 = _bias_corrections(count, self.beta1, self.beta2,
                                     self.bias_correction)
        wd, b1, b2, eps = (self.weight_decay, self.beta1, self.beta2,
                           self.eps)

        def leaf(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            if not self.adam_w_mode:
                g32 = g32 + wd * p32
            m2 = b1 * m + (1.0 - b1) * g32
            v2 = b2 * v + (1.0 - b2) * g32 * g32
            upd = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if self.adam_w_mode:
                upd = upd + wd * p32
            return _LeafOut((p32 - lr * upd).astype(p.dtype), m2, v2)

        out = jax.tree_util.tree_map(leaf, params, grads,
                                     state.slots["m"], state.slots["v"])
        p2, m2, v2 = self._split(out, 3)
        return p2, FusedOptState(count=count, slots={"m": m2, "v": v2})


class FusedSGD(FusedOptimizer):
    """SGD with momentum (`apex/optimizers/fused_sgd.py:6-217`)."""

    slot_names = ("m",)

    def __init__(self, lr: Scalar = 1e-3, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False, wd_after_momentum=False,
                 strategy: str = "auto"):
        super().__init__(lr, strategy)
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum

    def _partition_step(self, spec, dt, p, g, slots, count, lr, ctx):
        first = (count == 1) if self.momentum > 0 else False
        p2, m2 = K.sgd_update(
            p, g, slots["m"], lr=lr, momentum=self.momentum,
            dampening=self.dampening, weight_decay=self.weight_decay,
            nesterov=self.nesterov, first_run=first,
            wd_after_momentum=self.wd_after_momentum)
        return p2, {"m": m2}

    def _tree_step(self, grads, state, params):
        count = state.count + 1
        lr = self._resolve_lr(count)
        first = ((count == 1) if self.momentum > 0
                 else jnp.bool_(False))
        mom, damp, wd = self.momentum, self.dampening, self.weight_decay

        def leaf(p, g, m):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            if not self.wd_after_momentum:
                g32 = g32 + wd * p32
            m2 = jnp.where(first, g32, mom * m + (1.0 - damp) * g32)
            upd = (g32 + mom * m2) if self.nesterov else m2
            if self.wd_after_momentum:
                upd = upd + wd * p32
            return _LeafOut((p32 - lr * upd).astype(p.dtype), m2)

        out = jax.tree_util.tree_map(leaf, params, grads,
                                     state.slots["m"])
        p2, m2 = self._split(out, 2)
        return p2, FusedOptState(count=count, slots={"m": m2})


class FusedAdagrad(FusedOptimizer):
    """Adagrad (`apex/optimizers/fused_adagrad.py:5-95`)."""

    slot_names = ("h",)

    def __init__(self, lr: Scalar = 1e-2, eps=1e-10, weight_decay=0.0,
                 adagrad_w_mode=False, strategy: str = "auto"):
        super().__init__(lr, strategy)
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode

    def _partition_step(self, spec, dt, p, g, slots, count, lr, ctx):
        p2, h2 = K.adagrad_update(
            p, g, slots["h"], lr=lr, eps=self.eps,
            weight_decay=self.weight_decay,
            adagrad_w_mode=self.adagrad_w_mode)
        return p2, {"h": h2}

    def _tree_step(self, grads, state, params):
        count = state.count + 1
        lr = self._resolve_lr(count)
        wd, eps = self.weight_decay, self.eps

        def leaf(p, g, h):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            if not self.adagrad_w_mode:
                g32 = g32 + wd * p32
            h2 = h + g32 * g32
            upd = g32 / (jnp.sqrt(h2) + eps)
            if self.adagrad_w_mode:
                upd = upd + wd * p32
            return _LeafOut((p32 - lr * upd).astype(p.dtype), h2)

        out = jax.tree_util.tree_map(leaf, params, grads,
                                     state.slots["h"])
        p2, h2 = self._split(out, 2)
        return p2, FusedOptState(count=count, slots={"h": h2})


def lamb_trust_ratios(part, p, u, *, use_nvlamb, weight_decay):
    """Per-position LAMB trust ratios over one arena partition.

    Static arena ranges → per-tensor norms as fused slice-reduces and
    the trust-ratio spread as concatenated broadcasts; the traced
    segment_ids alternative lowers to scatter/gather over the whole
    arena, which TPU serializes (~500 ms on a BERT-Large buffer).
    NVLAMB applies the ratio even where wd==0 — with a single group,
    plain LAMB and NVLAMB agree unless wd==0 globally. Shared by the
    modern and legacy-contrib FusedLAMB surfaces.
    """
    p_norms = MT.per_tensor_l2norm_ranges(p, part.offsets, part.sizes)
    u_norms = MT.per_tensor_l2norm_ranges(u, part.offsets, part.sizes)
    ratio = jnp.where((p_norms > 0) & (u_norms > 0),
                      p_norms / u_norms, 1.0)
    if not use_nvlamb and weight_decay == 0.0:
        ratio = jnp.ones_like(ratio)
    return MT.spread_per_tensor(ratio, part.offsets, part.padded, len(p))


class FusedLAMB(FusedOptimizer):
    """LAMB (`apex/optimizers/fused_lamb.py:4-215`): global grad-norm clip,
    Adam-style direction, per-tensor trust ratio.

    Two Pallas stages with the per-tensor norms computed between them over
    the arena via segment reduction — the same split as the reference's
    `multi_tensor_lamb` stage pair.
    """

    slot_names = ("m", "v")

    def __init__(self, lr: Scalar = 1e-3, betas=(0.9, 0.999), eps=1e-6,
                 weight_decay=0.01, adam_w_mode=True, bias_correction=True,
                 max_grad_norm=1.0, use_nvlamb=False,
                 strategy: str = "auto"):
        super().__init__(lr, strategy)
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def _global_clip_scale(self, g_all):
        """clip factor from the global grad norm over *all* partitions
        (`fused_lamb.py:120-136`)."""
        if not self.max_grad_norm:
            return jnp.float32(1.0)
        sq = sum(jnp.square(MT.multi_tensor_l2norm(g)) for g in g_all.values())
        gnorm = jnp.sqrt(sq)
        return jnp.where(gnorm > self.max_grad_norm,
                         self.max_grad_norm / gnorm, 1.0).astype(jnp.float32)

    def _step_context(self, spec, g_bufs):
        # global grad norm computed ONCE per step over all partitions
        return self._global_clip_scale(g_bufs)

    def _partition_step(self, spec, dt, p, g, slots, count, lr, ctx):
        clip = ctx
        u, m2, v2 = K.lamb_stage1(
            p, g, slots["m"], slots["v"], beta1=self.beta1, beta2=self.beta2,
            eps=self.eps, weight_decay=self.weight_decay, step=count,
            bias_correction=self.bias_correction,
            adam_w_mode=self.adam_w_mode, clip_scale=clip)

        part = spec.partition(dt)
        ratio_pos = lamb_trust_ratios(part, p, u,
                                      use_nvlamb=self.use_nvlamb,
                                      weight_decay=self.weight_decay)
        p2 = K.lamb_stage2(p, u, ratio_pos, lr=lr)
        return p2, {"m": m2, "v": v2}

    def _tree_step(self, grads, state, params):
        count = state.count + 1
        lr = self._resolve_lr(count)
        bc1, bc2 = _bias_corrections(count, self.beta1, self.beta2,
                                     self.bias_correction)
        b1, b2, eps, wd = (self.beta1, self.beta2, self.eps,
                           self.weight_decay)

        # global grad-norm clip factor (`fused_lamb.py:120-136`)
        if self.max_grad_norm:
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in jax.tree_util.tree_leaves(grads))
            gnorm = jnp.sqrt(sq)
            clip = jnp.where(gnorm > self.max_grad_norm,
                             self.max_grad_norm / gnorm, 1.0)
        else:
            clip = jnp.float32(1.0)
        plain_identity = not self.use_nvlamb and self.weight_decay == 0.0

        def leaf(p, g, m, v):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32) * clip
            if not self.adam_w_mode:
                g32 = g32 + wd * p32
            m2 = b1 * m + (1.0 - b1) * g32
            v2 = b2 * v + (1.0 - b2) * g32 * g32
            u = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
            if self.adam_w_mode:
                u = u + wd * p32
            # per-tensor trust ratio — each leaf IS one tensor, so the
            # norms are plain reduces (no arena segments needed)
            if plain_identity:
                ratio = jnp.float32(1.0)
            else:
                pn = jnp.sqrt(jnp.sum(jnp.square(p32)))
                un = jnp.sqrt(jnp.sum(jnp.square(u)))
                ratio = jnp.where((pn > 0) & (un > 0), pn / un, 1.0)
            return _LeafOut((p32 - lr * ratio * u).astype(p.dtype), m2,
                            v2)

        out = jax.tree_util.tree_map(leaf, params, grads,
                                     state.slots["m"], state.slots["v"])
        p2, m2, v2 = self._split(out, 3)
        return p2, FusedOptState(count=count, slots={"m": m2, "v": v2})


class FusedNovoGrad(FusedOptimizer):
    """NovoGrad (`apex/optimizers/fused_novograd.py:67-210`).

    Per-layer norm EMAs live in a (num_tensors,) fp32 vector per partition —
    the reference's ``exp_avg_sq`` buffer, which stores *norms* (not
    squares, `fused_novograd.py:157-158`) and blends them linearly. Defaults
    match the reference: decoupled decay (``reg_inside_moment=False`` ↔
    MOMENT_MODE_1), bias correction on, grad averaging on, L2 norms,
    first-step norm initialization (``init_zero=False``).
    """

    slot_names = ("m",)

    def __init__(self, lr: Scalar = 1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, bias_correction=True,
                 reg_inside_moment=False, grad_averaging=True, norm_type=2,
                 init_zero=False, strategy: str = "auto"):
        super().__init__(lr, strategy)
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.reg_inside_moment = reg_inside_moment
        self.grad_averaging = grad_averaging
        if norm_type not in (0, 2):
            raise ValueError("FusedNovoGrad only supports l2/inf norm")
        self.norm_type = norm_type
        self.init_zero = init_zero

    def init(self, params) -> FusedOptState:
        if self._use_tree(params):
            return FusedOptState(
                count=jnp.int32(0),
                slots={"m": jax.tree_util.tree_map(
                           lambda p: jnp.zeros(jnp.shape(p), jnp.float32),
                           params),
                       "vnorm": jax.tree_util.tree_map(
                           lambda p: jnp.float32(0.0), params)})
        spec = arena.plan(params)
        slots = {"m": arena.zeros(spec, dtype=jnp.float32)}
        slots["vnorm"] = {
            p.dtype: jnp.zeros((len(p.sizes),), jnp.float32)
            for p in spec.partitions}
        return FusedOptState(count=jnp.int32(0), slots=slots)

    def _per_tensor_norm(self, g, part):
        if self.norm_type == 2:
            return MT.per_tensor_l2norm_ranges(g, part.offsets, part.sizes)
        return MT.per_tensor_maxnorm_ranges(g, part.offsets, part.sizes)

    # custom step: vnorm slot has non-buffer shape
    def step(self, grads, state, params):
        if self._use_tree(params):
            return self._tree_step(grads, state, params)
        spec = arena.plan(params)
        p_bufs = arena.flatten(params, spec)
        g_bufs = arena.flatten(grads, spec, cast=jnp.float32)
        count = state.count + 1
        lr = self.lr(count) if callable(self.lr) else self.lr

        new_p = {}
        new_slots = {"m": {}, "vnorm": {}}
        for part in spec.partitions:
            dt = part.dtype
            p, g = p_bufs[dt], g_bufs[dt]
            norms = self._per_tensor_norm(g, part)
            v_prev = state.slots["vnorm"][dt]
            blended = self.beta2 * v_prev + (1.0 - self.beta2) * norms
            if self.init_zero:
                v_new = blended
            else:
                # init with first-step norm so the first blend is a no-op
                # (`fused_novograd.py:163-174`)
                v_new = jnp.where(count == 1, norms, blended)
            vpos = MT.spread_per_tensor(v_new, part.offsets, part.padded,
                                        len(p), fill=1.0)
            p2, m2 = K.novograd_update(
                p, g, state.slots["m"][dt], vpos, lr=lr, beta1=self.beta1,
                beta2=self.beta2, eps=self.eps,
                weight_decay=self.weight_decay, step=count,
                grad_averaging=self.grad_averaging,
                bias_correction=self.bias_correction,
                reg_inside_moment=self.reg_inside_moment)
            new_p[dt] = p2
            new_slots["m"][dt] = m2
            new_slots["vnorm"][dt] = v_new
        return (arena.unflatten(new_p, spec),
                FusedOptState(count=count, slots=new_slots))

    def _tree_step(self, grads, state, params):
        count = state.count + 1
        lr = self._resolve_lr(count)
        bc1, bc2 = _bias_corrections(count, self.beta1, self.beta2,
                                     self.bias_correction, sqrt2=True)
        b1, b2, wd, eps = (self.beta1, self.beta2, self.weight_decay,
                           self.eps)
        b3 = (1.0 - b1) if self.grad_averaging else 1.0

        def leaf(p, g, m, vprev):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            if self.norm_type == 2:
                nrm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            else:
                nrm = jnp.max(jnp.abs(g32))
            blended = b2 * vprev + (1.0 - b2) * nrm
            v_new = blended if self.init_zero else \
                jnp.where(count == 1, nrm, blended)
            denom = v_new / bc2 + eps
            if self.reg_inside_moment:
                gg = g32 / denom + wd * p32
                m2 = b1 * m + b3 * gg
                p2 = p32 - lr * (m2 / bc1)
            else:
                m2 = b1 * m + b3 * g32
                p2 = p32 - lr * ((m2 / bc1) / denom + wd * p32)
            return _LeafOut(p2.astype(p.dtype), m2, v_new)

        out = jax.tree_util.tree_map(leaf, params, grads,
                                     state.slots["m"],
                                     state.slots["vnorm"])
        p2, m2, v2 = self._split(out, 3)
        return p2, FusedOptState(count=count,
                                 slots={"m": m2, "vnorm": v2})
