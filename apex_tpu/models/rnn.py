"""RNN stacks — `apex.RNN` rebuilt on lax.scan.

The reference (`apex/RNN/models.py:8-54`, `RNNBackend.py:25-365`,
`cells.py`) hand-rolls Python-loop RNN execution so fp16 works (torch's
fused cuDNN RNNs didn't); on TPU the equivalent is ``lax.scan`` cells
compiled by XLA — flax's scan-based ``nn.RNN`` over standard cells, plus
the reference's signature extra: the multiplicative LSTM (``mLSTM``,
`cells.py` mLSTMRNNCell). Same factory surface: ``LSTM``, ``GRU``,
``Tanh``, ``ReLU``, ``mLSTM``, each returning a stacked (optionally
bidirectional) module.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn


class mLSTMCell(nn.RNNCellBase):
    """Multiplicative LSTM cell (`apex/RNN/cells.py` mLSTMRNNCell):
    an intermediate multiplicative state m = (Wmx·x) ⊙ (Wmh·h) replaces h
    in the gate computation."""
    features: int

    @nn.compact
    def __call__(self, carry, x):
        c, h = carry
        f = self.features
        m = nn.Dense(f, use_bias=False, name="wmx")(x) * \
            nn.Dense(f, use_bias=False, name="wmh")(h)
        z = nn.Dense(4 * f, name="wx")(x) + nn.Dense(4 * f, name="wm")(m)
        i, fg, g, o = jnp.split(z, 4, axis=-1)
        i, fg, o = map(jax.nn.sigmoid, (i, fg, o))
        g = jnp.tanh(g)
        new_c = fg * c + i * g
        new_h = o * jnp.tanh(new_c)
        return (new_c, new_h), new_h

    @nn.nowrap
    def initialize_carry(self, rng, input_shape):
        batch = input_shape[:-1]
        return (jnp.zeros((*batch, self.features)),
                jnp.zeros((*batch, self.features)))

    @property
    def num_feature_axes(self):
        return 1


def _make_cell(kind: str, hidden: int):
    if kind == "lstm":
        return nn.LSTMCell(hidden)
    if kind == "gru":
        return nn.GRUCell(hidden)
    if kind == "tanh":
        return nn.SimpleCell(hidden, activation_fn=jnp.tanh)
    if kind == "relu":
        return nn.SimpleCell(hidden, activation_fn=jax.nn.relu)
    if kind == "mlstm":
        return mLSTMCell(hidden)
    raise ValueError(f"unknown cell {kind!r}")


class StackedRNN(nn.Module):
    """Multi-layer (optionally bidirectional) RNN over (B, T, D) inputs —
    the `stackedRNN`/`bidirectionalRNN` wrapper (`RNNBackend.py:25-160`).
    Inter-layer dropout matches the reference's placement."""
    cell_type: str
    hidden: int
    num_layers: int = 1
    bidirectional: bool = False
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        for layer in range(self.num_layers):
            if self.bidirectional:
                fwd = nn.RNN(_make_cell(self.cell_type, self.hidden),
                             name=f"fwd_{layer}")
                bwd = nn.RNN(_make_cell(self.cell_type, self.hidden),
                             reverse=True, keep_order=True,
                             name=f"bwd_{layer}")
                x = jnp.concatenate([fwd(x), bwd(x)], axis=-1)
            else:
                cell = nn.RNN(_make_cell(self.cell_type, self.hidden),
                              name=f"cell_{layer}")
                x = cell(x)
            if self.dropout > 0 and not deterministic \
                    and layer < self.num_layers - 1:
                x = nn.Dropout(self.dropout, deterministic=False)(x)
        return x


def LSTM(input_size: int, hidden_size: int, num_layers: int = 1,
         bidirectional: bool = False, dropout: float = 0.0) -> StackedRNN:
    """`apex.RNN.LSTM` factory (`models.py:40-44`). ``input_size`` is
    accepted for signature parity (flax infers it)."""
    del input_size
    return StackedRNN("lstm", hidden_size, num_layers, bidirectional,
                      dropout)


def GRU(input_size, hidden_size, num_layers=1, bidirectional=False,
        dropout=0.0) -> StackedRNN:
    del input_size
    return StackedRNN("gru", hidden_size, num_layers, bidirectional,
                      dropout)


def Tanh(input_size, hidden_size, num_layers=1, bidirectional=False,
         dropout=0.0) -> StackedRNN:
    del input_size
    return StackedRNN("tanh", hidden_size, num_layers, bidirectional,
                      dropout)


def ReLU(input_size, hidden_size, num_layers=1, bidirectional=False,
         dropout=0.0) -> StackedRNN:
    del input_size
    return StackedRNN("relu", hidden_size, num_layers, bidirectional,
                      dropout)


def mLSTM(input_size, hidden_size, num_layers=1, bidirectional=False,
          dropout=0.0) -> StackedRNN:
    del input_size
    return StackedRNN("mlstm", hidden_size, num_layers, bidirectional,
                      dropout)
