"""Dynamic loss scaler semantics.

Mirrors the schedule the reference implements in `apex/amp/scaler.py:197-215`
and the overflow-skip property asserted by `tests/L0/run_amp/test_fused_sgd.py`
(skipped steps advance nothing), all on-device under jit.
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.utils import tree_select


def test_init_scale():
    cfg = amp.LossScaleConfig()
    st = amp.loss_scale_init(cfg)
    assert float(st.loss_scale) == 2.0 ** 16
    assert int(st.growth_tracker) == 0


def test_scale_and_unscale_roundtrip():
    cfg = amp.LossScaleConfig(init_scale=512.0)
    st = amp.loss_scale_init(cfg)
    loss = jnp.float32(2.0)
    assert float(amp.scale_loss(loss, st)) == 1024.0
    grads = {"w": jnp.full((4,), 512.0 * 3.0, jnp.bfloat16)}
    un, finite = amp.unscale_grads(grads, st)
    assert un["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(un["w"]), 3.0)
    assert bool(finite)


def test_backoff_on_overflow():
    cfg = amp.LossScaleConfig(init_scale=2.0 ** 16)
    st = amp.loss_scale_init(cfg)
    grads = {"w": jnp.array([1.0, jnp.inf], jnp.float32)}
    _, finite = amp.unscale_grads(grads, st)
    assert not bool(finite)
    st2 = amp.loss_scale_update(st, finite, cfg)
    assert float(st2.loss_scale) == 2.0 ** 15
    assert int(st2.growth_tracker) == 0


def test_growth_after_interval():
    cfg = amp.LossScaleConfig(init_scale=4.0, growth_interval=3)
    st = amp.loss_scale_init(cfg)
    finite = jnp.bool_(True)
    for _ in range(2):
        st = amp.loss_scale_update(st, finite, cfg)
        assert float(st.loss_scale) == 4.0
    st = amp.loss_scale_update(st, finite, cfg)  # third finite step: grow
    assert float(st.loss_scale) == 8.0
    assert int(st.growth_tracker) == 0


def test_growth_clamped_at_max():
    cfg = amp.LossScaleConfig(init_scale=2.0 ** 24, growth_interval=1)
    st = amp.loss_scale_init(cfg)
    st = amp.loss_scale_update(st, jnp.bool_(True), cfg)
    assert float(st.loss_scale) == 2.0 ** 24  # clamp, `scaler.py:203-213`


def test_backoff_clamped_at_min():
    cfg = amp.LossScaleConfig(init_scale=2.0, min_loss_scale=1.5)
    st = amp.loss_scale_init(cfg)
    st = amp.loss_scale_update(st, jnp.bool_(False), cfg)
    assert float(st.loss_scale) == 1.5


def test_static_scale_never_moves():
    cfg = amp.LossScaleConfig(init_scale=128.0, dynamic=False)
    st = amp.loss_scale_init(cfg)
    st = amp.loss_scale_update(st, jnp.bool_(False), cfg)
    assert float(st.loss_scale) == 128.0


def test_overflow_interleaving_matches_reference_schedule():
    """Inject overflows at chosen iterations (the `test_fused_sgd` pattern)
    and check the exact scale trajectory."""
    cfg = amp.LossScaleConfig(init_scale=2.0 ** 8, growth_interval=2)
    st = amp.loss_scale_init(cfg)
    # finite, finite (grow), overflow (halve), finite, finite (grow)
    expected = [2.0 ** 8, 2.0 ** 9, 2.0 ** 8, 2.0 ** 8, 2.0 ** 9]
    seq = [True, True, False, True, True]
    got = []
    for ok in seq:
        st = amp.loss_scale_update(st, jnp.bool_(ok), cfg)
        got.append(float(st.loss_scale))
    assert got == expected


def test_unscale_with_stashed_accumulation():
    """Cross-backward grad accumulation math (`scaler.py:152-190`)."""
    cfg = amp.LossScaleConfig(init_scale=256.0)
    st = amp.loss_scale_init(cfg)
    stashed = {"w": jnp.full((3,), 5.0, jnp.float32)}      # already unscaled
    grads = {"w": jnp.full((3,), 256.0 * 2.0, jnp.float32)}  # carries scale
    out, finite = amp.unscale_grads_with_stashed(grads, stashed, st)
    np.testing.assert_allclose(np.asarray(out["w"]), 7.0)
    assert bool(finite)


def test_value_and_scaled_grad_under_jit():
    cfg = amp.LossScaleConfig(init_scale=1024.0)
    st = amp.loss_scale_init(cfg)

    def loss_fn(params, x):
        return jnp.sum(params["w"] * x)

    f = jax.jit(amp.value_and_scaled_grad(loss_fn, cfg))
    params = {"w": jnp.arange(4.0)}
    x = jnp.ones((4,)) * 2.0
    loss, grads, new_st, finite = f(params, st, x)
    np.testing.assert_allclose(float(loss), float(jnp.sum(params["w"] * x)))
    np.testing.assert_allclose(np.asarray(grads["w"]), 2.0)  # unscaled
    assert bool(finite)
    assert int(new_st.growth_tracker) == 1


def test_skip_commit_semantics():
    """Overflow step: params and optimizer state unmoved via tree_select."""
    params = {"w": jnp.ones((2,))}
    new_params = {"w": jnp.zeros((2,))}
    out = tree_select(jnp.bool_(False), new_params, params)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    out = tree_select(jnp.bool_(True), new_params, params)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.0)
