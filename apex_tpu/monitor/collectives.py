"""Static per-step collective-traffic accounting from compiled HLO.

The reference could only *infer* allreduce volume from its own bucketing
bookkeeping (`apex/parallel/distributed.py:425-475`); on TPU the compiled
program itself is the ground truth: every collective the step performs is
an instruction in the optimized HLO with a typed result shape. This
module walks that text and sums result bytes per collective opcode —
a compile-time constant per executable, fetched once and attached to
every logged record (the accounting DynamiQ-style compressed collectives
need as their uncompressed baseline).

Async pairs (``all-reduce-start``/``all-reduce-done``) are counted once,
at the ``-done`` (whose result is the actual output shape); the
``-start`` result tuples carry both operand and result buffers and would
double-count.
"""

from __future__ import annotations

from typing import Dict, Optional

from apex_tpu.prof import hlo as _hlo
from apex_tpu.prof import xplane as _xplane

__all__ = ["COLLECTIVE_OPCODES", "collective_bytes",
           "collective_bytes_from_text"]

# The canonical prefix list lives next to the trace categorizer so live
# accounting and post-hoc attribution bucket opcodes identically.
COLLECTIVE_OPCODES = _xplane.COLLECTIVE_PREFIXES


def collective_bytes_from_text(hlo_text: str) -> Dict[str, int]:
    """Sum collective result bytes per opcode over an optimized-HLO dump.

    Returns ``{opcode: bytes, ..., "total": bytes}`` (opcodes with zero
    traffic are omitted; ``total`` is always present).

    Known limit: each instruction is counted ONCE — a collective inside
    a ``while``/``scan`` body (e.g. a per-microbatch psum) executes
    trip-count times per step but is summed once, so loop-wrapped steps
    are under-reported by the trip count. Hoist collectives out of the
    loop (the usual accumulate-then-sync pattern) or scale the estimate
    by the trip count yourself.
    """
    totals: Dict[str, int] = {}
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _hlo._INSTR_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        for prefix in COLLECTIVE_OPCODES:
            if op.startswith(prefix):
                if op.endswith("-start"):
                    break  # counted at the matching -done
                _, nbytes = _hlo._shape_elems_bytes(m.group("shape"))
                totals[prefix] = totals.get(prefix, 0) + nbytes
                break
    totals["total"] = sum(totals.values())
    return totals


def collective_bytes(fn=None, *args, hlo_text: Optional[str] = None,
                     **kwargs) -> Dict[str, int]:
    """Per-step collective bytes of a jittable step function.

    Either pass the step function + example args (compiled here via
    :func:`apex_tpu.prof.hlo.compiled_hlo`) or a pre-dumped optimized-HLO
    text via ``hlo_text=``.
    """
    if hlo_text is None:
        if fn is None:
            raise ValueError("pass a step function or hlo_text=")
        hlo_text = _hlo.compiled_hlo(fn, *args, **kwargs)
    return collective_bytes_from_text(hlo_text)
