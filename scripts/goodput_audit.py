#!/usr/bin/env python
"""goodput_audit — the asserting CI audit of the runtime performance
observatory (run by ``run_tier1.sh --smoke``; exit status is the
verdict).

Four asserted legs on the 8-device CPU mesh:

(a) **attribution closure**: an instrumented train loop (per-phase
    spans, a compile watcher, a synthetic input-wait, a joined ckpt
    capture stall) must decompose every step's measured wall time into
    the goodput buckets with the bucket sum closing within 5% —
    memory_budget-style, but over *time* instead of bytes. Step 0's
    back-dated compile span must land in ``recompile`` and vanish in
    steady state; the injected input wait and ckpt stall must land in
    their buckets.

(b) **straggler forensics**: 4 synthetic ranks heartbeat in lockstep,
    rank 2 seeded 60 ms late with the time parked in a ``data/load``
    span — the lockstep reader must flag EXACTLY rank 2 (hysteresis:
    only after 3 consecutive lagging steps), name ``data/load`` as its
    slowest span with class ``input_wait``, and feed the watchdog's
    early-warning tier (``on_fire`` sees it, ``on_stall`` must NOT —
    degraded progress is not a stall). The clean half of the window
    must flag nobody.

(c) **measured link calibration round-trip**: ``link_probe --cpu8``
    emits a MeshModel JSON whose ``link_bytes_per_s`` is measured
    (provenance in ``calibration``); ``apexlint --mesh <that file>``
    must ingest it and report APX203's flat-DCN hop milliseconds
    computed from the MEASURED bytes/s — not the default constant.

(d) every emitted stream validates under
    ``check_metrics_schema.py --kind goodput``.

Usage: JAX_PLATFORMS=cpu python scripts/goodput_audit.py --cpu8
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_schema(path: str, kind: str = "goodput") -> None:
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts",
                                      "check_metrics_schema.py"),
         "--kind", kind, path],
        capture_output=True, text=True)
    assert r.returncode == 0, (
        f"schema validation failed for {path}:\n{r.stdout}{r.stderr}")


def audit_goodput_closure(tmp: str) -> None:
    import jax
    import jax.numpy as jnp

    from apex_tpu import monitor, prof, trace

    print("== goodput attribution closure (8-device CPU host)")
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (32, 64), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    params = {"w1": jax.random.normal(jax.random.PRNGKey(2),
                                      (64, 64)) * 0.1,
              "w2": jax.random.normal(jax.random.PRNGKey(3),
                                      (64, 16)) * 0.1}

    def train_step(p, x, y):
        def loss_fn(p):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean(jnp.square(h @ p["w2"] - y))
        g = jax.grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g)

    watcher = prof.CompileWatcher()
    watched = watcher.watch(train_step, name="train_step")
    events_path = os.path.join(tmp, "goodput.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], goodput_sink=monitor.JSONLSink(events_path))
    tracer = trace.Tracer()
    ledger = monitor.GoodputLedger(tracer, tolerance=0.05)
    ledger.subscribe(logger.record_goodput)
    hb_dir = os.path.join(tmp, "hb")
    hb = trace.HeartbeatWriter(hb_dir, rank=0)
    tracer.subscribe(hb.on_step)

    n_steps = 6
    p = params
    with tracer:
        for i in range(n_steps):
            with trace.step(i):
                with trace.span("data/load"):
                    time.sleep(0.004)       # synthetic input wait
                with trace.span("dispatch"):
                    p = watched(p, x, y)
                with trace.span("fetch"):
                    jax.block_until_ready(p)
                if i == 3:
                    # a checkpoint capture stall: real step-path time
                    # (spent OUTSIDE any span, like Snapshotter's
                    # capture) reported through the ckpt event
                    # channel's record shape — noted before the step
                    # folds so the join moves it out of the residual
                    # into ckpt_stall (a join can only MOVE measured
                    # time; it never invents wall clock, so closure
                    # still holds)
                    t0 = time.perf_counter()
                    time.sleep(0.003)
                    stall_ms = (time.perf_counter() - t0) * 1e3
                    ledger.note_ckpt({"kind": "ckpt_save", "step": 3,
                                      "stall_ms": stall_ms, "path": tmp,
                                      "bytes": 0, "dur_ms": stall_ms})
    logger.close()

    assert len(ledger.steps) == n_steps, len(ledger.steps)
    ok, worst = ledger.check_closure(tolerance=0.05)
    assert ok, f"bucket sum does not close over wall time: worst " \
               f"relative error {worst:.4f} > 0.05"
    s0, tail = ledger.steps[0], ledger.steps[-1]
    assert s0.buckets["recompile"] > 0, \
        "step 0's compile span missing from the recompile bucket"
    assert tail.buckets["recompile"] == 0, \
        "steady-state step attributed compile time"
    for rec in ledger.steps:
        assert rec.buckets["input_wait"] >= 3.0, (
            rec.step, rec.buckets)
    joined = ledger.steps[3].buckets["ckpt_stall"]
    assert joined >= 2.0, f"ckpt stall join missing: {joined}"
    assert all(r.buckets["ckpt_stall"] == 0 for r in ledger.steps
               if r.step != 3), "ckpt stall leaked into other steps"
    gf = ledger.rolling_goodput()
    assert gf is not None and 0.0 < gf <= 1.0, gf
    # steady-state goodput must see through the injected waits: the
    # compute bucket exists and the overhead buckets are nonzero
    assert tail.buckets["compute"] > 0, tail.buckets
    print(ledger.table())
    print(f"  closure worst-step error {worst:.2%} (<= 5%), rolling "
          f"goodput {gf:.1%}")
    _run_schema(events_path)
    print(f"  events validate (--kind goodput): {events_path}")


def audit_straggler(tmp: str) -> None:
    from apex_tpu import trace

    print("== cross-rank straggler detection (synthetic 4-rank mesh)")
    hb_dir = os.path.join(tmp, "straggler")
    n_ranks, n_steps, lag_s = 4, 10, 0.060
    t0 = 1_000_000.0
    writers = [trace.HeartbeatWriter(hb_dir, rank=r)
               for r in range(n_ranks)]
    for step in range(n_steps):
        for r, w in enumerate(writers):
            lag = lag_s if (r == 2 and step >= 5) else 0.0
            spans = {"dispatch": 40.0, "fetch": 3.0,
                     "data/load": 5.0 + (lag * 1e3 if lag else 0.0)}
            w.beat(step, dur_ms=50.0 + lag * 1e3, spans=spans,
                   wall_time=t0 + step * 0.1 + r * 1e-4 + lag)

    det = trace.StragglerDetector(hb_dir, window=10, z_threshold=4.0,
                                  hysteresis=3, lag_floor_ms=1.0)
    reports = det.check()
    assert len(reports) == 1, f"want exactly rank 2 flagged, got " \
        f"{[(r.rank, r.z) for r in reports]}"
    rep = reports[0]
    assert rep.rank == 2, rep
    assert rep.consecutive >= 3, rep
    assert rep.lag_ms > 40.0, rep
    assert rep.slowest_span == "data/load", rep
    assert rep.span_class == "input_wait", rep
    print(f"  flagged rank {rep.rank}: lag {rep.lag_ms:.1f} ms "
          f"(z={rep.z:.1f}, {rep.consecutive} consecutive), slowest "
          f"span {rep.slowest_span!r} [{rep.span_class}]")

    # hysteresis negative twin: a window ending BEFORE the injected lag
    # has 3-in-a-row must flag nobody
    clean_dir = os.path.join(tmp, "straggler_clean")
    writers = [trace.HeartbeatWriter(clean_dir, rank=r)
               for r in range(n_ranks)]
    for step in range(n_steps):
        for r, w in enumerate(writers):
            lag = lag_s if (r == 2 and step == n_steps - 1) else 0.0
            w.beat(step, dur_ms=50.0 + lag * 1e3,
                   spans={"dispatch": 40.0},
                   wall_time=t0 + step * 0.1 + r * 1e-4 + lag)
    det2 = trace.StragglerDetector(clean_dir, window=10,
                                   z_threshold=4.0, hysteresis=3,
                                   lag_floor_ms=1.0)
    assert det2.check() == [], "one-step blip flagged without hysteresis"
    print("  one-step blip NOT flagged (hysteresis holds)")

    # early-warning tier: the watchdog's alerting hook sees the report,
    # its escalation hook does not
    fired, stalled = [], []
    wd = trace.HangWatchdog(deadline_s=3600.0, on_fire=fired.append,
                            on_stall=stalled.append)
    events_path = os.path.join(tmp, "straggler.jsonl")
    from apex_tpu import monitor
    logger = monitor.MetricsLogger(
        sinks=[], goodput_sink=monitor.JSONLSink(events_path))
    watch = trace.StragglerWatch(det, watchdog=wd,
                                 event_sink=logger.record_goodput)
    reports = watch.poll_once()
    logger.close()
    assert len(reports) == 1 and wd.warning_count == 1
    assert wd.last_warning["rank"] == 2
    assert len(fired) == 1 and fired[0]["reason"] == "early-warning"
    assert not stalled, "early warning must never reach on_stall"
    print("  watchdog early-warning tier fed (on_fire saw it, "
          "on_stall did not)")
    _run_schema(events_path)
    print(f"  events validate (--kind goodput): {events_path}")


def audit_link_calibration(tmp: str) -> None:
    print("== measured link calibration -> apexlint round-trip")
    model_path = os.path.join(tmp, "mesh_measured.json")
    fit_events = os.path.join(tmp, "linkfit.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "link_probe.py"),
         "--cpu8", "--out", model_path, "--jsonl", fit_events],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r.returncode == 0, f"link_probe failed:\n{r.stdout}{r.stderr}"
    print("  " + r.stdout.strip().splitlines()[-1])
    _run_schema(fit_events)

    model = json.load(open(model_path))
    from apex_tpu.lint.mesh_model import DEFAULT_LINK_BYTES_PER_S
    dcn_bps = model["link_bytes_per_s"]["dcn"]
    assert model.get("calibration", {}).get("dcn"), \
        "measured model carries no dcn calibration provenance"
    assert dcn_bps > 0 and dcn_bps != DEFAULT_LINK_BYTES_PER_S["dcn"], \
        "measured dcn bytes/s indistinguishable from the default"

    lint_jsonl = os.path.join(tmp, "lint_measured.jsonl")
    # --flat-sync: the flagship default is now the hierarchical
    # comm_plan (APX203-clean by design — docs/linting.md#apx203-clean);
    # this leg needs the FLAT twin precisely so APX203 fires and its
    # hop-ms evidence can be checked against the measured bytes/s
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "apexlint.py"),
         "--flagship", "resnet", "--mesh", model_path, "--flat-sync",
         "--fail-on", "error", "--jsonl", lint_jsonl],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r.returncode == 0, \
        f"apexlint --mesh (measured) failed:\n{r.stdout}\n{r.stderr}"

    findings = [json.loads(l) for l in open(lint_jsonl)]
    apx203 = [f for f in findings
              if f.get("rule") == "dcn-flat-collective"
              and (f.get("bytes") or 0) > 1000]
    assert apx203, "no APX203 finding with real wire bytes — the " \
        "flat ddp sync should have fired against the 2-slice model"
    f = max(apx203, key=lambda f: f["bytes"])
    measured_ms = f["bytes"] / dcn_bps * 1e3
    default_ms = f["bytes"] / DEFAULT_LINK_BYTES_PER_S["dcn"] * 1e3
    assert f"{measured_ms:.2f} ms" in f["message"], (
        f"APX203 hop evidence not computed from the measured bytes/s: "
        f"wanted ~{measured_ms:.2f} ms in: {f['message']}")
    assert f"{default_ms:.2f}" != f"{measured_ms:.2f}", (
        "measured and default hop times coincide — the audit proves "
        "nothing; re-run")
    print(f"  APX203 on {f['scope']}: {f['bytes']} B -> "
          f"{measured_ms:.2f} ms at the MEASURED {dcn_bps / 1e9:.3f} "
          f"GB/s (default model would claim {default_ms:.2f} ms)")
    _run_schema(lint_jsonl, kind="lint")


def main_cpu8() -> None:
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")
    from apex_tpu import _compat
    _compat.request_cpu_devices(8)

    with tempfile.TemporaryDirectory() as tmp:
        audit_goodput_closure(tmp)
        audit_straggler(tmp)
        audit_link_calibration(tmp)
    print("\ngoodput audit ok")


if __name__ == "__main__":
    if "--cpu8" in sys.argv:
        main_cpu8()
    else:
        print(__doc__)
        sys.exit(2)
