"""Fused BN(+add)(+relu) unit: custom VJP vs plain-autodiff oracle.

The reference validates its fused BN kernels against torch.nn.BatchNorm
outputs and grads (`tests/L0/run_optimizers/..`, groupbn unit tests);
here the oracle is the same math built from jnp primitives and
differentiated by JAX.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.bn_act import (
    FusedBNAct, bn_act_reference, bn_act_train, bn_add_act_train, make_cfg,
)


def _rand(shape, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32), dtype)


@pytest.mark.parametrize("relu", [True, False])
def test_forward_matches_reference(relu):
    x = _rand((4, 6, 6, 16))
    scale = _rand((16,), 1) * 0.5 + 1.0
    bias = _rand((16,), 2) * 0.1
    cfg = make_cfg(relu=relu)
    z, mean, var, count = bn_act_train(x, scale, bias, cfg)
    zr, mr, vr = bn_act_reference(x, scale, bias, relu=relu)
    np.testing.assert_allclose(z, zr, atol=1e-5)
    np.testing.assert_allclose(mean, mr, atol=1e-6)
    np.testing.assert_allclose(var, vr, atol=1e-6)
    assert float(count) == 4 * 6 * 6


@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("with_residual", [True, False])
def test_grads_match_autodiff(relu, with_residual):
    x = _rand((4, 6, 6, 16))
    r = _rand((4, 6, 6, 16), 7) * 0.3
    scale = _rand((16,), 1) * 0.5 + 1.0
    bias = _rand((16,), 2) * 0.1
    g = _rand((4, 6, 6, 16), 3)  # upstream cotangent
    cfg = make_cfg(relu=relu)

    if with_residual:
        def fused(x, r, s, b):
            z, *_ = bn_add_act_train(x, r, s, b, cfg)
            return jnp.sum(z * g)

        def oracle(x, r, s, b):
            z, _, _ = bn_act_reference(x, s, b, residual=r, relu=relu)
            return jnp.sum(z * g)

        got = jax.grad(fused, argnums=(0, 1, 2, 3))(x, r, scale, bias)
        want = jax.grad(oracle, argnums=(0, 1, 2, 3))(x, r, scale, bias)
    else:
        def fused(x, s, b):
            z, *_ = bn_act_train(x, s, b, cfg)
            return jnp.sum(z * g)

        def oracle(x, s, b):
            z, _, _ = bn_act_reference(x, s, b, relu=relu)
            return jnp.sum(z * g)

        got = jax.grad(fused, argnums=(0, 1, 2))(x, scale, bias)
        want = jax.grad(oracle, argnums=(0, 1, 2))(x, scale, bias)

    for gg, ww in zip(got, want):
        np.testing.assert_allclose(gg, ww, atol=2e-4, rtol=1e-4)


def test_grads_zero_init_scale():
    """The zero-init final-BN case (identity residual at init): grads
    must match autodiff when scale == 0 (mask comes from z > 0)."""
    x = _rand((2, 4, 4, 8))
    r = _rand((2, 4, 4, 8), 5)
    scale = jnp.zeros((8,))
    bias = jnp.zeros((8,))
    g = _rand((2, 4, 4, 8), 3)
    cfg = make_cfg(relu=True)

    def fused(x, r, s, b):
        z, *_ = bn_add_act_train(x, r, s, b, cfg)
        return jnp.sum(z * g)

    def oracle(x, r, s, b):
        z, _, _ = bn_act_reference(x, s, b, residual=r, relu=True)
        return jnp.sum(z * g)

    got = jax.grad(fused, argnums=(0, 1, 2, 3))(x, r, scale, bias)
    want = jax.grad(oracle, argnums=(0, 1, 2, 3))(x, r, scale, bias)
    for gg, ww in zip(got, want):
        np.testing.assert_allclose(gg, ww, atol=2e-4, rtol=1e-4)


def test_sync_grads_match_single_device(mesh8):
    """dp-sharded fused BN over the mesh == one-device BN on the full
    batch — forward and dx (the SyncBN contract, `two_gpu_unit_test.py`
    semantics)."""
    from jax.sharding import PartitionSpec as P

    x = _rand((16, 4, 4, 8))
    scale = _rand((8,), 1) * 0.5 + 1.0
    bias = _rand((8,), 2) * 0.1
    g = _rand((16, 4, 4, 8), 3)
    cfg1 = make_cfg(relu=True)
    cfgN = make_cfg(relu=True, axis_name="data")

    def single(x, s, b):
        z, *_ = bn_act_train(x, s, b, cfg1)
        return jnp.sum(z * g)

    want_val, want = jax.value_and_grad(single, argnums=(0, 1, 2))(
        x, scale, bias)

    def shard_step(x, s, b, g):
        # NB: the loss stays *local* under grad — the unit's backward
        # psums the channel sums itself, so each shard feeding its local
        # cotangent yields the exact global grads (psum-of-loss through
        # autodiff would double-count under check_vma=False)
        def local(x, s, b):
            z, *_ = bn_act_train(x, s, b, cfgN)
            return jnp.sum(z * g)
        val, grads = jax.value_and_grad(local, argnums=(0, 1, 2))(x, s, b)
        return jax.lax.psum(val, "data"), grads

    mapped = jax.shard_map(
        shard_step, mesh=mesh8,
        in_specs=(P("data"), P(), P(), P("data")),
        out_specs=(P(), (P("data"), P(), P())), check_vma=False)
    got_val, got = jax.jit(mapped)(x, scale, bias, g)

    np.testing.assert_allclose(got_val, want_val, atol=1e-4, rtol=1e-5)
    np.testing.assert_allclose(got[0], want[0], atol=2e-4, rtol=1e-4)
    # param grads are psum'd inside autodiff's transpose of the stat
    # gather; each shard holds the full-batch grad
    np.testing.assert_allclose(got[1], want[1], atol=2e-3, rtol=1e-4)
    np.testing.assert_allclose(got[2], want[2], atol=2e-3, rtol=1e-4)


def test_module_running_stats_and_eval():
    x = _rand((8, 5, 5, 12))
    mod = FusedBNAct(num_features=12, relu=True, momentum=0.9)
    variables = mod.init(jax.random.PRNGKey(0), x)
    z, mut = mod.apply(variables, x, train=True, mutable=["batch_stats"])
    stats = mut["batch_stats"]
    mean = jnp.mean(x, axis=(0, 1, 2))
    var = jnp.var(x, axis=(0, 1, 2))
    n = 8 * 5 * 5
    np.testing.assert_allclose(stats["mean"], 0.1 * mean, atol=1e-5)
    np.testing.assert_allclose(stats["var"],
                               0.9 + 0.1 * var * n / (n - 1), atol=1e-5)
    # eval path uses running stats
    z_eval = mod.apply({"params": variables["params"],
                        "batch_stats": stats}, x, train=False)
    assert z_eval.shape == x.shape


@pytest.mark.slow           # ~85s pair on CPU CI: full-model trajectories
@pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
def test_resnet_fused_matches_oracle(arch):
    """Full-model check: fused-BN ResNet loss and input grad equal the
    plain-autodiff model (param trees differ; values must not)."""
    from apex_tpu import models

    ctor = models.ResNet18 if arch == "resnet18" else models.ResNet50
    x = _rand((2, 32, 32, 3))
    y = jnp.asarray([1, 3])

    outs = {}
    leaves_fused = None
    for fused in (True, False):
        model = ctor(num_classes=10, fused_bn=fused)
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        leaves, treedef = jax.tree_util.tree_flatten(variables)
        if fused:
            leaves_fused = leaves
        else:
            # graft the fused-init values onto the oracle tree: the two
            # structures differ only in the BN submodule name, so the
            # sorted leaf order (and every shape) lines up
            assert len(leaves) == len(leaves_fused)
            for a, b in zip(leaves, leaves_fused):
                assert a.shape == b.shape
            variables = jax.tree_util.tree_unflatten(treedef, leaves_fused)

        def loss_fn(xb, variables=variables, model=model):
            logits, _ = model.apply(variables, xb, train=True,
                                    mutable=["batch_stats"])
            return jnp.mean(
                -jax.nn.log_softmax(logits)[jnp.arange(2), y])

        outs[fused] = jax.value_and_grad(loss_fn)(x)

    np.testing.assert_allclose(outs[True][0], outs[False][0],
                               atol=1e-4, rtol=1e-4)
    # isolated relu-threshold ties can flip masks between the two
    # formulations (fp32 reassociation); allow a few small outliers
    np.testing.assert_allclose(outs[True][1], outs[False][1],
                               atol=5e-3, rtol=1e-2)


def test_fp8_residuals_grads_close_and_trajectory():
    """Round-5 byte-floor experiment: fp8 x-hat residuals. Gradients
    stay within a few percent of exact (e4m3 on unit-variance x-hat),
    and a short training trajectory tracks the exact one — the option
    ships as a measured-neutral experiment knob (PERF.md round-5)."""
    import flax.linen as nn
    from apex_tpu.ops.bn_act import FusedBNAct

    class Net(nn.Module):
        fp8: bool = False

        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(16, (3, 3), use_bias=False)(x)
            x = FusedBNAct(16, relu=True, fp8_residuals=self.fp8)(
                x, train=train)
            x = nn.Conv(16, (3, 3), use_bias=False)(x)
            r = x
            x = FusedBNAct(16, relu=True, fp8_residuals=self.fp8)(
                x, r, train=train)
            return jnp.mean(x ** 2, axis=(1, 2, 3))

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 12, 12, 3), jnp.float32)

    def train_losses(fp8, steps=12, lr=0.05):
        net = Net(fp8=fp8)
        variables = net.init(jax.random.PRNGKey(0), x)
        params, bs = variables["params"], variables["batch_stats"]
        losses = []

        @jax.jit
        def step(params, bs):
            def loss_fn(p):
                out, mut = net.apply(
                    {"params": p, "batch_stats": bs}, x, train=True,
                    mutable=["batch_stats"])
                return jnp.mean((out - 1.0) ** 2), mut["batch_stats"]
            (loss, bs2), g = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            params = jax.tree_util.tree_map(
                lambda p, gg: p - lr * gg, params, g)
            return params, bs2, loss

        for _ in range(steps):
            params, bs, loss = step(params, bs)
            losses.append(float(loss))
        return np.asarray(losses)

    exact = train_losses(False)
    f8 = train_losses(True)
    # same descent, small numeric drift: every step within 10% rel
    np.testing.assert_allclose(f8, exact, rtol=0.1)
    assert f8[-1] < f8[0] * 0.9, "fp8 trajectory failed to descend"
