"""apex_tpu.monitor — runtime training-health telemetry.

Two halves (see docs/monitoring.md):

- **in-graph** (:mod:`~apex_tpu.monitor.metrics`): a :class:`Metrics`
  pytree of on-device counters/gauges (loss scale, overflow/skip/growth/
  backoff counts, grad & param norms) threaded through the jitted train
  step with zero extra dispatches — ``amp.Amp(..., monitor=True)`` and
  ``FP16_Optimizer(..., monitor=True)`` maintain it automatically;
- **host-side** (:mod:`~apex_tpu.monitor.logger` /
  :mod:`~apex_tpu.monitor.sinks`): :class:`MetricsLogger` with pluggable
  sinks (stdout table / JSONL / CSV), a rolling step-time + throughput +
  MFU estimator reusing :mod:`apex_tpu.prof`, and amortized device→host
  flushes; :mod:`~apex_tpu.monitor.collectives` accounts per-step
  collective bytes from the compiled HLO.

Plus the runtime performance observatory (docs/monitoring.md#goodput):

- :mod:`~apex_tpu.monitor.goodput` — :class:`GoodputLedger` decomposes
  each step's wall clock into compute / exposed-comm / input-wait /
  host-callback / ckpt-stall / recompile / guard-rewind buckets off
  the :class:`apex_tpu.trace.Tracer` timeline, with an asserted
  attribution closure, a rolling goodput fraction, and a per-mesh-axis
  split of the exposed-comm buckets (``comm_axes_ms``) joined through
  the planned-collective registry;
- :mod:`~apex_tpu.monitor.linkbench` — α–β link calibration sweeping
  collectives per mesh axis into a MEASURED
  :class:`apex_tpu.lint.mesh_model.MeshModel`
  (``scripts/link_probe.py``);
- :mod:`~apex_tpu.monitor.comm_drift` — plan-vs-measured per-hop comm
  drift: times each :class:`apex_tpu.parallel.CommPlan` hop (or joins
  the pod observatory's measured wire times) against the plan's
  α–β-predicted ``hop_seconds`` and flags a stale link model with a
  re-linkbench trigger (``scripts/pod_audit.py --cpu8``;
  docs/tracing.md#podview);
- :mod:`~apex_tpu.monitor.numerics` — the numerics observatory
  (docs/numerics.md): per-tensor dynamic-range telemetry
  (:class:`NumericsState` carried through the step like GuardState),
  a format table pricing fp32/bf16/fp16/fp8 exponent coverage against
  the measured histograms, and :func:`precision_report` /
  :func:`placement_advisor` — the fp8 candidate generator ROADMAP
  items 2 and 5 consult (``scripts/numerics_audit.py``);
- :mod:`~apex_tpu.monitor.dynamics` — the training-dynamics
  observatory (docs/dynamics.md): gradient noise scale /
  critical-batch-size estimation (:class:`DynamicsState` carried
  through the step like NumericsState, fed by
  :func:`apex_tpu.parallel.distributed.dynamics_probe`'s registered
  scalar collectives), replica-gradient cosine/Adasum-projection
  geometry, and per-site effective-LR trajectories;
- :mod:`~apex_tpu.monitor.convergence` — the noise-calibrated A/B
  trajectory comparator (:func:`calibrate_band` /
  :func:`convergence_report`): "run B matches run A within seed
  noise", the done-bar instrument for ROADMAP items 4 and 5
  (``scripts/dynamics_audit.py --cpu8``).
"""

from apex_tpu.monitor.check import module_count_and_host_ops
from apex_tpu.monitor.comm_drift import (CommDriftReport, HopDrift,
                                         compare as compare_comm_drift,
                                         measure_hops, wire_from_pod)
from apex_tpu.monitor.collectives import (COLLECTIVE_OPCODES,
                                          collective_bytes,
                                          collective_bytes_by_axis,
                                          collective_bytes_by_dtype,
                                          collective_bytes_by_hop,
                                          collective_bytes_from_text,
                                          scope_axis_row, scope_hop,
                                          wire_report)
from apex_tpu.monitor.goodput import (BUCKETS, GoodputLedger, StepLedger,
                                      classify_span)
from apex_tpu.monitor.linkbench import (LinkFit, LinkSample, calibrate,
                                        fit_alpha_beta, linkfit_events,
                                        sweep_axis)
from apex_tpu.monitor.logger import CHANNELS, ChannelSpec, MetricsLogger
from apex_tpu.monitor.metrics import (METRIC_FIELDS, Metrics, metrics_init,
                                      metrics_snapshot, metrics_to_dict)
from apex_tpu.monitor.numerics import (FORMAT_LADDER, FORMAT_TABLE,
                                       NumericsConfig, NumericsReport,
                                       NumericsState, SiteVerdict,
                                       numerics_init, numerics_observe,
                                       placement_advisor,
                                       precision_report, site_names)
from apex_tpu.monitor.convergence import (Band, ConvergenceVerdict,
                                          calibrate_band,
                                          convergence_report)
from apex_tpu.monitor.dynamics import (DynamicsConfig, DynamicsProbe,
                                       DynamicsReport, DynamicsState,
                                       dynamics_init, dynamics_observe,
                                       dynamics_report)
from apex_tpu.monitor.sinks import CSVSink, JSONLSink, Sink, StdoutSink

__all__ = [
    "Metrics", "metrics_init", "metrics_to_dict", "metrics_snapshot",
    "METRIC_FIELDS",
    "MetricsLogger", "CHANNELS", "ChannelSpec",
    "FORMAT_TABLE", "FORMAT_LADDER", "NumericsConfig", "NumericsState",
    "NumericsReport", "SiteVerdict", "numerics_init", "numerics_observe",
    "precision_report", "placement_advisor", "site_names",
    "DynamicsConfig", "DynamicsState", "DynamicsProbe", "DynamicsReport",
    "dynamics_init", "dynamics_observe", "dynamics_report",
    "Band", "ConvergenceVerdict", "calibrate_band", "convergence_report",
    "Sink", "StdoutSink", "JSONLSink", "CSVSink",
    "COLLECTIVE_OPCODES", "collective_bytes", "collective_bytes_from_text",
    "collective_bytes_by_dtype", "collective_bytes_by_hop",
    "collective_bytes_by_axis", "scope_hop", "scope_axis_row",
    "wire_report",
    "module_count_and_host_ops",
    "GoodputLedger", "StepLedger", "BUCKETS", "classify_span",
    "LinkFit", "LinkSample", "calibrate", "fit_alpha_beta",
    "linkfit_events", "sweep_axis",
    "CommDriftReport", "HopDrift", "compare_comm_drift",
    "measure_hops", "wire_from_pod",
]
