"""Overlapped & compressed gradient collectives.

The reference DDP's entire performance story is ``allreduce_bucket``
(`apex/parallel/distributed.py:363-510`): gradients are packed into
``message_size``-bounded buckets in **reverse parameter order** (the
order backward produces them) and each bucket's NCCL all-reduce launches
as soon as its gradients are ready, overlapping the remaining backward
compute. On TPU the launch machinery is XLA's latency-hiding scheduler,
but the *structure* must still be authored: a single terminal psum gives
the scheduler nothing to hide behind. This module emits **one psum per
bucket**, chained through ``optimization_barrier`` so

- the collective combiner cannot re-merge the buckets into one terminal
  all-reduce (each bucket's reduce depends on the previous bucket's
  result — the single-comm-channel ordering of the reference), and
- each bucket's all-reduce still depends only on *its own* gradients
  upstream, so the scheduler can hoist ``all-reduce-start`` of the
  late-layer bucket behind the early-layer backward compute and emit
  ``all-reduce-start``/``all-reduce-done`` pairs with real compute
  between them (audited by ``scripts/pod_comm_budget.py``).

On top of bucketing ride **compressed collectives** in the spirit of
EQuARX (quantized all-reduce inside XLA) and DynamiQ (compressed
all-reduce with error feedback):

- ``compress="bf16"`` — the bucket psums in bf16 against fp32 masters,
  halving wire bytes;
- ``compress="int8"`` — blockwise-scaled int8 quantization with the
  two-phase quantized-all-reduce decomposition (all_to_all the quantized
  shards, dequantize+sum locally, re-quantize the summed shard,
  all_gather): per-chip ring traffic is 2·(N−1)/N of the *quantized*
  buffer, i.e. ~¼ of the fp32 all-reduce, plus one fp32 scale per
  ``compress_block`` elements.

Both carry an optional **error-feedback residual**: the compression
error of step *t* is returned to the caller and re-injected into the
gradients of step *t+1*, so the quantization bias does not accumulate
in the trajectory (the 1-bit-Adam/EF-SGD argument). The exact path
(``compress=None``) is arithmetic-identical to
:func:`apex_tpu.parallel.distributed.sync_gradients`.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.arena import native
from apex_tpu.parallel.mesh import DATA_AXIS

__all__ = ["Bucket", "bucket_plan", "bucket_table", "wire_bytes",
           "bucketed_all_reduce", "init_residual",
           "DEFAULT_MESSAGE_SIZE", "DEFAULT_COMPRESS_BLOCK",
           "COMPRESS_MODES"]

#: apex DDP parity: ``message_size`` defaults to 1e7 elements
#: (`apex/parallel/distributed.py:165`).
DEFAULT_MESSAGE_SIZE = 10_000_000

#: elements per int8 quantization block (one fp32 scale each — 1.6%
#: wire overhead at 256)
DEFAULT_COMPRESS_BLOCK = 256

COMPRESS_MODES = (None, "bf16", "int8")


def _leaf_dtype(x):
    dt = getattr(x, "dtype", None)
    return dt if dt is not None else jnp.asarray(x).dtype


def _leaf_size(x) -> int:
    shape = getattr(x, "shape", None)
    if shape is None:
        shape = jnp.asarray(x).shape
    return int(np.prod(shape, dtype=np.int64)) if shape else 1


def _is_float(x) -> bool:
    return jnp.issubdtype(_leaf_dtype(x), jnp.floating)


class Bucket(NamedTuple):
    """One reduction unit: contiguous (in reverse-parameter order) float
    leaves of one dtype, capped at ``message_size`` elements."""
    dtype: str
    leaf_idx: Tuple[int, ...]   # indices into the flattened grad tree
    elems: int

    def bytes(self) -> int:
        return self.elems * jnp.dtype(self.dtype).itemsize


def bucket_plan(leaves, message_size: Optional[int] = None) -> List[Bucket]:
    """Static bucket layout for a flattened gradient tree.

    Float leaves are grouped per dtype (the reference's type-bucketed
    ``flat_dist_call``) and walked in **reverse** leaf order — the last
    parameters' gradients, which backward finishes first, land in bucket
    0 so their reduce can launch earliest. Greedy ``message_size`` caps
    (elements) via the native planner; ``None`` packs each dtype into a
    single bucket (the ``delay_allreduce``-shaped plan).

    Works on concrete arrays, tracers, and ShapeDtypeStructs alike (the
    plan is a pure function of shapes/dtypes).
    """
    groups: Dict[str, List[int]] = {}
    for i in range(len(leaves) - 1, -1, -1):
        if _is_float(leaves[i]):
            groups.setdefault(str(jnp.dtype(_leaf_dtype(leaves[i]))),
                              []).append(i)
    out: List[Bucket] = []
    for dt, idxs in groups.items():
        sizes = np.asarray([_leaf_size(leaves[i]) for i in idxs], np.int64)
        cap = int(message_size) if message_size else int(sizes.sum()) + 1
        ids, nb = native.plan_buckets(sizes, cap)
        for b in range(nb):
            sel = tuple(i for i, bid in zip(idxs, ids) if bid == b)
            out.append(Bucket(dtype=dt, leaf_idx=sel,
                              elems=int(sum(sizes[j]
                                            for j, bid in enumerate(ids)
                                            if bid == b))))
    return out


def dtype_wire_bytes(elems: int, dtype: Optional[str],
                     compress_block: int = DEFAULT_COMPRESS_BLOCK) -> int:
    """Payload bytes of ``elems`` fp32-logical elements at a wire
    dtype: ``None`` fp32, ``"bf16"`` half, ``"int8"`` one byte per
    element plus one fp32 scale per ``compress_block``."""
    if dtype is None:
        return elems * 4
    if dtype == "bf16":
        return elems * 2
    if dtype == "int8":
        return elems + 4 * (-(-elems // compress_block))
    raise ValueError(f"unknown compress mode {dtype!r}")


def wire_bytes(plan: List[Bucket], compress=None,
               compress_block: int = DEFAULT_COMPRESS_BLOCK) -> int:
    """Payload bytes on the wire for one full sync under ``compress``,
    in **all-reduce-equivalent** units (the buffer bytes a flat
    all-reduce would carry, before the ring's 2·(N−1)/N factor), so the
    ratio against ``wire_bytes(plan, None)`` is the wire compression.

    ``compress`` is a single mode string applied to the whole sync
    (``None``/``"bf16"``/``"int8"`` — int8 includes the per-block fp32
    scales of both phases) **or** a
    :class:`apex_tpu.parallel.hierarchy.CommPlan`, whose hops may mix
    dtypes: each hop's per-chip ring-factored bytes are summed and
    normalized back by the flat ring factor, so one number stays
    comparable across flat and hierarchical schedules."""
    if hasattr(compress, "hops"):          # a hierarchy.CommPlan —
        total = sum(compress.bucket_wire_bytes(b.elems)  # duck-typed to
                    for b in plan)                       # avoid the
        return int(total / compress.flat_ring_factor())  # import cycle
    # dtype_wire_bytes raises on unknown modes
    return sum(dtype_wire_bytes(b.elems, compress, compress_block)
               for b in plan)


def bucket_table(plan: List[Bucket], compress=None,
                 compress_block: int = DEFAULT_COMPRESS_BLOCK) -> str:
    """Human-readable bytes-per-bucket table. ``compress`` (a mode
    string or a hierarchical ``CommPlan``) appends the wire MiB the
    bucket actually moves under that schedule — mixed per-hop dtypes
    accounted, not the single-mode approximation."""
    head = "  bucket  dtype     tensors      elems        MiB"
    lines = [head + ("   wire MiB" if compress is not None else "")]
    for i, b in enumerate(plan):
        row = (f"  {i:6d}  {b.dtype:8s} {len(b.leaf_idx):7d} "
               f"{b.elems:10d} {b.bytes() / 2 ** 20:10.2f}")
        if compress is not None:
            w = wire_bytes([b], compress, compress_block)
            row += f" {w / 2 ** 20:10.2f}"
        lines.append(row)
    return "\n".join(lines)


def init_residual(grads):
    """Zeroed error-feedback residual for a gradient pytree: fp32
    zeros per float leaf (compression error lives in master precision),
    empty placeholders for non-float leaves. Carry it through your step
    state with a **per-device** sharding (the residual is device-local
    state — see docs/parallel.md)."""
    def _init(g):
        if _is_float(g):
            return jnp.zeros(getattr(g, "shape", ()), jnp.float32)
        return jnp.zeros((0,), jnp.float32)
    return jax.tree_util.tree_map(_init, grads)


# --- codecs ------------------------------------------------------------------

def _quantize_int8(x: jax.Array, block: int):
    """Blockwise symmetric int8: one fp32 scale per ``block`` elements.

    Lengths not divisible by ``block`` are zero-padded to the next
    block boundary (zeros quantize exactly and never raise a block's
    max-abs scale, so the pad is invisible to the payload); ``q`` comes
    back at the padded length — mask it off with
    ``_dequantize_int8(..., n=x.shape[0])``. This lets a planner pick
    ``compress_block`` independently of bucket boundaries."""
    n = x.shape[0]
    npad = -(-n // block) * block - n
    if npad:
        x = jnp.pad(x, (0, npad))
    xb = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(xb), axis=1) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xb / safe[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale


def _dequantize_int8(q: jax.Array, scale: jax.Array, block: int,
                     n: Optional[int] = None):
    out = (q.astype(jnp.float32).reshape(-1, block)
           * scale[:, None]).reshape(-1)
    return out if n is None or n == out.shape[0] else out[:n]


def _int8_all_reduce(buf: jax.Array, axis_name: str, block: int):
    """Two-phase blockwise-quantized all-reduce of an fp32 vector whose
    length is a multiple of ``world * block``.

    Phase 1: quantize locally, ``all_to_all`` so each device collects
    every peer's copy of its own shard, dequantize + sum exactly in
    fp32. Phase 2: re-quantize the summed shard, ``all_gather``. Wire:
    2·(N−1)/N of the int8 payload + scales — the fp32 ring factor at a
    quarter of the bytes (DynamiQ / DeepSpeed compressed-allreduce
    decomposition; the reference has no distributed counterpart).

    Returns ``(sum, err_local, err_shard)``: the phase-1 quantization
    error over the whole local buffer and the phase-2 error over this
    device's shard (both in fp32, for error feedback).
    """
    world = jax.lax.axis_size(axis_name)
    per = buf.shape[0] // world
    q, s = _quantize_int8(buf, block)
    err_local = buf - _dequantize_int8(q, s, block)
    qt = jax.lax.all_to_all(q.reshape(world, per), axis_name,
                            split_axis=0, concat_axis=0, tiled=True)
    st = jax.lax.all_to_all(s.reshape(world, per // block), axis_name,
                            split_axis=0, concat_axis=0, tiled=True)
    deq = (qt.astype(jnp.float32).reshape(world, per // block, block)
           * st[:, :, None])
    shard_sum = jnp.sum(deq, axis=0).reshape(per)
    q2, s2 = _quantize_int8(shard_sum, block)
    err_shard = shard_sum - _dequantize_int8(q2, s2, block)
    total_q = jax.lax.all_gather(q2, axis_name, axis=0, tiled=True)
    total_s = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
    total = _dequantize_int8(total_q, total_s, block)
    return total, err_local, err_shard


# --- the bucketed reduction --------------------------------------------------

def bucketed_all_reduce(grads, axis_name: str = DATA_AXIS, *,
                        message_size: Optional[int] = None,
                        gradient_average: bool = True,
                        gradient_predivide_factor: float = 1.0,
                        allreduce_always_fp32: bool = False,
                        compress: Optional[str] = None,
                        residual=None,
                        compress_block: int = DEFAULT_COMPRESS_BLOCK,
                        chain: bool = True):
    """Bucketed backward-ordered (and optionally compressed) all-reduce
    of a gradient pytree. Call inside ``shard_map`` over ``axis_name``.

    Arithmetic knobs match :func:`~apex_tpu.parallel.distributed
    .sync_gradients` (`apex/parallel/distributed.py:425-475`). With
    ``compress`` set, bucket buffers are carried in fp32 (the master
    domain) through the codec; pass the previous step's ``residual``
    (from :func:`init_residual` or an earlier call) to enable error
    feedback — the return value is then ``(synced, new_residual)``
    instead of just ``synced``.

    ``chain=True`` threads each bucket's input through an
    ``optimization_barrier`` on the previous bucket's result: buckets
    reduce strictly in reverse-parameter order on one logical comm
    channel (the reference's in-order NCCL launches) and the collective
    combiner cannot fuse them back into a terminal all-reduce.
    """
    if compress not in COMPRESS_MODES:
        raise ValueError(f"compress must be one of {COMPRESS_MODES}, "
                         f"got {compress!r}")
    if compress is not None and allreduce_always_fp32:
        raise ValueError("compress already fixes the wire dtype; "
                         "allreduce_always_fp32 does not compose with it")
    if compress == "int8" and not isinstance(axis_name, str):
        raise NotImplementedError("int8 all-reduce needs a single named "
                                  "axis (all_to_all shard ownership)")
    from apex_tpu.trace.spans import span as _span

    world = jax.lax.axis_size(axis_name)
    pre = gradient_predivide_factor
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    r_leaves = None
    if residual is not None:
        r_leaves = jax.tree_util.tree_leaves(residual)
        if len(r_leaves) != len(leaves):
            raise ValueError(
                f"residual has {len(r_leaves)} leaves, grads have "
                f"{len(leaves)} — build it with init_residual(grads)")
        r_leaves = list(r_leaves)

    out = list(leaves)
    token = None
    for bi, bkt in enumerate(bucket_plan(leaves, message_size)):
        with _span(f"bucket{bi:02d}", kind="collective"):
            flat = jnp.concatenate(
                [jnp.ravel(jnp.asarray(leaves[i])) for i in bkt.leaf_idx])
            if compress is not None or allreduce_always_fp32:
                flat = flat.astype(jnp.float32)
            if pre != 1.0:
                flat = flat / pre
            if compress is not None and r_leaves is not None:
                flat = flat + jnp.concatenate(
                    [jnp.ravel(r_leaves[i]) for i in bkt.leaf_idx])
            if chain and token is not None:
                # serialize on the previous bucket's reduce: the barrier
                # is the data dependency that pins bucket order and
                # keeps the combiner from merging the buckets
                flat, _ = jax.lax.optimization_barrier((flat, token))

            err = None
            if compress == "bf16":
                wire = flat.astype(jnp.bfloat16)
                if r_leaves is not None:
                    err = flat - wire.astype(jnp.float32)
                red = jax.lax.psum(wire, axis_name).astype(jnp.float32)
            elif compress == "int8":
                n0 = flat.shape[0]
                mult = world * compress_block
                npad = -(-n0 // mult) * mult - n0
                fpad = jnp.pad(flat, (0, npad)) if npad else flat
                red, err_local, err_shard = _int8_all_reduce(
                    fpad, axis_name, compress_block)
                red = red[:n0]
                if r_leaves is not None:
                    # phase-2 error belongs to this device's shard: the
                    # owner re-injects it so it enters next step's sum
                    rank = jax.lax.axis_index(axis_name)
                    per = fpad.shape[0] // world
                    mine = jax.lax.dynamic_slice(err_local,
                                                 (rank * per,), (per,))
                    err = jax.lax.dynamic_update_slice(
                        err_local, mine + err_shard, (rank * per,))[:n0]
            else:
                red = jax.lax.psum(flat, axis_name)

            if gradient_average:
                post = world / pre
                if post != 1.0:
                    red = red / post
            token = red

            off = 0
            for i in bkt.leaf_idx:
                n = _leaf_size(leaves[i])
                shape = jnp.asarray(leaves[i]).shape
                out[i] = red[off:off + n].reshape(shape).astype(
                    _leaf_dtype(leaves[i]))
                if err is not None:
                    r_leaves[i] = err[off:off + n].reshape(shape)
                off += n

    synced = jax.tree_util.tree_unflatten(treedef, out)
    if residual is None:
        return synced
    r_def = jax.tree_util.tree_structure(residual)
    return synced, jax.tree_util.tree_unflatten(r_def, r_leaves)
