"""Multi-host bring-up — the `apex.parallel.multiproc` equivalent.

The reference launches one Python process per GPU with ``--rank i`` args
and env-var rendezvous (`apex/parallel/multiproc.py:1-35`,
`torch.distributed.launch`). On TPU pods the runtime already starts one
process per host; what remains is initializing the JAX distributed
client so every host sees the global device set. :func:`distributed_init`
wraps ``jax.distributed.initialize`` with the same env-var conventions
(`MASTER_ADDR``/``MASTER_PORT``/``RANK``/``WORLD_SIZE``) the reference's
launcher exports, so scripts written against either convention come up.

Single-host / single-process runs are a no-op — exactly like running a
reference script without the launcher.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["distributed_init", "is_distributed", "process_index",
           "process_count", "maybe_print", "enable_crash_dumps",
           "elastic_run", "shrink_schedule"]

_initialized = False


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None) -> None:
    """Initialize multi-host JAX, tolerating the reference's env vars.

    Resolution order per field: explicit argument → JAX's own env/TPU
    metadata (pass-through None) → the torch.distributed.launch
    convention (``MASTER_ADDR:MASTER_PORT``, ``WORLD_SIZE``, ``RANK``).
    Safe to call unconditionally: single-process (no env, no args) is a
    no-op, and repeat calls are ignored.
    """
    global _initialized
    if _initialized:
        return

    if coordinator_address is None and "MASTER_ADDR" in os.environ:
        port = os.environ.get("MASTER_PORT", "1234")
        coordinator_address = f"{os.environ['MASTER_ADDR']}:{port}"
    if num_processes is None and "WORLD_SIZE" in os.environ:
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and "RANK" in os.environ:
        process_id = int(os.environ["RANK"])

    if (coordinator_address is None and num_processes is None
            and process_id is None
            and not os.environ.get("TPU_WORKER_HOSTNAMES")
            and not os.environ.get("COORDINATOR_ADDRESS")):
        return  # single process — nothing to initialize

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True


def enable_crash_dumps(path: str = "apex_tpu_crash.jsonl", *,
                       capacity: int = 64,
                       hang_deadline_s: Optional[float] = None,
                       escalation=None,
                       collective_deadline_s: Optional[float] = None,
                       membership=None):
    """One-call forensics bring-up for (multi-host) launches.

    Builds a :class:`apex_tpu.trace.Tracer`, a per-rank
    :class:`~apex_tpu.trace.FlightRecorder` (``path`` gets
    ``trace.rank_path`` applied on multi-process runs, so every rank of
    a pod dumps to its own file) with the excepthook/SIGTERM/atexit
    handlers installed, and — when ``hang_deadline_s`` is set — a
    started :class:`~apex_tpu.trace.HangWatchdog`. Call after
    :func:`distributed_init` so rank resolution sees the cluster.

    ``escalation`` (an :class:`apex_tpu.ckpt.EscalationPolicy`) wires
    fault *recovery* on top of the forensics: SIGTERM preemption saves
    the last host checkpoint snapshot before the dump, and a watchdog
    stall escalates to checkpoint-save → crash-dump → nonzero exit
    (docs/checkpointing.md §escalation).

    ``collective_deadline_s`` adds the tier between "one rank slow"
    and "no step landed": a started
    :class:`apex_tpu.cluster.CollectiveDeadline` polling the tracer's
    open ``kind="collective"`` spans — a collective still open past
    the deadline is *hung*, not slow, and trips
    ``escalation.trip("collective:<span>")`` with the offender named.
    ``membership`` (an :class:`apex_tpu.cluster.ClusterMembership`)
    tags its events with the current generation.

    Returns ``(tracer, recorder, watchdog-or-None,
    collective-deadline-or-None)`` — a fixed shape regardless of which
    tiers are enabled; enter the tracer around the train loop and wrap
    steps in ``trace.step()`` / ``trace.span`` so dumps carry span
    timelines (docs/tracing.md).
    """
    from apex_tpu import trace as _trace
    tracer = _trace.Tracer()
    recorder = _trace.FlightRecorder(path, capacity=capacity,
                                     tracer=tracer,
                                     escalation=escalation).install()
    if escalation is not None and getattr(escalation, "recorder",
                                          None) is None:
        escalation.recorder = recorder
    watchdog = None
    if hang_deadline_s:
        watchdog = _trace.HangWatchdog(
            hang_deadline_s, recorder=recorder, tracer=tracer,
            on_stall=escalation).start()
    deadline = None
    if collective_deadline_s:
        from apex_tpu.cluster import CollectiveDeadline
        deadline = CollectiveDeadline(
            tracer, deadline_s=collective_deadline_s,
            escalation=escalation,
            event_sink=getattr(membership, "event_sink", None),
            generation=(membership.refresh if membership is not None
                        else None)).start()
    return tracer, recorder, watchdog, deadline


# --- elastic restart-on-smaller-mesh -----------------------------------------

def shrink_schedule(world: int, *, min_world: int = 1,
                    factor: int = 2) -> list:
    """The default mesh-shrink ladder: ``world, world//factor, ...``
    down to ``min_world`` — each entry a size every apex_tpu ZeRO/DDP
    axis accepts (shards re-partition to any size; see
    docs/checkpointing.md elasticity matrix)."""
    if int(factor) < 2:
        raise ValueError(f"shrink factor must be >= 2, got {factor} "
                         f"(factor 1 would never shrink)")
    out, w = [], int(world)
    while w >= max(int(min_world), 1):
        out.append(w)
        if w == 1:
            break
        w //= int(factor)
    return out


def elastic_run(train_fn, *, world_sizes, max_restarts: Optional[int]
                = None, escalation_exit_codes=(75,),
                restart_backoff_s: float = 0.0,
                restart_backoff_cap_s: float = 60.0,
                cluster_dir: Optional[str] = None,
                heartbeat_dir: Optional[str] = None,
                event_sink=None):
    """Restart-on-smaller-mesh: the single-controller recovery loop.

    ``train_fn(world, attempt)`` runs the training job on ``world``
    devices (restoring from the latest committed checkpoint itself —
    ``ckpt.CheckpointManager.restore`` makes resume mesh-agnostic). A
    completed call returns its result; an escalation —
    :class:`apex_tpu.ckpt.PreemptionError`, or ``SystemExit`` with a
    code in ``escalation_exit_codes`` (the watchdog policy's
    ``os._exit(75)`` surfaces this way when ``train_fn`` wraps a
    subprocess) — shrinks to the next mesh size and continues instead
    of dying. Any other exception propagates: escalation is for
    capacity loss, not for masking bugs.

    On a multi-process pod the same contract holds one level up: the
    process manager re-launches ranks with the smaller ``WORLD_SIZE``
    when a rank exits with :data:`apex_tpu.ckpt.ESCALATION_EXIT_CODE`;
    this helper is that loop for single-controller (one-process,
    many-device) jobs and for tests.

    ``restart_backoff_s`` > 0 sleeps a jittered exponential delay
    (``backoff · 2^(attempt-1)``, capped at ``restart_backoff_cap_s``,
    ×[0.5, 1.5) jitter) before each relaunch: a pod-wide preemption
    makes every controller escalate in the same instant, and N jobs
    re-attaching to the scheduler/checkpoint filesystem in lockstep is
    a thundering herd the chaos runs exercise. Default 0 keeps tests
    instant.

    ``cluster_dir`` makes the loop *generation-fenced* (elastic_run
    v2, docs/resilience.md#control-plane): before every shrink-restart
    it calls :func:`apex_tpu.cluster.relaunch` — reporting any
    lease-expired (dead) ranks, committing the next generation so
    every straggler of the failed attempt is fenced out of the shared
    checkpoint tree, and garbage-collecting stale lease files (and,
    with ``heartbeat_dir``, stale straggler heartbeats — a dead rank's
    last beat must not read as a "silent rank" of the new epoch).
    ``event_sink`` (``logger.record_cluster``) streams the hygiene
    pass's events.
    """
    from apex_tpu.ckpt import PreemptionError
    from apex_tpu.utils.backoff import backoff_sleep
    sizes = list(world_sizes)
    if not sizes:
        raise ValueError("world_sizes must name at least one mesh size")
    i, attempt = 0, 0
    while True:
        world = sizes[i]
        try:
            return train_fn(world, attempt)
        except PreemptionError as e:
            maybe_print(f"apex_tpu.elastic: escalated on world={world} "
                        f"({e.reason}); shrinking", rank0=True)
        except SystemExit as e:
            if e.code not in escalation_exit_codes:
                raise
            maybe_print(f"apex_tpu.elastic: exit code {e.code} on "
                        f"world={world}; shrinking", rank0=True)
        attempt += 1
        if max_restarts is not None and attempt > max_restarts:
            raise RuntimeError(
                f"elastic_run: {attempt} restarts exhausted "
                f"max_restarts={max_restarts}")
        if i + 1 < len(sizes):
            i += 1
        else:
            raise RuntimeError(
                f"elastic_run: escalated at the smallest mesh size "
                f"{sizes[-1]} — no capacity left to shrink to")
        # backoff only before an actual relaunch — sleeping ahead of
        # the capacity check above would burn the whole delay right
        # before a guaranteed-fatal raise
        if restart_backoff_s > 0:
            backoff_sleep(attempt - 1, base_s=restart_backoff_s,
                          cap_s=restart_backoff_cap_s)
        if cluster_dir is not None:
            # fence + clean BEFORE the relaunch: the new attempt joins
            # a fresh generation and a clean lease/heartbeat table; a
            # zombie of the failed attempt now fails its fence checks
            # instead of corrupting the new run's checkpoints. The
            # controller only OBSERVES here — join() would overwrite
            # the dead rank's lease with the controller's own (same
            # default rank) and silently drop it from the report
            from apex_tpu import cluster as _cluster
            member = _cluster.ClusterMembership(cluster_dir,
                                               event_sink=event_sink)
            dead = member.expired_ranks()
            if dead:
                maybe_print(f"apex_tpu.elastic: lease-expired ranks "
                            f"{dead} (dead members of the failed "
                            f"attempt)", rank0=True)
            gen = _cluster.relaunch(
                cluster_dir, reason=f"elastic_restart:{attempt}",
                heartbeat_dir=heartbeat_dir, event_sink=event_sink)
            maybe_print(f"apex_tpu.elastic: relaunching under "
                        f"generation {gen}", rank0=True)


def is_distributed() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


#: print verbosity, the `_amp_state.verbosity` knob
#: (`apex/amp/_amp_state.py:36-50`). 0 silences maybe_print entirely.
verbosity = 1


def maybe_print(msg: str, rank0: bool = False) -> None:
    """Verbosity- and rank-aware print (`_amp_state.maybe_print`,
    `apex/amp/_amp_state.py:38-50`)."""
    if verbosity <= 0:
        return
    if rank0 and jax.process_index() != 0:
        return
    print(msg)
