"""Roofline observatory + perf sentinel tests.

The CI-shaped halves of `scripts/roofline_audit.py --cpu8`: the per-op
roofline join over the committed BERT-layer fixture (attribution
closure, bound classes, the known fused-backward gap), the AOT-only
analytic path, the noise-aware sentinel's direction/threshold/waiver
semantics over synthetic trajectories, schema negative twins for
``--kind roofline``, and the autotune-origin compile split in
`prof.compile_watch`.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import monitor, prof
from apex_tpu.prof import roofline, sentinel
from apex_tpu.prof.compile_watch import autotune_scope, in_autotune

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCHEMA_SCRIPT = os.path.join(_REPO_ROOT, "scripts",
                              "check_metrics_schema.py")
BERT_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                            "bert_layer.xplane.pb")


def _load_schema_mod():
    from importlib import util as _util
    spec = _util.spec_from_file_location("check_metrics_schema",
                                        _SCHEMA_SCRIPT)
    mod = _util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- the roofline join over the committed fixture ----------------------------

class TestBertFixtureJoin:
    """The committed BERT-layer fixture reproduces PERF.md's round-5
    ledger through the tool (regenerate with
    scripts/make_xplane_fixture.py --bert)."""

    @pytest.fixture(autouse=True)
    def _pure(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_XPLANE_PURE", "1")

    @pytest.fixture()
    def report(self):
        tp = prof.parse_trace(BERT_FIXTURE)
        return roofline.roofline_report(profile=tp,
                                        device_kind="TPU v5 lite")

    def test_closure_over_module_device_time(self, report):
        ok, err = report.check_closure(tolerance=0.05)
        assert ok, f"attribution hole: {err:.4f} > 0.05"
        assert report.measured and len(report.rows) == 7

    def test_bound_classes_and_mxu_cap(self, report):
        by_name = {r.name: r for r in report.rows}
        for name in ("custom-call.201", "custom-call.202"):
            r = by_name[name]
            assert (r.family, r.bound, r.mxu_cap) == \
                ("attention", "compute", 0.5), r
        for name in ("fusion.210", "fusion.211", "fusion.230"):
            assert by_name[name].bound == "memory", by_name[name]
        for name in ("dot.220", "dot.221"):
            assert (by_name[name].family, by_name[name].bound) == \
                ("mlp", "compute"), by_name[name]
        for r in report.rows:
            assert r.efficiency is not None and 0.0 <= r.efficiency <= 1.0

    def test_worst_gaps_names_the_fused_backward_gap(self, report):
        """The PERF.md round-5 line — backward attention ~550 us vs its
        ~440 us d=64 MXU floor — reproduced by the tool."""
        gaps = report.worst_gaps(3)
        bwd = [g for g in gaps if g["op"] == "custom-call.202"]
        assert bwd, [g["op"] for g in gaps]
        top = bwd[0]
        assert 540.0 <= top["measured_us"] <= 560.0
        assert 420.0 <= top["attainable_us"] <= 450.0
        assert top["fingerprint"].startswith("attention|custom-call|")

    def test_fingerprints_stable_across_reruns(self, report):
        tp = prof.parse_trace(BERT_FIXTURE)
        rep2 = roofline.roofline_report(profile=tp,
                                        device_kind="TPU v5 lite")
        assert [r.fingerprint for r in report.rows] == \
            [r.fingerprint for r in rep2.rows]

    def test_events_pass_schema(self, report, tmp_path):
        path = tmp_path / "roofline.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], roofline_sink=monitor.JSONLSink(str(path)))
        logger.attach_roofline_report(report, step=5)
        logger.close()
        mod = _load_schema_mod()
        lines = path.read_text().splitlines()
        assert mod.check_roofline_lines(lines) == []
        assert all(json.loads(l)["kind"] == "roofline" for l in lines)


def test_aot_only_report_has_no_measurements():
    def step(x, w):
        return jnp.tanh(x @ w).sum()

    compiled = jax.jit(step).lower(
        jax.ShapeDtypeStruct((128, 256), jnp.float32),
        jax.ShapeDtypeStruct((256, 64), jnp.float32)).compile()
    rep = roofline.roofline_report(compiled=compiled,
                                   device_kind="TPU v5 lite")
    assert rep.rows and not rep.measured
    assert all(r.measured_us is None and r.efficiency is None
               and r.gap_us is None for r in rep.rows)
    assert rep.worst_gaps(5) == []
    # dot FLOPs land (in the dot row or folded into a calling fusion)
    assert sum(r.flops for r in rep.rows) == \
        pytest.approx(2 * 128 * 256 * 64, rel=0.01)


def test_unknown_device_classifies_unknown():
    """CPU/unknown chips have no peak table entry: bounds degrade to
    'unknown' rather than inventing an efficiency."""
    tp = None
    rep = roofline.roofline_report(
        compiled="ENTRY main {\n  %dot.1 = f32[8,8]{1,0} "
                 "dot(f32[8,8]{1,0} %a, f32[8,8]{1,0} %b), "
                 "lhs_contracting_dims={1}, rhs_contracting_dims={0}\n}",
        profile=tp, device_kind="weird-chip")
    assert rep.peak_flops == 0.0 and rep.hbm_bw == 0.0
    assert all(r.bound == "unknown" for r in rep.rows)


def test_classify_family_scope_then_structure():
    assert roofline.classify_family("bert/attn/flash_attention_fwd") \
        == "attention"
    assert roofline.classify_family("encoder/layer_norm/ln_bwd") \
        == "layer_norm"
    assert roofline.classify_family("", "all-reduce") == "collective"
    assert roofline.classify_family("", "dot") == "gemm"
    assert roofline.classify_family("", "convolution") == "conv"
    assert roofline.classify_family("nothing/here", "fusion") == "other"
    for fam in roofline.FAMILIES:
        assert isinstance(fam, str)


# --- the sentinel ------------------------------------------------------------

def _spec(**kw):
    defaults = dict(name="mfu", path=("extra", "mfu"),
                    direction="higher")
    defaults.update(kw)
    return sentinel.MetricSpec(**defaults)


class TestSentinelCheckRow:
    def test_direction_aware_gain_never_flags(self):
        hist = [0.30, 0.31, 0.30, 0.32]
        v = sentinel.check_row(hist, 0.45, _spec())
        assert not v.regressed and v.degradation < 0

    def test_drop_beyond_threshold_flags(self):
        hist = [0.30, 0.31, 0.30, 0.32]
        v = sentinel.check_row(hist, 0.20, _spec())
        assert v.regressed and v.baseline == pytest.approx(0.305)

    def test_noise_widens_the_threshold(self):
        """The same absolute drop passes on a noisy trajectory and
        fails on a quiet one — the MAD term at work."""
        quiet = [100.0, 100.5, 99.8, 100.2]
        noisy = [100.0, 80.0, 120.0, 95.0, 108.0]
        drop = 90.0
        assert sentinel.check_row(quiet, drop, _spec()).regressed
        assert not sentinel.check_row(noisy, drop, _spec()).regressed

    def test_lower_is_better_direction(self):
        spec = _spec(name="ms_per_step", direction="lower")
        hist = [46.0, 46.5, 45.8]
        assert sentinel.check_row(hist, 60.0, spec).regressed
        assert not sentinel.check_row(hist, 40.0, spec).regressed

    def test_counter_any_increase_fires(self):
        spec = _spec(name="lint_errors", direction="lower", counter=True)
        assert sentinel.check_row([0.0, 0.0], 1.0, spec).regressed
        assert not sentinel.check_row([0.0, 0.0], 0.0, spec).regressed

    def test_min_history_guard(self):
        v = sentinel.check_row([0.30], 0.01, _spec())
        assert not v.regressed and "insufficient history" in v.note


class TestSentinelTrajectory:
    def _rows(self, mfus):
        return [{"path": f"r{i}", "metrics": {"mfu": m}}
                for i, m in enumerate(mfus)]

    def test_clean_trajectory_quiet(self):
        rep = sentinel.check_trajectory(self._rows([0.30, 0.31, 0.32]))
        assert rep.ok and rep.subject == "r2"

    def test_regression_fires_and_waiver_suppresses(self):
        rows = self._rows([0.30, 0.31, 0.30, 0.18])
        rep = sentinel.check_trajectory(rows)
        assert [v.metric for v in rep.regressions] == ["mfu"]
        waived = sentinel.check_trajectory(
            rows, waivers={"regress|mfu": {"reason": "accepted",
                                           "allow_to": 0.18}})
        assert waived.ok and waived.verdicts[0].waived

    def test_waiver_allow_to_refires_past_the_floor(self):
        rows = self._rows([0.30, 0.31, 0.30, 0.10])
        rep = sentinel.check_trajectory(
            rows, waivers={"regress|mfu": {"reason": "accepted",
                                           "allow_to": 0.18}})
        assert not rep.ok, "degrading past allow_to must re-fire"

    def test_metricless_rows_noted_not_flagged(self):
        rows = self._rows([0.30, 0.31, 0.32])
        rows.insert(2, {"path": "failed", "metrics": {},
                        "note": "no parsed bench row (rc=1) — skipped"})
        rep = sentinel.check_trajectory(rows)
        assert rep.ok and any("skipped" in n for n in rep.notes)

    def test_replay_judges_every_prefix(self):
        reports = sentinel.replay_trajectory(
            self._rows([0.30, 0.31, 0.30, 0.32, 0.31]))
        assert len(reports) == 3 and all(r.ok for r in reports)

    def test_regress_events_pass_schema(self):
        rep = sentinel.check_trajectory(
            self._rows([0.30, 0.31, 0.30, 0.18]))
        mod = _load_schema_mod()
        lines = [json.dumps(e) for e in rep.to_events()]
        assert mod.check_roofline_lines(lines) == []


def test_extract_metrics_from_bench_row():
    row = {"value": 2755.0, "extra": {"batch": 128, "mfu": 0.343,
                                      "lint_errors": 0}}
    m = sentinel.extract_metrics(row)
    assert m["device_img_s"] == 2755.0
    assert m["ms_per_step"] == pytest.approx(128 / 2755.0 * 1e3)
    assert m["mfu"] == 0.343 and m["lint_errors"] == 0.0
    assert sentinel.extract_metrics(None) == {}


def test_save_and_load_baseline_roundtrip(tmp_path):
    path = str(tmp_path / "perf_baseline.json")
    rep = sentinel.check_trajectory(
        [{"path": f"r{i}", "metrics": {"mfu": m}}
         for i, m in enumerate([0.30, 0.31, 0.30, 0.18])])
    assert not rep.ok
    sentinel.save_baseline(path, rep, reason="tile sweep tradeoff")
    waivers = sentinel.load_baseline(path)
    assert waivers["regress|mfu"]["allow_to"] == 0.18
    # the written waiver suppresses the same regression
    rep2 = sentinel.check_trajectory(
        [{"path": f"r{i}", "metrics": {"mfu": m}}
         for i, m in enumerate([0.30, 0.31, 0.30, 0.18])],
        waivers=waivers)
    assert rep2.ok


def test_baseline_declared_metric_judged_and_preserved(tmp_path):
    """The committed perf-baseline's "metrics" section declares extra
    judged columns (the ddp_wire_bytes gate): parsed into MetricSpecs,
    extracted from rows, direction-aware flagged on regression, quiet
    on no-change — and --write-baseline refreshes must not drop the
    section."""
    path = str(tmp_path / "perf_baseline.json")
    with open(path, "w") as f:
        json.dump({"version": 1, "waivers": {}, "metrics": [
            {"name": "ddp_wire_bytes",
             "path": ["extra", "ddp_comm_modes", "modes", "hier_int8",
                      "wire_bytes"],
             "direction": "lower", "rel_floor": 0.02}]}, f)
    extra = sentinel.metric_specs_from_baseline(path)
    assert [s.name for s in extra] == ["ddp_wire_bytes"]
    specs = tuple(sentinel.METRICS) + tuple(extra)

    def row(w):
        return {"metrics": sentinel.extract_metrics(
            {"value": 100.0, "extra": {"batch": 8, "ddp_comm_modes": {
                "modes": {"hier_int8": {"wire_bytes": w}}}}}, specs)}

    base = [row(25_000_000), row(25_100_000), row(24_900_000)]
    rep = sentinel.check_trajectory(base + [row(99_000_000)],
                                    specs=specs)
    bad = [v for v in rep.verdicts if v.metric == "ddp_wire_bytes"]
    assert bad and bad[0].regressed
    rep_ok = sentinel.check_trajectory(base + [row(25_000_000)],
                                       specs=specs)
    ok = [v for v in rep_ok.verdicts if v.metric == "ddp_wire_bytes"]
    assert ok and not ok[0].regressed
    # write-baseline keeps the metrics section alongside new waivers
    sentinel.save_baseline(path, rep, reason="accepted")
    assert sentinel.metric_specs_from_baseline(path) == extra
    assert "regress|ddp_wire_bytes" in sentinel.load_baseline(path)
    # malformed entries are loud, not silently dropped
    with open(path, "w") as f:
        json.dump({"metrics": [{"name": "x", "direction": "lower"}]}, f)
    with pytest.raises(ValueError):
        sentinel.metric_specs_from_baseline(path)
    with pytest.raises(ValueError):
        sentinel.metric_specs_from_baseline(
            {"metrics": [{"name": "x", "path": ["v"],
                          "direction": "sideways"}]})


# --- schema negative twins ---------------------------------------------------

def test_roofline_schema_rejects_bad_streams():
    mod = _load_schema_mod()
    ok_roofline = {"kind": "roofline", "rank": 0, "step": None,
                   "op": "dot.1", "opcode": "dot", "family": "mlp",
                   "scope": "bert/mlp/fc1", "bound": "compute",
                   "flops": 1e9, "bytes": 1e6, "attainable_us": 100.0,
                   "measured_us": None, "efficiency": None,
                   "gap_us": None, "occurrences": 0, "dtype": "bf16",
                   "fingerprint": "mlp|dot|bert/mlp/fc1|bf16[8,8]"}
    ok_regress = {"kind": "regress", "rank": 0, "metric": "mfu",
                  "direction": "higher", "latest": 0.3,
                  "baseline": 0.31, "mad": 0.005, "threshold": 0.02,
                  "degradation": 0.01, "n_history": 3,
                  "regressed": False, "waived": False,
                  "fingerprint": "regress|mfu"}
    ok = [json.dumps(ok_roofline), json.dumps(ok_regress)]
    assert mod.check_roofline_lines(ok) == []
    # bad bound enum
    bad = dict(ok_roofline, bound="io")
    assert mod.check_roofline_lines([json.dumps(bad)])
    # efficiency out of [0, 1]
    bad = dict(ok_roofline, measured_us=50.0, efficiency=1.7)
    assert mod.check_roofline_lines([json.dumps(bad)])
    # null on a non-nullable key
    bad = dict(ok_roofline, attainable_us=None)
    assert mod.check_roofline_lines([json.dumps(bad)])
    # missing required key
    bad = dict(ok_roofline); bad.pop("fingerprint")
    assert mod.check_roofline_lines([json.dumps(bad)])
    # negative device time
    bad = dict(ok_roofline, measured_us=-3.0)
    assert mod.check_roofline_lines([json.dumps(bad)])
    # bad regress direction / non-bool regressed
    bad = dict(ok_regress, direction="sideways")
    assert mod.check_roofline_lines([json.dumps(bad)])
    bad = dict(ok_regress, regressed=1)
    assert mod.check_roofline_lines([json.dumps(bad)])
    # unknown kind / empty stream
    assert mod.check_roofline_lines([json.dumps({"kind": "metrics"})])
    assert mod.check_roofline_lines([])


def test_roofline_schema_cli_on_real_stream(tmp_path):
    """Subprocess leg: the exact CLI a deployment runs, over a stream
    the logger actually wrote (AOT report rows are the nullable-
    measured case)."""
    import subprocess

    def step(x):
        return (x @ x).sum()

    compiled = jax.jit(step).lower(
        jax.ShapeDtypeStruct((64, 64), jnp.float32)).compile()
    rep = roofline.roofline_report(compiled=compiled,
                                   device_kind="TPU v5 lite")
    path = tmp_path / "events.jsonl"
    logger = monitor.MetricsLogger(
        sinks=[], roofline_sink=monitor.JSONLSink(str(path)))
    logger.attach_roofline_report(rep)
    logger.close()
    r = subprocess.run([sys.executable, _SCHEMA_SCRIPT, "--kind",
                        "roofline", str(path)],
                       capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r.returncode == 0, r.stdout + r.stderr


def test_sentinel_cli_never_reports_clean_without_judging(tmp_path):
    """A gate that judged nothing must exit 2, not 'clean': unreadable
    inputs (a moved trajectory, a literally-passed glob) and
    metric-less trajectories are IO/usage errors."""
    import subprocess

    cli = os.path.join(_REPO_ROOT, "scripts", "perf_sentinel.py")

    def run(*args):
        return subprocess.run([sys.executable, cli, "--check", *args],
                              capture_output=True, text=True,
                              cwd=_REPO_ROOT)

    r = run(str(tmp_path / "nope_r01.json"))
    assert r.returncode == 2 and "unreadable" in r.stderr
    failed = tmp_path / "failed.json"
    failed.write_text(json.dumps({"n": 5, "rc": 1, "parsed": None}))
    r = run(str(failed))
    assert r.returncode == 2 and "no metric-bearing rows" in r.stderr
    # --write-baseline without --baseline is a usage error, not a
    # silently-dropped waiver
    good = tmp_path / "good.json"
    good.write_text(json.dumps(
        {"value": 100.0, "extra": {"batch": 8, "mfu": 0.3}}))
    r = run(str(good), "--write-baseline", "reason")
    assert r.returncode == 2 and "--baseline" in r.stderr
    # a corrupt committed waiver file is a config error (2), never an
    # "unwaived regression" (1)
    bad_baseline = tmp_path / "baseline.json"
    bad_baseline.write_text('{"waivers": {,}}')
    r = run(str(good), str(good), str(good),
            "--baseline", str(bad_baseline))
    assert r.returncode == 2 and str(bad_baseline) in r.stderr


def test_sentinel_cli_replay_jsonl_carries_every_prefix(tmp_path):
    """--replay exit 1 on a MID-trajectory regression must be backed by
    the emitted JSONL: the regressed verdicts of every prefix-report
    appear in the stream, not only the final row's."""
    import subprocess

    cli = os.path.join(_REPO_ROOT, "scripts", "perf_sentinel.py")
    files = []
    for i, m in enumerate([0.30, 0.31, 0.30, 0.18, 0.31, 0.30]):
        p = tmp_path / f"r{i:02d}.json"
        p.write_text(json.dumps({"extra": {"mfu": m}}))
        files.append(str(p))
    out = tmp_path / "out.jsonl"
    r = subprocess.run([sys.executable, cli, "--check", *files,
                        "--replay", "--jsonl", str(out)],
                       capture_output=True, text=True, cwd=_REPO_ROOT)
    assert r.returncode == 1, r.stdout + r.stderr
    events = [json.loads(l) for l in out.read_text().splitlines()]
    assert any(e["regressed"] for e in events), \
        "the r03 regression (recovered later) is missing from the JSONL"


# --- autotune-origin compile split -------------------------------------------

def test_autotune_scope_splits_compile_counters():
    from apex_tpu.prof import compile_watch

    compile_watch.install()
    base = prof.global_counters()

    def candidate(x):
        return jnp.sin(x).sum()

    assert not in_autotune()
    with autotune_scope():
        assert in_autotune()
        with autotune_scope():              # re-entrant
            assert in_autotune()
        jax.jit(candidate).lower(
            jax.ShapeDtypeStruct((17, 3), jnp.float32)).compile()
    assert not in_autotune()
    jax.jit(candidate).lower(
        jax.ShapeDtypeStruct((19, 5), jnp.float32)).compile()

    g = prof.global_counters()
    d_compiles = g["compiles"] - base["compiles"]
    d_autotune = g["autotune_compiles"] - base["autotune_compiles"]
    if not compile_watch.installed():
        pytest.skip("jax.monitoring hooks unavailable")
    assert d_compiles == 2, (base, g)
    assert d_autotune == 1, "exactly the in-scope compile tags autotune"
    assert g["autotune_secs"] >= base["autotune_secs"]


def test_function_watch_counts_autotune_subset():
    from apex_tpu.prof import compile_watch
    if not compile_watch.installed():
        pytest.skip("jax.monitoring hooks unavailable")
    watcher = prof.CompileWatcher()
    f = watcher.watch(lambda x: x * 2.0, name="f")
    with autotune_scope():
        f(jnp.ones((4,)))                   # first compile: autotune
    f(jnp.ones((8,)))                       # retrace, plain compile
    w = watcher.watches["f"]
    assert w.n_compiles == 2 and w.n_autotune_compiles == 1
    assert watcher.counters()["f"]["n_autotune_compiles"] == 1
    assert "autotune" in watcher.report()
