"""ImageNet ResNet trainer — the `examples/imagenet/main_amp.py` mirror.

Reference: `examples/imagenet/main_amp.py` (argparse flags mapping 1:1 to
``amp.initialize`` kwargs `:157-161`, ``--sync_bn`` conversion `:142-145`,
apex DDP wrap `:168-175`, CUDA-stream ``data_prefetcher`` with async H2D +
fp16 cast `:264-317`, train loop printing img/s `:319`).

TPU-native translation:

- one SPMD program over a data mesh replaces the per-rank launch;
  ``--local_rank`` is gone (`jax.distributed` handles multi-host);
- the prefetcher overlaps host→device transfer with compute by keeping
  ``--prefetch`` batches in flight (JAX dispatch is async, so a plain
  bounded queue of device-put batches is the whole machinery);
- ``--opt-level/--keep-batchnorm-fp32/--loss-scale`` build the Policy
  exactly like the reference feeds ``amp.initialize``.

Runs out of the box on synthetic data (no dataset in the image); point
``--data`` at an ImageFolder-style tree to train on real JPEGs through
``apex_tpu.data`` (threaded PIL decode + RandomResizedCrop/flip + device
prefetch). At startup with ``--data`` the loader-only throughput is
measured and printed next to the compute throughput, so input-bound
configs are called out explicitly.

    python main_amp.py -b 128 --epochs 1 --steps-per-epoch 50
    python main_amp.py --sync_bn --opt-level O2 --loss-scale dynamic
"""

import argparse

import os
import sys

# allow running from a source checkout without installation
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..")))

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, models, ops, parallel
from apex_tpu.data import (DevicePrefetcher, ImageFolderSource,
                           measure_source, synthetic_source)
from apex_tpu.optim import FusedSGD


ARCHS = {
    "resnet18": models.ResNet18,
    "resnet50": models.ResNet50,
    "resnet101": models.ResNet101,
}


def parse_args():
    parser = argparse.ArgumentParser(description="apex_tpu ImageNet")
    parser.add_argument("--data", metavar="DIR", default=None,
                        help="path to dataset (synthetic if omitted)")
    parser.add_argument("--arch", "-a", default="resnet50", choices=ARCHS)
    parser.add_argument("--epochs", default=1, type=int)
    parser.add_argument("--steps-per-epoch", default=100, type=int)
    parser.add_argument("-b", "--batch-size", default=128, type=int,
                        help="GLOBAL batch size (split over the mesh)")
    parser.add_argument("--lr", "--learning-rate", default=0.1, type=float)
    parser.add_argument("--momentum", default=0.9, type=float)
    parser.add_argument("--weight-decay", "--wd", default=1e-4, type=float)
    parser.add_argument("--print-freq", "-p", default=10, type=int)
    parser.add_argument("--image-size", default=224, type=int)
    parser.add_argument("--prof", default=-1, type=int,
                        help="profile this many steps into ./prof_trace")
    parser.add_argument("--deterministic", action="store_true")
    parser.add_argument("--sync_bn", action="store_true",
                        help="sync BN stats over the data axis")
    parser.add_argument("--opt-level", type=str, default="O2")
    parser.add_argument("--keep-batchnorm-fp32", type=str, default=None)
    parser.add_argument("--loss-scale", type=str, default=None)
    parser.add_argument("--cache", metavar="CACHEDIR", default=None,
                        help="packed pre-decoded uint8 shard cache "
                             "(built from --data on first use) — the "
                             "DALI-class input path")
    parser.add_argument("--prefetch", default=2, type=int)
    parser.add_argument("--loader-workers", default=None, type=int,
                        help="decode threads for --data (default: cores)")
    return parser.parse_args()


# the device-put prefetcher lives in apex_tpu.data now; keep the example
# name for readers of the reference script
Prefetcher = DevicePrefetcher
synthetic_batches = synthetic_source


def main():
    args = parse_args()
    if args.deterministic:
        # one seed, highest matmul precision — the cudnn.deterministic
        # analogue (`main_amp.py:120-128`)
        jax.config.update("jax_default_matmul_precision", "highest")

    mesh = parallel.data_parallel_mesh()
    n_dev = mesh.shape[parallel.DATA_AXIS]
    if args.batch_size % n_dev:
        raise SystemExit(f"global batch {args.batch_size} must divide "
                         f"over {n_dev} devices")

    # --opt-level/--keep-batchnorm-fp32/--loss-scale -> Policy, exactly the
    # reference's amp.initialize kwarg plumbing (`main_amp.py:157-161`)
    overrides = {}
    if args.keep_batchnorm_fp32 is not None:
        overrides["keep_batchnorm_fp32"] = \
            args.keep_batchnorm_fp32.lower() == "true"
    if args.loss_scale is not None:
        overrides["loss_scale"] = (
            "dynamic" if args.loss_scale == "dynamic"
            else float(args.loss_scale))
    policy = amp.Policy.from_opt_level(args.opt_level, **overrides)

    model = ARCHS[args.arch](
        num_classes=1000, dtype=policy.compute_dtype,
        bn_axis_name=parallel.DATA_AXIS if args.sync_bn else None)

    ddp = parallel.DistributedDataParallel(mesh)
    tx = FusedSGD(lr=args.lr, momentum=args.momentum,
                  weight_decay=args.weight_decay)

    x0 = jnp.zeros((2, args.image_size, args.image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    amp_opt = amp.Amp(policy, tx)
    state = amp_opt.init(params)

    def step(state, batch_stats, xb, yb):
        if xb.dtype == jnp.uint8:
            # packed-cache raw mode: normalize on-device (the DALI
            # GPU-side normalize — quarters host->device bytes and
            # keeps the single host core off the float convert)
            xb = xb.astype(policy.compute_dtype or jnp.float32) \
                * (1.0 / 255.0)

        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            acc = jnp.mean((jnp.argmax(logits, -1) == yb).astype(jnp.float32))
            return jax.lax.pmean(loss, ddp.axis_name), (mut["batch_stats"], acc)

        (loss, (new_bs, acc)), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        grads = ddp.sync(grads)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss, jax.lax.pmean(acc, ddp.axis_name)

    spmd_step = jax.jit(
        jax.shard_map(step, mesh=mesh,
                      in_specs=(P(), P(), P(parallel.DATA_AXIS),
                                P(parallel.DATA_AXIS)),
                      out_specs=(P(), P(), P(), P()), check_vma=False),
        donate_argnums=(0, 1))

    batch_sharding = parallel.batch_sharding(mesh)
    folder = None
    if args.data and args.cache:
        from apex_tpu.data import PackedSource, build_cache
        build_cache(args.data, args.cache)
        # raw uint8 out: augmented crops ship as-is and normalize
        # on-device in the step (see the uint8 branch there)
        folder = PackedSource(args.cache, args.batch_size,
                              args.image_size, dtype=np.uint8,
                              workers=args.loader_workers)
    elif args.data:
        folder = ImageFolderSource(
            args.data, args.batch_size, args.image_size,
            workers=args.loader_workers)
    if folder is not None:
        # loader-only throughput probe: input-bound configs announced up
        # front instead of silently capping the training numbers. Runs
        # on its OWN source instance — probing the training source would
        # advance its epoch/shuffle state and make seeded runs
        # non-reproducible (ADVICE r3 item 3).
        if args.cache:
            from apex_tpu.data import PackedSource
            probe_ctx = PackedSource(args.cache, args.batch_size,
                                     args.image_size, dtype=np.uint8,
                                     workers=args.loader_workers)
        else:
            probe_ctx = ImageFolderSource(args.data, args.batch_size,
                                          args.image_size,
                                          workers=args.loader_workers)
        with probe_ctx as probe_src:
            probe = measure_source(
                probe_src.batches(min(6, args.steps_per_epoch) + 1),
                steps=min(5, args.steps_per_epoch))
        print(f"loader: {probe:.0f} img/s with {folder.workers} "
              f"{'cache-read' if args.cache else 'decode'} threads "
              f"(training is input-bound below this rate)")
    for epoch in range(args.epochs):
        src = (folder.batches(args.steps_per_epoch)
               if folder is not None else
               synthetic_batches(args.batch_size, args.image_size,
                                 args.steps_per_epoch, seed=epoch))
        # transfer inputs pre-cast to the compute dtype — the reference
        # prefetcher's side-stream half cast (`main_amp.py:264-317`);
        # halves host->device bytes under O2/O3. Packed-cache batches
        # ship raw uint8 (already the smallest wire format; the step
        # normalizes on-device), so no host cast for THAT source —
        # keyed on the actual source kind, not the flag (synthetic
        # runs that happen to pass --cache still want the half cast).
        uint8_src = folder is not None and args.cache is not None
        cast = (policy.compute_dtype
                if policy.cast_model_type is not None and not uint8_src
                else None)
        pre = Prefetcher(src, sharding=batch_sharding, cast_dtype=cast,
                         depth=args.prefetch)

        t0, seen = time.perf_counter(), 0
        prof_ctx = None
        for i, (xb, yb) in enumerate(pre):
            if i == 0 and 0 < args.prof:
                prof_ctx = jax.profiler.trace("./prof_trace")
                prof_ctx.__enter__()
            state, batch_stats, loss, acc = spmd_step(
                state, batch_stats, xb, yb)
            seen += args.batch_size
            if prof_ctx is not None and i + 1 == args.prof:
                float(loss)
                prof_ctx.__exit__(None, None, None)
                prof_ctx = None
            if (i + 1) % args.print_freq == 0:
                lv = float(loss)          # syncs the pipeline
                dt = time.perf_counter() - t0
                print(f"epoch {epoch} step {i+1}: loss {lv:.4f} "
                      f"acc {float(acc):.3f}  {seen/dt:.1f} img/s "
                      f"({seen/dt/n_dev:.1f}/chip)")
        if prof_ctx is not None:
            prof_ctx.__exit__(None, None, None)
    print("done. amp state_dict:", amp_opt.state_dict(state))


if __name__ == "__main__":
    main()
