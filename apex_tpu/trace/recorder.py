"""Flight recorder: a bounded ring of step records + crash dumps.

A wedged or dead 256-chip job must be diagnosable from artifacts, not
reproduction. The recorder keeps the last N completed steps — span
timings, the :class:`apex_tpu.monitor.Metrics` snapshot (buffered as
device arrays, fetched only at dump time, so recording never syncs),
loss scale, collective bytes, rank/host ids — and writes a JSONL crash
report on any abnormal exit:

- unhandled exception (``sys.excepthook``, chained to the previous hook);
- SIGTERM (the preemption signal on managed clusters; previous handler
  chained);
- ``atexit`` as a safety net, only when an exception/signal was seen but
  no dump was written (a clean exit writes nothing).

The dump is one header line (``kind="crash"``: reason, rank, hostname,
pid, last-completed span, in-flight spans, in-flight collective,
recent guard interventions and straggler blame reports, exception +
traceback) followed by one ``kind="step"`` line per buffered
step — the schema ``scripts/check_metrics_schema.py --kind trace``
validates. On multi-host runs every rank records independently;
:func:`rank_path` (used automatically for directory paths) keeps the
files apart so post-mortem tooling can diff ranks.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import socket
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import jax

from apex_tpu.trace.spans import StepTrace, Tracer

__all__ = ["FlightRecorder", "StepRecord", "rank_path"]


def _rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", "0"))


def _process_count() -> int:
    try:
        return jax.process_count()
    except Exception:
        return int(os.environ.get("WORLD_SIZE", "1"))


def rank_path(path: str, rank: Optional[int] = None) -> str:
    """Per-rank dump path: ``crash.jsonl`` → ``crash.rank0.jsonl``.

    Identity on single-process runs, so local scripts get the filename
    they asked for; ranked on multi-process runs (or when ``rank`` is
    given) so N hosts never clobber one file.
    """
    if rank is None:
        if _process_count() <= 1:
            return path
        rank = _rank()
    root, ext = os.path.splitext(path)
    return f"{root}.rank{rank}{ext or '.jsonl'}"


class StepRecord:
    """One ring-buffer entry. Metrics stay device-side until dump()."""

    __slots__ = ("step", "dur_ms", "spans", "metrics", "extra", "wall_time")

    def __init__(self, step, dur_ms, spans, metrics, extra):
        self.step = step
        self.dur_ms = dur_ms
        self.spans = spans            # [(name, dur_ms)]
        self.metrics = metrics        # monitor.Metrics (device) or None
        self.extra = extra            # host scalars (loss scale override, ...)
        self.wall_time = time.time()

    def to_event(self, rank: int, fetch_metrics: bool = True) -> Dict:
        """``fetch_metrics=False`` skips the device fetch — required on
        the hang path, where a device_get against the wedged runtime
        would block the watchdog thread forever."""
        rec: Dict[str, Any] = {
            "kind": "step", "step": self.step, "rank": rank,
            "dur_ms": self.dur_ms, "wall_time": self.wall_time,
            "spans": [{"name": n, "dur_ms": round(d, 4)}
                      for n, d in self.spans],
        }
        if self.metrics is not None and not fetch_metrics:
            rec["metrics"] = None
            rec["metrics_error"] = "not fetched (hung runtime)"
        elif self.metrics is not None:
            from apex_tpu.monitor.metrics import metrics_to_dict
            try:
                m = metrics_to_dict(jax.device_get(self.metrics))
                # strict-JSON contract: non-finite gauges become null,
                # same as MetricsLogger.flush
                import math
                for k, v in m.items():
                    if isinstance(v, float) and not math.isfinite(v):
                        m[k] = None
                rec["metrics"] = m
                if m.get("loss_scale") is not None:
                    rec["loss_scale"] = m["loss_scale"]
            except Exception as e:           # dead runtime mid-crash
                rec["metrics"] = None
                rec["metrics_error"] = repr(e)[:200]
        if self.extra:
            rec.update(self.extra)
        return rec


class FlightRecorder:
    """Ring buffer of the last ``capacity`` steps + crash-dump handlers.

    ::

        recorder = trace.FlightRecorder("dumps/crash.jsonl", capacity=64)
        recorder.install()                  # excepthook / SIGTERM / atexit
        tracer = trace.Tracer(on_step=recorder.on_step)
        ...
        recorder.record(step=i, metrics=state.metrics)   # or via tracer

    ``collective_bytes``/``extra`` statics attach to every subsequent
    record (e.g. from ``MetricsLogger.attach`` /
    ``ddp.collective_bytes``). Directory paths get :func:`rank_path`
    applied so multi-host runs dump per rank.
    """

    def __init__(self, path: str = "apex_tpu_crash.jsonl", *,
                 capacity: int = 64, tracer: Optional[Tracer] = None,
                 collective_bytes: Optional[int] = None,
                 escalation=None):
        self.path = rank_path(path)
        #: optional :class:`apex_tpu.ckpt.EscalationPolicy`: its
        #: ``on_preempt`` runs FIRST in the SIGTERM handler, so a
        #: managed-cluster preemption commits the last host checkpoint
        #: snapshot durably *before* the crash dump is written — lost
        #: work becomes a resume point (docs/checkpointing.md)
        self.escalation = escalation
        self.capacity = max(int(capacity), 1)
        self._ring: "collections.deque[StepRecord]" = collections.deque(
            maxlen=self.capacity)
        self.tracer = tracer
        if tracer is not None:
            tracer.subscribe(self.on_step)
        self.collective_bytes = collective_bytes
        self.extra_statics: Dict[str, Any] = {}
        # JSON-able digest of the compiled step's HBM footprint
        # (prof.MemoryReport.summary()) — embedded in the crash header
        # so an OOM dump names the biggest buffers instead of just dying
        self.memory_report: Optional[Dict[str, Any]] = None
        # bounded ring of recent guard interventions (note_guard) —
        # embedded in the crash header: a post-mortem must show whether
        # the run was already skipping/rewinding before it died
        self._guard_events: "collections.deque[Dict]" = collections.deque(
            maxlen=16)
        # bounded ring of straggler reports (note_straggler) — a hang
        # or collective timeout is routinely PRECEDED by one rank
        # lagging; the dump must name that rank and its slowest span,
        # not just this rank's heartbeat view
        self._straggler_reports: "collections.deque[Dict]" = \
            collections.deque(maxlen=16)
        self._installed = False
        self._dumped = False
        self._abnormal_seen = False
        self._last_completed_span: Optional[str] = None
        self._prev_excepthook = None
        self._prev_sigterm = None
        self._pending = None          # (metrics, extra) for the open step
        # RLock, not Lock: the SIGTERM handler runs on the main thread
        # and calls dump() -> lock; if the signal lands while record()
        # holds the lock on that same thread, a plain Lock deadlocks the
        # handler forever (and the process then ignores SIGTERM)
        self._lock = threading.RLock()

    # -- recording -----------------------------------------------------------

    def on_step(self, st: StepTrace) -> None:
        """Tracer subscriber: fold a finished StepTrace into the ring."""
        pending, self._pending = self._pending, None
        metrics, extra = pending if pending is not None else (None, {})
        if st.aborted:
            extra = dict(extra, aborted=True)
        self.record(step=st.step, dur_ms=st.dur_ms,
                    spans=[(s.name, s.dur_ms) for s in st.spans],
                    metrics=metrics, **extra)
        completed = [s for s in st.spans if not s.aborted]
        if completed:
            self._last_completed_span = completed[-1].name

    def record(self, *, step: Optional[int] = None,
               dur_ms: Optional[float] = None,
               spans: Optional[List] = None,
               metrics=None, **extra) -> None:
        """Append one step record (never fetches from device)."""
        merged = dict(self.extra_statics)
        if self.collective_bytes is not None:
            merged["collective_bytes"] = self.collective_bytes
        merged.update(extra)
        with self._lock:
            self._ring.append(StepRecord(step, dur_ms, spans or [],
                                         metrics, merged))

    def record_metrics(self, metrics, **extra) -> None:
        """Attach a Metrics snapshot to the current step — call next to
        ``MetricsLogger.record``, inside or right after the
        ``trace.step()`` block; costs a slot write, no sync. Inside an
        open step the snapshot is held pending and folded into that
        step's record when it completes; otherwise it attaches to the
        latest ring entry (or starts one)."""
        if (self.tracer is not None
                and self.tracer._current is not None):
            self._pending = (metrics, dict(extra))
            return
        with self._lock:
            if self._ring and self._ring[-1].metrics is None:
                self._ring[-1].metrics = metrics
                if extra:
                    self._ring[-1].extra.update(extra)
                return
        self.record(metrics=metrics, **extra)

    def attach_memory_report(self, report) -> "FlightRecorder":
        """Attach the compiled step's :class:`apex_tpu.prof.MemoryReport`
        (or an already-digested ``summary()`` dict). Stored as a plain
        JSON-able dict — no live references, so dumping never touches
        the (possibly wedged) runtime."""
        if report is None:
            self.memory_report = None
        elif isinstance(report, dict):
            self.memory_report = dict(report)
        else:
            self.memory_report = report.summary()
        return self

    def note_guard(self, event: Dict) -> None:
        """Record one :mod:`apex_tpu.guard` event (anomaly / action /
        rewind) for crash forensics — wire ``GuardPolicy(recorder=...)``.
        Plain-dict copy into a bounded ring; the newest 16 land in the
        crash header as ``guard_events``. No device access, never
        raises."""
        try:
            self._guard_events.append(dict(event))
        except Exception:
            pass

    def note_straggler(self, event: Dict) -> None:
        """Record one ``kind="straggler"`` event (the detector's
        span-level blame: lagging rank, z, slowest span + its goodput
        class) for crash forensics — wire
        ``StragglerWatch(recorder=...)``. The newest 16 land in the
        crash header as ``straggler_reports``. No device access,
        never raises."""
        try:
            self._straggler_reports.append(dict(event))
        except Exception:
            pass

    @property
    def last_completed_span(self) -> Optional[str]:
        if self.tracer is not None and self.tracer.last_completed_span:
            return self.tracer.last_completed_span
        return self._last_completed_span

    # -- crash handlers ------------------------------------------------------

    def install(self) -> "FlightRecorder":
        """Install excepthook/SIGTERM/atexit handlers (all chained)."""
        if self._installed:
            return self
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._sigterm)
        except ValueError:        # not the main thread
            self._prev_sigterm = None
        atexit.register(self._atexit)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
        atexit.unregister(self._atexit)
        self._installed = False

    def _excepthook(self, etype, value, tb) -> None:
        self._abnormal_seen = True
        try:
            self.dump(reason="exception", exc=(etype, value, tb))
        finally:
            (self._prev_excepthook or sys.__excepthook__)(etype, value, tb)

    def _sigterm(self, signum, frame) -> None:
        self._abnormal_seen = True
        if self.escalation is not None:
            try:
                self.escalation.on_preempt()
            except Exception:
                pass          # the dump below must still land
        self.dump(reason="signal:SIGTERM")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _atexit(self) -> None:
        # safety net only: an abnormal path was seen but no dump landed
        # (e.g. the excepthook itself died). Clean exits write nothing.
        if self._abnormal_seen and not self._dumped:
            self.dump(reason="atexit-after-abnormal")

    # -- the dump ------------------------------------------------------------

    def header(self, reason: str, exc=None) -> Dict:
        hdr: Dict[str, Any] = {
            "kind": "crash", "reason": reason,
            "rank": _rank(), "process_count": _process_count(),
            "hostname": socket.gethostname(), "pid": os.getpid(),
            "wall_time": time.time(),
            "last_completed_span": self.last_completed_span,
            "in_flight_spans": (self.tracer.open_spans
                                if self.tracer is not None else []),
            "in_flight_collective": (self.tracer.in_flight_collective
                                     if self.tracer is not None else None),
            "n_steps_recorded": len(self._ring),
        }
        if self.memory_report is not None:
            hdr["memory_report"] = self.memory_report
        if self._guard_events:
            hdr["guard_events"] = list(self._guard_events)
        if self._straggler_reports:
            hdr["straggler_reports"] = list(self._straggler_reports)
        from apex_tpu.trace.debug_nans import first_nan
        hit = first_nan()
        if hit is not None:
            hdr["first_nan_span"] = hit["span"]
        if exc is not None:
            etype, value, tb = exc
            hdr["exception"] = "".join(
                traceback.format_exception_only(etype, value))[:2000].strip()
            hdr["traceback"] = [l.rstrip() for l in
                                traceback.format_tb(tb, limit=40)]
        return hdr

    def _fetch_metrics_bounded(self, records: List[StepRecord],
                               timeout_s: float = 5.0) -> bool:
        """device_get every buffered Metrics snapshot with a bounded
        wait, replacing them in-place with host values. Returns False on
        timeout/error — a crash can leave the runtime wedged on a dead
        collective, and an unbounded device_get there would hang the
        crash handler and lose the whole dump (the very artifact this
        class exists to produce)."""
        idx = [i for i, r in enumerate(records) if r.metrics is not None]
        if not idx:
            return True
        box: Dict[str, Any] = {}
        done = threading.Event()

        def work():
            try:
                box["host"] = jax.device_get(
                    [records[i].metrics for i in idx])
            except Exception as e:
                box["err"] = e
            done.set()

        threading.Thread(target=work, daemon=True,
                         name="apex_tpu.trace.dump-fetch").start()
        if not done.wait(timeout_s) or "host" not in box:
            return False
        for i, host in zip(idx, box["host"]):
            records[i].metrics = host
        return True

    def dump_records(self, f, rank: int, fetch_metrics: bool = True,
                     records: Optional[List[StepRecord]] = None) -> None:
        """Serialize the ring (one ``kind="step"`` line each) to an open
        file — the one implementation behind both the crash dump and the
        watchdog's hang dump."""
        if records is None:
            with self._lock:
                records = list(self._ring)
        for rec in records:
            f.write(json.dumps(rec.to_event(
                rank, fetch_metrics=fetch_metrics)) + "\n")

    def dump(self, reason: str = "manual", exc=None,
             path: Optional[str] = None) -> str:
        """Write the crash report; returns the path written."""
        out = path or self.path
        d = os.path.dirname(out)
        if d:
            os.makedirs(d, exist_ok=True)
        rank = _rank()
        with self._lock:
            records = list(self._ring)
        fetched = self._fetch_metrics_bounded(records)
        with open(out, "w") as f:
            f.write(json.dumps(self.header(reason, exc)) + "\n")
            self.dump_records(f, rank, fetch_metrics=fetched,
                              records=records)
        self._dumped = True
        return out
