"""Legacy multi-loss optimizer wrapper — ``apex.amp.opt.OptimWrapper``.

The reference's old-API wrapper (`apex/amp/opt.py:9-103`) gives one
optimizer N independent dynamic loss scalers, a ``scale_loss`` context
per loss, and skip bookkeeping; grads for earlier losses are stashed so
each loss unscales at its own scale (`opt.py:25-52`). The *scaling*
semantics are exactly :class:`apex_tpu.amp.Amp` with ``num_losses=N``;
precision casting is applied only when a ``policy`` is passed (see
``__init__``) — this shim keeps the legacy *shape* of the API for users
porting old scripts: a wrapper object owning per-loss scaler states and
an explicit accumulate/step cycle.

Deprecated in the reference too; prefer ``Amp``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import (
    LossScaleConfig, loss_scale_init, loss_scale_update, scale_loss,
    unscale_grads, unscale_grads_with_stashed,
)
from apex_tpu.utils import tree_select


class OptimWrapper:
    """Per-loss dynamic scalers around one optimizer (legacy API).

    One iteration with two losses::

        wrapper = OptimWrapper(tx, num_loss=2)
        wstate = wrapper.init(params)
        out0, acc, wstate = wrapper.backward(wstate, params, loss0, 0,
                                             None)
        out1, acc, wstate = wrapper.backward(wstate, params, loss1, 1,
                                             acc)
        params, wstate = wrapper.step(wstate, acc, params)

    Each loss unscales at its own (independent, dynamic) scale and
    accumulates into the fp32 stash (`opt.py:25-52`); ``step`` skips the
    update if ANY loss of the round overflowed (`opt.py:58-77`).
    """

    def __init__(self, optimizer, num_loss: int = 1,
                 cfg: LossScaleConfig = None, policy=None):
        """``policy``: an optional :class:`apex_tpu.amp.Policy`. When
        given, each ``backward`` runs ``loss_fn`` under
        ``auto_cast(policy)`` so O1-style casting applies — without it
        this shim handles *scaling only* and casting is the caller's job
        (wrap the forward in ``auto_cast`` yourself, or pass a model
        already cast per O2)."""
        self.tx = optimizer
        self.num_loss = num_loss
        self.cfg = cfg or LossScaleConfig(dynamic=True)
        self.policy = policy

    def init(self, params):
        return {
            "scalers": tuple(loss_scale_init(self.cfg)
                             for _ in range(self.num_loss)),
            "finite": jnp.bool_(True),
            "inner": self.tx.init(params),
        }

    def loss_scale(self, wstate):
        """Current per-loss scales (`opt.py:95-103`)."""
        return [float(s.loss_scale) for s in wstate["scalers"]]

    def backward(self, wstate, params, loss_fn: Callable, loss_idx: int,
                 stashed, *args, **kwargs):
        """Scaled backward for ``loss_idx``: grads of
        ``loss_fn(params, ...)`` unscaled at this loss's scale,
        accumulated onto ``stashed`` fp32 grads (None for the first
        loss of the round). Returns (out, acc_grads, wstate')."""
        sstate = wstate["scalers"][loss_idx]

        def scaled(p):
            if self.policy is not None:
                from apex_tpu.amp import auto_cast
                with auto_cast(self.policy):
                    out = loss_fn(p, *args, **kwargs)
            else:
                out = loss_fn(p, *args, **kwargs)
            loss = out[0] if isinstance(out, tuple) else out
            return scale_loss(loss, sstate), out

        grads, out = jax.grad(scaled, has_aux=True)(params)
        if stashed is None:
            acc, finite = unscale_grads(grads, sstate)
        else:
            acc, finite = unscale_grads_with_stashed(grads, stashed,
                                                     sstate)
        scalers = tuple(
            loss_scale_update(s, finite, self.cfg) if i == loss_idx else s
            for i, s in enumerate(wstate["scalers"]))
        wstate = dict(wstate, scalers=scalers,
                      finite=jnp.logical_and(wstate["finite"], finite))
        return out, acc, wstate

    def step(self, wstate, grads, params):
        """Inner optimizer step, skipped entirely if any loss overflowed
        this round; the skip flag resets for the next round."""
        if hasattr(self.tx, "step") and callable(self.tx.step):
            new_p, inner = self.tx.step(grads, wstate["inner"], params)
        else:
            updates, inner = self.tx.update(grads, wstate["inner"],
                                            params)
            new_p = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates)
        fin = wstate["finite"]
        new_p = tree_select(fin, new_p, params)
        inner = tree_select(fin, inner, wstate["inner"])
        return new_p, dict(wstate, inner=inner, finite=jnp.bool_(True))
