"""apexlint v2 — cross-rank SPMD congruence + topology pass suite.

The per-rule contract ISSUE 8 demands: one seeded violation plus a
negative twin per APX2xx rule, with the deadlock constructed from REAL
compiled programs on the 8-device CPU mesh (two shard_map programs over
differently-factored meshes produce genuinely mismatched replica
groups), the sharding-propagation full-gather from a real
``in_shardings``/``out_shardings`` mismatch, and the APX202/203
wire-byte evidence pinned against ``monitor.wire_report`` (the
acceptance criterion's 5% agreement — both read result shapes off the
same module, so the agreement is exact). Plus: mesh-model units
(specs, coordinates, hop classification, JSON round-trip), the
declarative collective-scope registry, replica-group parsing for both
HLO syntaxes, ``lint_step(mesh_model=)`` integration, and the lint
JSONL schema round-trip for the new axes/ranks/hop finding fields.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu import lint, monitor, parallel
from apex_tpu.lint import mesh_model as mmod
from apex_tpu.lint import spmd_pass as sp


# --- shared builders ---------------------------------------------------------

@pytest.fixture(scope="module")
def mm2x4():
    return lint.parse_mesh_spec("dp2x4")


@pytest.fixture(scope="module")
def mesh2x4(devices):
    return Mesh(np.array(devices).reshape(2, 4),
                ("data_inter", "data_intra"))


def _compile_psum(mesh, axes):
    """Compiled HLO of one psum over ``axes`` of ``mesh`` (in/out
    sharded over all mesh axes)."""
    spec = P(*mesh.axis_names)

    def step(x):
        return jax.lax.psum(x, axes)

    m = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=(spec,),
                              out_specs=spec, check_vma=False))
    return m.lower(jnp.ones((8, 128))).compile().as_text()


def _compile_two_psums(mesh):
    def step(x):
        return jax.lax.psum(jax.lax.psum(x, "data_intra"),
                            ("data_inter", "data_intra"))

    spec = P(*mesh.axis_names)
    m = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=(spec,),
                              out_specs=spec, check_vma=False))
    return m.lower(jnp.ones((8, 128))).compile().as_text()


# --- mesh model --------------------------------------------------------------

class TestMeshModel:
    def test_dp2x4_spec(self, mm2x4):
        assert mm2x4.n_devices == 8
        assert mm2x4.axis_names == ("data_inter", "data_intra")
        assert mm2x4.axis("data_inter").link == "dcn"
        assert mm2x4.axis("data_intra").link == "ici"

    def test_slice_spec_needs_devices(self):
        with pytest.raises(ValueError):
            lint.parse_mesh_spec("2slice")
        mm = lint.parse_mesh_spec("2slice", n_devices=8)
        assert [a.size for a in mm.axes] == [2, 4]
        assert mm.axes[0].link == "dcn"

    def test_ici_spec_and_unknown(self):
        mm = lint.parse_mesh_spec("ici8")
        assert mm.n_devices == 8 and mm.axes[0].link == "ici"
        with pytest.raises(ValueError):
            lint.parse_mesh_spec("pod9000")

    def test_coords_and_slice_id(self, mm2x4):
        # row-major, major-to-minor: device 5 = (inter 1, intra 1)
        assert mm2x4.coords(5) == {"data_inter": 1, "data_intra": 1}
        assert mm2x4.slice_id(3) == (0,)
        assert mm2x4.slice_id(4) == (1,)
        with pytest.raises(ValueError):
            mm2x4.coords(8)

    def test_hop_classification(self, mm2x4):
        assert mm2x4.group_hop((0, 1, 2, 3)) == "ici"
        assert mm2x4.group_hop((0, 4)) == "dcn"
        # flat = crosses DCN AND >1 member inside a slice
        assert mm2x4.is_flat_dcn_group(range(8))
        assert not mm2x4.is_flat_dcn_group((0, 4))       # hierarchical
        assert not mm2x4.is_flat_dcn_group((0, 1, 2, 3))  # intra-slice

    def test_group_axes(self, mm2x4):
        assert mm2x4.group_axes((0, 1)) == ["data_intra"]
        assert mm2x4.group_axes((0, 4)) == ["data_inter"]
        assert mm2x4.group_axes(range(8)) == ["data_inter",
                                              "data_intra"]

    def test_json_round_trip(self, mm2x4, tmp_path):
        data = mm2x4.to_json()
        mm = mmod.MeshModel.from_json(json.dumps(data))
        assert mm.axis_names == mm2x4.axis_names
        assert mm.axis("data_inter").link == "dcn"
        p = tmp_path / "mesh.json"
        p.write_text(json.dumps(data))
        mm = lint.parse_mesh_spec(str(p))
        assert mm.n_devices == 8
        with pytest.raises(ValueError):
            mmod.MeshModel.from_json('{"nope": 1}')

    def test_hop_seconds_budgets(self, mm2x4):
        assert mm2x4.hop_seconds(mm2x4.link_bytes_per_s["dcn"],
                                 "dcn") == pytest.approx(1.0)
        assert (mm2x4.hop_seconds(1 << 20, "ici")
                < mm2x4.hop_seconds(1 << 20, "dcn"))


# --- collective-scope registry -----------------------------------------------

class TestRegistry:
    def test_flat_view_matches_registry(self):
        from apex_tpu.parallel.distributed import KNOWN_COLLECTIVE_SCOPES
        assert KNOWN_COLLECTIVE_SCOPES == parallel.known_patterns()
        assert len(KNOWN_COLLECTIVE_SCOPES) >= 5

    def test_axis_attribution(self):
        assert parallel.scope_axis("ddp/sync_gradients") == \
            parallel.DATA_AXIS
        assert parallel.scope_axis("ring_attention/ring_permute") == \
            parallel.SEQ_AXIS
        assert parallel.scope_axis("somewhere/else") is None

    def test_extra_patterns_match_anonymously(self):
        entry = parallel.scope_entry("my/custom_sync",
                                     extra=(r"custom_sync",))
        assert entry is not None and entry.subsystem == "user"
        assert parallel.scope_entry("my/custom_sync") is None


# --- replica-group / schedule parsing ----------------------------------------

class TestScheduleExtraction:
    def test_parse_explicit_groups(self):
        assert sp.parse_replica_groups("{{0,1},{2,3}}") == \
            ((0, 1), (2, 3))
        assert sp.parse_replica_groups("{}") == ()

    def test_parse_iota_groups(self):
        assert sp.parse_replica_groups("[1,8]<=[8]") == \
            (tuple(range(8)),)
        assert sp.parse_replica_groups("[2,4]<=[8]") == \
            ((0, 1, 2, 3), (4, 5, 6, 7))
        # transposed iota: arange(8).reshape(4,2).T -> rows
        assert sp.parse_replica_groups("[2,4]<=[4,2]T(1,0)") == \
            ((0, 2, 4, 6), (1, 3, 5, 7))
        with pytest.raises(ValueError):
            sp.parse_replica_groups("nonsense")

    def test_schedule_from_compiled_module(self, mesh2x4):
        text = _compile_two_psums(mesh2x4)
        sched = sp.extract_collective_schedule(text)
        assert len(sched) == 2
        first, second = sched
        assert first.opcode == second.opcode == "all-reduce"
        assert first.replica_groups == ((0, 1, 2, 3), (4, 5, 6, 7))
        assert second.replica_groups == (tuple(range(8)),)
        assert first.channel_id != second.channel_id
        # wire bytes: 8x128 f32 sharded (2,4) -> 4x32 per shard
        assert first.bytes == 4 * 32 * 4
        assert "psum" in first.scope

    def test_wire_bytes_match_monitor_accounting(self, mesh2x4):
        """The acceptance criterion's 5% agreement claim — schedule
        bytes and ``monitor.wire_report`` read the same result shapes,
        so the totals agree exactly."""
        text = _compile_two_psums(mesh2x4)
        sched = sp.extract_collective_schedule(text)
        wire = monitor.wire_report(hlo_text=text)["wire_bytes"]
        assert wire > 0
        total = sum(i.bytes for i in sched)
        assert abs(total - wire) <= 0.05 * wire
        assert total == wire


# --- APX201: congruence / deadlock -------------------------------------------

class TestSpmdDivergence:
    def test_single_spmd_module_is_congruent(self, mesh2x4, mm2x4):
        text = _compile_two_psums(mesh2x4)
        assert sp.congruence_findings(text, mesh_model=mm2x4) == []
        # a pre-extracted schedule is accepted directly (the bench.py
        # path — no second HLO parse)
        sched = sp.extract_collective_schedule(text)
        assert sp.congruence_findings(sched, mesh_model=mm2x4) == []

    def test_identical_per_rank_modules_are_congruent(self, mesh2x4,
                                                      mm2x4):
        text = _compile_psum(mesh2x4, "data_intra")
        mods = {r: text for r in range(8)}
        assert sp.congruence_findings(mods, mesh_model=mm2x4) == []

    def test_mismatched_replica_groups_deadlock(self, mesh2x4, mm2x4):
        """The seeded APX201: rank 1 compiled its psum over the OTHER
        mesh axis — its replica groups ({{0,4},...}) disagree with
        everyone else's ({{0,1,2,3},...}) at the first collective."""
        t_intra = _compile_psum(mesh2x4, "data_intra")
        t_inter = _compile_psum(mesh2x4, "data_inter")
        mods = {r: (t_inter if r == 1 else t_intra) for r in range(8)}
        fs = sp.congruence_findings(mods, mesh_model=mm2x4)
        assert len(fs) == 1
        f = fs[0]
        assert f.rule == "spmd-divergence" and f.severity == "error"
        assert f.id == "APX201"
        assert f.ranks == [0, 1]             # the diverging pair
        assert "first diverging op" in f.message
        assert "replica groups" in f.message
        assert f.op == "all-reduce"

    def test_missing_collective_deadlocks(self, mesh2x4, mm2x4):
        """Rank 2's program issues ONE collective where everyone else
        issues two — the walk names the rank whose schedule runs dry
        while its peers wait."""
        t_two = _compile_two_psums(mesh2x4)
        t_one = _compile_psum(mesh2x4, "data_intra")
        mods = {r: (t_one if r == 2 else t_two) for r in range(8)}
        fs = sp.congruence_findings(mods, mesh_model=mm2x4)
        assert len(fs) == 1
        f = fs[0]
        assert "deadlock" in f.message and "exhausted" in f.message
        assert f.ranks is not None and 2 in f.ranks

    def test_dtype_mismatch_diverges(self, mesh2x4, mm2x4):
        def step32(x):
            return jax.lax.psum(x, "data_intra")

        def step16(x):
            return jax.lax.psum(x.astype(jnp.bfloat16),
                                "data_intra").astype(jnp.float32)

        spec = P(*mesh2x4.axis_names)

        def compile_(f):
            m = jax.jit(jax.shard_map(f, mesh=mesh2x4, in_specs=(spec,),
                                      out_specs=spec, check_vma=False))
            return m.lower(jnp.ones((8, 128))).compile().as_text()

        t32, t16 = compile_(step32), compile_(step16)
        # CPU may normalize bf16 reductions; only assert when the wire
        # dtypes actually differ in the optimized modules
        d32 = sp.extract_collective_schedule(t32)[0].dtypes
        d16 = sp.extract_collective_schedule(t16)[0].dtypes
        if d32 == d16:
            pytest.skip("backend normalized the wire dtype")
        mods = {r: (t16 if r == 3 else t32) for r in range(8)}
        fs = sp.congruence_findings(mods)
        assert fs and fs[0].rule == "spmd-divergence"

    def test_non_covering_groups_flagged(self):
        """Hand-written module whose groups omit ranks 4..7 — they
        execute the op but belong to no group."""
        text = """HloModule m
ENTRY %main {
  %p = f32[8,16]{1,0} parameter(0)
  ROOT %all-reduce.1 = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %p), channel_id=1, replica_groups={{0,1},{2,3}}, use_global_device_ids=true
}
"""
        fs = sp.congruence_findings(text, n_ranks=8)
        assert len(fs) == 1
        assert "no group" in fs[0].message
        assert fs[0].ranks == [0, 4]

    def test_overlapping_groups_flagged(self):
        text = """HloModule m
ENTRY %main {
  %p = f32[8,16]{1,0} parameter(0)
  ROOT %all-reduce.1 = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %p), channel_id=1, replica_groups={{0,1},{1,2}}, use_global_device_ids=true
}
"""
        fs = sp.congruence_findings(text, n_ranks=3)
        assert len(fs) == 1 and "more than one replica group" in \
            fs[0].message
        # a single double-listed rank is not a PAIR — the event schema
        # wants ranks as exactly two ids or null
        assert fs[0].ranks is None


# --- APX202: implicit full gather --------------------------------------------

class TestImplicitFullGather:
    def _forced_gather_text(self, mesh8):
        """Sharding propagation inserts the all-gather: data-sharded
        input, replicated output, nothing in the program asks for the
        materialization."""
        f = jax.jit(lambda x: x * 2.0,
                    in_shardings=NamedSharding(mesh8, P("data")),
                    out_shardings=NamedSharding(mesh8, P()))
        return f.lower(jnp.ones((16, 64))).compile().as_text()

    def test_fires_on_propagated_gather(self, mesh8):
        text = self._forced_gather_text(mesh8)
        mm = lint.parse_mesh_spec("ici8")
        fs = sp.full_gather_findings(text, mesh_model=mm)
        assert len(fs) == 1
        f = fs[0]
        assert f.rule == "implicit-full-gather" and f.id == "APX202"
        assert f.severity == "warning"
        assert "whole mesh" in f.message
        assert f.hop == "ici" and f.axes == ["data"]
        # wire-byte evidence = monitor accounting (5% criterion, exact)
        wire = monitor.wire_report(hlo_text=text)["wire_bytes"]
        assert f.bytes == wire > 0

    def test_negative_twin_known_scope(self, mesh8):
        """The SAME gather under the ZeRO param-gather span is planned
        — registered in parallel.registry — and must not fire."""
        from apex_tpu.optim.distributed import _all_gather_shard

        def step(x):
            return _all_gather_shard(x, "data")

        m = jax.jit(jax.shard_map(step, mesh=mesh8, in_specs=(P(),),
                                  out_specs=P(), check_vma=False))
        text = m.lower(jnp.ones((64, 16))).compile().as_text()
        assert sp.extract_collective_schedule(text), \
            "twin compiled no collective"
        assert sp.full_gather_findings(text) == []

    def test_caller_known_scopes_suppress(self, mesh8):
        text = self._forced_gather_text(mesh8)
        assert sp.full_gather_findings(
            text, known_scopes=(r".*",)) == []


# --- APX203: DCN-crossing flat collective ------------------------------------

class TestDcnFlatCollective:
    def test_fires_on_flat_whole_mesh_reduce(self, mesh2x4, mm2x4):
        text = _compile_psum(mesh2x4, ("data_inter", "data_intra"))
        fs = sp.dcn_flat_findings(text, mm2x4)
        assert len(fs) == 1
        f = fs[0]
        assert f.rule == "dcn-flat-collective" and f.id == "APX203"
        assert f.hop == "dcn"
        assert f.axes == ["data_inter", "data_intra"]
        assert "hierarchical" in f.message
        wire = monitor.wire_report(hlo_text=text)["wire_bytes"]
        assert abs(f.bytes - wire) <= 0.05 * wire
        assert f.bytes == wire > 0

    def test_intra_slice_twin_clean(self, mesh2x4, mm2x4):
        # whole-slice groups never leave ICI
        text = _compile_psum(mesh2x4, "data_intra")
        assert sp.dcn_flat_findings(text, mm2x4) == []

    def test_hierarchical_inter_twin_clean(self, mesh2x4, mm2x4):
        # one member per slice: the DCN hop is already minimal
        text = _compile_psum(mesh2x4, "data_inter")
        assert sp.dcn_flat_findings(text, mm2x4) == []

    def test_single_slice_model_never_fires(self, mesh2x4):
        text = _compile_psum(mesh2x4, ("data_inter", "data_intra"))
        mm = lint.parse_mesh_spec("ici8")
        assert sp.dcn_flat_findings(text, mm) == []


# --- APX204: nondeterminism ---------------------------------------------------

class TestNondeterminism:
    def test_fires_on_dropped_rng_state(self):
        def f(x, key):
            _, bits = jax.lax.rng_bit_generator(key, (4,),
                                                dtype=jnp.uint32)
            return x + bits.astype(jnp.float32)

        rep = lint.lint_step(f, jnp.ones(4), jnp.zeros((4,), jnp.uint32),
                             rules=("nondeterminism",))
        fs = rep.by_rule("nondeterminism")
        assert len(fs) == 1 and fs[0].severity == "error"
        assert fs[0].id == "APX204"
        assert "dropped output state" in fs[0].message

    def test_threaded_rng_state_clean(self):
        def f(x, key):
            key2, bits = jax.lax.rng_bit_generator(key, (4,),
                                                   dtype=jnp.uint32)
            return x + bits.astype(jnp.float32), key2

        rep = lint.lint_step(f, jnp.ones(4), jnp.zeros((4,), jnp.uint32),
                             rules=("nondeterminism",))
        assert rep.by_rule("nondeterminism") == []

    def test_fires_on_commit_path_callback(self):
        def f(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a) * 2,
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return x + y

        rep = lint.lint_step(f, jnp.ones(4), rules=("nondeterminism",))
        fs = rep.by_rule("nondeterminism")
        assert len(fs) == 1 and "commit" in fs[0].message

    def test_off_path_probe_clean(self):
        # debug prints have no committed outputs (APX004/103 own them)
        def f(x):
            jax.debug.print("v={v}", v=x.sum())
            return x * 2

        rep = lint.lint_step(f, jnp.ones(4), rules=("nondeterminism",))
        assert rep.by_rule("nondeterminism") == []

    def test_scatter_add_nonunique_warns(self):
        def f(x, idx, v):
            return x.at[idx].add(v)

        rep = lint.lint_step(f, jnp.zeros(4), jnp.array([0, 1, 0]),
                             jnp.ones(3), rules=("nondeterminism",))
        fs = rep.by_rule("nondeterminism")
        assert len(fs) == 1 and fs[0].severity == "warning"

    def test_scatter_add_unique_clean(self):
        def f(x, idx, v):
            return x.at[idx].add(v, unique_indices=True)

        rep = lint.lint_step(f, jnp.zeros(4), jnp.array([0, 1, 2]),
                             jnp.ones(3), rules=("nondeterminism",))
        assert rep.by_rule("nondeterminism") == []


# --- lint_step integration ----------------------------------------------------

class TestLintStepMeshIntegration:
    def test_mesh_model_activates_spmd_rules(self, mesh2x4, mm2x4):
        spec = P(*mesh2x4.axis_names)

        def step(x):
            return jax.lax.psum(x, ("data_inter", "data_intra"))

        fn = jax.jit(jax.shard_map(step, mesh=mesh2x4,
                                   in_specs=(spec,), out_specs=spec,
                                   check_vma=False))
        rep = lint.lint_step(fn, jnp.ones((8, 128)), mesh_model=mm2x4)
        assert rep.by_rule("dcn-flat-collective")
        # without the model the topology rule stays off
        rep2 = lint.lint_step(fn, jnp.ones((8, 128)))
        assert rep2.by_rule("dcn-flat-collective") == []

    def test_apx202_subsumes_apx102_for_gathers(self, mesh8):
        f = jax.jit(lambda x: x * 2.0,
                    in_shardings=NamedSharding(mesh8, P("data")),
                    out_shardings=NamedSharding(mesh8, P()))
        mm = lint.parse_mesh_spec("ici8")
        rep = lint.lint_step(f, jnp.ones((16, 64)), mesh_model=mm)
        assert rep.by_rule("implicit-full-gather")
        assert not any(f_.rule == "implicit-resharding"
                       and f_.op == "all-gather" for f_ in rep)

    def test_per_rank_hlo_reaches_congruence(self, mesh2x4, mm2x4):
        t_intra = _compile_psum(mesh2x4, "data_intra")
        t_inter = _compile_psum(mesh2x4, "data_inter")
        rep = lint.lint_step(
            None, per_rank_hlo={r: (t_inter if r == 5 else t_intra)
                                for r in range(8)},
            mesh_model=mm2x4, fn_name="mpmd")
        fs = rep.by_rule("spmd-divergence")
        assert fs and fs[0].ranks is not None and 5 in fs[0].ranks

    def test_per_rank_topology_rules_cover_every_module(self, mesh8,
                                                        mesh2x4, mm2x4):
        """An unplanned gather living only in one MPMD peer's program
        must still surface (APX202/203 audit every distinct module,
        not just the lowest rank's)."""
        clean = _compile_psum(mesh2x4, "data_intra")
        f = jax.jit(lambda x: x * 2.0,
                    in_shardings=NamedSharding(mesh8, P("data")),
                    out_shardings=NamedSharding(mesh8, P()))
        gather = f.lower(jnp.ones((16, 64))).compile().as_text()
        fs = sp.lint_spmd_text({0: clean, 1: gather},
                               rules=("implicit-full-gather",))
        assert [f_.rule for f_ in fs] == ["implicit-full-gather"]


# --- schema / event plumbing --------------------------------------------------

class TestSpmdEventSchema:
    def _finding(self):
        return lint.Finding(rule="dcn-flat-collective", message="m",
                            op="all-reduce", scope="ddp/sync_gradients",
                            bytes=1024, axes=["data_inter"],
                            ranks=[0, 4], hop="dcn")

    def test_event_carries_topology_evidence(self):
        ev = self._finding().to_event(fn="step")
        assert ev["axes"] == ["data_inter"]
        assert ev["ranks"] == [0, 4] and ev["hop"] == "dcn"
        assert ev["id"] == "APX203"

    def test_invalid_hop_rejected(self):
        with pytest.raises(ValueError):
            lint.Finding(rule="dcn-flat-collective", message="m",
                         hop="carrier-pigeon")

    def test_jsonl_round_trip_validates(self, tmp_path):
        import os
        import sys
        _repo = os.path.abspath(os.path.join(
            os.path.dirname(__file__), ".."))
        sys.path.insert(0, os.path.join(_repo, "scripts"))
        try:
            import check_metrics_schema as cms
        finally:
            sys.path.pop(0)
        rep = lint.Report([self._finding()], fn_name="mesh_step")
        path = tmp_path / "lint.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], lint_sink=monitor.JSONLSink(str(path)))
        logger.attach_lint_report(rep)
        logger.close()
        lines = path.read_text().strip().splitlines()
        assert cms.check_lint_lines(lines) == []
        # negative twins: the validator rejects malformed evidence
        bad_hop = dict(json.loads(lines[1]), hop="smoke-signal")
        assert cms.check_lint_lines(
            [lines[0], json.dumps(bad_hop)]) != []
        bad_ranks = dict(json.loads(lines[1]), ranks=[1])
        assert cms.check_lint_lines(
            [lines[0], json.dumps(bad_ranks)]) != []
        bad_axes = dict(json.loads(lines[1]), axes=[3])
        assert cms.check_lint_lines(
            [lines[0], json.dumps(bad_axes)]) != []

    def test_fingerprint_excludes_topology_evidence(self):
        a = self._finding()
        b = lint.Finding(rule="dcn-flat-collective", message="m",
                         op="all-reduce", scope="ddp/sync_gradients",
                         bytes=999, axes=["x"], ranks=[3, 7], hop="ici")
        assert a.fingerprint() == b.fingerprint()


# --- the self-audit guard: instrumented programs stay clean -------------------

class TestSelfAuditClean:
    def test_ckpt_copy_program_lints_clean(self):
        """The snapshot copy program (ckpt landed after the linter):
        no donation findings (fresh buffers ARE its donation safety),
        no host traffic, no nondeterminism."""
        from apex_tpu.ckpt.snapshot import _copy_leaves
        leaves = [jnp.zeros((64, 64)), jnp.zeros((64,), jnp.bfloat16)]
        rep = lint.lint_step(_copy_leaves, leaves)
        assert rep.errors == [], rep.table()

    def test_guarded_toy_step_has_no_new_errors(self):
        """Amp.step(guard=) threading (guard landed after the linter):
        the guard arithmetic adds no host callbacks, no rng hazards,
        no donation regressions over the unguarded twin."""
        from apex_tpu import amp, guard
        from apex_tpu.optim import FusedSGD

        pol = amp.Policy.from_opt_level("O2")
        amp_opt = amp.Amp(pol, FusedSGD(lr=0.1, momentum=0.9))
        params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((64,))}
        state = amp_opt.init(params)
        cfg = guard.GuardConfig()
        gs = guard.guard_init(cfg)
        x = jnp.zeros((8, 64))
        y = jnp.zeros((8, 64))

        def step(state, gs, x, y):
            def loss_fn(mp):
                return jnp.mean((x @ mp["w"] + mp["b"] - y) ** 2)
            state, loss, committed, gs = amp_opt.step(
                state, loss_fn, guard=(gs, cfg))
            return state, gs, loss

        rep = lint.lint_step(jax.jit(step, donate_argnums=(0, 1)),
                             state, gs, x, y, policy=pol)
        assert rep.errors == [], rep.table()
