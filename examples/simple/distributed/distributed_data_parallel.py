"""Minimal DDP + amp pattern — the `examples/simple/distributed` mirror.

Reference: `examples/simple/distributed/distributed_data_parallel.py:1-66`
(a Linear regression trained under amp O1 + apex DDP, launched with
`torch.distributed.launch`). TPU-native, there is no per-rank process
dance: one program shards the batch over a named mesh axis and `psum`s
gradients. Multi-host pods use the same script after
``apex_tpu.parallel.distributed_init()`` (the `multiproc` equivalent).

Run (any host, any chip count — falls back to a virtual CPU mesh):

    python distributed_data_parallel.py [--steps 500]
"""

import argparse

import os
import sys

# allow running from a source checkout without installation
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")))


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, parallel
from apex_tpu.optim import FusedSGD


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", default=500, type=int)
    parser.add_argument("--opt_level", default="O1", type=str)
    args = parser.parse_args()

    # FOR DISTRIBUTED: one mesh over every available device; the same
    # script is SPMD across a pod once distributed_init() has run.
    mesh = parallel.data_parallel_mesh()
    ddp = parallel.DistributedDataParallel(mesh)

    N, D_in, D_out = 64, 1024, 16
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, D_in).astype(np.float32))
    y = jnp.asarray(rng.randn(N, D_out).astype(np.float32))

    w = jnp.asarray(rng.randn(D_in, D_out).astype(np.float32) * 0.01)
    b = jnp.zeros((D_out,), jnp.float32)
    params = {"w": w, "b": b}

    amp_opt, state = amp.initialize(params, FusedSGD(lr=1e-3),
                                    opt_level=args.opt_level)

    def step(state, xb, yb):
        def loss_fn(p):
            pred = xb @ p["w"] + p["b"]
            return jnp.mean(jnp.square(pred - yb))

        loss, grads, state, finite = amp_opt.backward(state, loss_fn)
        grads = ddp.sync(grads)                     # the DDP allreduce
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, jax.lax.pmean(loss, ddp.axis_name)

    spmd_step = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(parallel.DATA_AXIS), P(parallel.DATA_AXIS)),
        out_specs=(P(), P()), check_vma=False))

    for _ in range(args.steps):
        state, loss = spmd_step(state, x, y)
    print("final loss = ", float(loss))


if __name__ == "__main__":
    main()
