"""Bit-pattern reinterpretation helpers — the one dtype-width table the
bit-exact seams share (the integrity fingerprint fold and the repair
broadcast must agree on which leaves are covered bit-exactly, so the
dispatch lives once)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["uint_view_dtype"]


def uint_view_dtype(dtype):
    """The unsigned dtype that reinterprets ``dtype``'s bit pattern
    via ``lax.bitcast_convert_type``: width-matched for 1/2/4-byte
    types; 8-byte types get ``uint32`` — the bitcast then yields a
    trailing pair of uint32 lanes (both halves carry bits; the reverse
    bitcast folds the pair back), still exact."""
    return {1: jnp.uint8, 2: jnp.uint16}.get(
        jnp.dtype(dtype).itemsize, jnp.uint32)
