#!/usr/bin/env python
"""Checkpoint roundtrip audit: save → kill → elastic-restore, asserted.

The asserting sibling of ``memory_budget.py --cpu8`` for the resilience
axis (``run_tier1.sh --smoke`` runs it; exit status is the verdict).
Four claims, each printed and asserted:

(a) **crash-safe commit** — a subprocess SIGKILLed mid-save (at BOTH
    instrumented crash points: data file staged but not renamed, and
    data committed but manifest not) leaves the previous committed
    checkpoint as ``latest()`` and hash-verified loadable;
(b) **elastic resume is bitwise** — a ZeRO (DistributedFusedAdam) run
    trained on the 8-device mesh, checkpointed, and resumed on a
    4-device mesh finishes bitwise-equal (params, masters, moments) to
    an uninterrupted 4-device run — exercised with dyadic-rational
    grads so every collective sum is exact in fp32 and "bitwise" is a
    meaningful oracle, not luck;
(c) **async save stays off the step path** — the capture stall is a
    small fraction of the full synchronous save+write duration (the
    structural claim behind bench.py's ``ckpt_save_stall_ms`` column);
(d) **the event stream validates** — every emitted
    save/restore/escalation event passes
    ``check_metrics_schema.py --kind ckpt``.

Usage: python scripts/ckpt_roundtrip.py --cpu8
       python scripts/ckpt_roundtrip.py          # same audit, local devices
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_PARAM_ROWS = 600          # 720k+ elements → every one of 8 shards real


def _mesh(devs):
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.array(devs), ("data",))


def _opt():
    from apex_tpu.optim import DistributedFusedAdam
    return DistributedFusedAdam(lr=1e-2, weight_decay=0.01)


def _state_specs(opt):
    from jax.sharding import PartitionSpec as P
    from apex_tpu.optim.distributed import ShardedOptState
    return ShardedOptState(
        count=P(), slots={n: {"float32": P("data")}
                          for n in opt.slot_names})


def _make_data(rng, params, n_slices=8):
    """Per-slice dyadic grads: integers/64, so any summation order is
    exact in fp32 and 8-way vs 4-way collectives agree bitwise."""
    import jax.numpy as jnp
    return {k: jnp.asarray(
        rng.randint(-64, 64, (n_slices,) + v.shape).astype("float32")
        / 64.0) for k, v in params.items()}


def _local_means(gstack, world):
    """Combine the 8 global grad slices into ``world`` local means
    (exact: pairwise dyadic sums)."""
    import jax
    per = 8 // world
    return jax.tree_util.tree_map(
        lambda g: g.reshape(world, per, *g.shape[1:]).mean(axis=1),
        gstack)


def _train(mesh, params, gstack, steps, state=None):
    """Run ``steps`` ZeRO Adam steps on ``mesh``; init in-graph when
    ``state`` is None. Returns (params', state')."""
    import jax
    from jax.sharding import PartitionSpec as P

    opt = _opt()
    world = mesh.shape["data"]
    glocal = _local_means(gstack, world)
    sspec = _state_specs(opt)

    if state is None:
        def body(p, g):
            g0 = jax.tree_util.tree_map(lambda x: x[0], g)
            s = opt.init(p)
            for _ in range(steps):
                p, s = opt.step(g0, s, p)
            return p, s
        f = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=(P(), sspec), check_vma=False))
        return f(params, glocal)

    def body(p, g, s):
        g0 = jax.tree_util.tree_map(lambda x: x[0], g)
        for _ in range(steps):
            p, s = opt.step(g0, s, p)
        return p, s
    f = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(), P("data"), sspec),
        out_specs=(P(), sspec), check_vma=False))
    return f(params, glocal, state)


# --- (a) the mid-save kill, both crash points --------------------------------

_CHILD = """
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
from apex_tpu import ckpt
mgr = ckpt.CheckpointManager({root!r})
tree = {{"w": np.arange(1000, dtype=np.float32)}}
mgr.save(9, tree, block=True)     # the crash env kills us mid-write
print("UNREACHABLE past the crash point", file=sys.stderr)
sys.exit(3)
"""


def audit_crash_consistency(root, repo):
    from apex_tpu import ckpt
    before = ckpt.latest_checkpoint(root)
    assert before is not None, "need a committed checkpoint first"
    manifest_before = ckpt.read_manifest(before)
    for point in ("before_data_rename", "before_manifest"):
        env = dict(os.environ, APEX_TPU_CKPT_TEST_CRASH=point,
                   JAX_PLATFORMS="cpu")
        p = subprocess.run(
            [sys.executable, "-c",
             _CHILD.format(repo=repo, root=root)],
            env=env, capture_output=True, text=True, timeout=120)
        assert p.returncode == -signal.SIGKILL, (
            f"child should die by SIGKILL at {point}, got "
            f"{p.returncode}: {p.stderr}")
        after = ckpt.latest_checkpoint(root)
        assert after == before, (
            f"mid-save kill at {point} moved latest: {before} -> "
            f"{after}")
        m = ckpt.read_manifest(after)
        assert m["step"] == manifest_before["step"]
        # the survivor still loads with hashes verified
        from apex_tpu.ckpt import format as _fmt
        arrays = _fmt.assemble_arrays(after, m, verify=True)
        assert arrays, "previous checkpoint unreadable after kill"
        print(f"  (a) kill@{point}: latest unchanged "
              f"(step {m['step']}), hash-verified load ok")


# --- the audit ----------------------------------------------------------------

def main_audit():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import arena, ckpt, monitor

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit("audit needs 8 devices — pass --cpu8 for the "
                         "8-device virtual mesh")
    mesh8, mesh4 = _mesh(devs[:8]), _mesh(devs[:4])

    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    params0 = {
        "w1": jnp.asarray(rng.randn(N_PARAM_ROWS, 1200).astype("float32")),
        "w2": jnp.asarray(rng.randn(257).astype("float32")),
    }
    gstack = _make_data(rng, params0)

    tmp = tempfile.mkdtemp(prefix="apex_ckpt_audit_")
    root = os.path.join(tmp, "ckpts")
    events_path = os.path.join(tmp, "ckpt_events.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], ckpt_sink=monitor.JSONLSink(events_path))
    mgr = ckpt.CheckpointManager(root, keep=3,
                                 event_sink=logger.record_ckpt)

    # train on 8, checkpoint at step 3
    p8, s8 = _train(mesh8, params0, gstack, steps=3)
    stall_ms = mgr.save(3, {"params": p8, "opt": s8}, params=params0,
                        extra={"note": "audit"})
    t_sync0 = time.perf_counter()
    mgr.wait()
    sync_ms = stall_ms + (time.perf_counter() - t_sync0) * 1e3
    print(f"saved step 3 on the 8-mesh: stall {stall_ms:.1f} ms of "
          f"{sync_ms:.1f} ms total (async write off the step path)")

    # (a) crash consistency
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    audit_crash_consistency(root, repo)

    # (b) elastic resume bitwise vs the uninterrupted 4-mesh run at the
    # same program granularity (3-step program + 2-step program — one
    # fused 5-step program rounds FMA contractions differently, a
    # compiler property, not a checkpoint one; docs/checkpointing.md).
    # Two claims compose: 3 ZeRO steps on 8 devices == 3 on 4 devices
    # bitwise (dyadic grads → exact collectives), and restore-from-8
    # + continue == in-memory-4-mesh + continue bitwise.
    like_s4 = _train(mesh4, params0, gstack, steps=0)[1]
    like = {"params": jax.device_put(p8, NamedSharding(mesh4, P())),
            "opt": like_s4}
    restored, manifest = mgr.restore(like)
    assert manifest["step"] == 3
    spec = arena.plan(params0)
    L = spec.partition("float32").buffer_len
    p4, s4 = _train(mesh4, params0, gstack, steps=3)
    for k in params0:
        assert np.array_equal(np.asarray(p8[k]), np.asarray(p4[k])), \
            f"8-mesh vs 4-mesh training diverged at params[{k}]"
    p_el, s_el = _train(mesh4, restored["params"], gstack, steps=2,
                        state=restored["opt"])
    p_un, s_un = _train(mesh4, p4, gstack, steps=2, state=s4)
    for k in params0:
        assert np.array_equal(np.asarray(p_el[k]), np.asarray(p_un[k])), \
            f"elastic params[{k}] != uninterrupted 4-mesh run"
    for slot in ("master", "m", "v"):
        a = np.asarray(s_el.slots[slot]["float32"])[:L]
        b = np.asarray(s_un.slots[slot]["float32"])[:L]
        assert np.array_equal(a, b), f"elastic {slot} != uninterrupted"
    assert int(s_el.count) == int(s_un.count) == 5
    print("  (b) elastic 8→4 resume: params + master/m/v bitwise-equal "
          "to the uninterrupted 4-mesh run (5 steps)")

    # (c) the async capture stall is bounded by (and on any real write,
    # well under) the full synchronous save duration — the measured
    # ratio is bench.py's ckpt_save_stall_ms column; here we assert the
    # accounting (capture ⊆ save) rather than a flaky timing ratio
    assert 0.0 <= stall_ms <= sync_ms + 1e-6, (stall_ms, sync_ms)
    print(f"  (c) capture stall {stall_ms:.1f} ms vs full save "
          f"{sync_ms:.1f} ms (write runs off the step path)")

    # (d) event stream validates (save + restore kinds present)
    logger.close()
    from scripts.check_metrics_schema import check_ckpt_lines
    with open(events_path) as f:
        errors = check_ckpt_lines(f)
    assert not errors, "ckpt event schema violations:\n" + "\n".join(errors)
    with open(events_path) as f:
        kinds = [json.loads(l)["kind"] for l in f if l.strip()]
    assert "ckpt_save" in kinds and "ckpt_restore" in kinds, kinds
    print(f"  (d) {len(kinds)} ckpt events validate (--kind ckpt): "
          f"{sorted(set(kinds))}")
    print("ckpt roundtrip audit ok")


def main():
    if "--cpu8" in sys.argv:
        import jax
        from apex_tpu import _compat
        jax.config.update("jax_platforms", "cpu")
        _compat.request_cpu_devices(8)
    main_audit()


if __name__ == "__main__":
    main()
