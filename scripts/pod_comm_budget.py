"""Pod-scale comm evidence: AOT-compile the flagship O2+DDP step
against a v5e-64 topology and audit its collective structure.

No pod hardware is needed: `jax.experimental.topologies` gives 64
abstract v5e devices and the TPU AOT compiler produces the real
optimized HLO for that topology (VERDICT r4 item 5 — the analogue of
the hierarchy the reference hand-builds,
`apex/contrib/optimizers/distributed_fused_adam.py:250-290`,
`apex/parallel/distributed.py:604-624`).

Prints, per DDP mode (per-tensor, delay_allreduce, bucketed,
bucketed+bf16):
- every collective in the optimized module (op, dtype, bytes,
  replica-group shape),
- the bytes-on-ICI budget: a bidirectional-ring all-reduce moves
  2*(N-1)/N * buffer bytes per chip,
- the weak-scaling prediction against the measured single-chip step,
- for the bucketed modes, the **overlap audit**: the scheduled module's
  ``all-reduce-start``/``-done`` pairs with the count of real compute
  instructions scheduled between them (nonzero gap = the latency-hiding
  scheduler put backward compute behind the collective), plus the
  bytes-per-bucket table.

Usage: python scripts/pod_comm_budget.py [--topology v5e:8x8]
       python scripts/pod_comm_budget.py --cpu8   # 8-device CPU-mesh
           # structural variant (run_tier1.sh --smoke): asserts the
           # per-bucket all-reduce structure + bf16 wire halving without
           # TPU hardware; exit 1 on violation
       python scripts/pod_comm_budget.py --mesh model.json
           # budget against a (measured) MeshModel's link_bytes_per_s
           # instead of the default constant — feed it the calibrated
           # model `scripts/link_probe.py` emits and the weak-scaling
           # milliseconds rest on measurements (combines with --cpu8)
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.lint.mesh_model import DEFAULT_LINK_BYTES_PER_S

# measured round-4/5 single-chip numbers (BENCH_TABLE.md)
RESNET_STEP_MS = 97.9       # b=256 device-time isolated step
#: v5e per-chip ICI bandwidth class (~450GB/s) — the ONE source of
#: truth is the mesh model's default table (a pin test keeps this
#: import from regressing into a re-declared copy); --mesh model.json
#: overrides it with a link_probe-measured value
ICI_BYTES_PER_S = DEFAULT_LINK_BYTES_PER_S["ici"]

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "pred": 1, "f64": 8, "s64": 8, "u64": 8, "s16": 2,
                "u16": 2, "s8": 1, "u8": 1, "f8e4m3fn": 1,
                "f8e5m2": 1, "f8e4m3fnuz": 1, "f8e5m2fnuz": 1}

_COLL_RE = re.compile(
    r"(all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute)(?:-start)?\(")
# fp8 dtypes print as f8e4m3fn[...] — match the full name, not just
# the leading letter+digits
_SHAPE_RE = re.compile(
    r"((?:pred|bf16|f8e[0-9]m[0-9](?:fn|fnuz)?|f16|f32|f64|"
    r"[su](?:8|16|32|64)))\[([0-9,]*)\]")


def collectives(hlo: str):
    """(op, dtype, n_operands, bytes) per collective instruction. A
    combined (variadic) collective has a tuple result shape — every
    element is summed, so a 161-operand fused all-reduce reports its
    full byte count, not its first operand's."""
    out = []
    for line in hlo.splitlines():
        m = _COLL_RE.search(line)
        if not m or "=" not in line:
            continue
        op = m.group(1)
        # result shape(s): everything between '=' and the opcode
        head = line.split(f" {m.group(0)}")[0]
        head = head.split("=", 1)[1] if "=" in head else head
        nbytes, n_ops, dts = 0, 0, set()
        for sm in _SHAPE_RE.finditer(head):
            dt = sm.group(1)
            dims = [int(x) for x in sm.group(2).split(",") if x] or [1]
            nbytes += int(np.prod(dims)) * _DTYPE_BYTES.get(dt, 4)
            n_ops += 1
            dts.add(dt)
        if not n_ops:
            continue
        out.append((op, "+".join(sorted(dts)), n_ops, nbytes))
    return out


# StableHLO (lowered, pre-optimization) collectives: the WIRE dtype as
# authored. Needed because CPU's float-normalization pass promotes bf16
# all-reduces to f32 in the *optimized* module — the compiled text then
# under-reports the compression (TPU keeps bf16 native, so the optimized
# audit is authoritative there).
_STABLE_COLL_RE = re.compile(
    r'"stablehlo\.(all_reduce|reduce_scatter|all_gather|all_to_all|'
    r'collective_permute)".*?->\s*\(?tensor<([^>]*)>', re.S)
_STABLE_DTYPE_BYTES = dict(_DTYPE_BYTES, i8=1, i16=2, i32=4, i64=8, i1=1)


def stablehlo_collectives(text: str):
    """(op, dtype, n_operands, bytes) per collective in a lowered
    StableHLO module — same row shape as :func:`collectives`."""
    out = []
    for op, ty in _STABLE_COLL_RE.findall(text):
        parts = ty.split("x")
        dt = parts[-1]
        elems = int(np.prod([int(p) for p in parts[:-1]] or [1]))
        out.append((op.replace("_", "-"), dt, 1,
                    elems * _STABLE_DTYPE_BYTES.get(dt, 4)))
    return out


def build_step(mesh, delay_allreduce, model=None, *,
               bucket_allreduce=False, message_size=None, compress=None,
               comm_plan=None):
    """The flagship O2+DDP step — ONE definition shared by this
    script's v5e-64 audit and tests/test_pod_hlo.py's CI assertions,
    so what CI pins is exactly what the pod evidence compiled.
    ``bucket_allreduce``/``message_size``/``compress`` select the
    overlapped/compressed sync modes (apex_tpu.parallel.comm);
    ``comm_plan`` (a ``parallel.hierarchy.CommPlan``) the hierarchical
    per-hop schedule over a factored mesh — the loss mean then also
    goes hierarchical (``ddp.pmean``), so no scalar flat reduce crosses
    the DCN boundary either."""
    from apex_tpu import amp, models, ops, parallel
    from apex_tpu.optim import FusedSGD

    ddp = parallel.DistributedDataParallel(
        mesh, delay_allreduce=delay_allreduce,
        bucket_allreduce=bucket_allreduce, message_size=message_size,
        compress=compress, comm_plan=comm_plan)
    if model is None:
        model = models.ResNet(stage_sizes=[3, 4, 6, 3],
                              num_classes=1000, dtype=jnp.bfloat16)
    amp_opt = amp.Amp(amp.Policy.from_opt_level("O2"),
                      FusedSGD(lr=0.1, momentum=0.9))

    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            from apex_tpu.trace.spans import span
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb,
                train=True, mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            # registered scope (parallel.registry "ddp/loss_pmean") —
            # a bare pmean here is an APX102 finding in the --mesh audit
            with span("ddp/loss_pmean", kind="collective"):
                loss = ddp.pmean(loss)
            return loss, mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        grads = ddp.sync(grads)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    return step, model, amp_opt, ddp


def lower_flagship(mesh, n, *, delay_allreduce, per_chip_batch=256,
                   model=None, image_size=224, bucket_allreduce=False,
                   message_size=None, compress=None, comm_plan=None):
    """Lower the full ResNet-50 O2+DDP step over ``mesh`` using only
    avals (no real arrays — works on abstract topology devices). With
    ``comm_plan`` the batch splits over the plan's (inter, intra) axis
    tuple instead of the flat data axis."""
    from jax.sharding import PartitionSpec as P

    step, model, amp_opt, ddp = build_step(
        mesh, delay_allreduce, model=model,
        bucket_allreduce=bucket_allreduce, message_size=message_size,
        compress=compress, comm_plan=comm_plan)

    # shape-only init on the default backend (tiny arrays, real mesh
    # not needed): we just need the state/batch_stats avals
    x1 = jnp.ones((2, image_size, image_size, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x1, train=True))
    params_s, bs_s = variables["params"], variables["batch_stats"]
    state_s = jax.eval_shape(
        lambda: amp_opt.init(jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), params_s)))

    batch = per_chip_batch * n
    x_s = jax.ShapeDtypeStruct((batch, image_size, image_size, 3),
                               jnp.float32)
    y_s = jax.ShapeDtypeStruct((batch,), jnp.int32)

    batch_axes = ddp.axis_name    # flat name, or the plan's axis tuple
    stepped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(batch_axes), P(batch_axes)),
        out_specs=(P(), P(), P()),
        check_vma=False))
    return stepped.lower(state_s, bs_s, x_s, y_s), params_s


def _mesh_override(argv):
    """(ici_bytes_per_s, model|None) from an optional ``--mesh
    model.json`` arg — the link_probe-measured ingestion path."""
    if "--mesh" not in argv:
        return ICI_BYTES_PER_S, None
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    mm = parse_mesh_spec(argv[argv.index("--mesh") + 1])
    src = "measured" if mm.measured else "declared"
    print(f"link budget from {src} mesh model {mm!r}: "
          f"ici {mm.link_bytes_per_s['ici'] / 1e9:.3f} GB/s")
    return mm.link_bytes_per_s["ici"], mm


def report(hlo, params_s, n, ici_bytes_per_s=None):
    if ici_bytes_per_s is None:
        ici_bytes_per_s = ICI_BYTES_PER_S
    colls = collectives(hlo)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params_s))
    grad_bytes = n_params * 4               # fp32 master grads under O2
    print(f"  collectives in optimized HLO ({len(colls)}):")
    total_red = 0
    for op, dt, n_ops, nbytes in colls:
        print(f"    {op:20s} {dt:5s} {n_ops:4d} operands "
              f"{nbytes / 2 ** 20:8.2f} MiB")
        if op in ("all-reduce", "reduce-scatter"):
            total_red += nbytes
    # per-op ring factors: all-reduce moves 2(N-1)/N of the buffer,
    # reduce-scatter and all-gather (N-1)/N each
    ici = 0.0
    for op, dt, n_ops, nbytes in colls:
        if op == "all-reduce":
            ici += 2 * (n - 1) / n * nbytes
        elif op in ("reduce-scatter", "all-gather"):
            ici += (n - 1) / n * nbytes
    t_ms = ici / ici_bytes_per_s * 1e3
    eff = RESNET_STEP_MS / (RESNET_STEP_MS + t_ms)
    print(f"  param bytes (fp32 grads): {grad_bytes / 2 ** 20:.1f} MiB; "
          f"reduced bytes: {total_red / 2 ** 20:.1f} MiB")
    print(f"  ring ICI traffic/chip/step: {ici / 2 ** 20:.1f} MiB "
          f"-> {t_ms:.2f} ms at {ici_bytes_per_s / 1e9:.1f} GB/s")
    print(f"  unoverlapped weak-scaling efficiency vs "
          f"{RESNET_STEP_MS} ms step: {eff * 100:.1f}%")


# --- overlap audit -----------------------------------------------------------

# schedule-level "real compute" — the ops worth hiding a collective
# behind (elementwise glue rides inside fusions anyway)
_COMPUTE_RE = re.compile(
    r"= [^ ]+ (fusion|convolution|dot|custom-call|tpu_custom_call)\(")
_START_RE = re.compile(
    r"%?([\w.\-]+) = [^=]*?((?:all-reduce|reduce-scatter|all-gather|"
    r"all-to-all|collective-permute)-start)\(")
_DONE_RE = re.compile(
    r"= [^=]*?(?:all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute)-done\(\s*%?([\w.\-]+)")


def overlap_audit(hlo: str):
    """Audit async-collective overlap in a *scheduled* optimized module.

    HLO text of a compiled executable lists instructions in schedule
    order, so the distance between an ``all-reduce-start`` and its
    ``-done`` is exactly what the latency-hiding scheduler achieved.
    Returns one dict per start/done pair::

        {"op": ..., "bytes": ..., "compute_between": n,
         "start_line": i, "done_line": j}

    ``compute_between`` counts fusion/convolution/dot/custom-call
    instructions scheduled inside the window — nonzero means real
    (backward) compute runs behind the collective. Backends that do not
    emit async pairs (CPU) return an empty list; the structural bucket
    claims are then asserted on the sync ``all-reduce`` count instead
    (tests/test_pod_hlo.py does both).
    """
    lines = hlo.splitlines()
    starts = {}
    compute = np.zeros(len(lines) + 1, np.int64)
    for i, line in enumerate(lines):
        compute[i + 1] = compute[i] + bool(_COMPUTE_RE.search(line))
        m = _START_RE.search(line)
        if m:
            nbytes = 0
            head = line.split(f" {m.group(2)}(")[0]
            if "=" in head:
                for sm in _SHAPE_RE.finditer(head.split("=", 1)[1]):
                    dims = [int(x) for x in sm.group(2).split(",")
                            if x] or [1]
                    nbytes += int(np.prod(dims)) * _DTYPE_BYTES.get(
                        sm.group(1), 4)
            # the -start tuple carries operand AND result buffers;
            # halve to report the logical payload once
            starts[m.group(1)] = (i, m.group(2), nbytes // 2)
    out = []
    for j, line in enumerate(lines):
        m = _DONE_RE.search(line)
        if not m or m.group(1) not in starts:
            continue
        i, op, nbytes = starts[m.group(1)]
        out.append({"op": op.replace("-start", ""), "bytes": nbytes,
                    "compute_between": int(compute[j] - compute[i + 1]),
                    "start_line": i, "done_line": j})
    return out


def print_overlap(hlo, leaves, message_size):
    from apex_tpu.parallel import comm

    plan = comm.bucket_plan(leaves, message_size)
    print(f"  bucket plan ({len(plan)} buckets, message_size="
          f"{message_size}):")
    print(comm.bucket_table(plan))
    pairs = overlap_audit(hlo)
    if not pairs:
        print("  (no async start/done pairs — backend compiles sync "
              "collectives; bucket structure asserted on all-reduce "
              "count)")
        return
    overlapped = sum(1 for p in pairs if p["compute_between"] > 0)
    print(f"  async collective pairs: {len(pairs)}, with compute "
          f"scheduled inside the window: {overlapped}")
    for p in pairs:
        print(f"    {p['op']:16s} {p['bytes'] / 2 ** 20:8.2f} MiB  "
              f"compute-between={p['compute_between']}")


# --- hierarchical-schedule audit ---------------------------------------------

def _hier_model(override=None):
    """The 2-slice mesh model the hierarchical audit judges against:
    the ``dp2x4`` cpu8 topology, upgraded with a ``--mesh model.json``
    override — a multi-slice override replaces it outright, a
    single-slice measured model (what ``link_probe --cpu8`` emits on a
    flat mesh) contributes its measured budgets/calibration so the
    plan rests on measurements where we have them."""
    from apex_tpu.lint.mesh_model import parse_mesh_spec

    mm = parse_mesh_spec("dp2x4")
    if override is not None:
        if any(a.link == "dcn" for a in override.axes):
            return override
        mm.link_bytes_per_s.update(override.link_bytes_per_s)
        mm.calibration.update(override.calibration)
    return mm


def hierarchical_mesh_for_model(mesh_model, devices):
    """The factored device mesh a multi-slice model describes: devices
    reshaped row-major by the model's OWN axis sizes under its own
    axis names (a ``--mesh`` override with a different factorization
    than dp2x4 gets the mesh it declares, not a hardcoded 2x(n/2)).
    Refuses a device-count mismatch with a clear message instead of a
    reshape traceback."""
    from jax.sharding import Mesh

    devices = np.asarray(devices).reshape(-1)
    if devices.size != mesh_model.n_devices:
        raise SystemExit(
            f"mesh model {mesh_model!r} wants {mesh_model.n_devices} "
            f"devices, have {devices.size} — pass a model matching "
            "the audit mesh")
    sizes = [a.size for a in mesh_model.axes]
    return Mesh(devices.reshape(sizes), mesh_model.axis_names)


def hierarchical_structure_audit(hlo: str, mesh_model):
    """Assert the hierarchical collective structure of a compiled
    module against a mesh model — the standing APX203 gate:

    - every collective scoped under a ``dcn`` hop sub-span has replica
      groups with EXACTLY one member per slice (the hierarchical
      shape), and both hop classes are present;
    - every ``ici``-hop collective stays inside one slice;
    - ``dcn_flat_findings`` (apexlint APX203) is EMPTY over the whole
      module — a regression to a flat DCN-crossing reduce fails here
      before it costs a pod.

    Returns ``(dcn_instrs, ici_instrs)`` for reporting. Raises
    AssertionError on violation (``--cpu8`` exit status carries it)."""
    from apex_tpu.lint.spmd_pass import (dcn_flat_findings,
                                         extract_collective_schedule)
    from apex_tpu.monitor.collectives import scope_hop

    sched = extract_collective_schedule(hlo)
    hops = {"dcn": [], "ici": []}
    for i in sched:
        hops.setdefault(scope_hop(i.scope), []).append(i)
    assert hops["dcn"], ("no DCN-hop collectives in the module — the "
                         "hierarchical schedule did not compile")
    assert hops["ici"], "no ICI-hop collectives in the module"
    n_slices = 1
    for a in mesh_model.axes:
        if a.link == "dcn":
            n_slices *= a.size
    # empty replica_groups means ONE implicit whole-mesh group for
    # either hop class — a slice-crossing shape the ICI assertion must
    # see, not skip
    def _groups(instr):
        return instr.replica_groups or (
            tuple(range(mesh_model.n_devices)),)

    for instr in hops["dcn"]:
        for g in _groups(instr):
            slices = [mesh_model.slice_id(m) for m in g]
            assert len(g) == n_slices and len(set(slices)) == len(g), (
                f"DCN-hop {instr.describe()} group {g} is not "
                f"one-member-per-slice over {n_slices} slices")
    for instr in hops["ici"]:
        for g in _groups(instr):
            slices = {mesh_model.slice_id(m) for m in g}
            assert len(slices) == 1, (
                f"ICI-hop {instr.describe()} group {g} crosses slices "
                f"{sorted(slices)}")
    findings = dcn_flat_findings(sched, mesh_model)
    assert not findings, (
        "APX203 reappeared on the hierarchical path:\n"
        + "\n".join(f.message for f in findings))
    return hops["dcn"], hops["ici"]


def _flagship_modes():
    """(label, lower_flagship kwargs) per audited DDP mode."""
    return [
        ("delay_allreduce (one flat fused reduce per dtype)",
         dict(delay_allreduce=True)),
        ("per-tensor psum + XLA combiner",
         dict(delay_allreduce=False)),
        ("bucketed backward-ordered (message_size=1e7)",
         dict(delay_allreduce=False, bucket_allreduce=True,
              message_size=10_000_000)),
        ("bucketed + compress=bf16",
         dict(delay_allreduce=False, bucket_allreduce=True,
              message_size=10_000_000, compress="bf16")),
    ]


def main():
    topology = "v5e:8x8"
    if "--topology" in sys.argv:
        topology = sys.argv[sys.argv.index("--topology") + 1]
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from apex_tpu import parallel

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology)
    n = len(topo.devices)
    mesh = Mesh(np.array(topo.devices), (parallel.DATA_AXIS,))
    print(f"AOT target: {topology} ({n} chips)")
    ici_bps, override = _mesh_override(sys.argv)

    params_s = None
    for label, kw in _flagship_modes():
        print(f"\nDDP {label}:")
        lowered, params_s = lower_flagship(mesh, n, **kw)
        hlo = lowered.compile().as_text()
        report(hlo, params_s, n, ici_bytes_per_s=ici_bps)
        if kw.get("bucket_allreduce"):
            leaves = jax.tree_util.tree_leaves(params_s)
            print_overlap(hlo, leaves, kw["message_size"])

    # hierarchical: factor the pod as 2 slices over DCN and audit the
    # per-hop schedule (ICI reduce-scatter, one-member-per-slice DCN
    # reduce, ICI all-gather) from the scheduled HLO
    if n >= 4 and n % 2 == 0:
        from apex_tpu.lint.mesh_model import MeshAxis, MeshModel
        from apex_tpu.parallel import hierarchy

        mm = _hier_model(override)
        if mm.n_devices != n:
            mm = MeshModel((MeshAxis("data_inter", 2, "dcn"),
                            MeshAxis("data_intra", n // 2, "ici")),
                           link_bytes_per_s=mm.link_bytes_per_s,
                           calibration=mm.calibration,
                           name=f"dp2x{n // 2}")
        mesh_h = hierarchical_mesh_for_model(mm, topo.devices)
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(params_s))
        plan = hierarchy.plan_comm(mm, grad_bytes=4 * n_params)
        print(f"\nDDP hierarchical ({plan.describe()}):")
        pred = plan.predicted_seconds()
        print("  predicted hop ms: "
              + ", ".join(f"{k} {v * 1e3:.2f}" for k, v in pred.items()))
        lowered, params_s = lower_flagship(
            mesh_h, n, delay_allreduce=False,
            message_size=10_000_000, comm_plan=plan)
        hlo = lowered.compile().as_text()
        report(hlo, params_s, n, ici_bytes_per_s=ici_bps)
        dcn_i, ici_i = hierarchical_structure_audit(hlo, mm)
        print(f"  hierarchical structure ok: {len(ici_i)} ICI-hop + "
              f"{len(dcn_i)} one-per-slice DCN-hop collectives, "
              f"APX203 absent")


def main_cpu8():
    """8-device CPU-mesh structural variant of the audit
    (``run_tier1.sh --smoke``): no TPU needed. Compiles the small-model
    flagship step in bucketed and bucketed+bf16 modes and ASSERTS
    the per-bucket all-reduce structure and the bf16 wire halving —
    exit status is the audit verdict."""
    import jax as _jax
    _jax.config.update("jax_platforms", "cpu")
    from apex_tpu import _compat
    _compat.request_cpu_devices(8)
    from jax.sharding import Mesh

    from apex_tpu import models, parallel
    from apex_tpu.parallel import comm

    mesh = Mesh(np.array(jax.devices()), (parallel.DATA_AXIS,))
    model = models.ResNet(stage_sizes=[1, 1], num_classes=10, width=16,
                          dtype=jnp.bfloat16)
    message_size = 30_000
    _, override = _mesh_override(sys.argv)  # measured budget if given

    flat_hlo = None
    print("overlap audit, 8-device CPU mesh (structural variant)")
    for label, kw in (
            ("bucketed", dict(bucket_allreduce=True,
                              message_size=message_size)),
            ("bucketed+bf16", dict(bucket_allreduce=True,
                                   message_size=message_size,
                                   compress="bf16"))):
        lowered, params_s = lower_flagship(
            mesh, 8, delay_allreduce=False, model=model, image_size=32,
            per_chip_batch=4, **kw)
        hlo = lowered.compile().as_text()
        if kw.get("compress") is None:
            flat_hlo = hlo     # the APX203 negative twin's subject
        leaves = jax.tree_util.tree_leaves(params_s)
        plan = comm.bucket_plan(leaves, message_size)
        colls = collectives(hlo)
        ars = [c for c in colls if c[0] == "all-reduce" and c[3] > 128]
        print(f"\nmode {label}: {len(plan)} buckets -> "
              f"{len(ars)} grad all-reduces")
        print(comm.bucket_table(plan))
        assert len(plan) >= 2, "model too small to exercise bucketing"
        assert len(ars) >= len(plan), (
            f"buckets merged: {len(ars)} all-reduces < {len(plan)} "
            f"buckets\n" + "\n".join(map(str, ars)))
        if kw.get("compress") == "bf16":
            # wire dtype from the LOWERED module (CPU promotes bf16
            # all-reduces to f32 during optimization; TPU doesn't)
            n_params = sum(int(np.prod(l.shape)) for l in leaves)
            logical = n_params * 4
            wire = sum(c[3] for c in stablehlo_collectives(
                lowered.as_text())
                if c[0] == "all-reduce" and c[3] > 128)
            print(f"  wire {wire} B vs logical {logical} B "
                  f"(ratio {wire / logical:.3f})")
            assert wire <= logical * 0.505, (
                f"bf16 mode did not halve wire bytes: {wire} vs "
                f"{logical}")
        print_overlap(hlo, leaves, message_size)

    # --- hierarchical schedule: the standing APX203 gate -----------------
    # The factored (2-slice x 4-chip) mesh judged against the dp2x4
    # model (measured link budgets folded in when --mesh gives them):
    # one-member-per-slice DCN groups, within-slice ICI hops, APX203
    # ABSENT — plus the committed negative twin: the flat module above
    # must still FIRE APX203 against the same model, or the gate rotted.
    from apex_tpu import monitor
    from apex_tpu.lint.spmd_pass import dcn_flat_findings
    from apex_tpu.parallel import hierarchy

    mm = _hier_model(override)
    mesh_h = hierarchical_mesh_for_model(mm, jax.devices())
    n_params = sum(int(np.prod(l.shape)) for l in leaves)
    plan = hierarchy.plan_comm(mm, grad_bytes=4 * n_params)
    print(f"\nmode hierarchical: {plan.describe()}")
    lowered, params_s = lower_flagship(
        mesh_h, 8, delay_allreduce=False, model=model, image_size=32,
        per_chip_batch=4, message_size=message_size, comm_plan=plan)
    hlo = lowered.compile().as_text()
    leaves = jax.tree_util.tree_leaves(params_s)
    bplan = comm.bucket_plan(leaves, message_size)
    print(comm.bucket_table(bplan, plan))
    wire = comm.wire_bytes(bplan, plan)
    logical = comm.wire_bytes(bplan, None)
    print(f"  wire {wire} B vs logical {logical} B (all-reduce-equiv "
          f"ratio {wire / logical:.3f})")
    assert wire <= logical * 0.45, (
        f"hierarchical plan did not compress: {wire} vs {logical}")

    dcn_i, ici_i = hierarchical_structure_audit(hlo, mm)
    print(f"  structure ok: {len(ici_i)} ICI-hop + {len(dcn_i)} "
          f"one-per-slice DCN-hop collectives, APX203 absent")

    # per-hop per-dtype wire split (monitor.wire_report) — int8 payload
    # survives CPU optimization (no float-normalization on s8), so the
    # split is assertable wherever the plan put int8 on a hop
    by_hop = monitor.wire_report(hlo_text=hlo)["by_hop"]
    print("  per-hop wire split: "
          + "; ".join(f"{h} {{" + ", ".join(
              f"{dt}: {b}" for dt, b in sorted(per.items())) + "}"
              for h, per in sorted(by_hop.items())))
    assert "ici" in by_hop and "dcn" in by_hop, by_hop
    expect = {None: "f32", "bf16": "bf16", "int8": "s8"}
    for hop_name, hop in (("ici", plan.intra), ("dcn", plan.inter)):
        if hop.dtype == "bf16":
            continue     # CPU float-normalization promotes bf16 wires
        assert expect[hop.dtype] in by_hop[hop_name], (
            hop_name, hop.dtype, by_hop)

    assert flat_hlo is not None
    neg = dcn_flat_findings(flat_hlo, mm)
    assert neg, ("negative twin broken: the flat bucketed sync no "
                 "longer trips APX203 against the 2-slice model — the "
                 "hierarchical gate would pass vacuously")
    print(f"  negative twin ok: flat path still fires APX203 "
          f"({len(neg)} finding(s))")
    print("\ncpu8 overlap audit ok")


if __name__ == "__main__":
    if "--cpu8" in sys.argv:
        main_cpu8()
    else:
        main()
