"""The on-device validation harness itself must not rot: run a fast
subset of its cases in interpret mode on the CI mesh, and check the
driver/JSON plumbing."""

import json

from apex_tpu.ops import compile_check as cc


def test_case_registry_nonempty_and_named():
    names = [n for n, _ in cc.CASES]
    assert len(names) >= 20
    assert len(set(names)) == len(names)
    for family in ("attention", "layer_norm", "mlp", "xentropy",
                   "multi_tensor", "optim", "bn_act", "ckpt"):
        assert any(n.startswith(family + "/") for n in names), family


def test_ckpt_case_runs_green():
    """The ISSUE-6 acceptance case: a step with checkpointing attached
    compiles bit-identical HLO, donated and undonated."""
    assert cc.run(pattern="ckpt")


def test_fast_subset_runs_green(tmp_path):
    out = tmp_path / "cc.json"
    ok = cc.run(pattern="layer_norm", json_path=str(out))
    assert ok
    data = json.loads(out.read_text())
    assert data["ok"] and data["n_failed"] == 0
    assert data["backend"] == "cpu" and data["compiled"] is False
    assert all(r["ok"] for r in data["results"])


def test_multi_tensor_case_runs_green():
    ok = cc.run(pattern="multi_tensor")
    assert ok


def test_failure_is_reported(tmp_path, monkeypatch):
    def boom():
        raise AssertionError("intentional")

    monkeypatch.setattr(cc, "CASES", [("fake/boom", boom)])
    out = tmp_path / "cc.json"
    ok = cc.run(json_path=str(out))
    assert not ok
    data = json.loads(out.read_text())
    assert data["n_failed"] == 1
    assert "intentional" in data["results"][0]["error"]
