"""Legacy contrib optimizer surface — externally-scaled gradients.

The reference's deprecated ``apex.contrib.optimizers`` classes
(`fused_adam.py:64-206`, `fused_sgd.py`, `fused_lamb.py`) take
still-scaled gradients directly in ``step(grads=..., scale=...,
output_params=...)`` and unscale INSIDE the kernel, optionally writing a
reduced-precision copy of the updated params in the same pass — the API
their ``FP16_Optimizer`` (`fp16_optimizer.py:4-243`) drives with
flattened grads.

Here the same capability rides the modern arena kernels, which already
fuse ``grad_scale`` (the 1/scale) and ``param_copy_dtype`` (the
``output_params`` copy-out): these classes only adapt the legacy call
shape. Deprecated; prefer ``apex_tpu.optim.Fused*`` + ``amp.Amp``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu import arena
from apex_tpu.ops import multi_tensor as MT
from apex_tpu.ops import optim_kernels as K
from apex_tpu.optim import fused
from apex_tpu.optim.fused import FusedOptState, Scalar


class _LegacyFused:
    """Shared shape of the deprecated surface: ``step(grads, state,
    params, scale=..., output_dtype=...)`` with in-kernel unscale."""

    def init(self, params) -> FusedOptState:
        spec = arena.plan(params)
        slots = {name: arena.zeros(spec, dtype=jnp.float32)
                 for name in self.slot_names}
        return FusedOptState(count=jnp.int32(0), slots=slots)

    def _step_context(self, g_bufs, inv):
        """Per-step value computed once over ALL partitions before the
        per-partition kernels (LAMB's global grad-norm clip)."""
        return None

    def step(self, grads, state: FusedOptState, params, *,
             scale: float = 1.0, output_dtype=None):
        """One update from externally-scaled grads.

        ``scale`` divides the gradients inside the kernel
        (`fused_adam.py:76-78`: "factor to divide gradient tensor values
        by before applying to weights"). With ``output_dtype`` set, a
        reduced-precision copy of the new params is produced in the same
        pass (``output_params``) and returned as a third element.
        """
        spec = arena.plan(params)
        p_bufs = arena.flatten(params, spec)
        g_bufs = arena.flatten(grads, spec, cast=jnp.float32)
        count = state.count + 1
        lr = self.lr(count) if callable(self.lr) else self.lr
        inv = 1.0 / scale
        ctx = self._step_context(g_bufs, inv)

        new_p, new_slots = {}, {n: {} for n in self.slot_names}
        copies = {}
        for part in spec.partitions:
            dt = part.dtype
            slots = {n: state.slots[n][dt] for n in self.slot_names}
            out = self._kernel(part, p_bufs[dt], g_bufs[dt], slots, count,
                               lr, inv, output_dtype, ctx)
            new_p[dt] = out[0]
            for n, v in zip(self.slot_names, out[1:1 + len(
                    self.slot_names)]):
                new_slots[n][dt] = v
            if output_dtype is not None:
                copies[dt] = out[-1]
        params_out = arena.unflatten(new_p, spec)
        st = FusedOptState(count=count, slots=new_slots)
        if output_dtype is None:
            return params_out, st
        # the copy buffers already carry output_dtype; unflatten only
        # reshapes per-leaf
        return params_out, st, arena.unflatten(copies, spec)


class FusedAdam(_LegacyFused):
    """Deprecated contrib FusedAdam (`contrib/optimizers/fused_adam.py:
    64-206`): Adam/AdamW with in-kernel unscale + optional fp16 param
    copy-out."""

    slot_names = ("m", "v")

    def __init__(self, lr: Scalar = 1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True,
                 bias_correction=True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction

    def _kernel(self, part, p, g, slots, count, lr, inv, output_dtype,
                ctx):
        return K.adam_update(
            p, g, slots["m"], slots["v"], lr=lr, beta1=self.beta1,
            beta2=self.beta2, eps=self.eps,
            weight_decay=self.weight_decay, step=count,
            adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction, grad_scale=inv,
            param_copy_dtype=output_dtype)


class FusedSGD(_LegacyFused):
    """Deprecated contrib FusedSGD (`contrib/optimizers/fused_sgd.py`):
    momentum SGD whose kernel unscales and emits the model copy — the
    ``materialize_master_grads`` interop path."""

    slot_names = ("m",)

    def __init__(self, lr: Scalar = 1e-3, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False,
                 wd_after_momentum=False):
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum

    def _kernel(self, part, p, g, slots, count, lr, inv, output_dtype,
                ctx):
        first = (count == 1) if self.momentum > 0 else False
        return K.sgd_update(
            p, g, slots["m"], lr=lr, momentum=self.momentum,
            dampening=self.dampening, weight_decay=self.weight_decay,
            nesterov=self.nesterov, first_run=first,
            wd_after_momentum=self.wd_after_momentum, grad_scale=inv,
            param_copy_dtype=output_dtype)


class FusedLAMB(_LegacyFused):
    """Deprecated contrib FusedLAMB (`contrib/optimizers/fused_lamb.py:
    6-192`): global grad-norm clip + Adam direction + per-tensor trust
    ratio over the arena kernels.

    The reference's legacy class drives ``p.grad`` directly
    (`fused_lamb.py:95`), but this surface keeps the shared legacy call
    shape — ``step(grads, state, params, scale=..., output_dtype=...)``
    — so its ``FP16_Optimizer`` interop (scaled grads in, model-copy
    out) works identically across the legacy trio. The clip factor is
    computed from the *unscaled* global norm and folded with ``1/scale``
    into stage 1's grad multiplier, so unscale+clip cost no extra pass.
    """

    slot_names = ("m", "v")

    def __init__(self, lr: Scalar = 1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 adam_w_mode=True, grad_averaging=True,
                 max_grad_norm=1.0, use_nvlamb=False):
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        #: reference contrib FusedLAMB knob (`fused_lamb.py:45-47`):
        #: False accumulates raw grads into m (β3 = 1)
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def _step_context(self, g_bufs, inv):
        # differs from fused.FusedLAMB._global_clip_scale only in that
        # the buffers here hold SCALED grads: the threshold compare must
        # see gnorm*inv, and the clip factor folds with inv into stage
        # 1's single grad multiplier (unscaled grads never materialize)
        if not self.max_grad_norm:
            return jnp.float32(inv)
        sq = sum(jnp.square(MT.multi_tensor_l2norm(g))
                 for g in g_bufs.values())
        gnorm = jnp.sqrt(sq) * inv
        clip = jnp.where(gnorm > self.max_grad_norm,
                         self.max_grad_norm / gnorm, 1.0)
        return (clip * inv).astype(jnp.float32)

    def _kernel(self, part, p, g, slots, count, lr, inv, output_dtype,
                ctx):
        u, m2, v2 = K.lamb_stage1(
            p, g, slots["m"], slots["v"], beta1=self.beta1,
            beta2=self.beta2, eps=self.eps,
            weight_decay=self.weight_decay, step=count,
            bias_correction=self.bias_correction,
            adam_w_mode=self.adam_w_mode, clip_scale=ctx,
            grad_averaging=self.grad_averaging)
        ratio_pos = fused.lamb_trust_ratios(
            part, p, u, use_nvlamb=self.use_nvlamb,
            weight_decay=self.weight_decay)
        out = K.lamb_stage2(p, u, ratio_pos, lr=lr,
                            param_copy_dtype=output_dtype)
        if output_dtype is not None:
            return out[0], m2, v2, out[1]
        return out, m2, v2                 # single output is unwrapped
