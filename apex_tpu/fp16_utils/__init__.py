"""apex_tpu.fp16_utils — the legacy explicit-master-weights surface.

Rebuild of `apex/fp16_utils` (`apex/fp16_utils/__init__.py:1-16`): the
pre-Amp API where the user owns the master-weight bookkeeping —
``FP16_Optimizer`` plus the ``network_to_half`` / ``prep_param_lists`` /
``master_params_to_model_params`` / ``clip_grad_norm`` utility family.
Everything the modern :mod:`apex_tpu.amp` bundle does implicitly is
explicit here, for users who want the pieces.
"""

from apex_tpu.fp16_utils.fp16util import (
    FP16Model,
    clip_grad_norm,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
    tofp16,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer, FP16OptState
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler

__all__ = [
    "FP16Model", "clip_grad_norm", "convert_network",
    "master_params_to_model_params", "model_grads_to_master_grads",
    "network_to_half", "prep_param_lists", "to_python_float", "tofp16",
    "FP16_Optimizer", "FP16OptState",
    "DynamicLossScaler", "LossScaler",
]
