#!/usr/bin/env python
"""Chaos audit: inject faults, prove self-healing, compare to an oracle.

The asserting sibling of ``ckpt_roundtrip.py --cpu8`` for the guard axis
(``run_tier1.sh --smoke`` runs it; exit status is the verdict). A small
model trains over the real :mod:`apex_tpu.data.pipeline` ImageFolder
stream on the 8-device CPU mesh, guarded by
:mod:`apex_tpu.guard`, under deterministic
:class:`~apex_tpu.guard.FaultPlan` chaos. Four claims, each printed and
asserted:

(a) **zero false positives** — a fault-free guarded run triggers zero
    guard events, zero in-graph skips, zero rewinds; and driving the
    step under the host policy leaves its compiled HLO BIT-IDENTICAL
    (the observe-only contract; the ``guard/no-extra-dispatch``
    compile-check case pins the module-count half);
(b) **rewind is bitwise** — a NaN-spike injected into the *committed
    params* (the silent-corruption model) is detected by the
    nonfinite-param probe; the policy rewinds, REJECTING the newer
    checkpoint that captured the corruption (nonfinite restore
    verification), restores the last good snapshot and fast-forwards
    the data cursor past the offending window — after which every
    per-step loss and the final params are **bitwise-equal** to an
    oracle run that never saw those batches;
(c) **skip-class faults converge** — in-graph NaN/Inf grad injection
    and a corrupted batch are each skipped in-graph (state never
    moves), the LR backs off and recovers, and the run still converges
    to a final loss within tolerance of the clean run's;
(d) **the event stream validates** — every emitted guard event passes
    ``check_metrics_schema.py --kind guard`` and the expected kinds are
    present.

Usage: python scripts/chaos_audit.py --cpu8
       python scripts/chaos_audit.py          # same audit, local devices
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_STEPS = 14
SAVE_EVERY = 2
BATCH = 8
IMG = 16          # decode size: D = 16*16*3 = 768 features
# stable for the 768-feature linear-MSE probe model: the Hessian scale
# is ~mean||x||^2 ≈ 256 for inputs in [0,1), so 2e-3 < 2/256 converges
# (a diverging model would trip the guard's spike detector for real —
# the clean-run zero-intervention claim requires an actually-clean run)
LR = 0.002
SEED = 3


def _make_cfg():
    from apex_tpu import guard
    return guard.GuardConfig(window=16, min_history=4, z_threshold=8.0,
                             grad_factor=50.0, lr_growth_interval=3)


def _make_step(cfg):
    import jax
    import jax.numpy as jnp

    from apex_tpu import guard
    from apex_tpu.guard import chaos

    def train_step(params, gs, x, y, code):
        def loss_fn(p):
            h = x.reshape(x.shape[0], -1) @ p["w"] + p["b"]
            h = chaos.inject_activation(h, code)
            onehot = jax.nn.one_hot(y, p["b"].shape[0],
                                    dtype=jnp.float32)
            return jnp.mean(jnp.square(h - onehot))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = chaos.inject_grads(grads, code)
        gs = guard.guard_observe(gs, cfg, loss=loss, grads=grads,
                                 params=params)
        new_p = jax.tree_util.tree_map(
            lambda p, g: p - LR * gs.lr_scale * g, params, grads)
        return guard.guard_commit(gs, new_p, params, cfg), gs, loss

    return jax.jit(train_step)


def _init_params(mesh):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.RandomState(0)
    rep = NamedSharding(mesh, P())
    return {
        "w": jax.device_put(jnp.asarray(
            rng.randn(IMG * IMG * 3, 4).astype("float32") * 0.05), rep),
        "b": jax.device_put(jnp.zeros((4,), jnp.float32), rep),
    }


def run_guarded(imgroot, workdir, jstep, cfg, mesh, *, plan=None,
                oracle_skip=None, observe_only=False, tag="run",
                n_steps=N_STEPS):
    """One guarded training run. ``plan`` applies chaos;
    ``oracle_skip=(at_index, n)`` fast-forwards the cursor past n
    batches when it reaches linear index ``at_index`` (the fault-free
    oracle of claim (b)). Returns a result dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import ckpt, guard, monitor
    from apex_tpu.data.pipeline import ImageFolderSource

    shd = NamedSharding(mesh, P("data"))
    events_path = os.path.join(workdir, f"guard_{tag}.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], guard_sink=monitor.JSONLSink(events_path))
    mgr = ckpt.CheckpointManager(os.path.join(workdir, f"ck_{tag}"),
                                 keep=4)
    policy = guard.GuardPolicy(manager=mgr,
                               event_sink=logger.record_guard,
                               observe_only=observe_only,
                               rewind_budget=2)
    src = ImageFolderSource(imgroot, batch=BATCH, size=IMG, seed=SEED,
                            workers=4, process_index=0, process_count=1)
    harness = guard.ChaosHarness(plan) if plan is not None else None
    params = _init_params(mesh)
    gs = guard.guard_init(cfg)
    it_box = [None]

    def pull():
        while True:
            if it_box[0] is None:
                it_box[0] = src.epoch()
            try:
                return next(it_box[0])
            except StopIteration:
                it_box[0] = None

    losses, rewound_at = [], []
    for step in range(n_steps):
        if oracle_skip and src.cursor_index() == oracle_skip[0]:
            src.skip_batches(oracle_skip[1])
            it_box[0] = None
        x, y = pull()
        if harness is not None:
            x, y = harness.filter_batch(step, (x, y))
        code = harness.fault_code(step) if harness is not None else 0
        xd = jax.device_put(x, shd)
        yd = jax.device_put(np.asarray(y, np.int32), shd)
        params, gs, loss = jstep(params, gs, xd, yd, jnp.int32(code))
        losses.append(np.float32(np.asarray(loss)))
        if step % SAVE_EVERY == 0:
            mgr.save(step, {"params": params, "gs": gs},
                     extra={"cursor": src.state()})
            mgr.wait()
        if harness is not None:
            params = harness.post_step(step, params,
                                       ckpt_root=mgr.root)
        act = policy.update(step, gs)
        if act.kind == "rewind":
            restored, mf = policy.rewind(
                step, {"params": params, "gs": gs}, src,
                reason=act.reason)
            params, gs = restored["params"], restored["gs"]
            it_box[0] = None
            rewound_at.append((step, int(mf["step"])))
        elif act.kind == "escalate":
            raise AssertionError(f"unexpected escalation at step "
                                 f"{step}: {act}")
    src.close()
    logger.close()
    return {"losses": losses, "params": params, "gs": gs,
            "policy": policy, "events_path": events_path,
            "rewound_at": rewound_at,
            "final_cursor_index": src.cursor_index()}


def main_audit():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from apex_tpu import guard
    from apex_tpu.data.pipeline import make_fake_imagefolder
    from apex_tpu.monitor.check import module_count_and_host_ops

    devs = jax.devices()
    if len(devs) < 8:
        raise SystemExit("audit needs 8 devices — pass --cpu8 for the "
                         "8-device virtual mesh")
    mesh = Mesh(np.array(devs[:8]), ("data",))
    cfg = _make_cfg()
    jstep = _make_step(cfg)

    tmp = tempfile.mkdtemp(prefix="apex_chaos_audit_")
    imgroot = make_fake_imagefolder(os.path.join(tmp, "imgs"),
                                    n_classes=4, per_class=8, size=64,
                                    seed=0)

    # --- (a) clean guarded run: zero interventions, bit-identical HLO --------
    import jax.numpy as jnp
    params0, gs0 = _init_params(mesh), guard.guard_init(cfg)
    x0 = jnp.zeros((BATCH, IMG, IMG, 3), jnp.float32)
    y0 = jnp.zeros((BATCH,), jnp.int32)
    hlo_before = jstep.lower(params0, gs0, x0, y0,
                             jnp.int32(0)).compile().as_text()
    clean = run_guarded(imgroot, tmp, jstep, cfg, mesh, tag="clean")
    hlo_after = jstep.lower(params0, gs0, x0, y0,
                            jnp.int32(0)).compile().as_text()
    assert hlo_after == hlo_before, \
        "guard observation changed the compiled step"
    _n, host = module_count_and_host_ops(jstep, params0, gs0, x0, y0,
                                         jnp.int32(0))
    assert not host, f"guarded step compiled host traffic: {host}"
    with open(clean["events_path"]) as f:
        clean_events = [l for l in f if l.strip()]
    assert not clean_events, \
        f"clean run emitted guard events: {clean_events[:3]}"
    assert int(np.asarray(clean["gs"].skip_count)) == 0
    assert clean["policy"].rewinds_done == 0
    assert all(np.isfinite(l) for l in clean["losses"])
    print(f"  (a) clean run: {N_STEPS} steps, 0 guard events, 0 skips, "
          f"0 rewinds; compiled HLO bit-identical under observation")

    # --- (b) NaN-spike → rewind → bitwise oracle -----------------------------
    # params poisoned AFTER step 7 commits (silent corruption); detected
    # at step 8 by the nonfinite-param probe. ckpt cadence saves steps
    # 0,2,4,6,8 — ckpt@8 captured the corruption and MUST be rejected;
    # the good snapshot is step 6 (cursor -> batch 7). The offending
    # window is batches 7..8; the oracle never sees them.
    plan_b = guard.FaultPlan(seed=1).add(7, "params", "nan")
    faulted = run_guarded(imgroot, tmp, jstep, cfg, mesh, plan=plan_b,
                          tag="nanspike")
    assert faulted["rewound_at"] == [(8, 6)], faulted["rewound_at"]
    with open(faulted["events_path"]) as f:
        fk = [json.loads(l)["kind"] for l in f if l.strip()]
    assert "guard_anomaly" in fk and "guard_rewind" in fk, fk
    with open(faulted["events_path"]) as f:
        rewind_ev = [json.loads(l) for l in f
                     if '"guard_rewind"' in l][0]
    assert rewind_ev["skipped_batches"] == 2, rewind_ev
    assert rewind_ev["fallbacks"] == 1, \
        (rewind_ev, "the corrupt ckpt@8 must be rejected")

    # the oracle trains the same BATCHES (0..6, 9..13), which is two
    # fewer steps than the recovery run's loop count (whose steps 7-8
    # were discarded by the rewind)
    oracle = run_guarded(imgroot, tmp, jstep, cfg, mesh,
                         oracle_skip=(7, 2), tag="oracle",
                         n_steps=N_STEPS - 2)
    # steps 9..13 of the faulted run line up with oracle steps 7..11
    f_tail = [l.tobytes().hex() for l in faulted["losses"][9:]]
    o_tail = [l.tobytes().hex() for l in oracle["losses"][7:12]]
    assert f_tail == o_tail, (
        "post-rewind losses diverge from the never-saw-the-poison "
        f"oracle: {list(zip(f_tail, o_tail))}")
    for k in ("w", "b"):
        a = np.asarray(faulted["params"][k])
        b = np.asarray(oracle["params"][k])
        assert np.array_equal(a, b), f"final params[{k}] not bitwise"
    assert (faulted["final_cursor_index"]
            == oracle["final_cursor_index"])
    print(f"  (b) NaN-spike: detected at step 8, ckpt@8 rejected "
          f"(nonfinite), rewound to step 6, cursor fast-forwarded past "
          f"2 batches; 5 post-rewind losses + final params BITWISE == "
          f"oracle that never saw the poison window")

    # --- (c) skip-class faults: in-graph skip + backoff, still converges -----
    plan_c = (guard.FaultPlan(seed=2)
              .add(3, "grads", "nan")
              .add(6, "batch", "corrupt", arg=100.0)
              .add(9, "grads", "inf"))
    skippy = run_guarded(imgroot, tmp, jstep, cfg, mesh, plan=plan_c,
                         tag="skips")
    n_skips = int(np.asarray(skippy["gs"].skip_count))
    assert n_skips == 3, f"expected 3 in-graph skips, got {n_skips}"
    assert skippy["policy"].rewinds_done == 0
    final, clean_final = skippy["losses"][-1], clean["losses"][-1]
    assert np.isfinite(final)
    assert final <= clean["losses"][0], \
        (final, "skip-class run failed to make progress")
    assert final <= clean_final * 2.0 + 0.05, (final, clean_final)
    lr_end = float(np.asarray(skippy["gs"].lr_scale))
    assert lr_end == 1.0, \
        (lr_end, "lr_scale should have recovered by the end")
    print(f"  (c) skip-class chaos (grad-NaN, corrupt batch, grad-Inf):"
          f" 3/3 skipped in-graph, 0 rewinds, lr_scale backed off and "
          f"recovered to 1.0, final loss {float(final):.4f} vs clean "
          f"{float(clean_final):.4f} (within tolerance)")

    # --- (d) guard event stream validates ------------------------------------
    from scripts.check_metrics_schema import check_guard_lines
    n_events = 0
    for res in (faulted, skippy):
        with open(res["events_path"]) as f:
            errors = check_guard_lines(f)
        assert not errors, ("guard event schema violations:\n"
                            + "\n".join(errors))
        with open(res["events_path"]) as f:
            n_events += sum(1 for l in f if l.strip())
    print(f"  (d) {n_events} guard events validate (--kind guard)")
    print("chaos audit ok")


def main():
    if "--cpu8" in sys.argv:
        import jax
        from apex_tpu import _compat
        jax.config.update("jax_platforms", "cpu")
        _compat.request_cpu_devices(8)
    main_audit()


if __name__ == "__main__":
    main()
