"""Conv → BN (+residual) (+ReLU) unit with a *distributed-dgrad* VJP.

The round-3 PERF.md sketch (executed here, per VERDICT r3 item 1): BN's
input gradient is linear in three per-channel-scaled fields,

    dx = A⊙g + B⊙x + C⊙1,   A = γi,  B = −γi²k₂,  C = A·(ik₂μ − k₁)

(g = masked upstream cotangent, i = invstd, k₁ = Σg/n, k₂ = Σg·x̂/n), so
the producing convolution's input/weight gradients distribute over the
three terms and ``dx`` itself never has to materialize:

    da = dgrad(g, A⊙W) + dgrad(x, B⊙W) + dgrad-const
    dW = A⊙wgrad(a, g) + B⊙wgrad(a, x) + C⊙wgrad(a, 1)

Two TPU-specific observations shape (and bound) the design:

1. **Per-channel scales fold into the weights, never the operands.** XLA
   materializes convolution operands — ``dgrad(A⊙g, W)`` would write and
   re-read a full activation-sized scaled copy. But A acts on the
   contracting (output-channel) axis, so ``dgrad(A⊙g, W) ≡ dgrad(g, A⊙W)``
   and scaling W is free. Likewise wgrad's scale lands on its (tiny)
   output. The constant term C⊙1 is batch-independent: its dgrad runs on
   an N=1 ones-field and broadcasts; its wgrad reduces to box-sums of the
   batch-summed input.
2. **The masked gradient is still a conv operand.** The ReLU mask is
   elementwise, so g must materialize before feeding dgrad — exactly the
   write the old dx pass performed. For plain/ReLU units the C-term folds
   into that same materialization (g′ = mask⊙dz + (ik₂μ−k₁)); for
   residual joins ``dr`` (= mask⊙dz) is an obligatory output anyway and
   feeds the convs raw.

Byte ledger (per unit, T = |x| = |g|, I = |a|): the restructure trades
the dx chain (write T + dgrad read T + wgrad read T) for a second full
dgrad (read T, write I) and a second full wgrad (read I + T), i.e. it
*removes* 3T but *adds* 2T + 3I — strictly negative for plain/ReLU units
and break-even only when I < T/3 with the cotangent already materialized
(the 1×1 expansion joins, I = T/4). Measured on the chip in PERF.md
round 4; this module is the experiment, kept behind
``ResNet(dx_distribute=...)`` / ``APEX_TPU_DX_DISTRIBUTE``.

This is the TPU analysis of the role the reference's fused NHWC BN
backward kernels play (`apex/contrib/csrc/groupbn/nhwc_batch_norm_kernel.h`,
`csrc/welford.cu:259-903`): those fuse the dx pass into hand-written
kernels because CUDA kernels stream operands; XLA convs cannot, which is
where the accounting diverges.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.ops.bn_act import (
    _Cfg, _fwd_common, _normalize_groups, _reduce_axes, make_cfg,
)

__all__ = ["conv_bn_act_train", "conv_bn_add_act_train", "ConvBNAct",
           "make_conv_cfg"]


class _ConvCfg(NamedTuple):
    """Static conv + BN configuration (hashable custom_vjp nondiff arg)."""
    strides: Tuple[int, int]
    padding: Any            # "SAME" | "VALID" | ((lo,hi),(lo,hi))
    relu: bool
    eps: float
    axis_name: Optional[str]
    groups: Optional[Tuple[Tuple[int, ...], ...]]

    def bn(self) -> _Cfg:
        return _Cfg(relu=self.relu, eps=self.eps, axis_name=self.axis_name,
                    groups=self.groups)


def make_conv_cfg(*, strides=(1, 1), padding="SAME", relu: bool,
                  eps: float = 1e-5, axis_name: Optional[str] = None,
                  axis_index_groups=None) -> _ConvCfg:
    if not isinstance(padding, str):
        padding = tuple(tuple(int(p) for p in pair) for pair in padding)
    return _ConvCfg(strides=tuple(int(s) for s in strides),
                    padding=padding, relu=bool(relu), eps=float(eps),
                    axis_name=axis_name,
                    groups=_normalize_groups(axis_index_groups))


def _conv(a, w, cfg: _ConvCfg):
    return jax.lax.conv_general_dilated(
        a, w, window_strides=cfg.strides, padding=cfg.padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _channel_terms(cfg, dz_masked32, x, scale, mean, invstd, count):
    """Channel sums (psum'd over the stats group) and the per-channel
    distribution coefficients A, B and c′ = C/A (γ-free, safe at γ=0)."""
    axes = _reduce_axes(x)
    cshape = (1,) * len(axes) + (-1,)
    xhat = (x.astype(jnp.float32) - mean.reshape(cshape)) \
        * invstd.reshape(cshape)
    sum_dy = jnp.sum(dz_masked32, axis=axes)
    sum_dy_xhat = jnp.sum(dz_masked32 * xhat, axis=axes)
    if cfg.axis_name is not None:
        sum_dy, sum_dy_xhat = jax.lax.psum(
            (sum_dy, sum_dy_xhat), cfg.axis_name,
            axis_index_groups=cfg.groups)
    k1 = sum_dy / count
    k2 = sum_dy_xhat / count
    gam = scale.astype(jnp.float32)
    A = gam * invstd
    B = -gam * invstd * invstd * k2
    cprime = invstd * k2 * mean - k1
    return sum_dy, sum_dy_xhat, A, B, cprime


def _fold(w, s):
    """Scale the conv kernel along its output-channel (HWIO: last) axis."""
    return (w.astype(jnp.float32) * s).astype(w.dtype)


def _distributed_grads(cfg, a, w, x, gp, A, B, cprime_in_gp: bool,
                       C=None):
    """da and dW via term-distributed conv transposes.

    ``gp`` is the materialized masked-gradient operand (with c′ folded in
    when ``cprime_in_gp``); when it is not folded, ``C`` carries the
    constant term, handled batch-independently (N=1 dgrad broadcast +
    batch-summed wgrad).
    """
    # input gradients: scales folded into the weights
    _, vjp_a1 = jax.vjp(lambda a_: _conv(a_, _fold(w, A), cfg), a)
    (da,) = vjp_a1(gp.astype(a.dtype))
    _, vjp_a2 = jax.vjp(lambda a_: _conv(a_, _fold(w, B), cfg), a)
    (da2,) = vjp_a2(x)
    da = da + da2

    # weight gradients: scales land on the (weight-shaped) outputs
    _, vjp_w = jax.vjp(lambda w_: _conv(a, w_, cfg), w)
    (dw1,) = vjp_w(gp.astype(x.dtype))
    (dw2,) = vjp_w(x)
    dw = A * dw1.astype(jnp.float32) + B * dw2.astype(jnp.float32)

    if not cprime_in_gp:
        # constant term C⊙1: batch-independent, so dgrad runs once on an
        # N=1 ones-field (C folded into W) and broadcasts over batch
        ones1 = jnp.ones((1,) + x.shape[1:], x.dtype)
        _, vjp_a3 = jax.vjp(lambda a_: _conv(a_, _fold(w, C), cfg), a[:1])
        (da3,) = vjp_a3(ones1)
        da = da + da3  # broadcasts over N
        # wgrad(a, C⊙1) = C ⊙ wgrad(Σ_n a, 1): linear in a, cotangent
        # constant over batch — one channel/box reduce of a, tiny conv
        asum = jnp.sum(a.astype(jnp.float32), axis=0,
                       keepdims=True).astype(a.dtype)
        _, vjp_w3 = jax.vjp(lambda w_: _conv(asum, w_, cfg), w)
        (dw3,) = vjp_w3(ones1)
        dw = dw + C * dw3.astype(jnp.float32)
    return da, dw.astype(jnp.float32)


# --- conv → BN (+ReLU), no residual -----------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def conv_bn_act_train(a, w, scale, bias, cfg: _ConvCfg):
    """Training-mode ``relu?(bn(conv(a, w)))`` with the distributed-dgrad
    backward. Returns ``(z, mean, biased_var, count)`` like
    :func:`apex_tpu.ops.bn_act.bn_act_train`."""
    x = _conv(a, w, cfg)
    z, mean, var, count, _ = _fwd_common(x, None, scale, bias, cfg.bn())
    return z, mean, var, count


def _cba_fwd(a, w, scale, bias, cfg):
    x = _conv(a, w, cfg)
    z, mean, var, count, invstd = _fwd_common(x, None, scale, bias,
                                              cfg.bn())
    return (z, mean, var, count), (a, w, x, scale, bias, mean, invstd,
                                   count)


def _cba_bwd(cfg, res, cts):
    dz = cts[0]
    a, w, x, scale, bias, mean, invstd, count = res
    axes = _reduce_axes(x)
    cshape = (1,) * len(axes) + (-1,)

    g32 = dz.astype(jnp.float32)
    if cfg.relu:
        xhat = (x.astype(jnp.float32) - mean.reshape(cshape)) \
            * invstd.reshape(cshape)
        pre = xhat * scale.astype(jnp.float32).reshape(cshape) \
            + bias.astype(jnp.float32).reshape(cshape)
        g32 = jnp.where(pre > 0, g32, 0.0)

    sum_dy, sum_dy_xhat, A, B, cprime = _channel_terms(
        cfg, g32, x, scale, mean, invstd, count)

    # g′ = mask⊙dz + c′ — the one materialized operand (replaces the old
    # dx pass write byte-for-byte)
    gp = g32 + cprime.reshape(cshape)
    da, dw = _distributed_grads(cfg, a, w, x, gp, A, B,
                                cprime_in_gp=True)
    return da, dw.astype(jnp.float32), sum_dy_xhat.astype(scale.dtype), \
        sum_dy.astype(bias.dtype)


conv_bn_act_train.defvjp(_cba_fwd, _cba_bwd)


# --- conv → BN + residual (+ReLU) -------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def conv_bn_add_act_train(a, w, r, scale, bias, cfg: _ConvCfg):
    """Training-mode ``relu?(bn(conv(a, w)) + r)`` — the residual-join
    unit with the distributed backward. ``dr`` materializes once (it is a
    returned cotangent) and feeds the dgrad/wgrad terms raw; the constant
    term is handled batch-independently."""
    x = _conv(a, w, cfg)
    z, mean, var, count, _ = _fwd_common(x, r, scale, bias, cfg.bn())
    return z, mean, var, count


def _cbaa_fwd(a, w, r, scale, bias, cfg):
    x = _conv(a, w, cfg)
    z, mean, var, count, invstd = _fwd_common(x, r, scale, bias, cfg.bn())
    zres = z if cfg.relu else None
    rtok = jnp.zeros((), r.dtype)
    return (z, mean, var, count), (a, w, x, scale, bias, mean, invstd,
                                   count, zres, rtok)


def _cbaa_bwd(cfg, res, cts):
    dz = cts[0]
    a, w, x, scale, bias, mean, invstd, count, z, rtok = res

    if cfg.relu:
        dr = jnp.where(z > 0, dz, jnp.zeros((), dz.dtype)) \
            .astype(rtok.dtype)
    else:
        dr = dz.astype(rtok.dtype)

    sum_dy, sum_dy_xhat, A, B, cprime = _channel_terms(
        cfg, dr.astype(jnp.float32), x, scale, mean, invstd, count)
    C = A * cprime
    da, dw = _distributed_grads(cfg, a, w, x, dr, A, B,
                                cprime_in_gp=False, C=C)
    return da, dw.astype(jnp.float32), dr, \
        sum_dy_xhat.astype(scale.dtype), sum_dy.astype(bias.dtype)


conv_bn_add_act_train.defvjp(_cbaa_fwd, _cbaa_bwd)


# --- flax module -------------------------------------------------------------

class ConvBNAct(nn.Module):
    """Conv (no bias) → BN (+residual) (+ReLU) as one VJP unit with the
    distributed-dgrad backward. Parameter layout: ``kernel`` (HWIO, fp32)
    + ``scale``/``bias`` + ``batch_stats`` — note this differs from the
    separate ``nn.Conv`` + ``FusedBNAct`` tree (experiment module; see
    docs/models.md).
    """
    features: int
    kernel_size: Tuple[int, int] = (1, 1)
    strides: Tuple[int, int] = (1, 1)
    relu: bool = True
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    init_scale: float = 1.0
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, a, residual=None, train: bool = True):
        c = self.features
        kshape = tuple(self.kernel_size) + (a.shape[-1], c)
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            kshape, jnp.float32)
        scale = self.param("scale",
                           nn.initializers.constant(self.init_scale),
                           (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda *_: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda *_: jnp.ones((c,), jnp.float32))

        if self.dtype is not None:
            a = a.astype(self.dtype)
            if residual is not None:
                residual = residual.astype(self.dtype)
        w = kernel.astype(a.dtype)

        axis = None if self.is_initializing() else self.axis_name
        cfg = make_conv_cfg(strides=self.strides, relu=self.relu,
                            eps=self.epsilon, axis_name=axis)

        if not train:
            x = _conv(a, w, cfg)
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon)
            from apex_tpu.ops.bn_act import _apply
            y = _apply(x.astype(jnp.float32), residual, scale, bias,
                       ra_mean.value, inv, self.relu)
            return y.astype(a.dtype)

        if residual is None:
            z, mean, var, count = conv_bn_act_train(a, w, scale, bias,
                                                    cfg)
        else:
            z, mean, var, count = conv_bn_add_act_train(
                a, w, residual, scale, bias, cfg)

        if not self.is_initializing():
            unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * unbiased
        return z
