"""Arena layout + flatten/unflatten round-trips, native planner vs fallback."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import arena
from apex_tpu.arena import native


def _tree():
    k = jax.random.PRNGKey(0)
    ks = jax.random.split(k, 4)
    return {
        "conv": {"kernel": jax.random.normal(ks[0], (3, 3, 4, 8)),
                 "bias": jax.random.normal(ks[1], (8,))},
        "bn": {"scale": jax.random.normal(ks[2], (8,)).astype(jnp.float32)},
        "dense": {"kernel": jax.random.normal(ks[3], (8, 2))
                  .astype(jnp.bfloat16)},
    }


def test_native_planner_loaded():
    # the image has g++; the on-demand build should succeed
    assert native.native_available(), "native planner failed to build/load"


def test_plan_alignment_and_offsets():
    spec = arena.plan(_tree(), alignment=1024)
    for part in spec.partitions:
        for off, padded, size in zip(part.offsets, part.padded, part.sizes):
            assert off % 1024 == 0
            assert padded % 1024 == 0
            assert padded >= size
        assert part.total == sum(part.padded)


def test_dtype_partitioning():
    spec = arena.plan(_tree())
    assert set(spec.dtypes) == {"float32", "bfloat16"}


def test_flatten_unflatten_roundtrip():
    tree = _tree()
    spec = arena.plan(tree)
    flat = arena.flatten(tree, spec)
    out = arena.unflatten(flat, spec)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tree, out)


def test_flatten_under_jit():
    tree = _tree()
    spec = arena.plan(tree)

    @jax.jit
    def roundtrip(t):
        return arena.unflatten(arena.flatten(t, spec), spec)

    out = roundtrip(tree)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tree, out)


def test_padding_is_zero():
    tree = {"w": jnp.ones((3,), jnp.float32)}  # 3 elems -> 1024 slot
    spec = arena.plan(tree)
    flat = arena.flatten(tree, spec)["float32"]
    assert flat.shape[0] == 512 * 128
    np.testing.assert_array_equal(np.asarray(flat[3:]), 0.0)


def test_segment_ids_and_mask():
    tree = {"a": jnp.ones((3,)), "b": jnp.ones((5,))}
    spec = arena.plan(tree, alignment=8)
    ids = arena.segment_ids(spec, jnp.float32)
    assert ids.shape[0] == 512 * 128
    assert list(ids[:3]) == [0, 0, 0] and list(ids[3:8]) == [-1] * 5
    assert list(ids[8:13]) == [1] * 5
    mask = arena.valid_mask(spec, jnp.float32)
    assert mask.sum() == 8


def test_python_fallback_matches_native():
    sizes = np.array([100, 2048, 1, 999], np.int64)
    n_off, n_pad, n_tot = native.plan_layout(sizes, 1024)
    # force fallback (the failure sentinel stops any reload attempt)
    lib, native._lib = native._lib, None
    native._load_failed = True
    try:
        p_off, p_pad, p_tot = native.plan_layout(sizes, 1024)
    finally:
        native._lib, native._load_failed = lib, False
    np.testing.assert_array_equal(n_off, p_off)
    np.testing.assert_array_equal(n_pad, p_pad)
    assert n_tot == p_tot


def test_bucket_planning():
    padded = np.array([1024, 1024, 2048, 1024], np.int64)
    ids, nb = native.plan_buckets(padded, 2048)
    assert list(ids) == [0, 0, 1, 2]
    assert nb == 3
    lib, native._lib = native._lib, None
    native._load_failed = True
    try:
        ids2, nb2 = native.plan_buckets(padded, 2048)
    finally:
        native._lib, native._load_failed = lib, False
    np.testing.assert_array_equal(ids, ids2)
    assert nb == nb2


def test_shard_planning_and_pad():
    starts, per = native.plan_shards(10000, 8, 1024)
    assert per == 2048  # ceil(10000/8)=1250 -> align 2048
    assert list(starts) == [i * 2048 for i in range(8)]
    bufs = {"float32": jnp.ones((10000,))}
    padded = arena.shard_pad(bufs, 8)
    assert padded["float32"].shape[0] == 2048 * 8


def test_zeros_state_allocation():
    spec = arena.plan({"w": jnp.ones((10,), jnp.bfloat16)})
    state = arena.zeros(spec, dtype=jnp.float32)
    assert state["bfloat16"].dtype == jnp.float32  # fp32 state for bf16 arena
    assert state["bfloat16"].shape[0] == 512 * 128
