"""apex_tpu.prof.memory + compile_watch — HBM & compilation observability.

Pins the three acceptance claims of scripts/memory_budget.py at toy
scale (the flagship-scale asserting audit is the script itself, run by
``run_tier1.sh --smoke``):

- the MemoryReport class attribution sums to the ``memory_analysis()``
  total within 1% and classifies arguments by path (params vs optimizer
  state vs inputs);
- ZeRO ``DistributedFusedAdam`` optimizer-state bytes shrink vs the
  replicated optimizer in the *report*, matching the analytic
  ``state_bytes`` table;
- ``compile_watch`` counts exactly one trace for a steady-state step and
  names the changed argument on a forced retrace;
- a crash dump written by the FlightRecorder embeds the attached
  MemoryReport (subprocess, real excepthook path) and still passes the
  trace schema validator; the memory event channel passes
  ``check_metrics_schema.py --kind memory``.
"""

import io
import json
import os
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, monitor, optim, prof, trace
from apex_tpu.prof import compile_watch as cw
from apex_tpu.prof import memory as M

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_SCHEMA_SCRIPT = os.path.join(_REPO_ROOT, "scripts",
                              "check_metrics_schema.py")


def _validate(path, kind):
    return subprocess.run(
        [sys.executable, _SCHEMA_SCRIPT, "--kind", kind, str(path)],
        capture_output=True, text=True, cwd=_REPO_ROOT)


# --- MemoryReport ------------------------------------------------------------

def _toy_step():
    def step(params, x, y):
        def loss_fn(p):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean(jnp.square(h @ p["w2"] - y))
        g = jax.grad(loss_fn)(params)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, params, g)

    params = {"w1": jnp.zeros((64, 128)), "w2": jnp.zeros((128, 8))}
    x = jnp.zeros((32, 64))
    y = jnp.zeros((32, 8))
    return step, params, x, y


class TestMemoryReport:
    def test_attribution_closes_and_classifies(self):
        step, params, x, y = _toy_step()
        rep = prof.memory_report(jax.jit(step), params, x, y,
                                 batch_size=32)
        total, attr = rep.total_bytes, rep.attributed_total()
        assert total > 0
        assert abs(attr - total) / total < 0.01
        assert set(rep.classes) == set(M.BUFFER_CLASSES)
        # params classified from the arg path, batch inputs as inputs
        w_bytes = (64 * 128 + 128 * 8) * 4
        assert rep.classes["params"] == w_bytes
        assert rep.classes["inputs"] == (32 * 64 + 32 * 8) * 4
        args = [r for r in rep.buffers if r.kind == "argument"]
        by_path = {r.scope: r for r in args}
        assert by_path["params['w1']"].cls == "params"
        assert by_path["x"].cls == "inputs"
        assert by_path["x"].batch_scaled
        assert not by_path["params['w1']"].batch_scaled
        assert rep.peak_live_bytes >= rep.stats["argument"]

    def test_table_and_summary_render(self):
        step, params, x, y = _toy_step()
        rep = prof.memory_report(jax.jit(step), params, x, y)
        t = rep.table()
        assert "params" in t and "MiB" in t or "KiB" in t
        s = rep.summary()
        json.dumps(s)                       # JSON-able, by contract
        assert s["classes"]["params"] == rep.classes["params"]
        assert s["top_buffers"]
        ev = rep.to_event(rank=0, step=3)
        assert ev["kind"] == "memory_report" and ev["step"] == 3

    def test_forecast_and_max_batch(self):
        step, params, x, y = _toy_step()
        rep = prof.memory_report(jax.jit(step), params, x, y,
                                 batch_size=32)
        assert rep.batch_bytes > 0
        f2 = rep.forecast(64)
        assert f2["peak_bytes"] == rep.peak_live_bytes + rep.batch_bytes
        assert f2["fits"] is None           # CPU reports no capacity
        # synthetic capacity: forecasts + max batch become decidable
        rep.hbm_limit = rep.peak_live_bytes + rep.batch_bytes
        assert rep.forecast(64)["fits"] is True
        assert rep.forecast(128)["fits"] is False
        mb = rep.max_batch()
        assert 64 <= mb < 128

    def test_accepts_precompiled_executable(self):
        step, params, x, y = _toy_step()
        compiled = jax.jit(step).lower(params, x, y).compile()
        rep = prof.memory_report(compiled)
        assert rep.total_bytes == M.memory_stats_of(compiled)["total"]

    def test_classify_arg_path(self):
        c = M.classify_arg_path
        assert c("state.params['w']") == "params"
        assert c("state.opt_state.slots['m']['float32']") == \
            "optimizer_state"
        assert c("state.opt_state.count") == "optimizer_state"
        assert c("x") == "inputs"
        assert c("batch['tokens']") == "inputs"
        assert c("residual['w']") == "comm"

    def test_scope_classification(self):
        assert M.classify_scope("ddp/sync_gradients/bucket00", "fusion") \
            == "comm"
        assert M.classify_scope("", "all-gather") == "comm"
        assert M.classify_scope("amp/fwd/conv", "fusion") == "activations"

    def test_device_sample_shape(self):
        s = prof.device_memory_sample()
        assert set(s) == {"bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit"}


# --- ZeRO shard savings in the report ---------------------------------------

class TestZeroShardReport:
    def _report(self, mesh8, tx, sync=None):
        params = {"w1": jnp.zeros((600, 1200)), "w2": jnp.zeros((257,))}
        amp_opt = amp.Amp(amp.Policy.from_opt_level("O2"), tx)

        def step(state, x):
            def loss_fn(mp):
                h = jnp.tanh(x @ mp["w1"])
                return jnp.sum(h * h)
            loss, grads, state, finite = amp_opt.backward(state, loss_fn)
            if sync is not None:
                grads = sync(grads)
            return amp_opt.apply_gradients(state, grads, finite), loss

        state = jax.jit(jax.shard_map(
            lambda p: amp_opt.init(p), mesh=mesh8, in_specs=(P(),),
            out_specs=P(), check_vma=False))(params)
        x = jnp.zeros((64, 600))
        mapped = jax.jit(jax.shard_map(
            step, mesh=mesh8, in_specs=(P(), P("data")),
            out_specs=(P(), P()), check_vma=False))
        compiled = mapped.lower(state, x).compile()
        return prof.memory_report(compiled, batch_size=8), params

    def test_zero_opt_state_shrinks_and_matches_analytic(self, mesh8):
        from apex_tpu import parallel

        zero_tx = optim.DistributedFusedAdam(lr=1e-3, axis_name="data")
        rep_zero, params = self._report(mesh8, zero_tx)
        rep_repl, _ = self._report(
            mesh8, optim.FusedAdam(lr=1e-3),
            sync=lambda g: parallel.sync_gradients(g, "data"))

        for rep in (rep_zero, rep_repl):          # (a) at toy scale
            assert abs(rep.attributed_total() - rep.total_bytes) \
                <= 0.01 * rep.total_bytes
        opt_z = rep_zero.classes["optimizer_state"]
        opt_r = rep_repl.classes["optimizer_state"]
        analytic = zero_tx.state_bytes(params, world=8)
        # report within 2% of the analytic shard table (the int32 count
        # scalar is the report's only extra)
        assert abs(opt_z - analytic["sharded_bytes"]) \
            <= 0.02 * analytic["sharded_bytes"], (opt_z, analytic)
        # slot-normalized shrink: 3 sharded slots vs 2 replicated ones;
        # alignment padding on this deliberately small tree caps the
        # saving at ~1.45/N (the flagship-scale ~1/N claim is
        # scripts/memory_budget.py's)
        ratio = (opt_z / 3) / (opt_r / 2)
        assert ratio < 0.35, (opt_z, opt_r, ratio)
        assert analytic["ratio"] == pytest.approx(
            (opt_z / 3) / (analytic["per_slot_replicated"]), rel=0.02)


# --- compile_watch -----------------------------------------------------------

class TestCompileWatch:
    def test_steady_state_single_trace(self):
        w = prof.CompileWatcher()
        f = w.watch(lambda x: x * 2 + 1, name="f")
        a = jnp.ones((8,))
        for _ in range(4):
            f(a)
        rec = w["f"]
        assert (rec.n_calls, rec.n_traces, rec.n_retraces) == (4, 1, 0)
        assert rec.last_change == "first call"

    def test_retrace_names_changed_argument(self):
        w = prof.CompileWatcher()
        f = w.watch(lambda x, y: (x @ y).sum(), name="mm")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(jnp.ones((8, 4)), jnp.ones((4, 2)))
            f(jnp.ones((16, 4)), jnp.ones((4, 2)))     # x rows changed
            f(jnp.ones((16, 4)), jnp.ones((4, 8)))     # y cols changed
        rec = w["mm"]
        assert rec.n_traces == 3 and rec.n_retraces == 2
        assert "(8, 4)" in rec.retraces[0]["changed"]
        assert "(16, 4)" in rec.retraces[0]["changed"]
        assert "(4, 2)" in rec.retraces[1]["changed"]
        assert "(4, 8)" in rec.retraces[1]["changed"]
        # dtype changes are named too
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(jnp.ones((16, 4)), jnp.ones((4, 8), jnp.bfloat16))
        assert "bfloat16" in rec.retraces[2]["changed"]

    def test_warns_after_n_retraces(self):
        w = prof.CompileWatcher(warn_after=2)
        f = w.watch(lambda x: x + 1, name="g")
        with pytest.warns(RuntimeWarning, match="retraced 2 times"):
            for n in (1, 2, 3):
                f(jnp.ones((n,)))

    def test_compile_spans_and_events(self):
        events = []
        w = prof.CompileWatcher(on_event=events.append)
        tracer = trace.Tracer()
        with tracer:
            with trace.step(0):
                f = w.watch(lambda x: x * x, name="sq")
                f(jnp.ones((4,)))
                f(jnp.ones((4,)))               # no new span
        spans = [s for st in tracer.steps for s in st.spans]
        compile_spans = [s for s in spans if s.kind == "compile"]
        assert len(compile_spans) == 1
        assert compile_spans[0].name == "compile/sq"
        assert compile_spans[0].dur_ms > 0
        kinds = [e["kind"] for e in events]
        assert kinds == ["compile"]
        assert events[0]["fn"] == "sq"

    def test_report_and_counters_render(self):
        w = prof.CompileWatcher()
        f = w.watch(lambda x: x, name="id")
        f(jnp.ones(2))
        out = w.report()
        assert "id" in out and "process totals" in out
        c = w.counters()
        assert c["id"]["n_traces"] == 1
        assert "_process" in c
        json.dumps(c)

    def test_fallback_mode_dedupes_cached_shapes(self):
        """Without jit cache introspection (a non-jit callable exposing
        .lower), alternating between already-seen shapes must NOT count
        as retracing — only genuinely new signatures do."""
        class FakeJitted:
            def lower(self, *a, **k):           # duck-types as jitted,
                raise NotImplementedError       # but no _cache_size
            def __call__(self, x):
                return x

        w = prof.CompileWatcher()
        f = w.watch(FakeJitted(), name="fake")
        a, b = jnp.ones((4,)), jnp.ones((8,))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for arg in (a, b, a, b, a):
                f(arg)
        rec = w["fake"]
        assert rec.n_calls == 5
        assert rec.n_traces == 2, rec.n_traces
        assert rec.n_retraces == 1

    def test_global_counters_advance(self):
        assert cw.install()
        before = cw.global_counters()["compiles"]
        jax.jit(lambda x: x - 3)(jnp.ones(7))
        after = cw.global_counters()["compiles"]
        assert after >= before + 1


# --- the memory event channel ------------------------------------------------

class TestMemoryChannel:
    def test_stream_validates(self, tmp_path):
        path = tmp_path / "memory.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], memory_sink=monitor.JSONLSink(str(path)))
        w = prof.CompileWatcher(warn_after=1,
                                on_event=logger.record_memory)
        step, params, x, y = _toy_step()
        f = w.watch(step, name="toy_step")
        f(params, x, y)
        rep = prof.memory_report(f.jitted, params, x, y, batch_size=32)
        logger.attach_memory_report(rep)
        logger.sample_memory(step=0)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(params, x[:16], y[:16])           # retrace event
        logger.close()
        r = _validate(path, "memory")
        assert r.returncode == 0, r.stderr + r.stdout
        kinds = [json.loads(l)["kind"] for l in path.read_text()
                 .splitlines()]
        assert "memory" in kinds and "memory_report" in kinds
        assert "retrace" in kinds and "compile" in kinds
        assert logger.memory_report is rep

    def test_closed_logger_drops_events(self, tmp_path):
        path = tmp_path / "memory.jsonl"
        logger = monitor.MetricsLogger(
            sinks=[], memory_sink=monitor.JSONLSink(str(path)))
        logger.sample_memory(step=0)
        logger.close()
        logger.sample_memory(step=1)            # after close: dropped
        assert len(path.read_text().splitlines()) == 1


# --- crash dump embeds the MemoryReport (acceptance, subprocess) -------------

_CRASH_CHILD = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from apex_tpu import prof, trace

def step(params, x):
    g = jax.grad(lambda p: jnp.sum(jnp.tanh(x @ p["w"])))(params)
    return {"w": params["w"] - 0.1 * g["w"]}

params = {"w": jnp.zeros((64, 32))}
x = jnp.ones((16, 64))
jstep = jax.jit(step)
rep = prof.memory_report(jstep, params, x, batch_size=16)

recorder = trace.FlightRecorder(sys.argv[1], capacity=8)
recorder.attach_memory_report(rep)
recorder.install()
params = jstep(params, x)
recorder.record(step=0, metrics=None)
raise MemoryError("synthetic OOM: RESOURCE_EXHAUSTED")
"""


def test_crash_dump_contains_memory_report(tmp_path):
    """The OOM-forensics acceptance: a crashing run whose recorder has
    an attached MemoryReport writes a dump whose header carries the
    class breakdown and names the biggest buffers — and the dump still
    passes the trace schema validator."""
    dump = tmp_path / "crash.jsonl"
    r = subprocess.run([sys.executable, "-c", _CRASH_CHILD, str(dump)],
                       cwd=_REPO_ROOT, capture_output=True, text=True,
                       timeout=240)
    assert r.returncode != 0
    assert "synthetic OOM" in r.stderr
    assert dump.exists(), r.stderr
    hdr = json.loads(dump.read_text().splitlines()[0])
    assert hdr["kind"] == "crash" and "MemoryError" in hdr["exception"]
    mr = hdr["memory_report"]
    assert mr["total_bytes"] > 0
    assert mr["classes"]["params"] == 64 * 32 * 4
    names = [b["name"] for b in mr["top_buffers"]]
    assert names, "dump names no buffers"
    assert mr["peak_live_bytes"] >= mr["classes"]["params"]
    v = _validate(dump, "trace")
    assert v.returncode == 0, v.stderr + v.stdout


# --- DDP surface -------------------------------------------------------------

def test_ddp_memory_report_infers_per_device_batch(mesh8):
    from apex_tpu import parallel

    ddp = parallel.DistributedDataParallel(mesh8)

    def step(p, x):
        g = jax.grad(lambda p, x: jnp.sum(jnp.tanh(x @ p["w"])))(p, x)
        g = ddp.sync(g)
        return {"w": p["w"] - 0.1 * g["w"]}

    wrapped = ddp.wrap(step, out_specs=P())
    p = {"w": jnp.zeros((32, 16))}
    x = jnp.zeros((64, 32))                    # global batch 64 -> 8/dev
    rep = ddp.memory_report(wrapped, p, x)
    assert rep.batch_size == 8
    assert abs(rep.attributed_total() - rep.total_bytes) \
        <= 0.01 * rep.total_bytes

    # ambiguous batch-side leading dims (a stats vector whose length is
    # also world-divisible) must yield NO inferred batch, not a wrong one
    def step2(p, stats, x):
        g = jax.grad(lambda p, x: jnp.sum(jnp.tanh(x @ p["w"])
                                          + stats.sum()))(p, x)
        return {"w": p["w"] - 0.1 * ddp.sync(g)["w"]}

    def step2w(p, batch):
        stats, xb = batch
        return step2(p, stats, xb)

    wrapped2 = ddp.wrap(step2w, batch_specs=(P(), P("data")),
                        out_specs=P())
    rep2 = ddp.memory_report(wrapped2, p, (jnp.zeros((16,)), x))
    assert rep2.batch_size is None


def test_amp_memory_footprint_accounting():
    params = {"w": jnp.zeros((100, 10)), "b": jnp.zeros((10,))}
    a2 = amp.Amp(amp.Policy.from_opt_level("O2"),
                 optim.FusedSGD(lr=0.1))
    fp = a2.memory_footprint(params)
    assert fp["n_params"] == 1010
    assert fp["master_bytes"] == 1010 * 4       # fp32 masters
    assert fp["model_copy_bytes"] == 1010 * 2   # bf16 forward copy
    a3 = amp.Amp(amp.Policy.from_opt_level("O3"),
                 optim.FusedSGD(lr=0.1))
    fp3 = a3.memory_footprint(params)
    assert fp3["master_bytes"] == 1010 * 2      # pure-half: one copy...
    assert fp3["model_copy_bytes"] == 0         # ...and ONLY one (the
                                                # cast is an elided no-op)
    assert fp3["total_bytes"] == 1010 * 2 + fp3["scaler_bytes"]
