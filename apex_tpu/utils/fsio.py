"""Shared-filesystem atomic-commit primitives.

One temp→fsync→rename recipe for every layer that publishes records
on a shared filesystem — the checkpoint manifest (``ckpt.format``)
and the cluster control plane's generation/lease files
(``cluster.membership``) each need the identical guarantee (readers
see either the old record or the new one, never a torn write, and
the rename IS the commit point); a private copy per layer is exactly
the drift :mod:`apex_tpu.utils.format` exists to prevent for byte
formatting.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

__all__ = ["write_atomic", "fsync_dir"]


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives a crash."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass                     # not all filesystems allow dir fsync


def write_atomic(path: str, data: bytes, *,
                 tmp_suffix: str = ".tmp",
                 before_rename: Optional[Callable[[], None]] = None
                 ) -> None:
    """temp → fsync → rename; durable against crash at any instant.

    ``tmp_suffix`` disambiguates the temp file when several processes
    may replace the same path concurrently (pass a pid-qualified
    suffix); ``before_rename`` is the test-crash hook seam — it runs
    after the data is durable but before the rename commits it."""
    tmp = path + tmp_suffix
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    if before_rename is not None:
        before_rename()
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))
