"""Training-dynamics telemetry: gradient noise scale + replica geometry.

ROADMAP items 4 and 5 both end in a judgment the repo could not make:
"O4/fp8 converges within tolerance of O2" and "Adasum raises the
effective-batch LR ceiling" are *dynamics* claims — fp8 kernels and
projection-combined gradients change the arithmetic on purpose, so the
bitwise-vs-oracle proof style of every prior arc does not apply. This
module is the measurement instrument those arcs cash in, the PR-10
pattern (land the observatory, then spend it) applied to training
dynamics:

- **the fold** (:func:`dynamics_observe`): every ``check_every`` steps
  the jitted step folds (a) the **gradient noise scale** inputs — the
  mean per-replica squared grad norm vs the pooled mean's squared norm,
  which DDP's sync already has in hand
  (:func:`apex_tpu.parallel.distributed.dynamics_probe` psums one
  scalar alongside the existing gradient psum, under the registered
  ``ddp/dynamics_gns`` scope) — into rolling EMAs the host turns into
  the unbiased ``B_simple`` estimator and a critical-batch-size
  estimate; (b) **replica-gradient geometry** — per-replica cosine
  against the pooled mean and the Adasum projection coefficient
  ``g_i·g_j/|g_i|²`` (arXiv 2006.02924's combiner quantity), from one
  tiny all-gather of scalar pairs under ``ddp/dynamics_geom``; (c)
  per-site **effective-LR** (``‖update‖/‖grad‖``) and
  update-to-weight trajectories, the numerics fold's companion
  mechanism extended to the update/grad pair. Off-steps take the empty
  ``lax.cond`` branch — no fold, no extra dispatch (the
  ``dynamics/no-extra-dispatch`` compile-check case pins the
  host-polling half bit-identical). The result is a
  :class:`DynamicsState` pytree carried next to GuardState /
  NumericsState / IntegrityState: checkpointable, donate-able,
  scan-carryable; surfaced through ``Amp.step(dynamics=(ds, dcfg))``
  composing with the ``guard=`` / ``numerics=`` hooks;
- **the verdict** (:func:`dynamics_report`): the host joins the EMAs
  into GNS / B_crit (McCandlish et al., "An Empirical Model of
  Large-Batch Training", arXiv 1812.06162 — provenance and the
  estimator algebra in docs/dynamics.md#gns), the cosine/projection
  spectrum, and median/MAD per-site effective-LR outliers, each row
  carrying an apexlint-style ``dynamics|kind|site`` fingerprint;
- **the comparator** (:mod:`apex_tpu.monitor.convergence`): the
  noise-calibrated A/B trajectory harness — the perf_sentinel
  robust statistics applied to convergence — lives next door.

Events ride the **13th** MetricsLogger channel
(``MetricsLogger(dynamics_sink=…)``; ``kind="dynamics_check" | "gns" |
"convergence_verdict"``; ``check_metrics_schema.py --kind dynamics``
validates). The asserted CI audit is ``scripts/dynamics_audit.py
--cpu8``. Cadence is the knob (docs/dynamics.md#cadence): the GNS
estimator is a ratio of *noisy* EMAs, so a coarser ``check_every``
trades estimator variance for fold cost, not correctness.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, \
    Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "DynamicsConfig", "DynamicsState", "DynamicsProbe",
    "DynamicsReport", "site_names", "dynamics_init",
    "dynamics_observe", "dynamics_report", "check_events",
    "stats_to_json", "stats_from_json",
]

#: sentinel for "no probe folded yet" in the world field
_NO_WORLD = -1.0
#: sentinel for spectrum rows with no data (cosine lives in [-1, 1])
_NO_COS = -2.0
#: sentinel for per-site gauges with no data yet
_NO_DATA = -1.0


# site_names is the SAME identity scheme as the numerics observatory —
# one naming convention across observatories, so a dynamics site and a
# numerics site over the same leaf share their suffix.
from apex_tpu.monitor.numerics import site_names  # noqa: E402


class DynamicsConfig(NamedTuple):
    """Static observatory configuration (hashable; safe to close over
    in jit)."""

    check_every: int = 1   #: fold cadence in steps; 1 = every step
    ema: float = 0.9       #: EMA decay (first check seeds the window)
    local_batch: int = 1   #: per-replica batch size b — the GNS
                           #: estimator's small-batch operand; the big
                           #: batch is ``world * local_batch``


class DynamicsProbe(NamedTuple):
    """The traced per-step scalars :func:`dynamics_observe` folds for
    GNS + geometry — produced inside the DDP sync scope by
    :func:`apex_tpu.parallel.distributed.dynamics_probe` (which owns
    the two registered collectives); every field is replicated across
    the axis after the probe."""

    local_sq_mean: jax.Array  # f32 mean over replicas of |g_local|²
    pooled_sq: jax.Array      # f32 |pooled mean gradient|²
    local_sqs: jax.Array      # f32[world] per-replica |g_local|²
    dots: jax.Array           # f32[world] per-replica g_i · g̅
    world: jax.Array          # f32 replica count (static axis size)


class DynamicsState(NamedTuple):
    """The in-graph training-dynamics monitor: scalar + ``[world]`` +
    ``[n_sites]`` device arrays carried through the jitted step next to
    GuardState / NumericsState — checkpointable, donate-able,
    ``lax.scan``-carryable. Site names are static strings and live with
    the host (:func:`site_names`); row ``i`` of the per-site arrays is
    site ``i`` in that tuple's order."""

    step: jax.Array            # i32 observed (attempted) steps
    check_count: jax.Array     # i32 cumulative folds executed
    last_check_step: jax.Array  # i32 step of the last executed fold
    world: jax.Array           # f32 replica count; -1 until a probe folds
    local_sq: jax.Array        # f32 last-check mean per-replica |g|²
    local_sq_ema: jax.Array    # f32 EMA of local_sq
    pooled_sq: jax.Array       # f32 last-check |pooled mean|²
    pooled_sq_ema: jax.Array   # f32 EMA of pooled_sq
    cos: jax.Array             # f32[W] last-check per-replica cosine
                               #   vs the pooled mean; -2 = no data
    proj: jax.Array            # f32[W] last-check Adasum projection
                               #   coefficient dot_i/|g_i|²; 0 = no data
    cos_min_ema: jax.Array     # f32 EMA of min-over-replicas cosine
    cos_mean_ema: jax.Array    # f32 EMA of mean-over-replicas cosine
    eff_lr: jax.Array          # f32[S] last-check ‖update‖/‖grad‖;
                               #   -1 = site has no grad companion
    eff_lr_ema: jax.Array      # f32[S] EMA of eff_lr
    uw_ratio: jax.Array        # f32[S] EMA ‖update‖/‖weight‖;
                               #   -1 = site has no weight companion


def dynamics_init(cfg: DynamicsConfig = DynamicsConfig(), *,
                  sites: Sequence[str],
                  world: int = 1) -> DynamicsState:
    """Fresh dynamics state for a static site tuple (from
    :func:`site_names`) and a static replica count ``world`` (the dp
    axis size — sizes the geometry spectrum rows; 1 is fine for
    single-replica runs, which simply never fold a probe). Thread
    through the step like GuardState."""
    if int(cfg.check_every) < 1:
        raise ValueError(f"DynamicsConfig.check_every must be >= 1, "
                         f"got {cfg.check_every}")
    if not 0.0 < float(cfg.ema) < 1.0:
        raise ValueError(f"DynamicsConfig.ema must be in (0, 1), "
                         f"got {cfg.ema}")
    if int(cfg.local_batch) < 1:
        raise ValueError(f"DynamicsConfig.local_batch must be >= 1, "
                         f"got {cfg.local_batch}")
    s = len(tuple(sites))
    if s < 1:
        raise ValueError("dynamics_init needs at least one site")
    w = int(world)
    if w < 1:
        raise ValueError(f"dynamics_init world must be >= 1, got {world}")
    z = jnp.int32(0)
    f0 = jnp.float32(0)
    return DynamicsState(
        step=z, check_count=z, last_check_step=jnp.int32(-1),
        world=jnp.float32(_NO_WORLD),
        local_sq=f0, local_sq_ema=f0,
        pooled_sq=f0, pooled_sq_ema=f0,
        cos=jnp.full((w,), _NO_COS, jnp.float32),
        proj=jnp.zeros((w,), jnp.float32),
        cos_min_ema=jnp.float32(_NO_COS),
        cos_mean_ema=jnp.float32(_NO_COS),
        eff_lr=jnp.full((s,), _NO_DATA, jnp.float32),
        eff_lr_ema=jnp.full((s,), _NO_DATA, jnp.float32),
        uw_ratio=jnp.full((s,), _NO_DATA, jnp.float32))


def _norm(tree) -> jax.Array:
    """fp32 L2 norm of a single leaf."""
    return jnp.sqrt(jnp.sum(jnp.square(
        jnp.asarray(tree).astype(jnp.float32))))


def dynamics_observe(ds: DynamicsState, cfg: DynamicsConfig,
                     trees, *,
                     probe=None,
                     grads: Optional[Dict[str, Any]] = None,
                     weights: Optional[Dict[str, Any]] = None
                     ) -> DynamicsState:
    """Observe one step: fold GNS/geometry/effective-LR statistics
    every ``cfg.check_every`` steps, advance counters.

    ``trees`` carries the SAME (prefix → pytree) structure the state's
    sites were built from (:func:`site_names` — sorted prefixes,
    flatten order): the per-site *update* tensors. Like the numerics
    fold it may be a zero-arg callable returning that dict, in which
    case derived tensors (the update delta) are built inside the fold's
    ``lax.cond`` branch and cost nothing on off-steps (the
    :meth:`Amp.step <apex_tpu.amp.Amp.step>` hook uses this).

    ``grads`` optionally maps a prefix to the matching *gradient*
    pytree; those sites fold the effective learning rate
    ``‖update‖₂ / ‖grad‖₂`` (per-coordinate step size the optimizer
    actually took — the Adam-style gauge a raw LR cannot show).
    ``weights`` maps a prefix to the weight pytree for the
    update-to-weight ratio, exactly the numerics companion mechanism.

    ``probe`` is a :class:`DynamicsProbe` (or a zero-arg callable
    returning one — the collectives then trace inside the cond branch,
    which is safe because the cadence predicate is replicated) from
    :func:`apex_tpu.parallel.distributed.dynamics_probe`. ``None``
    (single-replica runs) leaves the GNS/geometry fields at their
    sentinels.

    Off-steps take the empty ``lax.cond`` branch: no fold, no extra
    work (``check_every=1`` skips the cond entirely). Observation is
    read-only — the trajectory with it enabled is bit-identical to the
    trajectory without (the O0–O3 parity sweep in
    tests/test_dynamics.py asserts it per opt level).
    """
    grads = grads or {}
    weights = weights or {}
    s_total = int(ds.eff_lr.shape[0])
    w_total = int(ds.cos.shape[0])

    def _fold(st: DynamicsState) -> DynamicsState:
        tr = trees() if callable(trees) else trees
        for name, companion in (("grads", grads), ("weights", weights)):
            for k in companion:
                if k not in tr:
                    raise ValueError(f"{name} prefix {k!r} has no "
                                     f"matching tree in trees="
                                     f"{sorted(tr)}")
        effs: List[jax.Array] = []
        uws: List[jax.Array] = []
        for prefix in sorted(tr):
            leaves = jax.tree_util.tree_leaves(tr[prefix])
            gleaves = (jax.tree_util.tree_leaves(grads[prefix])
                       if prefix in grads else [None] * len(leaves))
            wleaves = (jax.tree_util.tree_leaves(weights[prefix])
                       if prefix in weights else [None] * len(leaves))
            if len(gleaves) != len(leaves) or len(wleaves) != len(leaves):
                raise ValueError(
                    f"companion trees for {prefix!r} have "
                    f"{len(gleaves)}/{len(wleaves)} leaves, "
                    f"trees[{prefix!r}] has {len(leaves)}")
            for leaf, g, w in zip(leaves, gleaves, wleaves):
                un = _norm(leaf)
                if g is None:
                    effs.append(jnp.float32(_NO_DATA))
                else:
                    effs.append(un / jnp.maximum(_norm(g), 1e-30))
                if w is None:
                    uws.append(jnp.float32(_NO_DATA))
                else:
                    uws.append(un / jnp.maximum(_norm(w), 1e-30))
        if len(effs) != s_total:
            raise ValueError(
                f"dynamics_observe saw {len(effs)} sites, state has "
                f"{s_total} — trees must match dynamics_init's sites")
        eff = jnp.stack(effs)
        uw = jnp.stack(uws)
        d = jnp.float32(cfg.ema)
        first = st.check_count == 0
        ema = lambda prev, cur: jnp.where(  # noqa: E731 — N-use local
            first, cur, d * prev + (1 - d) * cur)
        # a -1 slot means "no companion": it never mixes into the EMA
        had_eff = st.eff_lr_ema >= 0
        new_eff_ema = jnp.where(
            eff < 0, st.eff_lr_ema,
            jnp.where(had_eff, d * st.eff_lr_ema + (1 - d) * eff, eff))
        had_uw = st.uw_ratio >= 0
        new_uw = jnp.where(
            uw < 0, st.uw_ratio,
            jnp.where(had_uw, d * st.uw_ratio + (1 - d) * uw, uw))
        st = st._replace(
            eff_lr=eff, eff_lr_ema=new_eff_ema, uw_ratio=new_uw,
            check_count=st.check_count + 1, last_check_step=st.step)
        pr = probe() if callable(probe) else probe
        if pr is None:
            return st
        if int(pr.local_sqs.shape[0]) != w_total:
            raise ValueError(
                f"dynamics_observe probe has world="
                f"{pr.local_sqs.shape[0]}, state was initialized with "
                f"world={w_total} — pass the dp axis size to "
                f"dynamics_init")
        # the probe EMAs seed on the first PROBE fold, which may come
        # later than the first site fold (world < 0 marks "never")
        pfirst = st.world < 0
        pema = lambda prev, cur: jnp.where(  # noqa: E731 — 4-use local
            pfirst, cur, d * prev + (1 - d) * cur)
        lsq = pr.local_sq_mean.astype(jnp.float32)
        psq = pr.pooled_sq.astype(jnp.float32)
        # geometry: cos_i = dot_i / (|g_i| |g̅|); proj_i = dot_i/|g_i|²
        # (the Adasum combiner coefficient, arXiv 2006.02924 eq. 2)
        lsqs = pr.local_sqs.astype(jnp.float32)
        dots = pr.dots.astype(jnp.float32)
        denom = jnp.sqrt(jnp.maximum(lsqs * psq, 1e-30))
        cos = dots / denom
        proj = dots / jnp.maximum(lsqs, 1e-30)
        return st._replace(
            world=pr.world.astype(jnp.float32),
            local_sq=lsq, local_sq_ema=pema(st.local_sq_ema, lsq),
            pooled_sq=psq, pooled_sq_ema=pema(st.pooled_sq_ema, psq),
            cos=cos, proj=proj,
            cos_min_ema=pema(st.cos_min_ema, jnp.min(cos)),
            cos_mean_ema=pema(st.cos_mean_ema, jnp.mean(cos)))

    if int(cfg.check_every) <= 1:
        new = _fold(ds)
    else:
        new = lax.cond((ds.step % cfg.check_every) == 0, _fold,
                       lambda st: st, ds)
    return new._replace(step=ds.step + 1)


# -- the host half: GNS estimator + report ------------------------------------

def _gns_estimate(local_sq: float, pooled_sq: float, world: float,
                  local_batch: int) -> Dict[str, Optional[float]]:
    """The unbiased small/big-batch pair estimator (McCandlish et al.,
    arXiv 1812.06162 appendix A): with per-replica batch ``b`` and big
    batch ``B = world·b``,

      ``|G|²̂  = (B·|G_B|² − b·|G_b|²) / (B − b)``   (true grad norm²)
      ``S`̂    = (|G_b|² − |G_B|²) / (1/b − 1/B)``   (per-example noise)
      ``B_simple = S`̂ / |G|²̂  ≈ B_crit``

    where ``|G_b|²`` is the mean per-replica squared norm and
    ``|G_B|²`` the pooled mean's squared norm. Returns None fields when
    the estimate is undefined (world ≤ 1, no probe, or a noise-free
    trajectory driving the estimator non-positive)."""
    out: Dict[str, Optional[float]] = {
        "g2_est": None, "s_est": None, "gns": None, "b_crit": None}
    if world is None or world <= 1 or local_batch < 1:
        return out
    b = float(local_batch)
    B = float(world) * b
    g2 = (B * pooled_sq - b * local_sq) / (B - b)
    s = (local_sq - pooled_sq) / (1.0 / b - 1.0 / B)
    out["g2_est"] = g2
    out["s_est"] = s
    if g2 > 0 and s > 0:
        gns = s / g2
        out["gns"] = gns
        out["b_crit"] = gns  # B_simple ≈ B_crit (1812.06162 §2.2)
    return out


@dataclasses.dataclass
class DynamicsReport:
    """One observed run's training-dynamics verdict: the GNS estimate,
    the replica-geometry spectrum, and the per-site effective-LR rows
    with robust outlier flags."""

    step: int
    check_count: int
    world: Optional[float]          # None until a probe folded
    local_batch: int
    gns: Optional[float]            # B_simple; None when undefined
    b_crit: Optional[float]         # critical-batch-size estimate
    g2_est: Optional[float]
    s_est: Optional[float]
    cos_spectrum: List[float]       # per-replica cosine vs pooled mean
    proj_spectrum: List[float]      # per-replica Adasum projection
    cos_min: Optional[float]
    cos_mean: Optional[float]
    cos_min_ema: Optional[float]
    sites: List[str]
    eff_lr: List[Optional[float]]   # EMA rows; None = no companion
    uw_ratio: List[Optional[float]]
    outlier_z: float
    #: sites whose effective-LR EMA sits > outlier_z robust sigmas from
    #: the median (perf_sentinel's med/MAD statistics) — a layer whose
    #: optimizer step size ran away from the pack
    eff_lr_outliers: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    @property
    def fingerprint(self) -> str:
        """Stable ``dynamics|gns|global`` key — the waiver/pin
        identity, apexlint-fingerprint style (never includes measured
        numbers)."""
        return "dynamics|gns|global"

    def table(self, top: int = 8) -> str:
        lines = [f"dynamics — step {self.step}, "
                 f"{self.check_count} checks, world="
                 f"{self.world if self.world is not None else '?'}",
                 f"  gns(B_simple)={_fmt(self.gns)} "
                 f"b_crit={_fmt(self.b_crit)} "
                 f"cos_min={_fmt(self.cos_min)} "
                 f"cos_mean={_fmt(self.cos_mean)}",
                 f"{'site':<44} {'eff_lr':>10} {'uw':>10}"]
        rows = sorted(range(len(self.sites)),
                      key=lambda i: -(self.eff_lr[i] or 0.0))
        for i in rows[:top]:
            lines.append(f"{self.sites[i][:44]:<44} "
                         f"{_fmt(self.eff_lr[i]):>10} "
                         f"{_fmt(self.uw_ratio[i]):>10}")
        for o in self.eff_lr_outliers:
            lines.append(f"  OUTLIER {o['site']}: eff_lr="
                         f"{_fmt(o['eff_lr'])} ({o['sigmas']:.1f}σ)")
        return "\n".join(lines)

    def to_events(self, rank: int = 0) -> List[Dict]:
        """The ``kind="gns"`` event row (``check_metrics_schema.py
        --kind dynamics`` validates) — the per-site emission is
        :func:`check_events`."""
        return [{
            "kind": "gns", "rank": rank, "step": self.step,
            "check_count": self.check_count,
            "gns": _finite_or_none(self.gns),
            "b_crit": _finite_or_none(self.b_crit),
            "local_sq": None, "pooled_sq": None,
            "world": self.world, "local_batch": self.local_batch,
            "cos_min": self.cos_min, "cos_mean": self.cos_mean,
            "fingerprint": self.fingerprint,
        }]


def _fmt(v) -> str:
    return "-" if v is None else f"{v:.4g}"


def _finite_or_none(v):
    return v if v is not None and math.isfinite(v) else None


def _fetch_stats(ds: DynamicsState, sites: Sequence[str]) -> Dict:
    import numpy as np
    host = jax.device_get(ds)
    if len(sites) != host.eff_lr.shape[0]:
        raise ValueError(f"{len(sites)} sites for a state with "
                         f"{host.eff_lr.shape[0]} rows")
    return {"sites": tuple(sites),
            "step": int(host.step), "check_count": int(host.check_count),
            "last_check_step": int(host.last_check_step),
            "world": float(host.world),
            "local_sq": float(host.local_sq),
            "local_sq_ema": float(host.local_sq_ema),
            "pooled_sq": float(host.pooled_sq),
            "pooled_sq_ema": float(host.pooled_sq_ema),
            "cos": np.asarray(host.cos),
            "proj": np.asarray(host.proj),
            "cos_min_ema": float(host.cos_min_ema),
            "cos_mean_ema": float(host.cos_mean_ema),
            "eff_lr": np.asarray(host.eff_lr),
            "eff_lr_ema": np.asarray(host.eff_lr_ema),
            "uw_ratio": np.asarray(host.uw_ratio)}


def dynamics_report(ds_or_stats, sites: Optional[Sequence[str]] = None,
                    *, local_batch: Optional[int] = None,
                    outlier_z: float = 3.5) -> DynamicsReport:
    """Join the folded statistics into the host-side verdict.

    ``ds_or_stats`` is a :class:`DynamicsState` (with ``sites`` — ONE
    host fetch, amortized like a metrics flush) or a stats dict from
    :func:`stats_to_json` (the committed-fixture path). ``local_batch``
    overrides the per-replica batch the GNS algebra uses (defaults to
    the value recorded in the stats, or 1). ``outlier_z`` is the
    robust-sigma threshold for effective-LR outlier rows (med/MAD, the
    perf_sentinel statistics)."""
    import numpy as np
    if isinstance(ds_or_stats, DynamicsState):
        if sites is None:
            raise ValueError("dynamics_report(DynamicsState) needs "
                             "the matching sites tuple")
        stats = _fetch_stats(ds_or_stats, sites)
    else:
        stats = dict(ds_or_stats)
        sites = tuple(stats["sites"])
    b = int(local_batch if local_batch is not None
            else stats.get("local_batch", 1))
    world = stats["world"]
    probed = world is not None and world > 0
    est = _gns_estimate(stats["local_sq_ema"], stats["pooled_sq_ema"],
                        world if probed else None, b)
    cos = np.asarray(stats["cos"], dtype=np.float64)
    proj = np.asarray(stats["proj"], dtype=np.float64)
    if probed:
        w = int(world)
        cos_spec = [float(v) for v in cos[:w]]
        proj_spec = [float(v) for v in proj[:w]]
        cos_min = float(np.min(cos[:w]))
        cos_mean = float(np.mean(cos[:w]))
        cme = float(stats["cos_min_ema"])
    else:
        cos_spec, proj_spec = [], []
        cos_min = cos_mean = cme = None
    eff = np.asarray(stats["eff_lr_ema"], dtype=np.float64)
    uw = np.asarray(stats["uw_ratio"], dtype=np.float64)
    eff_rows = [None if v < 0 else float(v) for v in eff]
    uw_rows = [None if v < 0 else float(v) for v in uw]
    outliers: List[Dict[str, Any]] = []
    have = np.asarray([v for v in eff_rows if v is not None])
    if have.size >= 3:
        med = float(np.median(have))
        mad = float(np.median(np.abs(have - med)))
        sigma = 1.4826 * mad
        if sigma > 0:
            for i, v in enumerate(eff_rows):
                if v is None:
                    continue
                z = abs(v - med) / sigma
                if z > outlier_z:
                    outliers.append({
                        "site": sites[i], "eff_lr": v,
                        "sigmas": round(z, 2),
                        "fingerprint":
                            f"dynamics|eff_lr|{sites[i]}"})
    return DynamicsReport(
        step=stats["step"], check_count=stats["check_count"],
        world=(float(world) if probed else None), local_batch=b,
        gns=est["gns"], b_crit=est["b_crit"],
        g2_est=est["g2_est"], s_est=est["s_est"],
        cos_spectrum=cos_spec, proj_spectrum=proj_spec,
        cos_min=cos_min, cos_mean=cos_mean, cos_min_ema=cme,
        sites=list(sites), eff_lr=eff_rows, uw_ratio=uw_rows,
        outlier_z=outlier_z, eff_lr_outliers=outliers)


# -- events (the dynamics channel) --------------------------------------------

def check_events(ds: DynamicsState, sites: Sequence[str], *,
                 rank: int = 0,
                 local_batch: int = 1) -> List[Dict]:
    """One ``kind="dynamics_check"`` aggregate row (``site`` null) plus
    one per-site row, plus the ``kind="gns"`` estimator row — the
    host-poll emission (wire through
    ``MetricsLogger(dynamics_sink=…)``; ``--kind dynamics``
    validates). Fetches the state ONCE."""
    stats = _fetch_stats(ds, sites)
    rep = dynamics_report(stats, local_batch=local_batch)
    eff = stats["eff_lr_ema"]
    uw = stats["uw_ratio"]
    events: List[Dict] = [{
        "kind": "dynamics_check", "rank": rank, "step": stats["step"],
        "check_count": stats["check_count"], "site": None,
        "n_sites": len(sites),
        "eff_lr": max((v for v in rep.eff_lr if v is not None),
                      default=None),
        "uw_ratio": max((v for v in rep.uw_ratio if v is not None),
                        default=None),
        "cos_min": rep.cos_min, "cos_mean": rep.cos_mean,
        "world": rep.world,
    }]
    for i, site in enumerate(sites):
        events.append({
            "kind": "dynamics_check", "rank": rank,
            "step": stats["step"],
            "check_count": stats["check_count"], "site": site,
            "n_sites": len(sites),
            "eff_lr": None if eff[i] < 0 else float(eff[i]),
            "uw_ratio": None if uw[i] < 0 else float(uw[i]),
            "cos_min": None, "cos_mean": None, "world": None,
        })
    gns_row = rep.to_events(rank=rank)[0]
    gns_row["local_sq"] = _finite_or_none(stats["local_sq_ema"])
    gns_row["pooled_sq"] = _finite_or_none(stats["pooled_sq_ema"])
    events.append(gns_row)
    return events


# -- fixture round-trip --------------------------------------------------------

def stats_to_json(ds: DynamicsState, sites: Sequence[str], *,
                  local_batch: int = 1) -> str:
    """Serialize one fetched measurement (the committed-fixture
    format: CI pins :func:`dynamics_report` verdicts on a committed
    measurement with no device in sight)."""
    st = _fetch_stats(ds, sites)
    return json.dumps({
        "version": 1, "sites": list(st["sites"]),
        "step": st["step"], "check_count": st["check_count"],
        "last_check_step": st["last_check_step"],
        "world": st["world"], "local_batch": int(local_batch),
        "local_sq": st["local_sq"], "local_sq_ema": st["local_sq_ema"],
        "pooled_sq": st["pooled_sq"],
        "pooled_sq_ema": st["pooled_sq_ema"],
        "cos": [float(v) for v in st["cos"]],
        "proj": [float(v) for v in st["proj"]],
        "cos_min_ema": st["cos_min_ema"],
        "cos_mean_ema": st["cos_mean_ema"],
        "eff_lr": [float(v) for v in st["eff_lr"]],
        "eff_lr_ema": [float(v) for v in st["eff_lr_ema"]],
        "uw_ratio": [float(v) for v in st["uw_ratio"]],
    }, indent=1)


def stats_from_json(text: str) -> Dict:
    """Inverse of :func:`stats_to_json` — feed the result straight to
    :func:`dynamics_report`."""
    import numpy as np
    data = json.loads(text)
    out = dict(data)
    for k in ("cos", "proj", "eff_lr", "eff_lr_ema", "uw_ratio"):
        out[k] = np.asarray(data[k], dtype=np.float64)
    return out
