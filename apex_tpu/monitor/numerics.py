"""Per-tensor dynamic-range telemetry: the numerics observatory.

ROADMAP item 5 (fp8 end-to-end) has guard rails — the anomaly guard
catches a diverging trajectory, the sentinel a perf regression — but no
*measurement* layer: nothing can say which tensors' measured exponent
ranges actually fit e4m3/e5m2, so any fp8 rollout would be flying
blind. This module is the measurement half, the PR-10 pattern (land the
observatory, then spend it) applied to numerics:

- **the fold** (:func:`numerics_observe`): every ``check_every`` steps
  the jitted step folds, per tracked *site* (a stable apexlint-style
  string like ``"amp/grads/['encoder']['w']"``), pure-``jnp`` bit-trick
  statistics: amax/amin EMA windows, a bucketed **biased-exponent
  histogram** (the f32 bit pattern's exponent field, ``bits >> 23 &
  0xFF`` — no host ops, no data-dependent shapes), zero / nonfinite
  fractions, and update-to-weight ratios for optimizer-update sites.
  Off-steps take the empty ``lax.cond`` branch — no fold, no extra
  dispatch (the ``numerics/no-extra-dispatch`` compile-check case pins
  the host-polling half bit-identical). The result is a
  :class:`NumericsState` pytree carried next to GuardState /
  IntegrityState: checkpointable, donate-able, scan-carryable;
- **the format table** (:data:`FORMAT_TABLE`): exponent range +
  mantissa bits for fp32 / bf16 / fp16 / fp8-e4m3 / fp8-e5m2 (OCP
  variants; provenance in docs/numerics.md). Because the histogram is
  kept in *exponent space*, the host can price ANY target format — and
  any power-of-two scale, which is just an index shift — against the
  measured distribution without re-observing;
- **the verdict** (:func:`precision_report`): the host joins measured
  exponent coverage against the table into a machine-readable per-site
  verdict list ({required_dtype, predicted underflow/saturation
  fractions, recommended_scale}) — the fp8 candidate generator, shaped
  like the roofline observatory's ``worst_gaps(k)``;
- **the advisor** (:func:`placement_advisor`): joins the verdicts with
  a :class:`~apex_tpu.prof.RooflineReport`'s new what-if dtype column
  (``RooflineReport.what_if``) so candidate sites rank by *measured
  perf headroom × numeric safety*, not by either alone.

The per-tensor delayed-scaling state machine the verdicts' scales feed
(amax window → next-step scale, the loss scaler's growth/backoff
semantics generalized per site) is :mod:`apex_tpu.amp.scale_history`.

Cadence is the knob (docs/numerics.md#cadence): ``check_every=1``
observes every step — the histogram then covers the whole trajectory —
at the cost of one fold per tracked tensor per step; a coarser cadence
amortizes the fold but can miss a transient between checks (the scale
machinery's backoff still catches a nonfinite amax at the next check).

Events ride the **10th** MetricsLogger channel
(``MetricsLogger(numerics_sink=…)``; ``kind="numerics_check" |
"scale_update" | "precision_verdict"``; ``check_metrics_schema.py
--kind numerics`` validates). The asserted CI audit is
``scripts/numerics_audit.py --cpu8``. The guard's nonfinite probes
(:mod:`apex_tpu.guard.detect`) say *that* something went nonfinite and
veto the commit; :func:`nonfinite_sites` names *where* — the forensic
cross-link docs/resilience.md describes.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "FormatSpec", "FORMAT_TABLE", "FORMAT_LADDER", "HIST_BINS",
    "NumericsConfig", "NumericsState", "SiteVerdict", "NumericsReport",
    "site_names", "numerics_init", "numerics_observe", "finite_ok",
    "scale_amax", "nonfinite_sites", "precision_report",
    "placement_advisor",
    "check_events", "stats_to_json", "stats_from_json",
]

#: biased-exponent histogram resolution: one bucket per f32 exponent
#: value. Bucket 0 = exact zeros are EXCLUDED (tracked as zero_frac);
#: nonzero subnormals land in bucket 0; bucket 255 (inf/nan) is
#: excluded too (tracked as nonfinite_frac) — the histogram is the
#: distribution of *finite nonzero* magnitudes.
HIST_BINS = 256

#: f32 exponent bias: bucket b holds magnitudes in [2^(b-127), 2^(b-126))
_BIAS = 127


class FormatSpec(NamedTuple):
    """One target floating format's range, as the verdict machinery
    prices it: ``min_exp``/``max_exp`` are the unbiased exponents of the
    smallest normal and the largest finite binade; ``max_finite`` the
    largest representable magnitude. Mantissa bits are carried for the
    docs/advisor (rounding error ~2^-(m+1)); the range verdict itself is
    exponent-space only."""

    name: str
    mantissa_bits: int
    min_exp: int          # smallest normal binade: 2^min_exp
    max_exp: int          # largest finite binade: max_finite in [2^max_exp, 2^(max_exp+1))
    max_finite: float


#: the dtype ladder, narrow → wide. e4m3 is the OCP "FN" variant (no
#: inf, max 448); e5m2 is IEEE-like (max 57344); provenance and the
#: half-bucket saturation approximation are documented in
#: docs/numerics.md#formats.
FORMAT_TABLE: Dict[str, FormatSpec] = {
    "fp8_e4m3": FormatSpec("fp8_e4m3", 3, -6, 8, 448.0),
    "fp8_e5m2": FormatSpec("fp8_e5m2", 2, -14, 15, 57344.0),
    "fp16": FormatSpec("fp16", 10, -14, 15, 65504.0),
    "bf16": FormatSpec("bf16", 7, -126, 127, 3.3895314e38),
    "fp32": FormatSpec("fp32", 23, -126, 127, 3.4028235e38),
}

#: verdict search order (narrowest safe format wins)
FORMAT_LADDER: Tuple[str, ...] = ("fp8_e4m3", "fp8_e5m2", "fp16",
                                  "bf16", "fp32")

#: jnp dtype name → FORMAT_TABLE key (the ``current_dtype`` join)
_DTYPE_TO_FORMAT = {
    "float32": "fp32", "f32": "fp32", "fp32": "fp32",
    "bfloat16": "bf16", "bf16": "bf16",
    "float16": "fp16", "f16": "fp16", "fp16": "fp16",
    "float8_e4m3fn": "fp8_e4m3", "f8e4m3fn": "fp8_e4m3",
    "f8e4m3": "fp8_e4m3", "fp8_e4m3": "fp8_e4m3",
    "float8_e5m2": "fp8_e5m2", "f8e5m2": "fp8_e5m2",
    "fp8_e5m2": "fp8_e5m2",
}


def format_of_dtype(dtype) -> Optional[str]:
    """FORMAT_TABLE key for a jnp/HLO dtype name, or None when the
    dtype has no entry (ints, f64, …)."""
    return _DTYPE_TO_FORMAT.get(str(jnp.dtype(dtype).name)
                                if not isinstance(dtype, str) else dtype)


class NumericsConfig(NamedTuple):
    """Static observatory configuration (hashable; safe to close over
    in jit)."""

    check_every: int = 1   #: fold cadence in steps; 1 = every step
    ema: float = 0.9       #: EMA decay for the windows (first check
                           #: seeds the window — no zero-bias warmup)


class NumericsState(NamedTuple):
    """The in-graph numeric-health monitor: ``[n_sites]``-shaped device
    arrays carried through the jitted step next to GuardState —
    checkpointable, donate-able, ``lax.scan``-carryable. Site *names*
    are static strings and live with the host (:func:`site_names`);
    row ``i`` of every array is site ``i`` in that tuple's order.
    """

    step: jax.Array           # i32 observed (attempted) steps
    check_count: jax.Array    # i32 cumulative folds executed
    amax: jax.Array           # f32[S] last-check max |x| (finite)
    amax_ema: jax.Array       # f32[S] EMA of amax
    amin: jax.Array           # f32[S] last-check min nonzero |x|
    amin_ema: jax.Array       # f32[S] EMA of amin
    exp_hist: jax.Array       # f32[S, HIST_BINS] EMA'd normalized
                              #   biased-exponent histogram (finite
                              #   nonzero elements only)
    zero_frac: jax.Array      # f32[S] last-check exact-zero fraction
    nonfinite_frac: jax.Array  # f32[S] last-check inf/nan fraction
    uw_ratio: jax.Array       # f32[S] EMA update/weight norm ratio;
                              #   -1.0 = site has no weight companion
    last_check_step: jax.Array  # i32 step of the last executed fold


def site_names(trees: Dict[str, Any]) -> Tuple[str, ...]:
    """The stable site tuple for a dict of (prefix → pytree): one site
    per leaf, named ``"{prefix}/{keystr}"`` — the apexlint-style
    fingerprint identity the state's rows, the events and the verdicts
    all key on. Prefixes iterate sorted, leaves in ``tree_flatten``
    order, so the mapping is reproducible across processes and runs.
    Use the SAME dict structure in :func:`numerics_observe`."""
    names: List[str] = []
    for prefix in sorted(trees):
        leaves = jax.tree_util.tree_leaves_with_path(trees[prefix])
        for path, _leaf in leaves:
            names.append(f"{prefix}/{jax.tree_util.keystr(path)}"
                         if path else prefix)
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate numerics sites: {names}")
    return tuple(names)


def numerics_init(cfg: NumericsConfig = NumericsConfig(), *,
                  sites: Sequence[str]) -> NumericsState:
    """Fresh numerics state for a static site tuple (from
    :func:`site_names`) — thread through the step like GuardState."""
    if int(cfg.check_every) < 1:
        raise ValueError(f"NumericsConfig.check_every must be >= 1, "
                         f"got {cfg.check_every}")
    if not 0.0 < float(cfg.ema) < 1.0:
        raise ValueError(f"NumericsConfig.ema must be in (0, 1), "
                         f"got {cfg.ema}")
    s = len(tuple(sites))
    if s < 1:
        raise ValueError("numerics_init needs at least one site")
    z = jnp.int32(0)
    zs = jnp.zeros((s,), jnp.float32)
    return NumericsState(
        step=z, check_count=z,
        amax=zs, amax_ema=zs, amin=zs, amin_ema=zs,
        exp_hist=jnp.zeros((s, HIST_BINS), jnp.float32),
        zero_frac=zs, nonfinite_frac=zs,
        uw_ratio=jnp.full((s,), -1.0, jnp.float32),
        last_check_step=jnp.int32(-1))


def _leaf_stats(x: jax.Array):
    """One leaf's (amax, amin_nonzero, normalized exponent histogram,
    zero_frac, nonfinite_frac) — pure-jnp bit tricks: the f32 bit
    pattern's exponent field buckets every finite nonzero element, a
    scatter-add builds the histogram, no host ops and no
    data-dependent shapes."""
    xf = jnp.asarray(x).astype(jnp.float32).reshape(-1)
    n = xf.size
    if n == 0:
        return (jnp.float32(0), jnp.float32(0),
                jnp.zeros((HIST_BINS,), jnp.float32),
                jnp.float32(0), jnp.float32(0))
    ax = jnp.abs(xf)
    finite = jnp.isfinite(xf)
    nz = jnp.logical_and(finite, ax > 0)
    amax = jnp.max(jnp.where(finite, ax, 0.0))
    amin = jnp.min(jnp.where(nz, ax, jnp.inf))
    amin = jnp.where(jnp.isfinite(amin), amin, 0.0)  # all-zero leaf
    bits = lax.bitcast_convert_type(xf, jnp.uint32)
    be = ((bits >> 23) & jnp.uint32(0xFF)).astype(jnp.int32)
    hist = jnp.zeros((HIST_BINS,), jnp.float32).at[be].add(
        jnp.where(nz, 1.0, 0.0))
    nz_count = jnp.sum(nz.astype(jnp.float32))
    hist = hist / jnp.maximum(nz_count, 1.0)
    inv_n = jnp.float32(1.0 / n)
    zero_frac = jnp.sum(jnp.logical_and(
        finite, ax == 0).astype(jnp.float32)) * inv_n
    nonfinite_frac = jnp.sum(
        jnp.logical_not(finite).astype(jnp.float32)) * inv_n
    return amax, amin, hist, zero_frac, nonfinite_frac


def numerics_observe(ns: NumericsState, cfg: NumericsConfig,
                     trees, *,
                     weights: Optional[Dict[str, Any]] = None
                     ) -> NumericsState:
    """Observe one step: fold per-site statistics every
    ``cfg.check_every`` steps, advance counters. ``trees`` must carry
    the SAME (prefix → pytree) structure the state's sites were built
    from (:func:`site_names` — sorted prefixes, flatten order) — or be
    a zero-arg callable *returning* that dict, in which case the
    tensors are built inside the fold's ``lax.cond`` branch and
    derived observation inputs (a cast copy, an update delta) cost
    nothing on off-steps (the :meth:`Amp.step <apex_tpu.amp.Amp.step>`
    hook uses this). ``weights`` optionally maps a prefix whose
    tensors are optimizer *updates* to the matching weight pytree;
    those sites additionally fold the update-to-weight norm ratio
    (``‖update‖₂ / ‖weight‖₂`` — the classic silent-stall /
    blown-update gauge).

    Off-steps take the empty ``lax.cond`` branch: no fold, no extra
    work (``check_every=1`` skips the cond entirely). Observation is
    read-only — the trajectory with it enabled is bit-identical to the
    trajectory without (the parity sweep in tests/test_numerics.py
    asserts it per opt level).
    """
    weights = weights or {}
    s_total = int(ns.amax.shape[0])

    def _fold(st: NumericsState) -> NumericsState:
        tr = trees() if callable(trees) else trees
        for k in weights:
            if k not in tr:
                raise ValueError(f"weights prefix {k!r} has no "
                                 f"matching tree in trees="
                                 f"{sorted(tr)}")
        amaxs, amins, hists, zeros, nonfin = [], [], [], [], []
        uws: List[jax.Array] = []
        for prefix in sorted(tr):
            leaves = jax.tree_util.tree_leaves(tr[prefix])
            wleaves = (jax.tree_util.tree_leaves(weights[prefix])
                       if prefix in weights else [None] * len(leaves))
            if len(wleaves) != len(leaves):
                raise ValueError(
                    f"weights[{prefix!r}] has {len(wleaves)} leaves, "
                    f"trees[{prefix!r}] has {len(leaves)}")
            for leaf, w in zip(leaves, wleaves):
                amax, amin, hist, zf, nf = _leaf_stats(leaf)
                amaxs.append(amax)
                amins.append(amin)
                hists.append(hist)
                zeros.append(zf)
                nonfin.append(nf)
                if w is None:
                    uws.append(jnp.float32(-1.0))
                else:
                    un = jnp.sqrt(jnp.sum(jnp.square(
                        jnp.asarray(leaf).astype(jnp.float32))))
                    wn = jnp.sqrt(jnp.sum(jnp.square(
                        jnp.asarray(w).astype(jnp.float32))))
                    uws.append(un / jnp.maximum(wn, 1e-30))
        if len(amaxs) != s_total:
            raise ValueError(
                f"numerics_observe saw {len(amaxs)} sites, state has "
                f"{s_total} — trees must match numerics_init's sites")
        amax = jnp.stack(amaxs)
        amin = jnp.stack(amins)
        hist = jnp.stack(hists)
        uw = jnp.stack(uws)
        d = jnp.float32(cfg.ema)
        first = st.check_count == 0
        ema = lambda prev, cur: jnp.where(  # noqa: E731 — 3-use local
            first, cur, d * prev + (1 - d) * cur)
        # a -1 slot means "no weight companion": it never mixes
        had_uw = st.uw_ratio >= 0
        new_uw = jnp.where(
            uw < 0, st.uw_ratio,
            jnp.where(had_uw, d * st.uw_ratio + (1 - d) * uw, uw))
        return st._replace(
            amax=amax, amax_ema=ema(st.amax_ema, amax),
            amin=amin, amin_ema=ema(st.amin_ema, amin),
            exp_hist=ema(st.exp_hist, hist),
            zero_frac=jnp.stack(zeros),
            nonfinite_frac=jnp.stack(nonfin),
            uw_ratio=new_uw,
            check_count=st.check_count + 1,
            last_check_step=st.step)

    if int(cfg.check_every) <= 1:
        new = _fold(ns)
    else:
        new = lax.cond((ns.step % cfg.check_every) == 0, _fold,
                       lambda st: st, ns)
    return new._replace(step=ns.step + 1)


def scale_amax(ns: NumericsState, rows=None) -> jax.Array:
    """The amax feed for :func:`apex_tpu.amp.scale_history_update`:
    per-site last-check amax with **inf substituted wherever the fold
    saw nonfinite elements**. ``NumericsState.amax`` itself is the max
    of the *finite* magnitudes by design (the EMAs, histograms and
    verdicts must stay usable through an overflow episode), which
    means it alone can never carry the overflow signal the scale
    machinery's backoff keys on — feeding ``ns.amax`` directly would
    let a poisoned step's finite remainder GROW the scale
    mid-overflow. Always wire delayed scaling through this helper::

        sh = amp.scale_history_update(sh, scfg,
                                      nx.scale_amax(ns, grad_rows))

    ``rows`` optionally gathers a static subset of site rows (e.g.
    the grad sites). Pure ``jnp``; rides the step dispatch."""
    amax = jnp.where(ns.nonfinite_frac > 0, jnp.inf, ns.amax)
    if rows is None:
        return amax
    return amax[jnp.asarray(rows)]


def finite_ok(ns: NumericsState) -> jax.Array:
    """True when the last fold saw NO nonfinite element at any site —
    the in-graph predicate mirroring the guard's nonfinite probes
    (redundant as a veto when the step already runs ``guard_observe``;
    the numerics value-add is :func:`nonfinite_sites` naming WHERE)."""
    return jnp.all(ns.nonfinite_frac == 0)


def nonfinite_sites(ns: NumericsState,
                    sites: Sequence[str]) -> List[Tuple[str, float]]:
    """Host-side: the sites whose last fold saw nonfinite elements,
    with their fractions — the forensic complement of the guard's
    tree-level nonfinite probes (docs/resilience.md names the
    cross-link): the guard vetoes the commit, this names the tensor."""
    import numpy as np
    nf = np.asarray(ns.nonfinite_frac)
    return [(sites[i], float(nf[i])) for i in range(len(sites))
            if nf[i] > 0]


# -- the host half: format pricing + verdicts ---------------------------------

def _coverage(hist, fmt: FormatSpec, scale_exp: int) -> Tuple[float,
                                                              float]:
    """(underflow, saturation) fraction of the measured distribution if
    cast to ``fmt`` after multiplying by 2**scale_exp — a pure index
    shift on the exponent histogram. Elements in the top binade are
    counted representable (the half-bucket approximation
    docs/numerics.md#formats states; margin in the scale choice covers
    it)."""
    import numpy as np
    h = np.asarray(hist, dtype=np.float64)
    lo = fmt.min_exp - scale_exp + _BIAS          # first safe bucket
    hi = fmt.max_exp - scale_exp + _BIAS          # last safe bucket
    under = float(h[:max(min(lo, HIST_BINS), 0)].sum())
    sat = float(h[max(min(hi + 1, HIST_BINS), 0):].sum())
    return under, sat


def _recommended_scale_exp(amax: float, fmt: FormatSpec,
                           margin: float) -> int:
    """The power-of-two scale exponent centering the measured amax
    under ``fmt.max_finite / margin`` — the delayed-scaling formula
    (:mod:`apex_tpu.amp.scale_history` computes the same thing
    in-graph from the amax window)."""
    if amax <= 0 or not math.isfinite(amax):
        return 0
    return int(math.floor(math.log2(fmt.max_finite / (margin * amax))))


@dataclasses.dataclass
class SiteVerdict:
    """One site's measured-range verdict against the format ladder."""

    site: str                     # stable site name (site_names)
    kind: str                     # prefix before the first "/"
    amax: float                   # max(last, ema) measured |x|
    amin: float                   # min nonzero |x| (ema-joined)
    range_bits: Optional[float]   # log2(amax/amin), None w/o data
    zero_frac: float
    nonfinite_frac: float
    uw_ratio: Optional[float]     # None for sites without a companion
    required_dtype: str           # narrowest safe FORMAT_LADDER entry
    recommended_scale: float      # 2**k for the required format
    predicted_underflow_frac: float   # at required fmt + recommended scale
    predicted_saturation_frac: float
    current_dtype: Optional[str]  # FORMAT_TABLE key, when known
    by_format: Dict[str, Dict[str, float]]  # fmt -> {underflow,
                                  # saturation, scale} at that fmt's
                                  # own recommended scale
    #: True when the measured range fits the site's CURRENT format
    #: **unscaled** (no surprise) — the tensor runs at that format
    #: TODAY, with no scale applied, so the verdict prices its
    #: unscaled coverage against the report thresholds (the same
    #: number ``worst_gaps`` ranks by). A scale-assisted ladder
    #: comparison would miss a tensor wholly underflowing the format
    #: it already runs at — exactly the fp8-rollout surprise this
    #: field exists to flag. None when the current dtype is unknown.
    ok: Optional[bool] = None

    @property
    def fingerprint(self) -> str:
        """Stable ``numerics|kind|site`` key — the waiver/pin identity,
        apexlint-fingerprint style (never includes measured numbers)."""
        return f"numerics|{self.kind}|{self.site}"

    def to_event(self, rank: int = 0,
                 step: Optional[int] = None) -> Dict:
        """``kind="precision_verdict"`` event
        (``check_metrics_schema.py --kind numerics`` validates)."""
        return {"kind": "precision_verdict", "rank": rank, "step": step,
                "site": self.site, "site_kind": self.kind,
                "required_dtype": self.required_dtype,
                "current_dtype": self.current_dtype,
                "predicted_underflow_frac":
                    round(self.predicted_underflow_frac, 6),
                "predicted_saturation_frac":
                    round(self.predicted_saturation_frac, 6),
                "recommended_scale": self.recommended_scale,
                "amax": (None if not math.isfinite(self.amax)
                         else self.amax),
                "ok": self.ok,
                "fingerprint": self.fingerprint}


@dataclasses.dataclass
class NumericsReport:
    """The per-site verdict list of one observed run."""

    rows: List[SiteVerdict]
    underflow_threshold: float
    saturation_threshold: float
    margin: float

    def __iter__(self):
        return iter(self.rows)

    def surprises(self) -> List[SiteVerdict]:
        """Sites whose measured range does NOT fit their current format
        — the "zero-surprise" claim the clean audit asserts empty."""
        return [r for r in self.rows if r.ok is False]

    def worst_gaps(self, k: int = 5) -> List[Dict[str, Any]]:
        """The top-k numerically-at-risk sites (measured range does
        NOT fit the current format unscaled), ranked by error mass at
        the CURRENT format — the numeric-safety complement of the
        roofline observatory's perf ``worst_gaps(k)``; JSON-able
        dicts."""
        gaps = []
        for r in self.rows:
            if r.ok is not False:
                continue
            # price the current format UNSCALED — that is what the
            # tensor experiences today
            u, s = _err_at(r, FORMAT_TABLE[r.current_dtype])
            gaps.append((u + s, r, u, s))
        gaps.sort(key=lambda t: -t[0])
        return [{"fingerprint": r.fingerprint, "site": r.site,
                 "kind": r.kind, "current_dtype": r.current_dtype,
                 "required_dtype": r.required_dtype,
                 "underflow_frac": round(u, 6),
                 "saturation_frac": round(s, 6),
                 "recommended_scale": r.recommended_scale}
                for _, r, u, s in gaps[:k]]

    def fp8_candidates(self, k: Optional[int] = None
                       ) -> List[Dict[str, Any]]:
        """Sites whose measured range fits an fp8 format (with the
        recommended scale applied) — the item-5 rollout candidate list,
        ranked safest-first (least predicted error mass at e4m3), each
        entry fingerprinted like a ``worst_gaps`` row."""
        cands = []
        for r in self.rows:
            if r.required_dtype not in ("fp8_e4m3", "fp8_e5m2"):
                continue
            f8 = r.by_format["fp8_e4m3"]
            cands.append((f8["underflow"] + f8["saturation"], r))
        cands.sort(key=lambda t: (t[0], t[1].site))
        out = [{"fingerprint": r.fingerprint, "site": r.site,
                "kind": r.kind, "required_dtype": r.required_dtype,
                "recommended_scale": r.recommended_scale,
                "predicted_underflow_frac":
                    round(r.predicted_underflow_frac, 6),
                "predicted_saturation_frac":
                    round(r.predicted_saturation_frac, 6)}
               for _, r in cands]
        return out if k is None else out[:k]

    def table(self, top: int = 12) -> str:
        lines = [f"numerics — {len(self.rows)} sites, "
                 f"{len(self.surprises())} surprises "
                 f"(u<{self.underflow_threshold:g} "
                 f"s<{self.saturation_threshold:g})",
                 f"{'site':<38} {'cur':<9} {'req':<9} {'amax':>9} "
                 f"{'scale':>9} {'u%':>7} {'s%':>7}"]
        rows = sorted(self.rows,
                      key=lambda r: (r.ok is not False, r.site))
        for r in rows[:top]:
            lines.append(
                f"{r.site[:38]:<38} {r.current_dtype or '?':<9} "
                f"{r.required_dtype:<9} {r.amax:>9.3g} "
                f"{r.recommended_scale:>9.3g} "
                f"{100 * r.predicted_underflow_frac:>6.2f}% "
                f"{100 * r.predicted_saturation_frac:>6.2f}%")
        return "\n".join(lines)

    def to_events(self, rank: int = 0,
                  step: Optional[int] = None) -> List[Dict]:
        return [r.to_event(rank=rank, step=step) for r in self.rows]


def _err_at(r: SiteVerdict, fmt: FormatSpec) -> Tuple[float, float]:
    """(underflow, saturation) of a verdict's stored histogram at
    ``fmt`` unscaled (scale_exp 0) — re-derived from the per-format
    table rather than the raw histogram, which the verdict does not
    retain; falls back to the recorded per-format coverage."""
    ent = r.by_format.get(fmt.name)
    if ent is None:
        return 0.0, 0.0
    return ent.get("unscaled_underflow", ent["underflow"]), \
        ent.get("unscaled_saturation", ent["saturation"])


def precision_report(ns_or_stats, sites: Optional[Sequence[str]] = None,
                     *, current_dtypes=None,
                     underflow_threshold: float = 1e-3,
                     saturation_threshold: float = 1e-3,
                     margin: float = 2.0) -> NumericsReport:
    """Join measured exponent coverage against :data:`FORMAT_TABLE`
    into the per-site verdict list.

    ``ns_or_stats`` is a :class:`NumericsState` (with ``sites`` — ONE
    host fetch, amortized like a metrics flush) or a stats dict from
    :func:`stats_to_json` (the committed-fixture path: CI pins the
    verdict list on a committed measurement with no device in sight).
    ``current_dtypes`` maps site → jnp dtype / format name (or one
    value for all sites); verdicts then carry the ``ok`` no-surprise
    bit. ``margin`` is the saturation headroom the recommended scale
    reserves (2 = half the format's top binade, absorbing the
    half-bucket approximation AND one growth step of the scale
    machinery).

    A format is *safe* for a site when, at that format's own
    recommended power-of-two scale, predicted underflow ≤
    ``underflow_threshold`` and predicted saturation ≤
    ``saturation_threshold``; ``required_dtype`` is the narrowest safe
    ladder entry (fp32 as the unconditional fallback).
    """
    import numpy as np
    if isinstance(ns_or_stats, NumericsState):
        if sites is None:
            raise ValueError("precision_report(NumericsState) needs "
                             "the matching sites tuple")
        stats = _fetch_stats(ns_or_stats, sites)
    else:
        stats = dict(ns_or_stats)
        sites = tuple(stats["sites"])

    def _cur(i: int) -> Optional[str]:
        if current_dtypes is None:
            return None
        if isinstance(current_dtypes, dict):
            v = current_dtypes.get(sites[i])
        else:
            v = current_dtypes
        return None if v is None else format_of_dtype(v)

    rows: List[SiteVerdict] = []
    for i, site in enumerate(sites):
        amax = max(float(stats["amax"][i]), float(stats["amax_ema"][i]))
        amin_candidates = [v for v in (float(stats["amin"][i]),
                                       float(stats["amin_ema"][i]))
                           if v > 0]
        amin = min(amin_candidates) if amin_candidates else 0.0
        hist = np.asarray(stats["exp_hist"][i], dtype=np.float64)
        by_format: Dict[str, Dict[str, float]] = {}
        required = "fp32"
        for name in FORMAT_LADDER:
            fmt = FORMAT_TABLE[name]
            k = _recommended_scale_exp(amax, fmt, margin)
            u, s = _coverage(hist, fmt, k)
            u0, s0 = _coverage(hist, fmt, 0)
            by_format[name] = {"underflow": u, "saturation": s,
                               "scale": float(2.0 ** k),
                               "unscaled_underflow": u0,
                               "unscaled_saturation": s0}
            if (required == "fp32" and name != "fp32"
                    and u <= underflow_threshold
                    and s <= saturation_threshold):
                required = name
        req = by_format[required]
        uw = float(stats["uw_ratio"][i])
        cur = _cur(i)
        if cur is None:
            ok = None
        else:
            c = by_format[cur]
            ok = (c["unscaled_underflow"] <= underflow_threshold
                  and c["unscaled_saturation"] <= saturation_threshold)
        rows.append(SiteVerdict(
            site=site, kind=site.split("/", 1)[0],
            amax=amax, amin=amin,
            range_bits=(math.log2(amax / amin)
                        if amax > 0 and amin > 0 else None),
            zero_frac=float(stats["zero_frac"][i]),
            nonfinite_frac=float(stats["nonfinite_frac"][i]),
            uw_ratio=None if uw < 0 else uw,
            required_dtype=required,
            recommended_scale=req["scale"],
            predicted_underflow_frac=req["underflow"],
            predicted_saturation_frac=req["saturation"],
            current_dtype=cur, by_format=by_format, ok=ok))
    return NumericsReport(rows=rows,
                          underflow_threshold=underflow_threshold,
                          saturation_threshold=saturation_threshold,
                          margin=margin)


def _fetch_stats(ns: NumericsState, sites: Sequence[str]) -> Dict:
    import numpy as np
    host = jax.device_get(ns)
    if len(sites) != host.amax.shape[0]:
        raise ValueError(f"{len(sites)} sites for a state with "
                         f"{host.amax.shape[0]} rows")
    return {"sites": tuple(sites),
            "step": int(host.step), "check_count": int(host.check_count),
            "amax": np.asarray(host.amax),
            "amax_ema": np.asarray(host.amax_ema),
            "amin": np.asarray(host.amin),
            "amin_ema": np.asarray(host.amin_ema),
            "exp_hist": np.asarray(host.exp_hist),
            "zero_frac": np.asarray(host.zero_frac),
            "nonfinite_frac": np.asarray(host.nonfinite_frac),
            "uw_ratio": np.asarray(host.uw_ratio)}


def stats_to_json(ns: NumericsState, sites: Sequence[str]) -> str:
    """Serialize one fetched measurement (the committed-fixture
    format: ``tests/fixtures/*_numerics_stats.json`` pins
    :func:`precision_report` verdicts in CI without a device). The
    histogram is sparsified (zero buckets dropped) to keep fixtures
    reviewable."""
    st = _fetch_stats(ns, sites)
    hist = [{str(b): round(float(v), 9)
             for b, v in enumerate(row) if v > 0}
            for row in st["exp_hist"]]
    return json.dumps({
        "version": 1, "sites": list(st["sites"]),
        "step": st["step"], "check_count": st["check_count"],
        "amax": [float(v) for v in st["amax"]],
        "amax_ema": [float(v) for v in st["amax_ema"]],
        "amin": [float(v) for v in st["amin"]],
        "amin_ema": [float(v) for v in st["amin_ema"]],
        "exp_hist": hist,
        "zero_frac": [float(v) for v in st["zero_frac"]],
        "nonfinite_frac": [float(v) for v in st["nonfinite_frac"]],
        "uw_ratio": [float(v) for v in st["uw_ratio"]],
    }, indent=1)


def stats_from_json(text: str) -> Dict:
    """Inverse of :func:`stats_to_json` — feed the result straight to
    :func:`precision_report`."""
    import numpy as np
    data = json.loads(text)
    s = len(data["sites"])
    hist = np.zeros((s, HIST_BINS), dtype=np.float64)
    for i, row in enumerate(data["exp_hist"]):
        for b, v in row.items():
            hist[i, int(b)] = v
    out = dict(data)
    out["exp_hist"] = hist
    return out


# -- events (the numerics channel) --------------------------------------------

def check_events(ns: NumericsState, sites: Sequence[str], *,
                 rank: int = 0,
                 current_dtype=None) -> List[Dict]:
    """One ``kind="numerics_check"`` aggregate row (``site`` null) plus
    one per-site row per call — the host-poll emission (wire through
    ``MetricsLogger(numerics_sink=…)``; ``--kind numerics``
    validates). Fetches the state ONCE. ``current_dtype`` prices the
    per-site underflow/overflow fractions against one format's range
    (the live gauge; the full ladder verdict is
    :func:`precision_report`)."""
    import numpy as np
    st = _fetch_stats(ns, sites)
    if current_dtype is None:
        fmt = FORMAT_TABLE["bf16"]
    else:
        key = format_of_dtype(current_dtype)
        if key is None:
            # a silent bf16 fallback would emit fractions priced
            # against a range the caller never asked about — refuse
            # loudly, like precision_report refuses nothing but maps
            # unknowns to ok=None
            raise ValueError(
                f"check_events: {current_dtype!r} is not a known "
                f"format/dtype — one of {FORMAT_LADDER} or a float "
                f"dtype name")
        fmt = FORMAT_TABLE[key]
    events: List[Dict] = [{
        "kind": "numerics_check", "rank": rank, "step": st["step"],
        "check_count": st["check_count"], "site": None,
        "n_sites": len(sites),
        "amax": float(np.max(st["amax"])),
        "amin": None,
        "nonfinite_frac": float(np.max(st["nonfinite_frac"])),
        "zero_frac": float(np.mean(st["zero_frac"])),
        "underflow_frac": None, "overflow_frac": None,
        "uw_ratio": None,
    }]
    for i, site in enumerate(sites):
        u, s = _coverage(st["exp_hist"][i], fmt, 0)
        uw = float(st["uw_ratio"][i])
        events.append({
            "kind": "numerics_check", "rank": rank, "step": st["step"],
            "check_count": st["check_count"], "site": site,
            "n_sites": len(sites),
            "amax": float(st["amax"][i]),
            "amin": float(st["amin"][i]),
            "underflow_frac": round(u, 6),
            "overflow_frac": round(s, 6),
            "zero_frac": round(float(st["zero_frac"][i]), 6),
            "nonfinite_frac": round(float(st["nonfinite_frac"][i]), 6),
            "uw_ratio": None if uw < 0 else uw,
        })
    return events


# -- the advisor: perf headroom × numeric safety ------------------------------

def placement_advisor(roofline_report, report: NumericsReport, *,
                      k: int = 5) -> List[Dict[str, Any]]:
    """Rank precision-placement candidates by **measured perf headroom
    × numeric safety**: join the verdict list's fp8/half candidates
    with the roofline observatory's what-if dtype column
    (:meth:`apex_tpu.prof.RooflineReport.what_if` — attainable time if
    the site's verdict were applied). A site only ranks when (a) its
    measured range fits the narrower format (the verdict) AND (b) the
    roofline says the op is near enough its bound that the dtype
    change buys wall time (the headroom). Sites are matched to
    roofline rows by case-insensitive substring of the stripped scope
    — name observation sites after the named-scope conventions
    (docs/numerics.md#advisor)."""
    plan = {}
    for r in report.rows:
        if r.required_dtype == "fp32":
            continue
        if r.current_dtype is not None and r.ok is False:
            continue          # numerically unsafe today — not a cand.
        plan[r.site] = r.required_dtype
    if not plan:
        return []
    whatif = roofline_report.what_if(plan)
    by_site: Dict[str, SiteVerdict] = {r.site: r for r in report.rows}
    out = []
    for row in whatif:
        v = by_site.get(row["site"])
        if v is None:
            continue
        err = (v.predicted_underflow_frac
               + v.predicted_saturation_frac)
        safety = 1.0 - min(1.0, err / max(
            report.underflow_threshold
            + report.saturation_threshold, 1e-12))
        gain = row.get("whatif_gain_us") or 0.0
        out.append({**row, "required_dtype": v.required_dtype,
                    "recommended_scale": v.recommended_scale,
                    "numeric_safety": round(safety, 4),
                    "rank_score": round(gain * safety, 3),
                    "verdict_fingerprint": v.fingerprint})
    out.sort(key=lambda e: -e["rank_score"])
    return out[:k]
