"""Functional dynamic loss scaling — no host syncs, no step patching.

The reference's ``LossScaler`` (`apex/amp/scaler.py:33-215`) keeps a CUDA
overflow flag filled by the multi-tensor kernels, reads it back with
``.item()`` once per iteration (`scaler.py:197-200` — a forced
device-to-host sync), and on overflow monkey-patches ``optimizer.step`` to
skip once (`apex/amp/handle.py:128-154`).

Here the scaler is explicit state threaded through the jitted train step:

    cfg   = LossScaleConfig()                  # dynamic, 2^16, x2/2000, /2
    state = loss_scale_init(cfg)
    ...
    grads, finite = unscale_grads(grads, state)
    state = loss_scale_update(state, finite, cfg)
    params = tree_select(finite, new_params, params)   # skip == don't select

Everything stays on device; the "skipped step" is a `jnp.where` select, and
momentum/step counters simply aren't advanced for the skipped branch (the
property `tests/L0/run_amp/test_fused_sgd.py` asserts bitwise).

Scale schedule parity (`apex/amp/scaler.py:12-31,197-215`):
  * init 2**16, growth x2 every 2000 consecutive finite steps,
  * backoff x0.5 on overflow, clamped to [min_loss_scale, max_loss_scale],
  * max defaults to 2**24.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.utils import tree_all_finite, tree_select


class LossScaleConfig(NamedTuple):
    """Static scaler configuration (hashable; safe to close over in jit)."""
    init_scale: float = 2.0 ** 16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    min_loss_scale: Optional[float] = None
    max_loss_scale: float = 2.0 ** 24
    dynamic: bool = True

    @classmethod
    def from_policy_field(cls, loss_scale):
        """Build from a Policy.loss_scale field ('dynamic' | float | None)."""
        if loss_scale is None:
            return None
        if loss_scale == "dynamic":
            return cls(dynamic=True)
        return cls(init_scale=float(loss_scale), dynamic=False)


class LossScaleState(NamedTuple):
    """Dynamic scaler state: a pytree, checkpointable like any other state.

    The reference round-trips this through ``amp.state_dict()``
    (`apex/amp/frontend.py:361-400`); here it is just part of the train state.
    """
    loss_scale: jax.Array      # f32 scalar
    growth_tracker: jax.Array  # i32 scalar: consecutive finite steps


def loss_scale_init(cfg: Optional[LossScaleConfig]) -> Optional[LossScaleState]:
    if cfg is None:
        return None
    return LossScaleState(
        loss_scale=jnp.float32(cfg.init_scale),
        growth_tracker=jnp.int32(0),
    )


def scale_loss(loss, state: Optional[LossScaleState]):
    """``loss.float() * loss_scale`` (`apex/amp/handle.py:113`)."""
    loss = jnp.asarray(loss, jnp.float32)
    if state is None:
        return loss
    return loss * state.loss_scale


def unscale_grads(grads, state: Optional[LossScaleState], *,
                  upcast_to=jnp.float32):
    """Multiply grads by 1/scale (in fp32) and report global finiteness.

    The fused analogue of ``LossScaler.unscale`` (`apex/amp/scaler.py:94-125`):
    one traversal producing fp32 grads + a single on-device finite flag.
    On the overflow branch grads are garbage but never consumed — the caller
    selects the old state via :func:`apex_tpu.utils.tree_select`.
    """
    if state is None:
        finite = tree_all_finite(grads)
        if upcast_to is not None:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(upcast_to)
                if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
        return grads, finite
    inv = (1.0 / state.loss_scale).astype(jnp.float32)

    def _unscale(g):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        out = g.astype(jnp.float32) * inv
        target = g.dtype if upcast_to is None else upcast_to
        return out if target == jnp.float32 else out.astype(target)

    grads = jax.tree_util.tree_map(_unscale, grads)
    finite = tree_all_finite(grads)
    return grads, finite


def unscale_grads_with_stashed(grads, stashed, state: Optional[LossScaleState],
                               *, stashed_scale=1.0):
    """Gradient accumulation across backward passes at (possibly) different
    scales: ``out = stashed * (stashed_scale/new_scale? ...) + grads / scale``.

    Parity with ``unscale_with_stashed`` / ``multi_tensor_axpby``
    (`apex/amp/scaler.py:152-190`): the stashed fp32 grads were already
    unscaled (or carry ``stashed_scale``), the incoming grads carry the
    current scale; both are combined in fp32 in one pass.
    """
    inv = 1.0 if state is None else (1.0 / state.loss_scale)

    def _axpby(g, s):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        g32 = g.astype(jnp.float32) * inv
        s32 = s.astype(jnp.float32) * stashed_scale
        return g32 + s32

    out = jax.tree_util.tree_map(_axpby, grads, stashed)
    finite = tree_all_finite(out)
    return out, finite


def loss_scale_update(state: Optional[LossScaleState], grads_finite,
                      cfg: Optional[LossScaleConfig], *, metrics=None):
    """Advance the scale schedule — entirely on device.

    Parity with ``LossScaler.update_scale`` (`apex/amp/scaler.py:197-215`):
    overflow → scale *= backoff (clamped below by ``min_loss_scale``),
    tracker reset; else tracker += 1, and at ``growth_interval`` scale *=
    growth (clamped above by ``max_loss_scale``), tracker reset.

    With an :class:`apex_tpu.monitor.Metrics` pytree passed as
    ``metrics=``, the schedule's events are counted on-device (overflow /
    backoff / growth, plus the resulting scale gauge) — the telemetry
    replacement for the reference's "Gradient overflow. Skipping step"
    prints (`apex/amp/scaler.py:201-211`) — and ``(new_state, metrics')``
    is returned instead of just the state. Event arithmetic is pure
    ``jnp``; it rides the existing step dispatch.
    """
    if state is None or cfg is None or not cfg.dynamic:
        if metrics is None:
            return state
        overflow = jnp.logical_not(
            jnp.asarray(grads_finite, jnp.bool_)).astype(jnp.int32)
        metrics = metrics._replace(
            loss_scale=(jnp.float32(1.0) if state is None
                        else state.loss_scale),
            overflow_count=metrics.overflow_count + overflow)
        return state, metrics

    scale = state.loss_scale
    tracker = state.growth_tracker

    backoff = scale * cfg.backoff_factor
    if cfg.min_loss_scale is not None:
        backoff = jnp.maximum(backoff, cfg.min_loss_scale)

    grown_tracker = tracker + 1
    should_grow = grown_tracker >= cfg.growth_interval
    grown = jnp.minimum(scale * cfg.growth_factor, cfg.max_loss_scale)

    new_scale = jnp.where(
        grads_finite,
        jnp.where(should_grow, grown, scale),
        backoff).astype(jnp.float32)
    new_tracker = jnp.where(
        grads_finite,
        jnp.where(should_grow, 0, grown_tracker),
        0).astype(jnp.int32)
    new_state = LossScaleState(loss_scale=new_scale,
                               growth_tracker=new_tracker)
    if metrics is None:
        return new_state
    fin = jnp.asarray(grads_finite, jnp.bool_)
    overflow = jnp.logical_not(fin).astype(jnp.int32)
    grew = jnp.logical_and(fin, should_grow).astype(jnp.int32)
    metrics = metrics._replace(
        loss_scale=new_scale,
        overflow_count=metrics.overflow_count + overflow,
        backoff_count=metrics.backoff_count + overflow,
        growth_count=metrics.growth_count + grew)
    return new_state, metrics


def select_if_finite(grads_finite, new_tree, old_tree):
    """Commit ``new_tree`` where grads were finite, else keep ``old_tree``.

    The functional skipped-step: replaces the reference's one-shot
    ``optimizer.step`` patch + master-grad zeroing (`handle.py:128-154`).
    """
    return tree_select(grads_finite, new_tree, old_tree)


# --- Convenience: scaled value-and-grad -------------------------------------

def value_and_scaled_grad(loss_fn, cfg: Optional[LossScaleConfig], *,
                          has_aux: bool = False, upcast_to=jnp.float32):
    """Wrap ``loss_fn(params, *args) -> loss`` into
    ``f(params, scaler_state, *args) -> ((loss, aux?), grads, new_state, finite)``.

    The functional equivalent of the ``with amp.scale_loss(...) as scaled:
    scaled.backward()`` block (`apex/amp/handle.py:16-158`): scales the loss
    before differentiation, unscales the grads in fp32, folds the finiteness
    check in, and advances the scale schedule. The returned loss/aux are the
    *unscaled* values.
    """

    def wrapped(params, scaler_state, *args, **kwargs):
        def scaled_loss(p):
            out = loss_fn(p, *args, **kwargs)
            loss = out[0] if has_aux else out
            return scale_loss(loss, scaler_state), out

        grads, out = jax.grad(scaled_loss, has_aux=True)(params)
        grads, finite = unscale_grads(grads, scaler_state, upcast_to=upcast_to)
        new_state = loss_scale_update(scaler_state, finite, cfg)
        return out, grads, new_state, finite

    return wrapped
