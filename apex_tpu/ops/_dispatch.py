"""Backend dispatch for Pallas kernels.

Compiled Mosaic kernels require a real TPU; every kernel in apex_tpu runs in
Pallas interpret mode on other backends (the CI CPU mesh), preserving
semantics bit-for-bit at jnp precision. This mirrors the reference's
"Python-only build degrades gracefully" contract
(`apex/amp/scaler.py:39-52`) — except nothing is unavailable here, only
uncompiled.

``APEX_TPU_FORCE_INTERPRET=1`` forces interpret mode everywhere (debugging).
"""

from __future__ import annotations

import os

import jax


def use_interpret() -> bool:
    if os.environ.get("APEX_TPU_FORCE_INTERPRET") == "1":
        return True
    return jax.default_backend() != "tpu"


# Rows per grid step for flat-buffer elementwise kernels. A (512, 128) fp32
# block is 256 KiB — small enough that an 8-operand optimizer kernel stays
# well under the ~16 MiB VMEM budget with double buffering, large enough to
# saturate HBM bandwidth. Default only: `launch` consults the tuning DB
# (apex_tpu.ops.autotune, family "optimizer") and accepts an explicit
# ``block_rows`` per call; the module constant stays the arena's shard
# alignment anchor (optim.distributed imports it).
BLOCK_ROWS = 512
LANES = 128


def as_rows(buf, block_rows=None):
    """View a flat arena buffer as (rows, 128). Arena buffers are padded to
    BUFFER_MULTIPLE (= 512 * 128 elements) so rows % BLOCK_ROWS == 0 always
    holds for the default block; a tuned/explicit ``block_rows`` that does
    not divide the buffer is refused upstream in `_resolve_block_rows`, not
    here."""
    n = buf.shape[0]
    br = BLOCK_ROWS if block_rows is None else block_rows
    assert n % (br * LANES) == 0, (
        f"arena buffer length {n} is not a multiple of {br * LANES} "
        f"(block_rows={br} x {LANES} lanes). Flat optimizer buffers must "
        f"come from apex_tpu.arena.flatten, which pads to BUFFER_MULTIPLE "
        f"= {512 * LANES} elements; a buffer satisfying BUFFER_MULTIPLE "
        f"but not a tuned non-default block is rejected before launch by "
        f"_resolve_block_rows, which falls back to BLOCK_ROWS={BLOCK_ROWS} "
        f"and names the tuning-DB fingerprint responsible.")
    return buf.reshape(n // LANES, LANES)


def _resolve_block_rows(rows, buf0, block_rows):
    """Pick the grid block for one launch: explicit caller value, else a
    tuning-DB hit for this buffer's (length, dtype), else BLOCK_ROWS.

    A tuned/explicit block that does not divide the (BUFFER_MULTIPLE-padded)
    buffer would trip the `as_rows` shape assert deep in pallas plumbing
    with no hint of *which* DB entry chose it — the satellite-2 bug. Refuse
    it here instead: warn naming the offending fingerprint and the fallback
    taken, then launch on the default block.
    """
    import warnings

    n = int(buf0.shape[0])
    explicit = block_rows is not None
    if not explicit:
        from apex_tpu.ops import autotune
        block_rows = autotune.tuned_rows(
            "optimizer", (n,), buf0.dtype, lo=8, hi=4096)
        if block_rows is None:
            return BLOCK_ROWS
    br = int(block_rows)
    if br <= 0 or rows % br:
        from apex_tpu.ops import autotune
        fp = autotune.fingerprint("optimizer", (n,), buf0.dtype)
        src = "explicit block_rows" if explicit else "tuning entry"
        warnings.warn(
            f"{src} {fp}: block_rows={br} does not divide the "
            f"{rows}-row arena buffer (length {n}, BUFFER_MULTIPLE-padded) "
            f"— falling back to BLOCK_ROWS={BLOCK_ROWS}; re-run "
            f"scripts/kernel_tune.py --update-db to re-measure this shape",
            RuntimeWarning, stacklevel=3)
        return BLOCK_ROWS
    return br


def launch(kernel, inputs, outs, scalars=None, block_rows=None):
    """Shared pallas_call plumbing for flat-buffer elementwise kernels.

    The single launch convention every arena kernel uses (the analogue of
    the reference's `multi_tensor_apply.cuh` launcher): a 1-D grid over
    (BLOCK_ROWS, 128) VMEM blocks of each input buffer, an optional f32
    hyperparameter vector in SMEM prepended to the kernel args, and outputs
    that are either per-block buffers or (1,1) SMEM scalar accumulators
    revisited by every grid step (TPU grids are sequential, so
    read-modify-write accumulation is well-defined; Mosaic requires scalar
    stores to target SMEM, not VMEM).

    ``outs`` is a list of ("block", dtype) | ("scalar", dtype) entries.
    Block outputs come back as flat buffers, scalar outputs as (1, 1)
    arrays, in order.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows_arrs = [as_rows(b) for b in inputs]
    rows = rows_arrs[0].shape[0]
    br = _resolve_block_rows(rows, inputs[0], block_rows)
    block = pl.BlockSpec((br, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    scalar = pl.BlockSpec((1, 1), lambda i: (0, 0),
                          memory_space=pltpu.SMEM)

    in_specs = [block] * len(rows_arrs)
    args = tuple(rows_arrs)
    if scalars is not None:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        args = (jnp.asarray(scalars, jnp.float32),) + args

    out_specs, out_shapes = [], []
    for kind, dt in outs:
        if kind == "block":
            out_specs.append(block)
            out_shapes.append(jax.ShapeDtypeStruct((rows, LANES),
                                                   jnp.dtype(dt)))
        elif kind == "scalar":
            out_specs.append(scalar)
            out_shapes.append(jax.ShapeDtypeStruct((1, 1), jnp.dtype(dt)))
        else:
            raise ValueError(f"unknown out kind {kind!r}")

    results = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shapes),
        interpret=use_interpret(),
    )(*args)
    if not isinstance(results, (list, tuple)):
        results = (results,)
    final = tuple(r.reshape(-1) if kind == "block" else r
                  for r, (kind, _) in zip(results, outs))
    return final if len(final) > 1 else final[0]
