"""Cluster control plane (apex_tpu.cluster) — the ISSUE-11 suite.

Generation-fenced membership, coordinated multi-rank recovery, and the
relaunch hygiene that ties them into ``elastic_run``: lease lifecycle,
monotone generation commits with CAS semantics, the checkpoint-layer
fence (write/commit/delete all refused for stale tokens), the in-use
marker that stops ``gc_checkpoints`` deleting under a concurrent
restore, signed-intent coordination with deterministic oldest-good-step
resolution, the collective-deadline watchdog tier, generation-scoped
heartbeats/straggler detection, the cluster event schema (+negative
twins), the bench backend-init guard — and the two multi-process
acceptance runs: the SIGSTOP zombie whose late commit the fence
refuses, and the coordinated rewind that resumes bitwise vs a
fault-free oracle with exactly one generation bump.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import ckpt, cluster, guard, monitor, trace
from apex_tpu.ckpt import format as _format
from apex_tpu.trace import straggler as _straggler

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from scripts.check_metrics_schema import check_cluster_lines  # noqa: E402


def _collect():
    """An event sink capturing into a list."""
    events = []
    return events, events.append


# --- generation ---------------------------------------------------------------

class TestGeneration:
    def test_fresh_directory_is_generation_zero(self, tmp_path):
        d = str(tmp_path)
        assert cluster.read_generation(d) == 0
        assert cluster.read_generation_record(d) == {"generation": 0}

    def test_bump_is_monotone_and_recorded(self, tmp_path):
        d = str(tmp_path)
        assert cluster.bump_generation(d, rank=3, reason="test") == 1
        rec = cluster.read_generation_record(d)
        assert rec["generation"] == 1
        assert rec["prev_generation"] == 0
        assert rec["committed_by_rank"] == 3
        assert rec["reason"] == "test"
        assert cluster.bump_generation(d) == 2
        assert cluster.read_generation(d) == 2

    def test_bump_expect_cas_refuses_the_lost_race(self, tmp_path):
        d = str(tmp_path)
        cluster.bump_generation(d)                      # now at 1
        with pytest.raises(cluster.StaleGenerationError) as ei:
            cluster.bump_generation(d, expect=0)        # raced & lost
        assert ei.value.generation == 0
        assert ei.value.current == 1
        # the losing racer did NOT stack an epoch
        assert cluster.read_generation(d) == 1
        # a matching expect commits
        assert cluster.bump_generation(d, expect=1) == 2

    def test_epoch_filename_is_authoritative_over_torn_content(
            self, tmp_path):
        d = str(tmp_path)
        # a stray non-epoch file is ignored entirely
        with open(os.path.join(d, "generation.notanepoch.json"),
                  "w") as f:
            f.write("{torn")
        assert cluster.read_generation(d) == 0
        # an epoch FILE with torn/mismatched content still commits its
        # epoch — the filename is the commit (the no-hardlink
        # fallback's brief torn window), content is only forensics
        with open(cluster.generation_path(d, 2), "w") as f:
            f.write("{torn")
        assert cluster.read_generation(d) == 2
        assert cluster.read_generation_record(d) == {"generation": 2}

    def test_stalled_writer_cannot_roll_the_epoch_backwards(
            self, tmp_path, monkeypatch):
        """The rollback race the exclusive-create publish closes: a
        writer that read generation 0, passed its expect pre-check,
        then stalled while the cluster moved to 2 must be REFUSED at
        publish time — not land epoch 1 over the committed 2."""
        from apex_tpu.cluster import membership as _membership
        d = str(tmp_path)
        cluster.bump_generation(d)                      # 0 -> 1
        cluster.bump_generation(d)                      # 1 -> 2
        # replay the stalled writer: its read happened BEFORE the two
        # bumps, so both its pre-check and its error-path re-read see
        # the stale 0 — only the publish-side exclusive create (the
        # target epoch-1 file already exists) can refuse it
        monkeypatch.setattr(_membership, "read_generation",
                            lambda _d: 0)
        with pytest.raises(cluster.StaleGenerationError):
            _membership.bump_generation(d, expect=0)
        monkeypatch.undo()
        assert cluster.read_generation(d) == 2


# --- leases -------------------------------------------------------------------

class TestLease:
    def test_acquire_renew_release_roundtrip(self, tmp_path):
        d = str(tmp_path)
        lw = cluster.LeaseWriter(d, rank=2, ttl_s=30.0)
        assert lw.acquire(0)
        t0 = time.time()
        leases = cluster.read_leases(d)
        assert set(leases) == {2}
        rec = leases[2]
        assert rec["generation"] == 0 and rec["rank"] == 2
        assert rec["pid"] == os.getpid()
        assert abs(rec["expires_at"] - (t0 + 30.0)) < 5.0
        assert isinstance(rec["mac"], str) and len(rec["mac"]) == 64
        assert lw.renew()
        assert cluster.read_leases(d)[2]["n_renewals"] == 1
        lw.release()
        assert cluster.read_leases(d) == {}

    def test_torn_lease_file_is_skipped(self, tmp_path):
        d = str(tmp_path)
        cluster.LeaseWriter(d, rank=0).acquire(0)
        with open(cluster.lease_path(d, 1), "w") as f:
            f.write('{"rank": 1, "gener')       # torn tail
        assert set(cluster.read_leases(d)) == {0}

    def test_expire_now_is_the_lease_expire_chaos_site(self, tmp_path):
        d = str(tmp_path)
        m = cluster.ClusterMembership(d, rank=0, ttl_s=60.0)
        m.join()
        assert m.alive_ranks() == [0]
        assert m.expired_ranks() == []
        assert m.lease.expire_now()
        assert m.alive_ranks() == []
        assert m.expired_ranks() == [0]

    def test_alive_ranks_excludes_other_generations(self, tmp_path):
        d = str(tmp_path)
        m0 = cluster.ClusterMembership(d, rank=0)
        m0.join()
        stale = cluster.LeaseWriter(d, rank=1, ttl_s=60.0)
        stale.acquire(0)
        assert m0.alive_ranks() == [0, 1]
        m0.bump("shrink")           # commits generation 1, re-leases
        # rank 1's unexpired lease still claims generation 0: not alive
        assert m0.alive_ranks() == [0]

    def test_gc_stale_leases(self, tmp_path):
        d = str(tmp_path)
        old = cluster.LeaseWriter(d, rank=1)
        old.acquire(0)
        cluster.bump_generation(d)
        cur = cluster.LeaseWriter(d, rank=0)
        cur.acquire(1)
        removed = cluster.gc_stale_leases(d, 1)
        assert removed == [cluster.lease_path(d, 1)]
        assert set(cluster.read_leases(d)) == {0}

    def test_foreign_lease_is_not_a_member_and_gc_eligible(self,
                                                           tmp_path):
        """A stray/foreign lease file (valid JSON, no valid MAC) must
        not read as a phantom member — it would stall every recovery
        barrier for the full timeout waiting on its intent — and gc
        may remove it even when its claimed generation is current."""
        d = str(tmp_path)
        m = cluster.ClusterMembership(d, rank=0, ttl_s=60.0)
        m.join()
        with open(cluster.lease_path(d, 5), "w") as f:
            json.dump({"rank": 5, "generation": 0,
                       "expires_at": time.time() + 1e6,
                       "mac": "f" * 64}, f)
        # raw read still sees it; the verified membership view doesn't
        assert 5 in cluster.read_leases(d)
        assert 5 not in m.leases()
        assert m.alive_ranks() == [0]
        removed = cluster.gc_stale_leases(d, 0,
                                          token=m.lease.token)
        assert removed == [cluster.lease_path(d, 5)]
        assert 0 in cluster.read_leases(d)


# --- the membership fence -----------------------------------------------------

class TestMembershipFence:
    def test_join_check_pass_at_current_generation(self, tmp_path):
        events, sink = _collect()
        m = cluster.ClusterMembership(str(tmp_path), rank=0,
                                      event_sink=sink)
        assert m.join() == 0
        assert m.check("commit") == 0
        assert [e["kind"] for e in events] == ["cluster_lease"]
        assert events[0]["action"] == "acquire"

    def test_zombie_check_refuses_and_emits_fence_event(self, tmp_path):
        d = str(tmp_path)
        zombie_events, zsink = _collect()
        zombie = cluster.ClusterMembership(d, rank=1, event_sink=zsink)
        zombie.join()
        other = cluster.ClusterMembership(d, rank=0)
        other.join()
        other.bump("recovery")      # the world moves on
        with pytest.raises(cluster.StaleGenerationError) as ei:
            zombie.check("commit", path="/ck/step_8", step=8)
        assert "zombie" in str(ei.value)
        fences = [e for e in zombie_events
                  if e["kind"] == "cluster_fence"]
        assert len(fences) == 1
        ev = fences[0]
        assert ev["action"] == "refused_commit"
        assert ev["generation"] == 0 and ev["current_generation"] == 1
        assert ev["path"] == "/ck/step_8" and ev["step"] == 8
        # write and delete refusals carry their own action names
        with pytest.raises(cluster.StaleGenerationError):
            zombie.check("write")
        with pytest.raises(cluster.StaleGenerationError):
            zombie.check("delete")
        acts = [e["action"] for e in zombie_events
                if e["kind"] == "cluster_fence"]
        assert acts == ["refused_commit", "refused_write",
                        "refused_delete"]

    def test_bump_emits_and_rejoin_adopts(self, tmp_path):
        d = str(tmp_path)
        events, sink = _collect()
        m = cluster.ClusterMembership(d, rank=0, event_sink=sink)
        m.join()
        assert m.bump("coordinated_rewind") == 1
        bumps = [e for e in events if e["kind"] == "cluster_generation"]
        assert bumps[0]["action"] == "bump"
        assert bumps[0]["generation"] == 1
        assert bumps[0]["prev_generation"] == 0
        follower = cluster.ClusterMembership(d, rank=1)
        follower.join()
        assert follower.generation == 1
        assert follower.check("commit") == 1

    def test_split_brain_claim_is_refused_everywhere(self, tmp_path):
        d = str(tmp_path)
        events, sink = _collect()
        m = cluster.ClusterMembership(d, rank=1, event_sink=sink)
        m.join()
        m.claim_generation(5)       # an epoch the cluster never agreed
        # the fence refuses ANY mismatch — a future claim is
        # split-brain, not seniority
        with pytest.raises(cluster.StaleGenerationError) as ei:
            m.check("commit")
        assert "split-brain" in str(ei.value)
        assert any(e["kind"] == "cluster_fence" and e["generation"] == 5
                   and e["current_generation"] == 0 for e in events)
        # and the CAS bump refuses to commit the claim
        with pytest.raises(cluster.StaleGenerationError):
            m.bump("split")         # expect=5, disk at 0
        assert cluster.read_generation(d) == 0

    def test_gc_stale_cleans_leases_heartbeats_intents(self, tmp_path):
        d = str(tmp_path)
        hb_dir = str(tmp_path / "hb")
        old = cluster.LeaseWriter(d, rank=7)
        old.acquire(0)
        hb = _straggler.HeartbeatWriter(hb_dir, rank=7, generation=0)
        hb.on_step(_FakeStepTrace(3, 10.0))
        # a resolved round's intent files are inert once the epoch
        # moved — but must not accumulate under the per-step
        # pending() listdir forever
        stale_member = cluster.ClusterMembership(d, rank=7)
        stale_member.join()
        stale_intent = cluster.RecoveryCoordinator(
            stale_member).propose(action="rewind", step=3, good_step=2)
        events, sink = _collect()
        m = cluster.ClusterMembership(d, rank=0, event_sink=sink)
        m.join()
        m.bump("restart")
        removed = m.gc_stale(heartbeat_dir=hb_dir)
        assert cluster.lease_path(d, 7) in removed
        assert _straggler.heartbeat_path(hb_dir, 7) in removed
        assert stale_intent in removed
        assert not os.path.exists(stale_intent)
        assert any(e["kind"] == "cluster_lease" and e["action"] == "gc"
                   for e in events)


# --- checkpoint-layer fencing -------------------------------------------------

class TestCkptFence:
    def _tree(self, v=1.0):
        return {"w": jnp.full((8,), v, jnp.float32)}

    def test_fenced_save_records_generation(self, tmp_path):
        d, root = str(tmp_path / "c"), str(tmp_path / "ck")
        m = cluster.ClusterMembership(d, rank=0)
        m.join()
        mgr = ckpt.CheckpointManager(root, fence=m, rank=0,
                                     process_count=1)
        mgr.save(1, self._tree(), block=True)
        mgr.wait()
        manifest = ckpt.read_manifest(ckpt.latest_checkpoint(root))
        assert manifest["generation"] == 0

    def test_zombie_save_is_refused_before_any_byte_lands(self,
                                                          tmp_path):
        d, root = str(tmp_path / "c"), str(tmp_path / "ck")
        events, sink = _collect()
        zombie = cluster.ClusterMembership(d, rank=0, event_sink=sink)
        zombie.join()
        mgr = ckpt.CheckpointManager(root, fence=zombie, rank=0,
                                     process_count=1)
        mgr.save(1, self._tree(), block=True)
        mgr.wait()
        other = cluster.ClusterMembership(d, rank=1)
        other.join()
        other.bump("relaunch")
        mgr.save(2, self._tree(2.0), block=True)
        with pytest.raises(cluster.StaleGenerationError):
            mgr.wait()
        # nothing of step 2 landed: no dir, latest still step 1
        assert not os.path.exists(ckpt.step_dir(root, 2))
        assert ckpt.latest_checkpoint(root) == ckpt.step_dir(root, 1)
        assert any(e["kind"] == "cluster_fence"
                   and e["action"] == "refused_write" for e in events)

    def test_zombie_gc_is_refused(self, tmp_path):
        d, root = str(tmp_path / "c"), str(tmp_path / "ck")
        fresh = cluster.ClusterMembership(d, rank=0)
        fresh.join()
        mgr = ckpt.CheckpointManager(root, fence=fresh, rank=0,
                                     process_count=1, keep=0)
        for s in (1, 2, 3):
            mgr.save(s, self._tree(float(s)), block=True)
        mgr.wait()
        zombie = cluster.ClusterMembership(d, rank=1)
        zombie.join()
        fresh.bump("relaunch")
        with pytest.raises(cluster.StaleGenerationError):
            ckpt.gc_checkpoints(root, keep=1, fence=zombie)
        assert len(ckpt.committed_steps(root)) == 3
        # the CURRENT generation's holder may gc
        removed = ckpt.gc_checkpoints(root, keep=1, fence=fresh)
        assert len(removed) == 2
        assert ckpt.committed_steps(root) == [3]

    def test_commit_manifest_explicit_generation(self, tmp_path):
        d = str(tmp_path / "step_00000001")
        _format.write_process_file(d, 0, [("['w']",
                                           np.zeros(4, np.float32))])
        _format.commit_manifest(d, step=1, process_count=1,
                                generation=7)
        assert _format.read_manifest(d)["generation"] == 7


# --- the in-use marker vs concurrent gc ---------------------------------------

class TestInUseMarker:
    def _committed(self, root, steps):
        for s in steps:
            d = ckpt.step_dir(root, s)
            _format.write_process_file(
                d, 0, [("['w']", np.full(4, float(s), np.float32))])
            _format.commit_manifest(d, step=s, process_count=1)

    def test_marker_pins_directory_against_gc(self, tmp_path):
        root = str(tmp_path)
        self._committed(root, [1, 2, 3])
        oldest = ckpt.step_dir(root, 1)
        with ckpt.checkpoint_in_use(oldest, rank=0):
            assert ckpt.checkpoint_is_in_use(oldest)
            removed = ckpt.gc_checkpoints(root, keep=1)
            assert oldest not in removed
            assert os.path.isdir(oldest)
            # the unpinned middle one went
            assert ckpt.step_dir(root, 2) in removed
        assert not ckpt.checkpoint_is_in_use(oldest)
        removed = ckpt.gc_checkpoints(root, keep=1)
        assert oldest in removed

    def test_marker_ttl_expires(self, tmp_path):
        root = str(tmp_path)
        self._committed(root, [1, 2])
        d = ckpt.step_dir(root, 1)
        marker = os.path.join(d, f"{_format.INUSE_PREFIX}rank00000."
                              f"{os.getpid()}.json")
        with open(marker, "w") as f:
            json.dump({"rank": 0, "pid": 1,
                       "wall_time": time.time() - 1e4}, f)
        # a reader that died long ago cannot pin the dir forever
        assert not ckpt.checkpoint_is_in_use(d, ttl_s=300.0)
        assert ckpt.checkpoint_is_in_use(d, ttl_s=1e6)

    def test_corrupt_marker_counts_as_live(self, tmp_path):
        root = str(tmp_path)
        self._committed(root, [1])
        d = ckpt.step_dir(root, 1)
        with open(os.path.join(d, f"{_format.INUSE_PREFIX}x.json"),
                  "w") as f:
            f.write("{torn")
        assert ckpt.checkpoint_is_in_use(d)

    def test_restore_pins_its_directory(self, tmp_path,
                                        monkeypatch):
        root = str(tmp_path)
        mgr = ckpt.CheckpointManager(root, rank=0, process_count=1)
        mgr.save(1, {"w": jnp.ones(4)}, block=True)
        mgr.wait()
        d = ckpt.latest_checkpoint(root)
        seen = {}
        orig = _format.assemble_arrays

        def spying(ckpt_dir, *a, **kw):
            seen["in_use"] = ckpt.checkpoint_is_in_use(ckpt_dir)
            return orig(ckpt_dir, *a, **kw)

        monkeypatch.setattr(_format, "assemble_arrays", spying)
        mgr.restore({"w": jnp.zeros(4)})
        assert seen["in_use"], \
            "restore gathered without the in-use marker"
        assert not ckpt.checkpoint_is_in_use(d)

    def test_two_process_gc_vs_restore_race(self, tmp_path):
        """A reader in ANOTHER process pins the oldest checkpoint; a
        concurrent gc pass must skip it this round and collect it once
        the reader exits — the mid-read delete race, made
        deterministic."""
        root = str(tmp_path / "ck")
        self._committed(root, [1, 2, 3])
        oldest = ckpt.step_dir(root, 1)
        ready = str(tmp_path / "ready")
        go = str(tmp_path / "go")
        child = textwrap.dedent(f"""
            import os, sys, time
            sys.path.insert(0, {_REPO_ROOT!r})
            from apex_tpu.ckpt import format as f
            with f.checkpoint_in_use({oldest!r}, rank=1):
                open({ready!r}, "w").close()
                t0 = time.monotonic()
                while (not os.path.exists({go!r})
                       and time.monotonic() - t0 < 60):
                    time.sleep(0.02)
        """)
        p = subprocess.Popen([sys.executable, "-c", child],
                             cwd=_REPO_ROOT)
        try:
            t0 = time.monotonic()
            while not os.path.exists(ready):
                assert time.monotonic() - t0 < 60, "reader never pinned"
                time.sleep(0.02)
            removed = ckpt.gc_checkpoints(root, keep=1)
            assert oldest not in removed, \
                "gc deleted a checkpoint a live reader holds"
            assert os.path.isdir(oldest)
            assert _format.read_manifest(oldest)["step"] == 1
        finally:
            open(go, "w").close()
            p.wait(timeout=60)
        removed = ckpt.gc_checkpoints(root, keep=1)
        assert oldest in removed        # the reader left; next round


# --- the recovery coordinator -------------------------------------------------

def _member(d, rank, sink=None):
    m = cluster.ClusterMembership(d, rank=rank, event_sink=sink)
    m.join()
    return m


class TestCoordinator:
    def test_propose_pending_verify_roundtrip(self, tmp_path):
        d = str(tmp_path)
        m0, m1 = _member(d, 0), _member(d, 1)
        c0 = cluster.RecoveryCoordinator(m0, barrier_timeout_s=1.0)
        c1 = cluster.RecoveryCoordinator(m1, barrier_timeout_s=1.0)
        assert not c0.peer_requested()
        c1.propose(action="rewind", step=8, good_step=6)
        assert c0.peer_requested()
        pend = c0.pending()
        assert set(pend) == {1}
        assert pend[1]["good_step"] == 6 and pend[1]["action"] == \
            "rewind"
        assert c0.last_refused == ()

    def test_tampered_intent_is_refused(self, tmp_path):
        d = str(tmp_path)
        events, sink = _collect()
        m0 = _member(d, 0, sink)
        m1 = _member(d, 1)
        c0 = cluster.RecoveryCoordinator(m0, barrier_timeout_s=1.0)
        c1 = cluster.RecoveryCoordinator(m1, barrier_timeout_s=1.0)
        path = c1.propose(action="rewind", step=8, good_step=6)
        rec = json.load(open(path))
        rec["good_step"] = 0            # tamper without re-MACing
        with open(path, "w") as f:
            json.dump(rec, f)
        assert c0.pending() == {}
        assert c0.last_refused == (1,)
        refusals = [e for e in events if e["kind"] == "cluster_fence"]
        assert refusals and refusals[0]["action"] == "refused_intent"
        assert "bad signature" in refusals[0]["reason"]

    def test_split_brain_intent_is_refused(self, tmp_path):
        d = str(tmp_path)
        events, sink = _collect()
        m0 = _member(d, 0, sink)
        m1 = _member(d, 1)
        m1.claim_generation(3)      # the split_brain chaos site
        c0 = cluster.RecoveryCoordinator(m0, barrier_timeout_s=1.0)
        c1 = cluster.RecoveryCoordinator(m1, barrier_timeout_s=1.0)
        c1.propose(action="rewind", step=8, good_step=6)
        # the claimed epoch's intent lands under its OWN prefix — the
        # verifier at the committed generation never even counts it,
        # and a same-prefix forgery is refused by generation check
        assert c0.pending() == {}
        assert not c0.peer_requested()
        # forge the filename down to the committed generation: the
        # payload still claims generation 3 — refused, with evidence
        src = cluster.intent_path(d, 3, 1)
        dst = cluster.intent_path(d, 0, 1)
        os.replace(src, dst)
        assert c0.pending() == {}
        assert c0.last_refused == (1,)
        refusals = [e for e in events if e["kind"] == "cluster_fence"]
        assert refusals[-1]["action"] == "refused_intent"
        assert "claims generation 3" in refusals[-1]["reason"]

    def test_resolve_oldest_good_step_wins_single_bump(self, tmp_path):
        d = str(tmp_path)
        events, sink = _collect()
        m0, m1 = _member(d, 0, sink), _member(d, 1, sink)
        c0 = cluster.RecoveryCoordinator(m0, barrier_timeout_s=5.0)
        c1 = cluster.RecoveryCoordinator(m1, barrier_timeout_s=5.0)
        c0.propose(action="rewind", step=9, good_step=8)
        c1.propose(action="rewind", step=9, good_step=6)
        d0 = c0.resolve(expect_ranks=[0, 1])    # leader: bumps
        d1 = c1.resolve(expect_ranks=[0, 1])    # follower: observes
        for dec in (d0, d1):
            assert dec.action == "rewind"
            assert dec.target_step == 6         # oldest good wins
            assert dec.ranks == (0, 1) and dec.leader == 0
            assert dec.generation == 0 and dec.new_generation == 1
        assert cluster.read_generation(d) == 1
        bumps = [e for e in events
                 if e["kind"] == "cluster_generation"
                 and e["action"] == "bump"]
        assert len(bumps) == 1, "generation must bump exactly once"
        assert m0.generation == 1 and m1.generation == 1

    def test_escalate_dominates_and_none_good_forces_it(self,
                                                        tmp_path):
        d = str(tmp_path)
        m0, m1 = _member(d, 0), _member(d, 1)
        c0 = cluster.RecoveryCoordinator(m0, barrier_timeout_s=5.0)
        c1 = cluster.RecoveryCoordinator(m1, barrier_timeout_s=5.0)
        c0.propose(action="rewind", step=9, good_step=8)
        c1.propose(action="escalate", step=9, good_step=6)
        dec = c0.resolve(expect_ranks=[0, 1], bump=False)
        assert dec.action == "escalate" and dec.target_step is None

        d2 = str(tmp_path / "none")
        m0b, m1b = _member(d2, 0), _member(d2, 1)
        c0b = cluster.RecoveryCoordinator(m0b, barrier_timeout_s=5.0)
        c1b = cluster.RecoveryCoordinator(m1b, barrier_timeout_s=5.0)
        c0b.propose(action="rewind", step=9, good_step=8)
        c1b.propose(action="rewind", step=9, good_step=None)
        dec = c0b.resolve(expect_ranks=[0, 1], bump=False)
        assert dec.action == "escalate", \
            "a rank with NO restorable checkpoint forces escalation"

    def test_barrier_timeout_proceeds_with_present_intents(self,
                                                           tmp_path):
        d = str(tmp_path)
        events, sink = _collect()
        m0 = _member(d, 0, sink)
        c0 = cluster.RecoveryCoordinator(m0, barrier_timeout_s=0.3)
        c0.propose(action="rewind", step=9, good_step=4)
        t0 = time.monotonic()
        dec = c0.resolve(expect_ranks=[0, 1], bump=False)
        assert time.monotonic() - t0 < 10.0
        assert dec.action == "rewind" and dec.target_step == 4
        assert dec.ranks == (0,)
        timeouts = [e for e in events
                    if e.get("action") == "barrier_timeout"]
        assert timeouts and timeouts[0]["missing"] == [1]

    def test_zero_intents_raises_coordination_error(self, tmp_path):
        m0 = _member(str(tmp_path), 0)
        c0 = cluster.RecoveryCoordinator(m0, barrier_timeout_s=0.2)
        with pytest.raises(cluster.CoordinationError):
            c0.resolve(expect_ranks=[1])

    def test_invalid_action_refused_at_the_door(self, tmp_path):
        m0 = _member(str(tmp_path), 0)
        c0 = cluster.RecoveryCoordinator(m0)
        with pytest.raises(ValueError):
            c0.propose(action="reboot", step=1, good_step=0)


# --- coordinated rewind through GuardPolicy (in-process, 2 logical ranks) -----

class TestCoordinatedRewindInProcess:
    """The deterministic-resolution property, driven through real
    GuardPolicy/CheckpointManager instances for two logical ranks over
    one shared cluster directory — the multi-PROCESS acceptance twin is
    TestCoordinatedRewindAcceptance."""

    def test_both_ranks_land_on_the_common_target(self, tmp_path):
        d = str(tmp_path / "cluster")
        events, sink = _collect()
        members = [_member(d, r, sink) for r in (0, 1)]
        coords = [cluster.RecoveryCoordinator(m, barrier_timeout_s=10.0)
                  for m in members]
        mgrs, policies, likes = [], [], []
        for r in (0, 1):
            mgr = ckpt.CheckpointManager(
                str(tmp_path / f"ck_r{r}"), fence=members[r], rank=0,
                process_count=1, keep=0)
            # rank-local histories: rank 1's newest checkpoint captured
            # NaN params (the rank-asymmetric corruption), rank 0's is
            # healthy — so their newest GOOD steps differ (8 vs 6)
            for s in (4, 6, 8):
                bad = (r == 1 and s == 8)
                w = np.full((4,), np.nan if bad else float(s),
                            np.float32)
                mgr.save(s, {"w": jnp.asarray(w)},
                         extra={"cursor": {"index": s}}, block=True)
                mgr.wait()
            mgrs.append(mgr)
            policies.append(guard.GuardPolicy(manager=mgr))
            likes.append({"w": jnp.zeros((4,), jnp.float32)})
        assert policies[0].probe_good_step(likes[0]) == 8
        assert policies[1].probe_good_step(likes[1]) == 6

        src = _FakeCursorSource()
        # rank 1 detected the corruption; rank 0 is healthy but joins
        coords[1].propose(action="rewind", step=9,
                          good_step=policies[1].probe_good_step(
                              likes[1]))
        assert coords[0].peer_requested()
        dec0, res0 = coords[0].run_round(policies[0], 9, likes[0], src,
                                         expect_ranks=[0, 1])
        dec1, res1 = coords[1].run_round(policies[1], 9, likes[1], src,
                                         expect_ranks=[0, 1])
        for dec in (dec0, dec1):
            assert dec.action == "rewind" and dec.target_step == 6
            assert dec.new_generation == 1
        # BOTH ranks restored step 6 — rank 0 honored the cluster
        # target over its own newer good checkpoint
        for r, res in ((0, res0), (1, res1)):
            restored, manifest = res
            assert manifest["step"] == 6
            assert np.allclose(np.asarray(restored["w"]), 6.0)
        assert cluster.read_generation(d) == 1
        bumps = [e for e in events
                 if e["kind"] == "cluster_generation"
                 and e["action"] == "bump"]
        assert len(bumps) == 1
        # the whole exchange validates as a cluster event stream
        lines = [json.dumps(e) for e in events]
        assert not check_cluster_lines(lines)

    def test_unloadable_agreed_target_escalates_not_diverges(
            self, tmp_path):
        """A rank that cannot restore the AGREED target must escalate
        — rewind's fallback chain restoring an older step would put
        this rank on a different history than its peers, the exact
        split-brain the round exists to prevent."""
        d = str(tmp_path / "cluster")
        _, sink = _collect()
        members = [_member(d, r, sink) for r in (0, 1)]
        coords = [cluster.RecoveryCoordinator(m, barrier_timeout_s=10.0)
                  for m in members]
        mgrs, policies, likes = [], [], []
        for r in (0, 1):
            mgr = ckpt.CheckpointManager(
                str(tmp_path / f"ck_r{r}"), fence=members[r], rank=0,
                process_count=1, keep=0)
            # rank 1's newest (8) is NaN -> its good step is 6; rank 0
            # is all-healthy (good step 8)
            for s in (4, 6, 8):
                bad = (r == 1 and s == 8)
                w = np.full((4,), np.nan if bad else float(s),
                            np.float32)
                mgr.save(s, {"w": jnp.asarray(w)},
                         extra={"cursor": {"index": s}}, block=True)
                mgr.wait()
            mgrs.append(mgr)
            policies.append(guard.GuardPolicy(manager=mgr))
            likes.append({"w": jnp.zeros((4,), jnp.float32)})
        # truncate rank 0's copy of the agreed target (step 6) AFTER
        # it voted: the hash check rejects it at restore time and the
        # fallback chain would silently land on step 4
        tgt = _format.step_dir(str(tmp_path / "ck_r0"), 6)
        proc = os.path.join(tgt, "proc00000.npz")
        with open(proc, "r+b") as f:
            f.truncate(16)
        coords[1].propose(action="rewind", step=9,
                          good_step=policies[1].probe_good_step(
                              likes[1]))
        src = _FakeCursorSource()
        with pytest.raises(guard.GuardEscalation) as exc:
            coords[0].run_round(policies[0], 9, likes[0], src,
                                expect_ranks=[0, 1])
        assert "coordinated rewind diverged" in str(exc.value)
        assert "agreed on step 6" in str(exc.value)


class _FakeCursorSource:
    """Minimal GuardPolicy.rewind source: cursor only, no decode."""

    def __init__(self):
        self._index = 9

    def cursor_index(self):
        return self._index

    def load_state(self, state):
        self._index = int(state.get("index", 0)) if isinstance(
            state, dict) else 0

    def skip_batches(self, n):
        self._index += int(n)


# --- collective deadline ------------------------------------------------------

class _FakeTracer:
    def __init__(self):
        self.probe = None

    def in_flight_collective_age(self):
        return self.probe


class _TripSpy:
    def __init__(self):
        self.reasons = []

    def trip(self, reason):
        self.reasons.append(reason)


class TestCollectiveDeadline:
    def test_slow_collective_does_not_fire(self):
        tr = _FakeTracer()
        cd = cluster.CollectiveDeadline(tr, deadline_s=10.0)
        assert cd.poll_once() is None          # nothing open
        tr.probe = ("ddp/sync_gradients", 2.0)
        assert cd.poll_once() is None          # open but young
        assert cd.fired == 0

    def test_hung_collective_fires_once_per_instance(self):
        tr = _FakeTracer()
        spy = _TripSpy()
        events, sink = _collect()
        cd = cluster.CollectiveDeadline(tr, deadline_s=5.0,
                                        escalation=spy,
                                        event_sink=sink,
                                        generation=lambda: 2)
        # the third probe element is the span's STABLE start stamp —
        # the instance identity the fire-once logic keys on (a
        # re-derived now−age would drift between polls)
        tr.probe = ("ddp/sync_gradients", 7.5, 100.0)
        ev = cd.poll_once()
        assert ev is not None
        assert ev["action"] == "collective_hang"
        assert ev["collective"] == "ddp/sync_gradients"
        assert ev["generation"] == 2
        assert spy.reasons == ["collective:ddp/sync_gradients"]
        # the SAME span instance (age grows, start fixed) never refires
        tr.probe = ("ddp/sync_gradients", 8.5, 100.0)
        assert cd.poll_once() is None
        assert cd.fired == 1
        # a NEW instance (fresh start: the old one closed) re-arms
        tr.probe = None
        assert cd.poll_once() is None
        tr.probe = ("ddp/sync_gradients", 9.0, 200.0)
        assert cd.poll_once() is not None
        assert cd.fired == 2
        assert not check_cluster_lines([json.dumps(e) for e in events])

    def test_tracer_reports_open_collective_age(self):
        tracer = trace.Tracer()
        with tracer:
            assert tracer.in_flight_collective_age() is None
            with trace.step(0):
                with trace.span("fwd"):
                    pass            # a plain span is not a collective
                assert tracer.in_flight_collective_age() is None
                with trace.span("ddp/sync_gradients",
                                kind="collective"):
                    probe = tracer.in_flight_collective_age()
                    assert probe is not None
                    name, age, start = probe
                    assert name == "ddp/sync_gradients"
                    assert 0.0 <= age < 60.0
                    # the start stamp is stable across polls — the
                    # fire-once instance identity
                    assert tracer.in_flight_collective_age()[2] == \
                        start
                assert tracer.in_flight_collective_age() is None

    def test_daemon_lifecycle(self):
        tr = _FakeTracer()
        cd = cluster.CollectiveDeadline(tr, deadline_s=0.05,
                                        poll_s=0.02)
        tr.probe = ("zero/grad_scatter", 1.0)
        with cd:
            t0 = time.monotonic()
            while cd.fired == 0 and time.monotonic() - t0 < 10.0:
                time.sleep(0.02)
        assert cd.fired >= 1

    def test_enable_crash_dumps_returns_deadline_tier(self, tmp_path):
        from apex_tpu import parallel
        out = parallel.enable_crash_dumps(
            str(tmp_path / "crash.jsonl"),
            collective_deadline_s=60.0)
        assert len(out) == 4
        tracer, recorder, wd, deadline = out
        assert isinstance(deadline, cluster.CollectiveDeadline)
        deadline.stop()
        recorder.uninstall()


# --- generation-scoped heartbeats / straggler ---------------------------------

class _FakeStepTrace:
    def __init__(self, step, dur_ms, spans=None):
        self.step = step
        self.spans = []
        self._dur = dur_ms
        self._spans = spans or {}

    def span_ms(self):
        return dict(self._spans)

    @property
    def dur_ms(self):
        return self._dur


def _beat(directory, rank, steps, dur_ms, generation=None):
    w = _straggler.HeartbeatWriter(directory, rank=rank,
                                   generation=generation)
    for s in steps:
        w.on_step(_FakeStepTrace(s, dur_ms))
    return w


class TestHeartbeatGeneration:
    def test_generation_scoped_read(self, tmp_path):
        d = str(tmp_path)
        _beat(d, 0, [1, 2], 10.0, generation=1)
        _beat(d, 1, [1, 2], 10.0)               # untagged = gen 0
        allb = _straggler.read_heartbeats(d)
        assert set(allb) == {0, 1}
        g1 = _straggler.read_heartbeats(d, generation=1)
        assert set(g1) == {0}
        g0 = _straggler.read_heartbeats(d, generation=0)
        assert set(g0) == {1}
        assert g1[0][1]["generation"] == 1

    def test_set_generation_retags_across_a_bump(self, tmp_path):
        d = str(tmp_path)
        w = _straggler.HeartbeatWriter(d, rank=0, generation=0)
        w.on_step(_FakeStepTrace(1, 10.0))
        w.set_generation(1)
        w.on_step(_FakeStepTrace(2, 10.0))
        g1 = _straggler.read_heartbeats(d, generation=1)
        assert set(g1[0]) == {2}

    def test_gc_stale_heartbeats_keeps_survivors(self, tmp_path):
        d = str(tmp_path)
        _beat(d, 0, [1, 2], 10.0, generation=0)     # dead old rank
        surv = _straggler.HeartbeatWriter(d, rank=1, generation=0)
        surv.on_step(_FakeStepTrace(1, 10.0))
        surv.set_generation(1)
        surv.on_step(_FakeStepTrace(2, 10.0))       # survivor crossed
        removed = _straggler.gc_stale_heartbeats(d, 1)
        assert removed == [_straggler.heartbeat_path(d, 0)]
        assert set(_straggler.read_heartbeats(d)) == {1}

    def test_detector_ignores_stale_generation_laggard(self, tmp_path):
        d = str(tmp_path)
        # generation-0 history says rank 2 lags badly; the cluster is
        # at generation 1 where every rank is healthy
        for r in (0, 1):
            _beat(d, r, range(8), 10.0, generation=0)
        _beat(d, 2, range(8), 500.0, generation=0)
        for r in (0, 1, 2):
            _beat(d, r, range(8, 16), 10.0, generation=1)
        det = _straggler.StragglerDetector(d, window=8, hysteresis=2,
                                           generation=1)
        assert det.check() == []
        stale_view = _straggler.StragglerDetector(d, window=8,
                                                  hysteresis=2,
                                                  generation=0)
        flagged = stale_view.check()
        assert flagged and flagged[0].rank == 2

    def test_dead_rank_is_not_a_silent_rank_after_gc(self, tmp_path):
        """The satellite bug: a dead rank's last heartbeat read as a
        silent rank forever. After relaunch hygiene (gc + generation
        scoping) the detector simply no longer sees the dead rank."""
        d = str(tmp_path)
        _beat(d, 0, range(8), 10.0, generation=0)   # died in gen 0
        for r in (1, 2):
            w = _beat(d, r, range(8), 10.0, generation=0)
            w.set_generation(1)
            for s in range(8, 16):
                w.on_step(_FakeStepTrace(s, 10.0))
        _straggler.gc_stale_heartbeats(d, 1)
        beats = _straggler.read_heartbeats(d, generation=1)
        assert set(beats) == {1, 2}
        det = _straggler.StragglerDetector(d, window=8, generation=1)
        assert det.check() == []


# --- elastic_run v2 relaunch hygiene ------------------------------------------

class TestElasticRelaunchHygiene:
    def test_relaunch_bumps_and_cleans(self, tmp_path):
        d, hb = str(tmp_path / "c"), str(tmp_path / "hb")
        stale = cluster.LeaseWriter(d, rank=1)
        stale.acquire(0)
        _beat(hb, 1, [1, 2], 10.0, generation=0)
        events, sink = _collect()
        gen = cluster.relaunch(d, reason="elastic_restart:1",
                               heartbeat_dir=hb, event_sink=sink)
        assert gen == 1
        assert cluster.read_generation(d) == 1
        assert cluster.read_leases(d) == {}, \
            "relaunch must leave a clean lease table (incl. its own)"
        assert _straggler.read_heartbeats(hb) == {}
        assert not check_cluster_lines([json.dumps(e) for e in events])

    def test_elastic_run_fences_each_restart(self, tmp_path):
        from apex_tpu.parallel.launch import elastic_run
        d, hb = str(tmp_path / "c"), str(tmp_path / "hb")
        seen, events = [], []

        def train(world, attempt):
            seen.append((world, attempt, cluster.read_generation(d)))
            if attempt == 0:
                # the failing attempt leaves the stale debris a real
                # dead rank leaves: an EXPIRED rank-0 lease and a
                # heartbeat file (rank 0 because the controller's own
                # default rank collides with it — the report must
                # still see the dead member, not overwrite its lease)
                dead = cluster.LeaseWriter(d, rank=0)
                dead.acquire(0)
                dead.expire_now()
                _beat(hb, 0, [1], 10.0, generation=0)
                raise ckpt.PreemptionError("rank died")
            assert cluster.read_leases(d) == {}
            assert _straggler.read_heartbeats(hb) == {}

        elastic_run(train, world_sizes=[8, 4], cluster_dir=d,
                    heartbeat_dir=hb, event_sink=events.append)
        assert seen == [(8, 0, 0), (4, 1, 1)], \
            "the restart must run under a freshly bumped generation"
        # the dead rank was REPORTED (lease observed expired), not
        # silently overwritten by the controller's own lease
        expires = [e for e in events
                   if e["kind"] == "cluster_lease"
                   and e["action"] == "expire"]
        assert expires and expires[0]["expired_rank"] == 0


# --- event schema + logger channel --------------------------------------------

class TestClusterSchema:
    def _valid(self):
        return [
            {"kind": "cluster_lease", "action": "acquire",
             "generation": 0, "rank": 0, "ttl_s": 30.0,
             "wall_time": 1.0, "path": "/c/lease.rank00000.json"},
            {"kind": "cluster_generation", "action": "bump",
             "generation": 1, "prev_generation": 0, "rank": 0,
             "reason": "coordinated_rewind", "wall_time": 2.0},
            {"kind": "cluster_fence", "action": "refused_commit",
             "generation": 0, "current_generation": 1, "rank": 1,
             "what": "commit", "path": None, "step": None,
             "reason": None, "wall_time": 3.0},
            {"kind": "cluster_coord", "action": "resolve",
             "generation": 1, "new_generation": 2, "rank": 0,
             "decided": "rewind", "target_step": 6, "ranks": [0, 1],
             "leader": 0, "n_refused": 0, "timed_out": False,
             "wall_time": 4.0},
            {"kind": "cluster_coord", "action": "collective_hang",
             "generation": 2, "rank": 1,
             "collective": "ddp/sync_gradients", "age_s": 130.0,
             "deadline_s": 120.0, "wall_time": 5.0},
        ]

    def test_valid_stream_passes(self):
        lines = [json.dumps(e) for e in self._valid()]
        assert not check_cluster_lines(lines)

    def test_negative_twins(self):
        ok = self._valid()

        def bad(i, **kw):
            rec = dict(ok[i])
            rec.update(kw)
            return [json.dumps(rec)]

        # unknown kind / unknown action
        assert check_cluster_lines(
            ['{"kind": "cluster_party", "action": "acquire", '
             '"generation": 0}'])
        assert check_cluster_lines(bad(0, action="evict"))
        # a fence action on a lease record
        assert check_cluster_lines(bad(0, action="refused_commit"))
        # missing required keys
        assert check_cluster_lines(
            ['{"kind": "cluster_fence", "action": "refused_commit", '
             '"generation": 0}'])        # no current_generation
        # negative / boolean generation
        assert check_cluster_lines(bad(1, generation=-1))
        assert check_cluster_lines(bad(1, generation=True))
        # a bump that goes backwards
        assert check_cluster_lines(bad(1, generation=0,
                                       prev_generation=3))
        # non-monotone bumps ACROSS the stream
        seq = [json.dumps(dict(ok[1], generation=3,
                               prev_generation=2)),
               json.dumps(dict(ok[1], generation=1,
                               prev_generation=0))]
        assert check_cluster_lines(seq)
        # null in a non-nullable field
        assert check_cluster_lines(bad(2, generation=None))
        # target_step IS nullable on an escalate resolve
        assert not check_cluster_lines(bad(3, decided="escalate",
                                           target_step=None))
        # ranks must be a list of non-negative ints
        assert check_cluster_lines(bad(3, ranks=[0, -1]))
        assert check_cluster_lines(bad(3, ranks="0,1"))
        # negative deadline
        assert check_cluster_lines(bad(4, deadline_s=-1.0))

    def test_logger_channel_is_unbuffered_and_nulls_nonfinite(
            self, tmp_path):
        path = str(tmp_path / "cluster.jsonl")
        logger = monitor.MetricsLogger(
            sinks=[], cluster_sink=monitor.JSONLSink(path),
            flush_every=1000)       # buffering would hide a crash loss
        logger.record_cluster({"kind": "cluster_coord",
                               "action": "collective_hang",
                               "generation": 0, "rank": 1,
                               "collective": "ddp/sync_gradients",
                               "age_s": 130.0,
                               "deadline_s": float("nan"),
                               "wall_time": time.time()})
        # readable BEFORE close: the refusal survives the zombie exit
        lines = open(path).read().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["deadline_s"] is None, "non-finite must be nulled"
        logger.close()
        assert not check_cluster_lines(lines)

    def test_membership_events_validate_end_to_end(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        logger = monitor.MetricsLogger(
            sinks=[], cluster_sink=monitor.JSONLSink(path))
        d = str(tmp_path / "c")
        m = cluster.ClusterMembership(d, rank=0,
                                      event_sink=logger.record_cluster)
        m.join()
        m.heartbeat()
        m.bump("test")
        m.lease.expire_now()
        m.expired_ranks()
        m.leave()
        logger.close()
        lines = open(path).read().splitlines()
        assert not check_cluster_lines(lines)
        kinds = [json.loads(l)["kind"] for l in lines]
        assert kinds.count("cluster_generation") == 1
        assert kinds.count("cluster_lease") >= 3   # acquire/expire/rel


# --- chaos sites --------------------------------------------------------------

class TestClusterChaosSites:
    def test_sites_registered_and_validated(self):
        assert guard.chaos.SITES["cluster"] == (
            "lease_expire", "zombie_resume", "split_brain")
        plan = guard.FaultPlan(seed=1).add(3, "cluster",
                                           "lease_expire")
        rt = guard.FaultPlan.from_json(plan.to_json())
        assert rt.at(3, 0, "cluster").kind == "lease_expire"
        with pytest.raises(ValueError):
            guard.FaultPlan(seed=1).add(3, "cluster", "explode")

    def test_lease_expire_site(self, tmp_path):
        d = str(tmp_path)
        m = cluster.ClusterMembership(d, rank=0, ttl_s=60.0)
        m.join()
        plan = guard.FaultPlan(seed=1).add(2, "cluster",
                                           "lease_expire")
        h = guard.ChaosHarness(plan)
        state = {"w": np.ones(2)}
        h.post_step(1, state, membership=m)
        assert m.expired_ranks() == []
        h.post_step(2, state, membership=m)
        assert m.expired_ranks() == [0]
        assert h.injected == [(2, "cluster", "lease_expire")]

    def test_split_brain_site(self, tmp_path):
        d = str(tmp_path)
        m = cluster.ClusterMembership(d, rank=1, ttl_s=60.0)
        m.join()
        plan = guard.FaultPlan(seed=1).add(2, "cluster", "split_brain",
                                           rank=1)
        h = guard.ChaosHarness(plan, rank=1)
        h.post_step(2, {"w": np.ones(2)}, membership=m)
        assert m.generation == 1           # claimed, never committed
        assert cluster.read_generation(d) == 0
        with pytest.raises(cluster.StaleGenerationError):
            m.bump("post-split")           # the CAS refuses the claim

    def test_cluster_fault_requires_membership(self, tmp_path):
        plan = guard.FaultPlan(seed=1).add(2, "cluster",
                                           "lease_expire")
        h = guard.ChaosHarness(plan)
        with pytest.raises(ValueError):
            h.post_step(2, {"w": np.ones(2)})


# --- bench backend guard ------------------------------------------------------

class TestBenchBackendGuard:
    def test_backend_failure_emits_structured_row(self, monkeypatch,
                                                  capsys):
        import bench

        def dead_probe():
            raise RuntimeError("tunnel down: no TPU backend")

        monkeypatch.setattr(bench, "_backend_probe", dead_probe)
        called = []
        rc = bench.run_with_backend_guard(lambda: called.append(1))
        assert rc == bench.BACKEND_FAILURE_EXIT_CODE == 13
        assert not called, "the mode must not run on a dead backend"
        row = json.loads(capsys.readouterr().out.strip())
        assert row["parsed"] is None
        assert "tunnel down" in row["failure_reason"]
        assert row["rc"] == 13

    def test_healthy_backend_runs_the_mode(self, monkeypatch):
        import bench
        monkeypatch.setattr(bench, "_backend_probe", lambda: ["cpu"])
        called = []
        assert bench.run_with_backend_guard(
            lambda: called.append(1)) == 0
        assert called == [1]

    def test_sentinel_skips_the_failure_row_with_its_reason(self,
                                                            tmp_path):
        from apex_tpu.prof import sentinel
        p = str(tmp_path / "BENCH_r06.json")
        with open(p, "w") as f:
            json.dump({"parsed": None, "rc": 13,
                       "failure_reason": "backend init failed: "
                                         "tunnel down"}, f)
        rows = sentinel.load_rows([p])
        assert len(rows) == 1
        assert rows[0]["row"] is None
        assert "tunnel down" in rows[0]["note"]

    def test_transient_probe_flake_is_retried_and_absorbed(
            self, monkeypatch):
        """Round 5's failure mode: the tunnel blips once — the bounded
        jittered retry must absorb it instead of losing the round."""
        import bench
        from apex_tpu.utils import backoff
        monkeypatch.setattr(backoff, "backoff_sleep",
                            lambda *a, **k: 0.0)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("tunnel blip")
            return ["cpu"]

        monkeypatch.setattr(bench.jax, "devices", flaky)
        ran = []
        assert bench.run_with_backend_guard(lambda: ran.append(1)) == 0
        assert len(calls) == 3 and ran == [1]

    def test_failure_row_records_probe_attempts(self, monkeypatch,
                                                capsys):
        import bench
        from apex_tpu.utils import backoff
        monkeypatch.setattr(backoff, "backoff_sleep",
                            lambda *a, **k: 0.0)
        calls = []

        def dead():
            calls.append(1)
            raise RuntimeError("tunnel down for good")

        monkeypatch.setattr(bench.jax, "devices", dead)
        rc = bench.run_with_backend_guard(lambda: None)
        assert rc == 13
        assert len(calls) == bench.BACKEND_PROBE_ATTEMPTS == 3
        row = json.loads(capsys.readouterr().out.strip())
        assert row["attempts"] == 3

    def test_sentinel_note_names_the_attempt_count(self, tmp_path):
        from apex_tpu.prof import sentinel
        p = str(tmp_path / "BENCH_r07.json")
        with open(p, "w") as f:
            json.dump({"parsed": None, "rc": 13, "attempts": 3,
                       "failure_reason": "backend init failed: "
                                         "tunnel down"}, f)
        rows = sentinel.load_rows([p])
        assert "3 probe attempts" in rows[0]["note"]
        assert "tunnel down" in rows[0]["note"]


# --- acceptance: the SIGSTOP zombie is fenced ---------------------------------

_ZOMBIE_CHILD = textwrap.dedent("""
    import os, signal, sys, time
    import jax
    from apex_tpu import _compat
    jax.config.update("jax_platforms", "cpu")
    _compat.request_cpu_devices(4)

    root, cluster_dir, barrier, events = sys.argv[1:5]
    rank = int(sys.argv[5])

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu import ckpt, cluster, monitor, trace

    mesh = Mesh(np.array(jax.devices()), ("data",))

    def beat(r, i):
        open(os.path.join(barrier, f"beat_{r}_{i}"), "w").close()

    def wait_peer(r, i):
        p = os.path.join(barrier, f"beat_{r}_{i}")
        while not os.path.exists(p):   # the "collective": wedges when
            time.sleep(0.02)           # the peer pauses or dies

    logger = monitor.MetricsLogger(
        sinks=[], cluster_sink=monitor.JSONLSink(events))
    member = cluster.ClusterMembership(
        cluster_dir, rank=rank, ttl_s=2.0,
        event_sink=logger.record_cluster)
    assert member.join() == 0

    mgr = ckpt.CheckpointManager(root, fence=member, rank=rank,
                                 process_count=2, keep=0,
                                 barrier_timeout_s=60)
    policy = ckpt.EscalationPolicy(mgr)        # exit mode, code 75
    wd = None
    if rank == 0:
        wd = trace.HangWatchdog(deadline_s=4.0, poll_s=0.2,
                                on_stall=policy).start()

    np_rng = np.random.RandomState(0)
    w = jnp.asarray(np_rng.randn(16, 1), jnp.float32)
    xg = np_rng.randn(32, 16).astype("float32")
    yg = np_rng.randn(32, 1).astype("float32")

    def stepf(w, x, y):
        g = jax.grad(lambda w: jnp.mean((x @ w - y) ** 2))(w)
        g = jax.lax.pmean(g, "data")
        return w - 0.1 * g, jnp.mean((x @ w - y) ** 2)

    spmd = jax.jit(jax.shard_map(
        stepf, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))

    for i in range(1, 10):
        w, loss = spmd(w, xg, yg)
        float(np.asarray(loss))
        member.heartbeat()
        beat(rank, i)
        if rank == 1 and i == 4:
            print("RANK1 PAUSING", flush=True)
            os.kill(os.getpid(), signal.SIGSTOP)
            # ---- resumed: a zombie of generation 0. The world moved
            # on (escalation + relaunch bumped to generation 1); every
            # late mutation must be refused by the fence. ----
            print("RANK1 RESUMED", flush=True)
            refusals = 0
            try:
                mgr.save(99, {"w": w, "i": jnp.int32(99)}, block=True)
                mgr.wait()
                print("ZOMBIE COMMITTED", flush=True)
            except cluster.StaleGenerationError:
                refusals += 1
                print("ZOMBIE WRITE FENCED", flush=True)
            try:
                ckpt.gc_checkpoints(root, keep=1, fence=member)
                print("ZOMBIE DELETED", flush=True)
            except cluster.StaleGenerationError:
                refusals += 1
                print("ZOMBIE DELETE FENCED", flush=True)
            logger.close()
            sys.exit(88 if refusals == 2 else 1)
        wait_peer(1 - rank, i)
        mgr.snapshot(i, {"w": w, "i": jnp.int32(i)})
        if i in (1, 3):
            mgr.save(i, {"w": w, "i": jnp.int32(i)}, block=True)
        if wd is not None:
            wd.notify_step(i)
        print(f"STEP {i} rank {rank}", flush=True)
    print("FINISHED WITHOUT ESCALATION", flush=True)
""")

_NEWGEN_CHILD = textwrap.dedent("""
    import os, sys
    import jax
    from apex_tpu import _compat
    jax.config.update("jax_platforms", "cpu")
    _compat.request_cpu_devices(4)
    jax.config.update("jax_default_matmul_precision", "highest")

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from apex_tpu import ckpt, cluster

    root, cluster_dir = sys.argv[1:3]
    mesh = Mesh(np.array(jax.devices()), ("data",))
    rep = NamedSharding(mesh, P())

    member = cluster.ClusterMembership(cluster_dir, rank=0)
    gen = member.join()
    assert gen == 1, f"relaunch should have bumped: {gen}"

    mgr = ckpt.CheckpointManager(root, fence=member, rank=0,
                                 process_count=1, keep=4)
    like = {"w": jax.device_put(jnp.zeros((16, 1), jnp.float32), rep),
            "i": jax.device_put(jnp.int32(0), rep)}
    restored, manifest = mgr.restore(like)
    print("RESTORED_STEP", manifest["step"], flush=True)
    w = restored["w"]

    np_rng = np.random.RandomState(0)
    xg = np_rng.randn(32, 16).astype("float32")
    yg = np_rng.randn(32, 1).astype("float32")

    def stepf(w, x, y):
        g = jax.grad(lambda w: jnp.mean((x @ w - y) ** 2))(w)
        g = jax.lax.pmean(g, "data")
        return w - 0.1 * g, jnp.mean((x @ w - y) ** 2)

    spmd = jax.jit(jax.shard_map(
        stepf, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))

    for k in range(3):
        w, loss = spmd(w, xg, yg)
        print("LOSS", float(np.asarray(loss)).hex(), flush=True)
        member.heartbeat()
        mgr.save(101 + k, {"w": w, "i": jnp.int32(101 + k)},
                 block=True)
    mgr.wait()
    latest = ckpt.latest_checkpoint(root)
    print("LATEST", os.path.basename(latest),
          ckpt.read_manifest(latest)["generation"], flush=True)
""")


class TestZombieAcceptance:
    @pytest.mark.slow          # 4 subprocess jax bring-ups (~1 min);
    #                            the in-process twin runs in smoke via
    #                            scripts/cluster_audit.py --cpu8
    def test_sigstop_zombie_commit_is_fenced(self, tmp_path):
        """2 procs × 4 CPU devices. Rank 1 SIGSTOPs itself mid-run;
        rank 0 wedges on the cross-rank sync, its watchdog escalates
        (checkpoint + exit 75) and the controller relaunches under a
        bumped generation. Rank 1 is then resumed — a live zombie of
        generation 0 — and its late checkpoint write AND retention
        delete are both REFUSED by the fence, with the refusals in the
        cluster event stream; the generation-1 run's latest_checkpoint
        and training losses are bitwise identical to a twin run the
        zombie never touched."""
        rootA = str(tmp_path / "rootA")
        clusterA = str(tmp_path / "clusterA")
        barrier = str(tmp_path / "barrier")
        os.makedirs(barrier)
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "TF_CPP_MIN_LOG_LEVEL": "2"}
        procs, outs = [], ["", ""]
        for rank in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _ZOMBIE_CHILD, rootA, clusterA,
                 barrier, str(tmp_path / f"ev_rank{rank}.jsonl"),
                 str(rank)],
                env=env, cwd=_REPO_ROOT, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        try:
            try:
                outs[0], _ = procs[0].communicate(timeout=240)
            except subprocess.TimeoutExpired:
                pytest.fail("rank 0 never escalated:\n" + outs[0])
            if "STEP 1" not in outs[0]:
                pytest.fail(f"rank 0 never completed a step:"
                            f"\n{outs[0]}")
            # rank 0 escalated after the peer paused
            assert procs[0].returncode == ckpt.ESCALATION_EXIT_CODE, \
                outs[0]
            latest = ckpt.latest_checkpoint(rootA)
            assert latest is not None
            esc_step = ckpt.read_manifest(latest)["step"]
            assert esc_step == 4, esc_step

            # the elastic_run v2 hygiene pass: fence out generation 0.
            # Twin trees let the zombie-exposed run be compared
            # bitwise against a run the zombie can never touch.
            rootB = str(tmp_path / "rootB")
            clusterB = str(tmp_path / "clusterB")
            shutil.copytree(rootA, rootB)
            shutil.copytree(clusterA, clusterB)
            assert cluster.relaunch(clusterA) == 1
            assert cluster.relaunch(clusterB) == 1

            def newgen(root, cdir):
                r = subprocess.run(
                    [sys.executable, "-c", _NEWGEN_CHILD, root, cdir],
                    env=env, cwd=_REPO_ROOT, capture_output=True,
                    text=True, timeout=240)
                assert r.returncode == 0, r.stdout + r.stderr
                return r.stdout.splitlines()

            oracle = newgen(rootB, clusterB)

            # resume the zombie BEFORE the generation-1 run over
            # rootA: its late write/delete race the new epoch and
            # must both be refused
            os.kill(procs[1].pid, signal.SIGCONT)
            outs[1], _ = procs[1].communicate(timeout=240)
            assert procs[1].returncode == 88, outs[1]
            assert "ZOMBIE WRITE FENCED" in outs[1]
            assert "ZOMBIE DELETE FENCED" in outs[1]
            assert "ZOMBIE COMMITTED" not in outs[1]
            assert not os.path.exists(ckpt.step_dir(rootA, 99)), \
                "the zombie's write left debris"

            exposed = newgen(rootA, clusterA)
            assert exposed == oracle, (
                "the zombie changed the generation-1 run:\n"
                f"exposed={exposed}\noracle={oracle}")
            assert exposed[0] == f"RESTORED_STEP {esc_step}"
            assert exposed[-1].startswith("LATEST step_00000103 1")

            # the refusals are ON the zombie's cluster event stream
            ev = open(str(tmp_path / "ev_rank1.jsonl")
                      ).read().splitlines()
            assert not check_cluster_lines(ev)
            fences = [json.loads(l) for l in ev
                      if json.loads(l)["kind"] == "cluster_fence"]
            acts = {f["action"] for f in fences}
            assert acts == {"refused_write", "refused_delete"}, acts
            for f in fences:
                assert f["generation"] == 0
                assert f["current_generation"] == 1
        finally:
            for p in procs:
                if p.poll() is None:
                    try:
                        os.kill(p.pid, signal.SIGCONT)
                    except OSError:
                        pass
                    p.kill()
                    p.wait()


# --- acceptance: coordinated rewind, multi-process, bitwise vs oracle ---------

_COORD_CHILD = textwrap.dedent("""
    import json, os, sys, time
    import jax
    from apex_tpu import _compat
    jax.config.update("jax_platforms", "cpu")
    _compat.request_cpu_devices(4)
    jax.config.update("jax_default_matmul_precision", "highest")

    (imgroot, workdir, cluster_dir, barrier, rank, n_steps,
     poison_step, skip_spec) = sys.argv[1:9]
    rank, n_steps = int(rank), int(n_steps)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from apex_tpu import ckpt, cluster, guard, monitor
    from apex_tpu.data.pipeline import ImageFolderSource

    IMG, BATCH, LR = 16, 8, 0.002
    mesh = Mesh(np.array(jax.devices()), ("data",))
    shd = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())

    def beat(r, i):
        open(os.path.join(barrier, f"beat_{r}_{i}"), "w").close()

    def wait_peer(r, i):
        p = os.path.join(barrier, f"beat_{r}_{i}")
        while not os.path.exists(p):
            time.sleep(0.02)

    cfg = guard.GuardConfig(window=16, min_history=4, z_threshold=8.0,
                            grad_factor=50.0, lr_growth_interval=3)

    def train_step(params, gs, x, y):
        def loss_fn(p):
            h = x.reshape(x.shape[0], -1) @ p["w"] + p["b"]
            onehot = jax.nn.one_hot(y, p["b"].shape[0],
                                    dtype=jnp.float32)
            return jnp.mean(jnp.square(h - onehot))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        gs = guard.guard_observe(gs, cfg, loss=loss, grads=grads,
                                 params=params)
        new_p = jax.tree_util.tree_map(
            lambda p, g: p - LR * gs.lr_scale * g, params, grads)
        return guard.guard_commit(gs, new_p, params, cfg), gs, loss

    jstep = jax.jit(train_step)

    events = os.path.join(workdir, f"cluster_rank{rank}.jsonl")
    logger = monitor.MetricsLogger(
        sinks=[], cluster_sink=monitor.JSONLSink(events))
    member = cluster.ClusterMembership(
        cluster_dir, rank=rank, ttl_s=60.0,
        event_sink=logger.record_cluster)
    member.join()
    coord = cluster.RecoveryCoordinator(member, barrier_timeout_s=120.0)

    mgr = ckpt.CheckpointManager(
        os.path.join(workdir, f"ck_r{rank}"), fence=member, rank=0,
        process_count=1, keep=0)
    policy = guard.GuardPolicy(manager=mgr, rewind_budget=2)
    src = ImageFolderSource(imgroot, batch=BATCH, size=IMG, seed=3,
                            workers=2, process_index=rank,
                            process_count=2)
    plan = None
    if poison_step:
        plan = guard.FaultPlan(seed=1).add(int(poison_step), "params",
                                           "nan", rank=rank)
    harness = guard.ChaosHarness(plan, rank=rank) if plan else None

    rng = np.random.RandomState(0)      # identical init on both ranks
    params = {
        "w": jax.device_put(jnp.asarray(
            rng.randn(IMG * IMG * 3, 4).astype("float32") * 0.05),
            rep),
        "b": jax.device_put(jnp.zeros((4,), jnp.float32), rep),
    }
    gs = guard.guard_init(cfg)
    it_box = [None]

    def pull():
        while True:
            if it_box[0] is None:
                it_box[0] = src.epoch()
            try:
                return next(it_box[0])
            except StopIteration:
                it_box[0] = None

    if skip_spec:
        skip_at, skip_n = (int(v) for v in skip_spec.split(":"))
    losses, rewound = [], []
    for step in range(n_steps):
        if skip_spec and src.cursor_index() == skip_at:
            src.skip_batches(skip_n)
            it_box[0] = None
        x, y = pull()
        xd = jax.device_put(x, shd)
        yd = jax.device_put(np.asarray(y, np.int32), shd)
        params, gs, loss = jstep(params, gs, xd, yd)
        losses.append(np.float32(np.asarray(loss)))
        if step % 2 == 0:
            mgr.save(step, {"params": params, "gs": gs},
                     extra={"cursor": src.state()})
            mgr.wait()
        member.heartbeat()
        if harness is not None:
            params = harness.post_step(step, params)
        act = policy.update(step, gs)
        assert act.kind != "escalate", act
        need = act.kind == "rewind"
        like = {"params": params, "gs": gs}
        if need:
            # post the intent BEFORE the step barrier, so the healthy
            # peer sees it the moment it crosses — no rank ever runs
            # ahead into the next epoch unaware
            coord.propose(action="rewind", step=step,
                          good_step=policy.probe_good_step(like))
        beat(rank, step)
        wait_peer(1 - rank, step)
        if need or coord.peer_requested():
            dec, restored = coord.run_round(
                policy, step, like, src, expect_ranks=[0, 1],
                reason=act.reason if need else "peer request")
            tree, manifest = restored
            params, gs = tree["params"], tree["gs"]
            it_box[0] = None
            rewound.append((step, dec.target_step, dec.generation,
                            dec.new_generation))
    src.close()
    logger.close()
    out = {
        "losses": [l.tobytes().hex() for l in losses],
        "w": np.asarray(params["w"]).tobytes().hex(),
        "b": np.asarray(params["b"]).tobytes().hex(),
        "rewound": rewound,
        "generation": member.refresh(),
        "final_cursor": src.cursor_index(),
    }
    print("RESULT " + json.dumps(out), flush=True)
""")


def _run_coord_pair(imgroot, workdir, cluster_dir, *, n_steps,
                    poison_step="", skip_spec=""):
    barrier = os.path.join(workdir, "barrier")
    os.makedirs(barrier, exist_ok=True)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TF_CPP_MIN_LOG_LEVEL": "2"}
    procs = []
    for rank in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _COORD_CHILD, imgroot, workdir,
             cluster_dir, barrier, str(rank), str(n_steps),
             poison_step, skip_spec],
            env=env, cwd=_REPO_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("coordinated run timed out:\n"
                    + "\n---\n".join(outs + ["<pending>"]))
    results = []
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"rank {rank} failed:\n" + "\n---rank---\n".join(outs))
        line = [l for l in out.splitlines()
                if l.startswith("RESULT ")]
        assert line, out
        results.append(json.loads(line[0][len("RESULT "):]))
    return results


class TestCoordinatedRewindAcceptance:
    @pytest.mark.slow          # 4 subprocess jax bring-ups (~2 min);
    #                            the in-process twin runs in smoke via
    #                            scripts/cluster_audit.py --cpu8
    def test_asymmetric_corruption_resolves_to_common_target(
            self, tmp_path):
        """2 procs × 4 CPU devices, each training its own data shard.
        Chaos poisons rank 1's committed params after step 7 (rank 0
        stays clean). Rank 1's guard detects at step 8, posts a signed
        intent; rank 0 joins the round; both resolve to the SAME
        target (rank 1's newest good step 6 — ckpt@8 captured the
        corruption and probe_good_step rejects it; rank 0 honors the
        cluster verdict over its own good step 8), the generation
        increments EXACTLY once, and both ranks' post-rewind losses
        and final params are bitwise-equal to a fault-free oracle that
        never saw the poison window."""
        from apex_tpu.data.pipeline import make_fake_imagefolder
        imgroot = make_fake_imagefolder(str(tmp_path / "imgs"),
                                        n_classes=4, per_class=8,
                                        size=64, seed=0)
        n = 14
        faulted = _run_coord_pair(
            imgroot, str(tmp_path / "faulted"),
            str(tmp_path / "cluster_f"), n_steps=n, poison_step="7")
        oracle = _run_coord_pair(
            imgroot, str(tmp_path / "oracle"),
            str(tmp_path / "cluster_o"), n_steps=n - 2,
            skip_spec="7:2")

        for rank in (0, 1):
            f, o = faulted[rank], oracle[rank]
            # both ranks agreed on the same round: detected at step 8,
            # target step 6, generation 0 -> 1
            assert f["rewound"] == [[8, 6, 0, 1]], (rank, f["rewound"])
            assert o["rewound"] == []
            # post-rewind steps 9.. replay the oracle's 7.. bitwise
            assert f["losses"][9:] == o["losses"][7:], rank
            assert f["w"] == o["w"] and f["b"] == o["b"], (
                f"rank {rank} final params not bitwise vs oracle")
            assert f["final_cursor"] == o["final_cursor"]
        # the generation incremented exactly once, cluster-wide
        assert cluster.read_generation(
            str(tmp_path / "cluster_f")) == 1
        bump_count = 0
        for rank in (0, 1):
            ev = open(os.path.join(str(tmp_path / "faulted"),
                                   f"cluster_rank{rank}.jsonl")
                      ).read().splitlines()
            assert not check_cluster_lines(ev)
            bump_count += sum(
                1 for l in ev
                if json.loads(l)["kind"] == "cluster_generation"
                and json.loads(l)["action"] == "bump")
        assert bump_count == 1, \
            "the leader alone commits the generation bump"
