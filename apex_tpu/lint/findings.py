"""Finding/Report plumbing shared by both apexlint passes.

A lint run produces :class:`Finding` records — one per rule violation,
each carrying the rule id, severity, a human message, a fix-it hint, and
machine evidence (HLO op / scope path / bytes) — collected into a
:class:`Report` that renders a table, serializes to the ``lint`` JSONL
channel (``MetricsLogger(lint_sink=...)``,
``check_metrics_schema.py --kind lint``), and applies a baseline
suppression file so previously-accepted findings don't block CI
(docs/linting.md describes the workflow).

Severities:

- **error** — statically provable waste or a per-step host sync that
  will cost the run (donation miss, host transfer, f64 creep, RNG key
  reuse). CI gates on these (``apexlint --fail-on error``).
- **warning** — a smell that is sometimes intentional (fp32 matmul
  under an amp policy, a collective outside any known named scope).
- **info** — advisory (tile-grid padding waste estimates).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["Finding", "Report", "Rule", "RULES", "SEVERITIES",
           "DTYPE_NAMES", "PROVENANCES",
           "load_baseline", "save_baseline"]

#: severity names, most severe first (index = sort key)
SEVERITIES = ("error", "warning", "info")

#: dtype evidence vocabulary for the precision pass (APX3xx) —
#: the numerics FORMAT_LADDER names plus fp64 (f64-creep territory,
#: but a cast chain can still pass through it)
DTYPE_NAMES = ("fp8_e4m3", "fp8_e5m2", "fp16", "bf16", "fp32", "fp64")

#: the scale-provenance lattice the precision pass propagates
#: (docs/linting.md#apx3xx)
PROVENANCES = ("unscaled", "loss-scaled", "site-scaled",
               "unscaled-after-narrow")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One lint rule's identity: stable id, default severity, fix-it."""

    id: str            # stable id, e.g. "APX101"
    slug: str          # human name, e.g. "donation-miss"
    severity: str      # default severity
    title: str         # one-line description (the docs/linting.md row)
    fix: str           # generic fix-it hint (findings may specialize)


#: the rule catalog — ids are stable across releases (baselines and
#: dashboards key on them); keep docs/linting.md in lockstep.
RULES: Dict[str, Rule] = {r.slug: r for r in (
    # jaxpr pass (trace-time semantics)
    Rule("APX001", "rng-key-reuse", "error",
         "the same PRNG key feeds more than one random primitive — "
         "the draws are correlated, not independent",
         "jax.random.split the key and use one subkey per draw"),
    Rule("APX002", "f64-creep", "error",
         "float64 values in the step jaxpr — TPUs emulate f64 at a "
         "severe cost and it silently doubles HBM",
         "cast to float32 at the boundary (or find the numpy scalar "
         "that promoted the graph and .astype it)"),
    Rule("APX003", "fp32-matmul-in-amp", "warning",
         "an fp32 dot_general/conv runs inside an active bf16/fp16 amp "
         "policy region — fp32 creep halves MXU throughput",
         "cast the operands to the policy compute dtype (amp.auto_cast "
         "region, or check the cast list covers this op)"),
    Rule("APX004", "host-callback-in-step", "error",
         "a host callback / debug print is traced into the step fn — "
         "every step round-trips to the host",
         "remove jax.debug.print/pure_callback from the steady-state "
         "step (gate them behind trace.debug_nans-style flags)"),
    # HLO pass (what XLA actually compiled)
    Rule("APX101", "donation-miss", "error",
         "a params/opt-state-sized input is not aliased to any output — "
         "the buffer is double-allocated every step",
         "donate the carried state: jax.jit(step, donate_argnums=...)"),
    Rule("APX102", "implicit-resharding", "warning",
         "a compiled collective is not attributable to any known named "
         "scope — likely an implicit reshard XLA inserted",
         "name the intended collective (trace.span/ddp.sync) or fix the "
         "sharding so XLA stops moving data"),
    Rule("APX103", "host-transfer", "error",
         "the steady-state step compiles host traffic (infeed/outfeed/"
         "send/recv/python callbacks)",
         "keep device→host fetches out of the compiled step; amortize "
         "telemetry through MetricsLogger"),
    Rule("APX104", "tile-padding", "info",
         "matmul operand dims are off the TPU tile grid — XLA pads to "
         "(sublane,128) tiles and the padding is wasted HBM/MXU work",
         "size matmul dims to multiples of (8,128) for f32 / (16,128) "
         "for bf16 where the model allows; for shapes the model fixes, "
         "run scripts/kernel_tune.py --update-db to sweep tuned block "
         "shapes into scripts/kernel_tuning_db.json — a shape a "
         "committed tuning entry covers stays informational"),
    # SPMD pass (cross-rank congruence + topology)
    Rule("APX201", "spmd-divergence", "error",
         "ranks disagree on a collective's order, channel id, replica "
         "groups or dtype — every rank in the group deadlocks at the "
         "first diverging op",
         "compile one SPMD program for all ranks (identical code + "
         "mesh on every process); for per-rank programs, make the "
         "collective schedule a pure function of shared config"),
    Rule("APX202", "implicit-full-gather", "warning",
         "sharding propagation inserted an all-gather outside every "
         "registered collective scope — a replicated operand the "
         "program never asked for, paid in wire bytes and HBM",
         "pin the operand's sharding (in_shardings / with_sharding_"
         "constraint) or register the gather as a planned scope"),
    Rule("APX203", "dcn-flat-collective", "warning",
         "a flat one-hop reduction crosses a DCN (slice) boundary with "
         "whole-slice replica groups — the slow link carries the full "
         "payload",
         "reduce hierarchically: reduce-scatter within-slice over ICI, "
         "reduce across slices over DCN, all-gather back "
         "(parallel.hierarchical_data_mesh factors the axis)"),
    Rule("APX204", "nondeterminism", "error",
         "a nondeterministic or non-replayable draw is compiled into "
         "the step — it breaks guard's bitwise rewind-and-replay "
         "oracle (docs/resilience.md)",
         "thread PRNG state through the carried step state; keep host "
         "callback results off the commit path; scatter with "
         "unique_indices=True where the indices allow"),
    # precision pass (dtype-provenance dataflow over the same trace)
    Rule("APX301", "unscaled-narrow-cast", "error",
         "a convert_element_type narrows to fp8/fp16 without a "
         "dominating scale multiply — small magnitudes flush to zero "
         "and large ones saturate; the cast O4 must never emit",
         "multiply by a per-site scale (ScaleHistory / "
         "precision_report's recommended_scale) immediately before "
         "the cast, or widen the target dtype"),
    Rule("APX302", "double-rounding", "warning",
         "chained narrowing casts (f32 -> bf16 -> fp8) round the "
         "mantissa twice — one scaled cast from the wide value loses "
         "strictly less",
         "cast once from the widest live value (keep the f32 source "
         "and emit a single scaled narrow cast)"),
    Rule("APX303", "scale-leak", "error",
         "loss-scaled gradient taint reaches a committed (non-scalar) "
         "output without an unscale on every path — the update is "
         "silently multiplied by the loss scale",
         "unscale_grads before the optimizer / param-delta add "
         "(amp.Amp.backward does this; divide by the scale on every "
         "path that commits)"),
    Rule("APX304", "master-weight-violation", "error",
         "update arithmetic runs entirely in the half dtype on a "
         "half-precision carried param under a master-weights policy "
         "— small updates are lost to rounding against the f32 master "
         "contract",
         "keep the committed params in f32 (amp.Amp.init builds the "
         "masters; apply updates to the f32 copy and re-cast)"),
    Rule("APX305", "half-accumulation", "warning",
         "a dot/conv or sum/psum accumulates in fp16/fp8 (bf16 for "
         "reductions) without a widened accumulator — long "
         "accumulation chains lose low-order bits",
         "pass preferred_element_type=jnp.float32 to the dot/conv, or "
         "upcast the reduction operand to f32"),
    Rule("APX306", "wire-dtype-unsafe", "error",
         "a collective's wire dtype is narrower than the measured "
         "per-site precision_report verdict for its subsystem — the "
         "reduction quantizes below the measured safe format",
         "widen the collective dtype, or apply the verdict's "
         "recommended scale before the reduction (EQuARX-style "
         "scaled quantization); int8 error-feedback compression is "
         "exempt by design"),
)}

_RULES_BY_ID = {r.id: r for r in RULES.values()}


@dataclasses.dataclass
class Finding:
    """One rule violation with its evidence."""

    rule: str                      # Rule.slug
    message: str                   # specialized human message
    severity: Optional[str] = None  # default: the rule's severity
    op: Optional[str] = None       # HLO instruction / jaxpr primitive
    scope: Optional[str] = None    # named-scope / arg path / jaxpr path
    bytes: Optional[int] = None    # wasted / moved bytes, when estimable
    count: int = 1                 # occurrences folded into this finding
    fix: Optional[str] = None      # specialized fix-it (default: rule's)
    # cross-rank / topology evidence (the APX2xx SPMD pass; None for
    # single-program findings — excluded from fingerprints so a
    # baselined finding survives a mesh-shape change)
    axes: Optional[List[str]] = None   # mesh axes the groups span
    ranks: Optional[List[int]] = None  # the diverging rank pair
    hop: Optional[str] = None          # link class: "ici" | "dcn"
    # precision evidence (the APX3xx pass; None elsewhere — excluded
    # from fingerprints like the SPMD fields, so a baselined finding
    # survives a dtype-pair drift)
    dtype_from: Optional[str] = None   # source dtype (DTYPE_NAMES)
    dtype_to: Optional[str] = None     # target/required dtype
    scale_provenance: Optional[str] = None  # PROVENANCES entry

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f"unknown lint rule {self.rule!r}")
        if self.severity is None:
            self.severity = RULES[self.rule].severity
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")
        if self.fix is None:
            self.fix = RULES[self.rule].fix
        if self.hop is not None and self.hop not in ("ici", "dcn"):
            raise ValueError(f"unknown hop class {self.hop!r}")
        if self.axes is not None:
            self.axes = [str(a) for a in self.axes]
        if self.ranks is not None:
            self.ranks = [int(r) for r in self.ranks]
        for dt in (self.dtype_from, self.dtype_to):
            if dt is not None and dt not in DTYPE_NAMES:
                raise ValueError(f"unknown dtype name {dt!r} "
                                 f"(expected one of {DTYPE_NAMES})")
        if (self.scale_provenance is not None
                and self.scale_provenance not in PROVENANCES):
            raise ValueError(
                f"unknown scale provenance {self.scale_provenance!r} "
                f"(expected one of {PROVENANCES})")

    @property
    def id(self) -> str:
        return RULES[self.rule].id

    def fingerprint(self) -> str:
        """Stable identity for baseline suppression: rule + where.
        Bytes/counts are excluded — a baselined finding stays
        suppressed when its size drifts."""
        return f"{self.rule}|{self.op or ''}|{self.scope or ''}"

    def to_event(self, fn: Optional[str] = None,
                 step: Optional[int] = None) -> Dict:
        """``kind="lint_finding"`` event for the lint JSONL channel."""
        return {"kind": "lint_finding", "rule": self.rule, "id": self.id,
                "severity": self.severity, "message": self.message,
                "fix": self.fix, "op": self.op, "scope": self.scope,
                "bytes": self.bytes, "count": self.count, "fn": fn,
                "step": step, "axes": self.axes, "ranks": self.ranks,
                "hop": self.hop, "dtype_from": self.dtype_from,
                "dtype_to": self.dtype_to,
                "scale_provenance": self.scale_provenance}


def _fmt_bytes(n: Optional[float]) -> str:
    from apex_tpu.utils.format import fmt_bytes
    return fmt_bytes(n, none="")


class Report:
    """Ordered collection of findings from one lint run."""

    def __init__(self, findings: Iterable[Finding], *,
                 fn_name: Optional[str] = None, suppressed: int = 0):
        self.findings: List[Finding] = sorted(
            findings, key=lambda f: (SEVERITIES.index(f.severity),
                                     f.id, f.scope or "", f.op or ""))
        self.fn_name = fn_name
        #: findings dropped by a baseline file (apply_baseline)
        self.suppressed = suppressed

    def __len__(self) -> int:
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def by_severity(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def by_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def max_severity(self) -> Optional[str]:
        return self.findings[0].severity if self.findings else None

    def wasted_bytes(self, rule: Optional[str] = None) -> int:
        """Sum of byte evidence across findings (optionally one rule) —
        e.g. total HBM a donation fix would reclaim."""
        return sum(f.bytes or 0 for f in self.findings
                   if rule is None or f.rule == rule)

    # -- baseline suppression ------------------------------------------------

    def apply_baseline(self, baseline: Optional[Sequence[str]]) -> "Report":
        """New Report without findings whose fingerprint is baselined."""
        if not baseline:
            return self
        accepted = set(baseline)
        kept = [f for f in self.findings
                if f.fingerprint() not in accepted]
        return Report(kept, fn_name=self.fn_name,
                      suppressed=self.suppressed
                      + (len(self.findings) - len(kept)))

    # -- renderings ----------------------------------------------------------

    def table(self) -> str:
        head = f"apexlint: {len(self.findings)} finding(s)"
        if self.fn_name:
            head += f" on {self.fn_name}"
        sev = self.by_severity()
        head += (f" ({sev['error']} error, {sev['warning']} warning, "
                 f"{sev['info']} info"
                 + (f"; {self.suppressed} baselined" if self.suppressed
                    else "") + ")")
        lines = [head]
        if not self.findings:
            lines.append("  clean.")
            return "\n".join(lines)
        lines.append(f"  {'id':<7} {'severity':<8} {'rule':<22} "
                     f"{'bytes':>10}  evidence")
        for f in self.findings:
            where = f.scope or f.op or ""
            if f.op and f.scope:
                where = f"{f.scope} [{f.op}]"
            if f.count > 1:
                where += f" (x{f.count})"
            lines.append(f"  {f.id:<7} {f.severity:<8} {f.rule:<22} "
                         f"{_fmt_bytes(f.bytes):>10}  {where[:70]}")
            lines.append(f"          {f.message[:100]}")
            lines.append(f"          fix: {f.fix[:100]}")
        return "\n".join(lines)

    def summary(self) -> Dict:
        """JSON-able digest (the ``bench.py`` lint_findings source)."""
        return {"n_findings": len(self.findings),
                "by_severity": self.by_severity(),
                "suppressed": self.suppressed,
                "wasted_bytes": self.wasted_bytes(),
                "rules": sorted({f.rule for f in self.findings})}

    def to_events(self, step: Optional[int] = None) -> List[Dict]:
        """``kind="lint_report"`` header + one ``lint_finding`` event per
        finding — the stream ``check_metrics_schema.py --kind lint``
        validates (emit via ``MetricsLogger.record_lint`` /
        ``attach_lint_report``)."""
        ev: Dict = {"kind": "lint_report", "fn": self.fn_name,
                    "step": step, "suppressed": self.suppressed}
        ev.update({"n_findings": len(self.findings),
                   "by_severity": self.by_severity()})
        return [ev] + [f.to_event(self.fn_name, step)
                       for f in self.findings]


# -- baseline files -----------------------------------------------------------

def load_baseline(path: str) -> List[str]:
    """Fingerprints from a baseline file (see docs/linting.md).

    Format: ``{"version": 1, "suppress": ["rule|op|scope", ...]}``.
    A missing file is an empty baseline (the committed CI baseline
    starts empty on purpose — new error findings must break the gate).
    """
    try:
        with open(path) as f:
            data = json.load(f)
    except FileNotFoundError:
        return []
    if not isinstance(data, dict) or not isinstance(
            data.get("suppress"), list):
        raise ValueError(f"{path}: not a lint baseline "
                         '(expected {"version": 1, "suppress": [...]})')
    return [str(s) for s in data["suppress"]]


def save_baseline(path: str, report: Report) -> int:
    """Write every finding of ``report`` as the new baseline; returns
    the number of suppressions written."""
    fps = sorted({f.fingerprint() for f in report.findings})
    with open(path, "w") as f:
        json.dump({"version": 1, "suppress": fps}, f, indent=1)
        f.write("\n")
    return len(fps)
