"""End-to-end Amp bundle tests: init → train steps → overflow → checkpoint.

Functional mirror of `tests/L0/run_amp/test_checkpointing.py` and the
multi-loss DCGAN pattern (`examples/dcgan/main_amp.py:215-253`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp


def _toy_params(key=0):
    k = jax.random.PRNGKey(key)
    return {"dense": {"kernel": jax.random.normal(k, (4, 4)),
                      "bias": jnp.zeros((4,))}}


def _loss_fn(model_params, x):
    y = x @ model_params["dense"]["kernel"] + model_params["dense"]["bias"]
    return jnp.mean(jnp.square(y))


class TestAmpStep:
    @pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
    def test_loss_decreases(self, opt_level):
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), opt_level)
        x = jnp.ones((8, 4))

        @jax.jit
        def step(state):
            return amp_opt.step(state, _loss_fn, x)

        losses = []
        for _ in range(10):
            state, loss, finite = step(state)
            assert bool(finite)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_o2_masters_stay_fp32(self):
        amp_opt, state = amp.initialize(_toy_params(), optax.sgd(0.1), "O2")
        assert state.params["dense"]["kernel"].dtype == jnp.float32
        model_p = amp_opt.model_params(state)
        assert model_p["dense"]["kernel"].dtype == jnp.bfloat16

    def test_o3_params_half(self):
        amp_opt, state = amp.initialize(_toy_params(), optax.sgd(0.1), "O3")
        assert state.params["dense"]["kernel"].dtype == jnp.bfloat16

    def test_fp16_overflow_skips_step(self):
        """Poisoned grads: step must not move params, scale must halve
        (`test_fused_sgd.py` overflow-injection pattern)."""
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), "O2", half_dtype=jnp.float16)

        def bad_loss(model_params, x):
            return jnp.sum(model_params["dense"]["kernel"]) * jnp.inf

        before = np.asarray(state.params["dense"]["kernel"])
        scale_before = float(state.scalers[0].loss_scale)
        state, _, finite = jax.jit(
            lambda s: amp_opt.step(s, bad_loss, jnp.ones((2, 4))))(state)
        assert not bool(finite)
        np.testing.assert_array_equal(
            np.asarray(state.params["dense"]["kernel"]), before)
        assert float(state.scalers[0].loss_scale) == scale_before / 2
        assert int(state.step) == 0  # skipped steps don't count

    def test_multi_loss_independent_scalers(self):
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), "O2", half_dtype=jnp.float16,
            num_losses=2)

        def bad_loss(mp, x):
            return jnp.sum(mp["dense"]["kernel"]) * jnp.inf

        _, _, state, finite = amp_opt.backward(
            state, bad_loss, jnp.ones((2, 4)), loss_id=1)
        assert not bool(finite)
        # scaler 1 backed off; scaler 0 untouched
        assert float(state.scalers[1].loss_scale) == 2.0 ** 15
        assert float(state.scalers[0].loss_scale) == 2.0 ** 16

    def test_state_dict_roundtrip(self):
        amp_opt, state = amp.initialize(
            _toy_params(), optax.sgd(0.1), "O2", half_dtype=jnp.float16)
        # advance the scaler, then round-trip through state_dict
        _, _, state, _ = amp_opt.backward(
            state, _loss_fn, jnp.ones((2, 4)))
        sd = amp_opt.state_dict(state)
        fresh = amp_opt.init(_toy_params())
        restored = amp_opt.load_state_dict(fresh, sd)
        assert (float(restored.scalers[0].loss_scale)
                == float(state.scalers[0].loss_scale))
        assert (int(restored.scalers[0].growth_tracker)
                == int(state.scalers[0].growth_tracker))

    def test_checkpoint_resume_continues_identically(self):
        """Train 3 steps, checkpoint (pytree), restore, continue — identical
        to an uninterrupted run (`test_checkpointing.py:1-267` semantics)."""
        tx = optax.adam(1e-2)
        amp_opt, state = amp.initialize(_toy_params(), tx, "O2")
        x = jnp.ones((8, 4))
        step = jax.jit(lambda s: amp_opt.step(s, _loss_fn, x))

        for _ in range(3):
            state, _, _ = step(state)
        # "checkpoint": the whole AmpState is a pytree; serialize via numpy
        ckpt = jax.tree_util.tree_map(np.asarray, state)
        restored = jax.tree_util.tree_map(jnp.asarray, ckpt)

        out_a, out_b = state, restored
        for _ in range(3):
            out_a, la, _ = step(out_a)
            out_b, lb, _ = step(out_b)
            assert float(la) == float(lb)
        np.testing.assert_array_equal(
            np.asarray(out_a.params["dense"]["kernel"]),
            np.asarray(out_b.params["dense"]["kernel"]))


class TestFlaxAutoCast:
    """O1 ergonomics on an unmodified flax model."""

    def _model(self):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.Dense(8)(x)
                x = nn.LayerNorm()(x)
                x = nn.Dense(4)(x)
                return x
        return Net()

    def test_auto_cast_runs_dense_in_half(self):
        import flax.linen as nn
        model = self._model()
        x = jnp.ones((2, 8))
        variables = model.init(jax.random.PRNGKey(0), x)
        policy = amp.Policy.from_opt_level("O1")

        seen = {}
        half_mods, float_mods = (nn.Dense,), (nn.LayerNorm,)

        def spy(next_fun, args, kwargs, context):
            if isinstance(context.module, half_mods + float_mods) \
                    and context.method_name == "__call__":
                seen.setdefault(type(context.module).__name__,
                                jnp.asarray(args[0]).dtype)
            return next_fun(*args, **kwargs)

        with amp.auto_cast(policy):
            with nn.intercept_methods(spy):
                out = model.apply(variables, x)
        assert seen["Dense"] == jnp.bfloat16      # whitelist cast
        assert seen["LayerNorm"] == jnp.float32   # blacklist cast
        # params stayed fp32 (O1 keeps fp32 weights)
        assert variables["params"]["Dense_0"]["kernel"].dtype == jnp.float32

    def test_auto_cast_grads_flow(self):
        model = self._model()
        x = jnp.ones((2, 8))
        variables = model.init(jax.random.PRNGKey(0), x)
        policy = amp.Policy.from_opt_level("O1")

        def loss(params):
            with amp.auto_cast(policy):
                return jnp.mean(model.apply({"params": params}, x) ** 2)

        grads = jax.grad(loss)(variables["params"])
        # grads are w.r.t. fp32 params
        assert grads["Dense_0"]["kernel"].dtype == jnp.float32
        assert float(jnp.abs(grads["Dense_0"]["kernel"]).sum()) > 0


class TestDecorators:
    def test_half_float_promote(self):
        policy = amp.Policy.from_opt_level("O1")

        @amp.half_function
        def h(x):
            return x.dtype

        @amp.float_function
        def f(x):
            return x.dtype

        @amp.promote_function
        def p(x, y):
            return x.dtype, y.dtype

        x32 = jnp.ones((2,), jnp.float32)
        x16 = jnp.ones((2,), jnp.bfloat16)
        with amp.policy_scope(policy):
            assert h(x32) == jnp.bfloat16
            assert f(x16) == jnp.float32
            assert p(x16, x32) == (jnp.float32, jnp.float32)
        # outside the scope: no casting
        assert h(x32) == jnp.float32
