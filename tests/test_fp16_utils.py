"""fp16_utils tests — mirrors `tests/L0/run_fp16util` + the
FP16_Optimizer training/overflow/checkpoint semantics from
`tests/L0/run_amp/test_checkpointing.py` and `test_fused_sgd.py`.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from apex_tpu import fp16_utils
from apex_tpu.optim import FusedSGD


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(32)(x)
        x = nn.BatchNorm(use_running_average=True)(x)
        x = nn.relu(x)
        return nn.Dense(4)(x)


@pytest.fixture(scope="module")
def net_and_params():
    net = Net()
    x = jnp.ones((8, 16))
    variables = net.init(jax.random.PRNGKey(0), x)
    return net, variables, x


def test_network_to_half_keeps_norms_fp32(net_and_params):
    _, variables, _ = net_and_params
    half = fp16_utils.network_to_half(variables["params"])
    assert half["Dense_0"]["kernel"].dtype == jnp.float16
    assert half["Dense_1"]["bias"].dtype == jnp.float16
    # BN params exempt — BN_convert_float (`fp16util.py:22-33`)
    assert half["BatchNorm_0"]["scale"].dtype == jnp.float32
    assert half["BatchNorm_0"]["bias"].dtype == jnp.float32


def test_convert_network_bf16(net_and_params):
    _, variables, _ = net_and_params
    conv = fp16_utils.convert_network(variables["params"], jnp.bfloat16)
    assert conv["Dense_0"]["kernel"].dtype == jnp.bfloat16
    assert conv["BatchNorm_0"]["scale"].dtype == jnp.float32


def test_prep_param_lists_roundtrip(net_and_params):
    _, variables, _ = net_and_params
    model_p = fp16_utils.tofp16(variables["params"])
    model_p, masters = fp16_utils.prep_param_lists(model_p)
    tree = masters.to_tree()
    for m, p in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(model_p)):
        assert m.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(m, np.float32),
                                   np.asarray(p, np.float32), rtol=1e-3)
    back = fp16_utils.master_params_to_model_params(masters, model_p)
    for b, p in zip(jax.tree_util.tree_leaves(back),
                    jax.tree_util.tree_leaves(model_p)):
        assert b.dtype == p.dtype


def test_prep_param_lists_flat_master(net_and_params):
    _, variables, _ = net_and_params
    params = variables["params"]  # uniform fp32 -> single partition
    model_p, masters = fp16_utils.prep_param_lists(params, flat_master=True)
    assert masters.flat is not None
    bufs, spec = masters.flat
    (buf,) = bufs.values()
    assert buf.ndim == 1 and buf.dtype == jnp.float32
    rt = masters.to_tree()
    for a, b in zip(jax.tree_util.tree_leaves(rt),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_model_grads_to_master_grads(net_and_params):
    _, variables, _ = net_and_params
    model_p = fp16_utils.tofp16(variables["params"])
    _, masters = fp16_utils.prep_param_lists(model_p)
    grads = jax.tree_util.tree_map(jnp.ones_like, model_p)
    mg = fp16_utils.model_grads_to_master_grads(grads, masters)
    assert all(g.dtype == jnp.float32
               for g in jax.tree_util.tree_leaves(mg))


def test_clip_grad_norm():
    grads = {"a": jnp.full((4,), 3.0), "b": jnp.full((4,), 4.0)}
    clipped, norm = fp16_utils.clip_grad_norm(grads, max_norm=1.0)
    np.testing.assert_allclose(float(norm), 10.0, rtol=1e-6)
    total = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                         for g in jax.tree_util.tree_leaves(clipped)))
    np.testing.assert_allclose(float(total), 1.0, rtol=1e-4)


def test_fp16model_casts_inputs(net_and_params):
    net, _, x = net_and_params
    wrapped = fp16_utils.FP16Model(network=nn.Dense(4))
    variables = wrapped.init(jax.random.PRNGKey(0), x)
    out = wrapped.apply(variables, x)
    assert out.dtype == jnp.float16


# --- FP16_Optimizer ----------------------------------------------------------

def _quadratic_loss(target):
    def loss_fn(mp):
        err = mp["w"].astype(jnp.float32) - target
        return jnp.mean(jnp.square(err))
    return loss_fn


def test_fp16_optimizer_trains():
    opt = fp16_utils.FP16_Optimizer(FusedSGD(lr=0.5, momentum=0.9),
                                    static_loss_scale=128.0)
    params = {"w": jnp.zeros((256,), jnp.float16)}
    state = opt.init(params)
    target = jnp.linspace(-1, 1, 256)
    loss_fn = _quadratic_loss(target)

    @jax.jit
    def train(state):
        def body(state, _):
            loss, grads, finite, state = opt.backward(state, loss_fn)
            state = opt.step(state, grads, finite)
            return state, loss
        return jax.lax.scan(body, state, None, length=60)

    state, losses = train(state)
    assert float(losses[-1]) < 1e-3 * float(losses[0])
    assert int(state.step) == 60
    mp = opt.model_params(state, like=params)
    assert mp["w"].dtype == jnp.float16


def test_fp16_optimizer_overflow_skips_and_backs_off():
    opt = fp16_utils.FP16_Optimizer(FusedSGD(lr=0.1), dynamic_loss_scale=True,
                                    dynamic_loss_args={"init_scale": 2.0**8})
    params = {"w": jnp.ones((128,), jnp.float16)}
    state = opt.init(params)

    def poisoned(mp, poison):
        base = jnp.mean(jnp.square(mp["w"].astype(jnp.float32)))
        return base * jnp.where(poison, jnp.inf, 1.0)

    @jax.jit
    def one(state, poison):
        loss, grads, finite, state = opt.backward(state, poisoned, poison)
        return opt.step(state, grads, finite), finite

    state, finite = one(state, jnp.bool_(False))
    assert bool(finite) and int(state.step) == 1
    w_before = np.asarray(state.masters["w"])
    scale_before = float(opt.loss_scale(state))
    state, finite = one(state, jnp.bool_(True))
    assert not bool(finite)
    assert int(state.step) == 1, "overflow step must not count"
    np.testing.assert_array_equal(np.asarray(state.masters["w"]), w_before)
    assert float(opt.loss_scale(state)) == scale_before / 2


def test_fp16_optimizer_checkpoint_roundtrip():
    opt = fp16_utils.FP16_Optimizer(FusedSGD(lr=0.3, momentum=0.9),
                                    dynamic_loss_scale=True)
    params = {"w": jnp.zeros((64,), jnp.float16)}
    state = opt.init(params)
    target = jnp.linspace(0, 1, 64)
    loss_fn = _quadratic_loss(target)

    @jax.jit
    def one(state):
        loss, grads, finite, state = opt.backward(state, loss_fn)
        return opt.step(state, grads, finite), loss

    for _ in range(5):
        state, _ = one(state)

    sd = opt.state_dict(state)
    restored = opt.load_state_dict(opt.init(params), sd)

    # continue both for 3 steps: trajectories must match bitwise
    s_a, s_b = state, restored
    for _ in range(3):
        s_a, la = one(s_a)
        s_b, lb = one(s_b)
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    np.testing.assert_array_equal(np.asarray(s_a.masters["w"]),
                                  np.asarray(s_b.masters["w"]))
    assert int(s_a.step) == int(s_b.step)
