"""SyncBatchNorm — cross-device batch normalization.

Rebuild of the reference's optimized SyncBN
(`apex/parallel/optimized_sync_batchnorm.py:9-85`,
`optimized_sync_batchnorm_kernel.py:7-119`): per-device Welford stats, an
``all_gather`` of (mean, biased var, count) over the stats group, a
count-weighted parallel-Welford combine (exact for *unequal* per-device
batches — the `two_gpu_test_different_batch_size.py` semantics), then a
fused normalize with optional residual-add + ReLU
(`syncbn.batchnorm_forward` + the `relu_bw_c_last` fused variant).

Two design deltas from the reference, both TPU-idiomatic:

- **Backward is autodiff.** The reference hand-writes backward (local
  `reduce_bn` producing sum_dy / sum_dy_xmu, two `all_reduce`s, dgrad
  kernel, `optimized_sync_batchnorm_kernel.py:77-119`). Differentiating
  this forward under JAX produces *exactly* those collectives — the
  transpose of ``all_gather`` is ``psum_scatter`` — so the hand-derived
  VJP is the compiler's job.
- **Channel-last is the native layout** (TPUs are NHWC); the reference's
  ``channel_last=True`` variant is our default and NCHW is handled by
  ``channel_axis``.

Stats sub-groups (`create_syncbn_process_group`,
`apex/parallel/__init__.py:55-95`) map to ``axis_index_groups``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import flax.linen as nn


def syncbn_stats_groups(world_size: int, group_size: int):
    """Partition ``world_size`` devices into stats groups of ``group_size``
    — `create_syncbn_process_group` (`apex/parallel/__init__.py:55-95`).
    Returns ``axis_index_groups`` for the collectives."""
    if group_size == 0 or group_size >= world_size:
        return None                               # whole axis
    if group_size == 1:
        # per-device stats (non-sync BN) — None would mean the WHOLE axis
        return [[i] for i in range(world_size)]
    if world_size % group_size:
        raise ValueError(f"world {world_size} % group {group_size} != 0")
    return [list(range(i, i + group_size))
            for i in range(0, world_size, group_size)]


def _local_moments(x, reduce_axes):
    """Per-channel mean and biased variance in fp32 (the per-device Welford
    kernel `syncbn.welford_mean_var`, `csrc/welford.cu:259-400`; jnp's
    one-pass moments are the XLA equivalent)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=reduce_axes)
    # two-pass variance: E[x²]−E[x]² cancels catastrophically in fp32 for
    # large-mean/small-std channels (the reason the reference uses Welford)
    shape = [1 if a in reduce_axes else s for a, s in enumerate(x.shape)]
    var = jnp.mean(jnp.square(x32 - mean.reshape(shape)), axis=reduce_axes)
    return mean, var


def _welford_combine(means, variances, counts):
    """Count-weighted combine of per-device (mean, biased var, count) along
    a leading device axis — ``welford_parallel``
    (`csrc/welford.cu:905-1000`): exact for unequal counts."""
    total = jnp.sum(counts)
    gmean = jnp.sum(means * counts[:, None], axis=0) / total
    gvar = jnp.sum((variances + jnp.square(means - gmean[None, :]))
                   * counts[:, None], axis=0) / total
    return gmean, gvar, total


def sync_moments(x, *, axis_name: Optional[str], reduce_axes,
                 axis_index_groups=None, valid_count=None):
    """Cross-device per-channel (mean, biased var, total count).

    ``valid_count`` handles padded/ragged local batches — the
    unequal-batch-size case (`two_gpu_test_different_batch_size.py`):
    non-valid positions of ``x`` must be **zero-padded**, and the local
    moments then divide the (padding-invariant) sums by ``valid_count``
    instead of the padded element count, so the cross-device combine is
    weighted by true counts. With ``axis_name=None`` this degrades to
    single-device moments, the python_single_gpu fallback path."""
    if valid_count is None:
        mean, var = _local_moments(x, reduce_axes)
        n_local = 1
        for a in reduce_axes:
            n_local *= x.shape[a]
        count = jnp.float32(n_local)
    else:
        count = jnp.float32(valid_count)
        x32 = x.astype(jnp.float32)
        mean = jnp.sum(x32, axis=reduce_axes) / count
        # second pass on centered values: non-valid positions are
        # zero-padded, so sum((x-mean)^2) over valid positions equals
        # sum(x^2) - 2*mean*sum(x) + count*mean^2 computed via the
        # centered form below minus the padding correction. Using the
        # centered subtraction only at valid positions would need the
        # mask; instead center everywhere and correct for the
        # (n_padded - count) zero positions that became (-mean)^2.
        shape = [1 if a in reduce_axes else s for a, s in enumerate(x.shape)]
        n_padded = 1
        for a in reduce_axes:
            n_padded *= x.shape[a]
        centered_sq = jnp.sum(jnp.square(x32 - mean.reshape(shape)),
                              axis=reduce_axes)
        pad_correction = (jnp.float32(n_padded) - count) * jnp.square(mean)
        var = (centered_sq - pad_correction) / count
    if axis_name is None:
        return mean, var, count
    # all_gather of the stat triple over the stats group, then combine —
    # the forward of `optimized_sync_batchnorm_kernel.py:28-45`.
    means = jax.lax.all_gather(mean, axis_name,
                               axis_index_groups=axis_index_groups)
    variances = jax.lax.all_gather(var, axis_name,
                                   axis_index_groups=axis_index_groups)
    counts = jax.lax.all_gather(count, axis_name,
                                axis_index_groups=axis_index_groups)
    return _welford_combine(means, variances, counts)


def sync_batch_norm(x, scale, bias, *, axis_name: Optional[str] = None,
                    axis_index_groups=None, epsilon: float = 1e-5,
                    channel_axis: int = -1, z=None, relu: bool = False,
                    valid_count=None):
    """Functional training-mode SyncBN.

    Normalizes ``x`` with cross-device batch statistics; optionally fuses a
    residual add (``z``) and ReLU — the `include relu/add` variants of the
    optimized kernel (`optimized_sync_batchnorm.py:70-85`). Returns
    ``(y, mean, var, count)`` with biased var for the running-stat update.
    """
    channel_axis = channel_axis % x.ndim
    reduce_axes = tuple(a for a in range(x.ndim) if a != channel_axis)
    mean, var, count = sync_moments(
        x, axis_name=axis_name, reduce_axes=reduce_axes,
        axis_index_groups=axis_index_groups, valid_count=valid_count)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jax.lax.rsqrt(var + epsilon).reshape(shape)
    m = mean.reshape(shape)
    y = (x.astype(jnp.float32) - m) * inv
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(shape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(shape)
    if z is not None:
        y = y + z.astype(jnp.float32)
    if relu:
        y = jax.nn.relu(y)
    return y.astype(x.dtype), mean, var, count


class SyncBatchNorm(nn.Module):
    """flax module mirror of ``apex.parallel.SyncBatchNorm``
    (`optimized_sync_batchnorm.py:9-69`): BatchNorm whose batch statistics
    reduce over ``axis_name`` (a mesh axis inside shard_map), with optional
    stats sub-groups and fused add+relu.

    Eval mode uses running stats locally — the `F.batch_norm` fallback
    (`optimized_sync_batchnorm.py:78-81`).
    """
    num_features: int
    epsilon: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = None
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None
    channel_axis: int = -1
    fuse_relu: bool = False
    param_dtype: Any = jnp.float32
    scale_init: Any = None

    @nn.compact
    def __call__(self, x, z=None, *, use_running_average: bool = False,
                 valid_count=None):
        c = self.num_features
        scale_init = self.scale_init or nn.initializers.ones
        scale = (self.param("scale", scale_init, (c,),
                            self.param_dtype) if self.affine else None)
        bias = (self.param("bias", nn.initializers.zeros, (c,),
                           self.param_dtype) if self.affine else None)

        init_mean = nn.initializers.zeros
        init_var = nn.initializers.ones
        ra_mean = self.variable("batch_stats", "mean", init_mean,
                                jax.random.PRNGKey(0), (c,), jnp.float32)
        ra_var = self.variable("batch_stats", "var", init_var,
                               jax.random.PRNGKey(0), (c,), jnp.float32)

        if use_running_average:
            shape = [1] * x.ndim
            shape[self.channel_axis % x.ndim] = c
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon).reshape(shape)
            y = (x.astype(jnp.float32)
                 - ra_mean.value.reshape(shape)) * inv
            if scale is not None:
                y = y * scale.astype(jnp.float32).reshape(shape)
            if bias is not None:
                y = y + bias.astype(jnp.float32).reshape(shape)
            if z is not None:
                y = y + z.astype(jnp.float32)
            if self.fuse_relu:
                y = jax.nn.relu(y)
            return y.astype(x.dtype)

        # During module init there is no mesh context to resolve the axis
        # name, and stats don't matter — compute locally.
        axis = None if self.is_initializing() else self.axis_name
        y, mean, var, count = sync_batch_norm(
            x, scale, bias, axis_name=axis,
            axis_index_groups=self.axis_index_groups,
            epsilon=self.epsilon, channel_axis=self.channel_axis,
            z=z, relu=self.fuse_relu, valid_count=valid_count)

        if self.track_running_stats and not self.is_initializing():
            # EMA with unbiased var, `optimized_sync_batchnorm_kernel.py:55-58`
            unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
            m = self.momentum
            ra_mean.value = (1 - m) * ra_mean.value + m * mean
            ra_var.value = (1 - m) * ra_var.value + m * unbiased
        return y


def convert_sync_batchnorm(policy_axis_name: str, axis_index_groups=None):
    """Context manager: make *unmodified* flax models sync their BatchNorm
    stats — `convert_syncbn_model` (`apex/parallel/__init__.py:21-54`)
    without module surgery. Inside the context, every ``nn.BatchNorm``
    call has its ``axis_name``/``axis_index_groups`` retargeted so flax's
    own cross-device reduction kicks in.
    """
    import contextlib

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if isinstance(mod, nn.BatchNorm) and mod.axis_name is None:
            object.__setattr__(mod, "axis_name", policy_axis_name)
            object.__setattr__(mod, "axis_index_groups", axis_index_groups)
        return next_fun(*args, **kwargs)

    @contextlib.contextmanager
    def _ctx():
        with nn.intercept_methods(interceptor):
            yield

    return _ctx()
