"""DCGAN — the multi-model / multi-loss amp example.

Reference: `examples/dcgan/main_amp.py:214-253` — the canonical exercise
of ``amp.initialize([netD, netG], [optD, optG], num_losses=3)`` with a
``loss_id`` per backward, so each of the three losses (D-real, D-fake, G)
gets its own loss scaler.

TPU-native: two Amp bundles (one per model/optimizer pair, D's with
``num_losses=2``), each backward tagged with its ``loss_id``. The whole
G+D update is one jitted step.

    python main_amp.py --niter 200 --batchSize 64 --opt_level O2
"""

import argparse

import os
import sys

# allow running from a source checkout without installation
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..")))

import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp, models
from apex_tpu.optim import FusedAdam


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--batchSize", type=int, default=64)
    p.add_argument("--imageSize", type=int, default=64)
    p.add_argument("--nz", type=int, default=100)
    p.add_argument("--ngf", type=int, default=64)
    p.add_argument("--ndf", type=int, default=64)
    p.add_argument("--niter", type=int, default=100,
                   help="number of steps (synthetic data)")
    p.add_argument("--lr", type=float, default=0.0002)
    p.add_argument("--beta1", type=float, default=0.5)
    p.add_argument("--manualSeed", type=int, default=0)
    p.add_argument("--opt_level", default="O2")
    p.add_argument("--print-freq", type=int, default=20)
    return p.parse_args()


def bce_with_logits(logits, target):
    """Binary CE on logits — numerically safe in half precision, the
    fix-it the reference's banned-function message demands
    (`apex/amp/lists/functional_overrides.py` bans `binary_cross_entropy`
    on sigmoided inputs)."""
    logits = logits.astype(jnp.float32)
    return jnp.mean(jnp.maximum(logits, 0) - logits * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main():
    args = parse_args()
    rng = np.random.RandomState(args.manualSeed)

    netG = models.Generator(nz=args.nz, ngf=args.ngf)
    netD = models.Discriminator(ndf=args.ndf)

    policy = amp.Policy.from_opt_level(args.opt_level)
    z0 = jnp.zeros((2, 1, 1, args.nz), jnp.float32)
    x0 = jnp.zeros((2, args.imageSize, args.imageSize, 3), jnp.float32)
    varG = netG.init(jax.random.PRNGKey(1), z0, train=True)
    varD = netD.init(jax.random.PRNGKey(2), x0, train=True)

    # amp.initialize([netD, netG], [optD, optG], num_losses=3)
    # (`examples/dcgan/main_amp.py:214`): D's bundle owns losses 0 (real)
    # and 1 (fake), G's bundle owns loss 2 — scaler-per-loss parity.
    ampD = amp.Amp(policy, FusedAdam(lr=args.lr, betas=(args.beta1, 0.999)),
                   num_losses=2)
    ampG = amp.Amp(policy, FusedAdam(lr=args.lr, betas=(args.beta1, 0.999)))
    stateD = ampD.init(varD["params"])
    stateG = ampG.init(varG["params"])
    bsD, bsG = varD["batch_stats"], varG["batch_stats"]

    def step(stateD, stateG, bsD, bsG, real, z):
        # --- update D: two backwards, two scalers ------------------------
        def d_real_loss(p):
            logits, mut = netD.apply({"params": p, "batch_stats": bsD},
                                     real, train=True,
                                     mutable=["batch_stats"])
            return bce_with_logits(logits, 1.0), mut["batch_stats"]

        (errD_real, bsD1), gR, stateD, finR = ampD.backward(
            stateD, d_real_loss, loss_id=0, has_aux=True)

        fake, mutG = netG.apply({"params": stateG.params if not
                                 policy.master_weights else
                                 policy.cast_params(stateG.params),
                                 "batch_stats": bsG},
                                z, train=True, mutable=["batch_stats"])

        def d_fake_loss(p):
            logits, mut = netD.apply({"params": p, "batch_stats": bsD1},
                                     jax.lax.stop_gradient(fake),
                                     train=True, mutable=["batch_stats"])
            return bce_with_logits(logits, 0.0), mut["batch_stats"]

        (errD_fake, bsD2), gF, stateD, finF = ampD.backward(
            stateD, d_fake_loss, loss_id=1, has_aux=True)

        grads = jax.tree_util.tree_map(lambda a, b: a + b, gR, gF)
        stateD = ampD.apply_gradients(
            stateD, grads, jnp.logical_and(finR, finF)
            if not isinstance(finR, bool) else (finR and finF))

        # --- update G ----------------------------------------------------
        def g_loss(p):
            img, mut = netG.apply({"params": p, "batch_stats": bsG},
                                  z, train=True, mutable=["batch_stats"])
            logits, _ = netD.apply(
                {"params": policy.cast_params(stateD.params),
                 "batch_stats": bsD2},
                img, train=True, mutable=["batch_stats"])
            return bce_with_logits(logits, 1.0), mut["batch_stats"]

        (errG, bsG1), gG, stateG, finG = ampG.backward(
            stateG, g_loss, loss_id=0, has_aux=True)
        stateG = ampG.apply_gradients(stateG, gG, finG)
        return stateD, stateG, bsD2, bsG1, errD_real + errD_fake, errG

    jstep = jax.jit(step, donate_argnums=(0, 1, 2, 3))

    t0 = time.perf_counter()
    for i in range(args.niter):
        real = jnp.asarray(
            rng.rand(args.batchSize, args.imageSize, args.imageSize, 3)
            .astype(np.float32) * 2 - 1)
        z = jnp.asarray(
            rng.randn(args.batchSize, 1, 1, args.nz).astype(np.float32))
        stateD, stateG, bsD, bsG, errD, errG = jstep(
            stateD, stateG, bsD, bsG, real, z)
        if (i + 1) % args.print_freq == 0:
            print(f"[{i+1}/{args.niter}] Loss_D {float(errD):.4f} "
                  f"Loss_G {float(errG):.4f} "
                  f"({args.batchSize*(i+1)/(time.perf_counter()-t0):.1f} "
                  "img/s)")


if __name__ == "__main__":
    main()
