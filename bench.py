"""Headline bench: ResNet-50 mixed-precision training throughput.

The BASELINE.json metric — images/sec/chip + MFU on ResNet-50, amp O2
(bf16 compute, fp32 masters) + fused SGD — measured on whatever single
accelerator is present. Prints ONE JSON line, whose ``extra`` also
carries the BERT-Large LAMB row (the 61.0%-MFU headline workload), the
DDP comm-mode column (bucket plan + wire-byte ratios for
exact/bf16/int8 gradient sync — see apex_tpu.parallel.comm), the
``peak_hbm_bytes`` footprint column (runtime allocator peak on TPU,
apex_tpu.prof.memory report estimate elsewhere — AOT, zero extra
dispatches on the measured path), ``n_compiles`` (process-wide
backend-compile count from apex_tpu.prof.compile_watch — a step
silently retracing per call explodes this column),
``lint_findings``/``lint_errors`` (apexlint finding counts on the
compiled headline step — see apex_tpu.lint / docs/linting.md), and
``ckpt_save_stall_ms`` (per-step stall of an async apex_tpu.ckpt
snapshot vs a synchronous save — the checkpoint-overhead claim of
docs/checkpointing.md as a measured column), ``goodput_frac`` (the
steady-state useful-time fraction of the instrumented headline step
with its wall-time bucket breakdown — apex_tpu.monitor.GoodputLedger,
closure asserted by ``scripts/goodput_audit.py --cpu8``), ``link_fit`` (measured alpha-beta link calibration of the local device
mesh — apex_tpu.monitor.linkbench / ``scripts/link_probe.py``;
single-device hosts skip), ``roofline_worst_gap`` (the headline step's
worst measured-vs-attainable per-op gap — apex_tpu.prof.roofline; the
fingerprinted autotuner candidate, measured on TPU / AOT-only
classification elsewhere), ``n_autotune_compiles`` (the autotune-origin
subset of ``n_compiles`` — prof.compile_watch.autotune_scope),
``tuned_families``/``autotune_db_hits`` (the committed kernel tuning
DB's reach: families holding a sweep winner in
``scripts/kernel_tuning_db.json`` and exact-key trace-time consult
hits, off the same AOT executable — apex_tpu.ops.autotune /
``scripts/kernel_tune.py``),
``pod_goodput``/``comm_skew_p99``/``comm_drift_ratio`` (the pod
observatory columns: goodput after the comm_skew/comm_wire split on an
emulated pod merge, the p99 collective entry skew, and the worst
plan-vs-measured hop drift — apex_tpu.trace.podview /
apex_tpu.monitor.comm_drift, asserted by
``scripts/pod_audit.py --cpu8``), ``gns``/``grad_cosine_min`` (the
training-dynamics observatory columns off one instrumented
data-parallel step, zero extra compiles asserted inline —
apex_tpu.monitor.dynamics, asserted by
``scripts/dynamics_audit.py --cpu8``), and ``sentinel_regressions`` (the
noise-aware perf-regression gate's verdict on this row vs the
committed BENCH_r0*.json trajectory — apex_tpu.prof.sentinel /
``scripts/perf_sentinel.py``).

``python bench.py --all`` additionally measures the full BASELINE.md
config table (fp32/O0, O2, SyncBN, DCGAN multi-loss, BERT-Large LAMB)
and writes BENCH_TABLE.md. ``python bench.py --monitor`` drives the
headline step with live apex_tpu.monitor telemetry (stdout table +
MONITOR.jsonl). ``python bench.py --trace`` runs a short traced loop
with apex_tpu.trace spans + flight recorder, emitting a
Perfetto-loadable Chrome trace (TRACE.json), a trace-event JSONL stream
(TRACE_EVENTS.jsonl — validate with
``scripts/check_metrics_schema.py --kind trace``), and the per-step
span timeline table.

A backend that never comes up (the round-5 tunnel-down failure) does
not silently lose the round: every mode first forces backend init and,
on failure, prints a structured ``{"parsed": null, "failure_reason":
...}`` row and exits :data:`BACKEND_FAILURE_EXIT_CODE` (13) — which
``perf_sentinel`` skips with a note instead of judging.

See PERF.md for the profiling breakdown behind the current number
(captured with apex_tpu.prof).
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


# --- measurement regime ------------------------------------------------------
#
# ONE definition of throughput for every row (VERDICT r3 item 2; the
# reference's single `Speed` definition, `tests/L1/common/compare.py`):
# the *device-time* step, measured by scanning K steps per dispatch and
# DIFFERENCING two trip counts — wall(K) = dispatch_overhead + K·step, so
# (wall(K2) − wall(K1)) / (K2 − K1) cancels the host/tunnel dispatch
# constant (~0.4 s through the axon remote runtime) exactly. Host wall
# per single-step dispatch is reported alongside as the secondary
# number. Sync is via host fetch of a scalar: block_until_ready does not
# actually block on the experimental axon platform.

_SCAN_KS = (4, 16)


def _scan_device_time(step, carry, const, *, n_carry, ks=_SCAN_KS,
                      repeats=3, fetch=None):
    """Device seconds per step via trip-count differencing.

    ``step(*carry, *const) -> (*new_carry, scalar)``; the carry is
    donated. Returns (device_dt, wall_dt, last_scalar) where wall_dt
    is host wall per step of a ks[0]-step dispatch — i.e. it still
    carries 1/ks[0] of the dispatch constant, NOT a true single-step
    dispatch (which nothing measures: the scan regime exists to
    amortize exactly that constant)."""
    fetch = fetch or (lambda out: float(np.asarray(
        jax.tree_util.tree_leaves(out[-1])[0]).ravel()[0]))

    def make(K):
        def run(*args):
            c, cst = args[:n_carry], args[n_carry:]

            def body(c, _):
                out = step(*c, *cst)
                return tuple(out[:n_carry]), out[n_carry]

            c2, scal = jax.lax.scan(body, tuple(c), None, length=K)
            return (*c2, scal[-1])

        return jax.jit(run, donate_argnums=tuple(range(n_carry)))

    walls = {}
    last = None
    state = tuple(carry)
    for K in ks:
        jstep = make(K)
        out = jstep(*state, *const)        # warmup (compile)
        last = fetch(out)                  # sync
        state = tuple(out[:n_carry])
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = jstep(*state, *const)
            last = fetch(out)              # sync
            best = min(best, time.perf_counter() - t0)
            state = tuple(out[:n_carry])
        walls[K] = best
    k1, k2 = ks
    device_dt = (walls[k2] - walls[k1]) / (k2 - k1)
    wall_single = walls[k1] / k1
    return max(device_dt, 1e-9), wall_single, last


def _resnet_step_builder(batch: int, size: int, opt_level: str = "O2",
                         monitor: bool = False):
    from apex_tpu import amp, models, ops
    from apex_tpu.optim import FusedSGD

    policy = amp.Policy.from_opt_level(opt_level)
    model = models.ResNet50(num_classes=1000, dtype=policy.compute_dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, size, size, 3).astype(np.float32))
    # inputs arrive pre-cast to the compute dtype, as the example's
    # prefetcher ships them (the reference casts on a side stream,
    # `main_amp.py:264-317`) — the in-graph fp32->half cast is not part
    # of the step being measured
    if policy.cast_model_type is not None:
        x = x.astype(policy.compute_dtype)
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)

    variables = model.init(jax.random.PRNGKey(0), x[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    amp_opt = amp.Amp(policy, FusedSGD(lr=0.1, momentum=0.9),
                      monitor=monitor)
    state = amp_opt.init(params)

    def step(state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb, train=True,
                mutable=["batch_stats"])
            loss = jnp.mean(ops.softmax_cross_entropy_loss(logits, yb))
            return loss, mut["batch_stats"]

        (loss, new_bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        state = amp_opt.apply_gradients(state, grads, finite)
        return state, new_bs, loss

    return step, (state, batch_stats), (x, y)


def _measure(batch: int, size: int, opt_level: str = "O2"):
    """(device img/s, wall img/s, loss) for the ResNet step."""
    step, carry, const = _resnet_step_builder(batch, size, opt_level)
    dev_dt, wall_dt, loss = _scan_device_time(step, carry, const,
                                              n_carry=2)
    return batch / dev_dt, batch / wall_dt, loss


# --- BASELINE.md config table (`python bench.py --all`) ----------------------

def _timeit(jstep, args, iters, warmup=3, rebind=None):
    """Time a donated-state step; ``rebind(out, args) -> args`` threads the
    new state back in. Syncs via host fetch (see note in _measure)."""
    out = None
    for _ in range(warmup):
        out = jstep(*args)
        if rebind:
            args = rebind(out, args)
    jax.tree_util.tree_map(
        lambda l: np.asarray(l),
        [l for l in jax.tree_util.tree_leaves(out)][:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jstep(*args)
        if rebind:
            args = rebind(out, args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0:1])
    return (time.perf_counter() - t0) / iters


def _bench_resnet(opt_level, batch, size, sync_bn=False):
    """Configs 1-3: ResNet-50 under a preset, optionally with SyncBN over
    a (1-device here, N on a pod) data mesh. The plain (non-SyncBN)
    configs delegate to _measure — one implementation of the ResNet step
    for both the headline metric and the table. Returns
    (device img/s, wall img/s)."""
    from apex_tpu import amp, models, ops, parallel
    from apex_tpu.optim import FusedSGD

    if not sync_bn:
        dev_img_s, wall_img_s, _loss = _measure(batch, size, opt_level)
        return dev_img_s, wall_img_s

    policy = amp.Policy.from_opt_level(opt_level)
    model = models.ResNet50(num_classes=1000, dtype=policy.compute_dtype,
                            bn_axis_name="data")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, size, size, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)

    def build(xb, yb):
        variables = model.init(jax.random.PRNGKey(0), xb[:2], train=True)
        amp_opt = amp.Amp(policy, FusedSGD(lr=0.1, momentum=0.9))
        return amp_opt, amp_opt.init(variables["params"]), \
            variables["batch_stats"]

    def step(amp_opt, state, batch_stats, xb, yb):
        def loss_fn(mp):
            logits, mut = model.apply(
                {"params": mp, "batch_stats": batch_stats}, xb,
                train=True, mutable=["batch_stats"])
            return jnp.mean(ops.softmax_cross_entropy_loss(logits, yb)), \
                mut["batch_stats"]
        (loss, bs), grads, state, finite = amp_opt.backward(
            state, loss_fn, has_aux=True)
        grads = parallel.sync_gradients(grads, "data")
        return amp_opt.apply_gradients(state, grads, finite), bs, loss

    mesh = parallel.data_parallel_mesh()
    amp_opt, state, bs = build(x, y)
    from jax.sharding import PartitionSpec as P
    mapped = jax.shard_map(
        lambda s, b, xb, yb: step(amp_opt, s, b, xb, yb),
        mesh=mesh, in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P()), check_vma=False)

    dev_dt, wall_dt, _ = _scan_device_time(mapped, (state, bs), (x, y),
                                           n_carry=2)
    return batch / dev_dt, batch / wall_dt


def _bench_dcgan(batch, iters):
    """Config 4: DCGAN multi-model/multi-loss — two Amp bundles, three
    scaled backwards per iteration (`examples/dcgan/main_amp.py:215-253`
    pattern)."""
    from apex_tpu import amp, models
    from apex_tpu.optim import FusedAdam

    # unmodified flax models driven through the auto_cast interceptor —
    # the O1 ergonomics path (bf16 compute without touching the model)
    policy = amp.Policy.from_opt_level("O1")
    G = models.Generator()
    D = models.Discriminator()
    rng = np.random.RandomState(0)
    z = jnp.asarray(rng.randn(batch, 1, 1, 100).astype(np.float32))
    real = jnp.asarray(rng.rand(batch, 64, 64, 3).astype(np.float32))

    gv = G.init(jax.random.PRNGKey(0), z, train=True)
    dv = D.init(jax.random.PRNGKey(1), real, train=True)
    ampG = amp.Amp(policy, FusedAdam(lr=2e-4, betas=(0.5, 0.999)))
    ampD = amp.Amp(policy, FusedAdam(lr=2e-4, betas=(0.5, 0.999)),
                   num_losses=2)
    gstate, dstate = ampG.init(gv["params"]), ampD.init(dv["params"])

    def bce(logit, target):
        return jnp.mean(jnp.maximum(logit, 0) - logit * target
                        + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    def step(gstate, dstate, g_bs, d_bs, z, real):
        with amp.auto_cast(policy):
            fake, g_mut = G.apply({"params": ampG.model_params(gstate),
                                   "batch_stats": g_bs}, z, train=True,
                                  mutable=["batch_stats"])
        g_bs = g_mut["batch_stats"]

        def d_real(mp):
            with amp.auto_cast(policy):
                out, mut = D.apply({"params": mp, "batch_stats": d_bs},
                                   real, train=True,
                                   mutable=["batch_stats"])
            return bce(out, 1.0), mut["batch_stats"]

        (lr_, d_bs2), gr, dstate, f1 = ampD.backward(
            dstate, d_real, loss_id=0, has_aux=True)
        dstate = ampD.apply_gradients(dstate, gr, f1)

        def d_fake(mp):
            with amp.auto_cast(policy):
                out, mut = D.apply({"params": mp, "batch_stats": d_bs2},
                                   jax.lax.stop_gradient(fake), train=True,
                                   mutable=["batch_stats"])
            return bce(out, 0.0), mut["batch_stats"]

        (lf, d_bs3), gf, dstate, f2 = ampD.backward(
            dstate, d_fake, loss_id=1, has_aux=True)
        dstate = ampD.apply_gradients(dstate, gf, f2)

        def g_loss(mp):
            with amp.auto_cast(policy):
                fake2, mut = G.apply({"params": mp, "batch_stats": g_bs},
                                     z, train=True,
                                     mutable=["batch_stats"])
                out = D.apply({"params": ampD.model_params(dstate),
                               "batch_stats": d_bs3}, fake2, train=True,
                              mutable=["batch_stats"])[0]
            return bce(out.astype(jnp.float32), 1.0), mut["batch_stats"]

        (lg, g_bs4), gg, gstate, f3 = ampG.backward(
            gstate, g_loss, has_aux=True)
        gstate = ampG.apply_gradients(gstate, gg, f3)
        return gstate, dstate, g_bs4, d_bs3, lg

    # the generator/discriminator step is sub-ms on device; scan K
    # iterations per dispatch so tunnel/host dispatch overhead (hundreds
    # of ms through the axon remote runtime) doesn't swamp the number.
    # K=20 measured ±40% run-to-run (the dispatch overhead IS the
    # number); 200 device-side steps per dispatch stabilize it.
    K = 200 if jax.default_backend() == "tpu" else 5

    def scanned(gstate, dstate, g_bs, d_bs, z, real):
        def body(carry, _):
            gs, ds, gb, db = carry
            gs, ds, gb, db, l = step(gs, ds, gb, db, z, real)
            return (gs, ds, gb, db), l
        (gs, ds, gb, db), ls = jax.lax.scan(
            body, (gstate, dstate, g_bs, d_bs), None, length=K)
        return gs, ds, gb, db, ls[-1]

    jstep = jax.jit(scanned, donate_argnums=(0, 1, 2, 3))

    # model FLOPs of ONE step from XLA cost analysis — the DCGAN MFU
    # denominator (VERDICT r2 item 9: no dash cells). NB: analyzed on
    # the unscanned step; cost analysis counts a while-loop body once
    # regardless of trip count, so the scanned program undercounts.
    from apex_tpu.prof import hlo as _hlo
    args0 = (gstate, dstate, gv["batch_stats"], dv["batch_stats"], z, real)
    try:
        flops_step = _hlo.cost_analysis(
            jax.jit(step), gstate, dstate, gv["batch_stats"],
            dv["batch_stats"], z, real)["flops"]
    except Exception:
        flops_step = 0.0

    def rebind(out, args):
        return (out[0], out[1], out[2], out[3], args[4], args[5])

    dt = _timeit(jstep, args0, iters, rebind=rebind)
    return batch * K / dt, dt / K, flops_step * K / dt


def _bert_step_builder(batch, seq, encoder=None, vocab=30000,
                       ddp=None, opt_level="O1"):
    """ONE construction of the BERT-LAMB MLM step (amp O1 + FusedLAMB,
    auto_cast forward) shared by the bench row, the apexlint flagship
    (`scripts/apexlint.py --flagship bert` — the program the smoke gate
    lints must be the program the bench measures), and
    `scripts/prof_bert.py`. ``encoder=None`` builds the full BertLarge;
    pass a scaled `models.BertEncoder` for CPU structural variants.
    ``ddp`` (a `parallel.DistributedDataParallel`) syncs the gradients
    between backward and apply — the per-shard step the apexlint
    `--mesh` cross-rank audit wraps in `shard_map`; the batch is then
    the GLOBAL batch. ``opt_level`` is the amp opt level (O1 is the
    measured BASELINE.md configuration; the apexlint
    ``--opt-level`` sweep builds the others). Returns
    ``(step, state, (toks, labels), policy, enc, variables)``.
    """
    from apex_tpu import amp, models
    from apex_tpu.optim import FusedLAMB

    policy = amp.Policy.from_opt_level(opt_level)
    enc = encoder if encoder is not None else models.BertLarge()
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, vocab, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, vocab, (batch, seq)), jnp.int32)
    variables = enc.init(jax.random.PRNGKey(0), toks[:1])
    amp_opt = amp.Amp(policy, FusedLAMB(lr=1e-3))
    state = amp_opt.init(variables["params"])

    def step(state, toks, labels):
        def loss_fn(mp):
            with amp.auto_cast(policy):
                return models.mlm_loss(enc, {"params": mp}, toks, labels)
        loss, grads, state, finite = amp_opt.backward(state, loss_fn)
        if ddp is not None:
            from apex_tpu.trace.spans import span
            grads = ddp.sync(grads)
            with span("ddp/loss_pmean", kind="collective"):
                # topology-aware: one psum per axis under a hierarchical
                # comm_plan, the plain flat pmean otherwise
                loss = ddp.pmean(loss)
        return amp_opt.apply_gradients(state, grads, finite), loss

    return step, state, (toks, labels), policy, enc, variables


def _bench_bert(batch, seq):
    """Config 5: BERT-Large MLM step with FusedLAMB + fused LayerNorm +
    flash attention."""
    step, state, (toks, labels), _policy, _enc, variables = \
        _bert_step_builder(batch, seq)
    dev_dt, wall_dt, _ = _scan_device_time(step, (state,),
                                           (toks, labels), n_carry=1)
    n_params = sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(variables["params"]))
    flops = 6.0 * n_params * batch * seq    # fwd+bwd transformer rule
    return batch / dev_dt, batch / wall_dt, flops / dev_dt


def run_all():
    from apex_tpu import models, prof

    on_tpu = jax.default_backend() == "tpu"
    size = 224 if on_tpu else 64
    iters = 10 if on_tpu else 2
    peak = prof.device_peak_flops() or float("inf")
    rows = []
    measured = {}       # name -> best device img/s (for the loader note)

    def resnet_row(name, opt_level, batch, sync_bn=False):
        # single-batch row == degenerate one-element sweep
        resnet_row_sweep(name, opt_level, (batch,), sync_bn=sync_bn)

    def resnet_row_sweep(name, opt_level, batches, sync_bn=False):
        """Measure each batch and RECORD each point (a sweep that keeps
        only the winner can hide a regression at the documented
        operating point — VERDICT r3 weak 7); the row reports the best,
        the note carries every point."""
        results, last_err = [], None
        for b in batches:
            try:
                dev_s, wall_s = _bench_resnet(opt_level, b, size,
                                              sync_bn=sync_bn)
            except Exception as e:
                last_err = e
                continue
            results.append((dev_s, wall_s, b))
        if not results:
            rows.append((name, "failed", "-", "-",
                         type(last_err).__name__ if last_err else "-"))
            return
        dev_s, wall_s, b = max(results)
        measured[name] = dev_s
        flops_img = models.RESNET50_FLOPS_PER_IMAGE * 3 * (size / 224) ** 2
        mfu = dev_s * flops_img / peak
        note = f"batch {b}"
        if len(results) > 1:
            note += " (" + ", ".join(
                f"b{bb}: {ds:.0f}" for ds, _, bb in results) + ")"
        rows.append((name, f"{dev_s:.0f} img/s", f"{mfu:.1%}",
                     f"{wall_s:.0f} img/s", note))

    resnet_row_sweep("ResNet-50 fp32 (O0)", "O0",
                     (128, 64) if on_tpu else (8,))
    resnet_row_sweep("ResNet-50 amp O2 + FusedSGD", "O2",
                     (256, 128) if on_tpu else (8,))
    resnet_row("ResNet-50 DP + SyncBN (per chip)", "O2",
               256 if on_tpu else 8, sync_bn=True)
    try:
        dcgan_batch = 128 if on_tpu else 8
        img_s, dt, flops_s = _bench_dcgan(dcgan_batch, iters)
        mfu_cell = f"{flops_s / peak:.1%}" if flops_s else "-"
        rows.append(("DCGAN multi-loss (G+2xD steps)",
                     f"{img_s:.0f} img/s", mfu_cell, "~same",
                     f"batch {dcgan_batch}"))
    except Exception as e:
        rows.append(("DCGAN multi-loss", "failed", "-", "-",
                     f"{type(e).__name__}"))
    try:
        b, s = (16, 512) if on_tpu else (2, 128)
        seq_s, wall_seq_s, flops_s = _bench_bert(b, s)
        rows.append((f"BERT-Large LAMB (seq {s})",
                     f"{seq_s:.1f} seq/s", f"{flops_s / peak:.1%}",
                     f"{wall_seq_s:.1f} seq/s", f"batch {b}"))
    except Exception as e:
        rows.append(("BERT-Large LAMB", "failed", "-", "-",
                     f"{type(e).__name__}"))

    # the resilience + input-pipeline row notes (ckpt stall wired
    # through --all per ROADMAP 5a leftover; loader headroom per 5b)
    host = "TPU host" if on_tpu else "CPU (bench host)"
    try:
        ck = _ckpt_row(64 if on_tpu else 8, size)
        ckpt_note = (
            f"- Async checkpointing (`ckpt_save_stall_ms`, {host}-"
            f"measured): capture stall {ck['async_stall_ms']:.1f} ms "
            f"per save vs {ck['sync_save_ms']:.1f} ms synchronous "
            f"save-and-wait, against a {ck['step_ms']:.1f} ms step — "
            f"{ck['stall_frac_of_step']:.1%} of a step at a "
            f"save-every-step cadence (<5% contract, "
            f"docs/checkpointing.md; also in default bench JSON).")
    except Exception as e:
        ckpt_note = (f"- Async checkpointing (`ckpt_save_stall_ms`): "
                     f"row failed ({type(e).__name__}).")
    try:
        curve = _loader_row()
        best_w = max(curve, key=curve.get)
        best = curve[best_w]
        per_chip = measured.get("ResNet-50 amp O2 + FusedSGD")
        loader_note = (
            "- Input pipeline headroom (ROADMAP 5b): decode-thread "
            "scaling, loader-only img/s on this host — "
            + ", ".join(f"w{w}: {v:.0f}" for w, v in sorted(
                curve.items())) + ".")
        if per_chip:
            headroom = best / per_chip
            loader_note += (
                f" Best {best:.0f} img/s vs {per_chip:.0f} img/s/chip "
                f"compute (amp O2 row) -> {headroom:.2f}x headroom; "
                f"chips-per-host input budget ~= "
                f"{int(best // per_chip)} chip(s) at full rate.")
            if headroom < 1.5:
                loader_note += (
                    " **FLAG: <1.5x compute headroom — input-bound "
                    "risk; scale decode hosts or shard files wider "
                    "before adding chips per host.**")
    except Exception as e:
        loader_note = (f"- Input pipeline headroom: loader row failed "
                       f"({type(e).__name__}).")
    try:
        gp = _goodput_row(batches[-1], size)
        lf = _link_fit_row()
        lf_txt = (f"{lf['bytes_per_s'] / 1e9:.3f} GB/s measured over "
                  f"{lf['n_devices']} local devices (alpha "
                  f"{lf['alpha_us']:.0f} us, residual "
                  f"{lf['residual']:.3f})" if "bytes_per_s" in lf
                  else lf.get("skipped", lf.get("failed", "n/a")))
        goodput_note = (
            f"- Goodput + link calibration ({host}): steady-state "
            f"`goodput_frac` {gp['goodput_frac']:.1%} on the "
            f"instrumented headline step (attribution closure "
            f"{'OK' if gp['closure_ok'] else 'BROKEN'}, worst step "
            f"{gp['worst_closure_err']:.2%}; buckets in default bench "
            f"JSON); `link_fit`: {lf_txt}. Per-step decomposition: "
            f"apex_tpu.monitor.GoodputLedger; measured MeshModel: "
            f"scripts/link_probe.py (docs/monitoring.md#goodput).")
    except Exception as e:
        goodput_note = (f"- Goodput + link calibration: row failed "
                        f"({type(e).__name__}).")
    try:
        rl = _roofline_row(256 if on_tpu else 8, size)
        wg = (rl.get("worst_gaps") or [None])[0]
        wg_txt = (f"worst gap {wg['family']}/{wg['op']} "
                  f"{wg['measured_us']:.0f} us vs "
                  f"{wg['attainable_us']:.0f} us attainable "
                  f"(eff {wg['efficiency']:.0%})" if wg else
                  "no measured gaps"
                  + ("" if rl.get("measured") else
                     " (AOT-only off-TPU — the measured join is "
                     "CI-pinned on the committed BERT fixture)"))
        roofline_note = (
            f"- Roofline + sentinel ({host}): per-op efficiency "
            f"attribution of the headline step over "
            f"{rl.get('n_ops')} ops — {wg_txt}; `roofline_worst_gap` "
            f"+ `sentinel_regressions` ride the default bench JSON "
            f"(apex_tpu.prof.roofline / prof.sentinel; gate: "
            f"`scripts/perf_sentinel.py --check BENCH_r0*.json`, "
            f"audit: `scripts/roofline_audit.py --cpu8`, "
            f"docs/profiling.md#roofline).")
    except Exception as e:
        roofline_note = (f"- Roofline + sentinel: row failed "
                         f"({type(e).__name__}).")
    try:
        from apex_tpu.ops import autotune as _at
        st = _at.db_stats()
        autotune_note = (
            f"- Kernel autotuner ({host}): committed tuning DB "
            f"(scripts/kernel_tuning_db.json) holds {st['entries']} "
            f"sweep winner(s) over families "
            f"{'/'.join(st['tuned_families'])}; every dispatch seam "
            f"consults it at trace time (exact `family|dims|dtype|"
            f"chip` key, miss = bit-identical defaults), "
            f"`tuned_families` + `autotune_db_hits` ride the default "
            f"bench JSON off the same AOT executable (sweep: "
            f"`scripts/kernel_tune.py --update-db`, audit: "
            f"`scripts/kernel_tune.py --cpu8 --interpret`, "
            f"docs/profiling.md#autotuner).")
    except Exception as e:
        autotune_note = (f"- Kernel autotuner: note failed "
                         f"({type(e).__name__}).")

    dev = getattr(jax.devices()[0], "device_kind", "?")
    lines = [
        "# BENCH_TABLE — BASELINE.md config table",
        "",
        f"Device: {dev} (single chip). MFU vs {peak/1e12:.0f} TFLOP/s "
        f"bf16 peak.",
        "",
        "ONE measurement regime for every row (the reference's single "
        "`Speed` definition, `tests/L1/common/compare.py:40-46`): "
        "**Throughput/MFU are device-time** — K steps scanned per "
        "dispatch, two trip counts differenced to cancel the host/"
        "tunnel dispatch constant. `wall` is the secondary host-side "
        "number: host wall per step of a K=4-step dispatch (carries "
        "1/4 of the dispatch constant; on a local host it converges "
        "to the device number).",
        "",
        "| Config | Throughput (device) | MFU | wall | Notes |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append("| " + " | ".join(r) + " |")
    lines += [
        "",
        "Notes:",
        "- The SyncBN row runs the sync code path (fused BN unit with "
        "stats/backward-sums collectives) over a 1-device mesh on this "
        "host: the psums are no-ops, so the row measures the sync "
        "path's compute overhead vs the plain row — NOT cross-replica "
        "communication (that is exercised by dryrun_multichip on the "
        "virtual mesh). Round 3 note: within ~1% of plain (round 2 "
        "was −8%; the fused unit removed the extra stats pass).",
        "- DCGAN MFU uses XLA cost-analysis FLOPs of one unscanned "
        "step; throughput is measured over 200 scanned steps per "
        "dispatch (dispatch overhead < 0.5% there, so device ≈ wall).",
        "- Sweep rows record EVERY measured point in the note (a "
        "sweep that keeps only the winner can hide a regression at "
        "the documented operating point).",
        ckpt_note,
        loader_note,
        goodput_note,
        roofline_note,
        autotune_note,
    ]
    open("BENCH_TABLE.md", "w").write("\n".join(lines) + "\n")
    print("\n".join(lines))


def run_monitor(steps: int = 20, jsonl_path: str = "MONITOR.jsonl"):
    """`python bench.py --monitor`: drive the headline ResNet step with
    live telemetry — the apex_tpu.monitor consumer demo. Emits the
    stdout health table plus a JSONL stream (MONITOR.jsonl) that
    `scripts/check_metrics_schema.py` validates; flushes amortize the
    device→host fetch over 5-step windows, so the loop itself keeps the
    zero-extra-dispatch property of the unmonitored bench."""
    from apex_tpu import monitor

    on_tpu = jax.default_backend() == "tpu"
    batch, size = (128, 224) if on_tpu else (8, 64)
    step, (state, batch_stats), (x, y) = _resnet_step_builder(
        batch, size, monitor=True)
    # donate the carried state (apexlint APX101: an undonated
    # state+batch_stats double-allocates them every step — this loop
    # shipped without donation until the lint rule flagged it)
    jstep = jax.jit(step, donate_argnums=(0, 1))
    # donation_safe: the donated state carries the metrics pytree, so
    # the logger snapshots each record (async scalar copies) instead of
    # buffering buffers the next dispatch would invalidate
    logger = monitor.MetricsLogger(
        sinks=[monitor.StdoutSink(), monitor.JSONLSink(jsonl_path)],
        flush_every=5, donation_safe=True)
    logger.attach(jstep, state, batch_stats, x, y)
    for _ in range(steps):
        state, batch_stats, _loss = jstep(state, batch_stats, x, y)
        logger.record(state.metrics, images_per_step=batch)
    logger.close()
    print(f"wrote {jsonl_path} "
          f"(validate: python scripts/check_metrics_schema.py {jsonl_path})")


def run_trace(steps: int = 3, chrome_path: str = "TRACE.json",
              events_path: str = "TRACE_EVENTS.jsonl"):
    """`python bench.py --trace`: the apex_tpu.trace consumer demo — a
    short ResNet loop under a Tracer with host spans per phase, a flight
    recorder wired through the tracer, and the monitor trace-event
    channel streaming the step timeline. Artifacts: Chrome-trace JSON
    (loads in Perfetto / chrome://tracing), a trace-event JSONL stream,
    and the StepTimeline table on stdout."""
    from apex_tpu import monitor, trace

    on_tpu = jax.default_backend() == "tpu"
    batch, size = (128, 224) if on_tpu else (8, 64)
    step, (state, batch_stats), (x, y) = _resnet_step_builder(
        batch, size, monitor=True)
    # carried state donated (apexlint APX101, same fix as run_monitor)
    jstep = jax.jit(step, donate_argnums=(0, 1))

    tracer = trace.Tracer()
    recorder = trace.FlightRecorder("TRACE_CRASH.jsonl",
                                    tracer=tracer).install()
    logger = monitor.MetricsLogger(
        sinks=[monitor.StdoutSink()],
        trace_sink=monitor.JSONLSink(events_path), flush_every=steps)
    rank = 0
    tracer.subscribe(lambda st: logger.record_event(st.to_event(rank)))

    with tracer:
        for i in range(steps):
            with trace.step(i):
                with trace.span("dispatch"):
                    state, batch_stats, loss = jstep(state, batch_stats,
                                                     x, y)
                with trace.span("fetch"):
                    # sync point: materialize the loss so the span
                    # timeline measures real step time, not async submit
                    float(np.asarray(loss))
                # one donation-safe snapshot feeds both consumers (the
                # donated next dispatch would invalidate the originals)
                m = monitor.metrics_snapshot(state.metrics)
                logger.record(m, images_per_step=batch)
                recorder.record_metrics(m)
    logger.close()
    recorder.uninstall()
    tracer.write_chrome_trace(chrome_path)
    print(tracer.timeline().table())
    print(f"wrote {chrome_path} (load in Perfetto) and {events_path} "
          f"(validate: python scripts/check_metrics_schema.py "
          f"--kind trace {events_path})")


def _ddp_comm_modes():
    """Static DDP comm-mode column for the default bench output: the
    bucket plan + analytic wire bytes per compression mode over the
    headline model's parameter tree (host-side avals only — no device
    or pod needed, so the driver can verify the comm modes exist and
    halve/quarter bytes without hardware). The measured wire audit is
    `scripts/pod_comm_budget.py` (`--cpu8` for the CI variant)."""
    from apex_tpu import models
    from apex_tpu.parallel import comm

    model = models.ResNet50(num_classes=1000)
    x1 = jnp.ones((2, 224, 224, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x1, train=True))
    leaves = jax.tree_util.tree_leaves(variables["params"])
    plan = comm.bucket_plan(leaves, comm.DEFAULT_MESSAGE_SIZE)
    logical = comm.wire_bytes(plan, None)
    out = {"message_size": comm.DEFAULT_MESSAGE_SIZE,
           "n_buckets": len(plan),
           "logical_mib": round(logical / 2 ** 20, 2), "modes": {}}
    for mode in (None, "bf16", "int8"):
        w = comm.wire_bytes(plan, mode)
        out["modes"][mode or "exact"] = {
            "wire_mib": round(w / 2 ** 20, 2),
            "ratio": round(w / logical, 4)}

    # the hierarchical schedule over the canonical 2-slice model
    # (collectives v2): mixed per-hop dtypes accounted (all-reduce-
    # equivalent units, so the ratio is against the same flat-fp32
    # denominator), plus the predicted DCN milliseconds next to what
    # the FLAT sync's DCN crossing would cost — the number APX203
    # prints, now with the hierarchical answer beside it. wire_bytes
    # feeds the perf sentinel's ddp_wire_bytes metric
    # (scripts/perf_baseline.json): a regression toward flat sync
    # multiplies it.
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    from apex_tpu.parallel import hierarchy

    mm = parse_mesh_spec("dp2x4")
    cplan = hierarchy.plan_comm(mm, grad_bytes=logical)
    w = comm.wire_bytes(plan, cplan)
    pred = cplan.predicted_seconds(logical)
    out["modes"]["hier_int8"] = {
        "wire_mib": round(w / 2 ** 20, 2),
        "ratio": round(w / logical, 4),
        "wire_bytes": int(w),
        "dtype_by_link": {k: (v or "f32")
                          for k, v in cplan.dtype_by_link().items()},
        "predicted_dcn_ms": round(pred.get("dcn", 0.0) * 1e3, 3),
        "flat_dcn_ms": round(mm.hop_seconds(logical, "dcn") * 1e3, 3),
        "source": cplan.source}
    return out


def _bert_row(on_tpu: bool):
    """BERT-Large LAMB as a default-output row (the 61.0%-MFU headline
    workload — VERDICT r5 wanted it driver-verifiable without --all).
    Measured only on an accelerator: XLA:CPU takes minutes just to
    COMPILE the 24-layer module (measured 2m+ per scan program), so the
    CPU path reports the skip instead of blowing the bench budget
    (`bench.py --all` still measures it on CPU at tiny shapes)."""
    from apex_tpu import prof

    if not on_tpu:
        return {"skipped": "cpu backend — BERT-Large compile alone "
                           "takes minutes; measured on TPU"}
    b, s = 16, 512
    seq_s, wall_seq_s, flops_s = _bench_bert(b, s)
    peak = prof.device_peak_flops()
    return {"seq_per_sec": round(seq_s, 2),
            "wall_seq_per_sec": round(wall_seq_s, 2),
            "mfu": round(flops_s / peak, 4) if peak else 0.0,
            "batch": b, "seq": s}


def _ckpt_row(batch: int, size: int, steps: int = 4):
    """The ``ckpt_save_stall_ms`` column: per-step stall of an async
    checkpoint snapshot (apex_tpu.ckpt) vs a fully synchronous
    save-and-wait, against the measured plain step time — the
    <5%-of-step async-overhead claim as a measured number
    (docs/checkpointing.md). A short wall-clock loop on the headline
    step (the scan-differencing regime can't interleave host-side
    saves), small-N medians, temp dir discarded."""
    import statistics
    import tempfile

    from apex_tpu import ckpt as _ckpt

    step, (state, batch_stats), (x, y) = _resnet_step_builder(batch, size)
    jstep = jax.jit(step, donate_argnums=(0, 1))

    def run(mgr, mode, state, batch_stats):
        """steps plain steps (warm), then ONE measured save — the
        save-every-N cadence's marginal cost, not back-to-back saves
        serialized on the double buffer. The save path itself is warmed
        first (a throwaway save+wait): the first capture jit-compiles
        the batched copy program, a once-per-process cost that would
        otherwise masquerade as steady-state stall."""
        walls, stalls = [], []
        for i in range(steps):
            t0 = time.perf_counter()
            state, batch_stats, loss = jstep(state, batch_stats, x, y)
            float(np.asarray(loss))           # sync: true step wall
            walls.append((time.perf_counter() - t0) * 1e3)
            if mgr is not None:
                s = mgr.save(i, state, block=(mode == "sync"))
                mgr.wait()        # quiesce: isolate the NEXT stall
                if i > 0:         # i==0 warms (copy-program compile)
                    stalls.append(s)
        stall = min(stalls) if stalls else None      # best-of, like
        return (statistics.median(walls), stall,     # _scan_device_time
                state, batch_stats)

    step_ms, _, state, batch_stats = run(None, "none", state, batch_stats)
    with tempfile.TemporaryDirectory() as tmp:
        mgr = _ckpt.CheckpointManager(tmp + "/a", keep=1)
        _, async_ms, state, batch_stats = run(mgr, "async", state,
                                              batch_stats)
        mgr = _ckpt.CheckpointManager(tmp + "/s", keep=1)
        _, sync_ms, state, batch_stats = run(mgr, "sync", state,
                                             batch_stats)
    return {"async_stall_ms": round(async_ms, 3),
            "sync_save_ms": round(sync_ms, 3),
            "step_ms": round(step_ms, 3),
            "stall_frac_of_step": round(async_ms / step_ms, 4)
            if step_ms else None}


def _loader_row(workers=(1, 2, 4, 8, 16), batch: int = 32,
                steps: int = 4, size: int = 96):
    """Decode-thread scaling curve: loader-only img/s per worker count
    on a synthetic ImageFolder (ROADMAP item 5b). Decode is HOST work —
    the curve characterizes the machine driving the chips, not the
    chips — so the row exists to answer one question: how many chips'
    worth of input can one host feed? The BENCH_TABLE note divides the
    best point by the per-chip compute rate into a chips-per-host input
    budget and flags anything under 1.5x headroom as input-bound risk."""
    import tempfile

    from apex_tpu.data import pipeline as dp

    curve = {}
    with tempfile.TemporaryDirectory() as tmp:
        dp.make_fake_imagefolder(tmp, n_classes=4, per_class=48,
                                 size=160, seed=0)
        for w in workers:
            with dp.ImageFolderSource(tmp, batch=batch, size=size,
                                      workers=int(w), seed=0) as src:
                curve[int(w)] = round(dp.measure_source(
                    src.batches(steps + 2), steps=steps), 1)
    return curve


def _goodput_row(batch: int, size: int, steps: int = 4):
    """The ``goodput_frac`` column: drive the headline step a few
    instrumented steps under a Tracer + GoodputLedger (the same
    host-span pattern as ``--trace``) and report the steady-state
    goodput fraction with its bucket breakdown and the attribution-
    closure check (docs/monitoring.md#goodput). Step 0 is excluded
    from the fraction — it folds the trace+compile into the
    ``recompile`` bucket by design."""
    from apex_tpu import monitor, trace

    step, (state, batch_stats), (x, y) = _resnet_step_builder(batch, size)
    jstep = jax.jit(step, donate_argnums=(0, 1))
    tracer = trace.Tracer()
    ledger = monitor.GoodputLedger(tracer)
    with tracer:
        for i in range(steps):
            with trace.step(i):
                with trace.span("dispatch"):
                    state, batch_stats, loss = jstep(state, batch_stats,
                                                     x, y)
                with trace.span("fetch"):
                    float(np.asarray(loss))
    ok, worst = ledger.check_closure()
    tail = ledger.steps[1:] or ledger.steps
    fracs = [r.goodput_frac for r in tail if r.goodput_frac is not None]
    frac = sum(fracs) / len(fracs) if fracs else None
    return {"goodput_frac": round(frac, 4) if frac is not None else None,
            "closure_ok": bool(ok),
            "worst_closure_err": round(worst, 6),
            "steps": len(ledger.steps),
            "buckets_ms": {k: round(v, 3)
                           for k, v in ledger.steps[-1].buckets.items()}}


def _pod_row(n_ranks: int = 4, steps: int = 3):
    """The ``pod_goodput`` / ``comm_skew_p99`` / ``comm_drift_ratio``
    columns (apex_tpu.trace.podview + apex_tpu.monitor.comm_drift;
    the merge/blame/drift math is asserted by
    ``scripts/pod_audit.py --cpu8``, this row measures it live).

    The pod is EMULATED on this one host: the same tiny
    collective-tagged step runs ``n_ranks`` times, each run's span
    stream tagged as one rank on its own Tracer clock origin, then
    merged exactly as real per-rank streams would be — so the skew
    columns gauge the pipeline plus real run-to-run jitter
    (single-digit ms), not cross-host laggards; multi-host runs feed
    the same join with real ranks. ``comm_drift_ratio`` is fully
    measured: linkbench calibrates the local mesh, plan_comm schedules
    against it, and measure_hops times each hop (worst symmetric
    measured/predicted ratio — 1.0 means the link model holds)."""
    from jax.sharding import Mesh

    from apex_tpu import monitor, trace
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    from apex_tpu.parallel import plan_comm

    w = jax.random.normal(jax.random.PRNGKey(0), (256, 256),
                          jnp.float32)
    step_fn = jax.jit(lambda a: jnp.tanh(a @ a))
    jax.block_until_ready(step_fn(w))     # warm: compile outside spans

    events, tracers = [], []
    for r in range(n_ranks):
        tracer = trace.Tracer()
        with tracer:
            for i in range(steps):
                with trace.step(i):
                    with trace.span("dispatch"):
                        out = step_fn(w)
                    with trace.span("grad/sync", kind="collective"):
                        jax.block_until_ready(out)
        events.extend(tracer.span_events(rank=r))
        tracers.append(tracer)

    pod = trace.PodTimeline.merge(events)
    skews = sorted(c.skew_ms for c in pod.collective_skew())
    p99 = (skews[min(int(len(skews) * 0.99), len(skews) - 1)]
           if skews else None)

    # re-fold rank 0's steps with the pod-measured skew joined, so
    # pod_goodput is the fraction AFTER the comm_skew/comm_wire split
    ledger = monitor.GoodputLedger()
    for (r, s), ms in sorted(pod.rank_step_skew().items(),
                             key=lambda kv: (kv[0][1] or 0)):
        if r == 0:
            ledger.note_pod_skew(ms, step=s)
    for st in tracers[0].steps:
        ledger.on_step(st)
    ok, worst = ledger.check_closure()
    fracs = [rec.goodput_frac for rec in ledger.steps
             if rec.goodput_frac is not None]
    pod_goodput = sum(fracs) / len(fracs) if fracs else None

    devs = jax.devices()
    if len(devs) < 2:
        drift = {"skipped": "single device — a link needs two ends"}
        ratio = None
    else:
        template = parse_mesh_spec(f"ici{len(devs)}")
        mesh = Mesh(np.array(devs), ("data",))
        model, _, _ = monitor.calibrate(mesh, template, iters=2)
        plan = plan_comm(model, grad_bytes=1 << 20, dtypes=(None,))
        measured = monitor.measure_hops(plan, mesh, iters=2)
        report = monitor.compare_comm_drift(plan, measured,
                                            tolerance=8.0)
        ratio = round(report.drift_ratio, 3)
        drift = {"comm_drift_ratio": ratio,
                 "stale": report.stale,
                 "tolerance": report.tolerance,
                 "plan": plan.describe(),
                 "hops": [{"hop": h.hop, "op": h.op, "link": h.link,
                           "predicted_ms": round(h.predicted_ms, 4),
                           "measured_ms": round(h.measured_ms, 4),
                           "ratio": round(h.ratio, 3)}
                          for h in report.hops]}
    return {"pod_goodput": (round(pod_goodput, 4)
                            if pod_goodput is not None else None),
            "comm_skew_p99": (round(p99, 4) if p99 is not None
                              else None),
            "comm_drift_ratio": ratio,
            "closure_ok": bool(ok),
            "worst_closure_err": round(worst, 6),
            "n_ranks": n_ranks, "emulation": "sequential-local",
            "drift": drift}


def _link_fit_row():
    """The ``link_fit`` column: a quick alpha-beta calibration of the
    local device mesh (apex_tpu.monitor.linkbench — the same sweep
    `scripts/link_probe.py` runs, one flat ICI axis over the local
    devices). Single-device hosts report the skip: a link needs two
    ends."""
    from jax.sharding import Mesh

    from apex_tpu import monitor
    from apex_tpu.lint.mesh_model import parse_mesh_spec

    devs = jax.devices()
    if len(devs) < 2:
        return {"skipped": f"single {getattr(devs[0], 'platform', '?')} "
                           "device — link calibration needs >= 2"}
    template = parse_mesh_spec(f"ici{len(devs)}")
    mesh = Mesh(np.array(devs), ("data",))
    model, fits, _ = monitor.calibrate(mesh, template, iters=3)
    cal = model.calibration.get("ici", {})
    return {"link": "ici", "n_devices": len(devs),
            "bytes_per_s": cal.get("bytes_per_s"),
            "alpha_us": cal.get("alpha_us"),
            "residual": cal.get("residual"),
            "n_samples": cal.get("n_samples")}


def _roofline_row(batch: int, size: int):
    """The ``roofline_worst_gap`` column: per-op efficiency attribution
    of the headline step (apex_tpu.prof.roofline). On TPU a short
    profiled run joins MEASURED per-op device time with the analytic
    HLO costs against the chip's peak table; off-TPU the row is
    AOT-only (analytic classification, no gaps — the measured join is
    regression-tested in CI off the committed fixtures by
    ``scripts/roofline_audit.py --cpu8``). The profiled twin is a
    separate undonated jit, so the measured bench path is untouched."""
    from apex_tpu import prof

    step, (state, batch_stats), (x, y) = _resnet_step_builder(batch, size)
    jitted = jax.jit(step)
    compiled = jitted.lower(state, batch_stats, x, y).compile()
    profile = None
    if jax.default_backend() == "tpu":
        profile = prof.profile_step(jitted, state, batch_stats, x, y,
                                    iters=2, warmup=1).profile
        if not profile.ops:
            profile = None
    rep = prof.roofline_report(compiled, profile)
    return rep.summary(k=3)


def _numerics_row():
    """The ``numerics_underflow_frac`` column: a freshly MEASURED
    fp8-readiness gauge (apex_tpu.monitor.numerics /
    docs/numerics.md). A small deterministic BERT-shaped MLM
    trajectory (structural encoder, amp O1 + FusedLAMB — the
    numerics_audit subject downscaled) runs 4 observed steps; the
    column is the worst amp/grads site's fp8-e4m3 UNDERFLOW fraction
    AT that format's own recommended power-of-two scale — i.e. the
    underflow fp8 would experience after optimal delayed scaling,
    which rises only when a site's dynamic RANGE widens beyond the
    format's span (a scale shift cannot fix that; the scale's margin
    reserves the saturation headroom, so widening surfaces as the
    small tail underflowing — the matching saturation fraction rides
    along as its own context field). That is the numeric-health
    regression the sentinel gate (scripts/perf_baseline.json) watches
    the same way it watches a perf one."""
    import numpy as _np

    from apex_tpu import amp, models
    from apex_tpu.monitor import numerics as nx
    from apex_tpu.optim import FusedLAMB

    policy = amp.Policy.from_opt_level("O1")
    enc = models.BertEncoder(1000, hidden=64, layers=1, heads=2,
                             max_len=16)
    rng = _np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 1000, (4, 16)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, 1000, (4, 16)), jnp.int32)
    variables = enc.init(jax.random.PRNGKey(0), toks[:1])
    amp_opt = amp.Amp(policy, FusedLAMB(lr=1e-3))
    state = amp_opt.init(variables["params"])
    sites = amp_opt.numerics_sites(state.params)
    ncfg = nx.NumericsConfig()
    ns = nx.numerics_init(ncfg, sites=sites)

    def loss_fn(mp, toks, labels):
        with amp.auto_cast(policy):
            return models.mlm_loss(enc, {"params": mp}, toks, labels)

    @jax.jit
    def step(state, ns, toks, labels):
        state, loss, _finite, ns = amp_opt.step(
            state, loss_fn, toks, labels, numerics=(ns, ncfg))
        return state, ns, loss

    for _ in range(4):
        state, ns, _loss = step(state, ns, toks, labels)
    # current formats per site (cast copy at the policy's half dtype,
    # grads/updates at fp32) — without them every verdict's ok is None
    # and the surprises context column could never read anything
    half = nx.format_of_dtype(policy.compute_dtype) or "fp32"
    cur = {s: (half if s.startswith("amp/cast/") else "fp32")
           for s in sites}
    report = nx.precision_report(ns, sites, current_dtypes=cur)
    worst_site, worst, worst_sat, worst_unscaled = None, -1.0, 0.0, 0.0
    for r in report.rows:
        if not r.site.startswith("amp/grads/"):
            continue
        f8 = r.by_format["fp8_e4m3"]
        # the gauge is the UNDERFLOW half, matching its name (the
        # recommended scale reserves saturation headroom by margin,
        # so range widening shows up as the small tail underflowing);
        # saturation rides along as its own context field
        if f8["underflow"] > worst:
            worst_site, worst = r.site, f8["underflow"]
            worst_sat = f8["saturation"]
            worst_unscaled = f8["unscaled_underflow"]
    return {"underflow_frac": round(max(worst, 0.0), 6),
            "worst_site": worst_site,
            "worst_site_saturation_frac": round(worst_sat, 6),
            "worst_site_unscaled_underflow": round(worst_unscaled, 6),
            "n_sites": len(sites),
            "n_fp8_candidates": len(report.fp8_candidates()),
            "surprises": len(report.surprises())}


def _dynamics_row(steps: int = 6):
    """The ``gns`` + ``grad_cosine_min`` columns: freshly MEASURED
    training-dynamics gauges (apex_tpu.monitor.dynamics /
    docs/dynamics.md) off ONE instrumented step — a small
    data-parallel SGD step over every local device, with the
    ``ddp/dynamics_*`` probe collectives and the dynamics fold inside
    the same jit. The zero-extra-compiles property is asserted INLINE:
    after the first call compiles the one executable, the remaining
    observed steps (including on/off fold cadence flips) must add
    ZERO backend compiles — the fold is a cond branch, not a second
    program. On a single-device host the GNS column is null by
    contract (the estimator needs world > 1) and the cosine of the
    one replica against itself is 1.0; the sentinel gate skips null
    columns with a note."""
    import numpy as _np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.monitor import dynamics as dx
    from apex_tpu.parallel import distributed as dist
    from apex_tpu.prof import compile_watch as _cw

    devs = jax.devices()
    world, per = len(devs), 4
    mesh = Mesh(_np.array(devs), ("data",))
    rng = _np.random.RandomState(0)
    w0 = {"w": jnp.asarray(rng.randn(32, 8).astype("float32") * 0.1)}
    x = jnp.asarray(rng.randn(world * per, 32).astype("float32"))
    y = jnp.asarray(rng.randn(world * per, 8).astype("float32"))
    cfg = dx.DynamicsConfig(check_every=2, local_batch=per)
    sites = dx.site_names({"dynamics/update": w0})
    ds = dx.dynamics_init(cfg, sites=sites, world=world)

    def inner(w, ds, xb, yb):
        g_local = jax.grad(
            lambda w: jnp.mean(jnp.square(xb @ w["w"] - yb)))(w)
        g = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "data"), g_local)
        new_w = jax.tree_util.tree_map(lambda p, u: p - 0.05 * u, w, g)
        ds = dx.dynamics_observe(
            ds, cfg,
            lambda: {"dynamics/update": jax.tree_util.tree_map(
                lambda a, b: a - b, new_w, w)},
            probe=lambda: dist.dynamics_probe(g_local, g, "data"),
            grads={"dynamics/update": g},
            weights={"dynamics/update": w})
        return new_w, ds

    @jax.jit
    def step(w, ds, x, y):
        return jax.shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P()), check_vma=False)(w, ds, x, y)

    w = w0
    w, ds = step(w, ds, x, y)        # first call compiles the ONE program
    before = int(_cw.global_counters()["compiles"])
    for _ in range(steps - 1):
        w, ds = step(w, ds, x, y)
    added = int(_cw.global_counters()["compiles"]) - before
    assert added == 0, (
        f"dynamics-instrumented step retraced: {added} extra compiles "
        f"over {steps - 1} steady-state steps")
    rep = dx.dynamics_report(ds, sites, local_batch=per)
    return {"gns": rep.gns, "b_crit": rep.b_crit,
            "grad_cosine_min": rep.cos_min,
            "grad_cosine_mean": rep.cos_mean,
            "world": world, "check_count": rep.check_count,
            "steady_state_extra_compiles": added}


def _sentinel_row(current):
    """The ``sentinel_regressions`` column: judge THIS bench run (plus
    the committed BENCH_r0*.json trajectory) through the noise-aware
    perf-regression gate (apex_tpu.prof.sentinel / docs/profiling.md
    #sentinel). The current row only joins the trajectory when it was
    measured on the same device kind — a CPU smoke run is not a
    regression against the TPU history, it is skipped with a note."""
    import glob as _glob
    import os as _os

    from apex_tpu.prof import sentinel as sn

    repo = _os.path.dirname(_os.path.abspath(__file__))
    rows = sn.load_rows(sorted(_glob.glob(
        _os.path.join(repo, "BENCH_r0*.json"))))
    hist_dev = next((r["row"].get("extra", {}).get("device")
                     for r in rows if r.get("row")), None)
    cur_dev = current.get("extra", {}).get("device")
    if not rows or cur_dev != hist_dev:
        # the column means "unwaived regressions of THIS row"; a
        # cross-device comparison (CPU smoke vs the TPU history) or an
        # absent trajectory judges nothing, so it reports None, not a
        # verdict about some already-committed row
        return {"n_regressions": None, "regressed": [], "judged": None,
                "note": (f"current row ({cur_dev}) not judged against "
                         f"the {hist_dev} trajectory — device mismatch"
                         if rows else "no committed trajectory")}
    rows.append({"path": "(this run)", "row": current,
                 "metrics": sn.extract_metrics(current), "note": None})
    waivers = sn.load_baseline(
        _os.path.join(repo, "scripts", "perf_baseline.json"))
    rep = sn.check_trajectory(rows, waivers=waivers)
    return {"n_regressions": len(rep.regressions),
            "regressed": [v.metric for v in rep.regressions],
            "judged": rep.subject, "note": None}


def _load_mesh_explain():
    """Load scripts/mesh_explain.py as a module — its price_candidate
    is the ONE per-axis wire-pricing join (registry scope→axis + model
    link budgets); bench must reuse it, not re-derive it."""
    import importlib.util as _ilu
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scripts", "mesh_explain.py")
    spec = _ilu.spec_from_file_location("mesh_explain", path)
    mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _memory_row(batch: int, size: int):
    """The `peak_hbm_bytes` + `lint_findings` columns: AOT-compile the
    headline step (one compile, ZERO dispatches — the measured path is
    untouched) and read the footprint + apexlint report off the same
    executable. The compile is donated like the measured scan program
    (an undonated compile here was itself a donation-miss apexlint
    flagged — the report must describe the program actually measured).
    On TPU the runtime allocator's peak-bytes-in-use (which saw the
    measured run) is authoritative; off-TPU the report's peak-live
    estimate stands in. Also returns the class split so a driver diff
    can attribute a footprint regression."""
    from apex_tpu import amp, lint, prof

    step, (state, batch_stats), (x, y) = _resnet_step_builder(batch, size)
    compiled = jax.jit(step, donate_argnums=(0, 1)).lower(
        state, batch_stats, x, y).compile()
    rep = prof.memory_report(compiled, batch_size=batch)
    sample = prof.device_memory_sample()
    peak = sample.get("peak_bytes_in_use")
    policy = amp.Policy.from_opt_level("O2")
    # ONE trace shared by lint_step's jaxpr-side passes and the
    # precision analysis below (the same economy lint_step itself
    # applies internally)
    step_jaxpr = jax.make_jaxpr(step)(state, batch_stats, x, y)
    lint_rep = lint.lint_step(
        step, state, batch_stats, x, y,
        policy=policy, compiled=compiled, jaxpr=step_jaxpr,
        fn_name="resnet50_o2_step")
    # cross-rank congruence off the SAME executable (apexlint SPMD
    # pass): trivially 0 collectives on the single-chip headline, the
    # live deadlock canary once the measured step spans a mesh
    schedule = lint.extract_collective_schedule(compiled.as_text())
    spmd_errors = sum(
        1 for f in lint.congruence_findings(schedule)
        if f.severity == "error") if schedule else 0
    # per-axis sharding observatory off the SAME executable: shard
    # disposition + wire pricing via mesh_explain's price_candidate
    # (pure text+model arithmetic) — the compile_watch snapshot around
    # the block proves zero additional compiles ride the bench
    from apex_tpu.lint.mesh_model import parse_mesh_spec
    from apex_tpu.prof import compile_watch as _cw
    compiles_before = int(_cw.global_counters()["compiles"])
    # precision certification off the SAME trace + executable: static
    # APX3xx verdict, and — when the committed BERT numerics fixture is
    # present — the preflight's measured-safe candidate count (all
    # strictly AOT, inside the zero-extra-compiles pin)
    pa = lint.precision_analysis(step_jaxpr, policy=policy)
    precision_errors = sum(1 for f in pa.findings
                           if f.severity == "error")
    fixture = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tests", "fixtures",
                           "bert_numerics_stats.json")
    preflight_candidates = None
    if os.path.exists(fixture):
        from apex_tpu.monitor import numerics as _nx
        with open(fixture) as f:
            pf = lint.precision_preflight(
                step_jaxpr, stats=_nx.stats_from_json(f.read()),
                policy=policy, hlo_text=compiled.as_text())
        preflight_candidates = len(pf.candidates)
    # the headline step is single-chip: a 1-wide flat data axis — the
    # columns exist (and are sentinel-gated) from day one so the mesh
    # flagships inherit a populated schema, not a new column
    mm = parse_mesh_spec("ici1")
    sr = prof.shard_report(compiled, mm, report=rep)
    price = _load_mesh_explain().price_candidate(compiled.as_text(), mm)
    axis_hbm = {ax: sr.axis_bytes(ax) for ax in sr.axis_names}
    # rank by mesh_explain's candidate key (findings, predicted s): a
    # verdict-free module ranks 1, each APX code class demotes it
    mesh_rank = 1 + len(price["codes"])
    compiles_after = int(_cw.global_counters()["compiles"])
    assert compiles_after == compiles_before, \
        "per-axis pricing must not compile anything"
    return {
        "axis_hbm": axis_hbm,
        "axis_wire_bytes": price["wire_by_axis"],
        "mesh_explain": {"codes": price["codes"],
                         "predicted_total_s": price["predicted_total_s"],
                         "rank": mesh_rank},
        "peak_hbm_bytes": int(peak) if peak else int(rep.peak_live_bytes),
        "source": "device" if peak else "report",
        "peak_live_estimate_bytes": int(rep.peak_live_bytes),
        "hbm_limit_bytes": rep.hbm_limit,
        "classes_mib": {k: round(v / 2 ** 20, 2)
                        for k, v in rep.classes.items()},
        "lint": lint_rep.summary(),
        "lint_spmd": {"n_collectives": len(schedule),
                      "congruence_errors": spmd_errors},
        "lint_precision": {"n_sites": pa.n_sites,
                           "errors": precision_errors,
                           "preflight_candidates": preflight_candidates},
    }


def main():
    from apex_tpu import models, prof
    from apex_tpu.prof import compile_watch as _cw

    # process-wide compile counters for the n_compiles column — a
    # listener registration, nothing on the measured path
    _cw.install()
    on_tpu = jax.default_backend() == "tpu"
    size = 224 if on_tpu else 64
    # batch sweep: 256 is the sweet spot measured on v5e (see PERF.md).
    # EVERY point is recorded in the JSON (a sweep that keeps only the
    # winner can hide a regression at the documented operating point);
    # an OOM on the bigger batch falls back to the next instead of
    # killing the bench.
    batches = (256, 128) if on_tpu else (8,)
    best, best_loss, best_batch = 0.0, float("nan"), batches[0]
    best_wall, sweep = 0.0, {}
    for b in batches:
        try:
            dev_s, wall_s, loss_val = _measure(b, size)
        except Exception as e:  # RESOURCE_EXHAUSTED on small-HBM chips
            if "RESOURCE_EXHAUSTED" not in str(e) and "memory" not in \
                    str(e).lower():
                raise
            continue
        sweep[str(b)] = {"device_img_s": round(dev_s, 2),
                         "wall_img_s": round(wall_s, 2)}
        if dev_s > best:
            best, best_loss, best_batch = dev_s, loss_val, b
            best_wall = wall_s

    # fwd+bwd ≈ 3x fwd FLOPs, scaled to the bench image size
    flops_img = models.RESNET50_FLOPS_PER_IMAGE * 3 * (size / 224) ** 2
    peak = prof.device_peak_flops()
    mfu = (best * flops_img / peak) if peak else 0.0

    # secondary rows of the default output: the BERT-Large headline and
    # the DDP comm-mode column (VERDICT r5 gap — driver-verifiable
    # without --all); failures report, never kill the headline metric
    try:
        bert = _bert_row(on_tpu)
    except Exception as e:
        bert = {"failed": type(e).__name__}
    try:
        ddp_comm = _ddp_comm_modes()
    except Exception as e:
        ddp_comm = {"failed": type(e).__name__}
    try:
        mem = _memory_row(best_batch, size)
    except Exception as e:
        mem = {"failed": type(e).__name__}
    try:
        ckpt_row = _ckpt_row(8 if not on_tpu else 64, size)
    except Exception as e:
        ckpt_row = {"failed": type(e).__name__}
    try:
        goodput = _goodput_row(best_batch, size)
    except Exception as e:
        goodput = {"failed": type(e).__name__}
    try:
        link_fit = _link_fit_row()
    except Exception as e:
        link_fit = {"failed": type(e).__name__}
    try:
        roofline = _roofline_row(best_batch, size)
    except Exception as e:
        roofline = {"failed": type(e).__name__}
    try:
        numerics = _numerics_row()
    except Exception as e:
        numerics = {"failed": type(e).__name__}
    try:
        dyn = _dynamics_row()
    except Exception as e:
        dyn = {"failed": type(e).__name__}
    try:
        pod = _pod_row()
    except Exception as e:
        pod = {"failed": type(e).__name__}
    # every trace/lowering/backend-compile the bench performed — a
    # steady-state regression (a step silently retracing per call)
    # shows up here as n_compiles exploding; autotune-origin compiles
    # (prof.compile_watch.autotune_scope) are split out so a tuner
    # sweep never reads as a retrace storm
    counters = _cw.global_counters()
    n_compiles = int(counters["compiles"])
    n_autotune = int(counters["autotune_compiles"])
    try:
        from apex_tpu.ops import autotune as _autotune
        _tune_stats = _autotune.db_stats()
        tuned_families = _tune_stats["tuned_families"]
        autotune_db_hits = int(_tune_stats["hits"])
    except Exception as e:
        tuned_families, autotune_db_hits = {"failed": type(e).__name__}, None

    out = {
        "metric": "resnet50_amp_o2_images_per_sec",
        "value": round(best, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.60, 4),
        "extra": {"mfu": round(mfu, 4),
                  # vs_baseline IS the MFU ratio vs the 60% north star
                  # (BASELINE.json publishes no reference throughput
                  # numbers to ratio against) — named explicitly so the
                  # driver JSON is unambiguous
                  "mfu_ratio_vs_60pct_target": round(mfu / 0.60, 4),
                  # device-time regime (scan-K differencing); wall is
                  # per step of a K=4-step dispatch incl. its share of
                  # the tunnel dispatch constant
                  "regime": "device_time_scan_diff",
                  "wall_img_s": round(best_wall, 2),
                  "sweep": sweep,
                  "batch": best_batch, "size": size,
                  "device": getattr(jax.devices()[0], "device_kind", "?"),
                  "loss": best_loss,
                  "peak_hbm_bytes": mem.get("peak_hbm_bytes"),
                  "memory": mem,
                  # apexlint finding count on the compiled headline
                  # step (AOT — same executable the memory row reads);
                  # error-severity findings here mean the measured
                  # program wastes HBM or syncs the host per step
                  "lint_findings": mem.get("lint", {}).get("n_findings"),
                  "lint_errors": mem.get("lint", {}).get(
                      "by_severity", {}).get("error"),
                  # cross-rank SPMD congruence on the same executable
                  # (collective schedule length + APX201 error count;
                  # see docs/linting.md#apx2xx)
                  "lint_spmd_errors": mem.get("lint_spmd", {}).get(
                      "congruence_errors"),
                  # precision certification off the same trace +
                  # executable (apexlint precision pass,
                  # docs/linting.md#apx3xx): examined cast/dot/
                  # reduction sites, APX3xx error count, and — when
                  # the committed numerics fixture is present — the
                  # preflight's "statically castable ∩ measured-safe"
                  # fp8 candidate count
                  "lint_precision": mem.get("lint_precision"),
                  # the sharding observatory columns, off the SAME
                  # donated executable (apex_tpu.prof.shard_report +
                  # mesh_explain.price_candidate — zero extra
                  # compiles, asserted via compile_watch): per-axis
                  # sharded/replicated HBM, per-axis collective wire
                  # bytes (explicit "unknown" row for unregistered
                  # scopes), and the pre-flight rank the headline
                  # module earns under mesh_explain's (APX findings,
                  # predicted comm) key — 1 means verdict-free
                  "axis_hbm": mem.get("axis_hbm"),
                  "axis_wire_bytes": mem.get("axis_wire_bytes"),
                  "mesh_explain_rank": mem.get(
                      "mesh_explain", {}).get("rank"),
                  "n_compiles": n_compiles,
                  # the autotune-origin subset of n_compiles (the
                  # kernel_tune.py sweep's compiles are accounted here,
                  # never mistaken for steady-state retraces; 0 on a
                  # plain bench run)
                  "n_autotune_compiles": n_autotune,
                  # the committed tuning DB's reach on this run, off
                  # the same AOT executable: which kernel families hold
                  # ≥1 sweep winner in scripts/kernel_tuning_db.json,
                  # and how many trace-time consults hit an exact key
                  # (apex_tpu.ops.autotune — pure table stats, zero
                  # compiles, zero dispatches)
                  "tuned_families": tuned_families,
                  "autotune_db_hits": autotune_db_hits,
                  # per-op efficiency attribution of the headline step
                  # (apex_tpu.prof.roofline; worst_gaps is the
                  # autotuner's fingerprinted candidate list —
                  # measured on TPU, AOT-only classification off-TPU)
                  "roofline_worst_gap": (roofline.get("worst_gaps")
                                         or [None])[0],
                  "roofline": roofline,
                  # freshly measured fp8-readiness gauge: the worst
                  # grad site's e4m3 error fraction at its own
                  # recommended scale (apex_tpu.monitor.numerics; the
                  # sentinel's numerics_underflow_frac gate row
                  # watches it — numeric health regresses like perf
                  # does)
                  "numerics_underflow_frac": numerics.get(
                      "underflow_frac"),
                  "numerics": numerics,
                  # freshly measured training-dynamics gauges off one
                  # instrumented data-parallel step (apex_tpu.monitor.
                  # dynamics; estimators asserted by
                  # scripts/dynamics_audit.py --cpu8; zero extra
                  # compiles asserted inline): the GNS/B_simple
                  # estimate (null on single-device hosts — the
                  # estimator needs world > 1) and the worst
                  # per-replica gradient cosine vs the pooled mean
                  "gns": dyn.get("gns"),
                  "grad_cosine_min": dyn.get("grad_cosine_min"),
                  "dynamics": dyn,
                  # async checkpoint overhead on the step path (median
                  # per-step capture stall vs a synchronous
                  # save-and-wait; apex_tpu.ckpt, docs/checkpointing.md)
                  "ckpt_save_stall_ms": ckpt_row,
                  # steady-state goodput fraction of the instrumented
                  # headline step + its wall-time bucket breakdown
                  # (apex_tpu.monitor.goodput; closure asserted by
                  # scripts/goodput_audit.py --cpu8)
                  "goodput_frac": goodput.get("goodput_frac"),
                  "goodput": goodput,
                  # measured alpha-beta link calibration of the local
                  # device mesh (apex_tpu.monitor.linkbench /
                  # scripts/link_probe.py; single-device hosts skip)
                  "link_fit": link_fit,
                  # the pod observatory columns: goodput after the
                  # comm_skew/comm_wire split on an emulated pod
                  # merge, p99 collective entry skew, and the worst
                  # plan-vs-measured hop drift ratio
                  # (apex_tpu.trace.podview /
                  # apex_tpu.monitor.comm_drift; merge/blame/drift
                  # math asserted by scripts/pod_audit.py --cpu8)
                  "pod_goodput": pod.get("pod_goodput"),
                  "comm_skew_p99": pod.get("comm_skew_p99"),
                  "comm_drift_ratio": pod.get("comm_drift_ratio"),
                  "pod": pod,
                  "bert_large_lamb": bert,
                  "ddp_comm_modes": ddp_comm},
    }
    # the perf-regression sentinel judges the row just built against
    # the committed BENCH_r0*.json trajectory (device-matched;
    # docs/profiling.md#sentinel) — appended before print so the
    # column rides the same JSON line
    try:
        sentinel = _sentinel_row(out)
    except Exception as e:
        sentinel = {"failed": type(e).__name__, "n_regressions": None}
    out["extra"]["sentinel_regressions"] = sentinel.get("n_regressions")
    out["extra"]["sentinel"] = sentinel
    print(json.dumps(out))


#: exit status of a bench run whose BACKEND never came up (tunnel
#: down, no accelerator runtime, driver mismatch) — distinct from 0
#: (measured) and 1 (a bench bug), so the driver's trajectory keeps a
#: structured row instead of silently losing the round
BACKEND_FAILURE_EXIT_CODE = 13


#: bounded attempts for the backend probe — round 5's tunnel failure
#: was a transient flake, and a single probe turned it into a lost
#: round; three jittered tries absorb a blip without hiding a dead
#: backend for more than a few seconds
BACKEND_PROBE_ATTEMPTS = 3


def _backend_probe(attempts: int = BACKEND_PROBE_ATTEMPTS):
    """Force backend initialization NOW, before any measurement —
    jax is lazy, so a dead tunnel otherwise surfaces as an opaque
    rc=1 deep inside the first dispatch (the round-5 failure mode).
    Retried with bounded jittered backoff (``utils/backoff``): a
    transient tunnel blip must not cost the trajectory a round."""
    from apex_tpu.utils.backoff import backoff_sleep
    last = None
    for i in range(max(int(attempts), 1)):
        try:
            return jax.devices()
        except Exception as e:
            last = e
            if i + 1 < attempts:
                backoff_sleep(i, base_s=0.5, cap_s=4.0)
    raise last


def run_with_backend_guard(fn, mode: str = "default"):
    """Run one bench mode, degrading a backend-init failure into a
    STRUCTURED row: ``{"parsed": null, "failure_reason": ...,
    "attempts": N}`` on stdout (the committed BENCH_rNN.json then
    records a skippable row — ``perf_sentinel`` skips it with a note
    naming the reason AND the retry count) and exit code
    :data:`BACKEND_FAILURE_EXIT_CODE`. Only *backend bring-up*
    failures are absorbed — and only after
    :data:`BACKEND_PROBE_ATTEMPTS` jittered tries; an exception after
    devices enumerate is a bench bug and propagates with exit 1 as
    before."""
    try:
        _backend_probe()
    except Exception as e:
        reason = f"{type(e).__name__}: {e}"
        print(json.dumps({
            "parsed": None,
            "mode": mode,
            "failure_reason": f"backend init failed: {reason}",
            "attempts": BACKEND_PROBE_ATTEMPTS,
            "rc": BACKEND_FAILURE_EXIT_CODE,
        }))
        return BACKEND_FAILURE_EXIT_CODE
    fn()
    return 0


if __name__ == "__main__":
    if "--all" in sys.argv:
        mode_fn, mode_name = run_all, "all"
    elif "--monitor" in sys.argv:
        mode_fn, mode_name = run_monitor, "monitor"
    elif "--trace" in sys.argv:
        mode_fn, mode_name = run_trace, "trace"
    else:
        mode_fn, mode_name = main, "default"
    sys.exit(run_with_backend_guard(mode_fn, mode_name))
