"""Shared human-readable formatting helpers.

One byte formatter for every table in the codebase — ``prof.memory``,
``lint.findings`` and ``monitor.sinks`` each grew a private copy
before this module existed, and three drifting copies of the same
laddering is exactly the bug class the mesh-model link-constant pin
exists to prevent.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["fmt_bytes"]

_UNITS = (("GiB", "G", 2 ** 30), ("MiB", "M", 2 ** 20),
          ("KiB", "K", 2 ** 10))


def fmt_bytes(n: Optional[float], *, compact: bool = False,
              none: str = "n/a") -> str:
    """``47.70 MiB`` (default) or the column-width-friendly ``47.7M``
    (``compact=True``); ``None`` renders as ``none``."""
    if n is None:
        return none
    for unit, short, div in _UNITS:
        if abs(n) >= div:
            return (f"{n / div:.1f}{short}" if compact
                    else f"{n / div:.2f} {unit}")
    return f"{int(n)}" if compact else f"{int(n)} B"
