"""The in-graph half of the telemetry subsystem: a ``Metrics`` pytree.

The reference surfaces training health as opaque prints from the loss
scaler ("Gradient overflow.  Skipping step", `apex/amp/scaler.py:201-211`)
and post-hoc pyprof traces; a live run is a black box. Here the health
counters are a small pytree of on-device scalars threaded through the
jitted train step exactly like the loss-scaler state itself: updates are
pure ``jnp`` arithmetic riding along as an extra step output, so
monitoring adds **zero extra dispatches and no host syncs** — the host
only ever sees the values when :class:`apex_tpu.monitor.MetricsLogger`
flushes, amortized over N steps.

Design rules:

- every field is a scalar ``jax.Array`` (counters i32, gauges f32) — the
  tree is checkpointable, donate-able, and ``lax.scan``-carryable;
- ``step`` counts *attempted* optimizer steps (skipped ones included) so
  a logged stream is strictly monotonic — the committed-step count lives
  on the train state as before;
- cumulative counters (overflow/skip/growth/backoff) never reset; rates
  are a host-side subtraction.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["Metrics", "metrics_init", "metrics_to_dict",
           "metrics_snapshot", "METRIC_FIELDS"]


class Metrics(NamedTuple):
    """Training-health counters/gauges — a pure pytree of device scalars."""

    step: jax.Array            # i32: attempted optimizer steps (monotonic)
    loss: jax.Array            # f32: last (unscaled) loss value
    loss_scale: jax.Array      # f32: current loss scale (1.0 when unscaled)
    grad_norm: jax.Array       # f32: global L2 norm of the last grads
    param_norm: jax.Array      # f32: global L2 norm of the params
    overflow_count: jax.Array  # i32: cumulative non-finite-grad events
    skip_count: jax.Array      # i32: cumulative skipped optimizer steps
    growth_count: jax.Array    # i32: cumulative loss-scale growth events
    backoff_count: jax.Array   # i32: cumulative loss-scale backoff events

    # -- in-graph update helpers (all pure; no host interaction) -------------

    def record_loss(self, loss) -> "Metrics":
        return self._replace(loss=jnp.asarray(loss, jnp.float32))

    def record_norms(self, grad_norm=None, param_norm=None) -> "Metrics":
        m = self
        if grad_norm is not None:
            m = m._replace(grad_norm=jnp.asarray(grad_norm, jnp.float32))
        if param_norm is not None:
            m = m._replace(param_norm=jnp.asarray(param_norm, jnp.float32))
        return m

    def count_step(self, grads_finite) -> "Metrics":
        """Advance the attempt counter; count a skip when not finite."""
        fin = jnp.asarray(grads_finite, jnp.bool_)
        skipped = jnp.logical_not(fin).astype(jnp.int32)
        return self._replace(step=self.step + 1,
                             skip_count=self.skip_count + skipped)


METRIC_FIELDS = Metrics._fields


def metrics_init() -> Metrics:
    """Zeroed metrics — thread through the step like any other state."""
    return Metrics(
        step=jnp.int32(0),
        loss=jnp.float32(0.0),
        loss_scale=jnp.float32(1.0),
        grad_norm=jnp.float32(0.0),
        param_norm=jnp.float32(0.0),
        overflow_count=jnp.int32(0),
        skip_count=jnp.int32(0),
        growth_count=jnp.int32(0),
        backoff_count=jnp.int32(0),
    )


def metrics_snapshot(m):
    """Donation-safe copy of a metrics pytree (or any small pytree of
    device arrays): fresh device buffers via async scalar copies, so a
    step jitted with ``donate_argnums`` over the state carrying these
    arrays cannot invalidate what a :class:`MetricsLogger` /
    :class:`~apex_tpu.trace.FlightRecorder` buffered for a later
    amortized fetch. A handful of scalar device copies per call — still
    async, still no host sync (``MetricsLogger(donation_safe=True)``
    applies it automatically at record time)."""
    return jax.tree_util.tree_map(
        lambda a: a.copy() if hasattr(a, "copy") else a, m)


def metrics_to_dict(m: Metrics) -> dict:
    """Host-native dict of one (already fetched) metrics snapshot.

    Works on host values only — no jnp calls, so a flush that already
    did its one bulk ``device_get`` never touches the device again."""
    import numpy as np
    out = {}
    for name, v in zip(Metrics._fields, m):
        out[name] = (int(v) if np.issubdtype(np.asarray(v).dtype, np.integer)
                     else float(v))
    return out
