"""Backend dispatch for Pallas kernels.

Compiled Mosaic kernels require a real TPU; every kernel in apex_tpu runs in
Pallas interpret mode on other backends (the CI CPU mesh), preserving
semantics bit-for-bit at jnp precision. This mirrors the reference's
"Python-only build degrades gracefully" contract
(`apex/amp/scaler.py:39-52`) — except nothing is unavailable here, only
uncompiled.

``APEX_TPU_FORCE_INTERPRET=1`` forces interpret mode everywhere (debugging).
"""

from __future__ import annotations

import os

import jax


def use_interpret() -> bool:
    if os.environ.get("APEX_TPU_FORCE_INTERPRET") == "1":
        return True
    return jax.default_backend() != "tpu"


# Rows per grid step for flat-buffer elementwise kernels. A (512, 128) fp32
# block is 256 KiB — small enough that an 8-operand optimizer kernel stays
# well under the ~16 MiB VMEM budget with double buffering, large enough to
# saturate HBM bandwidth.
BLOCK_ROWS = 512
LANES = 128


def as_rows(buf):
    """View a flat arena buffer as (rows, 128). Arena buffers are padded to
    BUFFER_MULTIPLE so rows % BLOCK_ROWS == 0 always holds."""
    n = buf.shape[0]
    assert n % (BLOCK_ROWS * LANES) == 0, (
        f"arena buffer length {n} not a multiple of "
        f"{BLOCK_ROWS * LANES}; use apex_tpu.arena.flatten")
    return buf.reshape(n // LANES, LANES)


def launch(kernel, inputs, outs, scalars=None):
    """Shared pallas_call plumbing for flat-buffer elementwise kernels.

    The single launch convention every arena kernel uses (the analogue of
    the reference's `multi_tensor_apply.cuh` launcher): a 1-D grid over
    (BLOCK_ROWS, 128) VMEM blocks of each input buffer, an optional f32
    hyperparameter vector in SMEM prepended to the kernel args, and outputs
    that are either per-block buffers or (1,1) SMEM scalar accumulators
    revisited by every grid step (TPU grids are sequential, so
    read-modify-write accumulation is well-defined; Mosaic requires scalar
    stores to target SMEM, not VMEM).

    ``outs`` is a list of ("block", dtype) | ("scalar", dtype) entries.
    Block outputs come back as flat buffers, scalar outputs as (1, 1)
    arrays, in order.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows_arrs = [as_rows(b) for b in inputs]
    rows = rows_arrs[0].shape[0]
    block = pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    scalar = pl.BlockSpec((1, 1), lambda i: (0, 0),
                          memory_space=pltpu.SMEM)

    in_specs = [block] * len(rows_arrs)
    args = tuple(rows_arrs)
    if scalars is not None:
        in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] + in_specs
        args = (jnp.asarray(scalars, jnp.float32),) + args

    out_specs, out_shapes = [], []
    for kind, dt in outs:
        if kind == "block":
            out_specs.append(block)
            out_shapes.append(jax.ShapeDtypeStruct((rows, LANES),
                                                   jnp.dtype(dt)))
        elif kind == "scalar":
            out_specs.append(scalar)
            out_shapes.append(jax.ShapeDtypeStruct((1, 1), jnp.dtype(dt)))
        else:
            raise ValueError(f"unknown out kind {kind!r}")

    results = pl.pallas_call(
        kernel,
        grid=(rows // BLOCK_ROWS,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shapes),
        interpret=use_interpret(),
    )(*args)
    if not isinstance(results, (list, tuple)):
        results = (results,)
    final = tuple(r.reshape(-1) if kind == "block" else r
                  for r, (kind, _) in zip(results, outs))
    return final if len(final) > 1 else final[0]
