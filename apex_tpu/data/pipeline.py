"""Threaded ImageFolder input pipeline + device prefetch.

Rebuild of the reference's input machinery for `examples/imagenet`:
torch DataLoader / DALI decode+augment (`main_amp.py:28-57`) feeding the
CUDA-stream `data_prefetcher` (`main_amp.py:264-317`). The TPU design:

- **Decode/augment workers**: a thread pool decodes JPEGs with PIL
  (libjpeg releases the GIL inside the C decoder, so threads scale to
  the host's cores without torch's worker *processes*) and applies the
  standard train transform — RandomResizedCrop(scale 0.08-1.0, ratio
  3/4-4/3) + horizontal flip — in numpy.
- **Batch assembly** into one contiguous NHWC float32 (or pre-cast
  half) array per batch: a single host buffer per transfer, the
  "pinned staging buffer" role.
- **Device prefetch**: :class:`DevicePrefetcher` keeps ``depth``
  batches device_put ahead of the training loop; with JAX's async
  dispatch this is the whole stream-overlap machinery.

No tf.data/grain in the image; PIL is the decode engine (the same
libjpeg-turbo DALI wraps). Measured honestly: `measure_source` reports
loader-only throughput so input-bound configs are visible
(BENCH_TABLE.md notes) instead of silently capping training numbers.
"""

from __future__ import annotations

import concurrent.futures
import os
import queue
import threading
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def _list_imagefolder(root: str):
    """(paths, labels, class_names) for a torchvision-ImageFolder-style
    tree: root/<class>/<image>."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    paths, labels = [], []
    for i, c in enumerate(classes):
        cdir = os.path.join(root, c)
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith(IMG_EXTS):
                paths.append(os.path.join(cdir, f))
                labels.append(i)
    if not paths:
        raise FileNotFoundError(f"no images under {root!r}")
    return paths, np.asarray(labels, np.int32), classes


def _random_resized_crop(img, size: int, rng: np.random.RandomState,
                         scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """torchvision RandomResizedCrop semantics (the reference's train
    transform, `main_amp.py:230-236`), on a PIL image."""
    from PIL import Image

    w, h = img.size
    area = w * h
    for _ in range(10):
        target = area * rng.uniform(*scale)
        log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
        ar = np.exp(log_r)
        cw = int(round(np.sqrt(target * ar)))
        ch = int(round(np.sqrt(target / ar)))
        if 0 < cw <= w and 0 < ch <= h:
            x0 = rng.randint(0, w - cw + 1)
            y0 = rng.randint(0, h - ch + 1)
            box = (x0, y0, x0 + cw, y0 + ch)
            break
    else:  # fallback: center crop of the short side
        s = min(w, h)
        x0, y0 = (w - s) // 2, (h - s) // 2
        box = (x0, y0, x0 + s, y0 + s)
    return img.resize((size, size), Image.BILINEAR, box=box)


def _decode_one(path: str, size: int, seed: int, train: bool):
    from PIL import Image

    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    with Image.open(path) as img:
        img = img.convert("RGB")
        if train:
            img = _random_resized_crop(img, size, rng)
        else:
            s = min(img.size)
            w, h = img.size
            img = img.resize((size, size), Image.BILINEAR,
                             box=((w - s) // 2, (h - s) // 2,
                                  (w + s) // 2, (h + s) // 2))
        arr = np.asarray(img, np.uint8)
    if train and rng.rand() < 0.5:
        arr = arr[:, ::-1]
    return arr


class ImageFolderSource:
    """Batched (x, y) iterator over an ImageFolder tree.

    A thread pool decodes/augments ``workers`` images concurrently (PIL
    drops the GIL in libjpeg); batches come out as one contiguous NHWC
    array scaled to [0, 1) in ``dtype``. Iteration order reshuffles per
    epoch like the reference's ``shuffle=True`` loader.
    """

    def __init__(self, root: str, batch: int, size: int = 224, *,
                 workers: Optional[int] = None, train: bool = True,
                 seed: int = 0, dtype=np.float32,
                 drop_last: bool = True):
        self.paths, self.labels, self.classes = _list_imagefolder(root)
        self.batch = batch
        self.size = size
        self.train = train
        self.seed = seed
        self.dtype = dtype
        self.drop_last = drop_last
        self.workers = workers or min(16, (os.cpu_count() or 1))
        self._pool = concurrent.futures.ThreadPoolExecutor(self.workers)
        self._epoch = 0

    def __len__(self):
        n = len(self.paths) // self.batch
        if not self.drop_last and len(self.paths) % self.batch:
            n += 1
        return n

    def close(self) -> None:
        """Shut the decode pool down (idempotent). Sources used for a
        one-off probe should be closed so their worker threads don't
        outlive the measurement."""
        self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def epoch(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        rng = np.random.RandomState(self.seed + self._epoch)
        order = rng.permutation(len(self.paths))
        self._epoch += 1
        b = self.batch
        for start in range(0, len(order) - (b - 1 if self.drop_last
                                            else 0), b):
            idx = order[start:start + b]
            futs = [self._pool.submit(_decode_one, self.paths[i],
                                      self.size,
                                      int(rng.randint(1 << 31)),
                                      self.train)
                    for i in idx]
            x = np.empty((len(idx), self.size, self.size, 3), self.dtype)
            for j, f in enumerate(futs):
                x[j] = f.result().astype(self.dtype)
            x *= np.asarray(1.0 / 255.0, self.dtype)
            yield x, self.labels[idx]

    def batches(self, steps: int) -> Iterator[Tuple[np.ndarray,
                                                    np.ndarray]]:
        """Exactly ``steps`` batches, re-entering epochs as needed."""
        if len(self) == 0:
            raise ValueError(
                f"dataset has {len(self.paths)} images < batch size "
                f"{self.batch} with drop_last — no batch can be formed")
        done = 0
        while done < steps:
            for xb, yb in self.epoch():
                yield xb, yb
                done += 1
                if done >= steps:
                    return


def synthetic_source(batch, size, steps, seed=0, num_classes=1000):
    """Host-synthetic batches (the no-dataset default)."""
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        x = rng.rand(batch, size, size, 3).astype(np.float32)
        y = rng.randint(0, num_classes, batch).astype(np.int32)
        yield x, y


class DevicePrefetcher:
    """Host→device prefetch: the `data_prefetcher` role
    (`examples/imagenet/main_amp.py:264-317`).

    A background thread device_puts upcoming batches (with the fp16/bf16
    input cast the reference does on its side stream) into a bounded
    queue while the device trains on the current one. JAX's async
    dispatch provides the "stream overlap".
    """

    def __init__(self, it, sharding=None, cast_dtype=None, depth: int = 2):
        import jax

        self.q = queue.Queue(maxsize=depth)
        self._sentinel = object()
        self._error = None

        def work():
            try:
                for batch in it:
                    if cast_dtype is not None:
                        batch = (batch[0].astype(cast_dtype),) + batch[1:]
                    self.q.put(jax.device_put(batch, sharding))
            except BaseException as e:          # surface in the consumer
                self._error = e
            finally:
                self.q.put(self._sentinel)

        self.t = threading.Thread(target=work, daemon=True)
        self.t.start()

    def __iter__(self):
        while True:
            item = self.q.get()
            if item is self._sentinel:
                if self._error is not None:
                    raise self._error
                return
            yield item


def measure_source(src, steps: int = 20) -> float:
    """Loader-only images/sec — the input-bound-vs-compute-bound probe.

    Compare against the model's synthetic-data img/s: if this number is
    lower, the config is input-bound and training throughput will cap
    here no matter the chip.
    """
    import time

    it = iter(src)
    x, _ = next(it)       # warm the pool
    n = 0
    t0 = time.perf_counter()
    for i, (x, _) in enumerate(it):
        n += x.shape[0]
        if i + 1 >= steps:
            break
    dt = time.perf_counter() - t0
    return n / dt if dt > 0 else float("inf")


def make_fake_imagefolder(root: str, n_classes: int = 4,
                          per_class: int = 8, size: int = 256,
                          seed: int = 0) -> str:
    """Write a small synthetic ImageFolder tree of JPEGs (for tests and
    loader benchmarks in images-free environments)."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    for c in range(n_classes):
        d = os.path.join(root, f"class_{c:03d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 256, (size, size, 3), np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img_{i:04d}.jpg"),
                                      quality=85)
    return root
