"""apex_tpu.optim — fused optimizers (SURVEY.md §2.4, §2.6).

Single-process fused optimizers run one Pallas kernel per dtype partition
over the flat arena. The ZeRO-style distributed variants (reduce-scatter →
sharded update → all-gather, optionally compressed) live in
apex_tpu.optim.distributed and run inside shard_map.
"""

from apex_tpu.optim.fused import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedNovoGrad,
    FusedOptimizer,
    FusedOptState,
    FusedSGD,
)
from apex_tpu.optim.distributed import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
    ShardedOptState,
)
# deprecated contrib surface (externally-scaled grads), kept for parity
from apex_tpu.optim import legacy

__all__ = [
    "FusedAdagrad", "FusedAdam", "FusedLAMB", "FusedNovoGrad",
    "FusedOptimizer", "FusedOptState", "FusedSGD",
    "DistributedFusedAdam", "DistributedFusedLAMB", "ShardedOptState",
    "legacy",
]
