"""Minimal DDP + amp pattern — the `examples/simple/distributed` mirror.

Reference: `examples/simple/distributed/distributed_data_parallel.py:1-66`
(a Linear regression trained under amp O1 + apex DDP, launched with
`torch.distributed.launch`). TPU-native, there is no per-rank process
dance: one program shards the batch over a named mesh axis and `psum`s
gradients. Multi-host pods use the same script after
``apex_tpu.parallel.distributed_init()`` (the `multiproc` equivalent).

Also the minimal apex_tpu.monitor consumer: the train state carries the
in-graph Metrics pytree (``monitor=True``), a ``MetricsLogger`` ships it
to stdout/JSONL on an amortized flush cadence, and the per-step
collective traffic is read off the compiled HLO via
``ddp.collective_bytes`` — live telemetry with zero extra dispatches.

Also the minimal apex_tpu.trace consumer: ``--crash-dumps DIR`` installs
the per-rank flight recorder + hang watchdog
(``parallel.enable_crash_dumps``), wraps each step in
``trace.step``/``trace.span`` so dumps carry the span timeline, and
writes a Perfetto-loadable Chrome trace at the end — a wedged or dead
run leaves per-rank JSONL forensics instead of nothing.

Run (any host, any chip count — falls back to a virtual CPU mesh):

    python distributed_data_parallel.py [--steps 500]
                                        [--metrics-jsonl metrics.jsonl]
                                        [--crash-dumps dumps/]
"""

import argparse

import os
import sys

# allow running from a source checkout without installation
sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")))


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu import amp, monitor, parallel, trace
from apex_tpu.optim import FusedSGD


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", default=500, type=int)
    parser.add_argument("--opt_level", default="O1", type=str)
    parser.add_argument("--metrics-jsonl", default=None, type=str,
                        help="also stream metrics to this JSONL file")
    parser.add_argument("--log-every", default=50, type=int,
                        help="flush cadence of the metrics logger")
    parser.add_argument("--crash-dumps", default=None, type=str,
                        help="directory for per-rank flight-recorder / "
                             "watchdog dumps + a Chrome trace")
    parser.add_argument("--hang-deadline", default=300.0, type=float,
                        help="watchdog deadline (s) when --crash-dumps "
                             "is set")
    args = parser.parse_args()

    # FOR DISTRIBUTED: form the cluster first (no-op single-process;
    # honors MASTER_ADDR/RANK/WORLD_SIZE) — rank resolution below (per-
    # rank dump paths, mesh over the global device set) depends on it.
    parallel.distributed_init()

    # FORENSICS: flight recorder (excepthook/SIGTERM/atexit crash dumps)
    # + hang watchdog, one file per rank; the tracer feeds both.
    tracer, recorder = trace.Tracer(), None
    if args.crash_dumps:
        tracer, recorder, _wd, _cd = parallel.enable_crash_dumps(
            os.path.join(args.crash_dumps, "crash.jsonl"),
            hang_deadline_s=args.hang_deadline)

    # FOR DISTRIBUTED: one mesh over every available device; the same
    # script is SPMD across a pod once distributed_init() has run.
    mesh = parallel.data_parallel_mesh()
    ddp = parallel.DistributedDataParallel(mesh)

    N, D_in, D_out = 64, 1024, 16
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, D_in).astype(np.float32))
    y = jnp.asarray(rng.randn(N, D_out).astype(np.float32))

    w = jnp.asarray(rng.randn(D_in, D_out).astype(np.float32) * 0.01)
    b = jnp.zeros((D_out,), jnp.float32)
    params = {"w": w, "b": b}

    amp_opt, state = amp.initialize(params, FusedSGD(lr=1e-3),
                                    opt_level=args.opt_level,
                                    monitor=True)

    def step(state, xb, yb):
        def loss_fn(p):
            pred = xb @ p["w"] + p["b"]
            return jnp.mean(jnp.square(pred - yb))

        loss, grads, state, finite = amp_opt.backward(state, loss_fn)
        grads = ddp.sync(grads)                     # the DDP allreduce
        if not isinstance(finite, bool):
            # defensive: the default bf16 presets have no scaler (finite
            # is literally True). If this example is edited to fp16, the
            # COMMIT decision must be global — one shard overflowing
            # skips the step everywhere. Note the scaler *schedule* and
            # its event counters inside backward() still see shard-local
            # finiteness; a production fp16+DDP loop should sync grads
            # before unscaling via the standalone scaler API
            # (docs/amp.md "Loss scaling, standalone").
            finite = jax.lax.pmin(
                jnp.asarray(finite, jnp.int32), ddp.axis_name).astype(bool)
        state = amp_opt.apply_gradients(state, grads, finite)
        gloss = jax.lax.pmean(loss, ddp.axis_name)
        if state.metrics is not None:
            # backward recorded the shard-local loss; the logged stream
            # (fetched from shard 0) must carry the global mean — every
            # other gauge is already replicated (synced grads / params /
            # global finite)
            state = state._replace(metrics=state.metrics.record_loss(gloss))
        return state, gloss

    # the carried AmpState is donated (apexlint APX101: without it the
    # masters + optimizer state are double-allocated every step)
    spmd_step = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(parallel.DATA_AXIS), P(parallel.DATA_AXIS)),
        out_specs=(P(), P()), check_vma=False), donate_argnums=(0,))

    # MONITORING: per-step collective traffic and model FLOPs are
    # compile-time constants read off the optimized HLO; attach()
    # derives both from ONE AOT compile (ddp.collective_bytes exposes
    # the same accounting with a per-opcode breakdown, at the cost of
    # its own compile). The logger then ships the in-graph health
    # counters off-device every --log-every steps (one amortized fetch).
    sinks = [monitor.StdoutSink()]
    if args.metrics_jsonl:
        sinks.append(monitor.JSONLSink(args.metrics_jsonl))
    logger = monitor.MetricsLogger(sinks, flush_every=args.log_every)
    logger.attach(spmd_step, state, x, y)
    print(f"collective traffic/step: {logger.collective_bytes_per_step} "
          "bytes")

    with tracer:
        for i in range(args.steps):
            with trace.step(i):
                with trace.span("dispatch"):
                    state, loss = spmd_step(state, x, y)
                # donation-safe snapshot: the next donated dispatch
                # invalidates the state's own metrics buffers
                m = monitor.metrics_snapshot(state.metrics)
                logger.record(m)
                if recorder is not None:
                    recorder.record_metrics(m)
    logger.close()
    if args.crash_dumps:
        path = trace.rank_path(
            os.path.join(args.crash_dumps, "timeline.json"))
        tracer.write_chrome_trace(path)
        print("span timeline ->", path)
    print("final loss = ", float(loss))


if __name__ == "__main__":
    main()
